// Cluster-wide host-port allocator — native hot path.
//
// Reference analog: pkg/port-allocator (inventory #18, Go): random strategy
// in [start, start+range), cluster-singleton, thread-safe. This is the
// C++ implementation backing rbg_tpu.portalloc via ctypes; the Python
// fallback implements identical semantics.
//
// C ABI (ctypes-friendly): opaque handle + int results. -1 == failure.

#include <cstdint>
#include <cstring>
#include <mutex>
#include <random>
#include <vector>

struct PortAllocator {
  int32_t start;
  int32_t range;
  std::vector<uint8_t> used;  // bitmap over [0, range)
  int32_t n_used = 0;
  std::mt19937 rng;
  std::mutex mu;

  PortAllocator(int32_t s, int32_t r, uint64_t seed)
      : start(s), range(r), used(r, 0), rng(seed) {}
};

extern "C" {

void* pa_create(int32_t start, int32_t range, uint64_t seed) {
  if (range <= 0 || start <= 0 || start + range > 65536) return nullptr;
  return new PortAllocator(start, range, seed);
}

void pa_destroy(void* h) { delete static_cast<PortAllocator*>(h); }

// Random-probe allocation: O(1) expected while load < ~90%, linear sweep
// fallback guarantees completeness.
int32_t pa_allocate(void* h) {
  auto* a = static_cast<PortAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  if (a->n_used >= a->range) return -1;
  std::uniform_int_distribution<int32_t> dist(0, a->range - 1);
  for (int probe = 0; probe < 64; ++probe) {
    int32_t i = dist(a->rng);
    if (!a->used[i]) {
      a->used[i] = 1;
      ++a->n_used;
      return a->start + i;
    }
  }
  for (int32_t i = 0; i < a->range; ++i) {
    if (!a->used[i]) {
      a->used[i] = 1;
      ++a->n_used;
      return a->start + i;
    }
  }
  return -1;
}

// Reserve a specific port (startup reseed from persisted annotations).
// Returns 1 on success, 0 if already used or out of range.
int32_t pa_reserve(void* h, int32_t port) {
  auto* a = static_cast<PortAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  int32_t i = port - a->start;
  if (i < 0 || i >= a->range) return 0;
  if (a->used[i]) return 0;
  a->used[i] = 1;
  ++a->n_used;
  return 1;
}

void pa_release(void* h, int32_t port) {
  auto* a = static_cast<PortAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  int32_t i = port - a->start;
  if (i < 0 || i >= a->range) return;
  if (a->used[i]) {
    a->used[i] = 0;
    --a->n_used;
  }
}

int32_t pa_in_use(void* h) {
  auto* a = static_cast<PortAllocator*>(h);
  std::lock_guard<std::mutex> lock(a->mu);
  return a->n_used;
}

}  // extern "C"
