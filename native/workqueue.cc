// Rate-limitable dedup work queue — the control plane's hot loop, native.
//
// Reference analog: controller-runtime's workqueue (the reference's Go
// control plane spends its cycles here; SURVEY.md §2 notes the rebuild's
// native budget goes to the control plane itself). Semantics match
// rbg_tpu/runtime/queue.py exactly: dirty/processing dedup (an item re-added
// mid-reconcile re-queues on done), delayed adds, blocking get.
//
// Items are opaque int64 ids; the Python binding interns keys to ids.

#include <chrono>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <mutex>
#include <queue>
#include <unordered_set>
#include <vector>

using Clock = std::chrono::steady_clock;

struct Delayed {
  Clock::time_point at;
  uint64_t seq;
  int64_t item;
  bool operator>(const Delayed& o) const {
    return at != o.at ? at > o.at : seq > o.seq;
  }
};

struct WorkQueue {
  std::mutex mu;
  std::condition_variable cv;
  std::deque<int64_t> queue;
  std::unordered_set<int64_t> dirty, processing;
  std::priority_queue<Delayed, std::vector<Delayed>, std::greater<Delayed>> delayed;
  uint64_t seq = 0;
  bool shutdown = false;

  void pump_locked() {
    auto now = Clock::now();
    while (!delayed.empty() && delayed.top().at <= now) {
      int64_t item = delayed.top().item;
      delayed.pop();
      if (dirty.insert(item).second && !processing.count(item)) {
        queue.push_back(item);
      } else if (dirty.count(item) && !processing.count(item)) {
        // freshly inserted above; nothing more to do
      }
    }
  }
};

extern "C" {

void* wq_create() { return new WorkQueue(); }

void wq_destroy(void* h) { delete static_cast<WorkQueue*>(h); }

void wq_add(void* h, int64_t item) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  if (q->shutdown || q->dirty.count(item)) return;
  q->dirty.insert(item);
  if (!q->processing.count(item)) {
    q->queue.push_back(item);
    q->cv.notify_one();
  }
}

void wq_add_after(void* h, int64_t item, int64_t delay_us) {
  auto* q = static_cast<WorkQueue*>(h);
  if (delay_us <= 0) return wq_add(h, item);
  std::lock_guard<std::mutex> lock(q->mu);
  if (q->shutdown) return;
  q->delayed.push({Clock::now() + std::chrono::microseconds(delay_us),
                   ++q->seq, item});
  q->cv.notify_one();
}

// Blocking pop; timeout_us < 0 means wait forever. Returns -1 on timeout or
// shutdown-with-empty-queue.
int64_t wq_get(void* h, int64_t timeout_us) {
  auto* q = static_cast<WorkQueue*>(h);
  std::unique_lock<std::mutex> lock(q->mu);
  auto deadline = timeout_us >= 0
                      ? Clock::now() + std::chrono::microseconds(timeout_us)
                      : Clock::time_point::max();
  for (;;) {
    q->pump_locked();
    if (!q->queue.empty()) {
      int64_t item = q->queue.front();
      q->queue.pop_front();
      q->processing.insert(item);
      q->dirty.erase(item);
      return item;
    }
    if (q->shutdown) return -1;
    auto wait_until = deadline;
    if (!q->delayed.empty() && q->delayed.top().at < wait_until) {
      wait_until = q->delayed.top().at;
    }
    if (wait_until == Clock::time_point::max()) {
      q->cv.wait_for(lock, std::chrono::seconds(1));
    } else {
      if (q->cv.wait_until(lock, wait_until) == std::cv_status::timeout &&
          wait_until == deadline && Clock::now() >= deadline) {
        // real timeout (not a delayed-item wake)
        q->pump_locked();
        if (!q->queue.empty()) continue;
        return -1;
      }
    }
  }
}

void wq_done(void* h, int64_t item) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  q->processing.erase(item);
  if (q->dirty.count(item)) {
    q->queue.push_back(item);
    q->cv.notify_one();
  }
}

void wq_shutdown(void* h) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  q->shutdown = true;
  q->cv.notify_all();
}

int64_t wq_len(void* h) {
  auto* q = static_cast<WorkQueue*>(h);
  std::lock_guard<std::mutex> lock(q->mu);
  return static_cast<int64_t>(q->queue.size());
}

}  // extern "C"
