"""Headline benchmark — prints ONE JSON line.

Metric: steady-state decode throughput (tokens/sec) of the serving forward
path on the available chip (qwen2-0.5b-geometry model, randomly initialized —
zero-egress environment, so no weight downloads; throughput is
weight-value-independent).

The reference publishes no benchmark numbers (BASELINE.md), so ``vs_baseline``
is reported against this repo's recorded round-0 target below.
"""

import json
import time

import jax
import jax.numpy as jnp

# Round-0 target for this metric (tokens/sec); see BASELINE.md — reference
# publishes nothing, so this anchors cross-round comparisons.
TARGET_TOKENS_PER_SEC = 2000.0

BATCH = 8
PREFILL = 128
DECODE_STEPS = 32


def main():
    from rbg_tpu.models import KVCache, forward, get_config, init_params

    on_tpu = jax.devices()[0].platform == "tpu"
    cfg = get_config("qwen2-0.5b" if on_tpu else "tiny")
    params = init_params(cfg, jax.random.key(0))

    S = PREFILL + DECODE_STEPS + 8
    tokens = jax.random.randint(jax.random.key(1), (BATCH, PREFILL), 0, cfg.vocab_size)
    cache = KVCache.create(cfg, BATCH, S)

    fwd = jax.jit(lambda p, t, c: forward(p, cfg, t, c), donate_argnums=(2,))

    # Prefill
    logits, cache = fwd(params, tokens, cache)
    tok = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)

    # Warm up decode compile
    logits, cache = fwd(params, tok, cache)
    tok = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
    jax.block_until_ready(tok)

    start = time.perf_counter()
    for _ in range(DECODE_STEPS):
        logits, cache = fwd(params, tok, cache)
        tok = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
    jax.block_until_ready(tok)
    elapsed = time.perf_counter() - start

    tps = BATCH * DECODE_STEPS / elapsed
    print(json.dumps({
        "metric": f"decode_throughput_{cfg.name}_bs{BATCH}_{jax.devices()[0].platform}",
        "value": round(tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / TARGET_TOKENS_PER_SEC, 4),
    }))


if __name__ == "__main__":
    main()
