"""Headline benchmark — prints ONE JSON line.

Metric: steady-state decode throughput (tokens/sec) of the FULL serving
engine (paged KV + continuous batching + device sampling) on the available
chip — qwen2-0.5b-geometry model, randomly initialized (zero-egress
environment; throughput is weight-value-independent).

Hardened metric (round-3): the timed section runs ``REPS`` times and the
reported value is the MEDIAN, with per-run values in ``runs_tps`` so
cross-round comparisons can tell code change from machine noise. When the
TPU probe fails the JSON carries the probe diagnostics (what ran, how long,
stderr tail) instead of silently falling back.

The reference publishes no benchmark numbers (BASELINE.md), so
``vs_baseline`` is reported against this repo's recorded round-0 target.
"""

import json
import math
import os
import statistics
import subprocess
import sys
import time

# Round-0 target (tokens/sec) anchoring cross-round comparison; the reference
# publishes nothing for this metric (BASELINE.md). Replace with the measured
# TPU number once one lands (VERDICT r2 #1).
TARGET_TOKENS_PER_SEC = 2000.0

BATCH = 8
PROMPT_LEN = 128
DECODE_TOKENS_PER_REP = 64   # decode tokens per sequence per timed rep
MULTI_STEP = 8               # device-side decode window (EngineConfig.multi_step)
REPS = 5
PROBE_TIMEOUT_S = 240
# Spread gate (docs/benchmarks.md trust bar): a run whose min–max spread
# exceeds this is machine-noise-contaminated; re-measure with a FRESH
# batch (same shapes — comparability across rounds depends on identical
# conditions) up to MAX_ATTEMPTS times, else report the gate failure
# instead of publishing noise as signal.
SPREAD_GATE_PCT = 5.0
MAX_ATTEMPTS = 6
# The gate uses a TRIMMED spread: drop the single fastest and slowest rep,
# then (max-min)/median over the middle REPS-2. One scheduler hiccup in a
# rep landed the old raw min-max spread above the gate on an otherwise
# clean run (VERDICT weak-point #1) — the trimmed estimator keeps the gate
# meaningful (a real regime change still moves the middle runs) without
# publishing noise as failure. The raw spread is still reported alongside.

_PROBE_ENV = "RBG_BENCH_PROBE_JSON"


def spread_of(runs):
    med = statistics.median(runs)
    return 100.0 * (max(runs) - min(runs)) / med if med else float("inf")


def trimmed_spread_of(runs):
    """Spread over the middle runs (single min and max dropped) — THE
    gate estimator, shared by the headline metric and the mixed probe so
    a tweak here moves every gate in this file together."""
    if len(runs) < 4:
        return spread_of(runs)
    return spread_of(sorted(runs)[1:-1])

# Constrained-decode probe (guided_regex): a regex long enough that no
# row completes inside the timed window. Measured BOTH ways — device-
# resident grammar tables (fused multi-step scan) vs the host-synced
# per-token mask path — so the speedup is tracked in BENCH_*.json going
# forward. bs=4: at tiny-model CPU shapes the forward is cheap enough
# that wider batches amortize the host path's per-token overhead into
# the noise floor; production-sized forwards don't have that luxury, so
# the narrower batch is the representative dispatch-overhead regime.
CONSTRAINED_REGEX = "[ab]{400}"
CONSTRAINED_BATCH = 4
CONSTRAINED_WARM_STEPS = 2
# 2 warm windows + 3 x 96 timed tokens stay under the regex's 400-char
# span: no row may complete (and empty the batch) inside a timed window.
CONSTRAINED_TOKENS_PER_SEQ = 96
CONSTRAINED_REPS = 3


def constrained_probe(batch: int) -> dict:
    """guided_regex decode throughput, table path vs host-synced path.
    Reported ALONGSIDE the headline metric (never replacing it). Runs the
    tiny preset with the byte tokenizer on every backend: the probe
    tracks the PATH cost (per-token host syncs + host mask builds vs the
    fused device window), which the grammar machinery makes
    model-size-independent."""
    import dataclasses as _dc
    import time as _time

    from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
    from rbg_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()

    def measure(grammar_table: str) -> float:
        """Median of CONSTRAINED_REPS timed windows on one warm engine
        (same hardening rationale as the headline REPS)."""
        multi = MULTI_STEP if grammar_table == "auto" else 1
        eng = Engine(EngineConfig(
            model="tiny", vocab_size=512, page_size=16, num_pages=512,
            max_batch=batch, max_seq_len=512, prefill_chunk=16,
            enable_radix_cache=False, decode_buckets=(batch,),
            multi_step=multi, grammar_table=grammar_table))
        eng.enable_json_grammar(tok)
        sp = SamplingParams(max_new_tokens=440, temperature=0.7,
                            regex=CONSTRAINED_REGEX, stop_token=tok.eos_id)
        for i in range(batch):
            eng.add_request(tok.encode("p%d:" % i, add_bos=False),
                            _dc.replace(sp, seed=i))
        while eng.waiting or any(r.state != "running" for r in eng.running):
            eng.step()
        for _ in range(CONSTRAINED_WARM_STEPS):
            eng.step()
        steps = max(1, CONSTRAINED_TOKENS_PER_SEQ // multi)
        runs = []
        for _ in range(CONSTRAINED_REPS):
            start = eng.metrics["decode_tokens"]
            t0 = _time.perf_counter()
            for _ in range(steps):
                eng.step()
            elapsed = _time.perf_counter() - t0
            runs.append((eng.metrics["decode_tokens"] - start) / elapsed)
        for r in list(eng.running):
            eng.cancel_request(r.id)
        return statistics.median(runs)

    table_tps = measure("auto")
    host_tps = measure("off")
    return {
        "metric": f"guided_regex_decode_throughput_bs{batch}",
        "regex": CONSTRAINED_REGEX,
        "table_tps": round(table_tps, 2),
        "host_synced_tps": round(host_tps, 2),
        "speedup": round(table_tps / host_tps, 2) if host_tps else None,
    }


# Mixed continuous-batching probe: a Poisson arrival trace of mixed
# prompt lengths driven through the SAME engine twice — ragged unified
# dispatch (cfg.ragged="auto") vs the split prefill/decode baseline
# (cfg.ragged="off") — reporting tokens/sec AND TTFT percentiles for
# both. Greedy sampling, so the two paths must also be BIT-IDENTICAL
# per request (asserted, reported as mixed.bit_identical). Gated with
# the same trimmed-spread estimator as the headline metric.
MIXED_REQUESTS = 20
MIXED_PROMPT_LENS = (16, 48, 96, 160)
MIXED_MAX_NEW = 24
MIXED_MEAN_INTERARRIVAL_S = 0.015
MIXED_REPS = 4
# The --mla variant drives the SAME trace through the tiny MLA preset —
# the round-2 ragged latent path vs the phase-split baseline. The MLA
# win is smaller than the dense one (latent pools already shrink the KV
# read; ragged packing only removes the dispatch bubbles), so its gate
# asks for 1.1x instead of the dense block's 1.2x.
MIXED_MLA_GATE_RATIO = 1.1


def mixed_probe(model: str = "tiny", gate_ratio: float = 1.2) -> dict:
    import numpy as np

    from rbg_tpu.engine import Engine, EngineConfig, SamplingParams

    rng = np.random.RandomState(7)
    lens = [MIXED_PROMPT_LENS[rng.randint(len(MIXED_PROMPT_LENS))]
            for _ in range(MIXED_REQUESTS)]
    prompts = [rng.randint(1, 200, size=n).tolist() for n in lens]
    arrivals = np.cumsum(rng.exponential(MIXED_MEAN_INTERARRIVAL_S,
                                         size=MIXED_REQUESTS))

    def drive(eng):
        """One pass of the trace: wall-clock Poisson admissions against a
        continuously stepped engine. Returns (tokens/sec, ttfts, outputs
        keyed by arrival index)."""
        sp = SamplingParams(max_new_tokens=MIXED_MAX_NEW)
        t0 = time.perf_counter()
        nxt, ttft, outputs, idx_of = 0, {}, {}, {}
        arrive_at = {}
        total = 0
        while nxt < MIXED_REQUESTS or eng.has_work():
            now = time.perf_counter() - t0
            while nxt < MIXED_REQUESTS and arrivals[nxt] <= now:
                rid = eng.add_request(prompts[nxt], sp)
                idx_of[rid] = nxt
                arrive_at[rid] = t0 + arrivals[nxt]
                outputs[nxt] = []
                nxt += 1
            if not eng.has_work():
                time.sleep(0.0005)
                continue
            for ev in eng.step():
                total += 1
                i = idx_of.get(ev.request_id)
                if i is None:
                    continue
                outputs[i].append(ev.token)
                if i not in ttft:
                    ttft[i] = time.perf_counter() - arrive_at[ev.request_id]
        elapsed = time.perf_counter() - t0
        return total / elapsed, [ttft[i] for i in sorted(ttft)], outputs

    def mk_engine(ragged: str):
        from rbg_tpu.models.config import get_config
        eng = Engine(EngineConfig(
            model=model, page_size=16, num_pages=1024, max_batch=8,
            max_seq_len=min(512, get_config(model).max_seq_len),
            prefill_chunk=32, enable_radix_cache=False,
            decode_buckets=(8,), multi_step=MULTI_STEP, use_pallas="never",
            ragged=ragged))
        eng.warm_ragged()               # every (rows, tokens) ragged shape
        drive(eng)                      # warm: samplers + fused windows
        eng.warm_decode()               # full-window plain fused variants
        eng.warm_join_windows()         # K=1 early-exit fused variants
        eng.warm_samplers()             # host-path sampler per bucket
        return eng

    # Compile sentry (--jitwatch): everything mk_engine compiles is
    # warmup; once both engines exist the gate arms, and ANY compile
    # during the interleaved reps is a mid-measurement stall that
    # contaminates exactly one side — the probe FAILS on it.
    from rbg_tpu.utils import jitwatch
    if jitwatch.enabled():
        jitwatch.reset()

    # The two paths run INTERLEAVED, rep by rep, on two warm engines:
    # this machine's throughput is bimodal at multi-second granularity,
    # so measuring one path's reps back-to-back lets a slow regime land
    # entirely on one side and fake (or hide) a ratio. Interleaving puts
    # both paths in the same regime mix; the trimmed-spread gate (same
    # estimator and retry policy as the headline metric) re-measures a
    # whole attempt when even the interleaved reps came out contaminated.
    eng_ragged, eng_split = mk_engine("auto"), mk_engine("off")
    if jitwatch.enabled():
        jitwatch.warmup_complete()
    best, best_spread, attempt_spreads = None, None, []
    for _ in range(MAX_ATTEMPTS):
        ragged_runs, split_runs = [], []
        ragged_tt, split_tt = [], []
        ragged_out = split_out = None
        for _ in range(MIXED_REPS):
            tps, tt, ragged_out = drive(eng_ragged)
            ragged_runs.append(tps)
            ragged_tt.extend(tt)
            tps, tt, split_out = drive(eng_split)
            split_runs.append(tps)
            split_tt.extend(tt)
        s = max(trimmed_spread_of(ragged_runs),
                trimmed_spread_of(split_runs))
        attempt_spreads.append(round(s, 1) if math.isfinite(s) else None)
        if best_spread is None or s < best_spread:
            best = (ragged_runs, split_runs, ragged_tt, split_tt,
                    ragged_out, split_out)
            best_spread = s
        if s <= SPREAD_GATE_PCT:
            break
    ragged_runs, split_runs, ragged_tt, split_tt, ragged_out, split_out = best

    def side(runs, ttfts):
        s = sorted(ttfts)
        pct = lambda q: s[min(len(s) - 1, int(q * len(s)))]
        return {
            "tps": round(statistics.median(runs), 2),
            "runs_tps": [round(r, 1) for r in runs],
            "ttft_p50_ms": round(pct(0.50) * 1000, 2),
            "ttft_p95_ms": round(pct(0.95) * 1000, 2),
        }

    ragged = side(ragged_runs, ragged_tt)
    split = side(split_runs, split_tt)
    tps_ratio = (ragged["tps"] / split["tps"]) if split["tps"] else None
    ttft_cut = (100.0 * (1 - ragged["ttft_p50_ms"] / split["ttft_p50_ms"])
                if split["ttft_p50_ms"] else None)
    jw_violations = []
    if jitwatch.enabled():
        jw_violations = jitwatch.violations()
        jitwatch.reset()   # later probes' compiles are their own warmup
    return {
        **({"jitwatch_violations": jw_violations} if jw_violations else {}),
        "metric": (f"mixed_poisson_trace_{model}_bs8_"
                   f"n{MIXED_REQUESTS}_cpu"),
        "prompt_lens": list(MIXED_PROMPT_LENS),
        "mean_interarrival_ms": MIXED_MEAN_INTERARRIVAL_S * 1000,
        "ragged": ragged,
        "split": split,
        "tps_ratio": round(tps_ratio, 3) if tps_ratio else None,
        "ttft_p50_reduction_pct": (round(ttft_cut, 1)
                                   if ttft_cut is not None else None),
        "bit_identical": ragged_out == split_out,
        "spread_pct": (round(best_spread, 1)
                       if math.isfinite(best_spread) else None),
        "attempt_spreads_pct": attempt_spreads,
        "spread_estimator": "trimmed_minmax_drop1",
        "spread_gate": ("pass" if best_spread <= SPREAD_GATE_PCT
                        else "fail"),
        # The gate COUPLES speed to correctness: a ragged path that beats
        # the split baseline but diverges from its outputs is a
        # regression, never a pass.
        "gate_ratio": gate_ratio,
        # A mid-measurement compile (jitwatch) fails the A/B outright:
        # the stall landed on one side's reps and poisoned the ratio.
        "gate": ("pass" if (ragged_out == split_out)
                 and ((tps_ratio or 0) >= gate_ratio or (ttft_cut or 0) >= 30.0)
                 and not jw_violations
                 else "fail"),
    }


# Block-ragged kernel probe: the PR-7 token-grid ragged kernel (kept as
# ragged_paged_attention_pallas_tokengrid, bench baseline) vs the
# round-2 block-ragged grid, on a prefill-heavy pack — the mix the tile
# grid exists for (long prefill rows straddle tiles, decode singles
# share tiles with prefill tails). The two variants are REAL kernels
# only on a TPU; on CPU Pallas runs under the Python interpreter, whose
# timings say nothing about grid shape or DMA elision, so a CPU run
# reports the interpret-mode identity check plus measurable=false
# instead of publishing interpreter noise as a kernel ratio (honest-
# diagnostics precedent: BENCH_r05 tpu_probe).
BLOCK_RAGGED_SPECS = ((40, 40), (1, 96), (64, 64), (1, 30), (24, 24),
                      (1, 80), (48, 48))          # prefill-heavy mix
BLOCK_RAGGED_REPS = 5
BLOCK_RAGGED_ITERS = 20


def block_ragged_probe() -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from rbg_tpu.ops.pallas.ragged_attention_kernel import (
        ragged_paged_attention_pallas, ragged_paged_attention_pallas_tokengrid)
    from rbg_tpu.ops.ragged_paged_attention import ragged_paged_attention_xla

    on_tpu = jax.default_backend() == "tpu"
    H, hd, KV, page, NP, P = 8, 64, 4, 16, 128, 6
    rng = np.random.RandomState(31)
    k = jnp.asarray(rng.randn(NP, page, KV, hd), jnp.float32)
    v = jnp.asarray(rng.randn(NP, page, KV, hd), jnp.float32)
    perm = rng.permutation(NP - 1)[: len(BLOCK_RAGGED_SPECS) * P] + 1
    table = jnp.asarray(perm.reshape(len(BLOCK_RAGGED_SPECS), P), jnp.int32)
    kv_lens = jnp.asarray([kv for _, kv in BLOCK_RAGGED_SPECS], jnp.int32)
    T = sum(ql for ql, _ in BLOCK_RAGGED_SPECS)
    q = jnp.asarray(rng.randn(1, T, H, hd), jnp.float32)
    row_ids, q_pos = [], []
    for r, (ql, kv) in enumerate(BLOCK_RAGGED_SPECS):
        row_ids += [r] * ql
        q_pos += list(range(kv - ql, kv))
    row_ids = jnp.asarray(row_ids, jnp.int32)
    q_pos = jnp.asarray([q_pos], jnp.int32)
    args = (q, k, v, table, q_pos, kv_lens, row_ids)

    out = {
        "metric": ("ragged_kernel_tokengrid_vs_block_"
                   f"T{T}_rows{len(BLOCK_RAGGED_SPECS)}"),
        "prefill_heavy_specs": [list(s) for s in BLOCK_RAGGED_SPECS],
        "backend": jax.default_backend(),
        "measurable": on_tpu,
    }
    # Identity first (interpret mode off-TPU): a grid change that drifts
    # numerically is a regression whatever the timings say.
    ref = np.asarray(ragged_paged_attention_xla(*args))
    old = np.asarray(ragged_paged_attention_pallas_tokengrid(
        *args, interpret=not on_tpu))
    new = np.asarray(ragged_paged_attention_pallas(
        *args, interpret=not on_tpu))
    out["max_abs_diff_vs_xla"] = {
        "tokengrid": float(np.max(np.abs(old - ref))),
        "block_ragged": float(np.max(np.abs(new - ref))),
    }
    identical = bool(np.allclose(old, ref, rtol=1e-5, atol=1e-5)
                     and np.allclose(new, ref, rtol=1e-5, atol=1e-5))
    out["bit_identical"] = identical
    if not on_tpu:
        out["detail"] = ("kernel grids only exist on the TPU backend — "
                         "interpret-mode timings are Python-emulation "
                         "noise, not kernel launches; identity checked, "
                         "timing deferred to a TPU round")
        out["gate"] = "not_measurable"
        return out

    # TPU path: interleaved timed reps (bimodal-machine discipline).
    def timed(fn):
        t0 = time.perf_counter()
        for _ in range(BLOCK_RAGGED_ITERS):
            r = fn(*args)
        r.block_until_ready()
        return BLOCK_RAGGED_ITERS / (time.perf_counter() - t0)

    old_runs, new_runs = [], []
    for _ in range(BLOCK_RAGGED_REPS):
        old_runs.append(timed(ragged_paged_attention_pallas_tokengrid))
        new_runs.append(timed(ragged_paged_attention_pallas))
    ratio = statistics.median(new_runs) / statistics.median(old_runs)
    spread = max(trimmed_spread_of(old_runs), trimmed_spread_of(new_runs))
    out.update({
        "tokengrid_calls_per_s": round(statistics.median(old_runs), 1),
        "block_ragged_calls_per_s": round(statistics.median(new_runs), 1),
        "speedup": round(ratio, 3),
        "spread_pct": round(spread, 1) if math.isfinite(spread) else None,
        "spread_estimator": "trimmed_minmax_drop1",
        "gate": ("pass" if identical and ratio >= 1.15
                 and spread <= SPREAD_GATE_PCT else "fail"),
    })
    return out


# PD transfer-plane probe: the SAME PD pair drives a modeled link
# (kvtransfer.FakeICITransport — measured pacing, identical for both
# arms) twice per rep, INTERLEAVED: chunked layer-overlapped streaming
# (prefill publishes KV as chunks complete; decode admits at coverage)
# vs whole-bundle (all frames after prefill, admit at stream close).
# Metric: p50 time-to-first-DECODE-token — the decode-side stall the
# transfer plane shrinks (the first token's latency is identical by
# construction: it is produced prefill-side). Greedy sampling ⇒ the two
# arms must also be BIT-IDENTICAL per request (gate-coupled).
PD_STREAM_PROMPT_LEN = 96
PD_STREAM_REQUESTS = 4
PD_STREAM_REPS = 4
PD_STREAM_LINK_BYTES_PER_S = 2e6
PD_STREAM_MAX_NEW = 8


def pd_stream_probe() -> dict:
    import numpy as np

    from rbg_tpu.engine import EngineConfig, SamplingParams
    from rbg_tpu.engine.pd import PDStreamPair
    from rbg_tpu.kvtransfer import FakeICITransport

    rng = np.random.RandomState(13)
    cfg = EngineConfig(model="tiny", page_size=8, num_pages=512,
                       max_batch=4, max_seq_len=256, prefill_chunk=16,
                       enable_radix_cache=False, use_pallas="never")
    pair = PDStreamPair(cfg, transport=FakeICITransport(
        bytes_per_s=PD_STREAM_LINK_BYTES_PER_S, latency_s=0.0005))
    vocab = pair.prefill.engine.mcfg.vocab_size
    prompts = [rng.randint(1, vocab, size=PD_STREAM_PROMPT_LEN).tolist()
               for _ in range(PD_STREAM_REQUESTS)]
    sp = SamplingParams(max_new_tokens=PD_STREAM_MAX_NEW)
    # Warm both arms (jit compiles must not land in a timed rep).
    warm = rng.randint(1, vocab, size=PD_STREAM_PROMPT_LEN).tolist()
    pair.generate_one(warm, sp, stream=True, recv_timeout=120.0)
    pair.generate_one(warm, sp, stream=False, recv_timeout=120.0)

    def rep(stream: bool):
        ttfd, toks = [], []
        for p in prompts:
            r = pair.generate_one(p, sp, stream=stream,
                                  recv_timeout=120.0)
            ttfd.append(r["t_first_decode"])
            toks.append(r["tokens"])
        return statistics.median(ttfd), toks

    # Interleaved reps: this box's throughput is bimodal at multi-second
    # granularity — back-to-back arms fake (or hide) deltas. Trimmed
    # spread gates each arm like every other probe in this file.
    best = None
    attempt_spreads = []
    for _ in range(MAX_ATTEMPTS):
        s_runs, b_runs = [], []
        s_out = b_out = None
        for _ in range(PD_STREAM_REPS):
            p50, s_out = rep(stream=True)
            s_runs.append(p50)
            p50, b_out = rep(stream=False)
            b_runs.append(p50)
        spread = max(trimmed_spread_of(s_runs), trimmed_spread_of(b_runs))
        attempt_spreads.append(round(spread, 1)
                               if math.isfinite(spread) else None)
        if best is None or spread < best[0]:
            best = (spread, s_runs, b_runs, s_out, b_out)
        if spread <= SPREAD_GATE_PCT:
            break
    spread, s_runs, b_runs, s_out, b_out = best
    s_p50 = statistics.median(s_runs)
    b_p50 = statistics.median(b_runs)
    bit_identical = s_out == b_out
    delta_pct = 100.0 * (1 - s_p50 / b_p50) if b_p50 else None
    return {
        "metric": ("pd_first_decode_token_tiny_"
                   f"n{PD_STREAM_REQUESTS}x{PD_STREAM_REPS}_fakeici"),
        "link_bytes_per_s": PD_STREAM_LINK_BYTES_PER_S,
        "prompt_len": PD_STREAM_PROMPT_LEN,
        "stream_ttfd_p50_ms": round(s_p50 * 1000, 2),
        "bundle_ttfd_p50_ms": round(b_p50 * 1000, 2),
        "stream_runs_ms": [round(r * 1000, 1) for r in s_runs],
        "bundle_runs_ms": [round(r * 1000, 1) for r in b_runs],
        "ttfd_p50_reduction_pct": (round(delta_pct, 1)
                                   if delta_pct is not None else None),
        "bit_identical": bit_identical,
        "spread_pct": round(spread, 1) if math.isfinite(spread) else None,
        "attempt_spreads_pct": attempt_spreads,
        "spread_estimator": "trimmed_minmax_drop1",
        # The gate COUPLES speed to correctness: chunked streaming must
        # STRICTLY lower p50 decode-side TTFT AND decode bit-identically.
        "gate": ("pass" if bit_identical and s_p50 < b_p50
                 and spread <= SPREAD_GATE_PCT else "fail"),
    }


# Cache-hierarchy probe (Mooncake tier): a system-prompt-heavy trace —
# long shared prefixes, unique suffixes, round-robin across prefix
# groups so the deliberately undersized device pool EVICTS between
# groups — driven through two warm engines INTERLEAVED: host-DRAM spill
# tier under the radix cache vs the device-only pool (same pool size).
# Reports goodput (requests/s whose TTFT met the goal) and prefix-hit
# rate (radix + host hit tokens over prompt tokens). Greedy sampling,
# so the two arms must be BIT-IDENTICAL per request.
PREFIX_GROUPS = 4
PREFIX_LEN = 128
PREFIX_SUFFIX = 16
PREFIX_REQUESTS = 24
PREFIX_MAX_NEW = 8
PREFIX_INTERARRIVAL_S = 0.02
PREFIX_REPS = 4
PREFIX_TTFT_GOAL_S = 0.05
PREFIX_NUM_PAGES = 48
PREFIX_HOST_BYTES = 1 << 26


def prefix_probe() -> dict:
    import numpy as np

    from rbg_tpu.engine import Engine, EngineConfig, SamplingParams

    rng = np.random.RandomState(23)
    prefixes = [rng.randint(1, 200, size=PREFIX_LEN).tolist()
                for _ in range(PREFIX_GROUPS)]
    prompts = [prefixes[i % PREFIX_GROUPS]
               + rng.randint(1, 200, size=PREFIX_SUFFIX).tolist()
               for i in range(PREFIX_REQUESTS)]
    arrivals = np.cumsum(rng.exponential(PREFIX_INTERARRIVAL_S,
                                         size=PREFIX_REQUESTS))
    prompt_tokens = sum(len(p) for p in prompts)

    def drive(eng):
        """One pass of the trace. Returns (goodput_rps, hit_rate, ttfts,
        outputs)."""
        sp = SamplingParams(max_new_tokens=PREFIX_MAX_NEW)
        hit0 = (eng.metrics["radix_hit_tokens"]
                + eng.metrics["host_hit_tokens"])
        t0 = time.perf_counter()
        nxt, ttft, outputs, idx_of, arrive_at = 0, {}, {}, {}, {}
        while nxt < PREFIX_REQUESTS or eng.has_work():
            now = time.perf_counter() - t0
            while nxt < PREFIX_REQUESTS and arrivals[nxt] <= now:
                rid = eng.add_request(prompts[nxt], sp)
                idx_of[rid] = nxt
                arrive_at[rid] = t0 + arrivals[nxt]
                outputs[nxt] = []
                nxt += 1
            if not eng.has_work():
                time.sleep(0.0005)
                continue
            for ev in eng.step():
                i = idx_of.get(ev.request_id)
                if i is None:
                    continue
                outputs[i].append(ev.token)
                if i not in ttft:
                    ttft[i] = time.perf_counter() - arrive_at[ev.request_id]
        elapsed = time.perf_counter() - t0
        hits = (eng.metrics["radix_hit_tokens"]
                + eng.metrics["host_hit_tokens"]) - hit0
        met = sum(1 for t in ttft.values() if t <= PREFIX_TTFT_GOAL_S)
        return (met / elapsed, hits / prompt_tokens,
                [ttft[i] for i in sorted(ttft)], outputs)

    def mk_engine(host_bytes: int):
        eng = Engine(EngineConfig(
            model="tiny", page_size=8, num_pages=PREFIX_NUM_PAGES,
            max_batch=4, max_seq_len=256, prefill_chunk=16,
            decode_buckets=(4,), use_pallas="never",
            host_tier_bytes=host_bytes))
        eng.warm_ragged()
        drive(eng)                      # warm pass (compiles + fills tiers)
        eng.warm_join_windows()
        return eng

    # INTERLEAVED hierarchy-vs-device-only reps on two warm engines (the
    # bimodal-machine discipline — see mixed_probe).
    eng_h, eng_d = mk_engine(PREFIX_HOST_BYTES), mk_engine(0)
    best, best_spread, attempt_spreads = None, None, []
    for _ in range(MAX_ATTEMPTS):
        h_runs, d_runs, h_hits, d_hits = [], [], [], []
        h_tt, d_tt = [], []
        h_out = d_out = None
        for _ in range(PREFIX_REPS):
            g, hr, tt, h_out = drive(eng_h)
            h_runs.append(g)
            h_hits.append(hr)
            h_tt.extend(tt)
            g, hr, tt, d_out = drive(eng_d)
            d_runs.append(g)
            d_hits.append(hr)
            d_tt.extend(tt)
        s = max(trimmed_spread_of(h_runs), trimmed_spread_of(d_runs))
        attempt_spreads.append(round(s, 1) if math.isfinite(s) else None)
        if best_spread is None or s < best_spread:
            best = (h_runs, d_runs, h_hits, d_hits, h_tt, d_tt, h_out,
                    d_out)
            best_spread = s
        if s <= SPREAD_GATE_PCT:
            break
    h_runs, d_runs, h_hits, d_hits, h_tt, d_tt, h_out, d_out = best

    def side(runs, hits, ttfts, tier_stats=None):
        s = sorted(ttfts)
        pct = lambda q: s[min(len(s) - 1, int(q * len(s)))]
        out = {
            "goodput_rps": round(statistics.median(runs), 2),
            "runs_goodput_rps": [round(r, 2) for r in runs],
            "prefix_hit_rate": round(statistics.median(hits), 4),
            "ttft_p50_ms": round(pct(0.50) * 1000, 2),
            "ttft_p95_ms": round(pct(0.95) * 1000, 2),
        }
        if tier_stats is not None:
            out["host_tier"] = tier_stats
        return out
    hier = side(h_runs, h_hits, h_tt, eng_h.host_tier.stats())
    dev = side(d_runs, d_hits, d_tt)
    ratio = (hier["goodput_rps"] / dev["goodput_rps"]
             if dev["goodput_rps"] else None)
    return {
        "metric": (f"prefix_trace_tiny_pages{PREFIX_NUM_PAGES}_"
                   f"g{PREFIX_GROUPS}_n{PREFIX_REQUESTS}_cpu"),
        "ttft_goal_ms": PREFIX_TTFT_GOAL_S * 1000,
        "hierarchy": hier,
        "device_only": dev,
        "goodput_ratio": round(ratio, 3) if ratio else None,
        "hit_rate_gain": round(
            hier["prefix_hit_rate"] - dev["prefix_hit_rate"], 4),
        "bit_identical": h_out == d_out,
        "spread_pct": (round(best_spread, 1)
                       if math.isfinite(best_spread) else None),
        "attempt_spreads_pct": attempt_spreads,
        "spread_estimator": "trimmed_minmax_drop1",
        "spread_gate": ("pass" if best_spread <= SPREAD_GATE_PCT
                        else "fail"),
        # Speed coupled to correctness AND to the cache actually working:
        # the hierarchy must beat device-only on goodput AND hit rate
        # with bit-identical outputs.
        "gate": ("pass" if (h_out == d_out) and (ratio or 0) > 1.0
                 and hier["prefix_hit_rate"] > dev["prefix_hit_rate"]
                 else "fail"),
    }


def tpu_probe() -> dict:
    """Probe the chip in a THROWAWAY subprocess: the tunnel can wedge
    indefinitely (grant lost), and a hung probe must not hang the bench.
    Returns diagnostics either way."""
    code = ("import jax, jax.numpy as jnp; "
            "(jnp.ones((8,8))@jnp.ones((8,8))).block_until_ready(); "
            "print('ok', jax.default_backend())")
    t0 = time.monotonic()
    try:
        out = subprocess.run([sys.executable, "-c", code],
                             timeout=PROBE_TIMEOUT_S,
                             capture_output=True, text=True)
        elapsed = round(time.monotonic() - t0, 1)
        ok = "ok" in out.stdout
        return {
            "ok": ok, "elapsed_s": elapsed, "timeout_s": PROBE_TIMEOUT_S,
            "backend": out.stdout.split()[-1] if ok else None,
            "detail": None if ok else (
                "probe subprocess exited rc=%d" % out.returncode),
            "stderr_tail": None if ok else out.stderr[-400:] or None,
        }
    except subprocess.TimeoutExpired:
        return {
            "ok": False, "elapsed_s": round(time.monotonic() - t0, 1),
            "timeout_s": PROBE_TIMEOUT_S,
            "detail": ("probe subprocess hung past the timeout — the "
                       "platform tunnel wedged at jax import/first compute "
                       "(same failure judged reproducible in rounds 1-2)"),
        }


def main():
    flags = set(sys.argv[1:])
    probe = None
    if os.environ.get("RBG_BENCH_FORCE_CPU") != "1":
        probe = tpu_probe()
        if not probe["ok"]:
            # Re-exec on CPU so a wedged tunnel still yields a benchmark
            # line; carry the probe evidence into the fallback's JSON.
            from rbg_tpu.utils import scrubbed_cpu_env
            env = scrubbed_cpu_env(extra={
                "RBG_BENCH_FORCE_CPU": "1",
                _PROBE_ENV: json.dumps(probe),
            })
            os.execve(sys.executable,
                      [sys.executable, __file__] + sys.argv[1:], env)
    elif os.environ.get(_PROBE_ENV):
        probe = json.loads(os.environ[_PROBE_ENV])
    import jax

    if os.environ.get("RBG_BENCH_FORCE_CPU") == "1":
        # Externally-forced CPU runs may arrive WITHOUT the scrubbed env
        # the self-re-exec uses — pin the platform before the first
        # backend touch, or a wedged relay hangs the bench forever.
        jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from rbg_tpu.engine import Engine, EngineConfig, SamplingParams

    if "--jitwatch" in flags:
        # Compile sentry over the measurement windows: warn mode (record,
        # don't raise mid-rep) — violations fail the run via exit code
        # and the probes' gates. Armed before ANY engine exists so every
        # warmup compile is recorded as such.
        os.environ.setdefault("RBG_JITWATCH", "warn")
        from rbg_tpu.utils import jitwatch
        jitwatch.disarm()
        jitwatch.arm()

    if flags & {"--mla", "--block-ragged"}:
        # Selective mode: run only the requested blocks (still ONE JSON
        # line) — the full headline suite takes minutes and the ragged
        # round-2 artifacts only need these two.
        out = {"load1": round(os.getloadavg()[0], 2)}
        if "--mla" in flags:
            try:
                out["mixed_mla"] = mixed_probe(
                    model="tiny-mla", gate_ratio=MIXED_MLA_GATE_RATIO)
            except Exception as e:  # noqa: BLE001
                out["mixed_mla"] = {"error": f"{type(e).__name__}: {e}"}
        if "--block-ragged" in flags:
            try:
                out["block_ragged"] = block_ragged_probe()
            except Exception as e:  # noqa: BLE001
                out["block_ragged"] = {"error": f"{type(e).__name__}: {e}"}
        if probe is not None and not probe.get("ok"):
            out["tpu_probe"] = probe
        print(json.dumps(out))
        if _jitwatch_failed(flags, out):
            sys.exit(1)
        return

    on_tpu = jax.default_backend() == "tpu"
    model = "qwen2-0.5b" if on_tpu else "tiny"
    cfg = EngineConfig(
        model=model, page_size=16,
        num_pages=4096 if on_tpu else 512,
        max_batch=BATCH, max_seq_len=2048 if on_tpu else 512,
        prefill_chunk=PROMPT_LEN, enable_radix_cache=False,
        decode_buckets=(BATCH,), multi_step=MULTI_STEP,
    )
    eng = Engine(cfg)
    steps_per_rep = DECODE_TOKENS_PER_REP // MULTI_STEP
    rng = np.random.RandomState(0)
    vocab = cfg.model_config.vocab_size
    max_new = REPS * DECODE_TOKENS_PER_REP + 4 * MULTI_STEP + 8
    prompts = [rng.randint(0, vocab, size=PROMPT_LEN).tolist() for _ in range(BATCH)]

    def measure_once():
        """One gated attempt: fresh batch (identical shapes), warm-up,
        REPS timed windows, then release everything."""
        for p in prompts:
            eng.add_request(p, SamplingParams(max_new_tokens=max_new))
        while eng.waiting or any(r.state != "running" for r in eng.running):
            eng.step()
        for _ in range(4):
            eng.step()
        # Warm region over: arm the compile gate (idempotent; a no-op
        # unless --jitwatch installed the hooks). Any compile inside the
        # timed windows below is a recorded violation.
        from rbg_tpu.utils import jitwatch
        jitwatch.warmup_complete()
        runs = []
        for _ in range(REPS):
            start_tokens = eng.metrics["decode_tokens"]
            t0 = time.perf_counter()
            for _ in range(steps_per_rep):
                eng.step()
            elapsed = time.perf_counter() - t0
            tokens = eng.metrics["decode_tokens"] - start_tokens
            runs.append(tokens / elapsed)
        for r in list(eng.running):
            eng.cancel_request(r.id)
        return runs

    best_runs, best_spread, attempt_spreads = None, None, []
    for _ in range(MAX_ATTEMPTS):
        runs = measure_once()
        s = trimmed_spread_of(runs)
        # A zero-throughput attempt gives spread inf — keep the gate math
        # but never let Infinity reach the JSON line (unparseable).
        attempt_spreads.append(round(s, 1) if math.isfinite(s) else None)
        if best_spread is None or s < best_spread:
            best_runs, best_spread = runs, s
        if s <= SPREAD_GATE_PCT:
            break
    runs = best_runs
    tps = statistics.median(runs)
    raw_spread = spread_of(runs)

    jw = None
    if "--jitwatch" in flags:
        from rbg_tpu.utils import jitwatch
        jw = {"counters": jitwatch.counters(),
              "violations": jitwatch.violations(),
              "gate": "fail" if jitwatch.violations() else "pass"}
        jitwatch.reset()   # the probes below warm their own engines

    # MFU estimate: decode FLOPs/token ≈ 2·N_params (matmul MACs×2) plus
    # KV-read attention FLOPs (small at these lengths). Peak: v5e bf16
    # 197 TFLOP/s; CPU runs report mfu_est=null (no meaningful peak).
    mfu = None
    if on_tpu:
        flops_per_tok = 2.0 * cfg.model_config.num_params
        mfu = round(tps * flops_per_tok / 197e12, 5)
    out = {
        "metric": f"engine_decode_throughput_{model}_bs{BATCH}_{jax.default_backend()}",
        "value": round(tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / TARGET_TOKENS_PER_SEC, 4),
        "mfu_est": mfu,
        "runs_tps": [round(r, 1) for r in runs],
        "spread_pct": (round(best_spread, 1)
                       if math.isfinite(best_spread) else None),
        "raw_spread_pct": (round(raw_spread, 1)
                           if math.isfinite(raw_spread) else None),
        "spread_estimator": "trimmed_minmax_drop1",
        "spread_gate_pct": SPREAD_GATE_PCT,
        "spread_gate": ("pass" if best_spread <= SPREAD_GATE_PCT
                        else "fail"),
        "attempt_spreads_pct": attempt_spreads,
        "load1": round(os.getloadavg()[0], 2),
    }
    if jw is not None:
        out["jitwatch"] = jw
    # Constrained-decode probe rides along — a probe failure must never
    # cost the headline line.
    try:
        out["constrained"] = constrained_probe(CONSTRAINED_BATCH)
    except Exception as e:  # noqa: BLE001 — diagnostics beat a dead line
        out["constrained"] = {"error": f"{type(e).__name__}: {e}"}
    # Mixed continuous-batching probe (ragged unified dispatch vs the
    # split prefill/decode baseline under a Poisson arrival trace) —
    # same failure isolation.
    try:
        out["mixed"] = mixed_probe()
    except Exception as e:  # noqa: BLE001 — diagnostics beat a dead line
        out["mixed"] = {"error": f"{type(e).__name__}: {e}"}
    # MLA variant of the mixed trace (ragged latent path vs phase-split)
    # and the kernel-level token-grid vs block-ragged A/B.
    try:
        out["mixed_mla"] = mixed_probe(model="tiny-mla",
                                       gate_ratio=MIXED_MLA_GATE_RATIO)
    except Exception as e:  # noqa: BLE001 — diagnostics beat a dead line
        out["mixed_mla"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        out["block_ragged"] = block_ragged_probe()
    except Exception as e:  # noqa: BLE001 — diagnostics beat a dead line
        out["block_ragged"] = {"error": f"{type(e).__name__}: {e}"}
    # PD transfer-plane probe (chunked layer-overlapped KV streaming vs
    # whole-bundle over the same modeled link) — same failure isolation.
    try:
        out["pd_stream"] = pd_stream_probe()
    except Exception as e:  # noqa: BLE001 — diagnostics beat a dead line
        out["pd_stream"] = {"error": f"{type(e).__name__}: {e}"}
    # Cache-hierarchy probe (host-DRAM spill tier vs device-only pool on
    # a long-shared-prefix trace) — same failure isolation.
    try:
        out["prefix"] = prefix_probe()
    except Exception as e:  # noqa: BLE001 — diagnostics beat a dead line
        out["prefix"] = {"error": f"{type(e).__name__}: {e}"}
    if probe is not None and not probe.get("ok"):
        out["tpu_probe"] = probe
    print(json.dumps(out))
    if _jitwatch_failed(flags, out):
        sys.exit(1)


def _jitwatch_failed(flags: set, out: dict) -> bool:
    """True when --jitwatch ran and recorded a mid-measurement compile —
    in the headline windows or either side of an interleaved A/B probe."""
    if "--jitwatch" not in flags:
        return False
    if out.get("jitwatch", {}).get("gate") == "fail":
        return True
    return any(isinstance(v, dict) and v.get("jitwatch_violations")
               for v in out.values())


if __name__ == "__main__":
    main()
