"""Headline benchmark — prints ONE JSON line.

Metric: steady-state decode throughput (tokens/sec) of the FULL serving
engine (paged KV + continuous batching + device sampling) on the available
chip — qwen2-0.5b-geometry model, randomly initialized (zero-egress
environment; throughput is weight-value-independent).

The reference publishes no benchmark numbers (BASELINE.md), so
``vs_baseline`` is reported against this repo's recorded round-0 target.
"""

import json
import os
import subprocess
import sys
import time

# Round-0 target (tokens/sec) anchoring cross-round comparison; the reference
# publishes nothing for this metric (BASELINE.md).
TARGET_TOKENS_PER_SEC = 2000.0

BATCH = 8
PROMPT_LEN = 128
DECODE_STEPS = 64
PROBE_TIMEOUT_S = 240


def tpu_reachable() -> bool:
    """Probe the chip in a THROWAWAY subprocess: the tunnel can wedge
    indefinitely (grant lost), and a hung probe must not hang the bench."""
    code = "import jax, jax.numpy as jnp; (jnp.ones((8,8))@jnp.ones((8,8))).block_until_ready(); print('ok')"
    try:
        out = subprocess.run([sys.executable, "-c", code], timeout=PROBE_TIMEOUT_S,
                             capture_output=True, text=True)
        return "ok" in out.stdout
    except subprocess.TimeoutExpired:
        return False


def main():
    if os.environ.get("RBG_BENCH_FORCE_CPU") != "1":
        if not tpu_reachable():
            # Re-exec on CPU so a wedged tunnel still yields a benchmark line.
            from rbg_tpu.utils import scrubbed_cpu_env
            env = scrubbed_cpu_env(extra={"RBG_BENCH_FORCE_CPU": "1"})
            os.execve(sys.executable, [sys.executable, __file__], env)
    import jax
    import numpy as np

    from rbg_tpu.engine import Engine, EngineConfig, SamplingParams

    on_tpu = jax.default_backend() == "tpu"
    model = "qwen2-0.5b" if on_tpu else "tiny"
    cfg = EngineConfig(
        model=model, page_size=16,
        num_pages=4096 if on_tpu else 512,
        max_batch=BATCH, max_seq_len=2048 if on_tpu else 512,
        prefill_chunk=PROMPT_LEN, enable_radix_cache=False,
        decode_buckets=(BATCH,),
    )
    eng = Engine(cfg)
    rng = np.random.RandomState(0)
    vocab = cfg.model_config.vocab_size
    prompts = [rng.randint(0, vocab, size=PROMPT_LEN).tolist() for _ in range(BATCH)]

    # Warm-up: admit + prefill everything, compile decode bucket, settle.
    for p in prompts:
        eng.add_request(p, SamplingParams(max_new_tokens=DECODE_STEPS + 8))
    while eng.waiting or any(r.state != "running" for r in eng.running):
        eng.step()
    for _ in range(4):
        eng.step()

    start_tokens = eng.metrics["decode_tokens"]
    t0 = time.perf_counter()
    for _ in range(DECODE_STEPS):
        eng.step()
    elapsed = time.perf_counter() - t0
    tokens = eng.metrics["decode_tokens"] - start_tokens

    tps = tokens / elapsed

    # MFU estimate: decode FLOPs/token ≈ 2·N_params (matmul MACs×2) plus
    # KV-read attention FLOPs (small at these lengths). Peak: v5e bf16
    # 197 TFLOP/s; CPU runs report mfu_est=null (no meaningful peak).
    mfu = None
    if on_tpu:
        flops_per_tok = 2.0 * cfg.model_config.num_params
        mfu = round(tps * flops_per_tok / 197e12, 5)
    print(json.dumps({
        "metric": f"engine_decode_throughput_{model}_bs{BATCH}_{jax.default_backend()}",
        "value": round(tps, 2),
        "unit": "tokens/sec",
        "vs_baseline": round(tps / TARGET_TOKENS_PER_SEC, 4),
        "mfu_est": mfu,
    }))


if __name__ == "__main__":
    main()
