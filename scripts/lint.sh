#!/usr/bin/env bash
# Static-analysis gate: the domain rules (rbg-tpu lint) + ruff (generic
# pyflakes/pycodestyle tier, config in pyproject.toml [tool.ruff]).
#
#   scripts/lint.sh              # lint rbg_tpu/ (the repo gate)
#   scripts/lint.sh PATH...      # lint specific files/dirs
#
# ruff is OPTIONAL: this container image does not ship it and nothing may
# be pip-installed here, so when the binary is absent we run the domain
# rules alone and say so. CI images that have ruff get both tiers.
set -o pipefail
cd "$(dirname "$0")/.."

PATHS=("$@")
if [ ${#PATHS[@]} -eq 0 ]; then
    PATHS=(rbg_tpu)
fi

rc=0

echo "== rbg-tpu lint ${PATHS[*]} =="
python -m rbg_tpu.cli.main lint "${PATHS[@]}" || rc=1

if command -v ruff >/dev/null 2>&1; then
    echo "== ruff check ${PATHS[*]} =="
    ruff check "${PATHS[@]}" || rc=1
else
    echo "== ruff not installed: skipping the generic tier (domain rules ran) =="
fi

exit "$rc"
