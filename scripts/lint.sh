#!/usr/bin/env bash
# Static-analysis gate: the domain rules (rbg-tpu lint) + ruff (generic
# pyflakes/pycodestyle tier, config in pyproject.toml [tool.ruff]).
#
#   scripts/lint.sh                  # lint rbg_tpu/ (the repo gate)
#   scripts/lint.sh PATH...          # lint specific files/dirs
#   scripts/lint.sh --json [PATH...] # machine-readable findings
#                                    #   (file/line/col/rule/message/
#                                    #   severity/fingerprint); skips the
#                                    #   ruff tier so stdout stays pure
#                                    #   JSON. fingerprint = sha1 of
#                                    #   file:rule:normalized-line —
#                                    #   stable across line-number churn,
#                                    #   the key for finding trackers.
#   scripts/lint.sh --changed        # only files changed vs git HEAD —
#                                    #   the fast pre-commit mode
#   scripts/lint.sh --baseline FILE  # suppress blessed fingerprints; NEW
#                                    #   findings and stale entries still
#                                    #   fail. Default paths auto-apply
#                                    #   scripts/lint-baseline.json.
#
# ruff is OPTIONAL: this container image does not ship it and nothing may
# be pip-installed here, so when the binary is absent we run the domain
# rules alone and say so. CI images that have ruff get both tiers.
set -o pipefail
cd "$(dirname "$0")/.."

LINT_FLAGS=()
JSON=0
CHANGED=0
while [[ "${1:-}" == --* ]]; do
    case "$1" in
        --json) JSON=1; LINT_FLAGS+=(--format json) ;;
        --changed) CHANGED=1; LINT_FLAGS+=(--changed) ;;
        --baseline)
            # Value-taking flag: suppress findings fingerprinted in the
            # checked-in baseline JSON; NEW findings (and stale baseline
            # entries) still fail. See docs/static-analysis.md.
            if [ -z "${2:-}" ]; then
                echo "scripts/lint.sh: --baseline needs a FILE" >&2; exit 2
            fi
            LINT_FLAGS+=(--baseline "$2"); USER_BASELINE=1; shift ;;
        *) echo "scripts/lint.sh: unknown flag $1" >&2; exit 2 ;;
    esac
    shift
done

PATHS=("$@")
if [ ${#PATHS[@]} -eq 0 ]; then
    PATHS=(rbg_tpu)
    # The repo gate runs against the checked-in baseline (empty while the
    # tree is clean — it exists so the suppress/stale plumbing is always
    # exercised and the workflow documented; see docs/static-analysis.md).
    if [ -z "${USER_BASELINE:-}" ] && [ -f scripts/lint-baseline.json ]; then
        LINT_FLAGS+=(--baseline scripts/lint-baseline.json)
    fi
fi

rc=0

if [ "$JSON" -eq 0 ]; then
    echo "== rbg-tpu lint ${LINT_FLAGS[*]} ${PATHS[*]} =="
fi
python -m rbg_tpu.cli.main lint ${LINT_FLAGS[@]+"${LINT_FLAGS[@]}"} "${PATHS[@]}" || rc=1

if [ "$JSON" -eq 1 ]; then
    # Machine mode: stdout is the findings JSON alone; ruff would pollute it.
    exit "$rc"
fi

if [ "$CHANGED" -eq 1 ]; then
    # Fast pre-commit mode: the domain rules already ran over just the
    # changed files; a full-tree ruff sweep here would defeat the point
    # (and fail on files the commit never touched).
    echo "== --changed: skipping the ruff tier (run scripts/lint.sh for the full gate) =="
elif command -v ruff >/dev/null 2>&1; then
    echo "== ruff check ${PATHS[*]} =="
    ruff check "${PATHS[@]}" || rc=1
else
    echo "== ruff not installed: skipping the generic tier (domain rules ran) =="
fi

exit "$rc"
