#!/usr/bin/env bash
# Tier-1 test runner — the exact ROADMAP.md verify command (dots counting
# included) so builders run the same gate the driver enforces, plus an
# audit mode for keeping the suite inside its 870 s budget:
#
#   scripts/tier1.sh              # the gate: run tier-1, print DOTS_PASSED
#   scripts/tier1.sh --audit      # + pytest --durations=25: find the tests
#                                 #   to mark `slow` when the budget creeps
#   scripts/tier1.sh --lint       # static analysis FIRST (scripts/lint.sh:
#                                 #   rbg-tpu lint + ruff when available),
#                                 #   then the test gate, same 870 s budget
#   scripts/tier1.sh [pytest args...]   # extra args pass through
#
# Policy (CHANGES.md PR-2): heavy equivalence/e2e drills are marked `slow`
# and excluded here; run them explicitly with `pytest -m slow`. Mark any
# NEW heavy drill slow from the start — the budget has little headroom.
set -o pipefail
cd "$(dirname "$0")/.."

EXTRA=()
if [ "${1:-}" = "--audit" ]; then
    shift
    EXTRA+=(--durations=25)
elif [ "${1:-}" = "--lint" ]; then
    shift
    if ! scripts/lint.sh; then
        echo "TIER1 LINT FAILED — fix the findings (or justify with" \
             "'# lint: allow[rule] why' inline comments) before running tests" >&2
        exit 1
    fi
    # Dynamic complement to the guarded-by rule: a short overload drill
    # with the race detector AND request tracing armed. Catches unlocked
    # guarded-field access on real code paths the AST engine cannot see,
    # and asserts the trace layer produces a complete, non-empty
    # slowest-request waterfall (runs OUTSIDE the 870 s pytest budget,
    # only in --lint mode; the full preemption drill is the acceptance
    # run, kept out of the gate for time).
    echo "== rbg-tpu stress --scenario overload --racetrace --trace (smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario overload --racetrace --trace --clients 2 --requests 2 \
            --max-queue 2 --max-batch 1 --timeout-s 60 --json >/tmp/_t1_race.json; then
        echo "TIER1 RACETRACE SMOKE FAILED — see /tmp/_t1_race.json" \
             "(race_free/invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json, sys
r = json.load(open('/tmp/_t1_race.json'))
t = r.get('trace') or {}
assert t.get('waterfall'), 'slowest-trace waterfall is empty'
assert r['invariants'].get('trace_complete'), 'trace_complete invariant red'
assert r['invariants'].get('slo_accounted'), 'slo_accounted invariant red'
assert (r.get('slo') or {}).get('judged', 0) > 0, 'no SLO judgments'
assert 'goodput_rps' in (r.get('goodput_vs_throughput') or {}), \
    'goodput-vs-throughput summary missing'
"; then
        echo "TIER1 TRACE/SLO SMOKE FAILED — empty waterfall, incomplete" \
             "traces, or missing SLO accounting in /tmp/_t1_race.json" >&2
        exit 1
    fi
    # Dynamic complement to the jit-hygiene/bucket-discipline rules: the
    # overload drill with the compile sentry armed. The service warms up
    # (recording the blessed compile set, then warmup_complete() arms the
    # gate) and the drill itself must compile NOTHING cataloged — one
    # post-warmup compile of a rbg_* program is the mid-serving stall the
    # static rules exist to prevent, and fails this smoke red. Outside
    # the 870 s pytest budget, --lint mode only; capped at 300 s.
    echo "== rbg-tpu stress --scenario overload --jitwatch (compile-sentry smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario overload --jitwatch --clients 2 --requests 2 \
            --max-queue 2 --max-batch 1 --timeout-s 60 --json >/tmp/_t1_jitwatch.json; then
        echo "TIER1 JITWATCH SMOKE FAILED — see /tmp/_t1_jitwatch.json" \
             "(zero_unwarmed_compiles/invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_jitwatch.json'))
jw = r.get('jitwatch') or {}
assert r['invariants'].get('zero_unwarmed_compiles'), \
    'post-warmup compiles: %s' % jw.get('violations')
assert jw.get('counters', {}).get('rbg_jit_compiles_total', 0) > 0, \
    'sentry recorded no compiles at all — hook not installed?'
assert jw.get('warmed_programs'), 'no cataloged program in the warmup set'
"; then
        echo "TIER1 JITWATCH SMOKE FAILED — unwarmed post-warmup compiles" \
             "or a dead sentry in /tmp/_t1_jitwatch.json" >&2
        exit 1
    fi
    # Dynamic complement to the wire rules (op-registry/field-discipline/
    # error-code-flow): the overload drill with the frame validator armed
    # at the codec seam. Every frame that crosses send_msg/recv_msg is
    # checked against the api/ops.py catalog; one undeclared field or
    # unknown op reds wire_contract_clean and fails this smoke. Outside
    # the 870 s pytest budget, --lint mode only; capped at 300 s. (The
    # overload scenario exercises the service in-process; the ha smoke
    # below also arms --wirecheck and validates real TCP frames.)
    echo "== rbg-tpu stress --scenario overload --wirecheck (wire-contract smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario overload --wirecheck --clients 2 --requests 2 \
            --max-queue 2 --max-batch 1 --timeout-s 60 --json >/tmp/_t1_wirecheck.json; then
        echo "TIER1 WIRECHECK SMOKE FAILED — see /tmp/_t1_wirecheck.json" \
             "(wire_contract_clean/invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_wirecheck.json'))
wc = r.get('wirecheck') or {}
assert r['invariants'].get('wire_contract_clean'), \
    'wire contract violations: %s' % wc.get('violations_by_key')
assert 'rbg_wire_frames_checked' in wc.get('counters', {}), \
    'sentry report missing — --wirecheck fold did not run'
"; then
        echo "TIER1 WIRECHECK SMOKE FAILED — contract violations or a dead" \
             "sentry in /tmp/_t1_wirecheck.json" >&2
        exit 1
    fi
    # Capacity-follows-load smoke: the autoscale drill against a live
    # mini-plane (diurnal + burst trace; the AutoscaleController must
    # raise targets within an evaluation period of the burst, drop them
    # after, and scale down through the drain path without dropping one
    # in-flight stream). Outside the 870 s pytest budget, --lint only.
    echo "== rbg-tpu stress --scenario autoscale (capacity-follows-load smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario autoscale --json \
            >/tmp/_t1_autoscale.json; then
        echo "TIER1 AUTOSCALE SMOKE FAILED — see /tmp/_t1_autoscale.json" \
             "(invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_autoscale.json'))
inv = r.get('invariants') or {}
assert inv.get('capacity_follows_load'), \
    'targets did not track the burst: %s' % r.get('burst_react_s')
assert inv.get('zero_dropped_streams'), \
    'scale-down dropped streams: %s' % (r.get('requests') or {})
assert inv.get('slo_accounted'), 'finished != judged'
assert len(r.get('curve') or []) > 10, 'capacity-vs-load curve is empty'
"; then
        echo "TIER1 AUTOSCALE SMOKE FAILED — capacity-follows-load or" \
             "zero-dropped-streams invariant red in /tmp/_t1_autoscale.json" >&2
        exit 1
    fi
    # KV transfer-plane smoke: chunked PD streaming over an injected slow
    # lossy link (reorder + duplicates + one truncated stream). Asserts
    # kv_stream_overlap (decode starts before the stream closes),
    # directory_consistent (no lookup returns an evicted prefix),
    # zero_dropped_streams (truncation retried token-exact), and that
    # layer-sliced admission ENGAGED — at least one row admitted at
    # layer-k coverage with full coverage still pending. Outside the
    # 870 s pytest budget, --lint mode only.
    echo "== rbg-tpu stress --scenario kvstream --kv-slow-link --jitwatch (smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario kvstream --kv-slow-link 0.05 --jitwatch --json \
            >/tmp/_t1_kvstream.json; then
        echo "TIER1 KVSTREAM SMOKE FAILED — see /tmp/_t1_kvstream.json" \
             "(invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_kvstream.json'))
inv = r.get('invariants') or {}
assert inv.get('kv_stream_overlap'), \
    'decode never overlapped the stream: %s' % (r.get('transfer') or {})
assert inv.get('directory_consistent'), 'directory returned evicted prefix'
assert inv.get('zero_dropped_streams'), \
    'streams dropped: %s' % (r.get('requests') or {})
assert r.get('bit_identical'), 'streamed decode diverged from reference'
la = (r.get('transfer') or {}).get('layer_admit') or {}
assert la.get('engaged_requests', 0) >= 1, \
    'layer-sliced admission never engaged: %s' % la
assert any(c and c[0] < c[1]
           for c in la.get('coverage_at_admit') or []), \
    'no stream admitted with full coverage still pending: %s' % la
assert inv.get('zero_unwarmed_compiles'), \
    'measured phase compiled a cataloged program: %s' % \
    (r.get('jitwatch') or {}).get('violations')
"; then
        echo "TIER1 KVSTREAM SMOKE FAILED — overlap/directory/zero-drop" \
             "invariant red in /tmp/_t1_kvstream.json" >&2
        exit 1
    fi
    # Cache-hierarchy smoke: the Mooncake-tier drill — an undersized
    # device pool spilling into the host-DRAM tier under shared-prefix
    # churn, with predictive early rejection at admission. Asserts
    # tier_accounting (every cached page in exactly one tier, lifetime
    # identity closes), directory_consistent (tier-tagged claims backed
    # by the tiers), early_reject_before_prefill (rejected requests
    # consumed ZERO prefill steps), and zero_dropped_streams (everything
    # completes bit-identical or is a structured rejection). Outside the
    # 870 s pytest budget, --lint mode only.
    echo "== rbg-tpu stress --scenario prefixcache (cache-hierarchy smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario prefixcache --json \
            >/tmp/_t1_prefixcache.json; then
        echo "TIER1 PREFIXCACHE SMOKE FAILED — see /tmp/_t1_prefixcache.json" \
             "(invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_prefixcache.json'))
inv = r.get('invariants') or {}
assert inv.get('tier_accounting'), \
    'a cached page escaped tier accounting: %s' % (r.get('hierarchy') or {})
assert inv.get('directory_consistent'), 'directory overclaimed a tier'
assert inv.get('early_reject_before_prefill'), \
    'a rejected request consumed prefill: %s' % (r.get('burst') or {})
assert inv.get('zero_dropped_streams'), \
    'requests dropped: %s' % (r.get('burst') or {})
assert r.get('bit_identical'), 'hierarchy output diverged from reference'
tier = (r.get('hierarchy') or {}).get('host_tier') or {}
assert tier.get('spilled_pages', 0) > 0, 'nothing ever spilled'
assert tier.get('promoted_pages', 0) > 0, 'nothing ever promoted'
"; then
        echo "TIER1 PREFIXCACHE SMOKE FAILED — tier-accounting/early-" \
             "rejection invariant red in /tmp/_t1_prefixcache.json" >&2
        exit 1
    fi
    # Adaptive-topology smoke: the agg<->disagg drill at 1 repetition
    # (the goodput-vs-static gate needs interleaved reps and runs in the
    # full acceptance drill; the smoke asserts the safety + convergence
    # invariants and a non-empty goodput curve). Includes the real-engine
    # token-exact leg (mid-flip stream cut -> bundle fallback). Outside
    # the 870 s pytest budget, --lint mode only; capped at 300 s.
    echo "== rbg-tpu stress --scenario topoflip --reps 1 (adaptive topology smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario topoflip --reps 1 --json \
            >/tmp/_t1_topoflip.json; then
        echo "TIER1 TOPOFLIP SMOKE FAILED — see /tmp/_t1_topoflip.json" \
             "(invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_topoflip.json'))
inv = r.get('invariants') or {}
assert inv.get('zero_dropped_streams'), \
    'a flip dropped streams: %s' % r.get('dropped_streams')
assert inv.get('topology_converged'), \
    'controller never converged on the mix shift: %s' % [
        x.get('flip_started_after_shift_s')
        for x in (r.get('reps') or {}).get('adaptive', [])]
assert inv.get('no_flap'), 'flip count exceeded the flap bound'
assert inv.get('bit_identical'), \
    'mid-flip stream cut diverged from the unified reference: %s' \
    % r.get('token_exact')
curve = r.get('curve') or []
assert len(curve) > 10 and any(
    c.get('goodput_frac', 0) > 0 for c in curve), \
    'goodput curve empty or all-zero'
"; then
        echo "TIER1 TOPOFLIP SMOKE FAILED — zero-dropped/converged/" \
             "bit-identical invariant or goodput curve red in" \
             "/tmp/_t1_topoflip.json" >&2
        exit 1
    fi
    # HA smoke: kill-the-leader-mid-churn (standby resumes the mid-flight
    # migration AND topology flip exactly once; the deposed leader's
    # replayed writes are fenced; a live stream spans the failover) plus
    # kill-a-router-mid-stream (affected sessions re-hash and replay
    # token-exact, untouched sessions undisturbed) and the 1-vs-N ratio
    # identity. Runs with --wirecheck: this is the one smoke whose frames
    # cross real TCP, so the frame validator sees live traffic here.
    # Outside the 870 s pytest budget, --lint only; 300 s cap.
    echo "== rbg-tpu stress --scenario ha --wirecheck (leader failover + router kill smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario ha --wirecheck --json >/tmp/_t1_ha.json; then
        echo "TIER1 HA SMOKE FAILED — see /tmp/_t1_ha.json (invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_ha.json'))
inv = r.get('invariants') or {}
assert inv.get('leader_failover_completed'), \
    'standby never took the lease: %s' % (r.get('plane_ha') or {}).get('electors')
assert inv.get('migration_completed_by_standby') \
    and inv.get('flip_completed_by_standby'), \
    'standby did not finish the mid-flight machines: %s' \
    % (r.get('plane_ha') or {}).get('mid_state_at_takeover')
assert inv.get('deposed_writes_fenced'), 'a deposed write landed'
assert inv.get('no_double_actuation'), \
    'flip/migration actuated twice: %s' % {
        k: (r.get('plane_ha') or {}).get(k)
        for k in ('flips', 'migrations_completed')}
assert inv.get('zero_dropped_streams_plane') \
    and inv.get('zero_dropped_streams_tier'), 'a failover dropped streams'
assert inv.get('router_kill_token_exact') \
    and inv.get('untouched_sessions_undisturbed'), \
    'router kill broke a stream: %s' % (r.get('router_kill') or {})
assert inv.get('ratio_identical_1_vs_n'), \
    'tier ratio depends on router count: %s' % (r.get('ratio_identity') or {})
wc = r.get('wirecheck') or {}
assert inv.get('wire_contract_clean'), \
    'wire contract violations on live TCP: %s' % wc.get('violations_by_key')
assert wc.get('counters', {}).get('rbg_wire_frames_checked', 0) > 0, \
    'wirecheck saw no frames — sentry armed too late?'
"; then
        echo "TIER1 HA SMOKE FAILED — failover/fencing/token-exact" \
             "invariant red in /tmp/_t1_ha.json" >&2
        exit 1
    fi
    # Partition-tolerance smoke: the deterministic chaos plane thrown at
    # the production seams. Corrupted KV chunks must be caught at commit
    # and replayed token-exact (no_silent_corruption), the directory
    # breaker must degrade-not-block and reconnect via one half-open
    # probe, a silent tier member spills and re-admits, and a leader
    # whose renewals raise self-demotes BEFORE the TTL. Outside the
    # 870 s pytest budget, --lint only; 300 s cap.
    echo "== rbg-tpu stress --scenario partition (chaos-plane smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python -m rbg_tpu.cli.main \
            stress --scenario partition --json >/tmp/_t1_partition.json; then
        echo "TIER1 PARTITION SMOKE FAILED — see /tmp/_t1_partition.json" \
             "(invariants)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_partition.json'))
inv = r.get('invariants') or {}
assert inv.get('no_silent_corruption'), \
    'corruption not detected/recovered: %s' % (r.get('corruption') or {})
assert inv.get('zero_dropped_streams'), \
    'a wounded stream was dropped: %s' % (r.get('corruption') or {})
assert inv.get('degraded_not_down'), \
    'directory loss blocked instead of degrading: %s' \
    % (r.get('directory') or {})
assert inv.get('recovery_bounded_directory') \
    and inv.get('recovery_bounded_peer_feed') \
    and inv.get('recovery_bounded_lease'), \
    'post-heal recovery unbounded: %s' % {
        k: v for k, v in inv.items() if k.startswith('recovery_')}
assert inv.get('stale_peer_excluded'), \
    'silent tier member kept routable: %s' % (r.get('peer_staleness') or {})
assert inv.get('leader_self_demoted_before_ttl'), \
    'leader outlived its failed renewals: %s' % (r.get('lease') or {})
assert inv.get('all_faults_counted'), \
    'an injected fault class went uncounted: %s' % r.get('faults_injected')
"; then
        echo "TIER1 PARTITION SMOKE FAILED — corruption/degrade/recovery" \
             "invariant red in /tmp/_t1_partition.json" >&2
        exit 1
    fi
    # Control-plane fleet smoke: the 10k-node drill at ~500 nodes. Asserts
    # the control-plane observability invariants (workqueues drain to
    # empty, no stuck keys, event-recorder accounting) and that the
    # reconcile-latency and scheduler-throughput curves are NON-EMPTY —
    # the baseline the watch/informer refactor will be judged against.
    # Outside the 870 s pytest budget, --lint mode only.
    echo "== rbg-tpu stress --scenario fleet --nodes 500 (control-plane smoke) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 480 python -m rbg_tpu.cli.main \
            stress --scenario fleet --nodes 500 --groups 24 \
            --ab-reps 2 --ab-groups 12 --json \
            >/tmp/_t1_fleet.json; then
        echo "TIER1 FLEET SMOKE FAILED — see /tmp/_t1_fleet.json" \
             "(invariants incl. the event-plane throughput-rep gate)" >&2
        exit 1
    fi
    if ! python -c "
import json
r = json.load(open('/tmp/_t1_fleet.json'))
inv = r.get('invariants') or {}
assert inv.get('workqueue_drained'), 'workqueues never drained to empty'
assert inv.get('no_stuck_keys'), 'stuck keys: %s' % r.get('stuck_keys')
assert inv.get('events_accounted'), 'event recorder lost occurrences: %s' \
    % r.get('events')
assert r.get('reconcile_latency'), 'reconcile-latency curves are empty'
# Scheduler-throughput floor: a 24-group wave (96 pods) over a ~2 s bind
# window must clear 10 binds/s at peak, or the scheduler regressed.
peak = max((c.get('binds_per_s', 0)
            for c in r.get('throughput_curve') or []), default=0)
assert peak >= 10, 'scheduler-throughput floor: peak %.1f binds/s < 10' % peak
# Event-plane throughput reps: section present, non-empty, every rep
# completed, dedup engaged (the watch-carried plane doing real work).
ab = r.get('event_reps') or {}
assert ab.get('reps'), 'event-plane throughput-rep section missing or empty'
assert all(len(v) > 0 for v in ab['reps'].values()), 'throughput reps missing'
assert ab.get('reps_ok'), 'a throughput repetition failed to complete'
assert (ab.get('median') or {}).get('event', {}).get('deduped_total', 0) \
    > 0, 'throughput reps recorded zero dedup — event plane not engaged'
"; then
        echo "TIER1 FLEET SMOKE FAILED — drained/stuck-keys/events, the" \
             "throughput floor, or the event-plane throughput-rep section" \
             "in /tmp/_t1_fleet.json" >&2
        exit 1
    fi
    # Live windowed-signal render: boot a tiny engine server, push one
    # request through it, and assert `rbg-tpu top --once` renders the
    # per-role dashboard (attainment + goodput columns) from its slo +
    # metrics ops. Outside the 870 s pytest budget, --lint mode only.
    echo "== rbg-tpu top --once (live windowed-signal render) =="
    if ! env JAX_PLATFORMS=cpu timeout -k 10 300 python - <<'PYEOF'
import os, socket, subprocess, sys, time
from rbg_tpu.engine.protocol import request_once

s = socket.socket(); s.bind(("127.0.0.1", 0))
port = s.getsockname()[1]; s.close()
env = {k: v for k, v in os.environ.items()
       if k not in ("RBG_SERVE_PORT", "RBG_PORT_SERVE")}
env["JAX_PLATFORMS"] = "cpu"
proc = subprocess.Popen(
    [sys.executable, "-m", "rbg_tpu.engine.server", "--model", "tiny",
     "--port", str(port), "--max-batch", "2", "--num-pages", "64",
     "--max-seq-len", "128", "--prefill-chunk", "16",
     "--use-pallas", "never"], env=env)
try:
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline:
        try:
            h, _, _ = request_once(f"127.0.0.1:{port}", {"op": "health"},
                                   timeout=2)
            if h and h.get("ok"):
                break
        except OSError:
            pass
        time.sleep(0.5)
    else:
        raise SystemExit("engine never became ready")
    request_once(f"127.0.0.1:{port}",
                 {"op": "generate", "prompt": [1, 2, 3, 4],
                  "max_new_tokens": 4}, timeout=240)
    out = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "top", "--once",
         "--window", "10", "--engine", f"127.0.0.1:{port}"],
        env=env, capture_output=True, text=True, timeout=60)
    sys.stdout.write(out.stdout)
    assert out.returncode == 0, f"top --once rc={out.returncode}: {out.stderr}"
    assert "GOODPUT" in out.stdout and "TTFT-ATT" in out.stdout, out.stdout
    assert "unified" in out.stdout, out.stdout
finally:
    proc.terminate()
    proc.wait(timeout=10)
PYEOF
    then
        echo "TIER1 TOP SMOKE FAILED — rbg-tpu top --once could not render" \
             "live windowed signals from a running engine" >&2
        exit 1
    fi
fi

LOG=/tmp/_t1.log
rm -f "$LOG"
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    ${EXTRA[@]+"${EXTRA[@]}"} "$@" 2>&1 | tee "$LOG"
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$LOG" | tr -cd . | wc -c)"
if [ "$rc" -eq 124 ] || [ "$rc" -eq 137 ]; then
    echo "TIER1 TIMED OUT at 870s — run 'scripts/tier1.sh --audit' and mark the heaviest drills slow" >&2
fi
exit "$rc"
