"""SLO attainment plane (obs/slo.py + service/router judgment):

* SLOTracker verdicts, windowed attainment, goodput, group_by;
* the service judges every finished request exactly once (blocking path,
  rejected requests excluded);
* router TTFT anchors at the INGRESS arrival stamp — the regression the
  blocking path had: a scripted first-attempt failure must be charged to
  the reported TTFT (unified passthrough AND the PD prefill leg), and PD
  TTFT ends at the prefill hop, not at decode completion;
* per-backend router gauges are removed when the address leaves the
  registry (Registry.remove_series wired into BackendPool.retain).
"""

import json
import socketserver
import threading
import time

import pytest

from rbg_tpu.engine.protocol import recv_msg, request_once, send_msg
from rbg_tpu.engine.router import (Handler, Registry, RouterServer,
                                   RouterState)
from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.obs.slo import (SLOTargets, SLOTracker, reset_trackers,
                             slo_response, trackers)


# ---- tracker units ---------------------------------------------------------


def test_tracker_verdicts_and_attainment():
    t = SLOTracker(SLOTargets(ttft_s=1.0, tpot_s=0.1), component="t",
                   register=False)
    assert t.judge(0.5, 0.05, role="unified") == {
        "ttft_ok": True, "tpot_ok": True, "goodput": True}
    assert t.judge(2.0, 0.05, role="unified")["goodput"] is False
    assert t.judge(0.5, 0.5, role="decode") == {
        "ttft_ok": True, "tpot_ok": False, "goodput": False}
    assert t.judged_total() == 3
    assert t.totals() == {"judged": 3, "ttft_met": 2, "tpot_met": 2,
                          "goodput": 1}
    att = t.attainment(60.0)
    assert att["all"]["judged"] == 3
    assert att["all"]["ttft_attainment"] == pytest.approx(2 / 3, abs=1e-3)
    assert att["all"]["goodput_attainment"] == pytest.approx(1 / 3, abs=1e-3)
    by_role = t.attainment(60.0, group_by=("role",))
    assert by_role["role=unified"]["judged"] == 2
    assert by_role["role=decode"]["tpot_attainment"] == 0.0
    # goodput_rps = met-both / window.
    assert att["all"]["goodput_rps"] == pytest.approx(1 / 60.0, abs=1e-3)


def test_tracker_zero_target_disables_dimension():
    t = SLOTracker(SLOTargets(ttft_s=0.0, tpot_s=0.1), component="t",
                   register=False)
    v = t.judge(99.0, 0.05)
    assert v["ttft_ok"] and v["goodput"]


def test_tracker_window_excludes_old_events(monkeypatch):
    t = SLOTracker(SLOTargets(1.0, 1.0), component="t", register=False)
    t.judge(0.1, 0.0)
    # Judged "now"; a window anchored far in the future sees nothing.
    future = time.monotonic() + 1000.0
    assert t.attainment(60.0, now=future) == {}
    assert t.attainment(2000.0, now=future)["all"]["judged"] == 1


def test_tracker_publishes_registry_series():
    before = REGISTRY.counter(names.SLO_JUDGED_TOTAL, component="unit",
                              role="r")
    t = SLOTracker(SLOTargets(1.0, 1.0), component="unit", register=False)
    t.judge(0.5, 0.1, role="r")
    t.judge(5.0, 0.1, role="r")
    assert REGISTRY.counter(names.SLO_JUDGED_TOTAL, component="unit",
                            role="r") == before + 2
    assert REGISTRY.counter(names.SLO_GOODPUT_TOTAL, component="unit",
                            role="r") >= 1
    # snapshot() publishes the 60 s attainment gauges.
    t.snapshot()
    assert REGISTRY.gauge(names.SLO_TTFT_ATTAINMENT,
                          component="unit") == 0.5


def test_slo_response_clamps_malformed_window():
    reset_trackers()
    t = SLOTracker(SLOTargets(1.0, 1.0), component="resp")
    t.judge(0.1, 0.0, role="x")
    for bad, expect in (("bogus", 60.0), (None, 60.0), (-5, 1.0),
                        (10**9, 3600.0), ("30", 30.0)):
        resp = slo_response(bad)
        assert resp["window_s"] == expect
        assert "signals" in resp and "signals_by_window" in resp
    comps = [tr["component"] for tr in slo_response(None)["trackers"]]
    assert "resp" in comps
    reset_trackers()


def test_tracker_registry_bounded():
    reset_trackers()
    made = [SLOTracker(component=f"c{i}") for i in range(40)]
    live = trackers()
    assert len(live) == 16
    assert live[-1] is made[-1]
    reset_trackers()


# ---- service-side judgment (real tiny engine) ------------------------------


@pytest.fixture(scope="module")
def svc():
    from rbg_tpu.engine.config import EngineConfig
    from rbg_tpu.engine.service import EngineService

    s = EngineService(
        EngineConfig(model="tiny", page_size=8, num_pages=64, max_batch=1,
                     max_seq_len=128, prefill_chunk=16, use_pallas="never",
                     decode_buckets=(1,), slo_ttft_s=30.0, slo_tpot_s=5.0),
        max_queue=4)
    yield s
    s.stop()


def test_service_judges_every_finished_request_once(svc):
    from rbg_tpu.engine.config import SamplingParams
    from rbg_tpu.engine.service import DeadlineExceeded

    svc_label = "engineservice"
    judged0 = svc.slo.judged_total()
    fin0 = REGISTRY.counter(names.SERVING_REQUESTS_FINISHED_TOTAL,
                            service=svc_label)
    tok0 = REGISTRY.counter(names.SERVING_TOKENS_TOTAL, service=svc_label)
    for i in range(3):
        svc.submit_wait([1 + i, 2, 3], SamplingParams(max_new_tokens=4))
    assert svc.slo.judged_total() - judged0 == 3
    assert REGISTRY.counter(names.SERVING_REQUESTS_FINISHED_TOTAL,
                            service=svc_label) - fin0 == 3
    assert REGISTRY.counter(names.SERVING_TOKENS_TOTAL,
                            service=svc_label) - tok0 == 12
    # Generous targets on a tiny CPU engine: everything attains.
    att = svc.slo.attainment(60.0, group_by=("role",))
    assert att["role=unified"]["judged"] >= 3
    assert svc.service_stats()["slo_judged_total"] == svc.slo.judged_total()
    # A request rejected at submission never reaches the judged set.
    with pytest.raises(DeadlineExceeded):
        svc.submit_wait([9, 9, 9], SamplingParams(max_new_tokens=4),
                        deadline=time.monotonic() - 1.0)
    assert svc.slo.judged_total() - judged0 == 3


# ---- router-side judgment (scripted backends) ------------------------------


class _ScriptedBackend(socketserver.ThreadingTCPServer):
    """Engine stand-in with scripted behavior per op:

    * ``die_delay_s``: sleep then cut the socket on the FIRST data op
      (transport failure → router failover), then behave;
    * ``reply_delay_s``: sleep before answering (models compute time);
    * ``reply``: extra fields merged into the generate/decode response;
    * ``prefill=True``: answer op=prefill with a bundle-shaped header +
      empty KV bytes (the router forwards headers verbatim).
    """

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, die_delay_s=None, reply_delay_s=0.0, reply=None,
                 prefill=False, stream_tokens=0):
        backend = self
        self.die_delay_s = die_delay_s
        self.seen = []

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        obj, _, _ = recv_msg(self.request)
                    except (ConnectionError, json.JSONDecodeError):
                        return
                    if obj is None:
                        return
                    if obj.get("op") == "health":
                        send_msg(self.request, {"ok": True})
                        continue
                    backend.seen.append(obj)
                    if backend.die_delay_s is not None:
                        time.sleep(backend.die_delay_s)
                        backend.die_delay_s = None   # die once
                        return
                    if reply_delay_s:
                        time.sleep(reply_delay_s)
                    if prefill:
                        send_msg(self.request,
                                 {"prompt": obj.get("prompt"),
                                  "first_token": 5, "shape": [0],
                                  "dtype": "float32"},
                                 b"", b"")
                        continue
                    if stream_tokens and obj.get("stream"):
                        for t in range(stream_tokens):
                            send_msg(self.request,
                                     {"tokens": [t], "done": False})
                            time.sleep(0.01)
                        send_msg(self.request, {"tokens": [], "done": True})
                        continue
                    resp = {"tokens": [5, 6, 7]}
                    resp.update(reply or {})
                    send_msg(self.request, resp)

        super().__init__(("127.0.0.1", 0), H)
        self.addr = f"127.0.0.1:{self.server_address[1]}"
        threading.Thread(target=self.serve_forever, daemon=True).start()

    def stop(self):
        self.shutdown()
        self.server_close()


def _router(static, **kw):
    server = RouterServer(("127.0.0.1", 0), Handler)
    server.state = RouterState(Registry(None), None, static, **kw)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, f"127.0.0.1:{server.server_address[1]}"


def test_unified_blocking_ttft_charges_failed_attempt():
    """Regression (satellite 1): the backend-reported ttft_s restarted
    the clock on failover — a first attempt that burned 0.4 s before
    dying must appear in the client-visible TTFT."""
    flaky = _ScriptedBackend(die_delay_s=0.4)
    steady = _ScriptedBackend(reply={"ttft_s": 0.01})
    server, addr = _router({"worker": [flaky.addr, steady.addr]},
                           slo_targets=SLOTargets(10.0, 1.0))
    try:
        # Load the steady sibling so the flaky one is tried first.
        server.state.pool.acquire(steady.addr)
        resp, _, _ = request_once(addr, {"op": "generate",
                                         "prompt": [1, 2, 3],
                                         "timeout_s": 20}, timeout=20)
        assert resp and "error" not in resp, resp
        assert "_router_t_dispatch" not in resp
        # Old behavior: 0.01 passthrough. New: arrival-anchored.
        assert resp["ttft_s"] >= 0.35, resp
        assert server.state.metrics["failovers"] == 1
        assert server.state.slo.judged_total() == 1
        att = server.state.slo.attainment(60.0, group_by=("backend",))
        assert f"backend={steady.addr}" in att
    finally:
        server.shutdown()
        flaky.stop()
        steady.stop()


def test_pd_blocking_ttft_ends_at_prefill_not_decode():
    """PD TTFT = ingress → prefill hop return (the first token exists
    then). A scripted 0.3 s first-attempt prefill failure is charged; the
    0.8 s decode leg is NOT."""
    pf_flaky = _ScriptedBackend(die_delay_s=0.3, prefill=True)
    pf_ok = _ScriptedBackend(prefill=True)
    dec = _ScriptedBackend(reply_delay_s=0.8)
    server, addr = _router(
        {"prefill": [pf_flaky.addr, pf_ok.addr], "decode": [dec.addr]},
        slo_targets=SLOTargets(10.0, 1.0))
    try:
        server.state.pool.acquire(pf_ok.addr)   # flaky prefill goes first
        t0 = time.monotonic()
        resp, _, _ = request_once(addr, {"op": "generate",
                                         "prompt": [1, 2, 3],
                                         "timeout_s": 30}, timeout=30)
        e2e = time.monotonic() - t0
        assert resp and "error" not in resp, resp
        assert e2e >= 1.0                        # decode leg really ran
        assert 0.25 <= resp["ttft_s"] <= 0.7, resp   # charged, no decode
        att = server.state.slo.attainment(60.0, group_by=("role",))
        assert att["role=decode"]["judged"] == 1
    finally:
        server.shutdown()
        for b in (pf_flaky, pf_ok, dec):
            b.stop()


def test_streaming_judged_and_health_carries_slo():
    be = _ScriptedBackend(stream_tokens=5)
    server, addr = _router({"worker": [be.addr]},
                           slo_targets=SLOTargets(10.0, 1.0))
    try:
        import socket as _socket
        host, port = addr.rsplit(":", 1)
        got = []
        with _socket.create_connection((host, int(port)), timeout=10) as s:
            send_msg(s, {"op": "generate", "stream": True,
                         "prompt": [1, 2], "timeout_s": 20})
            while True:
                frame, _, _ = recv_msg(s)
                assert frame is not None and "error" not in frame, frame
                got.extend(frame.get("tokens") or [])
                if frame.get("done"):
                    break
        assert got == list(range(5))
        deadline = time.monotonic() + 5.0
        while (time.monotonic() < deadline
               and server.state.slo.judged_total() < 1):
            time.sleep(0.01)
        assert server.state.slo.judged_total() == 1
        health, _, _ = request_once(addr, {"op": "health"}, timeout=10)
        slo = health.get("slo")
        assert slo and slo["judged_total"] == 1
        assert "role=worker" in slo["per_role"]
        assert f"backend={be.addr}" in slo["per_backend"]
        assert slo["per_role"]["role=worker"]["goodput_attainment"] == 1.0
    finally:
        server.shutdown()
        be.stop()


def test_top_once_renders_engine_and_router(capsys):
    """`rbg-tpu top --once` renders a per-role dashboard frame from live
    slo/metrics ops and exits 0; an unreachable target exits 1."""
    from rbg_tpu.cli.top import run as top_run

    class _OpsBackend(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def __init__(self):
            tr = SLOTracker(SLOTargets(1.0, 0.5), component="engineservice",
                            register=False)
            tr.judge(0.1, 0.01, role="unified")

            class H(socketserver.BaseRequestHandler):
                def handle(self):
                    while True:
                        try:
                            obj, _, _ = recv_msg(self.request)
                        except (ConnectionError, json.JSONDecodeError):
                            return
                        if obj is None:
                            return
                        op = obj.get("op")
                        if op == "metrics":
                            send_msg(self.request, {
                                "mode": "unified",
                                "metrics": {"queue_depth": 2, "running": 1,
                                            "waiting": 0, "draining": False,
                                            "slo_judged_total": 1}})
                        elif op == "slo":
                            send_msg(self.request, {
                                "window_s": 60.0,
                                "sampler": {"samples": 5},
                                "signals": {"requests_per_s": 1.5,
                                            "tokens_per_s": 48.0,
                                            "shed_per_s": 0.0,
                                            "occupancy_mean": 0.5},
                                "trackers": [tr.snapshot(
                                    group_by=("role",))]})
                        else:
                            send_msg(self.request, {"ok": True})

            super().__init__(("127.0.0.1", 0), H)
            self.addr = f"127.0.0.1:{self.server_address[1]}"
            threading.Thread(target=self.serve_forever, daemon=True).start()

    ops = _OpsBackend()
    be = _ScriptedBackend(reply={"ttft_s": 0.01})
    rsrv, raddr = _router({"worker": [be.addr]},
                          slo_targets=SLOTargets(10.0, 1.0))
    try:
        request_once(raddr, {"op": "generate", "prompt": [1, 2],
                             "timeout_s": 10}, timeout=10)
        rc = top_run(["--once", "--engine", ops.addr, "--router", raddr])
        out = capsys.readouterr().out
        assert rc == 0
        assert "GOODPUT" in out and "TTFT-ATT" in out
        assert "unified" in out and "worker" in out
        assert f"router {raddr}" in out
        # JSON mode emits the raw payloads.
        rc = top_run(["--json", "--engine", ops.addr])
        raw = json.loads(capsys.readouterr().out)
        assert rc == 0 and raw[0]["kind"] == "engine"
        # Unreachable target: rendered as an error row, exit 1.
        rc = top_run(["--once", "--engine", "127.0.0.1:1"])
        assert rc == 1
        assert "unreachable" in capsys.readouterr().out
    finally:
        rsrv.shutdown()
        be.stop()
        ops.shutdown()


def test_backend_gauges_published_and_pruned():
    """Satellite 2: per-backend gauges follow the pool, and pruning an
    address out of the registry removes its series from the exposition."""
    from rbg_tpu.engine.router import BackendPool

    pool = BackendPool()
    a = "10.9.9.9:1234"
    pool.acquire(a)
    assert REGISTRY.gauge(names.ROUTER_BACKEND_OUTSTANDING, backend=a) == 1.0
    pool.set_draining(a, True)
    assert REGISTRY.gauge(names.ROUTER_BACKEND_DRAINING, backend=a) == 1.0
    # Router-minted per-backend SLO verdicts must be pruned with the
    # address too — pod churn otherwise grows slo series forever.
    tr = SLOTracker(SLOTargets(1.0, 1.0), component="router",
                    register=False)
    tr.judge(0.1, 0.0, role="worker", backend=a)
    assert REGISTRY.counter(names.SLO_JUDGED_TOTAL, component="router",
                            role="worker", backend=a) == 1
    assert a in REGISTRY.render()
    pool.release(a)
    pool.retain(live=set())        # address left the registry
    assert REGISTRY.gauge(names.ROUTER_BACKEND_OUTSTANDING,
                          backend=a) is None
    assert REGISTRY.gauge(names.ROUTER_BACKEND_DRAINING, backend=a) is None
    assert REGISTRY.counter(names.SLO_JUDGED_TOTAL, component="router",
                            role="worker", backend=a) == 0.0
    assert a not in REGISTRY.render()
    # Outstanding traffic pins the state (and its gauges) until drained.
    b = "10.9.9.9:4321"
    pool.acquire(b)
    pool.retain(live=set())
    assert REGISTRY.gauge(names.ROUTER_BACKEND_OUTSTANDING,
                          backend=b) == 1.0
