"""Store.list_for — the indexed per-reconcile child listing. The
contract under test is EQUIVALENCE: for every (kind, parent) pair the
controllers use, ``list_for`` must return exactly what the old full
listing + group filter returned, through creates, label/spec updates,
and deletes.
"""

from __future__ import annotations

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RoleBasedGroup
from rbg_tpu.api.instance import RoleInstance
from rbg_tpu.api.meta import owner_ref
from rbg_tpu.api.policy import (
    CoordinatedPolicy, CoordinatedPolicySpec, CoordinatedScaling,
    ScalingAdapter, ScalingAdapterSpec,
)
from rbg_tpu.runtime.store import Store
from rbg_tpu.testutil import make_group, simple_role


def _full_listing(store, kind, parent):
    """The pre-index semantics: scan the whole kind, keep objects in the
    parent's namespace that are owned by it, labeled for it, or
    back-reference it via spec.group_name."""
    m = parent.metadata
    out = []
    for o in store.list(kind, namespace=m.namespace):
        owned = any(r.uid == m.uid for r in o.metadata.owner_references)
        labeled = (parent.kind == "RoleBasedGroup"
                   and o.metadata.labels.get(C.LABEL_GROUP_NAME) == m.name)
        backref = (parent.kind == "RoleBasedGroup"
                   and getattr(getattr(o, "spec", None), "group_name",
                               None) == m.name)
        if owned or labeled or backref:
            out.append(o)
    return out


def _names(objs):
    return [o.metadata.name for o in objs]


def _adapter(name, group, role, ns="default", owner=None):
    sa = ScalingAdapter()
    sa.metadata.name = name
    sa.metadata.namespace = ns
    sa.spec = ScalingAdapterSpec(group_name=group, role_name=role)
    if owner is not None:
        sa.metadata.owner_references = [owner_ref(owner)]
    return sa


def _policy(name, group, ns="default"):
    p = CoordinatedPolicy()
    p.metadata.name = name
    p.metadata.namespace = ns
    p.spec = CoordinatedPolicySpec(
        group_name=group,
        scaling=CoordinatedScaling(roles=["a", "b"], max_skew_percent=10))
    return p


def _instance(name, group, role, ns="default", owner=None):
    inst = RoleInstance()
    inst.metadata.name = name
    inst.metadata.namespace = ns
    inst.metadata.labels = {C.LABEL_GROUP_NAME: group,
                            C.LABEL_ROLE_NAME: role}
    if owner is not None:
        inst.metadata.owner_references = [owner_ref(owner)]
    return inst


def _assert_equivalent(store, parents, kinds):
    for parent in parents:
        for kind in kinds:
            assert _names(store.list_for(kind, parent)) == \
                _names(_full_listing(store, kind, parent)), \
                f"{kind} for {parent.metadata.namespace}/" \
                f"{parent.metadata.name}"


def test_list_for_matches_full_listing_through_churn():
    store = Store()
    g1 = store.create(make_group("g", simple_role("serve")))
    # Same NAME in another namespace: the sharpest aliasing case the
    # label bucket (not namespace-scoped) must not leak across.
    g_other = store.create(make_group("g", simple_role("serve"),
                                      namespace="other"))
    g2 = store.create(make_group("g2", simple_role("serve")))

    # Children across all three attachment mechanisms:
    store.create(_adapter("sa-owned", "g", "serve", owner=g1))  # owner+spec
    store.create(_adapter("sa-spec-only", "g", "serve"))        # spec only
    store.create(_adapter("sa-other-ns", "g", "serve", ns="other",
                          owner=g_other))
    store.create(_adapter("sa-g2", "g2", "serve", owner=g2))
    store.create(_policy("cp-g", "g"))                          # spec only
    store.create(_policy("cp-other", "g", ns="other"))
    store.create(_policy("cp-g2", "g2"))
    store.create(_instance("g-serve-a", "g", "serve", owner=g1))  # label
    store.create(_instance("g2-serve-a", "g2", "serve", owner=g2))

    parents = [store.get("RoleBasedGroup", "default", "g"),
               store.get("RoleBasedGroup", "other", "g"),
               store.get("RoleBasedGroup", "default", "g2")]
    kinds = ("ScalingAdapter", "CoordinatedPolicy", "RoleInstance")
    _assert_equivalent(store, parents, kinds)

    # Spot-check the interesting rows landed where expected.
    assert _names(store.list_for("ScalingAdapter", parents[0])) == \
        ["sa-owned", "sa-spec-only"]
    assert _names(store.list_for("CoordinatedPolicy", parents[0])) == \
        ["cp-g"]
    assert _names(store.list_for("ScalingAdapter", parents[1])) == \
        ["sa-other-ns"]

    # Back-reference UPDATE moves the child between parents' views.
    def move(a):
        a.spec.group_name = "g2"
        return True
    store.mutate("ScalingAdapter", "default", "sa-spec-only", move)
    _assert_equivalent(store, parents, kinds)
    assert "sa-spec-only" in _names(
        store.list_for("ScalingAdapter", parents[2]))

    # Label UPDATE re-indexes.
    def relabel(i):
        i.metadata.labels[C.LABEL_GROUP_NAME] = "g2"
        return True
    store.mutate("RoleInstance", "default", "g-serve-a", relabel)
    _assert_equivalent(store, parents, kinds)

    # Deletes drop out of every view (owner cascade included).
    store.delete("ScalingAdapter", "default", "sa-owned")
    store.delete("RoleBasedGroup", "default", "g2")
    parents = [p for p in parents if store.get(
        p.kind, p.metadata.namespace, p.metadata.name)]
    _assert_equivalent(store, parents, kinds)
    # g2's cascade took its owned adapter; the moved spec-only adapter
    # now references a dead group name — and therefore appears for no
    # surviving parent.
    for p in parents:
        assert "sa-g2" not in _names(store.list_for("ScalingAdapter", p))


def test_list_for_owner_parent_instances():
    """RoleInstanceSet → RoleInstance: pure owner-index parentage (the
    instanceset controller's per-reconcile listing)."""
    from rbg_tpu.api.instance import RoleInstanceSet

    store = Store()
    ris = RoleInstanceSet()
    ris.metadata.name = "ris-a"
    ris.metadata.namespace = "default"
    ris = store.create(ris)
    ris2 = RoleInstanceSet()
    ris2.metadata.name = "ris-b"
    ris2.metadata.namespace = "default"
    ris2 = store.create(ris2)
    for i in range(3):
        store.create(_instance(f"ris-a-{i}", "g", "serve", owner=ris))
    store.create(_instance("ris-b-0", "g", "serve", owner=ris2))

    assert _names(store.list_for("RoleInstance", ris)) == \
        ["ris-a-0", "ris-a-1", "ris-a-2"]
    assert _names(store.list_for("RoleInstance", ris2)) == ["ris-b-0"]
    # copy_=False returns the live objects (read-only hot path).
    live = store.list_for("RoleInstance", ris, copy_=False)
    assert live[0] is store.get("RoleInstance", "default", "ris-a-0",
                                copy_=False)
