"""Multi-head latent attention (DeepSeek-V2/V3 family).

Reference context: the reference's flagship ecosystem deployments serve
DeepSeek via SGLang (``examples/inference/ecosystem/mooncake/*``,
BASELINE.md config 5); MLA's compressed latent cache is what makes their
KV transfer economical. Implemented in the absorbed inference form
(ops/mla_attention.py) — per-head K/V never materializes.

Load-bearing invariants mirrored from the GQA tests: full-context forward
== incremental decode, paged engine == contiguous greedy, and the
absorbed form == the naive materialized-K/V form."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.kvcache import PagedKVCache
from rbg_tpu.models import get_config, init_params
from rbg_tpu.models.llama import (KVCache, forward, forward_train,
                                  prefill_and_decode_greedy)

CFG = get_config("tiny-mla")
PARAMS = init_params(CFG, jax.random.key(0))


@pytest.mark.slow
def test_prefill_decode_equivalence():
    B, T = 2, 12
    toks = jax.random.randint(jax.random.key(1), (B, T), 0, CFG.vocab_size)
    full, _ = forward(PARAMS, CFG, toks, KVCache.create(CFG, B, 32))
    cache = KVCache.create(CFG, B, 32)
    outs = []
    for t in range(T):
        lg, cache = forward(PARAMS, CFG, toks[:, t:t + 1], cache)
        outs.append(lg[:, 0])
    inc = jnp.stack(outs, axis=1)
    assert float(jnp.max(jnp.abs(full - inc))) < 2e-4


def test_absorbed_equals_naive_attention():
    """score = q_nope·(c@W_uk) + q_pe·k_pe must equal the absorbed
    q_lat·c + q_pe·k_pe — checked by materializing per-head K/V."""
    from rbg_tpu.models.llama import _mla_qkv, _mla_scale
    B, T = 1, 6
    x = jax.random.normal(jax.random.key(2), (B, T, CFG.hidden_size),
                          jnp.float32)
    pos = jnp.arange(T, dtype=jnp.int32)[None, :]
    blk = jax.tree_util.tree_map(lambda a: a[0], PARAMS["blocks"])
    q_lat, q_pe, c, k_pe = _mla_qkv(CFG, blk, x, pos)
    h, dn = CFG.num_heads, CFG.qk_nope_head_dim
    dc, dr = CFG.kv_lora_rank, CFG.qk_rope_head_dim
    # naive: materialize k_nope per head and recompute q_nope
    from rbg_tpu.ops.norms import rms_norm
    from rbg_tpu.ops.rope import apply_rope
    xa = rms_norm(x, blk["attn_norm"], CFG.rms_norm_eps)
    q = (xa @ blk["wq"]).reshape(B, T, h, dn + dr)
    q_nope = q[..., :dn]
    k_nope = jnp.einsum("btc,chn->bthn", c,
                        blk["w_uk"].reshape(dc, h, dn))
    naive = jnp.einsum("bthn,bshn->bhts", q_nope, k_nope)
    absorbed = jnp.einsum("bthc,bsc->bhts", q_lat, c)
    assert float(jnp.max(jnp.abs(naive - absorbed))) < 1e-4


@pytest.mark.slow
def test_paged_engine_matches_contiguous_greedy():
    ref = prefill_and_decode_greedy(PARAMS, CFG, jnp.asarray([[1, 2, 3, 4]]),
                                    steps=8)
    eng = Engine(EngineConfig(model="tiny-mla", page_size=8, num_pages=96,
                              max_seq_len=128, use_pallas="never",
                              enable_radix_cache=False), params=PARAMS)
    got = eng.generate([[1, 2, 3, 4]], SamplingParams(max_new_tokens=8))[0]
    assert np.asarray(ref).reshape(-1).tolist() == got


@pytest.mark.slow
def test_engine_features_compose_with_mla():
    def mk(**kw):
        return Engine(EngineConfig(model="tiny-mla", page_size=8,
                                   num_pages=96, max_seq_len=128,
                                   use_pallas="never",
                                   enable_radix_cache=False, **kw),
                      params=PARAMS)
    prompt = [1, 2, 3, 4] * 4
    sp = SamplingParams(max_new_tokens=10)
    base = mk().generate([prompt], sp)[0]
    assert mk(multi_step=4).generate([prompt], sp)[0] == base
    assert mk(speculative="ngram").generate([prompt], sp)[0] == base


def test_mla_kv_pool_is_smaller():
    mla_big = get_config("deepseek-v2-lite")
    gqa_same = get_config("llama3-8b")
    mla_per_tok = (PagedKVCache.hbm_bytes(mla_big, 100)
                   / (100 * 16 * mla_big.num_layers))
    gqa_per_tok = (PagedKVCache.hbm_bytes(gqa_same, 100)
                   / (100 * 16 * gqa_same.num_layers))
    # 576 * 2 bytes vs 2*8*128*2 bytes per token-layer → ~3.6x smaller
    assert mla_per_tok * 3 < gqa_per_tok


def test_num_params_matches_init():
    real = sum(int(np.prod(v.shape))
               for v in jax.tree_util.tree_leaves(PARAMS))
    assert CFG.num_params == real


def test_deepseek_v2_lite_param_count():
    # Real model: ~15.7B (the ~3% overcount is the dense first layer the
    # homogeneous-scan architecture does not special-case).
    n = get_config("deepseek-v2-lite").num_params
    assert 15e9 < n < 16.6e9, n
    n3 = get_config("deepseek-v3").num_params
    assert 650e9 < n3 < 740e9, n3   # real: 671B (no q-LoRA modeled)


def test_training_forward_runs_with_mla():
    B, T = 2, 8
    toks = jax.random.randint(jax.random.key(3), (B, T), 0, CFG.vocab_size)
    logits = forward_train(PARAMS, CFG, toks)
    assert logits.shape == (B, T, CFG.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_mla_moe_combined_forward():
    cfg = get_config("tiny-moe", mla=True, kv_lora_rank=64,
                     qk_nope_head_dim=32, qk_rope_head_dim=16, v_head_dim=32)
    params = init_params(cfg, jax.random.key(4))
    toks = jnp.asarray([[1, 2, 3, 4, 5]])
    logits, _ = forward(params, cfg, toks, KVCache.create(cfg, 1, 16))
    assert logits.shape == (1, 5, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


@pytest.mark.slow
def test_mla_sharded_engine_tp2():
    from jax.sharding import Mesh
    devs = np.array(jax.devices()[:2]).reshape(1, 2)
    mesh = Mesh(devs, ("dp", "tp"))
    eng = Engine(EngineConfig(model="tiny-mla", page_size=8, num_pages=96,
                              max_seq_len=128, use_pallas="never",
                              enable_radix_cache=False),
                 params=PARAMS, mesh=mesh)
    got = eng.generate([[1, 2, 3, 4]], SamplingParams(max_new_tokens=8))[0]
    single = Engine(EngineConfig(model="tiny-mla", page_size=8, num_pages=96,
                                 max_seq_len=128, use_pallas="never",
                                 enable_radix_cache=False), params=PARAMS)
    assert got == single.generate([[1, 2, 3, 4]],
                                  SamplingParams(max_new_tokens=8))[0]


def test_mla_config_guards():
    # Both round-4 MLA guards fell in round 5 (latent decode kernel,
    # quantized latent pool) and the last one fell in round 16: the
    # latent kernel grew a dequantizing _q variant, so int8 + 'always'
    # is a working combination — no MLA-specific config guard remains.
    EngineConfig(model="tiny-mla", kv_dtype="int8").validate()
    EngineConfig(model="tiny-mla", use_pallas="always").validate()
    EngineConfig(model="tiny-mla", kv_dtype="int8",
                 use_pallas="always").validate()
    with pytest.raises(ValueError, match="unified"):
        EngineConfig(model="tiny-mla", kv_dtype="int8",
                     mode="prefill").validate()


@pytest.mark.slow
def test_pd_disagg_ships_latent_bundles():
    """PD-disagg with MLA: the KV bundle carries the compressed latent
    pages (the Mooncake-economics point of MLA) and decodes identically."""
    from rbg_tpu.engine.pd import PDPair
    base = dict(model="tiny-mla", page_size=8, num_pages=96, max_seq_len=128,
                use_pallas="never", enable_radix_cache=False)
    uni = Engine(EngineConfig(**base), params=PARAMS)
    expect = uni.generate([[1, 2, 3, 4, 5]],
                          SamplingParams(max_new_tokens=8))[0]
    pair = PDPair(EngineConfig(**base), params=PARAMS)
    got = pair.generate([[1, 2, 3, 4, 5]], SamplingParams(max_new_tokens=8))
    assert got[0] == expect


def test_mla_decode_service_warm_bundle_shapes():
    """DecodeService._warm_item must derive each bundle half from its OWN
    pool: under MLA the v pool (shared RoPE key) has a different channel
    dim than the k pool (latent) — deriving both from k_pages failed
    every MLA decode replica's {"op": "warmup"} at the inject scatter."""
    from rbg_tpu.engine.service import DecodeService
    svc = DecodeService(EngineConfig(
        model="tiny-mla", page_size=8, num_pages=64, max_batch=2,
        max_seq_len=128, prefill_chunk=16, use_pallas="never",
        decode_buckets=(1, 2)), params=PARAMS)
    try:
        b = svc._warm_item(16, 0, 0)
        assert b.k_data.shape[4] == CFG.kv_lora_rank
        assert b.v_data.shape[4] == CFG.qk_rope_head_dim
        # And the bundle actually injects + decodes (the crash site).
        toks = svc.submit_bundle(b, SamplingParams(max_new_tokens=2),
                                 timeout=240)
        assert len(toks) == 2
    finally:
        svc.stop()


@pytest.mark.slow
def test_mla_int8_latent_pool_numerics():
    """int8-quantized latent pool (round 5): half the already-compressed
    latent HBM; bounded deviation vs the fp32 pool and greedy agreement
    (the GQA int8 invariants, on the latent shape)."""
    mk = lambda dtype: Engine(
        EngineConfig(model="tiny-mla", page_size=8, num_pages=96,
                     max_seq_len=128, use_pallas="never",
                     enable_radix_cache=False, kv_dtype=dtype),
        params=PARAMS)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]

    ref = mk("model")
    q = mk("int8")
    assert q.cache.quantized and q.cache.k_pages.dtype == jnp.int8
    assert q.cache.k_pages.shape[-1] == CFG.kv_lora_rank
    assert q.cache.k_scales.shape[-1] == 1

    sp = SamplingParams(max_new_tokens=12)
    ref_out = ref.generate([prompt], sp)[0]
    q_out = q.generate([prompt], sp)[0]
    agree = sum(a == b for a, b in zip(ref_out, q_out)) / len(ref_out)
    assert agree >= 0.75, (ref_out, q_out)

    # Pages balance after generation (quantized pool accounting intact).
    assert not q.running and not q.waiting
    assert q.allocator.free_pages == q.cfg.num_pages - 1  # null page
