"""Control-plane restart safety: state is fully re-derivable from the store
(SURVEY.md §5 checkpoint/resume — level-triggered reconcile), and the
node-binding store reseeds from live pods."""

from rbg_tpu.api import constants as C
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import (
    make_group, make_tpu_nodes, simple_role, tpu_leaderworker_role,
)


def test_new_plane_resumes_from_existing_store():
    plane_a = ControlPlane(backend="fake")
    make_tpu_nodes(plane_a.store, slices=2, hosts_per_slice=2)
    with plane_a:
        plane_a.apply(make_group(
            "svc", simple_role("web", replicas=2),
            tpu_leaderworker_role("serve", replicas=1, topology="2x4")))
        plane_a.wait_group_ready("svc", timeout=30)
        nodes = {n.metadata.name: n for n in plane_a.store.list("Node")}
        slice0 = {nodes[p.node_name].tpu.slice_id
                  for p in plane_a.store.list("Pod", namespace="default")
                  if p.metadata.labels[C.LABEL_ROLE_NAME] == "serve"}.pop()
    # plane A is gone (controller crash / upgrade). Mutate spec while NO
    # controllers are running — the new plane must pick it up cold.
    store = plane_a.store
    g = store.get("RoleBasedGroup", "default", "svc")
    g.spec.roles[0].replicas = 3
    store.update(g)

    plane_b = ControlPlane(store=store, backend="fake")
    with plane_b:
        plane_b.wait_for(
            lambda: len([p for p in store.list("Pod", namespace="default")
                         if p.active
                         and p.metadata.labels[C.LABEL_ROLE_NAME] == "web"]) == 3,
            timeout=30, desc="offline scale-up applied by the new plane",
        )
        plane_b.wait_group_ready("svc", timeout=30)

        # Warm-placement memory reseeded from live pods (reference:
        # node_binding.go:200-204): the slice instance's binding survives.
        serve_pods = [p for p in store.list("Pod", namespace="default")
                      if p.metadata.labels[C.LABEL_ROLE_NAME] == "serve"]
        assert plane_b.node_binding.preferred_slice(serve_pods[0]) == slice0

        # Restart recovery still lands on the SAME slice after the restart.
        uid0 = {p.metadata.uid for p in serve_pods}
        plane_b.kubelet.fail_pod("default", serve_pods[0].metadata.name)

        def recreated():
            ps = [p for p in store.list("Pod", namespace="default")
                  if p.active and p.metadata.labels[C.LABEL_ROLE_NAME] == "serve"]
            return (len(ps) == 2 and uid0.isdisjoint({p.metadata.uid for p in ps})
                    and all(p.running_ready for p in ps))

        plane_b.wait_for(recreated, timeout=30, desc="gang recreated post-restart")
        nodes = {n.metadata.name: n for n in store.list("Node")}
        slice1 = {nodes[p.node_name].tpu.slice_id
                  for p in store.list("Pod", namespace="default")
                  if p.active and p.metadata.labels[C.LABEL_ROLE_NAME] == "serve"}.pop()
        assert slice1 == slice0


def test_resume_seeds_crashloop_backoff():
    """A plane resuming over an existing store must NOT reset crash-loop
    damping to zero: observed pod restart counts pre-charge the instance
    controller's per-key workqueue backoff (in-place-update restarts are
    legitimate and excluded)."""
    import json

    from rbg_tpu.runtime.controllers.instance import RoleInstanceController

    plane_a = ControlPlane(backend="fake")
    make_tpu_nodes(plane_a.store, slices=2, hosts_per_slice=2)
    with plane_a:
        plane_a.apply(make_group("svc", simple_role("web", replicas=1)))
        plane_a.wait_group_ready("svc", timeout=30)
    store = plane_a.store
    pods = [p for p in store.list("Pod", namespace="default")
            if p.metadata.labels[C.LABEL_ROLE_NAME] == "web"]
    crashing = pods[0]

    # Offline (no controllers running): the pod crashed its way to a high
    # restart count while the old plane was down.
    def bump(p):
        p.status.container_restarts = {"engine": 6}
        p.status.restart_count = 6
        return True

    store.mutate("Pod", "default", crashing.metadata.name, bump, status=True)

    ctrl = RoleInstanceController(store)
    ctrl.seed_backoff(store)
    ref = crashing.metadata.controller_owner()
    key = ("default", ref.name)
    assert ctrl.backoff.retries(key) == 6
    # The next failure continues the damped schedule instead of restarting
    # from the base delay.
    assert ctrl.backoff.next_delay(key) > ctrl.backoff.base

    # In-place-update restarts are expected, not crash-loops: a pod whose
    # counts match its recorded update baseline seeds nothing.
    from rbg_tpu.api import constants as CC
    def with_state(p):
        p.status.container_restarts = {"engine": 1}
        p.status.restart_count = 1
        return True
    store.mutate("Pod", "default", crashing.metadata.name, with_state,
                 status=True)

    def ann(p):
        p.metadata.annotations[CC.ANN_INPLACE_UPDATE_STATE] = json.dumps(
            {"revision": "r2", "images": {}, "restarted": ["engine"],
             "baselines": {"engine": 0}})
        return True
    store.mutate("Pod", "default", crashing.metadata.name, ann)
    ctrl2 = RoleInstanceController(store)
    ctrl2.seed_backoff(store)
    assert ctrl2.backoff.retries(key) == 0


def test_snapshot_lenient_load_and_schema(tmp_path):
    """Schema evolution (docs/architecture.md §5): a snapshot written by a
    NEWER release (extra unknown fields, same schema int) loads leniently;
    admission stays strict; an unmigratable schema int is a hard error."""
    import pytest

    from rbg_tpu.api import parse_manifest
    from rbg_tpu.runtime.store import Store
    from rbg_tpu.testutil import make_group, simple_role

    src = Store()
    src.create(make_group("g", simple_role("server", replicas=2)))
    snap = src.snapshot()
    assert snap["schema"] == Store.SNAPSHOT_SCHEMA

    # Simulate a newer release's extra fields at several depths.
    snap["objects"][0]["futureTopLevel"] = {"x": 1}
    snap["objects"][0]["spec"]["roles"][0]["futureKnob"] = 7

    dst = Store()
    assert dst.load_snapshot(snap) == 1
    g = dst.get("RoleBasedGroup", "default", "g")
    assert g.spec.roles[0].replicas == 2

    # Admission-path parsing of the same doc stays strict.
    with pytest.raises(KeyError):
        parse_manifest(snap["objects"][0])

    # Old schema with no migration chain → explicit error, not silent
    # misparse; same for a FUTURE schema (structural change by definition).
    snap2 = src.snapshot()
    snap2["schema"] = 0
    with pytest.raises(ValueError):
        Store().load_snapshot(snap2)
    snap3 = src.snapshot()
    snap3["schema"] = Store.SNAPSHOT_SCHEMA + 1
    with pytest.raises(ValueError):
        Store().load_snapshot(snap3)

    # Derived status must not leak into the wire format: a Ready group's
    # snapshot still loads on the previous strict-parsing release.
    from rbg_tpu.api import serde
    from rbg_tpu.api.group import RoleStatus
    assert "ready" not in serde.to_dict(
        RoleStatus(name="a", replicas=1, ready_replicas=1, ready=True))
