"""Device-resident grammar decode: finite-state grammars compile to dense
token-level transition tables (next_state[S, V] / legal[S, V]) and
constrained rows run INSIDE the fused multi-step scan — zero per-token
host syncs. The acceptance bar is exactness: the table path must emit
BIT-IDENTICAL tokens to the host-synced mask path (the engine's
position-keyed sampling makes that checkable), across greedy and
temperature sampling, mixed batches, preemption, and the state-budget
fallback."""

import json
import re

import jax
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.grammar import (JsonGrammar, JsonSchemaGrammar,
                                    RegexGrammar, TokenGrammar,
                                    compile_token_table, token_bytes_for)
from rbg_tpu.engine.tokenizer import ByteTokenizer
from rbg_tpu.models import get_config, init_params

_TOK = ByteTokenizer()

SCHEMA = {"type": "object", "properties": {
    "id": {"type": "integer"},
    "state": {"enum": ["on", "off"]},
}}


# ---- table compiler ----


def _tg(grammar):
    return TokenGrammar(grammar, token_bytes_for(_TOK), _TOK.eos_id)


def test_table_legality_matches_mask_on_every_state():
    """legal[s] must equal the host path's mask(state) for every table
    state — that equality is what makes fused decode provably exact."""
    tg = _tg(RegexGrammar(r"(GET|POST) /[a-z/]{0,6} HTTP"))
    t = compile_token_table(tg, state_budget=256)
    assert t is not None
    assert len(t.state_ids) == t.num_states
    for state, sid in t.state_ids.items():
        np.testing.assert_array_equal(t.legal[sid, :tg.V], tg.mask(state))
        assert not t.legal[sid, tg.V:].any()


def test_table_transitions_match_advance_token():
    tg = _tg(RegexGrammar(r"[ab]{1,4}c"))
    t = compile_token_table(tg, state_budget=64)
    for state, sid in t.state_ids.items():
        for v in np.nonzero(t.legal[sid])[0]:
            ns = tg.advance_token(state, int(v))
            assert ns is not None
            assert t.next_state[sid, v] == t.state_ids[ns]
        # Illegal tokens are -1 across the whole row.
        assert (t.next_state[sid][~t.legal[sid]] == -1).all()


def test_table_eos_is_identity_at_accepting_states():
    tg = _tg(RegexGrammar(r"ab?"))
    t = compile_token_table(tg, state_budget=64)
    for state, sid in t.state_ids.items():
        if tg.grammar.is_complete(state):
            assert t.legal[sid, _TOK.eos_id]
            assert t.next_state[sid, _TOK.eos_id] == sid
        else:
            assert not t.legal[sid, _TOK.eos_id]


def test_table_vocab_padding():
    tg = _tg(RegexGrammar(r"x+"))
    t = compile_token_table(tg, state_budget=16, vocab_size=512)
    assert t.next_state.shape == (t.num_states, 512)
    assert not t.legal[:, tg.V:].any()          # beyond tokenizer: illegal


def test_table_budget_exceeded_returns_none():
    tg = _tg(RegexGrammar(r"[ab]{1,40}c"))
    assert compile_token_table(tg, state_budget=3) is None
    assert compile_token_table(tg, state_budget=256) is not None


def test_schema_grammar_is_tableable():
    tg = _tg(JsonSchemaGrammar(SCHEMA))
    t = compile_token_table(tg, state_budget=512)
    assert t is not None and t.num_states > 2


# ---- engine integration ----


@pytest.fixture(scope="module")
def eng_factory():
    cfg = get_config("tiny", vocab_size=512)
    params = init_params(cfg, jax.random.key(0))

    def make(**kw):
        base = dict(model="tiny", vocab_size=512, page_size=8,
                    num_pages=128, max_seq_len=256, use_pallas="never",
                    multi_step=4)
        base.update(kw)
        e = Engine(EngineConfig(**base), params=params)
        e.enable_json_grammar(_TOK)
        return e

    return make


def _run(eng, reqs):
    ids = [eng.add_request(p, sp) for p, sp in reqs]
    outs = {r: [] for r in ids}
    while eng.has_work():
        for ev in eng.step():
            outs[ev.request_id].append(ev.token)
    return [outs[r] for r in ids]


def _constrained_reqs(temperature):
    return [
        (_TOK.encode("e:", add_bos=False),
         SamplingParams(max_new_tokens=48, temperature=temperature, seed=1,
                        json_schema=SCHEMA, stop_token=_TOK.eos_id)),
        (_TOK.encode("v:", add_bos=False),
         SamplingParams(max_new_tokens=24, temperature=temperature, seed=2,
                        regex=r"\d{3}-\d{4}", stop_token=_TOK.eos_id)),
        ([1, 2, 3], SamplingParams(max_new_tokens=12)),
        (_TOK.encode("v2:", add_bos=False),
         SamplingParams(max_new_tokens=30, temperature=temperature, seed=7,
                        regex=r"(alpha|beta|gamma)", stop_token=_TOK.eos_id)),
    ]


@pytest.mark.parametrize("temperature", [
    0.0,
    # The sampled variant re-proves the same table path with the sampler
    # stack on top — tier-2 material under the 870 s tier-1 budget.
    pytest.param(0.9, marks=pytest.mark.slow),
])
def test_fused_table_decode_is_bit_identical(eng_factory, temperature):
    """The headline contract: table-driven fused decode == host-synced
    decode, token for token, greedy AND sampled, in a mixed batch."""
    host = eng_factory(grammar_table="off")
    dev = eng_factory(grammar_table="auto")
    a = _run(host, _constrained_reqs(temperature))
    b = _run(dev, _constrained_reqs(temperature))
    assert a == b
    # And the paths genuinely differed: host-synced stepped per token,
    # the table engine never left the fused window.
    assert host.metrics["spec_steps"] > 0
    assert dev.metrics["spec_steps"] == 0
    # Outputs actually satisfy their constraints (a budget-truncated
    # schema row must still be a legal document prefix).
    stext = _TOK.decode([t for t in b[0] if t != _TOK.eos_id])
    if b[0] and b[0][-1] == _TOK.eos_id:
        doc = json.loads(stext)
        assert set(doc) == {"id", "state"} and doc["state"] in ("on", "off")
    else:
        g = JsonSchemaGrammar(SCHEMA)
        s = g.initial()
        for byte in stext.encode():
            s = g.advance(s, byte)
            assert s is not None, stext
    assert re.fullmatch(r"\d{3}-\d{4}",
                        _TOK.decode([t for t in b[1] if t != _TOK.eos_id]))


@pytest.mark.slow
def test_pushdown_json_mode_keeps_host_synced_path(eng_factory):
    """json_mode rides the pushdown JsonGrammar — no finite table — so it
    must keep the host-synced path even with tables on, and still match
    the tables-off engine exactly."""
    reqs = [(_TOK.encode("j:", add_bos=False),
             SamplingParams(max_new_tokens=30, temperature=0.7, seed=3,
                            json_mode=True, stop_token=_TOK.eos_id))]
    dev = eng_factory(grammar_table="auto")
    host = eng_factory(grammar_table="off")
    b, a = _run(dev, reqs), _run(host, reqs)
    assert a == b
    assert dev.metrics["spec_steps"] > 0       # pushdown went host-synced
    assert dev._grammar_table(dev.grammar) is None


@pytest.mark.slow
def test_state_budget_fallback_is_exact(eng_factory):
    """A grammar exceeding the budget falls back to the host-synced path
    — same output, no crash — while small grammars in the same batch
    still ride the table."""
    small = eng_factory(grammar_table="auto", grammar_state_budget=3)
    dev = eng_factory(grammar_table="auto")
    a = _run(small, _constrained_reqs(0.8))
    b = _run(dev, _constrained_reqs(0.8))
    assert a == b
    assert small.metrics["spec_steps"] > 0     # budget-exceeded rows
    assert dev.metrics["spec_steps"] == 0


def test_fused_grammar_rows_leave_plain_rows_alone(eng_factory):
    """A tabled grammar row joining the fused window must not perturb a
    plain greedy row's stream."""
    solo = eng_factory(grammar_table="auto")
    ref = solo.generate([[1, 2, 3]], SamplingParams(max_new_tokens=12))[0]
    eng = eng_factory(grammar_table="auto")
    got = _run(eng, _constrained_reqs(0.9))
    assert got[2] == ref


@pytest.mark.slow
def test_preemption_mid_stream_is_exact(eng_factory):
    """Preemption forces a decode-state rebuild (gstate recovered from
    host bookkeeping via table.state_ids) and a re-prefill; the final
    streams must still match the host-synced engine exactly."""
    # A page pool small enough that three growing sequences with held
    # pending windows preempt each other.
    reqs = [
        (_TOK.encode("a:", add_bos=False),
         SamplingParams(max_new_tokens=80, temperature=0.9, seed=11,
                        regex=r"[ab]{60,}c", stop_token=_TOK.eos_id)),
        (_TOK.encode("b:", add_bos=False),
         SamplingParams(max_new_tokens=80, temperature=0.9, seed=12,
                        regex=r"[cd]{60,}e", stop_token=_TOK.eos_id)),
        ([4, 5, 6], SamplingParams(max_new_tokens=60)),
    ]
    host = eng_factory(grammar_table="off", num_pages=24,
                       enable_radix_cache=False)
    dev = eng_factory(grammar_table="auto", num_pages=24,
                      enable_radix_cache=False)
    a = _run(host, list(reqs))
    b = _run(dev, list(reqs))
    assert a == b
    assert dev.metrics["preemptions"] > 0      # the scenario actually hit
    assert re.fullmatch(r"[ab]{60,}c?",
                        _TOK.decode([t for t in b[0] if t != _TOK.eos_id]))


def test_grammar_table_off_knob_and_validation():
    with pytest.raises(ValueError, match="grammar_table"):
        EngineConfig(model="tiny", grammar_table="maybe").validate()
    with pytest.raises(ValueError, match="grammar_state_budget"):
        EngineConfig(model="tiny", grammar_state_budget=1).validate()


def test_device_table_upload_is_cached_per_combination(eng_factory):
    eng = eng_factory(grammar_table="auto")
    g1 = eng._grammar_for(SamplingParams(regex=r"\d+"))
    g2 = eng._grammar_for(SamplingParams(regex=r"[a-f]+"))
    n1, l1, off1 = eng._device_grammar_tables([g1, g2])
    n2, l2, off2 = eng._device_grammar_tables([g2, g1])   # order-insensitive
    assert n1 is n2 and l1 is l2 and off1 is off2
    assert set(off1) == {id(g1), id(g2)}
    # Blocks are pow-2-padded (shape reuse within a bucket, without a
    # full budget-sized block per tiny grammar).
    s1 = eng._grammar_dev_block(g1)[0].shape[0]
    assert s1 & (s1 - 1) == 0 and n1.shape[0] >= s1
    # A single-grammar batch reuses the grammar's own device block — no
    # combination entry, no copy.
    before = len(eng._gtable_dev)
    ns, _, offs = eng._device_grammar_tables([g1])
    assert ns is eng._grammar_dev_block(g1)[0]
    assert offs == {id(g1): 0} and len(eng._gtable_dev) == before


@pytest.mark.slow
def test_shared_grammar_rows_share_one_table_block(eng_factory):
    """Two rows with the SAME pattern share one compiled grammar (the
    LRU) and therefore one table block — and decode exactly."""
    eng = eng_factory(grammar_table="auto")
    reqs = [
        (_TOK.encode("p%d:" % i, add_bos=False),
         SamplingParams(max_new_tokens=20, temperature=0.8, seed=20 + i,
                        regex=r"[xy]{3,9}z", stop_token=_TOK.eos_id))
        for i in range(3)
    ]
    host = eng_factory(grammar_table="off")
    assert _run(eng, list(reqs)) == _run(host, list(reqs))
    # One shared grammar → the rows rode its cached device block; no
    # multi-grammar combination was ever materialized.
    assert len(eng._gtable_dev) == 0
    g = eng._grammar_for(SamplingParams(regex=r"[xy]{3,9}z"))
    assert getattr(g, "_dev_block", None) is not None
