"""Serving benchmark harness: open-loop Poisson load, TTFT/ITL/e2e
percentiles (sglang.bench_serving analog; BASELINE.json SLO shape)."""

import pytest

import argparse

from rbg_tpu.engine.bench_serving import _percentile, main, run


def test_percentile_edges():
    assert _percentile([1.0], 50) == 1.0
    assert _percentile([1.0, 2.0, 3.0], 0) == 1.0
    assert _percentile([1.0, 2.0, 3.0], 100) == 3.0
    assert str(_percentile([], 50)) == "nan"


@pytest.mark.slow
def test_inprocess_run_produces_slo_report():
    args = argparse.Namespace(
        requests=8, rate=64.0, input_len=8, output_len=8, model="tiny",
        page_size=8, num_pages=128, max_seq_len=128, max_batch=8,
        use_pallas="never", multi_step=1, speculative="off", addr="",
        slo_ttft_s=1000.0, slo_tpot_s=1000.0, seed=0)
    out = run(args)
    assert out["completed"] == 8
    assert out["output_tok_per_s"] > 0
    for k in ("p50", "p90", "p99"):
        assert out["ttft_s"][k] >= 0
    assert out["e2e_s"]["p50"] > 0
    # Absurdly generous targets: every completion is goodput, so
    # goodput_rps equals the completion rate and attainment is 1.0.
    assert out["slo"]["goodput_fraction"] == 1.0
    assert out["goodput_rps"] == pytest.approx(
        out["completed"] / out["duration_s"], rel=0.05)
    # An impossible TTFT target zeroes goodput without touching the
    # latency quantiles.
    args.slo_ttft_s = 1e-9
    out2 = run(args)
    assert out2["goodput_rps"] == 0.0
    assert out2["slo"]["goodput_fraction"] == 0.0


@pytest.mark.slow
def test_cli_json_line(capsys):
    rc = main(["--requests", "4", "--rate", "64", "--input-len", "8",
               "--output-len", "4", "--model", "tiny", "--use-pallas",
               "never", "--num-pages", "128", "--max-seq-len", "128",
               "--json"])
    assert rc == 0
    import json
    line = capsys.readouterr().out.strip().splitlines()[-1]
    parsed = json.loads(line)
    assert parsed["completed"] == 4
