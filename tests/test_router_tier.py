"""Sharded router tier: consistent-hash ring, bounded-load spill, the
router-to-router event feed, cross-router ingress aggregation, and the
drain/replay machinery that makes a router replica disposable.

The tier exists so the router stops being a single point of failure: N
replicas own disjoint hash ranges of the session/prefix key space, a
member leaving moves ONLY its ranges (to ring successors), and signals
that feed global decisions — the topology ratio above all — are computed
from tier SUMS, never from one replica's shard of the traffic.
"""

import threading
import time

import pytest

from rbg_tpu.engine.router import Registry, RetryBudget, RouterState
from rbg_tpu.engine.routertier import (
    BOUNDED_LOAD_FACTOR, HashRing, MemberDown, RouterTier, TierClient,
)
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.topology.signals import tier_ingress_ratio


# ---- hash ring -------------------------------------------------------------


def test_ring_owner_deterministic_and_covering():
    r1, r2 = HashRing(), HashRing()
    for m in ("a", "b", "c"):
        r1.add(m)
        r2.add(m)
    keys = [f"sess-{i}" for i in range(500)]
    owners = [r1.owner(k) for k in keys]
    # Deterministic across instances (blake2b, not salted hash()) and
    # every member owns a share.
    assert owners == [r2.owner(k) for k in keys]
    assert set(owners) == {"a", "b", "c"}
    for k in keys[:50]:
        assert r1.owners(k)[0] == r1.owner(k)


def test_ring_removal_moves_only_the_removed_members_keys():
    ring = HashRing()
    for m in ("a", "b", "c", "d"):
        ring.add(m)
    keys = [f"sess-{i}" for i in range(1000)]
    before = {k: ring.owner(k) for k in keys}
    ring.remove("b")
    moved = [k for k in keys if ring.owner(k) != before[k]]
    assert moved, "removal moved nothing"
    assert all(before[k] == "b" for k in moved)
    # The moved keys land on the removed member's ring successors.
    assert all(ring.owner(k) in ("a", "c", "d") for k in moved)


def test_ring_empty_and_single():
    ring = HashRing()
    assert ring.owner("k") is None and ring.owners("k") == []
    ring.add("solo")
    assert ring.owner("k") == "solo" and "solo" in ring
    ring.remove("solo")
    assert len(ring) == 0


# ---- bounded-load routing --------------------------------------------------


def test_route_spills_overloaded_owner_to_successor():
    tier = RouterTier(name="t-spill")
    for m in ("a", "b", "c"):
        tier.register(m)
    key = "sess-42"
    owner = tier.ring.owner(key)
    successor = tier.ring.owners(key)[1]
    assert tier.route(key) == owner
    # Load the owner past the bounded-load limit (mean stays low because
    # the siblings are idle): the SAME key now spills to the SAME
    # successor — consistent spill, not scatter.
    for _ in range(10):
        tier.acquire(owner)
    assert tier.route(key) == successor
    assert tier.route(key) == successor
    for _ in range(10):
        tier.release(owner)
    assert tier.route(key) == owner


def test_route_skips_draining_and_falls_back_when_all_loaded():
    tier = RouterTier(name="t-drain")
    for m in ("a", "b", "c"):
        tier.register(m)
    key = "sess-7"
    owner = tier.ring.owner(key)
    tier.set_draining(owner, True)
    pick = tier.route(key)
    assert pick is not None and pick != owner
    # Everyone over the limit: the first non-draining candidate is the
    # floor — routing never returns None while a live member exists.
    for m in ("a", "b", "c"):
        for _ in range(5):
            tier.acquire(m)
    assert tier.route(key) is not None
    tier.set_draining(owner, False)
    assert BOUNDED_LOAD_FACTOR > 1.0


def test_routes_counter_and_members_gauge():
    tier = RouterTier(name="t-metrics")
    tier.register("a")
    before = REGISTRY.counter(obs_names.ROUTER_RING_ROUTES_TOTAL,
                              tier="t-metrics", member="a")
    tier.route("k1")
    tier.route("k2")
    assert REGISTRY.counter(obs_names.ROUTER_RING_ROUTES_TOTAL,
                            tier="t-metrics", member="a") == before + 2
    assert REGISTRY.gauge(obs_names.ROUTER_RING_MEMBERS,
                          tier="t-metrics") == 1.0
    resh = REGISTRY.counter(obs_names.ROUTER_RING_RESHARDS_TOTAL,
                            tier="t-metrics")
    tier.remove("a")
    assert REGISTRY.counter(obs_names.ROUTER_RING_RESHARDS_TOTAL,
                            tier="t-metrics") == resh + 1
    assert REGISTRY.gauge(obs_names.ROUTER_RING_MEMBERS,
                          tier="t-metrics") == 0.0


# ---- peer event feed -------------------------------------------------------


def _tier_with_states(n=2, prefix="r"):
    tier = RouterTier(name="t-feed")
    states = []
    for i in range(n):
        st = RouterState(Registry(None), None,
                         {"worker": [f"10.0.0.{i}:9000"]},
                         router_id=f"{prefix}{i}", tier=tier)
        states.append(st)
    return tier, states


def test_backend_draining_event_folds_into_peer_pools():
    tier, (s0, s1) = _tier_with_states()
    addr = "10.9.9.9:7000"
    # s0 learns its backend is draining (CODE_DRAINING shed) and tells
    # the tier; s1's pool must reflect it WITHOUT probing that backend.
    delivered = tier.publish(s0.router_id, "draining",
                             {"backend": addr, "draining": True})
    assert delivered == 1
    assert s1.pool.is_draining(addr)
    tier.publish(s0.router_id, "draining",
                 {"backend": addr, "draining": False})
    assert not s1.pool.is_draining(addr)


def test_backend_health_event_folds_into_peer_pools():
    tier, (s0, s1) = _tier_with_states()
    addr = "10.9.9.8:7000"
    tier.publish(s0.router_id, "health",
                 {"backend": addr, "available": False})
    assert addr in s1.pool.evicted()
    tier.publish(s0.router_id, "health",
                 {"backend": addr, "available": True})
    assert addr not in s1.pool.evicted()


def test_link_rates_propagate_without_echo_loop():
    tier, (s0, s1) = _tier_with_states()
    before = tier.events_published
    # s0 observes a transfer rate locally → republishes on the feed; s1
    # folds it with _from_peer=True and must NOT republish (no echo
    # storm: exactly ONE feed event for one observation).
    s0.merge_link_rates({"10.0.0.1:9000": 2.5e9})
    assert tier.events_published == before + 1
    assert s1.linkstats.rate("10.0.0.1:9000") is not None


def test_router_drain_protocol_announces_and_waits():
    tier, (s0, s1) = _tier_with_states()
    assert s0.enter_request()
    done = []
    t = threading.Thread(
        target=lambda: done.append(s0.begin_drain(wait_s=5.0)),
        daemon=True)
    t.start()
    deadline = time.monotonic() + 2.0
    while not s0.draining and time.monotonic() < deadline:
        time.sleep(0.005)
    # Draining: new requests refused, tier re-routes its ranges.
    assert not s0.enter_request()
    assert tier.draining(s0.router_id)
    key = next(k for k in (f"s{i}" for i in range(200))
               if tier.ring.owner(k) == s0.router_id)
    assert tier.route(key) == s1.router_id
    s0.exit_request()          # the in-flight stream finishes
    t.join(timeout=5.0)
    assert done == [True], "drain did not complete clean"


# ---- cross-router ingress aggregation --------------------------------------


def test_ingress_rates_window_and_absence_discipline():
    t = {"t": 100.0}
    tier = RouterTier(name="t-ing", clock=lambda: t["t"])
    tier.register("a")
    tier.register("b")
    tier.note_ingress("a", "prefill", 600.0)
    tier.note_ingress("b", "prefill", 600.0)
    tier.note_ingress("a", "decode", 60.0)
    rates = tier.ingress_rates(window_s=60.0)
    assert rates["prefill"] == pytest.approx(20.0)   # tier SUM / window
    assert rates["decode"] == pytest.approx(1.0)
    # Outside the window: no samples → None, never 0.0.
    t["t"] = 200.0
    rates = tier.ingress_rates(window_s=60.0)
    assert rates["prefill"] is None and rates["decode"] is None
    assert tier.ingress_totals()["prefill"] == pytest.approx(1200.0)


def test_tier_ingress_ratio_identical_one_vs_n_members():
    """The aggregation contract: ratio of SUMS across members. Feeding
    the same trace to 1 member or sharding it over 3 must produce the
    IDENTICAL ratio — a mean of per-member ratios would not."""
    t = {"t": 0.0}
    one = RouterTier(name="t-one", clock=lambda: t["t"])
    one.register("solo")
    many = RouterTier(name="t-many", clock=lambda: t["t"])
    for m in ("a", "b", "c"):
        many.register(m)
    trace = [("a", 2048.0, 16.0), ("b", 32.0, 128.0), ("c", 64.0, 64.0),
             ("a", 32.0, 256.0), ("b", 4096.0, 8.0)]
    for member, prefill, decode in trace:
        t["t"] += 1.0
        one.note_ingress("solo", "prefill", prefill)
        one.note_ingress("solo", "decode", decode)
        many.note_ingress(member, "prefill", prefill)
        many.note_ingress(member, "decode", decode)
    r1 = tier_ingress_ratio(one, window_s=60.0, now=t["t"])
    rn = tier_ingress_ratio(many, window_s=60.0, now=t["t"])
    assert r1 is not None and r1 == pytest.approx(rn, abs=1e-12)
    # And it is NOT what any single member would report.
    assert r1 != pytest.approx(2048.0 / 16.0)


def test_tier_ingress_ratio_absence_is_none():
    t = {"t": 0.0}
    tier = RouterTier(name="t-none", clock=lambda: t["t"])
    tier.register("a")
    assert tier_ingress_ratio(tier, now=0.0) is None
    tier.note_ingress("a", "prefill", 100.0)
    assert tier_ingress_ratio(tier, now=0.0) is None  # one side missing


# ---- session replay across a member loss -----------------------------------


def test_tier_client_replays_token_exact_after_member_loss():
    tier = RouterTier(name="t-replay")
    for m in ("a", "b", "c"):
        tier.register(m)

    def token_fn(seed, pos):
        return (seed * 31 + pos * 7) & 0xFFFF

    killed = set()

    def deliver(member, key, seed, start, n):
        if member in killed or member not in tier.ring:
            raise MemberDown(member)
        return [token_fn(seed, p) for p in range(start, start + n)]

    client = TierClient(tier, token_fn, deliver_fn=deliver)
    key = "sess-replay"
    victim = tier.ring.owner(key)

    # Uninterrupted session: single member, no rehash.
    out = client.run_session(key, seed=5, total=32, chunk=8)
    assert out["tokens"] == [token_fn(5, p) for p in range(32)]
    assert out["rehashes"] == 0 and out["members"] == [victim]

    # Kill the owner between sessions-in-flight: the next session on the
    # same key re-hashes mid-stream and the delivered prefix is skipped,
    # never re-sent — token-exact, no duplicates.
    orig = client.deliver_fn

    def deliver_then_kill(member, key_, seed, start, n):
        if member == victim and start >= 16:
            killed.add(victim)
            tier.remove(victim)
        return orig(member, key_, seed, start, n)

    client.deliver_fn = deliver_then_kill
    out = client.run_session(key, seed=9, total=32, chunk=8)
    assert out["tokens"] == [token_fn(9, p) for p in range(32)]
    assert out["rehashes"] == 1
    assert out["members"][0] == victim and out["members"][-1] != victim


# ---- satellite: retry-budget gauge ----------------------------------------


def test_retry_budget_publishes_tokens_gauge():
    rb = RetryBudget(rate=8.0, burst=4.0)
    assert rb.take()
    g = REGISTRY.gauge(obs_names.SERVING_RETRY_BUDGET_TOKENS)
    assert g is not None and g <= 3.0 + 0.1


# ---- satellite: directory breaker backoff ----------------------------------


def test_directory_breaker_window_grows_then_resets():
    from rbg_tpu.kvtransfer.directory import DirectoryClient

    # Unroutable address: every _call attempt fails fast with OSError.
    c = DirectoryClient("127.0.0.1:1", timeout=0.05,
                        backoff_s=0.2, backoff_max_s=30.0)
    before = REGISTRY.counter(obs_names.KVT_DIR_BREAKER_OPEN_TOTAL)
    windows = []
    for _ in range(4):
        c._down_until = 0.0        # force the half-open probe NOW
        t0 = time.monotonic()
        assert c.lookup_keys(["k"]) == (0, [])
        windows.append(c._down_until - t0)
    assert REGISTRY.counter(obs_names.KVT_DIR_BREAKER_OPEN_TOTAL) \
        == before + 4
    # Decorrelated jitter grows the window from the base: later windows
    # must be able to exceed the old fixed 5 s cadence's base, and the
    # FIRST is bounded by base*3 (jitter range), proving it is not fixed.
    assert windows[0] >= 0.0
    assert max(windows) > 0.2, f"breaker window never grew: {windows}"
    # A success snaps the window back (forget + closed breaker).
    with c._lock:
        c._backoff.forget(c.addr)
        c._down_until = 0.0
    assert c._down_until == 0.0
