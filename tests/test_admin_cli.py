"""Admin API + rollout history/diff/undo over a live plane."""

import pytest

from rbg_tpu.engine.protocol import request_once
from rbg_tpu.runtime.admin import AdminServer
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


@pytest.fixture()
def served_plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=2)
    p.start()
    admin = AdminServer(p, port=0).start()
    yield p, f"127.0.0.1:{admin.port}"
    admin.stop()
    p.stop()


def call(addr, obj):
    resp, _, _ = request_once(addr, obj)
    assert resp is not None and "error" not in resp, resp
    return resp


def test_apply_status_get(served_plane):
    plane, addr = served_plane
    from rbg_tpu.api import serde
    g = make_group("demo", simple_role("server", replicas=2))
    call(addr, {"op": "apply", "manifest": serde.to_dict(g)})
    plane.wait_group_ready("demo")

    st = call(addr, {"op": "status", "name": "demo"})
    assert st["ready"] is True
    assert len(st["pods"]) == 2
    items = call(addr, {"op": "list", "kind": "RoleInstanceSet"})["items"]
    assert len(items) == 1


def test_rollout_history_diff_undo(served_plane):
    plane, addr = served_plane
    plane.apply(make_group("r", simple_role("server", replicas=1, image="engine:v1")))
    plane.wait_group_ready("r")

    g = plane.store.get("RoleBasedGroup", "default", "r")
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(g)

    def two_revisions():
        return len(call(addr, {"op": "history", "name": "r"})["revisions"]) == 2

    plane.wait_for(two_revisions, desc="second revision recorded")
    hist = call(addr, {"op": "history", "name": "r"})["revisions"]
    assert [h["revision"] for h in hist] == [1, 2]

    diff = call(addr, {"op": "diff", "name": "r"})
    joined = "\n".join(diff["diff"])
    assert "engine:v1" in joined and "engine:v2" in joined

    # undo → image back to v1 on live pods
    undo = call(addr, {"op": "undo", "name": "r"})
    assert undo["restoredRevision"] == 1

    def rolled_back():
        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return pods and all(
            p.template.containers[0].image == "engine:v1" for p in pods
        ) and all(p.running_ready for p in pods)

    plane.wait_for(rolled_back, timeout=15, desc="undo restored v1 image")


def test_events_and_delete(served_plane):
    plane, addr = served_plane
    plane.apply(make_group("ev", simple_role("s")))
    plane.wait_group_ready("ev")
    call(addr, {"op": "delete", "kind": "RoleBasedGroup", "name": "ev"})
    plane.wait_for(
        lambda: not plane.store.list("Pod", namespace="default"),
        desc="cascade delete via admin",
    )


def test_metrics_and_profile_ops(served_plane):
    plane, addr = served_plane
    plane.apply(make_group("m", simple_role("s")))
    plane.wait_group_ready("m")

    text = call(addr, {"op": "metrics"})["text"]
    assert "rbg_reconcile_total" in text
    assert 'controller="rolebasedgroup"' in text
    assert "rbg_reconcile_duration_seconds_bucket" in text

    prof = call(addr, {"op": "profile", "seconds": 0.3})
    assert prof["samples"] > 0
    assert isinstance(prof["top"], list)


def test_admin_token_auth():
    """With a token configured, every op except health requires it
    (constant-time compare; VERDICT r1 item 9 — the admin socket was
    unauthenticated)."""
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=1)
    p.start()
    admin = AdminServer(p, port=0, token="s3cret").start()
    addr = f"127.0.0.1:{admin.port}"
    try:
        # health stays open for probes (and carries the disruption
        # posture snapshot — counters + spare-pool depth)
        resp, _, _ = request_once(addr, {"op": "health"})
        assert resp["ok"] is True
        assert "rbg_disruption_preemptions_total" in resp["disruption"]
        assert "spare_pool" in resp
        # missing / wrong token rejected
        resp, _, _ = request_once(addr, {"op": "list", "kind": "Pod"})
        assert resp == {"error": "unauthorized"}
        resp, _, _ = request_once(addr, {"op": "list", "kind": "Pod",
                                         "token": "wrong"})
        assert resp == {"error": "unauthorized"}
        # correct token accepted
        resp, _, _ = request_once(addr, {"op": "list", "kind": "Pod",
                                         "token": "s3cret"})
        assert "items" in resp
    finally:
        admin.stop()
        p.stop()


def test_admin_tls_with_token(tmp_path):
    """TLS admin wire (VERDICT r3 weak #8 / the webhook-cert analog):
    self-signed CA bootstrap, TLS-wrapped socket, token never in cleartext;
    plaintext and wrong-CA clients are rejected; cert material is reused
    across restarts (idempotent bootstrap)."""
    import ssl

    pytest.importorskip("cryptography")   # cert mint needs the optional dep
    from rbg_tpu.api import serde
    from rbg_tpu.runtime.tlsutil import client_context, ensure_certs

    cert_dir = str(tmp_path / "certs")
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=2)
    p.start()
    admin = AdminServer(p, port=0, token="s3cret",
                        cert_dir=cert_dir).start()
    addr = f"127.0.0.1:{admin.port}"
    try:
        ctx = client_context(admin.ca_path)
        g = make_group("tls", simple_role("srv", replicas=1))
        resp, _, _ = request_once(
            addr, {"op": "apply", "manifest": serde.to_dict(g),
                   "token": "s3cret"}, ssl_context=ctx)
        assert "error" not in resp, resp
        p.wait_group_ready("tls")

        # Wrong token over TLS → unauthorized.
        resp, _, _ = request_once(addr, {"op": "list", "kind": "Pod",
                                         "token": "wrong"}, ssl_context=ctx)
        assert resp.get("error") == "unauthorized"

        # A plaintext client cannot speak to the TLS socket.
        try:
            resp, _, _ = request_once(addr, {"op": "health"}, timeout=5)
            assert resp is None, "plaintext client must not get a reply"
        except (OSError, ConnectionError):
            pass

        # A client pinned to a DIFFERENT CA refuses the server.
        other = client_context(ensure_certs(str(tmp_path / "other"))[0])
        try:
            request_once(addr, {"op": "health"}, timeout=5,
                         ssl_context=other)
            assert False, "expected certificate verification failure"
        except ssl.SSLError:
            pass

        # Bootstrap is idempotent: same material on reuse.
        before = open(admin.ca_path, "rb").read()
        ensure_certs(cert_dir)
        assert open(admin.ca_path, "rb").read() == before
    finally:
        admin.stop()
        p.stop()


def test_deploy_manifests_parameterization(tmp_path):
    """Helm-chart analog (inventory #29): defaults -> values file -> --set
    overrides, rendered as valid multi-doc YAML."""
    import subprocess
    import sys

    import yaml

    from rbg_tpu.utils import scrubbed_cpu_env
    vals = tmp_path / "values.yaml"
    vals.write_text("image: gcr.io/me/rbg-tpu:v4\nstate:\n  size: 5Gi\n")
    out = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "deploy-manifests",
         "--values", str(vals), "--set", "admin.tls=true",
         "--set", "namespace=prod", "--set", "networkPolicy=false"],
        env=scrubbed_cpu_env(), timeout=120, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    docs = list(yaml.safe_load_all(out.stdout))
    kinds = [d["kind"] for d in docs]
    assert kinds == ["Deployment", "PersistentVolumeClaim", "Service"]
    dep = docs[0]
    c = dep["spec"]["template"]["spec"]["containers"][0]
    assert c["image"] == "gcr.io/me/rbg-tpu:v4"          # values file
    assert "--tls-cert-dir" in c["args"]                 # --set override
    assert dep["metadata"]["namespace"] == "prod"
    assert docs[1]["spec"]["resources"]["requests"]["storage"] == "5Gi"

    # backend=k8s without kubeApi is a rendering error, not silent output.
    bad = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "deploy-manifests",
         "--set", "backend=k8s"],
        env=scrubbed_cpu_env(), timeout=120, capture_output=True, text=True)
    assert bad.returncode == 2 and "kubeApi" in bad.stderr


def test_tls_server_cert_rotation_preserves_ca(tmp_path, monkeypatch):
    """Server-cert re-mint under the EXISTING CA: clients' pinned ca.crt
    stays valid across rotation; only CA expiry forces a re-pin."""
    import os

    pytest.importorskip("cryptography")   # cert mint needs the optional dep
    from rbg_tpu.runtime import tlsutil

    d = str(tmp_path / "certs")
    ca1, crt1, key1 = tlsutil.ensure_certs(d)
    ca_bytes = open(ca1, "rb").read()
    crt_bytes = open(crt1, "rb").read()
    # Private keys are born 0600.
    assert oct(os.stat(key1).st_mode & 0o777) == "0o600"
    assert oct(os.stat(os.path.join(d, tlsutil.CA_KEY)).st_mode
               & 0o777) == "0o600"

    # Force the SERVER cert (only) past the rotation horizon.
    real_valid = tlsutil._still_valid
    monkeypatch.setattr(
        tlsutil, "_still_valid",
        lambda p: False if p.endswith(tlsutil.SERVER_CERT) else real_valid(p))
    ca2, crt2, _ = tlsutil.ensure_certs(d)
    assert open(ca2, "rb").read() == ca_bytes, "CA must be preserved"
    assert open(crt2, "rb").read() != crt_bytes, "server cert must rotate"

    # The rotated server cert still verifies against the ORIGINAL CA.
    from cryptography import x509
    from cryptography.hazmat.primitives.asymmetric import ec
    ca_cert = x509.load_pem_x509_certificate(ca_bytes)
    srv = x509.load_pem_x509_certificate(open(crt2, "rb").read())
    ca_cert.public_key().verify(
        srv.signature, srv.tbs_certificate_bytes,
        ec.ECDSA(srv.signature_hash_algorithm))
