"""Admin API + rollout history/diff/undo over a live plane."""

import pytest

from rbg_tpu.engine.protocol import request_once
from rbg_tpu.runtime.admin import AdminServer
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


@pytest.fixture()
def served_plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=2)
    p.start()
    admin = AdminServer(p, port=0).start()
    yield p, f"127.0.0.1:{admin.port}"
    admin.stop()
    p.stop()


def call(addr, obj):
    resp, _, _ = request_once(addr, obj)
    assert resp is not None and "error" not in resp, resp
    return resp


def test_apply_status_get(served_plane):
    plane, addr = served_plane
    from rbg_tpu.api import serde
    g = make_group("demo", simple_role("server", replicas=2))
    call(addr, {"op": "apply", "manifest": serde.to_dict(g)})
    plane.wait_group_ready("demo")

    st = call(addr, {"op": "status", "name": "demo"})
    assert st["ready"] is True
    assert len(st["pods"]) == 2
    items = call(addr, {"op": "list", "kind": "RoleInstanceSet"})["items"]
    assert len(items) == 1


def test_rollout_history_diff_undo(served_plane):
    plane, addr = served_plane
    plane.apply(make_group("r", simple_role("server", replicas=1, image="engine:v1")))
    plane.wait_group_ready("r")

    g = plane.store.get("RoleBasedGroup", "default", "r")
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(g)

    def two_revisions():
        return len(call(addr, {"op": "history", "name": "r"})["revisions"]) == 2

    plane.wait_for(two_revisions, desc="second revision recorded")
    hist = call(addr, {"op": "history", "name": "r"})["revisions"]
    assert [h["revision"] for h in hist] == [1, 2]

    diff = call(addr, {"op": "diff", "name": "r"})
    joined = "\n".join(diff["diff"])
    assert "engine:v1" in joined and "engine:v2" in joined

    # undo → image back to v1 on live pods
    undo = call(addr, {"op": "undo", "name": "r"})
    assert undo["restoredRevision"] == 1

    def rolled_back():
        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return pods and all(
            p.template.containers[0].image == "engine:v1" for p in pods
        ) and all(p.running_ready for p in pods)

    plane.wait_for(rolled_back, timeout=15, desc="undo restored v1 image")


def test_events_and_delete(served_plane):
    plane, addr = served_plane
    plane.apply(make_group("ev", simple_role("s")))
    plane.wait_group_ready("ev")
    call(addr, {"op": "delete", "kind": "RoleBasedGroup", "name": "ev"})
    plane.wait_for(
        lambda: not plane.store.list("Pod", namespace="default"),
        desc="cascade delete via admin",
    )


def test_metrics_and_profile_ops(served_plane):
    plane, addr = served_plane
    plane.apply(make_group("m", simple_role("s")))
    plane.wait_group_ready("m")

    text = call(addr, {"op": "metrics"})["text"]
    assert "rbg_reconcile_total" in text
    assert 'controller="rolebasedgroup"' in text
    assert "rbg_reconcile_duration_seconds_bucket" in text

    prof = call(addr, {"op": "profile", "seconds": 0.3})
    assert prof["samples"] > 0
    assert isinstance(prof["top"], list)


def test_admin_token_auth():
    """With a token configured, every op except health requires it
    (constant-time compare; VERDICT r1 item 9 — the admin socket was
    unauthenticated)."""
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=1)
    p.start()
    admin = AdminServer(p, port=0, token="s3cret").start()
    addr = f"127.0.0.1:{admin.port}"
    try:
        # health stays open for probes
        resp, _, _ = request_once(addr, {"op": "health"})
        assert resp == {"ok": True}
        # missing / wrong token rejected
        resp, _, _ = request_once(addr, {"op": "list", "kind": "Pod"})
        assert resp == {"error": "unauthorized"}
        resp, _, _ = request_once(addr, {"op": "list", "kind": "Pod",
                                         "token": "wrong"})
        assert resp == {"error": "unauthorized"}
        # correct token accepted
        resp, _, _ = request_once(addr, {"op": "list", "kind": "Pod",
                                         "token": "s3cret"})
        assert "items" in resp
    finally:
        admin.stop()
        p.stop()
