"""Store-fault injection: a failed mutation must surface (error metric +
retry), never vanish (VERDICT r1 item 7 — the scheduler used to wrap bind
mutations in ``except Exception: pass``)."""

import threading

import pytest

from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.runtime.store import NotFound
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


def _error_count():
    total = 0.0
    for line in REGISTRY.render().splitlines():
        if line.startswith("rbg_reconcile_total") and 'result="error"' in line:
            total += float(line.rsplit(" ", 1)[1])
    return total


def test_injected_bind_fault_retries_and_converges():
    """One arbitrary store fault on a Pod mutation: the worker must count an
    error and retry until the group converges — silence is the bug."""
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=2)

    real_mutate = plane.store.mutate
    fired = threading.Event()

    def flaky_mutate(kind, ns, name, fn, status=False, retries=8):
        if kind == "Pod" and not status and not fired.is_set():
            fired.set()
            raise RuntimeError("injected store fault")
        return real_mutate(kind, ns, name, fn, status=status, retries=retries)

    plane.store.mutate = flaky_mutate
    errors_before = _error_count()
    with plane:
        plane.apply(make_group("flt", simple_role("srv", replicas=2)))
        plane.wait_group_ready("flt")
    assert fired.is_set()
    # The fault was counted, not swallowed.
    assert _error_count() > errors_before


def test_pod_deleted_mid_plan_is_skipped():
    """NotFound during binding is benign: the deleted pod is skipped and the
    rest of the system converges (replacement pods re-schedule)."""
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=2)

    real_mutate = plane.store.mutate
    fired = threading.Event()

    def vanish_once(kind, ns, name, fn, status=False, retries=8):
        if kind == "Pod" and not status and not fired.is_set():
            fired.set()
            raise NotFound(f"Pod/{ns}/{name}")
        return real_mutate(kind, ns, name, fn, status=status, retries=retries)

    plane.store.mutate = vanish_once
    with plane:
        plane.apply(make_group("gone", simple_role("srv", replicas=2)))
        plane.wait_group_ready("gone")
    assert fired.is_set()
