"""K8s pod backend: fake API server semantics, translation, and the full
plane scenario matrix against the fake cluster.

Reference analog: the reference IS a K8s operator (pod_reconciler.go); this
tier is our envtest equivalent for the boundary where plane pods become
REAL Kubernetes pods (VERDICT r3 missing #2)."""

import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RoleSpec
from rbg_tpu.api.pod import (Container, NodeAffinityTerm, Pod, PodTemplate,
                             Port, Resources)
from rbg_tpu.k8s import translate as T
from rbg_tpu.k8s.client import ApiError, Conflict, KubeClient, NotFound
from rbg_tpu.k8s.fake_apiserver import FakeK8sApiServer
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, simple_role


def gke_tpu_nodes(srv, slices=2, hosts=2, accelerator="tpu-v5-lite-podslice"):
    """Register fake GKE TPU nodes: one node pool per slice (the GKE
    multi-host contract: node pool == slice)."""
    for s in range(slices):
        for h in range(hosts):
            srv.add_node(
                f"slice-{s}-host-{h}",
                labels={
                    T.LABEL_GKE_TPU_ACCEL: accelerator,
                    T.LABEL_GKE_TPU_TOPOLOGY: "2x4",
                    T.LABEL_GKE_NODEPOOL: f"pool-{s}",
                    T.LABEL_WORKER_INDEX: str(h),
                    T.LABEL_HOSTNAME: f"slice-{s}-host-{h}",
                },
                address=f"10.0.{s}.{h + 10}",
                tpu=4,
            )


@pytest.fixture()
def cluster():
    srv = FakeK8sApiServer()
    gke_tpu_nodes(srv)
    with srv:
        yield srv, KubeClient(srv.url)


def wait_until(fn, timeout=10.0, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            v = fn()
        except Exception:
            v = None
        if v:
            return v
        time.sleep(0.02)
    raise TimeoutError(desc)


# ---- fake API server semantics ----


def test_apiserver_crud_resourceversion_conflict(cluster):
    srv, cli = cluster
    pod = {"metadata": {"name": "p1", "labels": {"app": "x"}},
           "spec": {"containers": [{"name": "c", "image": "i:1"}]}}
    created = cli.create_pod("default", pod)
    assert created["metadata"]["uid"]
    rv = created["metadata"]["resourceVersion"]

    with pytest.raises(Conflict):
        cli.create_pod("default", pod)  # duplicate name

    # PUT with the CURRENT RV succeeds and bumps it (the node agent may
    # have bumped RV since create — re-read, as a real client must).
    fresh = cli.get_pod("default", "p1")
    fresh["spec"]["containers"][0]["image"] = "i:2"
    updated = cli.update_pod("default", "p1", fresh)
    assert updated["metadata"]["resourceVersion"] != fresh["metadata"]["resourceVersion"]

    # PUT with a STALE RV → 409.
    fresh["metadata"]["resourceVersion"] = rv
    with pytest.raises(Conflict):
        cli.update_pod("default", "p1", fresh)

    # labelSelector filtering.
    cli.create_pod("default", {"metadata": {"name": "p2",
                                            "labels": {"app": "y"}},
                               "spec": {"containers": []}})
    names = [p["metadata"]["name"]
             for p in cli.list_pods("default", label_selector="app=x")]
    assert names == ["p1"]

    cli.delete_pod("default", "p1")
    with pytest.raises(NotFound):
        cli.get_pod("default", "p1")


def test_apiserver_watch_stream(cluster):
    srv, cli = cluster
    cli.create_pod("default", {"metadata": {"name": "w1", "labels": {}},
                               "spec": {"containers": []}})
    events = []
    for ev_type, obj in cli.watch_pods(resource_version="0", timeout_s=2.0):
        events.append((ev_type, obj["metadata"]["name"]))
        if len(events) >= 1:
            break
    assert ("ADDED", "w1") in events


def test_apiserver_token_auth():
    srv = FakeK8sApiServer(token="s3cret")
    with srv:
        bad = KubeClient(srv.url)
        with pytest.raises(ApiError) as ei:
            bad.list_pods("default")
        assert ei.value.status == 401
        good = KubeClient(srv.url, token="s3cret")
        assert good.list_pods("default") == []


# ---- translation ----


def test_translate_tpu_pod_shape():
    pod = Pod()
    pod.metadata.name = "g-role-0"
    pod.metadata.namespace = "default"
    pod.metadata.uid = "uid-123"
    pod.metadata.annotations[C.ANN_SLICE_BINDING] = "pool-1"
    pod.node_name = "slice-1-host-0"
    pod.template = PodTemplate(
        labels={"a": "b"},
        containers=[Container(
            name="engine", image="engine:v1", command=["serve"],
            ports=[Port(name="http", container_port=8000)],
            resources=Resources(cpu=2, memory_gb=8, tpu_chips=4))],
    )
    pod.affinity = [
        NodeAffinityTerm(key="x", operator="In", values=["1"], required=True),
        NodeAffinityTerm(key="warm", operator="In", values=["n1"],
                         required=False, weight=10),
    ]
    k = T.to_k8s_pod(pod)
    c = k["spec"]["containers"][0]
    assert c["resources"]["limits"][T.TPU_RESOURCE] == "4"
    assert c["resources"]["requests"][T.TPU_RESOURCE] == "4"
    assert k["spec"]["hostNetwork"] is True
    assert k["spec"]["nodeSelector"][T.LABEL_HOSTNAME] == "slice-1-host-0"
    assert k["metadata"]["labels"][T.LABEL_MANAGED_BY] == T.MANAGED_BY
    assert k["metadata"]["annotations"][T.ANN_PLANE_UID] == "uid-123"
    req = k["spec"]["affinity"]["nodeAffinity"][
        "requiredDuringSchedulingIgnoredDuringExecution"]
    exprs = req["nodeSelectorTerms"][0]["matchExpressions"]
    # Required terms AND-fold into one selector term (node_binding.go:409),
    # including the slice pin on the GKE node-pool label.
    assert {"key": T.LABEL_GKE_NODEPOOL, "operator": "In",
            "values": ["pool-1"]} in exprs
    assert {"key": "x", "operator": "In", "values": ["1"]} in exprs
    pref = k["spec"]["affinity"]["nodeAffinity"][
        "preferredDuringSchedulingIgnoredDuringExecution"]
    assert pref[0]["weight"] == 10


def test_node_from_k8s_tpu_labels(cluster):
    srv, cli = cluster
    nodes = [T.node_from_k8s(n) for n in cli.list_nodes()]
    by_name = {n.metadata.name: n for n in nodes}
    n = by_name["slice-1-host-1"]
    assert n.tpu.slice_id == "pool-1"
    assert n.tpu.slice_topology == "2x4"
    assert n.tpu.worker_index == 1
    assert n.tpu.chips == 4
    assert n.address == "10.0.1.11"
    assert n.ready


# ---- full plane scenarios (the --backend k8s matrix) ----


@pytest.fixture()
def k8s_plane(cluster):
    srv, cli = cluster
    plane = ControlPlane(backend="k8s", k8s_client=cli)
    with plane:
        yield srv, cli, plane


def test_group_becomes_ready_through_cluster(k8s_plane):
    srv, cli, plane = k8s_plane
    # Node sync happened at backend start: plane sees the cluster's nodes.
    assert len(plane.store.list("Node")) == 4
    plane.apply(make_group("svc", simple_role("worker", replicas=2)))
    plane.wait_group_ready("svc", timeout=10)

    kpods = cli.list_pods(
        label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}")
    assert len(kpods) == 2
    for kp in kpods:
        # Plane placement pinned via hostname selector; agent bound it.
        assert kp["spec"]["nodeSelector"][T.LABEL_HOSTNAME]
        assert kp["spec"]["nodeName"] == kp["spec"]["nodeSelector"][T.LABEL_HOSTNAME]
        assert kp["status"]["phase"] == "Running"
    # Cluster status reflected into the plane store.
    for pod in plane.store.list("Pod"):
        assert pod.status.phase == "Running" and pod.status.ready
        assert pod.status.pod_ip.startswith("10.0.")


def test_out_of_band_pod_delete_is_replaced(k8s_plane):
    srv, cli, plane = k8s_plane
    plane.apply(make_group("svc", simple_role("worker", replicas=1)))
    plane.wait_group_ready("svc", timeout=10)
    victim = cli.list_pods(
        label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}")[0]
    name = victim["metadata"]["name"]
    plane_uid = victim["metadata"]["annotations"][T.ANN_PLANE_UID]
    cli.delete_pod("default", name)  # kubectl delete / node drain analog

    # The restart engine must REPLACE it (a fresh plane pod incarnation,
    # new plane uid) — not resurrect the failed one's mirror.
    def recovered():
        pods = cli.list_pods(
            label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}")
        return (len(pods) == 1
                and pods[0]["status"].get("phase") == "Running"
                and pods[0]["metadata"]["annotations"][T.ANN_PLANE_UID]
                != plane_uid)
    wait_until(recovered, desc="pod replaced after out-of-band delete")
    plane.wait_group_ready("svc", timeout=10)


def test_group_delete_cleans_cluster(k8s_plane):
    srv, cli, plane = k8s_plane
    plane.apply(make_group("svc", simple_role("worker", replicas=2)))
    plane.wait_group_ready("svc", timeout=10)
    plane.store.delete("RoleBasedGroup", "default", "svc")
    wait_until(lambda: not cli.list_pods(
        label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}"),
        desc="cluster pods cleaned after group delete")
    wait_until(lambda: not plane.store.list("Pod"),
               desc="plane pods finalized")


def test_inplace_update_patches_cluster_pod(k8s_plane):
    # Deflake history: end-to-end asynchronous — plane reconcile → REST
    # patch → node-agent ack → watch reflector → plane status, five
    # thread/HTTP hops. Fit 10 s in isolation but starved order-dependently
    # under the full run's ambient load. Root causes fixed since: every
    # plane leaked ~8 controller resync threads parked 300 s (stop() now
    # Event-wakes and joins them), controller workqueues kept draining
    # reconciles AFTER stop (get() now returns None once shut down), and
    # the k8s reflector could outlive stop() by its watch window (join now
    # covers WATCH_WINDOW_S). The thread-lifecycle lint rule guards the
    # class; the wide budget below stays as load margin.
    srv, cli, plane = k8s_plane
    grp = make_group("svc", simple_role("worker", replicas=1))
    plane.apply(grp)
    plane.wait_group_ready("svc", timeout=30)
    # The reflector may still be syncing the fresh pod's status: wait for
    # the UID to be stable under the managed-by selector, not just ready.
    before = wait_until(lambda: (cli.list_pods(
        label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}") or [None])[0],
        timeout=30, desc="cluster pod mirrored")

    grp2 = make_group("svc", simple_role("worker", replicas=1,
                                         image="engine:v2"))
    plane.apply(grp2)

    def updated():
        pods = cli.list_pods(
            label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}")
        if len(pods) != 1:
            return False
        kp = pods[0]
        cs = kp["status"].get("containerStatuses", [])
        return (kp["spec"]["containers"][0]["image"] == "engine:v2"
                and cs and cs[0]["image"] == "engine:v2"
                and cs[0]["restartCount"] >= 1
                # Same K8s pod object — updated in place, not recreated.
                and kp["metadata"]["uid"] == before["metadata"]["uid"])
    wait_until(updated, timeout=30, desc="in-place image patch acked by cluster")
    plane.wait_group_ready("svc", timeout=30)
    pod = plane.store.list("Pod")[0]
    assert pod.status.restart_count >= 1


def test_serve_resume_adopts_cluster_pods(cluster):
    """A plane restarted from its snapshot adopts the mirrored pods instead
    of recreating them (SIGKILL-resume parity for the k8s backend)."""
    srv, cli = cluster
    plane = ControlPlane(backend="k8s", k8s_client=cli)
    with plane:
        plane.apply(make_group("svc", simple_role("worker", replicas=2)))
        plane.wait_group_ready("svc", timeout=10)
        snapshot = plane.store.snapshot()
        uids = sorted(p["metadata"]["uid"] for p in cli.list_pods(
            label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}"))

    from rbg_tpu.runtime.store import Store
    store2 = Store()
    store2.load_snapshot(snapshot)
    plane2 = ControlPlane(store=store2, backend="k8s", k8s_client=cli)
    with plane2:
        plane2.wait_group_ready("svc", timeout=10)
        uids2 = sorted(p["metadata"]["uid"] for p in cli.list_pods(
            label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}"))
        assert uids2 == uids  # adopted, not recreated


# ---- watch chaos: disconnects and 410 compaction (VERDICT r4 #4) ----


def test_watch_kill_mid_burst_no_lost_status(k8s_plane):
    """Closing every watch stream mid-burst (LB idle reset / apiserver
    rolling restart analog) must not lose pod status: the reflector
    reconnects at its bookmark and every group still converges."""
    srv, cli, plane = k8s_plane
    for i in range(6):
        plane.apply(make_group(f"wk-{i}", simple_role("worker", replicas=2)))
        if i == 2:
            srv.kill_watches()
    for i in range(6):
        plane.wait_group_ready(f"wk-{i}", timeout=20)
    for pod in plane.store.list("Pod"):
        assert pod.status.phase == "Running" and pod.status.ready


def test_watch_410_compaction_resyncs(k8s_plane):
    """Compacting the watch history past the reflector's bookmark makes
    the stream emit a 410 ERROR; the backend must full-relist (including
    synthesizing DELETED for mirrors that vanished while dark) and
    converge. Silent event loss was the pre-fix behavior."""
    srv, cli, plane = k8s_plane
    plane.apply(make_group("g410", simple_role("worker", replicas=2)))
    plane.wait_group_ready("g410", timeout=20)

    # Deterministic dark window: freeze event delivery, delete one mirror
    # out-of-band, wait for the fake agent to finalize it (the DELETED is
    # recorded but undelivered), then expire the history PAST the frozen
    # reflector's bookmark. On resume only the 410→relist path can
    # observe the deletion.
    victim = cli.list_pods(
        label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}")[0]
    vname = victim["metadata"]["name"]
    srv.pause_watches(True)
    cli.delete_pod("default", vname)

    def mirror_gone():
        try:
            cli.get_pod("default", vname)
            return False
        except NotFound:
            return True
    wait_until(mirror_gone, timeout=10, desc="mirror finalized")
    srv.compact(keep_last=1)
    srv.pause_watches(False)

    # The replacement proves the DELETED synthesis reached the restart
    # engine: back to 2 Running mirrors with a new incarnation.
    def healthy():
        pods = cli.list_pods(
            label_selector=f"{T.LABEL_MANAGED_BY}={T.MANAGED_BY}")
        return (len(pods) == 2
                and all(p["status"].get("phase") == "Running" for p in pods))
    wait_until(healthy, timeout=20, desc="replacement after 410 relist")
    plane.wait_group_ready("g410", timeout=20)


def test_stress_harness_k8s_backend_smoke():
    """`rbg-tpu stress --backend k8s` end to end at small scale: the full
    mirror path (REST create -> agent Running -> watch reflect -> plane
    Ready) under the same phases the fake-backend table uses."""
    from rbg_tpu.stress.harness import StressConfig, run_stress

    report = run_stress(StressConfig(
        groups=4, roles_per_group=2, replicas=2, create_qps=10.0,
        slices=4, hosts_per_slice=2, backend="k8s"))
    assert report["backend"] == "k8s"
    assert report["create_to_ready_ms"]["n"] == 4
    assert report["create_to_ready_ms"]["p99"] < 10_000
    assert report["update_to_converged_ms"]["n"] == 4
