"""tpu-check harness: verdict shape, timeout containment, wedged-state
skipping. The real-chip path can't run here (tunnel wedged — the exact
condition the harness exists to survive); these tests pin the harness
semantics themselves."""

import json
import subprocess
import sys

from rbg_tpu.cli import tpucheck


def test_stage_timeout_contains_hang(monkeypatch):
    monkeypatch.setitem(tpucheck.STAGE_TIMEOUTS, "probe", 1)
    res = tpucheck._run_stage("probe", "import time; time.sleep(30)")
    assert res["ok"] is False
    assert res["elapsed_s"] <= 5
    assert "hung past its timeout" in res["detail"]


def test_stage_collects_json_payload():
    res = tpucheck._run_stage("probe", "print(json.dumps({'backend': 'x'}))")
    assert res["ok"] is True and res["backend"] == "x"


def test_stage_failure_carries_stderr():
    res = tpucheck._run_stage("probe", "raise RuntimeError('boom')")
    assert res["ok"] is False
    assert "boom" in (res.get("stderr_tail") or "")


def test_wedged_probe_skips_later_stages(monkeypatch, capsys):
    monkeypatch.setitem(tpucheck.STAGE_TIMEOUTS, "probe", 1)
    monkeypatch.setattr(tpucheck, "_PROBE", "import time; time.sleep(30)")
    rc = tpucheck.run(["--stages", "probe,pallas,engine"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 2                       # wedged-tunnel exit code
    assert out["ok"] is False and out["wedged_tunnel"] is True
    assert out["stages"]["pallas"]["skipped"] is True
    assert out["stages"]["engine"]["skipped"] is True


def test_engine_stage_fails_cleanly_off_tpu():
    """On a CPU-only interpreter the engine stage must fail fast with a
    clear assertion, not hang or crash the harness."""
    from rbg_tpu.utils import scrubbed_cpu_env
    # Run the actual harness in a scrubbed-CPU subprocess so the stage's
    # own subprocesses inherit JAX_PLATFORMS=cpu (fast, no tunnel).
    env = scrubbed_cpu_env()
    out = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.tpucheck",
         "--stages", "engine"],
        env=env, timeout=300, capture_output=True, text=True)
    doc = json.loads(out.stdout.strip().splitlines()[-1])
    assert doc["ok"] is False and out.returncode == 1
    assert "not on tpu" in (doc["stages"]["engine"].get("stderr_tail") or "")


def test_stage_payloads_are_valid_python():
    for name, code in (("probe", tpucheck._PROBE),
                       ("pallas", tpucheck._PALLAS),
                       ("engine", tpucheck._ENGINE)):
        compile("import json\n" + code, f"<{name}>", "exec")
