"""NodeBindingStore depth tests (reference analog:
``sync/node_binding_test.go``, 1,378 LoC — reference parity matrix:
granularity modes, avoid labels, Required folding, node_binding.go:191,
276, 409).

Unit: pod vs component granularity keys, auto-resolution, mode semantics,
avoid-label injection, eviction, reseed. Integration: a vanished warm node
must not strand a pod; slice-binding annotations steer placement.
"""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RestartPolicyConfig
from rbg_tpu.api.pod import Node, Pod, TpuNodeInfo
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.runtime.store import Store
from rbg_tpu.sched import binding as B
from rbg_tpu.sched.binding import NodeBindingStore
from rbg_tpu.testutil import (
    make_group, make_tpu_nodes, simple_role, tpu_leaderworker_role,
)


def _pod(group, name="p", role="r", comp="main", index=None, ns="default"):
    p = Pod()
    p.metadata.name = name
    p.metadata.namespace = ns
    p.metadata.labels = {C.LABEL_GROUP_NAME: group,
                         C.LABEL_INSTANCE_NAME: f"{group}-{role}-0",
                         C.LABEL_ROLE_NAME: role,
                         C.LABEL_COMPONENT_NAME: comp}
    if index is not None:
        p.metadata.labels[C.LABEL_INSTANCE_INDEX] = str(index)
    return p


def _node(name, slice_id=""):
    n = Node()
    n.metadata.name = name
    n.tpu = TpuNodeInfo(slice_id=slice_id)
    return n


class TestGranularity:
    """resolveGranularity + buildKey matrix (node_binding.go:150-205)."""

    def test_auto_stateful_is_pod_stateless_is_component(self):
        assert B.resolve_granularity(_pod("g", index=0)) == B.GRANULARITY_POD
        assert B.resolve_granularity(_pod("g")) == B.GRANULARITY_COMPONENT

    def test_explicit_annotation_wins(self):
        ann = {C.ANN_INPLACE_SCHEDULING_GRANULARITY: B.GRANULARITY_COMPONENT}
        assert B.resolve_granularity(_pod("g", index=0), ann) == \
            B.GRANULARITY_COMPONENT
        ann = {C.ANN_INPLACE_SCHEDULING_GRANULARITY: B.GRANULARITY_POD}
        assert B.resolve_granularity(_pod("g"), ann) == B.GRANULARITY_POD

    def test_pod_granularity_binds_per_pod_name(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "s-0", index=0), _node("n1", "s1"))
        nb.record(_pod("g", "s-1", index=1), _node("n2", "s2"))
        assert nb.preferred_nodes(_pod("g", "s-0", index=0)) == {"n1"}
        assert nb.preferred_nodes(_pod("g", "s-1", index=1)) == {"n2"}
        assert nb.preferred_slice(_pod("g", "s-0", index=0)) == "s1"
        # A pod name never seen has no binding.
        assert nb.preferred_nodes(_pod("g", "s-9", index=9)) == set()

    def test_component_granularity_accumulates_across_pod_names(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "a1b2c", comp="worker"), _node("n1"))
        nb.record(_pod("g", "x9y8z", comp="worker"), _node("n2"))
        nb.record(_pod("g", "q7w6e", comp="cache"), _node("n3"))
        # Random stateless names share the component's warm set.
        assert nb.preferred_nodes(_pod("g", "NEW", comp="worker")) == \
            {"n1", "n2"}
        assert nb.preferred_nodes(_pod("g", "NEW", comp="cache")) == {"n3"}

    def test_namespace_and_group_isolation(self):
        nb = NodeBindingStore()
        nb.record(_pod("g1", "p", index=0), _node("n1", "s1"))
        nb.record(_pod("g2", "p", index=0), _node("n2", "s2"))
        nb.record(_pod("g1", "p", index=0, ns="other"), _node("n3", "s3"))
        assert nb.preferred_nodes(_pod("g1", "p", index=0)) == {"n1"}
        assert nb.preferred_slice(_pod("g2", "p", index=0)) == "s2"
        assert nb.preferred_nodes(_pod("g1", "p", index=0, ns="other")) == {"n3"}

    def test_unlabeled_pod_never_recorded(self):
        nb = NodeBindingStore()
        nb.record(Pod(), _node("n1"))
        assert nb.preferred_nodes(_pod("g", "p")) == set()
        assert nb.preferred_slice(Pod()) is None


class TestInjection:
    """InjectInPlaceScheduling matrix (node_binding.go:276-409)."""

    def test_preferred_mode_default(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "s-0", index=0), _node("n1"))
        terms = nb.affinity_terms(_pod("g", "s-0", index=0))
        assert len(terms) == 1
        assert terms[0].required is False and terms[0].values == ["n1"]
        assert nb.affinity_terms(_pod("g", "s-9", index=9)) == []

    def test_required_mode_hard_constraint(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "s-0", index=0), _node("n1"))
        ann = {C.ANN_INPLACE_SCHEDULING: B.MODE_REQUIRED}
        terms = nb.affinity_terms(_pod("g", "s-0", index=0), ann)
        assert len(terms) == 1
        assert terms[0].required is True and terms[0].values == ["n1"]

    def test_avoid_labels_become_required_doesnotexist(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "s-0", index=0), _node("n1"))
        ann = {C.ANN_INPLACE_SCHEDULING_AVOID: "maintenance, spot-vm ,"}
        terms = nb.affinity_terms(_pod("g", "s-0", index=0), ann)
        avoid = [t for t in terms if t.operator == "DoesNotExist"]
        assert [t.key for t in avoid] == ["maintenance", "spot-vm"]
        # Avoid terms are ALWAYS required (AND-folded with everything,
        # foldIntoRequired:409), even when the warm term is preferred.
        assert all(t.required for t in avoid)
        warm = [t for t in terms if t.operator == "In"]
        assert len(warm) == 1 and warm[0].required is False

    def test_avoid_injected_even_without_binding(self):
        nb = NodeBindingStore()
        ann = {C.ANN_INPLACE_SCHEDULING_AVOID: "maintenance"}
        terms = nb.affinity_terms(_pod("g", "new", index=0), ann)
        assert len(terms) == 1
        assert terms[0].operator == "DoesNotExist" and terms[0].required

    def test_disabled_mode_injects_nothing(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "s-0", index=0), _node("n1"))
        ann = {C.ANN_INPLACE_SCHEDULING: B.MODE_DISABLED,
               C.ANN_INPLACE_SCHEDULING_AVOID: "maintenance"}
        assert nb.affinity_terms(_pod("g", "s-0", index=0), ann) == []

    def test_exclusive_topology_skips_injection(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "s-0", index=0), _node("n1"))
        p = _pod("g", "s-0", index=0)
        p.metadata.annotations[C.ANN_EXCLUSIVE_TOPOLOGY] = "tpu-slice"
        assert nb.affinity_terms(p) == []


class TestLifecycle:
    def test_evict_group_scopes_to_that_group(self):
        nb = NodeBindingStore()
        nb.record(_pod("g1", "p", index=0), _node("n1", "s1"))
        nb.record(_pod("g2", "p", index=0), _node("n2", "s2"))
        nb.evict_group("g1")
        assert nb.preferred_nodes(_pod("g1", "p", index=0)) == set()
        assert nb.preferred_slice(_pod("g1", "p", index=0)) is None
        assert nb.preferred_nodes(_pod("g2", "p", index=0)) == {"n2"}

    def test_reseed_only_from_running_ready(self):
        store = Store()
        store.create(_node("n1", "s1"))
        store.create(_node("n2", "s2"))
        ready = _pod("g", "ready", index=0)
        ready.node_name = "n1"
        store.create(ready)
        store.mutate("Pod", "default", "ready",
                     lambda p: (setattr(p.status, "phase", "Running"),
                                setattr(p.status, "ready", True)) and True,
                     status=True)
        pending = _pod("g", "pending", index=1)
        pending.node_name = "n2"
        store.create(pending)

        nb = NodeBindingStore()
        nb.record(_pod("stale", "x", index=0), _node("n9"))  # garbage
        nb.reseed(store)
        assert nb.preferred_nodes(_pod("g", "ready", index=0)) == {"n1"}
        assert nb.preferred_slice(_pod("g", "ready", index=0)) == "s1"
        assert nb.preferred_nodes(_pod("g", "pending", index=1)) == set()
        assert nb.preferred_nodes(_pod("stale", "x", index=0)) == set()


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=3, hosts_per_slice=2)
    with p:
        yield p


def test_vanished_warm_node_does_not_strand(plane):
    """Warm affinity is a preference: if the recorded node is cordoned away,
    the recreated pod must land elsewhere rather than stay Pending
    (reference: preferred vs required folding, node_binding.go:276)."""
    role = simple_role("srv", replicas=1)
    role.restart_policy = RestartPolicyConfig(base_delay_seconds=0.01)
    plane.apply(make_group("van", role))
    plane.wait_group_ready("van")
    (pod0,) = plane.store.list("Pod", namespace="default")
    warm_node = pod0.node_name

    # Take the warm node down, then kill the pod.
    plane.store.mutate("Node", "default", warm_node,
                       lambda n: setattr(n, "ready", False) or True)
    plane.kubelet.fail_pod("default", pod0.metadata.name)

    def rescheduled():
        ps = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return (len(ps) == 1 and ps[0].metadata.uid != pod0.metadata.uid
                and ps[0].running_ready
                and ps[0].node_name != warm_node) or None

    plane.wait_for(rescheduled, timeout=15, desc="landed on a cold node")
    plane.wait_group_ready("van")


def test_slice_binding_annotation_steers_placement(plane):
    """A pod carrying the slice-binding annotation prefers that slice even
    when another slice is emptier (warm HBM wins over balance)."""
    role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
    plane.apply(make_group("sb", role))
    plane.wait_group_ready("sb")
    nodes = {n.metadata.name: n for n in plane.store.list("Node")}
    pods = plane.store.list("Pod", namespace="default")
    used_slice = {nodes[p.node_name].tpu.slice_id for p in pods}.pop()

    # The binding store now prefers used_slice for each REAL pod identity.
    for p in pods:
        assert plane.node_binding.preferred_slice(p) == used_slice


def test_group_delete_evicts_bindings(plane):
    role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
    plane.apply(make_group("ev", role))
    plane.wait_group_ready("ev")
    probe = plane.store.list("Pod", namespace="default")[0]
    assert plane.node_binding.preferred_slice(probe)

    plane.store.delete("RoleBasedGroup", "default", "ev")
    plane.wait_for(
        lambda: not plane.store.list("Pod", namespace="default"),
        timeout=15, desc="cascade delete")
    plane.wait_for(
        lambda: plane.node_binding.preferred_slice(probe) is None,
        timeout=10, desc="bindings evicted with the group")


def test_avoid_label_filters_slice_gang_placement(plane):
    """Required avoid terms must constrain the SLICE-GANG path too: a
    leaderworker instance whose role declares an avoid label never lands on
    a slice whose hosts carry it (review r4: _place_slice_group ignored
    pod.affinity)."""
    # Mark every host of slices 0 and 1 as under maintenance.
    for n in plane.store.list("Node"):
        if n.tpu.slice_id in ("slice-0", "slice-1"):
            plane.store.mutate(
                "Node", "default", n.metadata.name,
                lambda x: x.labels.__setitem__("maintenance", "true") or True)
    role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
    role.template.annotations = {
        C.ANN_INPLACE_SCHEDULING_AVOID: "maintenance"}
    g = make_group("avoid", role)
    plane.apply(g)
    plane.wait_group_ready("avoid", timeout=15)
    nodes = {n.metadata.name: n for n in plane.store.list("Node")}
    for p in plane.store.list("Pod", namespace="default"):
        assert nodes[p.node_name].tpu.slice_id == "slice-2"
