"""NodeBindingStore depth tests (reference analog:
``sync/node_binding_test.go``, 1,378 LoC — VERDICT r1 missing#6 test depth).

Unit: per-(group, instance) isolation, slice granularity, eviction, reseed.
Integration: preferred (never required) affinity semantics — a vanished warm
node must not strand a pod; slice-binding annotations steer placement.
"""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RestartPolicyConfig
from rbg_tpu.api.pod import Node, Pod, TpuNodeInfo
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.runtime.store import Store
from rbg_tpu.sched.binding import NodeBindingStore
from rbg_tpu.testutil import (
    make_group, make_tpu_nodes, simple_role, tpu_leaderworker_role,
)


def _pod(group, inst, name="p"):
    p = Pod()
    p.metadata.name = name
    p.metadata.namespace = "default"
    p.metadata.labels = {C.LABEL_GROUP_NAME: group, C.LABEL_INSTANCE_NAME: inst}
    return p


def _node(name, slice_id=""):
    n = Node()
    n.metadata.name = name
    n.tpu = TpuNodeInfo(slice_id=slice_id)
    return n


class TestUnit:
    def test_per_instance_isolation(self):
        nb = NodeBindingStore()
        nb.record(_pod("g1", "i1"), _node("n1", "s1"))
        nb.record(_pod("g1", "i2"), _node("n2", "s2"))
        nb.record(_pod("g2", "i1"), _node("n3", "s3"))
        assert nb.preferred_nodes(_pod("g1", "i1")) == {"n1"}
        assert nb.preferred_slice(_pod("g1", "i1")) == "s1"
        assert nb.preferred_nodes(_pod("g1", "i2")) == {"n2"}
        assert nb.preferred_slice(_pod("g2", "i1")) == "s3"

    def test_unlabeled_pod_never_recorded(self):
        nb = NodeBindingStore()
        nb.record(Pod(), _node("n1"))
        assert nb.preferred_nodes(_pod("g", "i")) == set()
        assert nb.preferred_slice(Pod()) is None

    def test_multi_host_accumulates_nodes_latest_slice_wins(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "i", "p0"), _node("h0", "sA"))
        nb.record(_pod("g", "i", "p1"), _node("h1", "sA"))
        assert nb.preferred_nodes(_pod("g", "i")) == {"h0", "h1"}
        # instance migrated: new slice replaces the binding
        nb.record(_pod("g", "i", "p0"), _node("h9", "sB"))
        assert nb.preferred_slice(_pod("g", "i")) == "sB"

    def test_evict_group_scopes_to_that_group(self):
        nb = NodeBindingStore()
        nb.record(_pod("g1", "i"), _node("n1", "s1"))
        nb.record(_pod("g2", "i"), _node("n2", "s2"))
        nb.evict_group("g1")
        assert nb.preferred_nodes(_pod("g1", "i")) == set()
        assert nb.preferred_slice(_pod("g1", "i")) is None
        assert nb.preferred_nodes(_pod("g2", "i")) == {"n2"}

    def test_affinity_terms_preferred_never_required(self):
        nb = NodeBindingStore()
        nb.record(_pod("g", "i"), _node("n1"))
        terms = nb.affinity_terms(_pod("g", "i"))
        assert len(terms) == 1
        assert terms[0].required is False and terms[0].values == ["n1"]
        assert nb.affinity_terms(_pod("g", "other")) == []

    def test_reseed_only_from_running_ready(self):
        store = Store()
        store.create(_node("n1", "s1"))
        store.create(_node("n2", "s2"))
        ready = _pod("g", "i1", "ready")
        ready.node_name = "n1"
        store.create(ready)
        store.mutate("Pod", "default", "ready",
                     lambda p: (setattr(p.status, "phase", "Running"),
                                setattr(p.status, "ready", True)) and True,
                     status=True)
        pending = _pod("g", "i2", "pending")
        pending.node_name = "n2"
        store.create(pending)

        nb = NodeBindingStore()
        nb.record(_pod("stale", "x"), _node("n9"))  # pre-restart garbage
        nb.reseed(store)
        assert nb.preferred_nodes(_pod("g", "i1")) == {"n1"}
        assert nb.preferred_slice(_pod("g", "i1")) == "s1"
        assert nb.preferred_nodes(_pod("g", "i2")) == set()   # not ready
        assert nb.preferred_nodes(_pod("stale", "x")) == set()  # cleared


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=3, hosts_per_slice=2)
    with p:
        yield p


def test_vanished_warm_node_does_not_strand(plane):
    """Warm affinity is a preference: if the recorded node is cordoned away,
    the recreated pod must land elsewhere rather than stay Pending
    (reference: preferred vs required folding, node_binding.go:276)."""
    role = simple_role("srv", replicas=1)
    role.restart_policy = RestartPolicyConfig(base_delay_seconds=0.01)
    plane.apply(make_group("van", role))
    plane.wait_group_ready("van")
    (pod0,) = plane.store.list("Pod", namespace="default")
    warm_node = pod0.node_name

    # Take the warm node down, then kill the pod.
    plane.store.mutate("Node", "default", warm_node,
                       lambda n: setattr(n, "ready", False) or True)
    plane.kubelet.fail_pod("default", pod0.metadata.name)

    def rescheduled():
        ps = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return (len(ps) == 1 and ps[0].metadata.uid != pod0.metadata.uid
                and ps[0].running_ready
                and ps[0].node_name != warm_node) or None

    plane.wait_for(rescheduled, timeout=15, desc="landed on a cold node")
    plane.wait_group_ready("van")


def test_slice_binding_annotation_steers_placement(plane):
    """A pod carrying the slice-binding annotation prefers that slice even
    when another slice is emptier (warm HBM wins over balance)."""
    # Occupy slice-0 partially so 'emptiest-first' would pick another.
    role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
    plane.apply(make_group("sb", role))
    plane.wait_group_ready("sb")
    nodes = {n.metadata.name: n for n in plane.store.list("Node")}
    pods = plane.store.list("Pod", namespace="default")
    used_slice = {nodes[p.node_name].tpu.slice_id for p in pods}.pop()

    # The binding store should now prefer used_slice for this instance.
    inst = plane.store.list("RoleInstance", namespace="default")[0]
    probe = Pod()
    probe.metadata.labels = dict(inst.metadata.labels)
    probe.metadata.labels[C.LABEL_INSTANCE_NAME] = inst.metadata.name
    assert plane.node_binding.preferred_slice(probe) == used_slice


def test_group_delete_evicts_bindings(plane):
    role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
    plane.apply(make_group("ev", role))
    plane.wait_group_ready("ev")
    inst = plane.store.list("RoleInstance", namespace="default")[0]
    probe = Pod()
    probe.metadata.labels = dict(inst.metadata.labels)
    probe.metadata.labels[C.LABEL_INSTANCE_NAME] = inst.metadata.name
    assert plane.node_binding.preferred_slice(probe)

    plane.store.delete("RoleBasedGroup", "default", "ev")
    plane.wait_for(
        lambda: not plane.store.list("Pod", namespace="default"),
        timeout=15, desc="cascade delete")
    plane.wait_for(
        lambda: plane.node_binding.preferred_slice(probe) is None,
        timeout=10, desc="bindings evicted with the group")
