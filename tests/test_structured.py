"""Structured output: JSON-mode grammar-constrained decoding.

The signature feature of the reference's flagship engine (SGLang —
structured generation; vLLM guided/JSON mode), built TPU-side as
host-computed token masks applied inside the jitted sampler (engine
routing: constrained rows decode through the host-synced verify step,
composing exactly with n-gram speculative drafts)."""

import json

import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.grammar import JsonGrammar, TokenGrammar, token_bytes_for
from rbg_tpu.engine.tokenizer import ByteTokenizer



# ---- byte automaton ----


def _accepts(text: str) -> bool:
    g = JsonGrammar()
    s = g.initial()
    for b in text.encode():
        s = g.advance(s, b)
        if s is None:
            return False
    return g.is_complete(s)


@pytest.mark.parametrize("text", [
    '{}', '[]', '{"a": 1}', '[1, 2.5, -3e+7, "x", true, false, null]',
    '{"k": {"n": [[]]}, "s": "\\u00e9 \\n"}', '  42  ', '"hi"', '0.5',
    '{"a":[{"b":null}]}', '-0', '[[], {}]', '1e9',
])
def test_grammar_accepts_valid_json(text):
    assert _accepts(text)
    json.loads(text)  # python agrees it is valid


@pytest.mark.parametrize("text", [
    '{', '{]', '{"a" 1}', '[1,]', '01', '+1', '1.', '.5', 'tru', '"\\x"',
    '{"a": 1,}', '{a: 1}', '[1 2]', '"unterminated', '{} x', '[],', 'nan',
])
def test_grammar_rejects_invalid_json(text):
    assert not _accepts(text)


def test_grammar_number_termination():
    g = JsonGrammar()
    s = g.initial()
    for b in b"12":
        s = g.advance(s, b)
    assert g.is_complete(s)            # "12" is a complete value
    s2 = g.advance(s, ord("3"))
    assert s2 is not None              # ...but may extend


# ---- token lifting ----


def test_token_mask_over_byte_tokenizer():
    tok = ByteTokenizer()
    tg = TokenGrammar(JsonGrammar(), token_bytes_for(tok), tok.eos_id)
    st = tg.initial()
    m = tg.mask(st)
    assert m[ord('{')] and m[ord('[')] and m[ord('"')] and m[ord('1')]
    assert not m[ord('}')] and not m[ord('x')] and not m[tok.eos_id]
    st = tg.advance_token(st, ord('{'))
    m = tg.mask(st)
    assert m[ord('"')] and m[ord('}')] and not m[ord('1')]
    st = tg.advance_token(st, ord('}'))
    assert tg.mask(st)[tok.eos_id]     # complete → EOS legal
    assert tg.advance_token(st, tok.eos_id) is not None


def test_token_bytes_byte_tokenizer_is_identity():
    table = token_bytes_for(ByteTokenizer())
    assert table[0x41] == b"A"
    assert table[0x80] == bytes([0x80])     # raw continuation byte, no U+FFFD
    assert table[256] is None and table[257] is None  # BOS/EOS specials


# ---- engine ----


_TOK = ByteTokenizer()


def _engine(**kw):
    eng = Engine(EngineConfig(model="tiny", vocab_size=512, page_size=8,
                              num_pages=128, max_seq_len=256,
                              use_pallas="never", **kw))
    eng.enable_json_grammar(_TOK)
    return eng


def _gen_text(eng, seed, max_new=80, temperature=0.9):
    sp = SamplingParams(max_new_tokens=max_new, temperature=temperature,
                        seed=seed, json_mode=True, stop_token=_TOK.eos_id)
    out = eng.generate([_TOK.encode("j:", add_bos=False)], sp)[0]
    done = bool(out) and out[-1] == _TOK.eos_id
    return _TOK.decode([t for t in out if t != _TOK.eos_id]), done


@pytest.mark.parametrize("seed", [1, 2, 7, 11])
def test_json_mode_outputs_are_valid_json(seed):
    text, done = _gen_text(_engine(), seed)
    if done:
        json.loads(text)               # finished → must parse
    else:
        # Budget-truncated: the emitted prefix must still be legal.
        g = JsonGrammar()
        s = g.initial()
        for b in text.encode():
            s = g.advance(s, b)
            assert s is not None, text


def test_json_mode_greedy_also_constrained():
    text, done = _gen_text(_engine(), seed=None, temperature=0.0)
    g = JsonGrammar()
    s = g.initial()
    for b in text.encode():
        s = g.advance(s, b)
        assert s is not None, text


@pytest.mark.slow
def test_json_mode_composes_with_speculative():
    sp = SamplingParams(max_new_tokens=60, temperature=0.0, json_mode=True,
                        stop_token=_TOK.eos_id)
    prompt = _TOK.encode("q", add_bos=False)
    a = _engine(speculative="ngram").generate([prompt], sp)[0]
    b = _engine().generate([prompt], sp)[0]
    assert a == b


def test_json_mode_mixed_with_unconstrained_batch():
    eng = _engine()
    rj = eng.add_request(_TOK.encode("a", add_bos=False),
                         SamplingParams(max_new_tokens=30, temperature=0.7,
                                        seed=4, json_mode=True,
                                        stop_token=_TOK.eos_id))
    rf = eng.add_request([1, 2, 3],
                         SamplingParams(max_new_tokens=10))
    outs = {rj: [], rf: []}
    while eng.has_work():
        for ev in eng.step():
            outs[ev.request_id].append(ev.token)
    assert len(outs[rf]) == 10          # free row unaffected
    text = _TOK.decode([t for t in outs[rj] if t != _TOK.eos_id])
    g = JsonGrammar()
    s = g.initial()
    for b in text.encode():
        s = g.advance(s, b)
        assert s is not None, text


def test_json_mode_with_penalties_same_step():
    # Penalized rows ride the host-synced step alongside grammar rows.
    eng = _engine()
    rj = eng.add_request(_TOK.encode("a", add_bos=False),
                         SamplingParams(max_new_tokens=20, temperature=0.7,
                                        seed=9, json_mode=True,
                                        stop_token=_TOK.eos_id))
    rp = eng.add_request([1, 2, 3],
                         SamplingParams(max_new_tokens=12,
                                        presence_penalty=1e9))
    outs = {rj: [], rp: []}
    while eng.has_work():
        for ev in eng.step():
            outs[ev.request_id].append(ev.token)
    assert len(set(outs[rp])) == len(outs[rp])   # penalty row: all distinct
    text = _TOK.decode([t for t in outs[rj] if t != _TOK.eos_id])
    g = JsonGrammar()
    s = g.initial()
    for b in text.encode():
        s = g.advance(s, b)
        assert s is not None, text


def test_json_mode_without_grammar_table_fails_request():
    eng = Engine(EngineConfig(model="tiny", vocab_size=512, page_size=8,
                              num_pages=64, max_seq_len=128,
                              use_pallas="never"))
    with pytest.raises(ValueError, match="json_mode"):
        eng.add_request([1, 2], SamplingParams(max_new_tokens=4,
                                               json_mode=True))


@pytest.mark.slow
@pytest.mark.e2e
def test_json_mode_over_wire():
    """generate_text with json_mode through a real server subprocess —
    decoded text parses as JSON (or is a legal truncated prefix)."""
    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once

    with SpawnedEngineServer(
            "--model", "tiny", "--vocab-size", "512", "--page-size", "8",
            "--num-pages", "128", "--max-seq-len", "256",
            "--use-pallas", "never") as srv:
        r, _, _ = request_once(
            srv.addr,
            {"op": "generate_text", "text": "emit json:",
             "max_new_tokens": 60, "temperature": 0.8, "seed": 5,
             "json_mode": True}, timeout=180)
        assert "error" not in r, r
        text = r["text"]
        g = JsonGrammar()
        s = g.initial()
        for b in text.encode():
            s = g.advance(s, b)
            assert s is not None, text


@pytest.mark.slow
def test_json_row_does_not_evict_fused_rows_from_their_path():
    """Mixed traffic: a grammar row decodes host-synced while plain rows
    keep the fused path — a greedy plain row's output must be identical
    with or without a JSON request in flight."""
    alone = _engine(multi_step=2).generate(
        [[1, 2, 3]], SamplingParams(max_new_tokens=10))[0]
    eng = _engine(multi_step=2)
    rj = eng.add_request(_TOK.encode("a", add_bos=False),
                         SamplingParams(max_new_tokens=20, temperature=0.7,
                                        seed=3, json_mode=True,
                                        stop_token=_TOK.eos_id))
    rf = eng.add_request([1, 2, 3], SamplingParams(max_new_tokens=10))
    outs = {rj: [], rf: []}
    while eng.has_work():
        for ev in eng.step():
            outs[ev.request_id].append(ev.token)
    assert outs[rf] == alone
    assert eng.metrics["spec_steps"] > 0       # grammar row went host-synced
