"""Coordinated rolling update: maxSkew-bounded multi-role rollout."""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RollingUpdate
from rbg_tpu.api.policy import (
    CoordinatedPolicy, CoordinatedPolicySpec, CoordinatedRollingUpdate,
)
from rbg_tpu.coordination.rollout import rollout_partitions
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


def test_rollout_partitions_math():
    g = make_group("x", simple_role("prefill", replicas=8),
                   simple_role("decode", replicas=8))
    pol = CoordinatedRollingUpdate(roles=["prefill", "decode"],
                                   max_skew_percent=25)
    # Nothing updated: both roles open 25% (+1 slowest rule) → allowed 2.
    parts = rollout_partitions(g, pol, {"prefill": 0, "decode": 0})
    assert parts == {"prefill": 6, "decode": 6}
    # prefill raced ahead: it gets capped; decode (slowest) gets +1 headroom.
    parts = rollout_partitions(g, pol, {"prefill": 4, "decode": 0})
    assert parts["prefill"] == 8 - 2   # floor(8*(0+0.25)) = 2
    assert parts["decode"] == 8 - 2    # max(floor(2), 0+1) = 2
    # Both done: fully open.
    parts = rollout_partitions(g, pol, {"prefill": 8, "decode": 8})
    assert parts == {"prefill": 0, "decode": 0}


def test_coordinated_rollout_end_to_end():
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=2)
    with plane:
        r1 = simple_role("prefill", replicas=4)
        r2 = simple_role("decode", replicas=4)
        # Recreate path so rollout progress is observable per-instance.
        r1.rolling_update = RollingUpdate(max_unavailable=2, in_place_if_possible=False)
        r2.rolling_update = RollingUpdate(max_unavailable=2, in_place_if_possible=False)
        plane.apply(make_group("pd", r1, r2))
        pol = CoordinatedPolicy()
        pol.metadata.name = "pd-ru"
        pol.spec = CoordinatedPolicySpec(
            group_name="pd",
            rolling_update=CoordinatedRollingUpdate(
                roles=["prefill", "decode"], max_skew_percent=25),
        )
        plane.apply(pol)
        plane.wait_group_ready("pd", timeout=30)

        rev0 = plane.store.get("RoleInstanceSet", "default",
                               "pd-prefill").status.update_revision
        g = plane.store.get("RoleBasedGroup", "default", "pd")
        for role in g.spec.roles:
            role.template.containers[0].image = "engine:v2"
        plane.store.update(g)

        skew_violations = []

        def converged():
            a = plane.store.get("RoleInstanceSet", "default", "pd-prefill")
            b = plane.store.get("RoleInstanceSet", "default", "pd-decode")
            if a.status.update_revision == rev0 or b.status.update_revision == rev0:
                return False  # rollout not observed yet — old-revision counts lie
            ua, ub = a.status.updated_ready_replicas, b.status.updated_ready_replicas
            # Track observed skew (allow the +1 no-deadlock step + in-flight
            # batch of maxUnavailable).
            if abs(ua - ub) > 4 * 0.25 + 1 + 2:
                skew_violations.append((ua, ub))
            return ua == 4 and ub == 4

        plane.wait_for(converged, timeout=60, desc="coordinated rollout done")
        assert not skew_violations, f"skew exceeded bound: {skew_violations}"

        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        assert all(p.template.containers[0].image == "engine:v2" for p in pods)
