"""rbg-lint: every rule flags its known-bad fixture and passes its
known-good one; the allowlist syntax suppresses with justification only;
the CLI gates; locktrace catches a seeded lock inversion."""

import os
import subprocess
import sys

import pytest

from rbg_tpu.analysis.core import run_lint
from rbg_tpu.analysis.rules import make_rules, rule_catalog

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")


def lint_fixture(fname, rule=None):
    rules = make_rules([rule] if rule else None)
    return run_lint([os.path.join(FIXTURES, fname)], rules,
                    skip_fixture_dirs=False)


# ---- each rule: bad flags, good passes ----


@pytest.mark.parametrize("rule,bad,good,min_bad", [
    ("blocking-in-critical-section", "bad_blocking.py",
     "good_blocking.py", 6),
    ("deadline-hygiene", "bad_deadline.py", "good_deadline.py", 5),
    ("error-code-registry", "bad_errorcodes.py", "good_errorcodes.py", 5),
    ("metric-name-registry", "bad_metrics.py", "good_metrics.py", 5),
    ("thread-lifecycle", "bad_threads.py", "good_threads.py", 3),
])
def test_rule_fires_on_bad_and_passes_good(rule, bad, good, min_bad):
    bad_findings = [f for f in lint_fixture(bad, rule) if f.rule == rule]
    assert len(bad_findings) >= min_bad, (
        f"{rule} found only {[f.render() for f in bad_findings]}")
    # Every BAD-marked line is caught (the fixture is the rule's contract).
    src = open(os.path.join(FIXTURES, bad)).readlines()
    bad_lines = {i for i, line in enumerate(src, 1) if "# BAD" in line}
    if bad_lines:
        flagged = {f.line for f in bad_findings}
        assert bad_lines <= flagged, (
            f"{rule} missed BAD lines {sorted(bad_lines - flagged)}")
    good_findings = [f for f in lint_fixture(good, rule) if f.rule == rule]
    assert good_findings == [], [f.render() for f in good_findings]


def test_rule_catalog_names_match():
    assert set(rule_catalog()) == {
        "blocking-in-critical-section", "deadline-hygiene",
        "error-code-registry", "metric-name-registry", "thread-lifecycle"}


# ---- allowlist semantics ----


def test_allow_comment_requires_justification(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import time as _t\n"
                 "def f():\n"
                 "    # lint: allow[deadline-hygiene]\n"
                 "    deadline = _t.monotonic() + 3.0\n"
                 "    return deadline\n")
    findings = run_lint([str(p)], make_rules())
    rules = {f.rule for f in findings}
    # The bare allow is itself a finding AND does not suppress.
    assert "lint-allow" in rules
    assert "deadline-hygiene" in rules


def test_allow_comment_with_justification_suppresses(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import time as _t\n"
                 "def f():\n"
                 "    # lint: allow[deadline-hygiene] ingress stamp, client sent no budget\n"
                 "    deadline = _t.monotonic() + 3.0\n"
                 "    return deadline\n")
    assert run_lint([str(p)], make_rules()) == []


def test_allow_scopes_to_named_rule_only(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import time as _t\n"
                 "def f():\n"
                 "    deadline = _t.monotonic() + 3.0  # lint: allow[thread-lifecycle] wrong rule named\n"
                 "    return deadline\n")
    assert {f.rule for f in run_lint([str(p)], make_rules())} == {
        "deadline-hygiene"}


# ---- the repo gate + CLI ----


def test_repo_tree_is_clean():
    """`rbg-tpu lint rbg_tpu/` exits 0 on the final tree (the acceptance
    gate) — run in-process for speed."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run_lint([os.path.join(repo, "rbg_tpu")], make_rules())
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_cli_exit_codes(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root}
    bad = os.path.join(FIXTURES, "bad_deadline.py")
    r = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "lint",
         "--include-fixtures", bad],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 1
    assert "deadline-hygiene" in r.stdout
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "lint", str(clean)],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "lint", "--rule",
         "no-such-rule", str(clean)],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 2


def test_fixture_dir_skipped_by_default():
    """The gate must not count the known-bad corpus."""
    findings = run_lint([FIXTURES], make_rules())
    assert findings == [], [f.render() for f in findings]


def test_missing_path_is_a_finding(tmp_path):
    """A typo'd path must not read as a clean gate."""
    findings = run_lint([str(tmp_path / "no_such_dir")], make_rules())
    assert [f.rule for f in findings] == ["io-error"]


def test_allow_syntax_in_docstring_is_inert(tmp_path):
    """Documenting the allow syntax inside a string must neither fail the
    gate (bare form) nor suppress findings (justified form)."""
    p = tmp_path / "mod.py"
    p.write_text('import time as _t\n'
                 'DOC = """use # lint: allow[deadline-hygiene] here"""\n'
                 'DOC2 = """or # lint: allow[deadline-hygiene] reasons why\n'
                 'deadline = 1"""\n'
                 'def f():\n'
                 '    deadline = _t.monotonic() + 3.0\n'
                 '    return deadline\n')
    rules = [f.rule for f in run_lint([str(p)], make_rules())]
    assert rules == ["deadline-hygiene"]  # no lint-allow, no suppression


def test_blocking_prefix_needs_module_import(tmp_path):
    """A local variable named `requests` is not HTTP I/O."""
    p = tmp_path / "mod.py"
    p.write_text("import threading\n"
                 "_lock = threading.Lock()\n"
                 "def f(requests, req):\n"
                 "    with _lock:\n"
                 "        requests.append(req)\n")
    assert run_lint([str(p)], make_rules()) == []


def test_nested_lock_withs_report_once(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import threading, time\n"
                 "a_lock = threading.Lock()\n"
                 "b_lock = threading.Lock()\n"
                 "def f():\n"
                 "    with a_lock:\n"
                 "        with b_lock:\n"
                 "            time.sleep(1)\n")
    findings = [f for f in run_lint([str(p)], make_rules())
                if f.rule == "blocking-in-critical-section"]
    assert len(findings) == 1


def test_metric_constant_from_foreign_module_not_borrowed(tmp_path):
    """Only constants imported from the catalog module resolve — a foreign
    module's same-named constant must not borrow the catalog's value."""
    p = tmp_path / "mod.py"
    p.write_text("from mypkg import consts\n"
                 "from rbg_tpu.obs.metrics import REGISTRY\n"
                 "def f():\n"
                 "    REGISTRY.inc(consts.SERVING_SHED_TOTAL)\n")
    assert run_lint([str(p)], make_rules()) == []  # unresolvable: unchecked
    p2 = tmp_path / "mod2.py"
    p2.write_text("from rbg_tpu.obs import names\n"
                  "from rbg_tpu.obs.metrics import REGISTRY\n"
                  "def f(dt):\n"
                  "    REGISTRY.observe(names.SERVING_SHED_TOTAL, dt)\n")
    findings = run_lint([str(p2)], make_rules())
    assert any("one name must have one kind" in f.message for f in findings)


# ---- metric catalog self-audit ----


def test_catalog_duplicate_detection(tmp_path, monkeypatch):
    from rbg_tpu.analysis.rules.metricnames import MetricNameRegistry
    rule = MetricNameRegistry()
    dup = tmp_path / "names.py"
    dup.write_text('A_TOTAL = "rbg_x_total"\nB_TOTAL = "rbg_x_total"\n'
                   'BAD_COUNter = "rbg_y"\n')
    rule._names_module = str(dup)
    rule.counters = frozenset({"rbg_x_total", "rbg_y"})
    msgs = [f.message for f in rule.finalize()]
    assert any("duplicate metric registration" in m for m in msgs)
    assert any("must end in _total" in m for m in msgs)


def test_registry_strict_mode_rejects_uncataloged():
    from rbg_tpu.obs.metrics import Registry
    r = Registry(strict=True)
    r.inc("rbg_serving_shed_total")          # cataloged counter: fine
    r.inc("unprefixed_counter")              # non-rbg namespace: unchecked
    with pytest.raises(ValueError):
        r.inc("rbg_typo_total")              # not cataloged
    with pytest.raises(ValueError):
        r.inc("rbg_serving_queue_depth")     # histogram used as counter


# ---- locktrace: the runtime half ----


@pytest.fixture()
def traced(monkeypatch):
    monkeypatch.setenv("RBG_LOCKTRACE", "1")
    from rbg_tpu.utils import locktrace
    locktrace.reset()
    yield locktrace
    locktrace.reset()


def test_locktrace_detects_seeded_inversion(traced):
    a = traced.named_lock("lockA")
    b = traced.named_lock("lockB")
    with a:
        with b:  # establishes A -> B
            pass
    with pytest.raises(traced.LockOrderError) as ei:
        with b:
            with a:  # B -> A closes the cycle
                pass
    assert "lockA" in str(ei.value) and "lockB" in str(ei.value)
    assert traced.inversions()


def test_locktrace_transitive_cycle(traced):
    a, b, c = (traced.named_lock(n) for n in ("tA", "tB", "tC"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(traced.LockOrderError):
        with c:
            with a:  # C -> A via A -> B -> C
                pass


def test_locktrace_consistent_order_is_silent(traced):
    a = traced.named_lock("okA")
    b = traced.named_lock("okB")
    for _ in range(3):
        with a:
            with b:
                pass
    assert traced.inversions() == []
    assert traced.snapshot().get("okA") == ["okB"]


def test_locktrace_rlock_reentrancy_no_self_edge(traced):
    r = traced.named_rlock("reent")
    with r:
        with r:
            pass
    assert "reent" not in traced.snapshot()


def test_locktrace_warn_mode_counts_instead_of_raising(traced, monkeypatch):
    monkeypatch.setenv("RBG_LOCKTRACE", "warn")
    from rbg_tpu.obs.metrics import REGISTRY
    from rbg_tpu.obs.names import LOCKTRACE_INVERSIONS_TOTAL
    before = REGISTRY.counter(LOCKTRACE_INVERSIONS_TOTAL)
    a = traced.named_lock("wA")
    b = traced.named_lock("wB")
    with a:
        with b:
            pass
    with b:
        with a:  # inversion: logged + counted, not raised
            pass
    assert REGISTRY.counter(LOCKTRACE_INVERSIONS_TOTAL) == before + 1
    assert len(traced.inversions()) == 1


def test_locktrace_disabled_returns_stdlib_locks(monkeypatch):
    monkeypatch.delenv("RBG_LOCKTRACE", raising=False)
    from rbg_tpu.utils import locktrace
    lock = locktrace.named_lock("plain")
    assert not isinstance(lock, locktrace.TracedLock)
    with lock:
        pass


def test_plane_lifecycle_under_locktrace(traced):
    """A full fake-backend plane converges with tracing armed and records
    an acyclic order graph (the integration the stress --locktrace flag
    relies on)."""
    from rbg_tpu.runtime.plane import ControlPlane
    from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role

    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=2)
    with plane:
        plane.apply(make_group("svc", simple_role("worker", replicas=2)))
        plane.wait_group_ready("svc", timeout=30)
    assert traced.inversions() == []
