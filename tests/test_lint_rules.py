"""rbg-lint: every rule flags its known-bad fixture and passes its
known-good one; the allowlist syntax suppresses with justification only;
the CLI gates; locktrace catches a seeded lock inversion."""

import os
import subprocess
import sys

import pytest

from rbg_tpu.analysis.core import run_lint
from rbg_tpu.analysis.rules import make_rules, rule_catalog

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures", "lint")


def lint_fixture(fname, rule=None):
    rules = make_rules([rule] if rule else None)
    return run_lint([os.path.join(FIXTURES, fname)], rules,
                    skip_fixture_dirs=False)


# ---- each rule: bad flags, good passes ----


@pytest.mark.parametrize("rule,bad,good,min_bad", [
    ("blocking-in-critical-section", "bad_blocking.py",
     "good_blocking.py", 6),
    ("deadline-hygiene", "bad_deadline.py", "good_deadline.py", 5),
    ("error-code-registry", "bad_errorcodes.py", "good_errorcodes.py", 5),
    ("guarded-by", "bad_guardedby.py", "good_guardedby.py", 5),
    ("metric-name-registry", "bad_metrics.py", "good_metrics.py", 5),
    ("span-name-registry", "bad_spannames.py", "good_spannames.py", 6),
    ("thread-lifecycle", "bad_threads.py", "good_threads.py", 3),
    ("jit-hygiene", "bad_jit.py", "good_jit.py", 10),
    ("bucket-discipline", "bad_bucket.py", "good_bucket.py", 4),
    ("donation-safety", "bad_donation.py", "good_donation.py", 4),
    ("op-registry", "bad_wire_registry.py", "good_wire_registry.py", 2),
    ("field-discipline", "bad_wire_fields.py", "good_wire_fields.py", 6),
    ("error-code-flow", "bad_wire_codes.py", "good_wire_codes.py", 3),
])
def test_rule_fires_on_bad_and_passes_good(rule, bad, good, min_bad):
    bad_findings = [f for f in lint_fixture(bad, rule) if f.rule == rule]
    assert len(bad_findings) >= min_bad, (
        f"{rule} found only {[f.render() for f in bad_findings]}")
    # Every BAD-marked line is caught (the fixture is the rule's contract).
    src = open(os.path.join(FIXTURES, bad)).readlines()
    bad_lines = {i for i, line in enumerate(src, 1) if "# BAD" in line}
    if bad_lines:
        flagged = {f.line for f in bad_findings}
        assert bad_lines <= flagged, (
            f"{rule} missed BAD lines {sorted(bad_lines - flagged)}")
    good_findings = [f for f in lint_fixture(good, rule) if f.rule == rule]
    assert good_findings == [], [f.render() for f in good_findings]


def test_span_catalog_audit_flags_unregistered_and_duplicates(tmp_path):
    """The finalize pass audits the catalog itself: duplicate SPAN_*
    values, constants missing from the SPANS frozenset, contract breaks."""
    from rbg_tpu.analysis.rules.spannames import SpanNameRegistry
    cat = tmp_path / "fake_names.py"
    cat.write_text('SPAN_A = "a.b"\n'
                   'SPAN_DUP = "a.b"\n'
                   'SPAN_BAD = "NotDotted"\n')
    rule = SpanNameRegistry()
    rule._names_module = str(cat)
    msgs = " | ".join(f.render() for f in rule.finalize())
    assert "duplicate span registration: SPAN_DUP and SPAN_A" in msgs
    assert "not in the SPANS frozenset" in msgs
    assert "naming contract" in msgs


def test_rule_catalog_names_match():
    assert set(rule_catalog()) == {
        "blocking-in-critical-section", "bucket-discipline",
        "deadline-hygiene", "donation-safety", "error-code-flow",
        "error-code-registry", "field-discipline", "guarded-by",
        "jit-hygiene", "metric-name-registry", "op-registry",
        "span-name-registry", "thread-lifecycle"}


# ---- allowlist semantics ----


def test_allow_comment_requires_justification(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import time as _t\n"
                 "def f():\n"
                 "    # lint: allow[deadline-hygiene]\n"
                 "    deadline = _t.monotonic() + 3.0\n"
                 "    return deadline\n")
    findings = run_lint([str(p)], make_rules())
    rules = {f.rule for f in findings}
    # The bare allow is itself a finding AND does not suppress.
    assert "lint-allow" in rules
    assert "deadline-hygiene" in rules


def test_allow_comment_with_justification_suppresses(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import time as _t\n"
                 "def f():\n"
                 "    # lint: allow[deadline-hygiene] ingress stamp, client sent no budget\n"
                 "    deadline = _t.monotonic() + 3.0\n"
                 "    return deadline\n")
    assert run_lint([str(p)], make_rules()) == []


def test_allow_scopes_to_named_rule_only(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import time as _t\n"
                 "def f():\n"
                 "    deadline = _t.monotonic() + 3.0  # lint: allow[thread-lifecycle] wrong rule named\n"
                 "    return deadline\n")
    # The wrong-rule allow does not suppress the deadline finding, and —
    # because thread-lifecycle never fires on that line — it is itself a
    # stale suppression.
    assert {f.rule for f in run_lint([str(p)], make_rules())} == {
        "deadline-hygiene", "stale-allow"}


# ---- the repo gate + CLI ----


def test_repo_tree_is_clean():
    """`rbg-tpu lint rbg_tpu/` exits 0 on the final tree (the acceptance
    gate) — run in-process for speed."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run_lint([os.path.join(repo, "rbg_tpu")], make_rules())
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_cli_exit_codes(tmp_path):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root}
    bad = os.path.join(FIXTURES, "bad_deadline.py")
    r = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "lint",
         "--include-fixtures", bad],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 1
    assert "deadline-hygiene" in r.stdout
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    r = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "lint", str(clean)],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
    r = subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "lint", "--rule",
         "no-such-rule", str(clean)],
        capture_output=True, text=True, env=env, timeout=120)
    assert r.returncode == 2


def test_fixture_dir_skipped_by_default():
    """The gate must not count the known-bad corpus."""
    findings = run_lint([FIXTURES], make_rules())
    assert findings == [], [f.render() for f in findings]


def test_missing_path_is_a_finding(tmp_path):
    """A typo'd path must not read as a clean gate."""
    findings = run_lint([str(tmp_path / "no_such_dir")], make_rules())
    assert [f.rule for f in findings] == ["io-error"]


def test_allow_syntax_in_docstring_is_inert(tmp_path):
    """Documenting the allow syntax inside a string must neither fail the
    gate (bare form) nor suppress findings (justified form)."""
    p = tmp_path / "mod.py"
    p.write_text('import time as _t\n'
                 'DOC = """use # lint: allow[deadline-hygiene] here"""\n'
                 'DOC2 = """or # lint: allow[deadline-hygiene] reasons why\n'
                 'deadline = 1"""\n'
                 'def f():\n'
                 '    deadline = _t.monotonic() + 3.0\n'
                 '    return deadline\n')
    rules = [f.rule for f in run_lint([str(p)], make_rules())]
    assert rules == ["deadline-hygiene"]  # no lint-allow, no suppression


def test_blocking_prefix_needs_module_import(tmp_path):
    """A local variable named `requests` is not HTTP I/O."""
    p = tmp_path / "mod.py"
    p.write_text("import threading\n"
                 "_lock = threading.Lock()\n"
                 "def f(requests, req):\n"
                 "    with _lock:\n"
                 "        requests.append(req)\n")
    assert run_lint([str(p)], make_rules()) == []


def test_nested_lock_withs_report_once(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import threading, time\n"
                 "a_lock = threading.Lock()\n"
                 "b_lock = threading.Lock()\n"
                 "def f():\n"
                 "    with a_lock:\n"
                 "        with b_lock:\n"
                 "            time.sleep(1)\n")
    findings = [f for f in run_lint([str(p)], make_rules())
                if f.rule == "blocking-in-critical-section"]
    assert len(findings) == 1


def test_metric_constant_from_foreign_module_not_borrowed(tmp_path):
    """Only constants imported from the catalog module resolve — a foreign
    module's same-named constant must not borrow the catalog's value."""
    p = tmp_path / "mod.py"
    p.write_text("from mypkg import consts\n"
                 "from rbg_tpu.obs.metrics import REGISTRY\n"
                 "def f():\n"
                 "    REGISTRY.inc(consts.SERVING_SHED_TOTAL)\n")
    assert run_lint([str(p)], make_rules()) == []  # unresolvable: unchecked
    p2 = tmp_path / "mod2.py"
    p2.write_text("from rbg_tpu.obs import names\n"
                  "from rbg_tpu.obs.metrics import REGISTRY\n"
                  "def f(dt):\n"
                  "    REGISTRY.observe(names.SERVING_SHED_TOTAL, dt)\n")
    findings = run_lint([str(p2)], make_rules())
    assert any("one name must have one kind" in f.message for f in findings)


# ---- guarded-by: the interprocedural corpus ----


def test_guardedby_direct_access_flagged():
    findings = [f for f in lint_fixture("bad_guardedby.py", "guarded-by")
                if f.rule == "guarded-by"]
    msgs = "\n".join(f.message for f in findings)
    assert "`_items` is guarded_by[fixture.cache]" in msgs
    assert "public entry point" in msgs


def test_guardedby_helper_without_lock_names_the_unlocked_caller():
    findings = [f for f in lint_fixture("bad_guardedby.py", "guarded-by")
                if f.rule == "guarded-by"]
    helper = [f for f in findings if "called from `public_bump`" in f.message]
    assert helper, [f.render() for f in findings]


def test_guardedby_helper_under_lock_is_clean():
    """good_guardedby's _insert/_bump chain (two levels deep) resolves via
    the call-graph fixpoint — no findings on the good corpus."""
    assert lint_fixture("good_guardedby.py", "guarded-by") == []


def test_guardedby_unverifiable_annotation_flagged():
    findings = [f for f in lint_fixture("bad_guardedby.py", "guarded-by")
                if "missing.lock" in f.message]
    assert findings and "cannot verify" in findings[0].message


def test_guardedby_module_global_checked():
    findings = [f for f in lint_fixture("bad_guardedby.py", "guarded-by")
                if "_registry" in f.message]
    assert findings, "module-global guarded access must be checked"


def test_guardedby_mixed_callers_flags_the_unlocked_path(tmp_path):
    """A helper called both under the lock and without it is NOT lock-held:
    the one unlocked caller poisons it (that is the race)."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from rbg_tpu.utils.locktrace import named_lock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('t.mixed')\n"
        "        self._x = 0  # guarded_by[t.mixed]\n"
        "    def locked_path(self):\n"
        "        with self._lock:\n"
        "            self._help()\n"
        "    def unlocked_path(self):\n"
        "        self._help()\n"
        "    def _help(self):\n"
        "        self._x += 1\n")
    findings = [f for f in run_lint([str(p)], make_rules(["guarded-by"]))]
    assert len(findings) == 1
    assert "unlocked_path" in findings[0].message


def test_guardedby_self_acquiring_helper_is_clean(tmp_path):
    """A helper that takes the lock itself is fine from any caller."""
    p = tmp_path / "mod.py"
    p.write_text(
        "from rbg_tpu.utils.locktrace import named_lock\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = named_lock('t.selfacq')\n"
        "        self._x = 0  # guarded_by[t.selfacq]\n"
        "    def anyone(self):\n"
        "        return self._grab()\n"
        "    def _grab(self):\n"
        "        with self._lock:\n"
        "            return self._x\n")
    assert run_lint([str(p)], make_rules(["guarded-by"])) == []


# ---- jit-hygiene / bucket-discipline / donation-safety edges ----


def test_jit_hygiene_silent_without_hot_path_roots(tmp_path):
    """No # hot_path annotation in the module -> the rule has no roots
    and must stay silent, whatever the code does."""
    p = tmp_path / "mod.py"
    p.write_text("import jax\n"
                 "import jax.numpy as jnp\n"
                 "def f():\n"
                 "    x = jnp.zeros(4)\n"
                 "    return float(x[0]), jax.device_get(x)\n")
    assert run_lint([str(p)], make_rules(["jit-hygiene"])) == []


def test_jit_hygiene_allow_with_justification_suppresses(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("import jax\n"
                 "import jax.numpy as jnp\n"
                 "# hot_path\n"
                 "def serve():\n"
                 "    x = jnp.zeros(4)\n"
                 "    # lint: allow[jit-hygiene] the one intrinsic emission fetch\n"
                 "    return jax.device_get(x)\n")
    assert run_lint([str(p)], make_rules(["jit-hygiene"])) == []


def test_bucket_catalog_audit_flags_uncataloged_annotation(tmp_path):
    """# bucket_fn in repo code without a BUCKET_FNS catalog entry is a
    finding — the sentry and rules gate on the catalog, not the comment."""
    d = tmp_path / "rbg_tpu"
    d.mkdir()
    p = d / "mod.py"
    p.write_text("# bucket_fn\n"
                 "def _my_rounding(n):\n"
                 "    return n\n")
    findings = run_lint([str(p)], make_rules(["bucket-discipline"]))
    assert any("not cataloged" in f.message for f in findings), (
        [f.render() for f in findings])


def test_bucket_catalog_audit_flags_stripped_annotation(tmp_path):
    """A cataloged helper whose definition lost its # bucket_fn comment is
    the reverse drift — also a finding."""
    d = tmp_path / "rbg_tpu"
    d.mkdir()
    p = d / "mod.py"
    p.write_text("def _pow2_bucket(n):\n"
                 "    return n\n")
    findings = run_lint([str(p)], make_rules(["bucket-discipline"]))
    assert any("lost the # bucket_fn annotation" in f.message
               for f in findings), [f.render() for f in findings]


def test_bucket_fixture_helper_launders_outside_repo_paths():
    """Outside rbg_tpu/ the catalog audit is off, but a locally-annotated
    helper still launders (good_bucket.py relies on this)."""
    findings = run_lint([os.path.join(FIXTURES, "good_bucket.py")],
                        make_rules(["bucket-discipline"]),
                        skip_fixture_dirs=False)
    assert findings == [], [f.render() for f in findings]


def test_donation_conditional_idiom_unions_positions():
    """bad_donation's _get_cond assigns donate = (2,) if q else (2, 3):
    the rule must treat BOTH positions as donated (sound
    over-approximation) and flag each reuse."""
    findings = [f for f in lint_fixture("bad_donation.py",
                                        "donation-safety")]
    cond = [f for f in findings if f.line and "b * 2" in open(
        os.path.join(FIXTURES, "bad_donation.py")).readlines()[f.line - 1]]
    assert len(cond) == 2, [f.render() for f in findings]


# ---- stale-allow ----


def test_stale_allow_fixture_corpus():
    findings = run_lint([os.path.join(FIXTURES, "bad_staleallow.py")],
                        make_rules(), skip_fixture_dirs=False)
    stale = [f for f in findings if f.rule == "stale-allow"]
    assert len(stale) == 2, [f.render() for f in findings]
    assert all(f.severity == "warning" for f in stale)
    src = open(os.path.join(FIXTURES, "bad_staleallow.py")).readlines()
    bad_lines = {i for i, line in enumerate(src, 1) if "# BAD" in line}
    assert bad_lines == {f.line for f in stale}
    good = run_lint([os.path.join(FIXTURES, "good_staleallow.py")],
                    make_rules(), skip_fixture_dirs=False)
    assert good == [], [f.render() for f in good]


def test_stale_allow_ignores_rules_not_running(tmp_path):
    """`--rule X` must not report allows for rule Y as stale — Y never got
    the chance to fire."""
    p = tmp_path / "mod.py"
    p.write_text("def f():\n"
                 "    x = 1  # lint: allow[thread-lifecycle] justified elsewhere\n"
                 "    return x\n")
    assert run_lint([str(p)], make_rules(["deadline-hygiene"])) == []
    stale = run_lint([str(p)], make_rules())
    assert [f.rule for f in stale] == ["stale-allow"]


# ---- CLI: json format + --changed ----


def _run_cli(args, cwd=None):
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root}
    return subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "lint", *args],
        capture_output=True, text=True, env=env, timeout=120, cwd=cwd)


def test_cli_json_format_fields():
    import json
    bad = os.path.join(FIXTURES, "bad_deadline.py")
    r = _run_cli(["--include-fixtures", "--format", "json", bad])
    assert r.returncode == 1
    payload = json.loads(r.stdout)
    assert payload, "expected findings"
    for item in payload:
        assert set(item) == {"file", "line", "col", "rule", "message",
                             "severity", "fingerprint"}
        assert len(item["fingerprint"]) == 40  # sha1 hex
    assert any(i["rule"] == "deadline-hygiene" for i in payload)
    assert all(i["severity"] in ("error", "warning") for i in payload)


def test_cli_json_fingerprint_stable_across_line_shift(tmp_path):
    """The fingerprint keys on file:rule:normalized-line-TEXT, so editing
    elsewhere in the file must not churn it (the finding-tracker
    contract); the line number itself may move."""
    import json
    body = ("import time as _t\n"
            "def f():\n"
            "    deadline = _t.monotonic() + 3.0\n"
            "    return deadline\n")
    p = tmp_path / "mod.py"
    p.write_text(body)
    r1 = _run_cli(["--format", "json", str(p)])
    p.write_text("# a new leading comment shifts every line\n" + body)
    r2 = _run_cli(["--format", "json", str(p)])
    f1, = json.loads(r1.stdout)
    f2, = json.loads(r2.stdout)
    assert f1["line"] != f2["line"]
    assert f1["fingerprint"] == f2["fingerprint"]


def test_cli_changed_mode(tmp_path):
    import json
    repo = tmp_path / "proj"
    pkg = repo / "pkg"
    pkg.mkdir(parents=True)
    env = {**os.environ, "GIT_AUTHOR_NAME": "t", "GIT_AUTHOR_EMAIL": "t@x",
           "GIT_COMMITTER_NAME": "t", "GIT_COMMITTER_EMAIL": "t@x"}

    def git(*argv):
        subprocess.run(["git", *argv], cwd=repo, check=True, env=env,
                       capture_output=True, timeout=60)

    (pkg / "clean.py").write_text("x = 1\n")
    git("init", "-q")
    git("add", "-A")
    git("commit", "-qm", "seed")
    # Untouched tree: --changed lints nothing and exits 0.
    r = _run_cli(["--changed", "--format", "json", "pkg"], cwd=str(repo))
    assert r.returncode == 0 and json.loads(r.stdout) == []
    # Touch one file with a finding; only it is linted.
    (pkg / "clean.py").write_text(
        "import time as _t\n"
        "def f():\n"
        "    deadline = _t.monotonic() + 3.0\n"
        "    return deadline\n")
    (pkg / "untouched.py").write_text("ignored = True\n")  # untracked: linted
    r = _run_cli(["--changed", "--format", "json", "pkg"], cwd=str(repo))
    assert r.returncode == 1, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert {os.path.basename(i["file"]) for i in payload} == {"clean.py"}
    # A path that excludes the changed file sees nothing.
    (repo / "other").mkdir()
    r = _run_cli(["--changed", "other"], cwd=str(repo))
    assert r.returncode == 0


# ---- one parse pass per file ----


def test_gate_parses_each_file_exactly_once(monkeypatch):
    """The repo gate must parse every module ONCE and share the tree across
    all rules (including the metric catalog consulted at finalize time)."""
    import ast as ast_mod
    from collections import Counter
    counts = Counter()
    real_parse = ast_mod.parse

    def counting_parse(source, *a, **kw):
        fn = kw.get("filename") or (a[0] if a else "<unknown>")
        counts[fn] += 1
        return real_parse(source, *a, **kw)

    monkeypatch.setattr(ast_mod, "parse", counting_parse)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    findings = run_lint([os.path.join(repo, "rbg_tpu")], make_rules())
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)
    multi = {f: c for f, c in counts.items() if c > 1}
    assert not multi, f"files parsed more than once: {multi}"
    assert len(counts) > 100  # the gate actually walked the tree


# ---- metric catalog self-audit ----


def test_catalog_duplicate_detection(tmp_path, monkeypatch):
    from rbg_tpu.analysis.rules.metricnames import MetricNameRegistry
    rule = MetricNameRegistry()
    dup = tmp_path / "names.py"
    dup.write_text('A_TOTAL = "rbg_x_total"\nB_TOTAL = "rbg_x_total"\n'
                   'BAD_COUNter = "rbg_y"\n')
    rule._names_module = str(dup)
    rule.counters = frozenset({"rbg_x_total", "rbg_y"})
    msgs = [f.message for f in rule.finalize()]
    assert any("duplicate metric registration" in m for m in msgs)
    assert any("must end in _total" in m for m in msgs)


def test_registry_strict_mode_rejects_uncataloged():
    from rbg_tpu.obs.metrics import Registry
    r = Registry(strict=True)
    r.inc("rbg_serving_shed_total")          # cataloged counter: fine
    r.inc("unprefixed_counter")              # non-rbg namespace: unchecked
    with pytest.raises(ValueError):
        r.inc("rbg_typo_total")              # not cataloged
    with pytest.raises(ValueError):
        r.inc("rbg_serving_queue_depth")     # histogram used as counter


# ---- locktrace: the runtime half ----


@pytest.fixture()
def traced(monkeypatch):
    monkeypatch.setenv("RBG_LOCKTRACE", "1")
    from rbg_tpu.utils import locktrace
    locktrace.reset()
    yield locktrace
    locktrace.reset()


def test_locktrace_detects_seeded_inversion(traced):
    a = traced.named_lock("lockA")
    b = traced.named_lock("lockB")
    with a:
        with b:  # establishes A -> B
            pass
    with pytest.raises(traced.LockOrderError) as ei:
        with b:
            with a:  # B -> A closes the cycle
                pass
    assert "lockA" in str(ei.value) and "lockB" in str(ei.value)
    assert traced.inversions()


def test_locktrace_transitive_cycle(traced):
    a, b, c = (traced.named_lock(n) for n in ("tA", "tB", "tC"))
    with a:
        with b:
            pass
    with b:
        with c:
            pass
    with pytest.raises(traced.LockOrderError):
        with c:
            with a:  # C -> A via A -> B -> C
                pass


def test_locktrace_consistent_order_is_silent(traced):
    a = traced.named_lock("okA")
    b = traced.named_lock("okB")
    for _ in range(3):
        with a:
            with b:
                pass
    assert traced.inversions() == []
    assert traced.snapshot().get("okA") == ["okB"]


def test_locktrace_rlock_reentrancy_no_self_edge(traced):
    r = traced.named_rlock("reent")
    with r:
        with r:
            pass
    assert "reent" not in traced.snapshot()


def test_locktrace_warn_mode_counts_instead_of_raising(traced, monkeypatch):
    monkeypatch.setenv("RBG_LOCKTRACE", "warn")
    from rbg_tpu.obs.metrics import REGISTRY
    from rbg_tpu.obs.names import LOCKTRACE_INVERSIONS_TOTAL
    before = REGISTRY.counter(LOCKTRACE_INVERSIONS_TOTAL)
    a = traced.named_lock("wA")
    b = traced.named_lock("wB")
    with a:
        with b:
            pass
    with b:
        with a:  # inversion: logged + counted, not raised
            pass
    assert REGISTRY.counter(LOCKTRACE_INVERSIONS_TOTAL) == before + 1
    assert len(traced.inversions()) == 1


def test_locktrace_disabled_returns_stdlib_locks(monkeypatch):
    monkeypatch.delenv("RBG_LOCKTRACE", raising=False)
    monkeypatch.delenv("RBG_RACETRACE", raising=False)
    from rbg_tpu.utils import locktrace
    lock = locktrace.named_lock("plain")
    assert not isinstance(lock, locktrace.TracedLock)
    with lock:
        pass


@pytest.mark.parametrize("value", ["0", "false", "off"])
def test_locktrace_explicit_off_values_construct_stdlib_locks(
        monkeypatch, value):
    """RBG_LOCKTRACE=0 (and friends) is the zero-overhead path: plain
    stdlib lock / rlock / condition objects, no wrapper anywhere."""
    import threading
    monkeypatch.setenv("RBG_LOCKTRACE", value)
    monkeypatch.delenv("RBG_RACETRACE", raising=False)
    from rbg_tpu.utils import locktrace
    assert type(locktrace.named_lock("z")) is type(threading.Lock())
    assert type(locktrace.named_rlock("z")) is type(threading.RLock())
    cond = locktrace.named_condition("z")
    assert isinstance(cond, threading.Condition)
    assert type(cond._lock) is type(threading.RLock())  # stdlib default
    assert locktrace.held_names() == []


def test_locktrace_reentrant_deep_nesting_keeps_order_clean(traced):
    """Re-entrant re-acquires at any depth add no edges and do not corrupt
    the held stack: the orders proven around them stay consistent."""
    r = traced.named_rlock("deepR")
    a = traced.named_lock("deepA")
    with r:
        with r:
            with r:
                with a:
                    pass
    # Same outer order again, no reentrancy: must still be clean.
    with r:
        with a:
            pass
    assert traced.inversions() == []
    assert traced.snapshot().get("deepR") == ["deepA"]
    assert traced.held_names() == []


def test_locktrace_warn_counter_accuracy_under_concurrent_inversions(
        traced, monkeypatch):
    """N threads racing the SAME B->A inversion: the first attempt records
    it, later attempts see an established (bad) edge and stay silent — the
    counter moves by exactly 1 and matches inversions()."""
    import threading
    monkeypatch.setenv("RBG_LOCKTRACE", "warn")
    from rbg_tpu.obs.metrics import REGISTRY
    from rbg_tpu.obs.names import LOCKTRACE_INVERSIONS_TOTAL
    before = REGISTRY.counter(LOCKTRACE_INVERSIONS_TOTAL)
    a = traced.named_lock("cwA")
    b = traced.named_lock("cwB")
    with a:
        with b:
            pass
    barrier = threading.Barrier(4)

    def invert():
        barrier.wait(timeout=10)
        with b:
            with a:
                pass

    threads = [threading.Thread(target=invert, daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=10)
    assert not any(t.is_alive() for t in threads)
    assert len(traced.inversions()) == 1
    assert REGISTRY.counter(LOCKTRACE_INVERSIONS_TOTAL) == before + 1


def test_locktrace_named_condition_participates(traced):
    """named_condition's mutex is traced: held_names sees it, and an order
    inversion through a condition still raises."""
    cond = traced.named_condition("condX")
    a = traced.named_lock("condA")
    with cond:
        assert "condX" in traced.held_names()
        with a:  # establishes condX -> condA
            pass
    assert traced.held_names() == []
    with pytest.raises(traced.LockOrderError):
        with a:
            with cond:
                pass


def test_locktrace_held_names_tracks_stack(traced):
    a = traced.named_lock("hnA")
    b = traced.named_lock("hnB")
    assert traced.held_names() == []
    with a:
        assert traced.held_names() == ["hnA"]
        with b:
            assert traced.held_names() == ["hnA", "hnB"]
        assert traced.held_names() == ["hnA"]
    assert traced.held_names() == []


def test_plane_lifecycle_under_locktrace(traced):
    """A full fake-backend plane converges with tracing armed and records
    an acyclic order graph (the integration the stress --locktrace flag
    relies on)."""
    from rbg_tpu.runtime.plane import ControlPlane
    from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role

    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=2)
    with plane:
        plane.apply(make_group("svc", simple_role("worker", replicas=2)))
        plane.wait_group_ready("svc", timeout=30)
    assert traced.inversions() == []


# ---- wire-contract rules: drift regressions, allow sweep, baseline ----


def test_wire_drift_regressions_stay_fixed():
    """The two genuine drifts the wire rules surfaced (a prefill stub
    still speaking the pre-shape/dtype bundle header with ``n_pages``; a
    scripted backend replying an undeclared ``addr`` field) were fixed
    in-tree — the wire rules over those test files must stay clean."""
    wire_rules = make_rules(["op-registry", "field-discipline",
                             "error-code-flow"])
    here = os.path.dirname(os.path.abspath(__file__))
    for fn in ("test_slo.py", "test_router_resilience.py"):
        findings = run_lint([os.path.join(here, fn)], wire_rules)
        assert findings == [], (
            fn + ":\n" + "\n".join(f.render() for f in findings))


def test_justified_allows_still_fire():
    """Every in-tree `# lint: allow[rule] why` must still be load-bearing:
    the full rule set over the files carrying them yields NO findings —
    the allow suppresses a live finding (else stale-allow fires) and the
    justification is present (else lint-allow fires)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    carriers = [
        "rbg_tpu/engine/pd.py",             # jit-hygiene: KV export copy
        "rbg_tpu/engine/engine.py",         # jit-hygiene: emission fetch
        "rbg_tpu/engine/server.py",         # deadline-hygiene: ingress stamp
        "rbg_tpu/utils/wirecheck.py",       # field-discipline: reply envelope
        "tests/test_trace.py",              # span-name-registry: negative test
    ]
    for rel in carriers:
        path = os.path.join(repo, rel)
        src = open(path).read()
        assert "# lint: allow[" in src, f"{rel}: allow comment vanished"
        findings = run_lint([path], make_rules())
        assert findings == [], (
            rel + ":\n" + "\n".join(f.render() for f in findings))


def _lint_cli(args, env):
    return subprocess.run(
        [sys.executable, "-m", "rbg_tpu.cli.main", "lint", *args],
        capture_output=True, text=True, env=env, timeout=120)


def test_cli_baseline_suppresses_and_fails_new(tmp_path):
    """--baseline blesses exactly the fingerprinted findings: a blessed
    run exits 0, while a NEW finding (not in the baseline) still fails."""
    import json as _json
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root}
    bad = os.path.join(FIXTURES, "bad_metrics.py")
    r = _lint_cli(["--include-fixtures", "--format", "json", bad], env)
    assert r.returncode == 1
    baseline = tmp_path / "baseline.json"
    baseline.write_text(r.stdout)
    # Everything blessed: clean exit.
    r = _lint_cli(["--include-fixtures", "--baseline", str(baseline), bad],
                  env)
    assert r.returncode == 0, r.stdout + r.stderr
    # A finding the baseline does not bless still fails.
    other = os.path.join(FIXTURES, "bad_deadline.py")
    r = _lint_cli(["--include-fixtures", "--baseline", str(baseline),
                   bad, other], env)
    assert r.returncode == 1
    assert "deadline-hygiene" in r.stdout
    # Malformed baseline is a usage error, not a clean pass.
    junk = tmp_path / "junk.json"
    junk.write_text('{"not": "a list"}')
    junk2 = tmp_path / "junk2.json"
    junk2.write_text('[{"no_fingerprint": true}]')
    for p in (junk, junk2):
        r = _lint_cli(["--include-fixtures", "--baseline", str(p), bad], env)
        assert r.returncode == 2, r.stdout + r.stderr


def test_cli_baseline_stale_entry_reported(tmp_path):
    """A baseline entry matching no current finding is itself a finding
    (stale-baseline) — the suppress-list cannot rot silently."""
    import json as _json
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = {**os.environ, "PYTHONPATH": repo_root}
    clean = tmp_path / "clean.py"
    clean.write_text("x = 1\n")
    baseline = tmp_path / "baseline.json"
    baseline.write_text(_json.dumps([{
        "fingerprint": "0" * 40, "file": "gone.py",
        "rule": "metric-name-registry"}]))
    r = _lint_cli(["--baseline", str(baseline), str(clean)], env)
    assert r.returncode == 1
    assert "stale-baseline" in r.stdout
    assert "gone.py" in r.stdout
    # --changed cannot prove an entry dead (partial tree): stale check off.
    # (Covered here via the in-process helper to avoid a git fixture.)
    from rbg_tpu.analysis.cli import _apply_baseline
    assert _apply_baseline([], str(baseline), check_stale=False) == []


def test_checked_in_baseline_is_valid_and_empty():
    """The repo gate's checked-in baseline (scripts/lint-baseline.json)
    must stay parseable — and empty while the tree is clean, so a new
    finding cannot hide in it unreviewed."""
    import json as _json
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "scripts", "lint-baseline.json")) as fh:
        entries = _json.load(fh)
    assert entries == []
