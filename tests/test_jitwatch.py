"""jitwatch: the runtime compile & host-sync sentry. The arming matrix,
warmup_complete gating (including a seeded violation proving the sentry
actually fires), warn-mode counters, the hot_section sync probe, and the
off-by-default zero-overhead contract."""

import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp

from rbg_tpu.obs import names
from rbg_tpu.utils import jitwatch


@pytest.fixture()
def watch(monkeypatch):
    monkeypatch.setenv("RBG_JITWATCH", "1")
    jitwatch.disarm()
    yield jitwatch
    jitwatch.disarm()


def _compile_cataloged(program, shape=(4,)):
    """Force a fresh XLA compile whose sym_name matches a cataloged
    program — the same __name__-stamping the engine getters use."""
    def f(x):
        return x * 2 + 1
    f.__name__ = program
    return jax.jit(f)(jnp.ones(shape))


# ---- arming matrix ----


@pytest.mark.parametrize("value,expect", [
    ("1", "raise"), ("true", "raise"), ("warn", "warn"),
    ("0", ""), ("false", ""), ("off", ""), ("", ""),
])
def test_arming_matrix(monkeypatch, value, expect):
    monkeypatch.setenv("RBG_JITWATCH", value)
    assert jitwatch.mode() == expect
    assert jitwatch.enabled() == bool(expect)


def test_off_by_default_nothing_patched(monkeypatch):
    monkeypatch.delenv("RBG_JITWATCH", raising=False)
    jitwatch.disarm()
    from jax._src import compiler
    from jax._src.array import ArrayImpl
    assert compiler.backend_compile.__name__ != "traced_backend_compile"
    item = getattr(ArrayImpl, "item", None)
    assert item is None or not item.__name__.startswith("jitwatch_")
    assert jax.device_get.__name__ != "traced_device_get"
    # hot_section without hooks is a no-op, not an error.
    with jitwatch.hot_section("cold", strict=True):
        pass


def test_disarm_restores_all_seams(watch):
    from jax._src import compiler
    orig_compile = compiler.backend_compile
    orig_get = jax.device_get
    watch.arm()
    assert compiler.backend_compile is not orig_compile
    assert jax.device_get is not orig_get
    watch.disarm()
    assert compiler.backend_compile is orig_compile
    assert jax.device_get is orig_get


# ---- warmup_complete gating ----


def test_sentry_fires_on_post_warmup_cataloged_compile(watch):
    """The seeded fixture: a cataloged program compiling AFTER the gate
    must raise — this is the proof the sentry is live, not decorative."""
    watch.arm()
    _compile_cataloged(names.PROGRAM_FUSED_DECODE)       # warmup set
    n = watch.warmup_complete()
    assert n >= 1 and watch.gate_armed()
    assert names.PROGRAM_FUSED_DECODE in watch.warmed_programs()
    with pytest.raises(watch.JitCompileError):
        _compile_cataloged(names.PROGRAM_FUSED_DECODE, shape=(8,))
    assert watch.violations()
    assert watch.unwarmed_by_program() == {names.PROGRAM_FUSED_DECODE: 1}


def test_pre_gate_compiles_are_the_blessed_warmup_set(watch):
    watch.arm()
    _compile_cataloged(names.PROGRAM_RAGGED_FWD)
    _compile_cataloged(names.PROGRAM_SAMPLER)
    watch.warmup_complete()
    assert {names.PROGRAM_RAGGED_FWD,
            names.PROGRAM_SAMPLER} <= watch.warmed_programs()
    assert watch.violations() == []
    assert watch.counters()["rbg_jit_unwarmed_compiles_total"] == 0.0


def test_uncataloged_compiles_never_gate(watch):
    """Eager-op scaffolding and test helpers compile freely post-gate:
    only the PROGRAMS catalog is the contract."""
    watch.arm()
    watch.warmup_complete()

    def f(x):
        return x + 3
    f.__name__ = "totally_uncataloged_program"
    jax.jit(f)(jnp.ones(3))
    assert watch.violations() == []
    recs = [r for r in watch.compiles()
            if r["program"] == "totally_uncataloged_program"]
    assert recs and recs[0]["post_warmup"] and not recs[0]["violation"]


def test_violation_names_program_and_origin(watch):
    watch.arm()
    watch.warmup_complete()
    with pytest.raises(watch.JitCompileError) as ei:
        _compile_cataloged(names.PROGRAM_PD_HEAD)
    assert names.PROGRAM_PD_HEAD in str(ei.value)
    assert "after warmup_complete()" in str(ei.value)


def test_warn_mode_counts_instead_of_raising(monkeypatch):
    monkeypatch.setenv("RBG_JITWATCH", "warn")
    jitwatch.disarm()
    try:
        jitwatch.arm()
        _compile_cataloged(names.PROGRAM_SAMPLER)
        jitwatch.warmup_complete()
        _compile_cataloged(names.PROGRAM_SAMPLER, shape=(8,))   # no raise
        c = jitwatch.counters()
        assert c["rbg_jit_unwarmed_compiles_total"] == 1.0
        assert c["rbg_jit_compiles_total"] >= 2.0
        assert jitwatch.unwarmed_by_program() == {names.PROGRAM_SAMPLER: 1}
        assert len(jitwatch.violations()) == 1
        assert len(jitwatch.unwarmed()) == 1
    finally:
        jitwatch.disarm()


def test_reset_clears_records_but_keeps_hooks(watch):
    watch.arm()
    _compile_cataloged(names.PROGRAM_RAGGED_FWD)
    watch.warmup_complete()
    watch.reset()
    assert not watch.gate_armed()
    assert watch.compiles() == [] and watch.warmed_programs() == set()
    from jax._src import compiler
    assert compiler.backend_compile.__name__ == "traced_backend_compile"


def test_warmup_complete_without_arm_is_harmless(monkeypatch):
    monkeypatch.delenv("RBG_JITWATCH", raising=False)
    jitwatch.disarm()
    try:
        assert jitwatch.warmup_complete() == 0
        jnp.ones(2).block_until_ready()      # no wrappers: nothing counted
        assert jitwatch.counters()["rbg_jit_host_syncs_total"] == 0.0
    finally:
        jitwatch.disarm()


# ---- host-sync probe ----


def test_hot_section_strict_raises_on_forcer(watch):
    watch.arm()
    x = jnp.ones(2)
    with watch.hot_section("decode", strict=True):
        with pytest.raises(watch.HostSyncError):
            x.item()


def test_hot_section_counts_without_strict(watch):
    watch.arm()
    x = jnp.arange(4)
    before = watch.counters()["rbg_jit_host_syncs_total"]
    with watch.hot_section("decode"):
        float(x[0])
    assert watch.counters()["rbg_jit_host_syncs_total"] > before


def test_gate_armed_counts_syncs_outside_hot_sections(watch):
    watch.arm()
    x = jnp.ones(3)
    watch.warmup_complete()
    base = watch.counters()["rbg_jit_host_syncs_total"]
    x.block_until_ready()
    assert watch.counters()["rbg_jit_host_syncs_total"] >= base + 1


def test_syncs_before_gate_and_outside_sections_are_free(watch):
    watch.arm()
    x = jnp.ones(3)
    x.block_until_ready()                     # pre-gate, not hot: untracked
    assert watch.counters()["rbg_jit_host_syncs_total"] == 0.0


def test_hot_section_nesting_unwinds_cleanly(watch):
    watch.arm()
    with watch.hot_section("outer"):
        with watch.hot_section("inner", strict=False):
            pass
    # Depth unwound: a sync after the sections (gate unarmed) is free.
    jnp.ones(2).block_until_ready()
    assert watch.counters()["rbg_jit_host_syncs_total"] == 0.0


# ---- catalog agreement ----


def test_programs_catalog_names_are_stamped_constants():
    """The PROGRAMS frozenset and the PROGRAM_* constants must agree —
    the warmers stamp __name__ from the constants and the sentry gates on
    the frozenset, so drift here silently disables the gate."""
    constants = {v for k, v in vars(names).items()
                 if k.startswith("PROGRAM_") and isinstance(v, str)}
    assert constants == set(names.PROGRAMS)
    assert all(p.startswith("rbg_") for p in names.PROGRAMS)
