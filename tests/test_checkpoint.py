"""Checkpointing: orbax round-trip + HF import validated against the REAL
transformers implementation (logit-level numerics)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.models import KVCache, forward, get_config, init_params
from rbg_tpu.models.checkpoint import (
    is_hf_checkpoint, load_hf_llama, load_checkpoint, save_checkpoint,
)


def test_orbax_roundtrip(tmp_path):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)
    restored = load_checkpoint(path, like=params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, restored,
    )
    assert not is_hf_checkpoint(path)


@pytest.mark.slow
@pytest.mark.parametrize("with_bias", [False, True], ids=["llama", "qwen2"])
def test_hf_import_matches_transformers(tmp_path, with_bias):
    """Build a tiny real HF model, save it, import it, and require our
    forward to reproduce transformers' logits."""
    torch = pytest.importorskip("torch")
    from transformers import LlamaConfig, LlamaForCausalLM, Qwen2Config, Qwen2ForCausalLM

    if with_bias:
        hf_cfg = Qwen2Config(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, rope_theta=10000.0, rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )
        hf_model = Qwen2ForCausalLM(hf_cfg)
    else:
        hf_cfg = LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=128, rope_theta=10000.0, rms_norm_eps=1e-5,
            tie_word_embeddings=False,
        )
        hf_model = LlamaForCausalLM(hf_cfg)
    hf_model.eval()
    hf_dir = str(tmp_path / "hf")
    hf_model.save_pretrained(hf_dir, safe_serialization=True)
    assert is_hf_checkpoint(hf_dir)

    cfg = get_config(
        "tiny", vocab_size=256, hidden_size=64, intermediate_size=128,
        num_layers=2, num_heads=4, num_kv_heads=2, rope_theta=10000.0,
        dtype="float32",
    )
    params = load_hf_llama(hf_dir, cfg)
    if with_bias:
        assert "bq" in params["blocks"]

    tokens = np.array([[1, 7, 42, 99, 5, 200, 3, 8]], np.int64)
    with torch.no_grad():
        hf_logits = hf_model(torch.from_numpy(tokens)).logits.numpy()

    ours, _ = forward(params, cfg, jnp.asarray(tokens, jnp.int32),
                      KVCache.create(cfg, 1, 16))
    np.testing.assert_allclose(np.asarray(ours), hf_logits, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_engine_loads_checkpoint(tmp_path):
    from rbg_tpu.engine import Engine, EngineConfig, SamplingParams

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(7))
    path = str(tmp_path / "ckpt")
    save_checkpoint(path, params)

    ref = Engine(EngineConfig(model="tiny", page_size=8, num_pages=64,
                              max_seq_len=128, use_pallas="never"), params=params)
    expect = ref.generate([[5, 6, 7]], SamplingParams(max_new_tokens=4))[0]

    eng = Engine(EngineConfig(model="tiny", page_size=8, num_pages=64,
                              max_seq_len=128, use_pallas="never",
                              checkpoint_path=path))
    got = eng.generate([[5, 6, 7]], SamplingParams(max_new_tokens=4))[0]
    assert got == expect
