"""Pluggable workload-backend seam (reference: inventory #23,
``pkg/reconciler/workload_reconciler.go:54-69`` factory + dynamic CRD watch
``rolebasedgroup_controller.go:1598-1621``): a custom workload kind attaches
via ``rbg_tpu.runtime.workload.register()`` with ZERO edits to the group
controller."""

import dataclasses

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RoleStatus
from rbg_tpu.api.meta import ObjectMeta, get_condition, owner_ref
from rbg_tpu.api.validation import ValidationError
from rbg_tpu.runtime import workload
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


@dataclasses.dataclass
class ExternalStatus:
    ready: bool = False
    observed_revision: str = ""


@dataclasses.dataclass
class ExternalWorkload:
    """A stand-in for an externally-operated workload kind (vendor operator,
    Kueue job...) — the plane only sees this handle object."""

    kind: str = "ExternalWorkload"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    replicas: int = 0
    image: str = ""
    status: ExternalStatus = dataclasses.field(default_factory=ExternalStatus)

    __serde_keep__ = ("kind", "metadata")


class ExternalBackend(workload.WorkloadBackend):
    kind = "ExternalWorkload"

    def __init__(self):
        self.validated = []

    def validate(self, store, rbg, role):
        self.validated.append(role.name)
        if role.replicas > 10:
            raise ValidationError("ExternalWorkload caps replicas at 10")

    def watches(self):
        from rbg_tpu.runtime.controller import Watch, owner_keys
        return [Watch("ExternalWorkload", owner_keys("RoleBasedGroup"))]

    def reconcile_role(self, store, rbg, role, role_hash, replicas, gang,
                       partition=None):
        from rbg_tpu.runtime.store import AlreadyExists
        ns = rbg.metadata.namespace
        wname = C.workload_name(rbg.metadata.name, role.name)
        image = role.template.containers[0].image if role.template.containers else ""
        cur = store.get("ExternalWorkload", ns, wname, copy_=False)
        if cur is None:
            w = ExternalWorkload()
            w.metadata.name = wname
            w.metadata.namespace = ns
            w.metadata.labels = {C.role_revision_label(role.name): role_hash}
            w.metadata.owner_references = [owner_ref(rbg)]
            w.replicas, w.image = replicas, image
            try:
                store.create(w)
            except AlreadyExists:
                pass
        elif (cur.replicas, cur.image) != (replicas, image) or \
                cur.metadata.labels.get(C.role_revision_label(role.name)) != role_hash:
            def fn(w):
                w.replicas, w.image = replicas, image
                w.metadata.labels[C.role_revision_label(role.name)] = role_hash
                return True
            store.mutate("ExternalWorkload", ns, wname, fn)

    def construct_role_status(self, store, rbg, role, role_hash, prev):
        ns = rbg.metadata.namespace
        wname = C.workload_name(rbg.metadata.name, role.name)
        w = store.get("ExternalWorkload", ns, wname, copy_=False)
        if w is None:
            return prev or RoleStatus(name=role.name)
        n = w.replicas if w.status.ready else 0
        return RoleStatus(name=role.name, replicas=w.replicas,
                          ready_replicas=n, updated_replicas=w.replicas,
                          updated_ready_replicas=n,
                          observed_revision=role_hash, ready=w.status.ready)

    def cleanup_orphans(self, store, rbg, valid_names):
        for w in store.list("ExternalWorkload", namespace=rbg.metadata.namespace,
                            owner_uid=rbg.metadata.uid):
            if w.metadata.name not in valid_names:
                store.delete("ExternalWorkload", w.metadata.namespace,
                             w.metadata.name)


@pytest.fixture()
def external_backend():
    b = workload.register(ExternalBackend())
    yield b
    workload.unregister(b.kind)


@pytest.fixture()
def plane(external_backend):
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        yield p


def external_role(name, replicas=2, image="vendor:v1"):
    r = simple_role(name, replicas=replicas, image=image)
    r.workload = "ExternalWorkload"
    return r


def test_custom_kind_end_to_end(plane, external_backend):
    """Group with a custom-kind role reaches Ready purely through the
    registered backend — the group controller never names the kind."""
    plane.apply(make_group("svc", external_role("db", replicas=3)))

    def created():
        w = plane.store.get("ExternalWorkload", "default", "svc-db")
        return w is not None and w.replicas == 3
    plane.wait_for(created, desc="backend created the external child")
    assert "db" in external_backend.validated

    # Group not Ready while the external workload isn't.
    g = plane.store.get("RoleBasedGroup", "default", "svc")
    c = get_condition(g.status.conditions, C.COND_READY)
    assert c is None or c.status == "False"

    # External operator reports ready → group goes Ready via the backend's
    # status rollup + the backend-declared watch.
    def mark(w):
        w.status.ready = True
        return True
    plane.store.mutate("ExternalWorkload", "default", "svc-db", mark, status=True)
    plane.wait_group_ready("svc")
    g = plane.store.get("RoleBasedGroup", "default", "svc")
    st = g.status.role("db")
    assert st.ready_replicas == 3


def test_mixed_kinds_in_one_group(plane):
    """Native InstanceSet role + custom-kind role coexist; group Ready only
    when BOTH backends report ready."""
    plane.apply(make_group("mix", simple_role("server", replicas=1),
                           external_role("cache", replicas=2)))
    plane.wait_for(
        lambda: plane.store.get("ExternalWorkload", "default", "mix-cache"),
        desc="external child")
    plane.wait_for(
        lambda: plane.store.get("RoleInstanceSet", "default", "mix-server"),
        desc="native child")

    # native role becomes ready via the fake kubelet; external still pending
    def native_ready():
        ris = plane.store.get("RoleInstanceSet", "default", "mix-server")
        return ris.status.ready_replicas == 1
    plane.wait_for(native_ready, timeout=20, desc="native role ready")
    g = plane.store.get("RoleBasedGroup", "default", "mix")
    c = get_condition(g.status.conditions, C.COND_READY)
    assert c is None or c.status == "False"

    plane.store.mutate("ExternalWorkload", "default", "mix-cache",
                       lambda w: setattr(w.status, "ready", True) or True,
                       status=True)
    plane.wait_group_ready("mix")


def test_template_change_reaches_custom_kind(plane):
    plane.apply(make_group("svc", external_role("db", image="vendor:v1")))
    plane.wait_for(
        lambda: plane.store.get("ExternalWorkload", "default", "svc-db"),
        desc="external child")
    g = plane.store.get("RoleBasedGroup", "default", "svc")
    g.spec.roles[0].template.containers[0].image = "vendor:v2"
    plane.store.update(g)
    plane.wait_for(
        lambda: plane.store.get("ExternalWorkload", "default", "svc-db").image
        == "vendor:v2",
        desc="image propagated to external child")


def test_kind_change_cleans_old_backend_child(plane):
    """Flipping a role's workload kind deletes the old backend's child."""
    plane.apply(make_group("svc", external_role("db")))
    plane.wait_for(
        lambda: plane.store.get("ExternalWorkload", "default", "svc-db"),
        desc="external child")
    g = plane.store.get("RoleBasedGroup", "default", "svc")
    g.spec.roles[0].workload = workload.DEFAULT_KIND
    plane.store.update(g)
    plane.wait_for(
        lambda: plane.store.get("ExternalWorkload", "default", "svc-db") is None,
        desc="old-kind child cleaned up")
    plane.wait_for(
        lambda: plane.store.get("RoleInstanceSet", "default", "svc-db"),
        desc="native child created")


def test_backend_validation_rejects(plane):
    plane.apply(make_group("svc", external_role("db", replicas=11)))

    def rejected():
        g = plane.store.get("RoleBasedGroup", "default", "svc")
        c = get_condition(g.status.conditions, C.COND_READY)
        return c is not None and c.reason == "ValidationFailed" \
            and "caps replicas" in (c.message or "")
    plane.wait_for(rejected, desc="backend validation surfaces")
    assert plane.store.get("ExternalWorkload", "default", "svc-db") is None


def test_unknown_kind_surfaces_validation_failure():
    """A role naming an unregistered kind → ValidationFailed condition
    (reference: unsupported workload type error)."""
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=2)
    with p:
        r = simple_role("x")
        r.workload = "NoSuchKind"
        p.apply(make_group("svc", r))

        def rejected():
            g = p.store.get("RoleBasedGroup", "default", "svc")
            c = get_condition(g.status.conditions, C.COND_READY)
            return (c is not None and c.reason == "ValidationFailed"
                    and "NoSuchKind" in (c.message or ""))
        p.wait_for(rejected, desc="unknown kind rejected")
