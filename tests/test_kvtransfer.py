"""KV transfer plane: transport contract, streaming inject identity,
cluster prefix directory lifecycle, and transfer-cost-aware routing.

Fast tests are engine-free (numpy + sockets). Engine-backed identity and
e2e drills are marked ``slow`` per the PR-2 budget policy.
"""

import threading
import time

import numpy as np
import pytest

from rbg_tpu.kvtransfer import (ChunkAssembler, DirectoryClient,
                                FakeICITransport, InProcTransport,
                                KVStreamReceiver, PrefixDirectory,
                                SlowLossyTransport, StreamError, StreamFin,
                                StreamFirstToken, StreamMeta,
                                bundle_to_frames, frame_from_wire,
                                frame_to_wire, prefix_keys)
from rbg_tpu.kvtransfer.transport import LinkStats


def mk_meta(sid="s1", n_pages=4, layers=3, page=8, kv=2, hd=4,
            prompt_len=None):
    prompt = list(range(1, (prompt_len or n_pages * page) + 1))
    return StreamMeta(stream_id=sid, prompt=prompt, n_pages=n_pages,
                      k_page_shape=(page, kv, hd), v_page_shape=(page, kv, hd),
                      dtype="float32", layers=layers, page_size=page)


def mk_payload(meta, seed=0):
    rng = np.random.RandomState(seed)
    k = rng.randn(*meta.k_shape()).astype(np.float32)
    v = rng.randn(*meta.v_shape()).astype(np.float32)
    return k, v


# ---- chunk model ----------------------------------------------------------


def test_prefix_keys_page_aligned_chain():
    toks = list(range(40))
    keys = prefix_keys(toks, 8)
    assert len(keys) == 5                       # 40 tokens / 8 per page
    # Deterministic across calls; a chain — shared prefixes share keys,
    # divergence changes everything downstream.
    assert keys == prefix_keys(toks, 8)
    other = prefix_keys(toks[:16] + [999] + toks[17:], 8)
    assert other[:2] == keys[:2]
    assert other[2:] != keys[2:]
    # Partial pages never get a key.
    assert prefix_keys(list(range(7)), 8) == []


def test_frame_wire_roundtrip():
    meta = mk_meta()
    k, v = mk_payload(meta)
    frames = bundle_to_frames(meta, k, v, first_token=42, layer_split=1)
    for f in frames:
        hdr, kb, vb = frame_to_wire(f)
        g = frame_from_wire(hdr, kb, vb)
        assert type(g) is type(f)
        assert g.stream_id == meta.stream_id
    assert isinstance(frames[0], StreamMeta)
    assert isinstance(frames[-2], StreamFirstToken)
    assert isinstance(frames[-1], StreamFin)
    # layer_split=1 ⇒ layers × pages data chunks
    assert frames[-1].n_chunks == meta.layers * meta.n_pages


def test_assembler_tolerates_reorder_and_duplicates():
    meta = mk_meta()
    k, v = mk_payload(meta)
    frames = bundle_to_frames(meta, k, v, first_token=7, layer_split=1)
    data = frames[1:-2]
    rng = np.random.RandomState(3)
    rng.shuffle(data)
    a = ChunkAssembler(meta)
    for ch in data + data[:5]:          # every chunk once, five twice
        a.feed(ch)
    assert a.coverage_complete()
    assert a.dup_chunks == 5
    assert not a.ready()                # no first token yet
    a.feed(StreamFirstToken(meta.stream_id, 7))
    assert a.ready()
    np.testing.assert_array_equal(a.k, k)
    np.testing.assert_array_equal(a.v, v)


def test_assembler_truncated_stream_structured_error():
    meta = mk_meta()
    k, v = mk_payload(meta)
    frames = bundle_to_frames(meta, k, v, first_token=7)
    a = ChunkAssembler(meta)
    for f in frames[1:3]:               # a strict subset of the data
        a.feed(f)
    a.feed(StreamFin(meta.stream_id, n_chunks=meta.n_pages))
    with pytest.raises(StreamError, match="truncated"):
        a.check_closed()


def test_assembler_rejects_out_of_bounds_and_bad_size():
    meta = mk_meta()
    k, v = mk_payload(meta)
    frames = bundle_to_frames(meta, k, v, first_token=7)
    ch = frames[1]
    ch.page_hi = meta.n_pages + 3
    with pytest.raises(StreamError, match="out of bounds"):
        ChunkAssembler(meta).feed(ch)
    ch2 = frames[2]
    ch2.k_bytes = ch2.k_bytes[:-4]
    with pytest.raises(StreamError, match="size mismatch"):
        ChunkAssembler(meta).feed(ch2)


# ---- transports -----------------------------------------------------------


def pump_stream(transport, meta, timeout=10.0):
    rx = KVStreamReceiver(meta.stream_id)
    t = threading.Thread(target=rx.pump, args=(transport,),
                         kwargs={"timeout": timeout}, daemon=True)
    t.start()
    return rx, t


def test_inproc_transport_stream_roundtrip():
    meta = mk_meta(sid="ip1")
    k, v = mk_payload(meta)
    tr = InProcTransport()
    rx, t = pump_stream(tr, meta)
    tr.send_chunks("", bundle_to_frames(meta, k, v, first_token=9))
    a = rx.wait_ready(5.0)
    t.join(5.0)
    assert a.first_token == 9
    np.testing.assert_array_equal(a.k, k)
    assert rx.error() is None
    assert rx.t_fin is not None


def test_fake_ici_transport_paces_to_link_rate():
    meta = mk_meta(sid="ici1")        # 4 pages ⇒ > MIN_SAMPLE_BYTES
    k, v = mk_payload(meta)
    nbytes = k.nbytes + v.nbytes
    tr = FakeICITransport(bytes_per_s=nbytes / 0.2, latency_s=0.0)
    rx, t = pump_stream(tr, meta)
    t0 = time.monotonic()
    tr.send_chunks("", bundle_to_frames(meta, k, v, first_token=1))
    elapsed = time.monotonic() - t0
    rx.wait_ready(5.0)
    t.join(5.0)
    # The payload alone must take ~0.2 s on this modeled link.
    assert elapsed >= 0.15
    # Real transfers feed the measured link rate.
    assert tr.stats.rate("") == pytest.approx(nbytes / elapsed, rel=0.5)


def test_slow_lossy_reorder_and_dup_still_assembles():
    meta = mk_meta(sid="sl1")
    k, v = mk_payload(meta)
    tr = SlowLossyTransport(InProcTransport(), delay_s=0.0,
                            reorder_window=4, dup_rate=0.5, seed=5)
    rx, t = pump_stream(tr, meta)
    tr.send_chunks("", bundle_to_frames(meta, k, v, first_token=3,
                                        layer_split=1))
    a = rx.wait_ready(5.0)
    t.join(5.0)
    np.testing.assert_array_equal(a.k, k)
    np.testing.assert_array_equal(a.v, v)


def test_slow_lossy_truncation_surfaces_structured_error():
    meta = mk_meta(sid="cut1")
    k, v = mk_payload(meta)
    tr = SlowLossyTransport(InProcTransport(), delay_s=0.0,
                            truncate_stream="cut1",
                            truncate_after_bytes=k.nbytes // 4)
    rx, t = pump_stream(tr, meta)
    tr.send_chunks("", bundle_to_frames(meta, k, v, first_token=3))
    t.join(5.0)
    with pytest.raises(StreamError):
        rx.wait_ready(2.0)
    assert rx.error() is not None       # failed, not wedged


def test_receiver_timeout_is_structured_not_a_wedge():
    tr = InProcTransport()
    rx = KVStreamReceiver("never")
    t = threading.Thread(target=rx.pump, args=(tr,),
                         kwargs={"timeout": 0.1}, daemon=True)
    t.start()
    t.join(5.0)
    assert not t.is_alive()
    assert "no frame within" in rx.error()


def test_linkstats_ewma_and_default():
    ls = LinkStats("test")
    assert ls.rate("a") is None
    assert ls.rate("a", default=5.0) == 5.0
    ls.observe("a", 1 << 20, 1.0)
    first = ls.rate("a")
    assert first == pytest.approx(1 << 20)
    ls.observe("a", 1 << 20, 0.5)       # faster sample moves the EWMA up
    assert ls.rate("a") > first
    ls.observe("a", 16, 1.0)            # tiny frames are ignored
    assert ls.rate("a") > first


# ---- prefix directory -----------------------------------------------------


def test_directory_register_lookup_longest_prefix():
    d = PrefixDirectory(page_size=8)
    toks = list(range(32))
    d.register(toks, "b1", slice_id="s1")
    d.register(toks[:16], "b2", slice_id="s2")
    matched, holders = d.lookup(toks)
    assert matched == 32 and holders == ["b1"]
    matched, holders = d.lookup(toks[:17])
    assert matched == 16 and sorted(holders) == ["b1", "b2"]
    assert d.lookup([99, 98, 97, 96, 95, 94, 93, 92])[0] == 0


def test_directory_invalidate_backend_and_slice():
    d = PrefixDirectory(page_size=8)
    toks = list(range(24))
    d.register(toks, "b1", slice_id="s1")
    d.register(toks, "b2", slice_id="s2")
    d.invalidate_backend("b1", reason="drain")
    assert d.lookup(toks)[1] == ["b2"]
    d.invalidate_slice("s2", reason="preemption")
    assert d.lookup(toks) == (0, [])
    assert d.stats()["keys"] == 0


def test_directory_ttl_expiry():
    d = PrefixDirectory(page_size=8, ttl_s=0.05)
    toks = list(range(16))
    d.register(toks, "b1")
    assert d.lookup(toks)[0] == 16
    time.sleep(0.08)
    assert d.lookup(toks) == (0, [])


def test_pool_eviction_invalidates_directory():
    from rbg_tpu.engine.kvpool import KVPoolStore

    d = PrefixDirectory(page_size=4)
    # Budget fits ~2 pages of this shape — the third put evicts.
    page_bytes = 2 * (2 * 4 * 2 * 4 * 4)
    store = KVPoolStore(4, max_bytes=page_bytes, directory=d)
    mk = lambda: np.ones((2, 1, 4, 2, 4), np.float32)
    p1, p2, p3 = [list(range(i * 10, i * 10 + 4)) for i in range(3)]
    for p in (p1, p2, p3):
        store.put(p, mk(), mk())
        d.register(p, "b1")
        time.sleep(0.01)   # distinct LRU stamps
    assert store.metrics["evicted_pages"] >= 1
    # Directory must not claim what the pool evicted: every remaining
    # claim is backed by the pool actually holding it.
    for p in (p1, p2, p3):
        matched, holders = d.lookup(p)
        if matched:
            assert store.match(p)[0] >= matched


def test_directory_wire_ops_against_live_pool_server():
    from rbg_tpu.engine.kvpool import KVPoolServer, KVPoolStore

    d = PrefixDirectory(page_size=8)
    store = KVPoolStore(8, directory=d)
    srv = KVPoolServer(("127.0.0.1", 0), store)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        c = DirectoryClient(addr, page_size=8, token="")
        toks = list(range(24))
        assert c.register(toks, "10.0.0.5:9000", slice_id="sl-a") == 3
        matched, holders = c.lookup(toks)
        assert matched == 24 and holders == ["10.0.0.5:9000"]
        # A page_size-less client (the router) looks up by prompt; the
        # server computes the key chain with ITS page size.
        rc = DirectoryClient(addr, token="")
        assert rc.lookup(toks) == (24, ["10.0.0.5:9000"])
        assert c.invalidate_slice("sl-a") == 3
        assert rc.lookup(toks) == (0, [])
        assert "lookups" in c.stats()
    finally:
        srv.shutdown()
        srv.server_close()


def test_disruption_controller_invalidates_slice():
    from rbg_tpu.runtime.controllers.disruption import DisruptionController
    from rbg_tpu.runtime.store import Store

    d = PrefixDirectory(page_size=8)
    toks = list(range(16))
    d.register(toks, "b1", slice_id="slice-x")
    ctl = DisruptionController(Store(), kv_directory=d)
    ctl._invalidate_kv_slice("slice-x", "preemption")
    assert d.lookup(toks) == (0, [])


# ---- router: affinity staleness + transfer-cost scoring -------------------


def test_affinity_demoted_on_drain_and_eviction():
    from rbg_tpu.engine.router import Registry, RouterState

    st = RouterState(Registry(None), None,
                     {"prefill": ["h1:1", "h2:2", "h3:3"]})
    prompt = list(range(40))
    akey = st.affinity.key(prompt)
    st.affinity.put(akey, "h3:3")
    assert st.candidates_for("prefill", prompt)[0] == "h3:3"
    # Drain notification demotes IMMEDIATELY — no waiting for eviction.
    st.pool.set_draining("h3:3", True)
    assert st.affinity.get(akey) is None
    assert st.candidates_for("prefill", prompt)[0] != "h3:3"
    assert st.metrics["affinity_demotions"] >= 1
    # Eviction (transport failure / preempted pod) demotes too.
    st.affinity.put(akey, "h2:2")
    st.pool.fail("h2:2")
    assert st.affinity.get(akey) is None


def test_affinity_never_fronts_draining_even_if_remembered():
    from rbg_tpu.engine.router import Registry, RouterState

    st = RouterState(Registry(None), None,
                     {"prefill": ["h1:1", "h2:2"]})
    prompt = list(range(40))
    akey = st.affinity.key(prompt)
    # A drain that bypassed the callback (e.g. direct state injection)
    # still must not be fronted: candidates_for checks the flag itself.
    st.pool._state("h2:2").draining = True
    st.affinity.put(akey, "h2:2")
    assert st.candidates_for("prefill", prompt)[0] == "h1:1"


def test_directory_backed_affinity_routes_to_any_holder():
    from rbg_tpu.engine.router import Registry, RouterState

    d = PrefixDirectory(page_size=8)
    st = RouterState(Registry(None), None,
                     {"prefill": ["h1:1", "h2:2", "h3:3"]},
                     directory=d)
    prompt = list(range(40))
    # No local LRU memory — but h2 registered the prefix cluster-wide.
    d.register(prompt, "h2:2")
    assert st.candidates_for("prefill", prompt)[0] == "h2:2"
    assert st.metrics["directory_hits"] == 1
    # Balance guard still applies: a much busier holder yields.
    for _ in range(10):
        st.pool.acquire("h2:2")
    assert st.candidates_for("prefill", prompt)[0] != "h2:2"


def test_transfer_cost_scoring_prefers_fast_link():
    from rbg_tpu.engine.router import Registry, RouterState

    st = RouterState(Registry(None), None,
                     {"decode": ["slow:1", "fast:2"]})
    st.linkstats.observe("slow:1", 100 << 20, 10.0)   # 10 MB/s
    st.linkstats.observe("fast:2", 100 << 20, 0.1)    # 1 GB/s
    # Equal queues: the measured-faster link wins for a big KV move.
    cands = st.candidates("decode", cost=st.kv_cost_fn(64 << 20))
    assert cands[0] == "fast:2"
    # Tiny KV: cost ≈ 0 either way — least-outstanding (tie: first) rules.
    st.pool.acquire("fast:2")
    st.pool.acquire("fast:2")
    cands = st.candidates("decode", cost=st.kv_cost_fn(1024))
    assert cands[0] == "slow:1"
    # Queue depth can out-weigh a fast link (it is a trade, not a pin).
    cands = st.candidates("decode", cost=st.kv_cost_fn(4 << 20))
    assert cands[0] == "slow:1"
    assert st.kv_cost_fn(0) is None


def test_pinned_stream_shed_falls_back_to_bundle():
    """A decode replica that SHEDS the pinned decode_stream leg
    (overloaded) must not surface 429 to the client: the router re-routes
    in bundle mode and the request completes on the decode_bundle path."""
    import json
    import socket
    import socketserver

    from rbg_tpu.engine.protocol import recv_msg, request_once, send_msg
    from rbg_tpu.engine.router import (Handler, Registry, RouterServer,
                                       RouterState)

    class Scripted(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def __init__(self, script):
            self.seen = []
            be = self

            class H(socketserver.BaseRequestHandler):
                def handle(self):
                    while True:
                        try:
                            obj, k, v = recv_msg(self.request)
                        except (ConnectionError, json.JSONDecodeError):
                            return
                        if obj is None:
                            return
                        be.seen.append(obj)
                        hdr, kb, vb = script(obj)
                        send_msg(self.request, hdr, kb, vb)

            super().__init__(("127.0.0.1", 0), H)
            self.addr = f"127.0.0.1:{self.server_address[1]}"
            threading.Thread(target=self.serve_forever,
                             daemon=True).start()

    kb = np.zeros((2, 1, 8, 2, 4), np.float32).tobytes()

    def prefill_script(obj):
        if obj.get("op") == "health":
            return {"ok": True}, None, None
        if "push_to" in obj:
            # Claims the push succeeded — the decode leg will shed it.
            return {"pushed": True, "stream_id": obj["stream_id"],
                    "first_token": 5, "prompt": obj["prompt"],
                    "kv_bytes": len(kb) * 2}, None, None
        return {"prompt": obj["prompt"], "first_token": 5,
                "shape": [2, 1, 8, 2, 4], "dtype": "float32"}, kb, kb

    def decode_script(obj):
        if obj.get("op") == "health":
            return {"ok": True}, None, None
        if obj.get("op") == "decode_stream":
            return {"error": "queue full", "code": "overloaded",
                    "retry_after_s": 0.5}, None, None
        return {"tokens": [5, 7, 9]}, None, None   # decode_bundle works

    pf, dc = Scripted(prefill_script), Scripted(decode_script)
    try:
        router = RouterServer(("127.0.0.1", 0), Handler)
        router.state = RouterState(
            Registry(None), None,
            {"prefill": [pf.addr], "decode": [dc.addr]})
        threading.Thread(target=router.serve_forever,
                         daemon=True).start()
        addr = f"127.0.0.1:{router.server_address[1]}"
        resp, _, _ = request_once(addr, {"op": "generate",
                                         "prompt": [1, 2, 3],
                                         "max_new_tokens": 3}, timeout=30)
        # Not a 429: the bundle fallback served it.
        assert resp.get("tokens") == [5, 7, 9], resp
        assert router.state.metrics["kv_stream_fallbacks"] == 1
        assert router.state.metrics["kv_stream_routed"] == 1
        ops = [o.get("op") for o in dc.seen if o.get("op") != "health"]
        assert ops == ["decode_stream", "decode_bundle"]
        router.shutdown()
    finally:
        pf.shutdown()
        dc.shutdown()


# ---- engine-backed identity + e2e (slow) ----------------------------------


@pytest.fixture(scope="module")
def tiny_setup():
    import jax

    from rbg_tpu.models import get_config, init_params

    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def ecfg(**kw):
    from rbg_tpu.engine import EngineConfig

    base = dict(model="tiny", page_size=8, num_pages=128, max_batch=4,
                max_seq_len=128, prefill_chunk=16, use_pallas="never")
    base.update(kw)
    return EngineConfig(**base)


@pytest.mark.slow
def test_stream_inject_bit_identity(tiny_setup):
    """Chunked/overlapped streaming decode must be token-identical to the
    unified engine AND the whole-bundle arm — over a clean link and over
    a reordering, duplicating slow link."""
    import jax  # noqa: F401

    from rbg_tpu.engine import Engine, SamplingParams
    from rbg_tpu.engine.pd import PDStreamPair
    from rbg_tpu.obs.metrics import REGISTRY
    from rbg_tpu.obs import names as obs_names

    cfg, params = tiny_setup
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in (9, 25, 14, 40)]
    sp = SamplingParams(max_new_tokens=8)
    expect = Engine(ecfg(enable_radix_cache=False),
                    params=params).generate(prompts, sp)

    clean = PDStreamPair(ecfg(), params=params,
                         transport=InProcTransport())
    assert clean.generate(prompts, sp, stream=True) == expect
    assert clean.generate(prompts, sp, stream=False) == expect
    assert clean.decode.metrics["streams_in"] == 8
    # pd_lock hold-time histogram populated by the commits.
    assert REGISTRY.quantile(obs_names.PD_LOCK_HOLD_SECONDS, 0.5,
                             lock="pd_commit") is not None

    lossy = PDStreamPair(ecfg(), params=params,
                         transport=SlowLossyTransport(
                             InProcTransport(), delay_s=0.002,
                             reorder_window=3, dup_rate=0.4, seed=2))
    assert lossy.generate(prompts, sp, stream=True) == expect


@pytest.mark.slow
def test_stream_truncation_retries_token_exact(tiny_setup):
    from rbg_tpu.engine import SamplingParams
    from rbg_tpu.engine.pd import PDStreamPair

    cfg, params = tiny_setup
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, cfg.vocab_size, size=30).tolist()
    sp = SamplingParams(max_new_tokens=6)
    ref = PDStreamPair(ecfg(), params=params,
                       transport=InProcTransport())
    expect = ref.generate_one(prompt, sp, stream=True)["tokens"]

    link = SlowLossyTransport(InProcTransport(), delay_s=0.0,
                              truncate_nth_stream=0,
                              truncate_after_bytes=1 << 10)
    pair = PDStreamPair(ecfg(), params=params, transport=link)
    r = pair.generate_one(prompt, sp, stream=True, max_retries=2)
    assert r["retries"] >= 1            # the first stream was cut
    assert r["tokens"] == expect        # retry is token-exact
    # Abandoned stream recycled its pages: everything freed after decode.
    assert pair.decode.engine.allocator.free_pages == 127


@pytest.mark.slow
def test_decode_service_streaming_admission(tiny_setup):
    """DecodeService admits a pushed stream at coverage (loop-thread
    commits), decode runs under continuous batching, and the pending's
    first decode step stamps the receiver (kv_stream_overlap input)."""
    from rbg_tpu.engine import SamplingParams
    from rbg_tpu.engine.pd import PrefillWorker, new_stream_id
    from rbg_tpu.engine.service import DecodeService

    cfg, params = tiny_setup
    rng = np.random.RandomState(9)
    prompt = rng.randint(0, cfg.vocab_size, size=20).tolist()
    sp = SamplingParams(max_new_tokens=5)
    pf = PrefillWorker(ecfg(), params=params)
    svc = DecodeService(ecfg(), params=params)
    try:
        tr = SlowLossyTransport(InProcTransport(), delay_s=0.01)
        rx = svc.kv_streams.get_or_create(new_stream_id())
        svc.watch_stream(rx)
        t = threading.Thread(target=rx.pump, args=(tr,), daemon=True)
        t.start()
        res = pf.prefill_stream(prompt, sp, transport=tr, peer="",
                                stream_id=rx.stream_id)
        rx.wait_ready(30.0)
        pending = svc.submit_stream(rx, sp)
        toks = [res.first_token] + svc.wait(pending, 60.0)
        assert len(toks) == 5
        assert res.wait(10.0) and res.error() is None
        t.join(10.0)
        assert rx.t_first_step is not None and rx.t_fin is not None
    finally:
        svc.stop()


@pytest.mark.slow
def test_router_kv_stream_e2e_matches_bundle_path(tmp_path):
    """Cross-process acceptance: router + prefill + decode servers with
    chunked KV streaming produce the SAME tokens as the whole-bundle wire
    path, and the router's health shows the stream was used."""
    import json
    import os
    import socket
    import subprocess
    import sys

    from rbg_tpu.engine.protocol import request_once

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    env = {k: v for k, v in os.environ.items()
           if k not in ("RBG_SERVE_PORT", "RBG_PORT_SERVE")}
    env["JAX_PLATFORMS"] = "cpu"
    prompt = [1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12]

    def run_group(kv_stream):
        pport, dport, rport = free_port(), free_port(), free_port()
        procs = [subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.server", "--model",
             "tiny", "--mode", mode, "--port", str(port), "--max-batch",
             "2", "--num-pages", "128", "--max-seq-len", "256",
             "--prefill-chunk", "16", "--page-size", "8",
             "--use-pallas", "never", "--kv-stream", kv_stream],
            env=env) for mode, port in (("prefill", pport),
                                        ("decode", dport))]
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.router", "--port",
             str(rport), "--kv-stream", kv_stream, "--backends",
             json.dumps({"prefill": [f"127.0.0.1:{pport}"],
                         "decode": [f"127.0.0.1:{dport}"]})], env=env))
        try:
            for port in (pport, dport, rport):
                deadline = time.monotonic() + 240
                while time.monotonic() < deadline:
                    try:
                        h, _, _ = request_once(f"127.0.0.1:{port}",
                                               {"op": "health"}, timeout=2)
                        if h and h.get("ok"):
                            break
                    except OSError:
                        pass
                    time.sleep(0.5)
                else:
                    raise AssertionError(f"port {port} never ready")
            resp, _, _ = request_once(
                f"127.0.0.1:{rport}",
                {"op": "generate", "prompt": prompt,
                 "max_new_tokens": 6}, timeout=240)
            assert "tokens" in resp, resp
            h, _, _ = request_once(f"127.0.0.1:{rport}",
                                   {"op": "health"}, timeout=5)
            return resp["tokens"], h["metrics"]
        finally:
            for p in procs:
                p.terminate()
            for p in procs:
                p.wait(timeout=15)

    streamed, m_stream = run_group("auto")
    bundled, m_bundle = run_group("off")
    assert streamed == bundled          # bit-identical across wire paths
    assert m_stream["kv_stream_routed"] == 1
    assert m_bundle["kv_stream_routed"] == 0
    assert m_bundle["kv_bytes_routed"] > 0   # bundle path moved KV bytes


# ---- layer-sliced decode admission (round 16) ------------------------------


@pytest.mark.slow
def test_layer_sliced_admission_bit_identity_clean(tiny_setup):
    """admit_layers=1 over a paced link: the decode side admits at
    layer-1 coverage and runs the first decode step as a layer-window
    chain under the transfer tail — token streams stay bit-identical to
    the full-coverage path, the layer-admit metrics populate, and
    admit-lead grows (full coverage was still pending at admission)."""
    from rbg_tpu.engine import SamplingParams
    from rbg_tpu.engine.pd import PDStreamPair
    from rbg_tpu.obs import names as obs_names
    from rbg_tpu.obs.metrics import REGISTRY

    cfg, params = tiny_setup
    rng = np.random.RandomState(11)
    prompt = rng.randint(0, cfg.vocab_size, size=37).tolist()
    sp = SamplingParams(max_new_tokens=8, temperature=0.7, seed=123)
    paced = lambda: FakeICITransport(bytes_per_s=2e5, latency_s=0.0005)

    full = PDStreamPair(ecfg(), params=params, transport=paced(),
                        layer_split=1, admit_layers=0)
    expect = full.generate_one(prompt, sp)
    assert expect["layers_at_admit"] is None   # plain path never stamps

    admits0 = REGISTRY.counter(obs_names.KVT_LAYER_ADMIT_TOTAL)
    sliced = PDStreamPair(ecfg(), params=params, transport=paced(),
                          layer_split=1, admit_layers=1)
    got = sliced.generate_one(prompt, sp)
    assert got["tokens"] == expect["tokens"]
    # Engaged early: admitted below full layer coverage...
    assert got["layers_at_admit"] is not None
    assert got["layers_at_admit"] < got["total_layers"]
    assert REGISTRY.counter(obs_names.KVT_LAYER_ADMIT_TOTAL) > admits0
    # ...and the admit-lead histogram recorded the overlap (full
    # coverage landed strictly after layer-ready).
    assert REGISTRY.quantile(obs_names.KVT_LAYER_ADMIT_LEAD_SECONDS,
                             0.5) is not None
    assert REGISTRY.quantile(obs_names.KVT_LAYER_ADMIT_COVERAGE_LAYERS,
                             0.5) is not None
    # Pages fully recycled after decode on both pairs.
    assert sliced.decode.engine.allocator.free_pages == 127


@pytest.mark.slow
def test_layer_sliced_admission_lossy_bit_identity(tiny_setup):
    """Layer-sliced admission over a reordering, duplicating paced link:
    retransmitted slabs below the dispatch watermark are clipped (they
    must not zero the decode token's freshly-written KV) — output stays
    bit-identical across fault seeds."""
    from rbg_tpu.engine import SamplingParams
    from rbg_tpu.engine.pd import PDStreamPair
    from rbg_tpu.kvtransfer.transport import FakeICITransport

    cfg, params = tiny_setup
    rng = np.random.RandomState(13)
    prompt = rng.randint(0, cfg.vocab_size, size=37).tolist()
    sp = SamplingParams(max_new_tokens=8, temperature=0.7, seed=321)
    ref = PDStreamPair(ecfg(), params=params, transport=InProcTransport(),
                       layer_split=1)
    expect = ref.generate_one(prompt, sp)["tokens"]

    engaged = 0
    for seed in range(3):
        lossy = SlowLossyTransport(
            FakeICITransport(bytes_per_s=2e5, latency_s=0.0005),
            delay_s=0.001, reorder_window=2, dup_rate=0.5, seed=seed)
        pair = PDStreamPair(ecfg(), params=params, transport=lossy,
                            layer_split=1, admit_layers=1)
        r = pair.generate_one(prompt, sp)
        assert r["tokens"] == expect, f"fault seed {seed} diverged"
        if r["layers_at_admit"] is not None:
            engaged += 1
    assert engaged >= 1   # the drill actually exercised the sliced path


def test_layer_sliced_needs_layer_split_to_engage(tiny_setup):
    """layer_split=0 ships all layers per chunk, so layer coverage and
    full coverage land together — admit_layers degrades to the plain
    full-coverage path (correct output, no layer-admit stamp)."""
    from rbg_tpu.engine import SamplingParams
    from rbg_tpu.engine.pd import PDStreamPair

    cfg, params = tiny_setup
    prompt = list(range(2, 25))
    sp = SamplingParams(max_new_tokens=4)
    ref = PDStreamPair(ecfg(), params=params, transport=InProcTransport(),
                       layer_split=0)
    expect = ref.generate_one(prompt, sp)["tokens"]
    pair = PDStreamPair(ecfg(), params=params, transport=InProcTransport(),
                        layer_split=0, admit_layers=1)
    r = pair.generate_one(prompt, sp)
    assert r["tokens"] == expect


def test_warm_layer_sliced_covers_first_step_sampler(tiny_setup):
    """The jitwatch-caught warmer gap: warm_layer_sliced promises 'window
    programs, head, default sampler' — the sampler half must actually be
    compiled, or the first layer-sliced token pays a mid-serving compile
    (the kvstream drill's zero_unwarmed_compiles invariant)."""
    from rbg_tpu.engine.pd import PDStreamPair

    cfg, params = tiny_setup
    pair = PDStreamPair(ecfg(), params=params,
                        transport=FakeICITransport(bytes_per_s=1e9,
                                                   latency_s=0.0),
                        layer_split=1, admit_layers=1)
    assert pair.decode.engine._samplers == {}
    pair.decode.warm_layer_sliced(1)
    samplers = pair.decode.engine._samplers
    assert (False, False, False) in samplers, sorted(samplers)
    assert (False, False, True) in samplers, sorted(samplers)


def test_pd_device_fetches_are_batched_pairs(tiny_setup, monkeypatch):
    """_export_pages fetches both page slabs in ONE jax.device_get (a
    2-tuple pytree), and the engines' emission fetches are the same
    batched-pair form — no sequential per-array syncs anywhere on the
    stream path."""
    import jax as _jax

    from rbg_tpu.engine import SamplingParams
    from rbg_tpu.engine.pd import PDStreamPair

    cfg, params = tiny_setup
    calls = []
    real = _jax.device_get

    def counting(x):
        calls.append(x)
        return real(x)

    monkeypatch.setattr(_jax, "device_get", counting)
    pair = PDStreamPair(ecfg(), params=params,
                        transport=FakeICITransport(bytes_per_s=1e9,
                                                   latency_s=0.0))
    out = pair.generate_one([3, 1, 4, 1, 5, 9, 2, 6],
                            SamplingParams(max_new_tokens=4))
    assert len(out["tokens"]) == 4
    assert calls, "the export/emission fetches must use jax.device_get"
    assert all(isinstance(c, tuple) and len(c) == 2 for c in calls), (
        [type(c) for c in calls])
