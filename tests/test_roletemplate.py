"""RoleTemplate (KEP-8): shared pod templates referenced by roles."""

from rbg_tpu.api.group import RoleSpec, RoleTemplate
from rbg_tpu.api.pod import Container, PodTemplate
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes


def test_template_ref_resolution():
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=2)
    with plane:
        tmpl = RoleTemplate()
        tmpl.metadata.name = "std-engine"
        tmpl.template = PodTemplate(containers=[Container(
            name="engine", image="engine:std", command=["serve"])])
        plane.apply(tmpl)

        # Two roles share the template; neither repeats the pod spec.
        plane.apply(make_group(
            "shared",
            RoleSpec(name="a", replicas=1, template_ref="std-engine"),
            RoleSpec(name="b", replicas=1, template_ref="std-engine"),
        ))
        plane.wait_group_ready("shared", timeout=20)
        pods = plane.store.list("Pod", namespace="default")
        assert len(pods) == 2
        assert all(p.template.containers[0].image == "engine:std" for p in pods)


def test_missing_template_ref_reports_event():
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=1)
    with plane:
        plane.apply(make_group(
            "ghost", RoleSpec(name="a", replicas=1, template_ref="nope")))

        def event_recorded():
            g = plane.store.get("RoleBasedGroup", "default", "ghost")
            return any(r == "MissingRoleTemplate"
                       for (_, _, r, _) in plane.store.events_for(g))

        plane.wait_for(event_recorded, timeout=10, desc="missing-template event")
