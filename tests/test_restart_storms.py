"""Restart-policy depth tests (reference analog:
``restart_policy_test.go`` 1,335 LoC + the storm-suppression machinery in
``sync/instance_scale.go:337-525`` — VERDICT r1 missing#6 test depth).

Covers: exponential backoff progression and cap, decay-window reset, blast
isolation across instances, Ignore-annotation confinement under repeated
failures, and restart-cycle idempotence under concurrent failures.
"""

import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RestartPolicyConfig
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        yield p


def _inst(plane, name=None):
    insts = plane.store.list("RoleInstance", namespace="default")
    if name is None:
        assert len(insts) == 1
        return insts[0]
    return next(i for i in insts if i.metadata.name == name)


def _fail_and_wait_restart(plane, expect_count, timeout=20):
    """Kill the current pod; wait for the gang recreate to finish with the
    expected cumulative restart count. Returns (restart wall time, status)."""
    pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
    uids = {p.metadata.uid for p in pods}
    t0 = time.perf_counter()
    plane.kubelet.fail_pod("default", pods[0].metadata.name)

    def done():
        inst = _inst(plane)
        ps = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        ok = (inst.status.restart_count == expect_count
              and ps and uids.isdisjoint({p.metadata.uid for p in ps})
              and all(p.running_ready for p in ps))
        return inst if ok else None

    inst = plane.wait_for(done, timeout=timeout,
                          desc=f"restart #{expect_count}")
    return time.perf_counter() - t0, inst


def test_backoff_progression_and_cap(plane):
    """Delays grow min(base*2^(n-1), max): with base 0.4 / max 0.8 the gaps
    are ~0, ~0.4, ~0.8, ~0.8 (reference backoff math,
    instance_scale.go:482-506)."""
    role = simple_role("w", replicas=1)
    role.restart_policy = RestartPolicyConfig(
        base_delay_seconds=0.4, max_delay_seconds=0.8, window_seconds=600)
    plane.apply(make_group("bo", role))
    plane.wait_group_ready("bo")

    gaps = []
    for n in range(1, 5):
        dt, inst = _fail_and_wait_restart(plane, n)
        gaps.append(dt)
        assert inst.status.restart_count == n
    # First restart is immediate; later ones honor the growing delay.
    assert gaps[0] < 0.4, f"first restart should be immediate, took {gaps[0]:.2f}s"
    assert gaps[1] >= 0.35, f"second restart ignored base delay ({gaps[1]:.2f}s)"
    assert gaps[2] >= 0.7, f"third restart ignored 2x backoff ({gaps[2]:.2f}s)"
    # Cap: the fourth delay must NOT grow to 1.6s (max_delay 0.8 + slack).
    assert 0.7 <= gaps[3] < 1.6, f"fourth restart not capped ({gaps[3]:.2f}s)"


def test_decay_window_resets_backoff(plane):
    """Stable for a full window => the next failure counts as #1 again
    (reference: restart-count decay)."""
    role = simple_role("w", replicas=1)
    role.restart_policy = RestartPolicyConfig(
        base_delay_seconds=0.3, max_delay_seconds=5.0, window_seconds=1.0)
    plane.apply(make_group("dk", role))
    plane.wait_group_ready("dk")

    _fail_and_wait_restart(plane, 1)
    _fail_and_wait_restart(plane, 2)
    # Ride out the decay window while healthy.
    time.sleep(1.2)
    dt, inst = _fail_and_wait_restart(plane, 1)   # count RESET to 1
    assert inst.status.restart_count == 1
    assert dt < 0.3, f"post-decay restart should be immediate ({dt:.2f}s)"


def test_blast_isolation_across_instances(plane):
    """A storm on one instance never touches its siblings' pods
    (reference: only the affected Instance recreates)."""
    role = simple_role("w", replicas=3)
    role.restart_policy = RestartPolicyConfig(
        base_delay_seconds=0.01, max_delay_seconds=0.05, window_seconds=600)
    plane.apply(make_group("bi", role))
    plane.wait_group_ready("bi")

    pods = [p for p in plane.store.list("Pod", namespace="default")]
    victim_inst = pods[0].metadata.labels[C.LABEL_INSTANCE_NAME]
    sibling_uids = {p.metadata.uid for p in pods
                    if p.metadata.labels[C.LABEL_INSTANCE_NAME] != victim_inst}

    # Three failure cycles against the same instance.
    for n in range(1, 4):
        vp = plane.wait_for(
            lambda: [p for p in plane.store.list("Pod", namespace="default")
                     if p.running_ready
                     and p.metadata.labels[C.LABEL_INSTANCE_NAME] == victim_inst]
            or None,
            timeout=20, desc="victim pod running")
        plane.kubelet.fail_pod("default", vp[0].metadata.name)
        plane.wait_for(
            lambda n=n: _inst(plane, victim_inst).status.restart_count == n
            and all(p.running_ready for p in plane.store.list(
                "Pod", namespace="default",
                selector={C.LABEL_INSTANCE_NAME: victim_inst}) if p.active),
            timeout=20, desc=f"victim restart #{n}")

    survivors = {p.metadata.uid for p in plane.store.list("Pod", namespace="default")
                 if p.metadata.labels[C.LABEL_INSTANCE_NAME] != victim_inst}
    assert survivors == sibling_uids, "sibling pods were recreated"
    for i in plane.store.list("RoleInstance", namespace="default"):
        if i.metadata.name != victim_inst:
            assert i.status.restart_count == 0
    plane.wait_group_ready("bi")


def test_ignored_component_storm_never_gang_restarts(plane):
    """Repeated failures of an Ignore-annotated component stay pod-level
    forever — the gang (and its restart budget) is untouched."""
    from rbg_tpu.api.group import ComponentSpec, PatternType
    from rbg_tpu.api.pod import PodTemplate
    from rbg_tpu.testutil import simple_container

    role = simple_role("mix", replicas=1)
    role.pattern = PatternType.CUSTOM_COMPONENTS
    role.components = [
        ComponentSpec(name="engine", size=1,
                      template=PodTemplate(containers=[simple_container()])),
        ComponentSpec(name="cache", size=1,
                      template=PodTemplate(
                          containers=[simple_container(name="cache")],
                          annotations={C.ANN_RESTART_TRIGGER_POLICY: "Ignore"})),
    ]
    plane.apply(make_group("ig", role))
    plane.wait_group_ready("ig")
    engine_uid = next(
        p.metadata.uid for p in plane.store.list("Pod", namespace="default")
        if p.metadata.labels[C.LABEL_COMPONENT_NAME] == "engine")

    for round_ in range(3):
        cache = next(
            p for p in plane.store.list("Pod", namespace="default")
            if p.metadata.labels[C.LABEL_COMPONENT_NAME] == "cache" and p.active)
        plane.kubelet.fail_pod("default", cache.metadata.name)
        plane.wait_for(
            lambda old=cache.metadata.uid: any(
                p.metadata.uid != old and p.running_ready
                for p in plane.store.list("Pod", namespace="default")
                if p.metadata.labels[C.LABEL_COMPONENT_NAME] == "cache"),
            timeout=20, desc=f"cache replaced (round {round_})")

    engine = next(p for p in plane.store.list("Pod", namespace="default")
                  if p.metadata.labels[C.LABEL_COMPONENT_NAME] == "engine")
    assert engine.metadata.uid == engine_uid
    assert _inst(plane).status.restart_count == 0
    plane.wait_group_ready("ig")


def test_concurrent_failures_one_cycle(plane):
    """Both pods of a 2-pod gang failing 'simultaneously' must produce ONE
    restart cycle, not two (Restarting-phase CAS; reference: the concurrent-
    cycle guard, instance_scale.go:337-525)."""
    from rbg_tpu.testutil import tpu_leaderworker_role
    role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
    role.restart_policy = RestartPolicyConfig(
        base_delay_seconds=0.01, max_delay_seconds=0.05, window_seconds=600)
    plane.apply(make_group("cc", role))
    plane.wait_group_ready("cc")
    pods = [p for p in plane.store.list("Pod", namespace="default")]
    assert len(pods) == 2
    for p in pods:
        plane.kubelet.fail_pod("default", p.metadata.name)

    def recovered():
        inst = _inst(plane)
        ps = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return (len(ps) == 2 and all(p.running_ready for p in ps)
                and inst.status.phase == "Running") or None

    plane.wait_for(recovered, timeout=20, desc="gang recovered")
    assert _inst(plane).status.restart_count == 1
    plane.wait_group_ready("cc")
