"""Ragged unified prefill/decode step + continuous batching: bit-identity
against the split paths (pure prefill, pure decode, mixed joins; greedy and
seeded-sampled), join accounting, window shortening, and the prefill-chunk
boundary / pending-window seq_len invariant."""

import jax
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.models import get_config, init_params
from rbg_tpu.models.llama import prefill_and_decode_greedy


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def make_engine(params, ragged="auto", **kw):
    defaults = dict(model="tiny", page_size=8, num_pages=64, max_batch=4,
                    max_seq_len=128, prefill_chunk=16,
                    enable_radix_cache=False, use_pallas="never",
                    multi_step=4)
    defaults.update(kw)
    return Engine(EngineConfig(ragged=ragged, **defaults), params=params)


def drain(eng, outputs, ids):
    while eng.has_work():
        for ev in eng.step():
            if ev.request_id in outputs:
                outputs[ev.request_id].append(ev.token)
    return [outputs[i] for i in ids]


def run_batch(params, ragged, prompts, sps, stagger_after=None, **kw):
    """Drive a batch to completion; ``stagger_after`` splits the adds
    around a few steps so late rows JOIN a decoding batch."""
    eng = make_engine(params, ragged=ragged, **kw)
    cut = stagger_after if stagger_after is not None else len(prompts)
    ids = [eng.add_request(p, s) for p, s in zip(prompts[:cut], sps[:cut])]
    outputs = {i: [] for i in ids}
    if stagger_after is not None:
        for _ in range(3):
            for ev in eng.step():
                outputs[ev.request_id].append(ev.token)
        for p, s in zip(prompts[cut:], sps[cut:]):
            i = eng.add_request(p, s)
            ids.append(i)
            outputs[i] = []
    return drain(eng, outputs, ids), eng


def _prompts(cfg, sizes, seed=1):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, cfg.vocab_size, size=n).tolist() for n in sizes]


def test_pure_prefill_bit_identity(tiny_setup):
    """max_new_tokens=1: the run is all prefill — the packed ragged
    dispatch must reproduce the split prefill path exactly."""
    cfg, params = tiny_setup
    prompts = _prompts(cfg, (4, 23, 9, 17))
    sps = [SamplingParams(max_new_tokens=1)] * 4
    got, eng = run_batch(params, "auto", prompts, sps)
    ref, _ = run_batch(params, "off", prompts, sps)
    assert got == ref
    assert eng.metrics["unified_steps"] > 0


@pytest.mark.slow
def test_pure_decode_keeps_fused_scan(tiny_setup):
    """Once every row is decoding, the engine must return to the fused
    multi-step scan (unified steps only cover the prefill-mixed phase) —
    and the output still matches the dense reference."""
    cfg, params = tiny_setup
    prompt = [5, 9, 13, 2, 7, 11, 3, 1, 8, 4]
    out = prefill_and_decode_greedy(
        params, cfg, np.asarray([prompt], np.int32), 8)
    expect = [int(t) for t in np.asarray(out)[0]]
    eng = make_engine(params, ragged="auto")
    got = eng.generate([prompt], SamplingParams(max_new_tokens=8))[0]
    assert got == expect
    # one chunk of prefill → exactly one unified step; the rest decoded
    # in fused windows
    assert eng.metrics["unified_steps"] == 1
    assert eng.metrics["decode_tokens"] > 4


@pytest.mark.slow
def test_mixed_join_bit_identity_greedy(tiny_setup):
    """Rows joining a decoding batch mid-stream (continuous admission)
    produce bit-identical streams to the split path for every row."""
    cfg, params = tiny_setup
    prompts = _prompts(cfg, (4, 23, 9, 17))
    sps = [SamplingParams(max_new_tokens=6)] * 4
    got, eng = run_batch(params, "auto", prompts, sps, stagger_after=2)
    ref, _ = run_batch(params, "off", prompts, sps, stagger_after=2)
    assert got == ref
    assert eng.metrics["unified_steps"] >= 2  # initial prefill + the join
    assert eng.metrics["joins"] == 4


@pytest.mark.slow
def test_mixed_join_bit_identity_sampled(tiny_setup):
    """Seeded sampling + penalties + logprobs across a mid-decode join:
    per-row keys are position-keyed, so the ragged path must replay the
    identical random stream."""
    cfg, params = tiny_setup
    prompts = _prompts(cfg, (4, 23, 9, 17), seed=3)
    sps = [SamplingParams(max_new_tokens=8, temperature=0.8, top_k=20,
                          seed=i, logprobs=True,
                          repetition_penalty=1.2 if i % 2 else 1.0)
           for i in range(4)]
    got, _ = run_batch(params, "auto", prompts, sps, stagger_after=2)
    ref, _ = run_batch(params, "off", prompts, sps, stagger_after=2)
    assert got == ref


@pytest.mark.slow
def test_mixed_join_bit_identity_int8_pool(tiny_setup):
    cfg, params = tiny_setup
    prompts = _prompts(cfg, (4, 23, 9), seed=5)
    sps = [SamplingParams(max_new_tokens=6)] * 3
    got, _ = run_batch(params, "auto", prompts, sps, stagger_after=1,
                       kv_dtype="int8")
    ref, _ = run_batch(params, "off", prompts, sps, stagger_after=1,
                       kv_dtype="int8")
    assert got == ref


@pytest.mark.slow
def test_grammar_row_joins_mid_decode(tiny_setup):
    """A regex-constrained row joining plain decoding rows rides the
    unified step on host-side masks — identical to the split path."""
    from rbg_tpu.engine.tokenizer import ByteTokenizer
    cfg, params = tiny_setup
    tok = ByteTokenizer()

    def run(ragged):
        eng = make_engine(params, ragged=ragged)
        eng.enable_json_grammar(tok)
        plain = eng.add_request(
            _prompts(cfg, (12,), seed=7)[0],
            SamplingParams(max_new_tokens=10))
        outputs = {plain: []}
        for _ in range(2):
            for ev in eng.step():
                outputs[ev.request_id].append(ev.token)
        gr = eng.add_request(
            tok.encode("p:", add_bos=False),
            SamplingParams(max_new_tokens=8, temperature=0.7, seed=1,
                           regex="[ab]{8}", stop_token=tok.eos_id))
        outputs[gr] = []
        return drain(eng, outputs, [plain, gr])

    assert run("auto") == run("off")


@pytest.mark.slow
def test_preemption_under_page_pressure_ragged(tiny_setup):
    """Page exhaustion mid-mix preempts the youngest and still completes
    every stream — identically to the split path."""
    cfg, params = tiny_setup
    prompts = _prompts(cfg, (20, 22, 24), seed=9)
    sps = [SamplingParams(max_new_tokens=12)] * 3
    got, eng = run_batch(params, "auto", prompts, sps, num_pages=16,
                        max_batch=3)
    ref, _ = run_batch(params, "off", prompts, sps, num_pages=16,
                       max_batch=3)
    assert got == ref
    assert all(len(o) == 12 for o in got)


@pytest.mark.slow
def test_seq_len_accounting_after_pending_drain(tiny_setup):
    """Regression for the prefill-chunk boundary invariant (the seq_len
    double-count the runtime-LoRA drain comment protects): a join forces
    a unified step while a fused window's tokens are still PENDING — the
    drain must reconcile seq_len with the emitted stream, and after any
    step with no device window in flight every running row satisfies
    seq_len == total_len - 1 (last_token not yet written)."""
    cfg, params = tiny_setup
    eng = make_engine(params, ragged="auto", multi_step=4)
    first = eng.add_request(_prompts(cfg, (10,), seed=11)[0],
                            SamplingParams(max_new_tokens=20))
    outputs = {first: []}
    # prefill + a couple of fused windows so a pending emission lag exists
    for _ in range(3):
        for ev in eng.step():
            outputs[ev.request_id].append(ev.token)
    assert eng._dec is not None and eng._dec["pending"] is not None
    joiner = eng.add_request(_prompts(cfg, (21,), seed=12)[0],
                             SamplingParams(max_new_tokens=20))
    outputs[joiner] = []
    for ev in eng.step():                  # unified: drains pending first
        outputs[ev.request_id].append(ev.token)
    assert eng._dec is None                # window consumed, not discarded
    for r in eng.running:
        if r.state == "running":
            assert r.seq_len == r.total_len - 1
    got = drain(eng, outputs, [first, joiner])
    # no token lost or duplicated across the drain: full streams, and
    # identical to the split path end to end
    assert [len(o) for o in got] == [20, 20]

    def split_run():
        eng2 = make_engine(params, ragged="off", multi_step=4)
        a = eng2.add_request(_prompts(cfg, (10,), seed=11)[0],
                             SamplingParams(max_new_tokens=20))
        outs = {a: []}
        for _ in range(3):
            for ev in eng2.step():
                outs[ev.request_id].append(ev.token)
        b = eng2.add_request(_prompts(cfg, (21,), seed=12)[0],
                             SamplingParams(max_new_tokens=20))
        outs[b] = []
        return drain(eng2, outs, [a, b])

    assert got == split_run()


@pytest.mark.slow
def test_join_accounting_metrics(tiny_setup):
    """Admissions record joins and (with free capacity) zero excess wait;
    page-blocked queueing counts as availability wait, not excess."""
    cfg, params = tiny_setup
    eng = make_engine(params, ragged="auto", num_pages=16, max_batch=4)
    sps = SamplingParams(max_new_tokens=8)
    for p in _prompts(cfg, (20, 22, 24, 26), seed=13):
        eng.add_request(p, sps)
    while eng.has_work():
        eng.step()
    m = eng.metrics
    assert m["joins"] >= 4            # preempted rows re-join
    assert m["join_excess_steps_max"] <= 1
    assert len(eng.last_join_waits) == m["joins"]


def test_decode_window_shortens_for_joins(tiny_setup):
    cfg, params = tiny_setup
    eng = make_engine(params, ragged="auto", multi_step=8, max_batch=4)
    rid = eng.add_request(_prompts(cfg, (8,), seed=15)[0],
                          SamplingParams(max_new_tokens=4))
    assert eng._decode_window() == 1          # queued request, free slot
    eng.step()                                # admit + prefill it
    assert eng._decode_window() == 8          # no waiting work
    eng.join_hint = True
    assert eng._decode_window() == 1          # free slot + hinted join
    eng.join_hint = False
    eng.cancel_request(rid)

    off = make_engine(params, ragged="off", multi_step=8)
    off.join_hint = True
    assert off._decode_window() == 8          # baseline keeps full windows


def test_service_publishes_join_and_occupancy_metrics(tiny_setup):
    from rbg_tpu.engine.service import EngineService
    from rbg_tpu.obs import names
    from rbg_tpu.obs.metrics import REGISTRY

    _, params = tiny_setup
    svc = EngineService(
        EngineConfig(model="tiny", page_size=8, num_pages=64, max_batch=2,
                     max_seq_len=128, prefill_chunk=16, use_pallas="never",
                     enable_radix_cache=False, decode_buckets=(2,)),
        params=params)
    try:
        svc.submit([1, 2, 3, 4], SamplingParams(max_new_tokens=4))
        assert svc.engine.metrics["joins"] >= 1
        assert not svc.engine.last_join_waits    # drained by the loop
        assert REGISTRY.quantile(names.SERVING_JOIN_LATENCY_SECONDS, 0.5,
                                 service="engineservice") is not None
        assert REGISTRY.quantile(names.SERVING_BATCH_OCCUPANCY, 0.5,
                                 service="engineservice") is not None
    finally:
        svc.stop()


# ---- MLA rides the unified step (round 16) ----


@pytest.fixture(scope="module")
def tiny_mla_setup():
    cfg = get_config("tiny-mla")
    params = init_params(cfg, jax.random.key(2))
    return cfg, params


def run_mla_batch(params, ragged, prompts, sps, stagger_after=None, **kw):
    return run_batch(params, ragged, prompts, sps,
                     stagger_after=stagger_after, model="tiny-mla", **kw)


def test_mla_unified_step_bit_identity(tiny_mla_setup):
    """MLA models join the unified prefill/decode step (the mcfg.mla
    exclusion fell in round 16): packed ragged latent attention must
    reproduce the phase-split MLA path exactly."""
    cfg, params = tiny_mla_setup
    prompts = _prompts(cfg, (4, 23, 9), seed=7)
    sps = [SamplingParams(max_new_tokens=4)] * 3
    got, eng = run_mla_batch(params, "auto", prompts, sps)
    ref, off = run_mla_batch(params, "off", prompts, sps)
    assert got == ref
    assert eng.metrics["unified_steps"] > 0
    assert off.metrics["unified_steps"] == 0


@pytest.mark.slow
def test_mla_unified_step_staggered_joins(tiny_mla_setup):
    """Late MLA rows joining a decoding batch mid-stream — the ragged
    pack carries a decode row and a prefill chunk through the latent
    kernel in one dispatch — stay bit-identical to phase-split."""
    cfg, params = tiny_mla_setup
    prompts = _prompts(cfg, (4, 23, 9, 17), seed=8)
    sps = [SamplingParams(max_new_tokens=6, temperature=0.8, top_k=20,
                          seed=i, logprobs=bool(i % 2)) for i in range(4)]
    got, eng = run_mla_batch(params, "auto", prompts, sps, stagger_after=2)
    ref, _ = run_mla_batch(params, "off", prompts, sps, stagger_after=2)
    assert got == ref
    assert eng.metrics["unified_steps"] >= 2
    assert eng.metrics["joins"] == 4


@pytest.mark.slow
def test_mla_unified_step_int8_latent_pool(tiny_mla_setup):
    """int8 latent pools through the ragged MLA path (scatter detour's
    _q reference on CPU) — identical to the phase-split int8 path."""
    cfg, params = tiny_mla_setup
    prompts = _prompts(cfg, (4, 23, 9), seed=9)
    sps = [SamplingParams(max_new_tokens=4)] * 3
    got, _ = run_mla_batch(params, "auto", prompts, sps, stagger_after=1,
                           kv_dtype="int8")
    ref, _ = run_mla_batch(params, "off", prompts, sps, stagger_after=1,
                           kv_dtype="int8")
    assert got == ref
