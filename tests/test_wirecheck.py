"""Runtime wire-contract sentry (utils/wirecheck.py): the arming matrix
(off by default / warn counts / strict raises / disarm restores the
codec seam), frame validation against the api/ops.py catalog on both
seam directions, kv-frame op tracking, and the seeded-violation drill a
stress report folds into ``wire_contract_clean``."""

import socket
import threading

import pytest

from rbg_tpu.engine import protocol
from rbg_tpu.utils import wirecheck


@pytest.fixture
def armed_warn(monkeypatch):
    monkeypatch.setenv(wirecheck.ENV_VAR, "warn")
    wirecheck.disarm()
    wirecheck.arm()
    yield wirecheck
    wirecheck.disarm()


@pytest.fixture
def armed_strict(monkeypatch):
    monkeypatch.setenv(wirecheck.ENV_VAR, "1")
    wirecheck.disarm()
    wirecheck.arm()
    yield wirecheck
    wirecheck.disarm()


def _pair():
    a, b = socket.socketpair()
    a.settimeout(5.0)
    b.settimeout(5.0)
    return a, b


# ---- arming matrix ----


def test_off_by_default_nothing_patched(monkeypatch):
    monkeypatch.delenv(wirecheck.ENV_VAR, raising=False)
    assert wirecheck.mode() == ""
    assert not wirecheck.enabled()
    # Importing the module patches nothing: the codec seam is pristine.
    assert not wirecheck.armed()
    assert protocol.send_msg.__name__ == "send_msg"
    assert protocol.recv_msg.__name__ == "recv_msg"


@pytest.mark.parametrize("val,expect", [
    ("1", "raise"), ("true", "raise"), ("warn", "warn"),
    ("0", ""), ("off", ""), ("", "")])
def test_env_mode_matrix(monkeypatch, val, expect):
    monkeypatch.setenv(wirecheck.ENV_VAR, val)
    assert wirecheck.mode() == expect


def test_warn_mode_counts_without_raising(armed_warn):
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "frobnicate"})     # unknown op: counted
        obj, _, _ = protocol.recv_msg(b)                # counted again on recv
        assert obj["op"] == "frobnicate"
    finally:
        a.close(); b.close()
    assert wirecheck.violations_by_key() == {"frobnicate/unknown_op": 2}
    assert wirecheck.counters()["rbg_wire_contract_violations_total"] == 2.0
    # The labeled metric counted too.
    from rbg_tpu.obs import names
    from rbg_tpu.obs.metrics import REGISTRY
    assert REGISTRY.counter(names.WIRE_CONTRACT_VIOLATIONS_TOTAL,
                            op="frobnicate", kind="unknown_op") >= 2


def test_strict_mode_raises_at_the_seam(armed_strict):
    a, b = _pair()
    try:
        with pytest.raises(wirecheck.WireContractError):
            protocol.send_msg(a, {"op": "frobnicate"})
        # The violating frame was never sent: the peer sees nothing.
        with pytest.raises(wirecheck.WireContractError):
            protocol.send_msg(a, {"op": "generate"})    # missing 'prompt'
    finally:
        a.close(); b.close()


def test_disarm_restores_codec_seam(monkeypatch):
    # Import a module-level from-importer BEFORE arming so its binding is
    # on record (a consumer imported after arm() binds the wrapper from
    # protocol instead — it degrades to passthrough on disarm, but its
    # identity is not restorable, so don't assert on that path).
    from rbg_tpu.engine import kvpool
    monkeypatch.setenv(wirecheck.ENV_VAR, "warn")
    wirecheck.disarm()
    orig_send, orig_recv = protocol.send_msg, protocol.recv_msg
    pre_send, pre_recv = kvpool.send_msg, kvpool.recv_msg
    wirecheck.arm()
    assert protocol.send_msg is not orig_send
    if pre_send is orig_send:
        # Consumer bound the original: patched alongside protocol.
        assert kvpool.send_msg is protocol.send_msg
        assert kvpool.recv_msg is protocol.recv_msg
    wirecheck.disarm()
    assert protocol.send_msg is orig_send
    assert protocol.recv_msg is orig_recv
    assert kvpool.send_msg is pre_send
    assert kvpool.recv_msg is pre_recv
    assert wirecheck.counters()["rbg_wire_frames_checked"] == 0.0


def test_arm_is_idempotent(armed_warn):
    patched = protocol.send_msg
    wirecheck.arm()
    assert protocol.send_msg is patched     # no double wrap


# ---- frame validation ----


def test_clean_request_reply_roundtrip(armed_warn):
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "generate", "prompt": [1, 2],
                              "timeout_s": 5})
        obj, _, _ = protocol.recv_msg(b)
        protocol.send_msg(b, {"tokens": [3], "ttft_s": 0.1, "done": True})
        resp, _, _ = protocol.recv_msg(a)
        assert resp["tokens"] == [3]
    finally:
        a.close(); b.close()
    assert wirecheck.violations() == []
    assert wirecheck.counters()["rbg_wire_frames_checked"] == 4.0


def test_undeclared_reply_field_flagged(armed_warn):
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "generate", "prompt": [1]})
        protocol.recv_msg(b)
        protocol.send_msg(b, {"tokens": [3], "addr": "10.0.0.1:1"})
        protocol.recv_msg(a)
    finally:
        a.close(); b.close()
    assert wirecheck.violations_by_key() == {
        "generate/undeclared_reply_field": 2}     # send seam + recv seam
    assert "addr" in wirecheck.violations()[0]


def test_underscore_reply_keys_exempt(armed_warn):
    """`_`-prefixed reply keys are debug plumbing (the router pops
    `_router_t_dispatch` before forwarding) — exempt, matching the lint
    rule."""
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "embed"})
        protocol.recv_msg(b)
        protocol.send_msg(b, {"embedding": [0.1], "_router_t_dispatch": 1.0})
        protocol.recv_msg(a)
    finally:
        a.close(); b.close()
    assert wirecheck.violations() == []


def test_undeclared_error_code_flagged(armed_warn):
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "health"})
        protocol.recv_msg(b)
        # health declares no error codes: a shed frame on it is drift.
        protocol.send_msg(b, {"error": "busy", "code": "overloaded"})
        protocol.recv_msg(a)
    finally:
        a.close(); b.close()
    assert wirecheck.violations_by_key() == {
        "health/undeclared_error_code": 2}


def test_kv_frames_update_socket_op(armed_warn):
    """kv_* frames retarget the socket's op, so the bare `{ok, bytes}`
    FIN ack validates against kv_fin's declared response — not against
    the generate/prefill op that opened the connection."""
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "kv_meta", "stream_id": "s", "seq": 0,
                              "prompt": [1], "n_pages": 1, "page_size": 8,
                              "layers": 2, "k_page_shape": [1],
                              "v_page_shape": [1], "dtype": "float32"})
        protocol.recv_msg(b)
        protocol.send_msg(a, {"op": "kv_fin", "stream_id": "s",
                              "n_chunks": 0})
        protocol.recv_msg(b)
        protocol.send_msg(b, {"ok": True, "bytes": 128})
        resp, _, _ = protocol.recv_msg(a)
        assert resp["ok"] is True
    finally:
        a.close(); b.close()
    assert wirecheck.violations() == []


def test_binary_framing_fields_tolerated(armed_warn):
    """send_msg adds bin_k/bin_v to the header after validation; the recv
    side sees them on the frame and must not flag them."""
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "prefill", "prompt": [1]})
        protocol.recv_msg(b)
        protocol.send_msg(b, {"prompt": [1], "first_token": 2,
                              "shape": [1, 1], "dtype": "float32"},
                          k_bytes=b"\x00" * 4, v_bytes=b"\x00" * 4)
        resp, k, v = protocol.recv_msg(a)
        assert k == b"\x00" * 4 and v == b"\x00" * 4
    finally:
        a.close(); b.close()
    assert wirecheck.violations() == []


def test_reset_clears_but_keeps_patches(armed_warn):
    protocol.send_msg.__wrapped__ = None   # attribute write must not break
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "frobnicate"})
    finally:
        a.close(); b.close()
    assert wirecheck.violations()
    wirecheck.reset()
    assert wirecheck.violations() == []
    assert wirecheck.armed()


# ---- the seeded-violation drill ----


def test_seeded_drill_scripted_backend(armed_warn):
    """The stress-shaped drill: a scripted TCP backend replies an
    undeclared field to a generate request; the sentry catches it at the
    client's recv seam and the verdict fails a report's
    wire_contract_clean invariant (the --wirecheck fold)."""
    import socketserver

    class H(socketserver.BaseRequestHandler):
        def handle(self):
            obj, _, _ = protocol.recv_msg(self.request)
            assert obj.get("op") == "generate"
            protocol.send_msg(self.request,
                              {"tokens": [1], "done": True,
                               "backend_addr": "10.0.0.1:1"})  # undeclared

    srv = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
    srv.daemon_threads = True
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        host, port = srv.server_address
        resp, _, _ = protocol.request_once(
            f"{host}:{port}", {"op": "generate", "prompt": [1]}, timeout=10)
        assert resp["tokens"] == [1]
    finally:
        srv.shutdown()
        srv.server_close()
    by_key = wirecheck.violations_by_key()
    # Flagged at the backend's send seam and the client's recv seam.
    assert by_key.get("generate/undeclared_reply_field", 0) >= 1, by_key

    # The harness fold: the verdict becomes a red invariant.
    from rbg_tpu.stress.harness import _attach_wirecheck

    class _Args:
        wirecheck = True

    report = {"invariants": {"other": True}}
    _attach_wirecheck(report, _Args())
    assert report["invariants"]["wire_contract_clean"] is False
    assert not all(report["invariants"].values())   # the drill exits 1
    assert report["wirecheck"]["violations_by_key"] == by_key
    assert not wirecheck.armed()                    # the fold disarms


def test_attach_wirecheck_clean_run(monkeypatch):
    monkeypatch.setenv(wirecheck.ENV_VAR, "warn")
    wirecheck.disarm()
    wirecheck.arm()
    from rbg_tpu.stress.harness import _attach_wirecheck

    class _Args:
        wirecheck = True

    report = {"invariants": {}}
    _attach_wirecheck(report, _Args())
    assert report["invariants"]["wire_contract_clean"] is True
    assert not wirecheck.armed()


def test_attach_wirecheck_noop_without_flag():
    from rbg_tpu.stress.harness import _attach_wirecheck

    class _Args:
        wirecheck = False

    report = {"invariants": {}}
    _attach_wirecheck(report, _Args())
    assert "wirecheck" not in report
    assert "wire_contract_clean" not in report["invariants"]


def test_strict_seeded_drill_raises_at_client(armed_strict):
    """RBG_WIRECHECK=1: the undeclared reply field raises at the seam —
    in-process here via a socketpair, the same codepath request_once
    crosses."""
    a, b = _pair()
    try:
        protocol.send_msg(a, {"op": "generate", "prompt": [1]})
        protocol.recv_msg(b)
        # The backend half bypasses its own send seam (raw codec) to
        # prove the CLIENT side catches a misbehaving peer.
        import json as _json
        b.sendall(_json.dumps({"tokens": [1], "rogue": True}).encode()
                  + b"\n")
        with pytest.raises(wirecheck.WireContractError):
            protocol.recv_msg(a)
    finally:
        a.close(); b.close()
