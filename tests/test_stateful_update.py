"""Stateful update engine — surge accounting, budgets, stable-unhealthy gate.

Table-driven over the pure planner (mirroring the reference's
``stateful_instance_set_control_test.go`` style) plus envtest-style e2e for
the surge rollout and slow-start scenarios (VERDICT r1 item 3 done-criteria).
"""

import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RollingUpdate
from rbg_tpu.api.instance import RoleInstance, RoleInstanceSet
from rbg_tpu.api.meta import Condition
from rbg_tpu.runtime.controllers import stateful_update as su
from rbg_tpu.runtime.controllers.instanceset import _ordinal

T0 = 1000.0
OLD, NEW = "rev-old", "rev-new"


def make_ris(replicas=3, max_unavailable=1, max_surge=0, partition=0,
             paused=False, min_ready_seconds=0,
             status_current="", status_update="", status_updated=0):
    ris = RoleInstanceSet()
    ris.metadata.name = "s"
    ris.metadata.namespace = "default"
    ris.metadata.uid = "uid-ris"
    ris.spec.replicas = replicas
    ris.spec.rolling_update = RollingUpdate(
        max_unavailable=max_unavailable, max_surge=max_surge,
        partition=partition, paused=paused,
        min_ready_seconds=min_ready_seconds)
    ris.status.current_revision = status_current
    ris.status.update_revision = status_update
    ris.status.updated_replicas = status_updated
    return ris


def make_inst(ordinal, rev, ready=True, terminating=False, ready_since=T0 - 60):
    inst = RoleInstance()
    inst.metadata.name = f"s-{ordinal}"
    inst.metadata.namespace = "default"
    inst.metadata.uid = f"uid-{ordinal}-{rev}"
    inst.metadata.labels = {C.LABEL_REVISION_NAME: rev}
    if terminating:
        inst.metadata.deletion_timestamp = T0 - 1
    inst.status.conditions = [Condition(
        type=C.COND_READY, status="True" if ready else "False",
        last_transition_time=ready_since)]
    return inst


def by_ord(*insts):
    return {_ordinal("s", i.metadata.name): i for i in insts}


def run_plan(ris, insts, observer=None, now=T0, current=OLD, update=NEW):
    obs = observer if observer is not None else su.HealthObserver()
    return obs, su.plan_stateful(
        ris, insts, current, update, obs,
        lambda i: _ordinal("s", i.metadata.name), now=now)


# ---------------- compute_topology tables ----------------

def test_topology_no_rollout_no_surge():
    ris = make_ris(replicas=2, max_surge=2)
    t = su.compute_topology(ris, by_ord(make_inst(0, NEW), make_inst(1, NEW)),
                            NEW, NEW)
    assert not t.in_rollout
    assert t.active_surge == 0 and t.end_ordinal == 2


def test_topology_surge_min_of_maxsurge_and_need():
    # All old healthy: surge_needed = healthyOld - maxUnav = 3 - 1 = 2,
    # clamped to maxSurge.
    ris = make_ris(replicas=3, max_unavailable=1, max_surge=1)
    t = su.compute_topology(
        ris, by_ord(*[make_inst(o, OLD) for o in range(3)]), OLD, NEW)
    assert t.in_rollout and t.active_surge == 1 and t.end_ordinal == 4
    ris2 = make_ris(replicas=3, max_unavailable=1, max_surge=4)
    t2 = su.compute_topology(
        ris2, by_ord(*[make_inst(o, OLD) for o in range(3)]), OLD, NEW)
    assert t2.active_surge == 2   # need (2) < maxSurge (4)


def test_topology_unhealthy_old_needs_no_surge():
    # 1 healthy old, 2 unhealthy old: surge_needed = max(0, 1 - 1) = 0.
    ris = make_ris(replicas=3, max_unavailable=1, max_surge=2)
    insts = by_ord(make_inst(0, OLD), make_inst(1, OLD, ready=False),
                   make_inst(2, OLD, ready=False))
    t = su.compute_topology(ris, insts, OLD, NEW)
    assert t.active_surge == 0


def test_topology_existing_surge_sticky_while_base_pending():
    # Surge already allocated at updateRev; healthy-old shrank to 0 but one
    # base ord is still mid-replacement (not ready) — surge must stay.
    ris = make_ris(replicas=2, max_unavailable=1, max_surge=2)
    insts = by_ord(make_inst(0, NEW), make_inst(1, NEW, ready=False),
                   make_inst(2, NEW), make_inst(3, NEW))
    t = su.compute_topology(ris, insts, OLD, NEW)
    assert t.active_surge == 2 and t.end_ordinal == 4


def test_topology_stale_rev_surge_not_sticky():
    # Surge slots at a STALE revision (superseded rollout) are not counted;
    # with healthy old base the need drives sizing and stale surge beyond
    # end_ordinal is condemned by the planner.
    ris = make_ris(replicas=2, max_unavailable=1, max_surge=2)
    insts = by_ord(make_inst(0, OLD), make_inst(1, OLD),
                   make_inst(2, "rev-stale"), make_inst(3, "rev-stale"))
    t = su.compute_topology(ris, insts, OLD, NEW)
    assert t.active_surge == 1   # healthyOld(2) - maxUnav(1)
    _, plan = run_plan(ris, list(insts.values()))
    assert plan.condemn == ["s-3"]   # ord 3 >= end_ordinal(3), highest first


def test_topology_paused_freezes_existing_surge():
    ris = make_ris(replicas=2, max_unavailable=1, max_surge=2, paused=True)
    insts = by_ord(make_inst(0, OLD), make_inst(1, OLD), make_inst(2, NEW))
    t = su.compute_topology(ris, insts, OLD, NEW)
    assert not t.in_rollout
    assert t.active_surge == 1 and t.end_ordinal == 3
    # Paused: no update actions, surge not condemned.
    _, plan = run_plan(ris, list(insts.values()))
    assert plan.updates == [] and plan.condemn == []


def test_topology_surge_collapses_when_base_done():
    # partition=1 pins ord 0 at OLD forever; ords [1, 2) at NEW healthy →
    # base work done → stickiness drops, surge condemned.
    ris = make_ris(replicas=2, max_unavailable=1, max_surge=2, partition=1)
    insts = by_ord(make_inst(0, OLD), make_inst(1, NEW),
                   make_inst(2, NEW), make_inst(3, NEW))
    t = su.compute_topology(ris, insts, OLD, NEW)
    assert t.active_surge == 0
    _, plan = run_plan(ris, list(insts.values()))
    assert plan.condemn == ["s-3", "s-2"]


def test_topology_maxunavailable_floor_and_partition_clamp():
    ris = make_ris(replicas=2, max_unavailable=0, max_surge=0, partition=99)
    t = su.compute_topology(ris, {}, OLD, NEW)
    assert t.max_unavailable == 1    # floored: rollout must progress
    assert t.partition == 2          # clamped to replicas
    ris2 = make_ris(replicas=2, max_unavailable=0, max_surge=1)
    t2 = su.compute_topology(ris2, {}, OLD, NEW)
    assert t2.max_unavailable == 0   # surge provides the progress path


# ---------------- plan_stateful tables ----------------

def test_plan_creates_missing_and_pins_below_partition():
    ris = make_ris(replicas=3, partition=2)
    _, plan = run_plan(ris, [])
    assert [(n, o, r) for n, o, r in plan.create] == [
        ("s-0", 0, OLD), ("s-1", 1, OLD), ("s-2", 2, NEW)]


def test_plan_budget_one_costly_update_per_pass():
    ris = make_ris(replicas=3, max_unavailable=1)
    insts = [make_inst(o, OLD) for o in range(3)]
    _, plan = run_plan(ris, insts)
    assert [a.name for a in plan.updates] == ["s-2"]   # descending, budget 1
    assert not plan.updates[0].is_free


def test_plan_slow_start_blocks_costly_without_surge():
    # Ord 2 already recreated at NEW but not ready (slow start): it occupies
    # the whole budget — no further costly updates, requeue not needed.
    ris = make_ris(replicas=3, max_unavailable=1)
    insts = [make_inst(0, OLD), make_inst(1, OLD),
             make_inst(2, NEW, ready=False)]
    _, plan = run_plan(ris, insts)
    assert plan.updates == []


def test_plan_surge_escape_valve_for_slow_start():
    # Same slow-start, but a READY surge instance raises the effective
    # budget — the rollout keeps moving (VERDICT r1 weak-point 3).
    ris = make_ris(replicas=3, max_unavailable=1, max_surge=1)
    insts = [make_inst(0, OLD), make_inst(1, OLD),
             make_inst(2, NEW, ready=False), make_inst(3, NEW)]
    _, plan = run_plan(ris, insts)
    assert [a.name for a in plan.updates] == ["s-1"]
    assert not plan.updates[0].is_free


def test_plan_unready_surge_provides_no_budget():
    ris = make_ris(replicas=3, max_unavailable=1, max_surge=1)
    insts = [make_inst(0, OLD), make_inst(1, OLD),
             make_inst(2, NEW, ready=False), make_inst(3, NEW, ready=False)]
    _, plan = run_plan(ris, insts)
    assert plan.updates == []


def test_plan_transient_unhealthy_not_free_until_window():
    # Old ord 1 just went unhealthy: not free yet → budget (1) is already
    # consumed by its unavailability → nothing happens, requeue scheduled.
    ris = make_ris(replicas=2, max_unavailable=1)
    insts = [make_inst(0, OLD), make_inst(1, OLD, ready=False)]
    obs, plan = run_plan(ris, insts, now=T0)
    assert plan.updates == []
    assert plan.requeue_after is not None
    assert plan.requeue_after <= su.STABLE_UNHEALTHY_SECONDS
    # After the stable window the same target becomes FREE: it is replaced
    # without consuming budget. The healthy ord 0 stays blocked — the base
    # is still one-unavailable, exactly at maxUnavailable.
    later = T0 + su.STABLE_UNHEALTHY_SECONDS + 1
    _, plan2 = run_plan(ris, insts, observer=obs, now=later)
    assert [(a.name, a.is_free) for a in plan2.updates] == [("s-1", True)]


def test_plan_flapping_health_resets_window():
    ris = make_ris(replicas=2, max_unavailable=1)
    bad = make_inst(1, OLD, ready=False)
    good = make_inst(1, OLD, ready=True)
    good.metadata.uid = bad.metadata.uid
    obs = su.HealthObserver()
    obs.observe([bad], now=T0)
    obs.observe([good], now=T0 + 5)           # heals → timer cleared
    obs.observe([bad], now=T0 + su.STABLE_UNHEALTHY_SECONDS + 1)
    assert not obs.stably_unhealthy(bad, now=T0 + su.STABLE_UNHEALTHY_SECONDS + 1)


def test_observer_gc_on_vanished_uid():
    obs = su.HealthObserver()
    a = make_inst(0, OLD, ready=False)
    obs.observe([a], now=T0)
    assert obs._since
    obs.observe([], now=T0 + 1)
    assert not obs._since


def test_plan_surge_recycled_before_base():
    # Stale-ish surge inside range: surge slot at OLD rev is a free target
    # and is recycled before base ordinals.
    ris = make_ris(replicas=2, max_unavailable=1, max_surge=1)
    insts = [make_inst(0, OLD), make_inst(1, OLD), make_inst(2, OLD)]
    # end_ordinal: healthyOld(2) - 1 = 1 surge → [0,3). Ord 2 is surge slot.
    _, plan = run_plan(ris, insts)
    names = [a.name for a in plan.updates]
    assert names[0] == "s-2" and plan.updates[0].is_free
    assert "s-1" in names   # one costly follows


def test_plan_terminating_target_skipped_and_counts_unavailable():
    ris = make_ris(replicas=2, max_unavailable=1)
    insts = [make_inst(0, OLD), make_inst(1, OLD, terminating=True)]
    _, plan = run_plan(ris, insts)
    # terminating ord1 gets no action (already on its way out), and it
    # consumes the unavailability budget — ord0 must wait.
    assert plan.updates == []


def test_plan_free_target_below_blocked_costly_still_processed():
    """Regression: a stably-unhealthy LOW ordinal must be replaced even when
    a higher-ordinal costly target hits the budget wall first — otherwise
    the rollout wedges with no wake-up event."""
    ris = make_ris(replicas=3, max_unavailable=1)
    insts = [make_inst(0, OLD), make_inst(1, OLD, ready=False),
             make_inst(2, OLD)]
    obs = su.HealthObserver()
    obs.observe(insts, now=T0)
    later = T0 + su.STABLE_UNHEALTHY_SECONDS + 1
    _, plan = run_plan(ris, insts, observer=obs, now=later)
    # s-2 (costly) is blocked — base already 1-unavailable — but free s-1
    # is still replaced.
    assert [(a.name, a.is_free) for a in plan.updates] == [("s-1", True)]


def test_plan_young_surge_provides_no_budget_under_min_ready():
    """Regression: surge that is ready but younger than min_ready_seconds is
    not yet an availability buffer — maxUnavailable=0 must hold."""
    ris = make_ris(replicas=3, max_unavailable=0, max_surge=1,
                   min_ready_seconds=60)
    insts = [make_inst(0, OLD), make_inst(1, OLD), make_inst(2, OLD),
             make_inst(3, NEW, ready_since=T0 - 1)]   # ready 1s ago
    _, plan = run_plan(ris, insts)
    assert plan.updates == []
    assert plan.requeue_after is not None and plan.requeue_after <= 59
    # Once the surge matures, one costly update is licensed.
    _, plan2 = run_plan(ris, insts, now=T0 + 60)
    assert [a.name for a in plan2.updates] == ["s-2"]


def test_plan_paused_recreates_missing_base_at_current_rev():
    """A paused mid-rollout set that loses a base ordinal (node failure)
    must recreate it at the CURRENT revision — pause means the new revision
    must not spread."""
    ris = make_ris(replicas=2, paused=True)
    # ordinal 1 vanished; ordinal 0 still at OLD
    _, plan = run_plan(ris, [make_inst(0, OLD)])
    assert plan.create == [("s-1", 1, OLD)]
    assert plan.updates == []
    # Unpaused: the same missing ordinal comes back at the UPDATE revision.
    ris2 = make_ris(replicas=2)
    _, plan2 = run_plan(ris2, [make_inst(0, OLD)])
    assert ("s-1", 1, NEW) in plan2.create


def test_plan_paused_freezes_gapped_surge_range():
    """Paused with a GAP in the surge range (ord 2 lost, ord 3 alive at the
    update revision): no re-numbering — the live surge instance is kept and
    no update-revision create is issued."""
    ris = make_ris(replicas=2, max_surge=2, paused=True)
    insts = [make_inst(0, OLD), make_inst(1, OLD), make_inst(3, NEW)]
    _, plan = run_plan(ris, insts)
    assert plan.create == []
    assert plan.condemn == []
    assert plan.updates == []


def test_plan_rollback_to_current_mid_rollout_converges():
    """Regression: rollout undo back to the CURRENT revision while an
    instance still sits at the abandoned intermediate revision leaves
    current == update; the stale instance must still be walked back or the
    set wedges with no wake-up event (admin-cli undo flake, round 2)."""
    ris = make_ris(replicas=2)
    insts = [make_inst(0, "rev-abandoned"), make_inst(1, OLD)]
    _, plan = run_plan(ris, insts, current=OLD, update=OLD)
    assert plan.topology.in_rollout
    assert [a.name for a in plan.updates] == ["s-0"]


# ---------------- advance guard ----------------

def test_advance_guard_table():
    done = by_ord(make_inst(0, NEW), make_inst(1, NEW))
    # all guards pass
    ris = make_ris(replicas=2, status_current=OLD, status_update=NEW,
                   status_updated=2)
    topo = su.compute_topology(ris, done, OLD, NEW)
    assert su.should_advance_current_revision(ris, done, topo, NEW)
    # partition > 0 → never advance
    risp = make_ris(replicas=2, partition=1, status_current=OLD,
                    status_update=NEW, status_updated=2)
    topop = su.compute_topology(risp, done, OLD, NEW)
    assert not su.should_advance_current_revision(risp, done, topop, NEW)
    # prior persisted status hasn't observed the rollout yet
    ris1 = make_ris(replicas=2, status_current=OLD, status_update=OLD,
                    status_updated=2)
    assert not su.should_advance_current_revision(ris1, done, topo, NEW)
    ris2 = make_ris(replicas=2, status_current=OLD, status_update=NEW,
                    status_updated=1)
    assert not su.should_advance_current_revision(ris2, done, topo, NEW)
    # a base ord not ready → no advance
    part = by_ord(make_inst(0, NEW), make_inst(1, NEW, ready=False))
    assert not su.should_advance_current_revision(ris, part, topo, NEW)


# ---------------- envtest-style e2e ----------------

@pytest.fixture()
def plane():
    from rbg_tpu.runtime.plane import ControlPlane
    from rbg_tpu.testutil import make_tpu_nodes
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        yield p


def _ready_actives(plane):
    return [p for p in plane.store.list("Pod", namespace="default")
            if p.active and p.running_ready]


def test_e2e_surge_rollout_keeps_capacity(plane):
    """maxUnavailable=0 + maxSurge=1: the rollout proceeds ONLY through
    surge, and the number of ready-serving pods never drops below replicas."""
    from rbg_tpu.testutil import make_group, simple_role
    role = simple_role("server", replicas=2)
    role.rolling_update = RollingUpdate(
        max_unavailable=0, max_surge=1, in_place_if_possible=False)
    plane.apply(make_group("sg", role))
    plane.wait_group_ready("sg")

    g = plane.store.get("RoleBasedGroup", "default", "sg")
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(g)

    low_water = [2]
    group_went_unready = [False]
    counter_overshoot = [False]

    def rolled():
        low_water[0] = min(low_water[0], len(_ready_actives(plane)))
        # Group Ready must never flip False: base capacity never dips, and
        # the rollup is base-scoped so the transient 3rd (surge) instance
        # can't break `replicas == spec.replicas`.
        from rbg_tpu.api.meta import get_condition
        g_now = plane.store.get("RoleBasedGroup", "default", "sg")
        c = get_condition(g_now.status.conditions, "Ready")
        if c is not None and c.status != "True":
            group_went_unready[0] = True
        ris = plane.store.get("RoleInstanceSet", "default", "sg-server")
        if (ris.status.replicas > 2 or ris.status.ready_replicas > 2
                or ris.status.updated_ready_replicas > 2):
            counter_overshoot[0] = True
        pods = [p for p in plane.store.list("Pod", namespace="default")
                if p.active]
        return (len(pods) == 2
                and all(p.template.containers[0].image == "engine:v2"
                        for p in pods)
                and all(p.running_ready for p in pods))

    plane.wait_for(rolled, timeout=30, desc="surge rollout complete")
    assert low_water[0] >= 2, f"ready pods dipped to {low_water[0]}"
    assert not group_went_unready[0], "zero-disruption surge rollout flipped group Ready"
    assert not counter_overshoot[0], "RIS status counters included surge instances"

    # Surge instance (ordinal 2) is condemned once the rollout completes.
    def surge_gone():
        insts = plane.store.list("RoleInstance", namespace="default")
        return sorted(i.metadata.name for i in insts
                      if i.metadata.deletion_timestamp is None) == [
                          "sg-server-0", "sg-server-1"]

    plane.wait_for(surge_gone, desc="surge instance cleaned up")

    def advanced():
        ris = plane.store.get("RoleInstanceSet", "default", "sg-server")
        return (ris.status.current_revision == ris.status.update_revision
                and ris.status.updated_replicas == 2)

    plane.wait_for(advanced, desc="CurrentRevision advanced")


def test_e2e_slow_start_does_not_eat_extra_ready_instances(plane):
    """A slow-starting replacement must freeze further costly updates
    (maxUnavailable=1, no surge): the still-old instance stays ready."""
    from rbg_tpu.testutil import make_group, simple_role
    role = simple_role("server", replicas=2)
    role.rolling_update = RollingUpdate(
        max_unavailable=1, in_place_if_possible=False)
    plane.apply(make_group("slow", role))
    plane.wait_group_ready("slow")

    # Hold v2 pods of ordinal 1 in Pending (slow start).
    plane.kubelet.hold_filter = (
        lambda p: p.template.containers[0].image == "engine:v2")

    g = plane.store.get("RoleBasedGroup", "default", "slow")
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(g)

    # Ordinal 1 (highest) is replaced first and its v2 pod hangs in Pending.
    def ord1_recreating():
        pods = [p for p in plane.store.list("Pod", namespace="default")
                if p.active and p.template.containers[0].image == "engine:v2"]
        return len(pods) >= 1

    plane.wait_for(ord1_recreating, desc="ordinal 1 recreated at v2")
    time.sleep(0.6)   # several reconcile cycles
    # Ordinal 0 must still be the OLD ready pod — budget is exhausted by the
    # slow-starting ordinal 1.
    old_ready = [p for p in _ready_actives(plane)
                 if p.template.containers[0].image != "engine:v2"]
    assert len(old_ready) == 1, "slow start ate the remaining ready instance"

    plane.kubelet.release_holds()

    def done():
        pods = [p for p in plane.store.list("Pod", namespace="default")
                if p.active]
        return (len(pods) == 2
                and all(p.template.containers[0].image == "engine:v2"
                        for p in pods)
                and all(p.running_ready for p in pods))

    plane.wait_for(done, timeout=30, desc="rollout completes after release")


def test_e2e_partition_pins_old_revision_spec(plane):
    """Ordinals below partition are recreated at the CURRENT revision's spec
    (from the stored snapshot), not the update revision."""
    from rbg_tpu.testutil import make_group, simple_role
    role = simple_role("server", replicas=2)
    role.rolling_update = RollingUpdate(
        max_unavailable=1, partition=1, in_place_if_possible=False)
    plane.apply(make_group("pin", role))
    plane.wait_group_ready("pin")

    g = plane.store.get("RoleBasedGroup", "default", "pin")
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(g)

    def split():
        pods = {p.metadata.labels[C.LABEL_INSTANCE_NAME]:
                p.template.containers[0].image
                for p in plane.store.list("Pod", namespace="default")
                if p.active}
        return (pods.get("pin-server-1") == "engine:v2"
                and pods.get("pin-server-0") == "engine:v1")

    plane.wait_for(split, timeout=30, desc="partition split revisions")

    # Kill the PINNED instance's pod: it must be recreated at the OLD image
    # from the revision snapshot.
    pod0 = [p for p in plane.store.list("Pod", namespace="default")
            if p.active
            and p.metadata.labels[C.LABEL_INSTANCE_NAME] == "pin-server-0"][0]
    old_image = pod0.template.containers[0].image
    plane.store.delete("Pod", "default", pod0.metadata.name)
    plane.store.delete("RoleInstance", "default", "pin-server-0")

    def recreated_old():
        pods = [p for p in plane.store.list("Pod", namespace="default")
                if p.active
                and p.metadata.labels[C.LABEL_INSTANCE_NAME] == "pin-server-0"]
        return pods and pods[0].template.containers[0].image == old_image

    plane.wait_for(recreated_old, timeout=30,
                   desc="pinned ordinal recreated at old revision")


# ---------------- IntOrString percent forms (sts_reconciler.go:198-449) ----


def test_topology_percent_knobs_k8s_rounding():
    """maxSurge rounds UP, maxUnavailable rounds DOWN against replicas."""
    ris = make_ris(replicas=4, max_surge="25%", max_unavailable="30%")
    t = su.compute_topology(ris, {}, OLD, NEW)
    assert t.max_surge == 1          # ceil(4 * 0.25) = 1
    assert t.max_unavailable == 1    # floor(4 * 0.30) = 1

    ris2 = make_ris(replicas=10, max_surge="15%", max_unavailable="25%")
    t2 = su.compute_topology(ris2, {}, OLD, NEW)
    assert t2.max_surge == 2         # ceil(1.5)
    assert t2.max_unavailable == 2   # floor(2.5)


def test_topology_percent_unavailable_floors_to_one_without_surge():
    """"10%" of 3 replicas floors to 0 — but with no surge the budget
    floor keeps the rollout able to progress."""
    ris = make_ris(replicas=3, max_surge=0, max_unavailable="10%")
    t = su.compute_topology(ris, {}, OLD, NEW)
    assert t.max_unavailable == 1


def test_percent_knob_validation_and_serde():
    from rbg_tpu.api import intstr, serde
    from rbg_tpu.api.group import RoleBasedGroup

    intstr.validate("25%")
    intstr.validate(3)
    import pytest as _pytest
    with _pytest.raises(ValueError):
        intstr.validate("25")
    with _pytest.raises(ValueError):
        intstr.validate("a%")

    # Wire round-trip keeps the string form.
    g = serde.from_dict(RoleBasedGroup, {
        "kind": "RoleBasedGroup",
        "metadata": {"name": "g"},
        "spec": {"roles": [{
            "name": "r", "replicas": 4,
            "rollingUpdate": {"maxUnavailable": "25%", "maxSurge": "50%"},
        }]},
    })
    assert g.spec.roles[0].rolling_update.max_unavailable == "25%"
    out = serde.to_dict(g)
    assert out["spec"]["roles"][0]["rollingUpdate"]["maxSurge"] == "50%"

    # Admission rejects malformed percent strings.
    from rbg_tpu.api.validation import ValidationError, validate_group
    g.spec.roles[0].rolling_update.max_surge = "half"
    with _pytest.raises(ValidationError):
        validate_group(g)

    # Schema advertises the oneOf contract.
    from rbg_tpu.api.schema import schema_for
    s = schema_for(RoleBasedGroup)
    ru = s["definitions"]["RollingUpdate"]["properties"]["maxUnavailable"]
    assert {"type": "integer"} in ru["oneOf"]
