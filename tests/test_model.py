"""Model numerics: shapes, prefill/decode consistency, padding, training loss."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.models import KVCache, forward, get_config, init_params
from rbg_tpu.models.llama import forward_train, prefill_and_decode_greedy


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def test_forward_shapes(tiny):
    cfg, params = tiny
    B, T, S = 2, 8, 32
    tokens = jax.random.randint(jax.random.key(1), (B, T), 0, cfg.vocab_size)
    cache = KVCache.create(cfg, B, S)
    logits, cache = forward(params, cfg, tokens, cache)
    assert logits.shape == (B, T, cfg.vocab_size)
    assert logits.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(cache.length), [T, T])


@pytest.mark.slow
def test_prefill_matches_incremental_decode(tiny):
    """Logits at position t from one full prefill == logits from feeding tokens
    one at a time through the cache. This validates cache writes, masking and
    RoPE offsets all at once."""
    cfg, params = tiny
    B, T, S = 2, 10, 16
    tokens = jax.random.randint(jax.random.key(2), (B, T), 0, cfg.vocab_size)

    full_logits, _ = forward(params, cfg, tokens, KVCache.create(cfg, B, S))

    cache = KVCache.create(cfg, B, S)
    step_logits = []
    for t in range(T):
        lg, cache = forward(params, cfg, tokens[:, t : t + 1], cache)
        step_logits.append(lg)
    step_logits = jnp.concatenate(step_logits, axis=1)

    np.testing.assert_allclose(
        np.asarray(full_logits), np.asarray(step_logits), rtol=2e-4, atol=2e-4
    )


def test_chunked_prefill_matches_full(tiny):
    """Prefill in two chunks == prefill in one (chunked-prefill correctness)."""
    cfg, params = tiny
    B, T, S = 1, 12, 16
    tokens = jax.random.randint(jax.random.key(3), (B, T), 0, cfg.vocab_size)
    full, _ = forward(params, cfg, tokens, KVCache.create(cfg, B, S))
    cache = KVCache.create(cfg, B, S)
    a, cache = forward(params, cfg, tokens[:, :5], cache)
    b, cache = forward(params, cfg, tokens[:, 5:], cache)
    np.testing.assert_allclose(
        np.asarray(full), np.asarray(jnp.concatenate([a, b], axis=1)),
        rtol=2e-4, atol=2e-4,
    )


def test_padding_does_not_affect_real_tokens(tiny):
    """Pad queries (token_mask False) must not write cache or shift results."""
    cfg, params = tiny
    B, T, S = 1, 6, 16
    tokens = jax.random.randint(jax.random.key(4), (B, T), 0, cfg.vocab_size)
    clean, _ = forward(params, cfg, tokens, KVCache.create(cfg, B, S))

    padded = jnp.concatenate([tokens, jnp.zeros((B, 2), jnp.int32)], axis=1)
    mask = jnp.concatenate([jnp.ones((B, T), bool), jnp.zeros((B, 2), bool)], axis=1)
    positions = jnp.broadcast_to(jnp.arange(T + 2, dtype=jnp.int32)[None], (B, T + 2))
    lg, cache = forward(params, cfg, padded, KVCache.create(cfg, B, S), positions, mask)
    np.testing.assert_allclose(
        np.asarray(clean), np.asarray(lg[:, :T]), rtol=2e-4, atol=2e-4
    )
    np.testing.assert_array_equal(np.asarray(cache.length), [T])


def test_forward_train_matches_forward(tiny):
    cfg, params = tiny
    B, T = 2, 9
    tokens = jax.random.randint(jax.random.key(5), (B, T), 0, cfg.vocab_size)
    serve, _ = forward(params, cfg, tokens, KVCache.create(cfg, B, T))
    train = forward_train(params, cfg, tokens)
    np.testing.assert_allclose(np.asarray(serve), np.asarray(train), rtol=2e-4, atol=2e-4)


def test_greedy_decode_runs(tiny):
    cfg, params = tiny
    prompt = jax.random.randint(jax.random.key(6), (2, 4), 0, cfg.vocab_size)
    out = prefill_and_decode_greedy(params, cfg, prompt, steps=3)
    assert out.shape == (2, 3)


def test_tied_embeddings():
    cfg = get_config("tiny", tie_word_embeddings=True)
    params = init_params(cfg, jax.random.key(0))
    assert "lm_head" not in params
    tokens = jnp.zeros((1, 4), jnp.int32)
    logits, _ = forward(params, cfg, tokens, KVCache.create(cfg, 1, 8))
    assert logits.shape == (1, 4, cfg.vocab_size)
