"""Adaptive agg↔disagg topology subsystem (rbg_tpu/topology): pure
policy transitions under an injected clock, and the controller's
persistent flip state machine against a live mini-plane — every
transition scripted, no engine: HOLD on stale/no-ratio/deadband,
cost-gate veto, cooldown suppression, mid-flip plane restart resuming
from annotations, and the autoscaler-conflict backoff.
"""

from __future__ import annotations

import json
import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import IdentityMode, ScalingAdapterHook
from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.controllers.scalingadapter import adapter_name
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role
from rbg_tpu.topology import (
    GroupTopology, POSTURE_DISAGG, POSTURE_UNIFIED, REC_HOLD,
    TopologyConfig, TopologyPolicy, TopologyPolicyConfig, TopologySignals,
)


def _sig(ratio=None, fresh=True, judged=10, kv=None, link=None, **kw):
    return TopologySignals(fresh=fresh, prefill_decode_ratio=ratio,
                           judged=judged, kv_bytes_to_move=kv,
                           link_bytes_per_s=link, **kw)


def _cfg(**kw) -> TopologyPolicyConfig:
    base = dict(disagg_ratio=6.0, unified_ratio=2.0, min_judged=3,
                disagg_stabilization_s=1.0, unified_stabilization_s=2.0,
                cooldown_s=5.0, max_switch_cost_s=10.0)
    base.update(kw)
    return TopologyPolicyConfig(**base)


# ---- policy (pure, injected clock) -----------------------------------------


def test_policy_flips_off_live_router_ingress_counters():
    """The production ratio seam: real rbg_router_ingress_tokens_total
    counter increments, sampled by the windowed plane, must drive the
    policy to a flip — no drill-only scripted signals involved."""
    from rbg_tpu.obs.metrics import Registry
    from rbg_tpu.obs.timeseries import TimeSeriesSampler
    from rbg_tpu.topology import router_ingress_signals_fn

    reg = Registry()
    sampler = TimeSeriesSampler(registry=reg, interval_s=1.0,
                                retention_s=300.0)
    fn = router_ingress_signals_fn(sampler, window_s=60.0)
    # No samples yet → absence of signal, never ratio 0/∞.
    assert fn(None) == {}
    reg.inc(names.ROUTER_INGRESS_TOKENS_TOTAL, 100.0, kind="prefill")
    reg.inc(names.ROUTER_INGRESS_TOKENS_TOTAL, 100.0, kind="decode")
    sampler.sample_now(now=0.0)
    # Sustained long-prompt mix: 10:1 prompt:output tokens at ingress —
    # what a router serving system-prompt-heavy traffic would publish.
    reg.inc(names.ROUTER_INGRESS_TOKENS_TOTAL, 10000.0, kind="prefill")
    reg.inc(names.ROUTER_INGRESS_TOKENS_TOTAL, 1000.0, kind="decode")
    sampler.sample_now(now=10.0)
    extras = fn(None)
    assert 9.0 <= extras["prefill_decode_ratio"] <= 11.0
    # One side idle → no ratio (the controller falls back / HOLDs).
    reg2 = Registry()
    s2 = TimeSeriesSampler(registry=reg2, interval_s=1.0, retention_s=300.0)
    reg2.inc(names.ROUTER_INGRESS_TOKENS_TOTAL, 100.0, kind="prefill")
    s2.sample_now(now=0.0)
    reg2.inc(names.ROUTER_INGRESS_TOKENS_TOTAL, 900.0, kind="prefill")
    s2.sample_now(now=10.0)
    assert router_ingress_signals_fn(s2, window_s=60.0)(None) == {}
    # The measured ratio drives a real flip through the policy's own
    # stabilization machinery.
    p = TopologyPolicy(_cfg())
    sig = _sig(ratio=extras["prefill_decode_ratio"])
    assert p.decide(0.0, sig, POSTURE_UNIFIED).recommendation == REC_HOLD
    d = p.decide(1.1, sig, POSTURE_UNIFIED)
    assert d.recommendation == POSTURE_DISAGG


def test_policy_stale_holds_and_forgets_onset():
    p = TopologyPolicy(_cfg())
    d = p.decide(0.0, _sig(ratio=10.0), POSTURE_UNIFIED)
    assert d.recommendation == REC_HOLD and d.suppressed == "stabilizing"
    # Stale window in the middle forgets the pressure onset...
    d = p.decide(0.5, _sig(ratio=10.0, fresh=False), POSTURE_UNIFIED)
    assert d.suppressed == "stale"
    # ...so pressure at t=1.2 has NOT been sustained since t=0.
    d = p.decide(1.2, _sig(ratio=10.0), POSTURE_UNIFIED)
    assert d.recommendation == REC_HOLD and d.suppressed == "stabilizing"
    d = p.decide(2.3, _sig(ratio=10.0), POSTURE_UNIFIED)
    assert d.recommendation == POSTURE_DISAGG


def test_policy_missing_ratio_and_low_sample_hold():
    p = TopologyPolicy(_cfg())
    d = p.decide(0.0, _sig(ratio=None), POSTURE_UNIFIED)
    assert d.recommendation == REC_HOLD and d.suppressed == "no_ratio"
    d = p.decide(1.0, _sig(ratio=10.0, judged=1), POSTURE_UNIFIED)
    assert d.recommendation == REC_HOLD and d.suppressed == "low_sample"


def test_policy_deadband_and_already_there_hold():
    p = TopologyPolicy(_cfg())
    d = p.decide(0.0, _sig(ratio=4.0), POSTURE_UNIFIED)
    assert d.recommendation == REC_HOLD and d.suppressed == "deadband"
    d = p.decide(1.0, _sig(ratio=1.0), POSTURE_UNIFIED)
    assert d.recommendation == REC_HOLD and d.suppressed is None


def test_policy_direction_split_stabilization_and_both_directions():
    p = TopologyPolicy(_cfg())
    assert p.decide(0.0, _sig(ratio=10.0),
                    POSTURE_UNIFIED).suppressed == "stabilizing"
    d = p.decide(1.1, _sig(ratio=10.0), POSTURE_UNIFIED)
    assert d.recommendation == POSTURE_DISAGG
    # The unified direction uses ITS OWN (longer) window, and the onset
    # restarts when the pressure direction changes.
    p2 = TopologyPolicy(_cfg())
    assert p2.decide(0.0, _sig(ratio=1.0),
                     POSTURE_DISAGG).suppressed == "stabilizing"
    assert p2.decide(1.1, _sig(ratio=1.0),
                     POSTURE_DISAGG).suppressed == "stabilizing"
    d = p2.decide(2.2, _sig(ratio=1.0), POSTURE_DISAGG)
    assert d.recommendation == POSTURE_UNIFIED


def test_policy_cooldown_suppresses_and_revoke_returns_it():
    p = TopologyPolicy(_cfg())
    p.decide(0.0, _sig(ratio=10.0), POSTURE_UNIFIED)
    d = p.decide(1.1, _sig(ratio=10.0), POSTURE_UNIFIED)
    assert d.recommendation == POSTURE_DISAGG
    assert p.cooldown_remaining(1.2) > 0
    # Flip back immediately: suppressed by cooldown even after the
    # unified stabilization window.
    p.decide(1.2, _sig(ratio=1.0), POSTURE_DISAGG)
    d = p.decide(3.4, _sig(ratio=1.0), POSTURE_DISAGG)
    assert d.recommendation == REC_HOLD and d.suppressed == "cooldown"
    # revoke(): the controller could not START the flip — the retry is
    # not charged cooldown + a fresh stabilization window.
    p3 = TopologyPolicy(_cfg())
    p3.decide(0.0, _sig(ratio=10.0), POSTURE_UNIFIED)
    d = p3.decide(1.1, _sig(ratio=10.0), POSTURE_UNIFIED)
    assert d.recommendation == POSTURE_DISAGG
    p3.revoke(d)
    assert p3.cooldown_remaining(1.2) == 0.0
    d = p3.decide(1.2, _sig(ratio=10.0), POSTURE_UNIFIED)
    assert d.recommendation == POSTURE_DISAGG


def test_policy_cost_gate_vetoes_until_affordable():
    p = TopologyPolicy(_cfg(max_switch_cost_s=2.0))
    p.decide(0.0, _sig(ratio=10.0), POSTURE_UNIFIED)
    # 1 GiB over 10 MB/s ~ 107 s: vetoed, with the estimate reported.
    d = p.decide(1.1, _sig(ratio=10.0, kv=float(1 << 30), link=10e6),
                 POSTURE_UNIFIED)
    assert d.recommendation == REC_HOLD and d.suppressed == "cost_gated"
    assert d.est_switch_cost_s == pytest.approx((1 << 30) / 10e6)
    # The veto does NOT burn cooldown; once the link speeds up (or the
    # resident KV shrinks) the same pressure flips.
    d = p.decide(1.2, _sig(ratio=10.0, kv=float(1 << 30), link=2e9),
                 POSTURE_UNIFIED)
    assert d.recommendation == POSTURE_DISAGG
    # Unknown cost (no measured link yet) never blocks the first flip.
    p2 = TopologyPolicy(_cfg(max_switch_cost_s=2.0))
    p2.decide(0.0, _sig(ratio=10.0, kv=float(1 << 30)), POSTURE_UNIFIED)
    d = p2.decide(1.1, _sig(ratio=10.0, kv=float(1 << 30)),
                  POSTURE_UNIFIED)
    assert d.recommendation == POSTURE_DISAGG


def test_policy_disabled_holds():
    p = TopologyPolicy(_cfg(enabled=False))
    d = p.decide(0.0, _sig(ratio=10.0), POSTURE_UNIFIED)
    assert d.recommendation == REC_HOLD and d.suppressed == "disabled"


# ---- controller state machine (live mini-plane, scripted signals) ----------


GROUP = "tp"


def _mk_plane(script: dict, candidacy_log=None, groups=None,
              policy_kw=None):
    """Mini-plane with one 3-role group and a TopologyController whose
    signals come from the mutable ``script`` dict."""
    gt = GroupTopology(group=GROUP, unified_replicas=2,
                       prefill_replicas=1, decode_replicas=1)

    def signals_fn(_gt):
        return dict(script)

    def candidacy_fn(group, role, active):
        if candidacy_log is not None:
            candidacy_log.append((role, active))

    pol = dict(disagg_ratio=6.0, unified_ratio=2.0, min_judged=3,
               disagg_stabilization_s=0.1, unified_stabilization_s=0.1,
               cooldown_s=0.3, max_switch_cost_s=0.0)
    pol.update(policy_kw or {})
    cfg = TopologyConfig(
        groups=[gt], policy=TopologyPolicyConfig(**pol),
        eval_period_s=0.05, window_s=2.0, stale_after_s=10.0,
        signals_fn=signals_fn, candidacy_fn=candidacy_fn)
    plane = ControlPlane(backend="fake", topology=cfg)
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=2)
    return plane, gt


def _mk_group(gt):
    roles = []
    for name, n in ((gt.unified_role, gt.unified_replicas),
                    (gt.prefill_role, 0), (gt.decode_role, 0)):
        r = simple_role(name, replicas=n)
        r.identity = IdentityMode.RANDOM
        r.drain_seconds = 0.2
        r.scaling_adapter = ScalingAdapterHook(enabled=True,
                                               min_replicas=0,
                                               max_replicas=4)
        roles.append(r)
    return make_group(GROUP, *roles)


def _ann(plane, key):
    g = plane.store.get("RoleBasedGroup", "default", GROUP, copy_=False)
    return g.metadata.annotations.get(key)


def test_controller_full_flip_lifecycle():
    script = {"fresh": True, "prefill_decode_ratio": 1.0, "judged": 20}
    cand = []
    plane, gt = _mk_plane(script, candidacy_log=cand)
    flips0 = REGISTRY.counter(names.TOPOLOGY_FLIPS_TOTAL, group=GROUP,
                              target=POSTURE_DISAGG)
    with plane:
        plane.apply(_mk_group(gt))
        plane.wait_group_ready(GROUP, timeout=30)
        # Chat mix: no flip, posture unified.
        time.sleep(0.3)
        assert _ann(plane, C.ANN_TOPOLOGY_STATE) is None
        assert REGISTRY.gauge(names.TOPOLOGY_POSTURE, group=GROUP) == 0.0
        # Sustained long-prompt mix: flip to disagg must run the whole
        # machine — warm, cutover, drain — and land with the old shape
        # gone.
        script["prefill_decode_ratio"] = 12.0
        plane.wait_for(
            lambda: _ann(plane, C.ANN_TOPOLOGY_POSTURE) == POSTURE_DISAGG
            and not _ann(plane, C.ANN_TOPOLOGY_STATE),
            timeout=30, desc="flip completed")
        # Old shape drained: no unified instances survive.
        assert not plane.store.list(
            "RoleInstance", namespace="default",
            selector={C.LABEL_GROUP_NAME: GROUP,
                      C.LABEL_ROLE_NAME: gt.unified_role})
        # Target shape serving.
        g = plane.store.get("RoleBasedGroup", "default", GROUP)
        assert g.status.role(gt.prefill_role).ready_replicas >= 1
        assert g.status.role(gt.decode_role).ready_replicas >= 1
        # Adapters: old shape written to 0, both stamped (two-writer
        # protocol — whoever writes, stamps).
        sa = plane.store.get("ScalingAdapter", "default",
                             adapter_name(GROUP, gt.unified_role))
        assert sa.spec.replicas == 0
        assert sa.metadata.annotations[C.ANN_AUTOSCALE_LAST_WRITE] == "0"
        sa = plane.store.get("ScalingAdapter", "default",
                             adapter_name(GROUP, gt.prefill_role))
        assert sa.spec.replicas == 1
        assert sa.metadata.annotations[C.ANN_AUTOSCALE_LAST_WRITE] == "1"
        # Candidacy flipped role-by-role: targets active BEFORE the old
        # role was withdrawn.
        on = [i for i, (r, a) in enumerate(cand) if a]
        off = [i for i, (r, a) in enumerate(cand) if not a]
        assert on and off and max(on[:2]) < min(off)
        assert (gt.unified_role, False) in cand
        # Serving-roles annotation reflects the new shape only.
        serving = json.loads(_ann(plane, C.ANN_TOPOLOGY_SERVING))
        assert serving == sorted([gt.prefill_role, gt.decode_role])
        # The annotation clear and the gauge write are two systems (store
        # + registry) — the gauge lands an instant after the wait_for
        # condition above, so poll it rather than race it.
        plane.wait_for(
            lambda: REGISTRY.gauge(names.TOPOLOGY_POSTURE,
                                   group=GROUP) == 1.0,
            timeout=10, desc="posture gauge settled")
        assert REGISTRY.counter(names.TOPOLOGY_FLIPS_TOTAL, group=GROUP,
                                target=POSTURE_DISAGG) == flips0 + 1


def test_controller_mid_flip_restart_resumes_from_annotations():
    script = {"fresh": True, "prefill_decode_ratio": 12.0, "judged": 20}
    plane, gt = _mk_plane(script)
    store = plane.store
    with plane:
        plane.apply(_mk_group(gt))
        plane.wait_group_ready(GROUP, timeout=30)
        plane.wait_for(lambda: _ann(plane, C.ANN_TOPOLOGY_STATE),
                       timeout=30, desc="flip started")
    # Plane died mid-flip. A FRESH plane over the same store (new
    # controller instance, no in-memory state) must resume the flip from
    # the annotations and complete it.
    assert _ann(plane, C.ANN_TOPOLOGY_STATE) in ("Warming", "CutOver",
                                                 "Draining")
    cfg2 = TopologyConfig(
              groups=[gt],
              policy=TopologyPolicyConfig(
                  disagg_ratio=6.0, unified_ratio=2.0, min_judged=3,
                  disagg_stabilization_s=0.1,
                  unified_stabilization_s=0.1, cooldown_s=0.3,
                  max_switch_cost_s=0.0),
              eval_period_s=0.05, window_s=2.0, stale_after_s=10.0,
              signals_fn=lambda _gt: dict(script))
    resumed = ControlPlane(store=store, backend="fake", topology=cfg2)
    with resumed:
        resumed.wait_for(
            lambda: _ann(resumed, C.ANN_TOPOLOGY_POSTURE)
            == POSTURE_DISAGG
            and not _ann(resumed, C.ANN_TOPOLOGY_STATE),
            timeout=30, desc="resumed flip completed")
        assert not resumed.store.list(
            "RoleInstance", namespace="default",
            selector={C.LABEL_GROUP_NAME: GROUP,
                      C.LABEL_ROLE_NAME: gt.unified_role})


def test_controller_autoscaler_conflict_backs_off():
    script = {"fresh": True, "prefill_decode_ratio": 12.0, "judged": 20}
    plane, gt = _mk_plane(script)
    conflicts0 = REGISTRY.counter(names.TOPOLOGY_CONFLICTS_TOTAL,
                                  group=GROUP)
    with plane:
        plane.apply(_mk_group(gt))
        plane.wait_group_ready(GROUP, timeout=30)
        sa_name = adapter_name(GROUP, gt.unified_role)
        plane.wait_for(
            lambda: plane.store.get("ScalingAdapter", "default", sa_name),
            timeout=30, desc="auto adapter")
        # Simulate an in-flight foreign/autoscaler write: stamp and
        # spec.replicas disagree — the flip must NOT start.
        def foreign(a):
            a.spec.replicas = 2
            a.metadata.annotations[C.ANN_AUTOSCALE_LAST_WRITE] = "1"
            return True
        plane.store.mutate("ScalingAdapter", "default", sa_name, foreign)
        plane.wait_for(
            lambda: REGISTRY.counter(names.TOPOLOGY_CONFLICTS_TOTAL,
                                     group=GROUP) > conflicts0,
            timeout=30, desc="conflict counted")
        assert _ann(plane, C.ANN_TOPOLOGY_STATE) is None
        # The stamping writer adopts (stamp catches up): the flip
        # proceeds on a later cycle — and the backoff did not burn the
        # policy cooldown.
        def adopt(a):
            a.metadata.annotations[C.ANN_AUTOSCALE_LAST_WRITE] = \
                str(a.spec.replicas)
            return True
        plane.store.mutate("ScalingAdapter", "default", sa_name, adopt)
        plane.wait_for(
            lambda: _ann(plane, C.ANN_TOPOLOGY_POSTURE) == POSTURE_DISAGG
            and not _ann(plane, C.ANN_TOPOLOGY_STATE),
            timeout=30, desc="flip after adoption")


def test_controller_holds_are_counted_and_status_reported():
    script = {"fresh": True, "prefill_decode_ratio": 4.0, "judged": 20}
    plane, gt = _mk_plane(script)
    holds0 = REGISTRY.counter(names.TOPOLOGY_HOLDS_TOTAL, group=GROUP,
                              reason="deadband")
    with plane:
        plane.apply(_mk_group(gt))
        plane.wait_group_ready(GROUP, timeout=30)
        plane.wait_for(
            lambda: REGISTRY.counter(names.TOPOLOGY_HOLDS_TOTAL,
                                     group=GROUP,
                                     reason="deadband") > holds0,
            timeout=30, desc="deadband hold counted")
        st = plane.topology_controller.status()
        row = next(r for r in st["groups"] if r["group"] == GROUP)
        assert row["posture"] == POSTURE_UNIFIED
        assert row["last_decision"]["suppressed"] == "deadband"
        # Kill switch: disabled groups hold with the reason reported.
        assert plane.topology_controller.set_enabled(GROUP, False)
        plane.wait_for(
            lambda: (plane.topology_controller.status()["groups"][0]
                     ["last_decision"] or {}).get("suppressed")
            == "disabled",
            timeout=30, desc="disabled hold")
        assert not plane.topology_controller.set_enabled("nope", False)


def test_controller_refuses_infeasible_flip_bounds():
    """Adapter bounds that make a flip un-completable (old shape with
    min_replicas > 0 can never drain; target capped under its plan) must
    refuse the flip UP FRONT — a visible retriable HOLD, never a
    permanent mid-flip wedge."""
    script = {"fresh": True, "prefill_decode_ratio": 12.0, "judged": 20}
    plane, gt = _mk_plane(script)
    holds0 = REGISTRY.counter(names.TOPOLOGY_HOLDS_TOTAL, group=GROUP,
                              reason="infeasible")
    with plane:
        plane.apply(_mk_group(gt))
        plane.wait_group_ready(GROUP, timeout=30)
        sa_name = adapter_name(GROUP, gt.unified_role)
        plane.wait_for(
            lambda: plane.store.get("ScalingAdapter", "default", sa_name),
            timeout=30, desc="auto adapter")
        def pin_min(a):
            a.spec.min_replicas = 1
            return True
        plane.store.mutate("ScalingAdapter", "default", sa_name, pin_min)
        plane.wait_for(
            lambda: REGISTRY.counter(names.TOPOLOGY_HOLDS_TOTAL,
                                     group=GROUP,
                                     reason="infeasible") > holds0,
            timeout=30, desc="infeasible hold counted")
        assert _ann(plane, C.ANN_TOPOLOGY_STATE) is None
        # Lifting the bound lets the same sustained pressure flip (the
        # refusal burned no cooldown).
        def unpin(a):
            a.spec.min_replicas = 0
            return True
        plane.store.mutate("ScalingAdapter", "default", sa_name, unpin)
        plane.wait_for(
            lambda: _ann(plane, C.ANN_TOPOLOGY_POSTURE) == POSTURE_DISAGG
            and not _ann(plane, C.ANN_TOPOLOGY_STATE),
            timeout=30, desc="flip after bound lift")


# ---- admin op --------------------------------------------------------------


def test_admin_topology_op_and_kill_switch():
    from rbg_tpu.engine.protocol import request_once
    from rbg_tpu.runtime.admin import AdminServer

    script = {"fresh": True, "prefill_decode_ratio": 4.0, "judged": 20}
    plane, gt = _mk_plane(script)
    admin = AdminServer(plane, port=0).start()
    addr = f"127.0.0.1:{admin.port}"
    try:
        with plane:
            plane.apply(_mk_group(gt))
            plane.wait_group_ready(GROUP, timeout=30)
            resp, _, _ = request_once(addr, {"op": "topology"})
            rows = resp["topology"]["groups"]
            assert rows and rows[0]["group"] == GROUP
            assert rows[0]["posture"] == POSTURE_UNIFIED
            resp, _, _ = request_once(addr, {"op": "topology",
                                             "disable": GROUP})
            assert not resp["topology"]["groups"][0]["enabled"]
            resp, _, _ = request_once(addr, {"op": "topology",
                                             "enable": "unknown"})
            assert "error" in resp
    finally:
        admin.stop()


# ---- router candidacy seam -------------------------------------------------


def test_router_candidacy_withdraws_roles():
    from rbg_tpu.engine.router import Registry, RouterState
    state = RouterState(Registry(None), None,
                        {"prefill": ["10.0.0.1:1"],
                         "decode": ["10.0.0.2:1"],
                         "unified": ["10.0.0.3:1"]})
    assert state.pd_mode()
    assert state.candidates("prefill")
    state.set_role_candidacy("prefill", False)
    state.set_role_candidacy("decode", False)
    # Withdrawn roles take no NEW requests; the unified role now fronts
    # generate traffic.
    assert not state.pd_mode()
    assert state.candidates("prefill") == []
    assert state.worker_role() == "unified"
    state.set_role_candidacy("prefill", True)
    state.set_role_candidacy("decode", True)
    assert state.pd_mode()
