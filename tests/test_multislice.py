"""Multi-slice (MEGASCALE) roles: sub-gang-per-slice placement + env contract."""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, tpu_leaderworker_role


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    # 4-host slices with 2-host sub-gangs: a single physical slice COULD fit
    # both sub-gangs — the scheduler must still split them across slices.
    make_tpu_nodes(p.store, slices=3, hosts_per_slice=4)
    with p:
        yield p


def test_multislice_instance_spans_slices(plane):
    role = tpu_leaderworker_role("train", replicas=1, topology="2x4")
    role.tpu.num_slices = 2  # 2 sub-gangs × 2 hosts = 4 pods
    plane.apply(make_group("ms", role))
    g = plane.wait_group_ready("ms", timeout=20)
    assert g.status.role("train").ready_replicas == 1

    pods = sorted(plane.store.list("Pod", namespace="default"),
                  key=lambda p: int(p.metadata.labels[C.LABEL_COMPONENT_INDEX]))
    assert len(pods) == 4
    nodes = {n.metadata.name: n for n in plane.store.list("Node")}

    # Sub-gang 0 (pods 0,1) on one slice; sub-gang 1 (pods 2,3) on another.
    s0 = {nodes[p.node_name].tpu.slice_id for p in pods[:2]}
    s1 = {nodes[p.node_name].tpu.slice_id for p in pods[2:]}
    assert len(s0) == 1 and len(s1) == 1
    assert s0 != s1, "multi-slice sub-gangs must land on distinct ICI domains"

    for p in pods:
        envs = {e.name: e.value for e in p.template.containers[0].env}
        idx = int(p.metadata.labels[C.LABEL_COMPONENT_INDEX])
        assert envs[C.ENV_JAX_NUM_PROCESSES] == "4"
        assert envs[C.ENV_JAX_PROCESS_ID] == str(idx)
        assert envs[C.ENV_MEGASCALE_NUM_SLICES] == "2"
        assert envs[C.ENV_MEGASCALE_SLICE_ID] == str(idx // 2)
        assert p.metadata.labels[C.LABEL_SLICE_ORDINAL] == str(idx // 2)
        assert C.ENV_MEGASCALE_COORDINATOR in envs
