"""OpenAI-compatible HTTP front end (VERDICT r3 missing #7): incremental
detokenization, completions + SSE streaming, and the PD-disagg streaming
e2e through real processes."""

import json
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from rbg_tpu.engine.tokenizer import ByteTokenizer, IncrementalDetokenizer


# ---- incremental detokenization ----


def test_incremental_detok_multibyte_boundaries():
    tok = ByteTokenizer()
    text = "héllo wörld 你好"
    ids = tok.encode(text, add_bos=False)
    detok = IncrementalDetokenizer(tok)
    out = []
    for i in ids:                      # one byte at a time — worst case
        out.append(detok.feed(i))
    joined = "".join(out) + detok.flush()
    assert joined == text
    # No chunk ever carries a replacement char.
    assert all("�" not in piece for piece in out)


def test_incremental_detok_flush_incomplete_tail():
    tok = ByteTokenizer()
    ids = tok.encode("ok 你", add_bos=False)
    detok = IncrementalDetokenizer(tok)
    emitted = detok.feed(ids[:-1])     # cut inside the multi-byte char
    assert emitted == "ok "
    assert "�" in detok.flush() or detok.flush() == ""


def test_incremental_detok_batch_feed_equals_full_decode():
    tok = ByteTokenizer()
    ids = tok.encode("streaming § text ≠ batch", add_bos=False)
    detok = IncrementalDetokenizer(tok)
    parts = [detok.feed(ids[:7]), detok.feed(ids[7:15]), detok.feed(ids[15:])]
    assert "".join(parts) + detok.flush() == tok.decode(ids)


# ---- subprocess plumbing ----


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_http(port, path="/healthz", timeout=180.0, expect_ok=True):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                body = json.loads(r.read())
                if not expect_ok or body.get("ok"):
                    return body
                last = body
        except Exception as e:  # noqa: BLE001 — retrying startup probe
            last = e
        time.sleep(0.3)
    raise TimeoutError(f"http {port}{path} never healthy: {last}")


def _post(port, path, body, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method="POST",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _sse_events(port, path, body, timeout=300):
    """POST and parse the SSE stream into a list of JSON payloads."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        assert resp.getheader("Content-Type") == "text/event-stream"
        events = []
        buf = b""
        while True:
            chunk = resp.read(1)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                raw, buf = buf.split(b"\n\n", 1)
                if not raw.startswith(b"data: "):
                    continue
                payload = raw[len(b"data: "):]
                if payload == b"[DONE]":
                    return events, True
                events.append(json.loads(payload))
        return events, False
    finally:
        conn.close()


ENGINE_ARGS = ["--model", "tiny", "--page-size", "8", "--num-pages", "128",
               "--max-seq-len", "256", "--prefill-chunk", "16",
               "--use-pallas", "never"]


@pytest.fixture(scope="module")
def stack():
    """prefill + decode + router + http frontend, all real processes —
    the pd-disagg-leader-worker.yaml shape with the HTTP edge."""
    from rbg_tpu.utils import scrubbed_cpu_env
    env = scrubbed_cpu_env()
    pf, dc, rt, fe = (_free_port() for _ in range(4))
    procs = []
    try:
        for mode, port in (("prefill", pf), ("decode", dc)):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "rbg_tpu.engine.server",
                 "--mode", mode, "--port", str(port)] + ENGINE_ARGS, env=env))
        backends = json.dumps({"prefill": [f"127.0.0.1:{pf}"],
                               "decode": [f"127.0.0.1:{dc}"]})
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.router",
             "--port", str(rt), "--backends", backends], env=env))
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.http_frontend",
             "--port", str(fe), "--host", "127.0.0.1",
             "--backend", f"127.0.0.1:{rt}", "--model", "tiny",
             "--default-max-tokens", "12"], env=env))
        # Engines report healthy only once their model is built.
        from rbg_tpu.engine.protocol import request_once
        for port in (pf, dc):
            deadline = time.monotonic() + 240
            while True:
                try:
                    h, _, _ = request_once(f"127.0.0.1:{port}",
                                           {"op": "health"}, timeout=5)
                    if h.get("ok"):
                        break
                except OSError:
                    pass
                assert time.monotonic() < deadline, f"engine {port} not ready"
                time.sleep(0.5)
        _wait_http(fe, timeout=240)
        yield fe
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.e2e
def test_models_and_health(stack):
    fe = stack
    body = _wait_http(fe, "/v1/models", expect_ok=False)
    assert body["data"][0]["id"] == "tiny"


@pytest.mark.e2e
def test_completions_nonstream_through_pd(stack):
    fe = stack
    resp = _post(fe, "/v1/completions",
                 {"model": "tiny", "prompt": "hello tpu", "max_tokens": 10})
    assert resp["object"] == "text_completion"
    choice = resp["choices"][0]
    assert choice["finish_reason"] in ("length", "stop")
    assert isinstance(choice["text"], str)
    assert resp["usage"]["completion_tokens"] == 10
    assert resp["usage"]["prompt_tokens"] == len("hello tpu")


@pytest.mark.e2e
def test_completions_sse_streaming_matches_nonstream(stack):
    fe = stack
    req = {"model": "tiny", "prompt": "stream me", "max_tokens": 12}
    full = _post(fe, "/v1/completions", req)["choices"][0]["text"]

    events, done = _sse_events(fe, "/v1/completions",
                               {**req, "stream": True})
    assert done, "stream must end with [DONE]"
    text_events = [e for e in events
                   if e["choices"][0].get("text")]
    assert len(text_events) >= 2, "streaming must chunk, not one blob"
    streamed = "".join(e["choices"][0]["text"] for e in events)
    assert streamed == full
    assert events[-1]["choices"][0]["finish_reason"] in ("length", "stop")


@pytest.mark.e2e
def test_chat_completions_stream(stack):
    fe = stack
    events, done = _sse_events(
        fe, "/v1/chat/completions",
        {"model": "tiny", "stream": True, "max_tokens": 8,
         "messages": [{"role": "user", "content": "hi"}]})
    assert done
    assert events[0]["choices"][0]["delta"].get("role") == "assistant"
    content = "".join(e["choices"][0]["delta"].get("content", "")
                      for e in events)
    assert isinstance(content, str)
    assert events[-1]["object"] == "chat.completion.chunk"


def test_incremental_detok_long_stream_commits_window():
    """The bounded commit window keeps per-feed work O(window) while the
    emitted stream stays byte-exact over a long generation."""
    tok = ByteTokenizer()
    text = ("héllo wörld 你好 " * 200)[:2000]
    ids = tok.encode(text, add_bos=False)
    detok = IncrementalDetokenizer(tok)
    out = []
    for i in range(0, len(ids), 3):
        out.append(detok.feed(ids[i:i + 3]))
    assert "".join(out) + detok.flush() == tok.decode(ids)
    # The tail must stay bounded (committed), not grow with the stream.
    assert len(detok._tail) <= 2 * IncrementalDetokenizer.WINDOW + 3
