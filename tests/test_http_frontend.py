"""OpenAI-compatible HTTP front end (VERDICT r3 missing #7): incremental
detokenization, completions + SSE streaming, and the PD-disagg streaming
e2e through real processes."""

import json
import socket
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

from rbg_tpu.engine.tokenizer import ByteTokenizer, IncrementalDetokenizer


# ---- incremental detokenization ----


def test_incremental_detok_multibyte_boundaries():
    tok = ByteTokenizer()
    text = "héllo wörld 你好"
    ids = tok.encode(text, add_bos=False)
    detok = IncrementalDetokenizer(tok)
    out = []
    for i in ids:                      # one byte at a time — worst case
        out.append(detok.feed(i))
    joined = "".join(out) + detok.flush()
    assert joined == text
    # No chunk ever carries a replacement char.
    assert all("�" not in piece for piece in out)


def test_incremental_detok_flush_incomplete_tail():
    tok = ByteTokenizer()
    ids = tok.encode("ok 你", add_bos=False)
    detok = IncrementalDetokenizer(tok)
    emitted = detok.feed(ids[:-1])     # cut inside the multi-byte char
    assert emitted == "ok "
    assert "�" in detok.flush() or detok.flush() == ""


def test_incremental_detok_batch_feed_equals_full_decode():
    tok = ByteTokenizer()
    ids = tok.encode("streaming § text ≠ batch", add_bos=False)
    detok = IncrementalDetokenizer(tok)
    parts = [detok.feed(ids[:7]), detok.feed(ids[7:15]), detok.feed(ids[15:])]
    assert "".join(parts) + detok.flush() == tok.decode(ids)


# ---- subprocess plumbing ----


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_http(port, path="/healthz", timeout=180.0, expect_ok=True):
    deadline = time.monotonic() + timeout
    last = None
    while time.monotonic() < deadline:
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}{path}", timeout=5) as r:
                body = json.loads(r.read())
                if not expect_ok or body.get("ok"):
                    return body
                last = body
        except Exception as e:  # noqa: BLE001 — retrying startup probe
            last = e
        time.sleep(0.3)
    raise TimeoutError(f"http {port}{path} never healthy: {last}")


def _post(port, path, body, timeout=300):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method="POST",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _sse_events(port, path, body, timeout=300):
    """POST and parse the SSE stream into a list of JSON payloads."""
    import http.client
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, body=json.dumps(body).encode(),
                     headers={"Content-Type": "application/json"})
        resp = conn.getresponse()
        assert resp.status == 200, resp.read()
        assert resp.getheader("Content-Type") == "text/event-stream"
        events = []
        buf = b""
        while True:
            chunk = resp.read(1)
            if not chunk:
                break
            buf += chunk
            while b"\n\n" in buf:
                raw, buf = buf.split(b"\n\n", 1)
                if not raw.startswith(b"data: "):
                    continue
                payload = raw[len(b"data: "):]
                if payload == b"[DONE]":
                    return events, True
                events.append(json.loads(payload))
        return events, False
    finally:
        conn.close()


ENGINE_ARGS = ["--model", "tiny", "--page-size", "8", "--num-pages", "128",
               "--max-seq-len", "256", "--prefill-chunk", "16",
               "--use-pallas", "never"]


@pytest.fixture(scope="module")
def stack():
    """prefill + decode + router + http frontend, all real processes —
    the pd-disagg-leader-worker.yaml shape with the HTTP edge."""
    from rbg_tpu.utils import scrubbed_cpu_env
    env = scrubbed_cpu_env()
    pf, dc, rt, fe = (_free_port() for _ in range(4))
    procs = []
    try:
        for mode, port in (("prefill", pf), ("decode", dc)):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "rbg_tpu.engine.server",
                 "--mode", mode, "--port", str(port)] + ENGINE_ARGS, env=env))
        backends = json.dumps({"prefill": [f"127.0.0.1:{pf}"],
                               "decode": [f"127.0.0.1:{dc}"]})
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.router",
             "--port", str(rt), "--backends", backends], env=env))
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.http_frontend",
             "--port", str(fe), "--host", "127.0.0.1",
             "--backend", f"127.0.0.1:{rt}", "--model", "tiny",
             "--default-max-tokens", "12"], env=env))
        # Engines report healthy only once their model is built.
        from rbg_tpu.engine.protocol import request_once
        for port in (pf, dc):
            deadline = time.monotonic() + 240
            while True:
                try:
                    h, _, _ = request_once(f"127.0.0.1:{port}",
                                           {"op": "health"}, timeout=5)
                    if h.get("ok"):
                        break
                except OSError:
                    pass
                assert time.monotonic() < deadline, f"engine {port} not ready"
                time.sleep(0.5)
        _wait_http(fe, timeout=240)
        yield fe
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


@pytest.mark.e2e
def test_models_and_health(stack):
    fe = stack
    body = _wait_http(fe, "/v1/models", expect_ok=False)
    assert body["data"][0]["id"] == "tiny"


@pytest.mark.e2e
@pytest.mark.slow
def test_completions_nonstream_through_pd(stack):
    fe = stack
    resp = _post(fe, "/v1/completions",
                 {"model": "tiny", "prompt": "hello tpu", "max_tokens": 10})
    assert resp["object"] == "text_completion"
    choice = resp["choices"][0]
    assert choice["finish_reason"] in ("length", "stop")
    assert isinstance(choice["text"], str)
    assert resp["usage"]["completion_tokens"] == 10
    assert resp["usage"]["prompt_tokens"] == len("hello tpu")


@pytest.mark.e2e
def test_completions_sse_streaming_matches_nonstream(stack):
    fe = stack
    req = {"model": "tiny", "prompt": "stream me", "max_tokens": 12}
    full = _post(fe, "/v1/completions", req)["choices"][0]["text"]

    events, done = _sse_events(fe, "/v1/completions",
                               {**req, "stream": True})
    assert done, "stream must end with [DONE]"
    text_events = [e for e in events
                   if e["choices"][0].get("text")]
    assert len(text_events) >= 2, "streaming must chunk, not one blob"
    streamed = "".join(e["choices"][0]["text"] for e in events)
    assert streamed == full
    assert events[-1]["choices"][0]["finish_reason"] in ("length", "stop")


@pytest.mark.e2e
def test_chat_completions_stream(stack):
    fe = stack
    events, done = _sse_events(
        fe, "/v1/chat/completions",
        {"model": "tiny", "stream": True, "max_tokens": 8,
         "messages": [{"role": "user", "content": "hi"}]})
    assert done
    assert events[0]["choices"][0]["delta"].get("role") == "assistant"
    content = "".join(e["choices"][0]["delta"].get("content", "")
                      for e in events)
    assert isinstance(content, str)
    assert events[-1]["object"] == "chat.completion.chunk"


def test_incremental_detok_long_stream_commits_window():
    """The bounded commit window keeps per-feed work O(window) while the
    emitted stream stays byte-exact over a long generation."""
    tok = ByteTokenizer()
    text = ("héllo wörld 你好 " * 200)[:2000]
    ids = tok.encode(text, add_bos=False)
    detok = IncrementalDetokenizer(tok)
    out = []
    for i in range(0, len(ids), 3):
        out.append(detok.feed(ids[i:i + 3]))
    assert "".join(out) + detok.flush() == tok.decode(ids)
    # The tail must stay bounded (committed), not grow with the stream.
    assert len(detok._tail) <= 2 * IncrementalDetokenizer.WINDOW + 3


# ---- front-end logic against a scripted backend (no JAX, fast) ----


class _MockBackend:
    """Protocol-speaking TCP backend returning canned token streams —
    isolates front-end behavior (stop strings, logprobs shaping, param
    forwarding) from engine nondeterminism."""

    def __init__(self, tokens, logprobs=None, frame_size=3):
        import socketserver

        from rbg_tpu.engine.protocol import recv_msg, send_msg
        self.seen = []
        mock = self

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        obj, _, _ = recv_msg(self.request)
                    except (ConnectionError, json.JSONDecodeError):
                        return
                    if obj is None:
                        return
                    if obj.get("op") == "health":
                        send_msg(self.request, {"ok": True, "mode": "unified"})
                        continue
                    mock.seen.append(obj)
                    toks, lps = list(tokens), logprobs and list(logprobs)
                    if obj.get("stream"):
                        for i in range(0, len(toks), frame_size):
                            frame = {"tokens": toks[i:i + frame_size],
                                     "done": False}
                            if lps and obj.get("logprobs"):
                                frame["logprobs"] = lps[i:i + frame_size]
                            send_msg(self.request, frame)
                        send_msg(self.request,
                                 {"tokens": [], "done": True, "ttft_s": 0.01})
                    else:
                        resp = {"tokens": toks, "ttft_s": 0.01}
                        if lps and obj.get("logprobs"):
                            resp["logprobs"] = lps
                        send_msg(self.request, resp)

        import socketserver
        self.server = socketserver.ThreadingTCPServer(("127.0.0.1", 0), H)
        self.server.daemon_threads = True
        self.port = self.server.server_address[1]
        threading.Thread(target=self.server.serve_forever, daemon=True).start()

    def close(self):
        self.server.shutdown()
        self.server.server_close()


def _frontend_for(backend_port):
    from rbg_tpu.engine import http_frontend as hf
    ns = type("A", (), {})()
    ns.host, ns.port = "127.0.0.1", _free_port()
    ns.backend = f"127.0.0.1:{backend_port}"
    ns.model, ns.tokenizer_path, ns.default_max_tokens = "tiny", "", 16
    server = hf.serve(ns)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    return server, ns.port


def _canned(text, logprobs=False, frame_size=3):
    tok = ByteTokenizer()
    ids = tok.encode(text, add_bos=False)
    lps = [-0.5] * len(ids) if logprobs else None
    return _MockBackend(ids, lps, frame_size=frame_size)


def test_stop_string_truncates_nonstream():
    be = _canned("hello STOP world")
    fe, port = _frontend_for(be.port)
    try:
        resp = _post(port, "/v1/completions",
                     {"prompt": "x", "stop": ["STOP"], "max_tokens": 32})
        c = resp["choices"][0]
        assert c["text"] == "hello "
        assert c["finish_reason"] == "stop"
    finally:
        fe.shutdown(); be.close()


def test_stop_string_streaming_holdback():
    # Frames of 2 bytes force the stop string to arrive split across
    # frames — the hold-back buffer must still cut exactly before it.
    be = _canned("ab STOP tail", frame_size=2)
    fe, port = _frontend_for(be.port)
    try:
        events, done = _sse_events(port, "/v1/completions",
                                   {"prompt": "x", "stop": "STOP",
                                    "max_tokens": 32, "stream": True})
        assert done
        text = "".join(e["choices"][0]["text"] for e in events)
        assert text == "ab "
        finishes = [e["choices"][0]["finish_reason"] for e in events]
        assert finishes[-1] == "stop"
    finally:
        fe.shutdown(); be.close()


def test_streaming_no_stop_passthrough_unchanged():
    be = _canned("plain text out", frame_size=4)
    fe, port = _frontend_for(be.port)
    try:
        events, done = _sse_events(port, "/v1/completions",
                                   {"prompt": "x", "max_tokens": 32,
                                    "stream": True})
        assert done
        text = "".join(e["choices"][0]["text"] for e in events)
        assert text == "plain text out"
    finally:
        fe.shutdown(); be.close()


def test_logprobs_shapes_completions_and_chat():
    be = _canned("abc", logprobs=True)
    fe, port = _frontend_for(be.port)
    try:
        resp = _post(port, "/v1/completions",
                     {"prompt": "x", "logprobs": 1, "max_tokens": 8})
        lp = resp["choices"][0]["logprobs"]
        assert lp["token_logprobs"] == [-0.5] * 3
        assert lp["tokens"] == ["a", "b", "c"]
        resp = _post(port, "/v1/chat/completions",
                     {"messages": [{"role": "user", "content": "x"}],
                      "logprobs": True, "max_tokens": 8})
        lp = resp["choices"][0]["logprobs"]
        assert [e["logprob"] for e in lp["content"]] == [-0.5] * 3
    finally:
        fe.shutdown(); be.close()


def test_sampling_fields_forwarded_to_backend():
    be = _canned("ok")
    fe, port = _frontend_for(be.port)
    try:
        _post(port, "/v1/completions",
              {"prompt": "x", "temperature": 0.7, "top_p": 0.9,
               "min_p": 0.05, "top_k": 40, "seed": 123,
               "presence_penalty": 0.1, "frequency_penalty": 0.2,
               "repetition_penalty": 1.1, "max_tokens": 4})
        seen = be.seen[-1]
        assert seen["temperature"] == 0.7 and seen["top_p"] == 0.9
        assert seen["min_p"] == 0.05 and seen["top_k"] == 40
        assert seen["seed"] == 123
        assert seen["presence_penalty"] == 0.1
        assert seen["frequency_penalty"] == 0.2
        assert seen["repetition_penalty"] == 1.1
    finally:
        fe.shutdown(); be.close()


def test_stop_truncates_logprobs_and_usage():
    # "hello STOP world" with stop → kept tokens = len("hello ") (byte
    # tokenizer: 1 token per char), and logprobs/usage must shrink with it.
    be = _canned("hello STOP world", logprobs=True)
    fe, port = _frontend_for(be.port)
    try:
        resp = _post(port, "/v1/completions",
                     {"prompt": "x", "stop": ["STOP"], "logprobs": 1,
                      "max_tokens": 32})
        c = resp["choices"][0]
        assert c["text"] == "hello "
        assert len(c["logprobs"]["token_logprobs"]) == len("hello ")
        assert resp["usage"]["completion_tokens"] == len("hello ")
    finally:
        fe.shutdown(); be.close()


def test_streaming_logprobs_chunks():
    be = _canned("abcdef", logprobs=True, frame_size=2)
    fe, port = _frontend_for(be.port)
    try:
        events, done = _sse_events(port, "/v1/completions",
                                   {"prompt": "x", "logprobs": 1,
                                    "max_tokens": 32, "stream": True})
        assert done
        lps = []
        for e in events:
            lp = e["choices"][0].get("logprobs")
            if lp:
                lps.extend(lp["token_logprobs"])
        assert lps == [-0.5] * 6
        text = "".join(e["choices"][0]["text"] for e in events)
        assert text == "abcdef"
        # chat shape too
        events, done = _sse_events(port, "/v1/chat/completions",
                                   {"messages": [{"role": "user",
                                                  "content": "x"}],
                                    "logprobs": True, "max_tokens": 32,
                                    "stream": True})
        assert done
        toks = []
        for e in events:
            lp = e["choices"][0].get("logprobs")
            if lp:
                toks.extend(lp["content"])
        assert [t["logprob"] for t in toks] == [-0.5] * 6
    finally:
        fe.shutdown(); be.close()


@pytest.mark.e2e
def test_pd_logprobs_first_token_null(stack):
    fe = stack
    resp = _post(fe, "/v1/completions",
                 {"model": "tiny", "prompt": "lp", "max_tokens": 6,
                  "logprobs": 1})
    lp = resp["choices"][0]["logprobs"]
    lps = lp["token_logprobs"]
    assert len(lps) == 6
    assert lps[0] is None               # prefill-side token: no logprob
    assert all(isinstance(v, float) and v <= 0 for v in lps[1:])


def test_invalid_sampling_params_return_400():
    be = _canned("ok")
    fe, port = _frontend_for(be.port)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/completions", method="POST",
            data=json.dumps({"prompt": "x", "temperature": -1}).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected HTTPError")
        except urllib.error.HTTPError as e:
            assert e.code == 400
            body = json.loads(e.read())
            assert body["error"]["type"] == "invalid_request_error"
    finally:
        fe.shutdown(); be.close()


def test_stop_at_offset_zero_reports_empty():
    be = _canned("STOP right away", logprobs=True)
    fe, port = _frontend_for(be.port)
    try:
        resp = _post(port, "/v1/completions",
                     {"prompt": "x", "stop": ["STOP"], "logprobs": 1,
                      "max_tokens": 32})
        c = resp["choices"][0]
        assert c["text"] == "" and c["finish_reason"] == "stop"
        assert resp["usage"]["completion_tokens"] == 0
        assert c["logprobs"] is None or c["logprobs"]["token_logprobs"] == []
    finally:
        fe.shutdown(); be.close()


def test_streaming_stop_logprobs_match_emitted_text():
    # Stop + logprobs in a stream: exactly one logprobs chunk, truncated to
    # the emitted text, mirroring the non-stream contract.
    be = _canned("hello STOP world", logprobs=True, frame_size=2)
    fe, port = _frontend_for(be.port)
    try:
        events, done = _sse_events(port, "/v1/completions",
                                   {"prompt": "x", "stop": ["STOP"],
                                    "logprobs": 1, "max_tokens": 32,
                                    "stream": True})
        assert done
        text = "".join(e["choices"][0]["text"] for e in events)
        assert text == "hello "
        lp_chunks = [e["choices"][0]["logprobs"] for e in events
                     if e["choices"][0].get("logprobs")]
        assert len(lp_chunks) == 1
        assert lp_chunks[0]["token_logprobs"] == [-0.5] * len("hello ")
        assert "".join(lp_chunks[0]["tokens"]) == "hello "
    finally:
        fe.shutdown(); be.close()


def test_non_numeric_sampling_fields_return_400():
    be = _canned("ok")
    fe, port = _frontend_for(be.port)
    try:
        for bad in ({"temperature": "hot"}, {"max_tokens": "abc"},
                    {"top_p": None}):
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/v1/completions", method="POST",
                data=json.dumps({"prompt": "x", **bad}).encode(),
                headers={"Content-Type": "application/json"})
            try:
                urllib.request.urlopen(req, timeout=30)
                raise AssertionError(f"expected 400 for {bad}")
            except urllib.error.HTTPError as e:
                assert e.code == 400, (bad, e.code)
    finally:
        fe.shutdown(); be.close()


def test_response_format_json_forwarded():
    be = _canned("ok")
    fe, port = _frontend_for(be.port)
    try:
        _post(port, "/v1/chat/completions",
              {"messages": [{"role": "user", "content": "x"}],
               "response_format": {"type": "json_object"}, "max_tokens": 4})
        assert be.seen[-1].get("json_mode") is True
        _post(port, "/v1/completions", {"prompt": "x", "max_tokens": 4})
        assert "json_mode" not in be.seen[-1]
    finally:
        fe.shutdown(); be.close()


def test_unsupported_response_format_returns_400():
    be = _canned("ok")
    fe, port = _frontend_for(be.port)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v1/chat/completions", method="POST",
            data=json.dumps({"messages": [{"role": "user", "content": "x"}],
                             "response_format": {"type": "json_schema"}}
                            ).encode(),
            headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(req, timeout=30)
            raise AssertionError("expected 400")
        except urllib.error.HTTPError as e:
            assert e.code == 400
    finally:
        fe.shutdown(); be.close()


def test_request_id_stamped_and_echoed():
    """Every POST gets an X-Request-Id: the caller's value is echoed back
    verbatim; without one the edge stamps (and returns) a fresh id."""
    import http.client
    be = _canned("hi")
    fe, port = _frontend_for(be.port)
    try:
        body = json.dumps({"prompt": "x", "max_tokens": 4}).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/completions", body,
                     headers={"Content-Type": "application/json",
                              "X-Request-Id": "req-mine-42"})
        r = conn.getresponse()
        r.read()
        assert r.status == 200
        assert r.getheader("X-Request-Id") == "req-mine-42"
        conn.request("POST", "/v1/completions", body,
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        r.read()
        stamped = r.getheader("X-Request-Id")
        assert stamped and stamped.startswith("req-")
        conn.close()
    finally:
        fe.shutdown(); be.close()


def test_traceparent_ingress_continues_trace_and_injects_wire_ctx():
    """With tracing armed, a W3C traceparent header continues the
    client's trace: the edge's http.request span parents under it, the
    backend request carries the wire context, and the finalized record is
    complete. With tracing off, requests stay untouched (no trace key)."""
    import http.client

    from rbg_tpu.obs import trace

    be = _canned("hi")
    fe, port = _frontend_for(be.port)
    old = (trace._CFG.enabled, trace._CFG.sample, trace._CFG.strict)
    trace.configure(enabled=True, sample=1.0, strict=False)
    trace.SINK.reset()
    try:
        tid, parent = "ab" * 16, "cd" * 8
        body = json.dumps({"prompt": "x", "max_tokens": 4}).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=30)
        conn.request("POST", "/v1/completions", body,
                     headers={"Content-Type": "application/json",
                              "traceparent": f"00-{tid}-{parent}-01"})
        r = conn.getresponse()
        r.read()
        assert r.status == 200
        wire = be.seen[-1].get("trace")
        assert wire and wire["trace_id"] == tid and wire["sampled"]
        # The span END happens on the handler thread after the reply: poll.
        deadline = time.monotonic() + 10.0
        recs = []
        while time.monotonic() < deadline and not recs:
            recs = [rec for rec in trace.SINK.recent(10)
                    if rec["trace_id"] == tid]
            time.sleep(0.01)
        assert recs, "edge span never finalized"
        span = recs[0]["spans"][0]
        assert span["name"] == "http.request"
        assert span["parent_id"] == parent          # continued, not re-rooted
        assert span["attrs"]["status"] == 200
        assert span["attrs"]["path"] == "/v1/completions"
        assert recs[0]["complete"]
        # The backend saw the edge span (not the remote parent) as parent.
        assert wire["parent_id"] == span["span_id"]

        # Tracing off: zero footprint on the wire.
        trace.configure(enabled=False)
        conn.request("POST", "/v1/completions", body,
                     headers={"Content-Type": "application/json"})
        r = conn.getresponse()
        r.read()
        assert "trace" not in be.seen[-1]
        conn.close()
    finally:
        trace.configure(enabled=old[0], sample=old[1], strict=old[2])
        trace.SINK.reset()
        fe.shutdown(); be.close()
