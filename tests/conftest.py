"""Test bootstrap: force an 8-device virtual CPU platform BEFORE jax imports.

Mirrors the reference's envtest approach (SURVEY.md §4: real apiserver, no
kubelet, synthetic status) — here: real XLA, no TPU, virtual 8-device mesh.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment may pre-import jax (site customization registering a TPU
# plugin), in which case env vars above are too late — force via config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax (< 0.5) has no jax_num_cpu_devices option; there the
    # XLA_FLAGS env var above (set before any backend touch) is the only
    # device-count knob — and sufficient unless jax was pre-imported.
    pass

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from rbg_tpu.parallel import make_mesh
    return make_mesh(dp=2, sp=2, tp=2)


class SpawnedEngineServer:
    """Shared spawn-server + health-poll boilerplate for subprocess e2e
    tests (the pattern previously copy-pasted per test file). Scrubs the
    CPU env AND ambient data-plane/port vars so a developer's exported
    RBG_DATA_TOKEN / RBG_SERVE_PORT never silently arms a gate or
    rebinds the port under the test.

        with SpawnedEngineServer("--model", "tiny", ...) as srv:
            request_once(srv.addr, {...})
    """

    def __init__(self, *args, env_extra=None, timeout=240.0):
        import socket
        import subprocess
        import sys as _sys

        from rbg_tpu.utils import scrubbed_cpu_env

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            self.port = s.getsockname()[1]
        env = scrubbed_cpu_env(extra={
            "RBG_DATA_TOKEN": None, "RBG_SERVE_PORT": str(self.port),
            "RBG_PORT_SERVE": None, **(env_extra or {})})
        self.addr = f"127.0.0.1:{self.port}"
        self.timeout = timeout
        self.proc = subprocess.Popen(
            [_sys.executable, "-m", "rbg_tpu.engine.server", *args],
            env=env, stdout=__import__("subprocess").DEVNULL,
            stderr=__import__("subprocess").DEVNULL)

    def wait_ready(self):
        import time

        from rbg_tpu.engine.protocol import request_once
        deadline = time.monotonic() + self.timeout
        while True:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"engine server died at startup rc={self.proc.returncode}")
            try:
                h, _, _ = request_once(self.addr, {"op": "health"}, timeout=2)
                if h and h.get("ok"):
                    return self
            except OSError:
                pass
            assert time.monotonic() < deadline, "server never healthy"
            time.sleep(0.3)

    def __enter__(self):
        return self.wait_ready()

    def __exit__(self, *exc):
        self.proc.terminate()
        self.proc.wait()
