"""Test bootstrap: force an 8-device virtual CPU platform BEFORE jax imports.

Mirrors the reference's envtest approach (SURVEY.md §4: real apiserver, no
kubelet, synthetic status) — here: real XLA, no TPU, virtual 8-device mesh.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# The environment may pre-import jax (site customization registering a TPU
# plugin), in which case env vars above are too late — force via config.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from rbg_tpu.parallel import make_mesh
    return make_mesh(dp=2, sp=2, tp=2)
