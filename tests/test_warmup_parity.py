"""Warmup parity (VERDICT r3 #5): per-image pull containers, per-role
actions, and scheduler-routed placement with capacity admission.

Reference: ``rolebasedgroupwarmup_controller.go:535`` (buildWarmupPod),
types ``:34-249``."""

import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.pod import Container, Node, PodTemplate
from rbg_tpu.api.policy import (ImagePreload, Warmup, WarmupActions,
                                WarmupTarget)
from rbg_tpu.runtime.controllers.warmup import (LABEL_WARMUP_NAME,
                                                LABEL_WARMUP_NODE)
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


def make_warmup(name, **spec_kw):
    w = Warmup()
    w.metadata.name = name
    w.metadata.namespace = "default"
    for k, v in spec_kw.items():
        setattr(w.spec, k, v)
    return w


def warmup_pods(plane, name):
    return plane.store.list("Pod", namespace="default",
                            selector={LABEL_WARMUP_NAME: name})


def test_image_preload_and_custom_containers():
    """Per-image pull containers (deduped) + custom containers (content-
    deduped, renamed) in one pod, per buildWarmupPod."""
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=1)
    with plane:
        custom = Container(name="prime", image="tool:v1",
                           command=["prime-cache"])
        w = make_warmup(
            "w1",
            target=WarmupTarget(nodes=["slice-0-host-0"]),
            actions=WarmupActions(
                image_preload=ImagePreload(
                    images=["engine:v1", "engine:v2", "engine:v1"],
                    pull_secrets=["regcred"]),
                containers=[custom, custom],   # duplicate → deduped
            ),
        )
        plane.apply(w)
        plane.wait_for(
            lambda: plane.store.get("Warmup", "default", "w1")
            .status.phase == "Succeeded", desc="warmup done")
        pods = warmup_pods(plane, "w1")
        assert len(pods) == 1
        ctrs = pods[0].template.containers
        names = [c.name for c in ctrs]
        assert names == ["image-preload-0", "image-preload-1", "custom-2"]
        assert [c.image for c in ctrs[:2]] == ["engine:v1", "engine:v2"]
        assert ctrs[0].command == ["sh", "-c", "exit 0"]
        assert ctrs[2].command == ["prime-cache"]
        assert pods[0].template.annotations[
            f"{C.DOMAIN}/image-pull-secrets"] == "regcred"


def test_group_targeted_per_role_actions():
    """TargetRoleBasedGroup semantics: each node receives the union of the
    actions of the roles whose pods it hosts."""
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=1)
    with plane:
        g = make_group("svc", simple_role("prefill", replicas=1),
                       simple_role("decode", replicas=1))
        plane.apply(g)
        plane.wait_group_ready("svc", timeout=10)
        # Force-verify the two roles landed on distinct nodes.
        by_role = {}
        for p in plane.store.list("Pod", namespace="default",
                                  selector={C.LABEL_GROUP_NAME: "svc"}):
            by_role[p.metadata.labels[C.LABEL_ROLE_NAME]] = p.node_name
        assert len(set(by_role.values())) == 2

        w = make_warmup(
            "w2",
            target=WarmupTarget(group_name="svc", roles={
                "prefill": WarmupActions(
                    image_preload=ImagePreload(images=["prefill-img:v1"])),
                "decode": WarmupActions(
                    image_preload=ImagePreload(images=["decode-img:v1"])),
            }),
        )
        plane.apply(w)
        plane.wait_for(
            lambda: plane.store.get("Warmup", "default", "w2")
            .status.phase == "Succeeded", desc="warmup done")
        for pod in warmup_pods(plane, "w2"):
            node = pod.metadata.labels[LABEL_WARMUP_NODE]
            images = [c.image for c in pod.template.containers]
            if node == by_role["prefill"]:
                assert images == ["prefill-img:v1"]
            else:
                assert images == ["decode-img:v1"]


def test_warmup_routes_through_scheduler():
    """Warmup pods are NOT direct-bound: the scheduler places them (with
    required node affinity), so capacity admission applies (VERDICT r3
    weak #3)."""
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=2)
    with plane:
        w = make_warmup(
            "w3", target=WarmupTarget(nodes=["slice-0-host-1"]),
            actions=WarmupActions(
                image_preload=ImagePreload(images=["engine:v1"])))
        plane.apply(w)
        plane.wait_for(
            lambda: plane.store.get("Warmup", "default", "w3")
            .status.phase == "Succeeded", desc="warmup done")
        (pod,) = warmup_pods(plane, "w3")
        # The binding came from the scheduler honoring required affinity.
        assert pod.node_name == "slice-0-host-1"
        assert pod.affinity and pod.affinity[0].required
        assert pod.affinity[0].values == ["slice-0-host-1"]


def test_warmup_rejected_on_full_node():
    """A warmup targeting a node with no free pod capacity must NOT run
    there — it stays unscheduled and the warmup times out Failed, instead
    of overcommitting the host behind the scheduler's back."""
    plane = ControlPlane(backend="fake")
    nodes = make_tpu_nodes(plane.store, slices=1, hosts_per_slice=1)
    # Shrink capacity to exactly the filler pod.
    def shrink(n):
        n.capacity_pods = 1
        return True
    plane.store.mutate("Node", "default", nodes[0].metadata.name, shrink)
    with plane:
        g = make_group("filler", simple_role("srv", replicas=1))
        plane.apply(g)
        plane.wait_group_ready("filler", timeout=10)

        w = make_warmup(
            "w4", target=WarmupTarget(nodes=["slice-0-host-0"]),
            actions=WarmupActions(
                image_preload=ImagePreload(images=["engine:v1"])),
            timeout_seconds=1.5)
        plane.apply(w)
        plane.wait_for(
            lambda: plane.store.get("Warmup", "default", "w4")
            .status.phase == "Failed", timeout=15, desc="warmup times out")
        for pod in warmup_pods(plane, "w4"):
            assert not pod.node_name, "warmup overcommitted a full node"


def test_legacy_template_still_works():
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=1)
    with plane:
        w = make_warmup(
            "w5", target=WarmupTarget(nodes=["slice-0-host-0"]),
            template=PodTemplate(containers=[Container(
                name="warm", image="engine:v1", command=["warm"])]))
        plane.apply(w)
        plane.wait_for(
            lambda: plane.store.get("Warmup", "default", "w5")
            .status.phase == "Succeeded", desc="warmup done")
        (pod,) = warmup_pods(plane, "w5")
        assert pod.template.containers[0].name == "warm"


def test_roles_target_skips_unlisted_role_nodes():
    """A roles-targeted warmup must not create pods on group nodes that
    host only UNLISTED roles."""
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=1)
    with plane:
        g = make_group("svc", simple_role("prefill", replicas=1),
                       simple_role("decode", replicas=1))
        plane.apply(g)
        plane.wait_group_ready("svc", timeout=10)
        by_role = {}
        for p in plane.store.list("Pod", namespace="default",
                                  selector={C.LABEL_GROUP_NAME: "svc"}):
            by_role[p.metadata.labels[C.LABEL_ROLE_NAME]] = p.node_name
        assert len(set(by_role.values())) == 2

        w = make_warmup(
            "w6",
            target=WarmupTarget(group_name="svc", roles={
                "prefill": WarmupActions(
                    image_preload=ImagePreload(images=["prefill-img:v1"])),
            }),
        )
        plane.apply(w)
        plane.wait_for(
            lambda: plane.store.get("Warmup", "default", "w6")
            .status.phase == "Succeeded", desc="warmup done")
        pods = warmup_pods(plane, "w6")
        assert len(pods) == 1
        assert pods[0].metadata.labels[LABEL_WARMUP_NODE] == by_role["prefill"]
        w_obj = plane.store.get("Warmup", "default", "w6")
        assert w_obj.status.desired_nodes == 1
