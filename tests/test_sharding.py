"""Multi-device sharding: tp/dp/sp-sharded forward equals single-device, and a
sharded train step runs and reduces loss (8 virtual CPU devices)."""

import pytest

import jax
import jax.numpy as jnp
import numpy as np

from rbg_tpu.models import KVCache, forward, get_config, init_params
from rbg_tpu.models.training import next_token_loss, train_n_steps
from rbg_tpu.parallel import (
    cache_specs, make_mesh, named, param_specs, shard_pytree, tokens_spec,
)


def test_mesh_axes(mesh8):
    assert mesh8.axis_names == ("dp", "sp", "ep", "tp")
    assert mesh8.devices.size == 8


def test_sharded_forward_matches_single_device(mesh8):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    B, T, S = 4, 8, 16
    tokens = jax.random.randint(jax.random.key(1), (B, T), 0, cfg.vocab_size)
    cache = KVCache.create(cfg, B, S)

    ref_logits, ref_cache = jax.jit(
        lambda p, t, c: forward(p, cfg, t, c)
    )(params, tokens, cache)

    p_sh = shard_pytree(params, param_specs(cfg), mesh8)
    c_specs = cache_specs()
    c_sh = KVCache(
        k=jax.device_put(cache.k, jax.sharding.NamedSharding(mesh8, c_specs["k"])),
        v=jax.device_put(cache.v, jax.sharding.NamedSharding(mesh8, c_specs["v"])),
        length=jax.device_put(cache.length, jax.sharding.NamedSharding(mesh8, c_specs["length"])),
    )
    t_sh = jax.device_put(tokens, jax.sharding.NamedSharding(mesh8, tokens_spec()))

    logits, out_cache = jax.jit(lambda p, t, c: forward(p, cfg, t, c))(p_sh, t_sh, c_sh)

    np.testing.assert_allclose(np.asarray(ref_logits), np.asarray(logits), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(ref_cache.k), np.asarray(out_cache.k), rtol=1e-4, atol=1e-4
    )


@pytest.mark.slow
def test_train_step_reduces_loss(mesh8):
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    B, T = 4, 16
    tokens = jax.random.randint(jax.random.key(2), (B, T), 0, cfg.vocab_size)
    loss0 = float(next_token_loss(params, cfg, tokens))
    _, loss = train_n_steps(cfg, mesh8, params, tokens, n=5)
    assert float(loss) < loss0


@pytest.mark.slow
def test_remat_grads_match(mesh8):
    """jax.checkpoint rematerialization changes memory, not math."""
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(9), (2, 16), 0, cfg.vocab_size)

    g_plain = jax.grad(lambda p: next_token_loss(p, cfg, tokens))(params)
    g_remat = jax.grad(lambda p: next_token_loss(p, cfg, tokens, remat=True))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        g_plain, g_remat,
    )

    # remat composes with ring attention (sp mesh) too — under jit, as the
    # train step always is (checkpoint-of-shard_map has no eager path)
    g_ring = jax.jit(jax.grad(
        lambda p: next_token_loss(p, cfg, tokens, mesh=mesh8, remat=True)
    ))(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5),
        g_plain, g_ring,
    )
