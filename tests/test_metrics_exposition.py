"""Prometheus exposition hygiene (obs/metrics.py) + profiler folded
stacks: # HELP/# TYPE metadata, label-value escaping, the finite-max
overflow quantile, and trace exemplars per histogram bucket."""

import threading

import pytest

from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import _BUCKETS, Registry, _fmt


@pytest.fixture()
def reg():
    return Registry(strict=False)


def test_render_emits_type_and_help_metadata(reg):
    reg.inc(names.SERVING_SHED_TOTAL, reason="queue_full")
    reg.set_gauge(names.SERVING_DRAINING, 1.0)
    reg.observe(names.RECONCILE_DURATION_SECONDS, 0.2, controller="rbg")
    text = reg.render()
    lines = text.splitlines()
    assert f"# TYPE {names.SERVING_SHED_TOTAL} counter" in lines
    assert f"# TYPE {names.SERVING_DRAINING} gauge" in lines
    assert f"# TYPE {names.RECONCILE_DURATION_SECONDS} histogram" in lines
    for metric in (names.SERVING_SHED_TOTAL, names.SERVING_DRAINING,
                   names.RECONCILE_DURATION_SECONDS):
        help_line = next(ln for ln in lines
                         if ln.startswith(f"# HELP {metric} "))
        assert help_line == f"# HELP {metric} {names.HELP[metric]}"
        # Metadata precedes the first sample of its family, exactly once.
        assert text.count(f"# TYPE {metric} ") == 1
    # Every # TYPE line sits before its family's first sample line.
    first_sample = next(i for i, ln in enumerate(lines)
                        if ln.startswith(names.SERVING_SHED_TOTAL))
    type_line = lines.index(f"# TYPE {names.SERVING_SHED_TOTAL} counter")
    assert type_line < first_sample


def test_type_emitted_once_across_label_sets(reg):
    reg.inc(names.SERVING_SHED_TOTAL, reason="a")
    reg.inc(names.SERVING_SHED_TOTAL, reason="b")
    assert reg.render().count(f"# TYPE {names.SERVING_SHED_TOTAL}") == 1


def test_label_values_escape_quotes_backslashes_newlines(reg):
    reg.inc(names.SERVING_SHED_TOTAL,
            reason='queue "full" at C:\\dev\nnow')
    text = reg.render()
    line = next(ln for ln in text.splitlines()
                if ln.startswith(names.SERVING_SHED_TOTAL))
    assert 'reason="queue \\"full\\" at C:\\\\dev\\nnow"' in line
    # The exposition stays one-sample-per-line parseable.
    assert "\n" not in line


def test_fmt_escaping_unit():
    assert _fmt((("k", 'a"b'),)) == '{k="a\\"b"}'
    assert _fmt((("k", "a\\b"),)) == '{k="a\\\\b"}'
    assert _fmt((("k", "a\nb"),)) == '{k="a\\nb"}'


def test_quantile_overflow_bucket_returns_observed_max(reg):
    top = _BUCKETS[-1]
    for v in (top + 1.0, top + 2.0, top + 7.5):
        reg.observe(names.RECONCILE_DURATION_SECONDS, v, controller="c")
    # Every sample overflowed — the answer is the finite observed max,
    # not +Inf, for ANY quantile.
    assert reg.quantile(names.RECONCILE_DURATION_SECONDS, 0.5,
                        controller="c") == top + 7.5
    assert reg.quantile(names.RECONCILE_DURATION_SECONDS, 0.99,
                        controller="c") == top + 7.5
    # Mixed: a mid-bucket quantile still reports the bucket upper bound.
    reg2 = Registry(strict=False)
    for v in (0.002, 0.002, 0.002, top + 3.0):
        reg2.observe(names.RECONCILE_DURATION_SECONDS, v, controller="c")
    assert reg2.quantile(names.RECONCILE_DURATION_SECONDS, 0.5,
                         controller="c") == 0.0025
    assert reg2.quantile(names.RECONCILE_DURATION_SECONDS, 0.99,
                         controller="c") == top + 3.0


def test_histogram_exemplars_keep_slowest_per_bucket(reg):
    m = names.SERVING_REQUEST_DURATION_SECONDS
    reg.observe(m, 0.002, exemplar="trace-fast")
    reg.observe(m, 0.0021, exemplar="trace-faster")   # same bucket, slower
    reg.observe(m, 0.0015, exemplar="trace-loser")    # same bucket, faster
    reg.observe(m, 99.0, exemplar="trace-overflow")   # +Inf bucket
    reg.observe(m, 0.3)                               # untraced: no exemplar
    ex = reg.exemplars(m)
    assert ex["0.0025"] == {"value": 0.0021, "trace_id": "trace-faster"}
    assert ex["+Inf"] == {"value": 99.0, "trace_id": "trace-overflow"}
    assert "0.5" not in ex
    flat = reg.exemplars_snapshot()
    assert {e["trace_id"] for e in flat} == {"trace-faster",
                                            "trace-overflow"}
    assert all(e["metric"] == m for e in flat)
    # render(exemplars=True) appends OpenMetrics-style exemplar suffixes;
    # the default render stays plain for strict text-format scrapers.
    plain = reg.render()
    assert "trace-faster" not in plain
    rich = reg.render(exemplars=True)
    assert '# {trace_id="trace-faster"} 0.0021' in rich


def test_remove_series_label_scoped(reg):
    """Gauge staleness (backend eviction): remove_series drops exactly
    the series whose labels include the selector — across counters,
    gauges, and histograms — and the exposition forgets them."""
    reg.set_gauge(names.ROUTER_BACKEND_OUTSTANDING, 3.0, backend="h:1")
    reg.set_gauge(names.ROUTER_BACKEND_OUTSTANDING, 1.0, backend="h:2")
    reg.set_gauge(names.ROUTER_BACKEND_DRAINING, 1.0, backend="h:1")
    reg.inc(names.SERVING_SHED_TOTAL, 2, backend="h:1")
    reg.observe(names.SERVING_QUEUE_DEPTH, 5.0, backend="h:1")
    assert reg.remove_series(names.ROUTER_BACKEND_OUTSTANDING,
                             backend="h:1") == 1
    assert reg.gauge(names.ROUTER_BACKEND_OUTSTANDING, backend="h:1") is None
    # The sibling series with other labels survives.
    assert reg.gauge(names.ROUTER_BACKEND_OUTSTANDING, backend="h:2") == 1.0
    # Name is part of the selector: other families with the same label
    # are untouched until removed themselves.
    assert reg.counter(names.SERVING_SHED_TOTAL, backend="h:1") == 2
    assert reg.remove_series(names.SERVING_SHED_TOTAL, backend="h:1") == 1
    assert reg.remove_series(names.SERVING_QUEUE_DEPTH, backend="h:1") == 1
    assert reg.remove_series(names.ROUTER_BACKEND_DRAINING, backend="h:1") == 1
    text = reg.render()
    assert names.SERVING_QUEUE_DEPTH not in text
    assert 'backend="h:1"' not in text
    assert 'backend="h:2"' in text
    # Removing an absent series is a no-op, not an error.
    assert reg.remove_series(names.SERVING_SHED_TOTAL, backend="h:1") == 0


def test_remove_series_whole_family(reg):
    reg.set_gauge(names.ROUTER_BACKEND_DRAINING, 1.0, backend="h:1")
    reg.set_gauge(names.ROUTER_BACKEND_DRAINING, 0.0, backend="h:2")
    assert reg.remove_series(names.ROUTER_BACKEND_DRAINING) == 2
    assert names.ROUTER_BACKEND_DRAINING not in reg.render()


def test_snapshot_values_shapes(reg):
    reg.inc(names.SERVING_SHED_TOTAL, 3)
    reg.set_gauge(names.SERVING_DRAINING, 1.0)
    reg.observe(names.SERVING_QUEUE_DEPTH, 2.0)
    reg.observe(names.SERVING_QUEUE_DEPTH, 4.0)
    counters, gauges, hists = reg.snapshot_values()
    assert counters[(names.SERVING_SHED_TOTAL, ())] == 3
    assert gauges[(names.SERVING_DRAINING, ())] == 1.0
    assert hists[(names.SERVING_QUEUE_DEPTH, ())] == (6.0, 2)
    # Copies, not views: later registry writes don't mutate the snapshot.
    reg.inc(names.SERVING_SHED_TOTAL, 1)
    assert counters[(names.SERVING_SHED_TOTAL, ())] == 3


def test_profiler_folded_stacks_full_depth():
    from rbg_tpu.obs.profiler import sample_profile

    stop = threading.Event()

    def outer_frame_anchor():
        def inner_frame_anchor():
            stop.wait(5.0)
        inner_frame_anchor()

    t = threading.Thread(target=outer_frame_anchor, daemon=True)
    t.start()
    try:
        prof = sample_profile(seconds=0.3, interval=0.01)
    finally:
        stop.set()
        t.join(timeout=5.0)
    assert prof["samples"] > 0 and prof["folded"]
    anchored = [f for f in prof["folded"] if "inner_frame_anchor" in f]
    assert anchored, prof["folded"][:5]
    stack, count = anchored[0].rsplit(" ", 1)
    assert int(count) >= 1
    frames = stack.split(";")
    # FULL caller chain, oldest-first — the leaf-only top table can't
    # show that outer_frame_anchor owns this leaf.
    ii = next(i for i, fr in enumerate(frames)
              if "inner_frame_anchor" in fr)
    oi = next(i for i, fr in enumerate(frames)
              if "outer_frame_anchor" in fr)
    assert oi < ii
    # Leaf table still present and leaf-only (no joined stacks).
    assert prof["top"] and all(";" not in t["site"] for t in prof["top"])
