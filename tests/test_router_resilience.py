"""Router resilience: health-checked backend pool, least-outstanding
selection, failover retries, and the SIGKILL-mid-stream e2e.

Reference analog: the deployed sglang-router role
(``examples/inference/pd-disagg-leader-worker.yaml``) is cache-aware and
fault-tolerant; a dead backend must not surface as a client error while a
sibling lives."""

import json
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time

import pytest

from rbg_tpu.engine.protocol import recv_msg, request_once, send_msg
from rbg_tpu.engine.router import (BackendPool, Handler, Registry,
                                   RouterServer, RouterState)


# ---- fake backends --------------------------------------------------------


class _EchoBackend(socketserver.ThreadingTCPServer):
    """Minimal engine stand-in: answers health / generate / embed; records
    the requests it saw."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, reply=None):
        self.seen = []
        self.reply = reply or {}

        backend = self

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        obj, _, _ = recv_msg(self.request)
                    except (ConnectionError, json.JSONDecodeError):
                        return
                    if obj is None:
                        return
                    backend.seen.append(obj)
                    if obj.get("op") == "health":
                        send_msg(self.request, {"ok": True})
                        continue
                    resp = {"tokens": [1, 2, 3]}
                    resp.update(backend.reply)
                    send_msg(self.request, resp)

        super().__init__(("127.0.0.1", 0), H)
        self.addr = f"127.0.0.1:{self.server_address[1]}"
        threading.Thread(target=self.serve_forever, daemon=True).start()

    def stop(self):
        self.shutdown()
        self.server_close()


def _dead_addr():
    """An address nothing listens on."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return f"127.0.0.1:{s.getsockname()[1]}"


def _wait_for(cond, timeout=5.0):
    """The done frame reaches the client a hair before the router handler
    thread finishes its bookkeeping (release/ok/metrics) — poll."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    assert cond()


# ---- BackendPool unit ------------------------------------------------------


def test_pool_least_outstanding_order():
    p = BackendPool()
    a, b, c = "h:1", "h:2", "h:3"
    p.acquire(a)
    p.acquire(a)
    p.acquire(b)
    assert p.order([a, b, c])[0] == c          # zero outstanding wins
    p.acquire(c)
    p.acquire(c)
    p.acquire(c)
    assert p.order([a, b, c])[0] == b          # now b has the fewest
    p.release(a)
    p.release(a)
    assert p.order([a, b, c])[0] == a


def test_pool_eviction_and_backoff():
    p = BackendPool()
    a, b = "h:1", "h:2"
    p.fail(a)
    assert p.order([a, b]) == [b, a]           # evicted sorts last
    assert p.evicted() == [a]
    p.ok(a)
    assert p.evicted() == []
    # Exponential backoff grows with consecutive fails, capped.
    for _ in range(10):
        p.fail(b)
    snap = p.snapshot()[b]
    assert snap["fails"] == 10
    assert snap["down_for_s"] <= BackendPool.EVICT_MAX_S + 0.1


def test_pool_all_evicted_still_returns_candidates():
    p = BackendPool()
    a, b = "h:1", "h:2"
    p.fail(a)
    time.sleep(0.01)
    p.fail(b)
    order = p.order([a, b])
    assert order[0] == a                       # soonest recovery first
    assert set(order) == {a, b}


def test_pool_probe_readmits_live_backend():
    be = _EchoBackend()
    p = BackendPool()
    dead = _dead_addr()
    p.fail(be.addr)
    p.fail(dead)
    try:
        readmitted = p.probe(timeout=1.0)
        assert readmitted == [be.addr]
        assert p.evicted() == [dead]
    finally:
        be.stop()


# ---- RouterState.call failover --------------------------------------------


def test_call_fails_over_to_sibling_and_evicts():
    be = _EchoBackend()
    dead = _dead_addr()
    st = RouterState(Registry(None), None,
                     {"worker": [dead, be.addr]})
    # Force the dead backend to be tried first (fresh pool: registry order).
    try:
        addr, resp, _, _ = st.call("worker", {"op": "generate", "prompt": [1]})
        assert addr == be.addr
        assert resp["tokens"] == [1, 2, 3]
        assert st.metrics["retries"] == 1 and st.metrics["failovers"] == 1
        assert dead in st.pool.evicted()
        # Next call skips the evicted backend without a retry.
        st.call("worker", {"op": "generate", "prompt": [1]})
        assert st.metrics["retries"] == 1
    finally:
        be.stop()


def test_call_app_error_passes_through_without_eviction():
    be = _EchoBackend(reply={"error": "bad params"})
    st = RouterState(Registry(None), None, {"worker": [be.addr]})
    try:
        _, resp, _, _ = st.call("worker", {"op": "generate", "prompt": [1]})
        assert resp["error"] == "bad params"
        assert st.pool.evicted() == []         # engine answered: healthy
    finally:
        be.stop()


def test_call_all_backends_dead_raises():
    st = RouterState(Registry(None), None,
                     {"worker": [_dead_addr(), _dead_addr()]})
    with pytest.raises(RuntimeError, match="all worker backends failed"):
        st.call("worker", {"op": "generate", "prompt": [1]})


# ---- rolling preemption: EVERY backend of the role draining at once --------


def _draining_reply(retry_after_s):
    from rbg_tpu.engine.protocol import CODE_DRAINING
    return {"error": "server is draining", "code": CODE_DRAINING,
            "retry_after_s": retry_after_s, "done": True}


def test_call_every_backend_draining_returns_min_retry_after():
    """Rolling preemption drains a whole role at once. The client must
    get the structured retriable error carrying the SMALLEST
    retry_after_s of the fleet — not an eviction storm, not a generic
    'all backends failed'."""
    from rbg_tpu.engine.router import _Rejected

    slow = _EchoBackend(reply=_draining_reply(3.0))
    soon = _EchoBackend(reply=_draining_reply(1.5))
    st = RouterState(Registry(None), None,
                     {"worker": [slow.addr, soon.addr]})
    try:
        with pytest.raises(_Rejected) as exc:
            st.call("worker", {"op": "generate", "prompt": [1]})
        assert exc.value.frame["code"] == "draining"
        assert exc.value.frame["retry_after_s"] == 1.5
        # Draining is a healthy answer: nobody gets evicted, both are
        # marked draining, and the shed is accounted.
        assert st.pool.evicted() == []
        assert set(st.pool.draining()) == {slow.addr, soon.addr}
        assert st.metrics["draining_routed_around"] == 2
        assert st.metrics["sheds_returned"] == 1
    finally:
        slow.stop()
        soon.stop()


def test_stream_every_backend_draining_structured_frame_no_hang():
    """The streaming path under a fleet-wide drain: one structured done
    frame (smallest retry_after_s), delivered promptly — never a hang,
    never a half-open stream."""
    a = _EchoBackend(reply=_draining_reply(4.0))
    b = _EchoBackend(reply=_draining_reply(2.0))
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None,
                               {"worker": [a.addr, b.addr]})
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        port = router.server_address[1]
        t0 = time.monotonic()
        with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
            send_msg(s, {"op": "generate", "prompt": [1], "stream": True,
                         "timeout_s": 30})
            frame, _, _ = recv_msg(s)
        assert frame is not None and frame.get("done")
        assert frame.get("code") == "draining"
        assert frame.get("retry_after_s") == 2.0
        assert time.monotonic() - t0 < 10.0   # structured, not a timeout
    finally:
        router.shutdown()
        router.server_close()
        a.stop()
        b.stop()


def test_pin_seed_only_for_unseeded_sampling():
    pin = Handler._pin_seed
    assert "seed" not in pin({"temperature": 0.0})
    assert "seed" not in pin({})
    assert pin({"temperature": 0.7, "seed": 42})["seed"] == 42
    pinned = pin({"temperature": 0.7})
    assert isinstance(pinned["seed"], int)


# ---- in-process streaming failover ----------------------------------------


class _StreamBackend(socketserver.ThreadingTCPServer):
    """Streams tokens 0..n-1 one per frame; optionally dies after
    ``die_after`` frames — a clean FIN by default, a hard RST (SIGKILL-
    shaped: the router's recv raises ConnectionResetError instead of
    seeing a close) with ``rst=True``, optionally mid-frame with
    ``partial=True``."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, n=10, die_after=None, rst=False, partial=False):
        backend = self

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                obj, _, _ = recv_msg(self.request)
                if obj is None or obj.get("op") == "health":
                    if obj:
                        send_msg(self.request, {"ok": True})
                    return
                for i in range(n):
                    if backend.die_after is not None and i >= backend.die_after:
                        if backend.partial:
                            self.request.sendall(b'{"tokens": [99')
                        if backend.rst:
                            self.request.setsockopt(
                                socket.SOL_SOCKET, socket.SO_LINGER,
                                __import__("struct").pack("ii", 1, 0))
                        return                  # abrupt close, no done
                    send_msg(self.request, {"tokens": [i], "done": False})
                    time.sleep(0.01)
                send_msg(self.request, {"tokens": [], "done": True,
                                        "ttft_s": 0.0})

        self.die_after = die_after
        self.rst = rst
        self.partial = partial
        super().__init__(("127.0.0.1", 0), H)
        self.addr = f"127.0.0.1:{self.server_address[1]}"
        threading.Thread(target=self.serve_forever, daemon=True).start()

    def stop(self):
        self.shutdown()
        self.server_close()


def test_stream_failover_resumes_without_duplicates():
    """Backend A dies after 4 frames; the router replays on B and the
    client sees exactly tokens 0..9 once each, then done — no error."""
    a = _StreamBackend(n=10, die_after=4)
    b = _StreamBackend(n=10)
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None,
                               {"worker": [a.addr, b.addr]})
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        port = router.server_address[1]
        with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
            send_msg(s, {"op": "generate", "prompt": [1], "stream": True,
                         "max_new_tokens": 10})
            tokens, done = [], False
            while not done:
                frame, _, _ = recv_msg(s)
                assert frame is not None, "router closed mid-stream"
                assert "error" not in frame, frame
                tokens.extend(frame.get("tokens") or [])
                done = frame.get("done", False)
        assert tokens == list(range(10))
        _wait_for(lambda: router.state.metrics["failovers"] == 1)
        assert a.addr in router.state.pool.evicted()
    finally:
        router.shutdown()
        router.server_close()
        a.stop()
        b.stop()


@pytest.mark.parametrize("kill", ["rst", "partial"])
def test_stream_failover_dirty_close_no_duplicates(kill):
    """An abrupt RST (or a death mid-frame, leaving a partial header) must
    not lose the delivered-token count — the replay on the sibling still
    skips exactly the delivered prefix."""
    a = _StreamBackend(n=10, die_after=4, rst=(kill == "rst"),
                       partial=(kill == "partial"))
    b = _StreamBackend(n=10)
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None,
                               {"worker": [a.addr, b.addr]})
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        port = router.server_address[1]
        with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
            send_msg(s, {"op": "generate", "prompt": [1], "stream": True})
            tokens, done = [], False
            while not done:
                frame, _, _ = recv_msg(s)
                assert frame is not None, "router closed mid-stream"
                assert "error" not in frame, frame
                tokens.extend(frame.get("tokens") or [])
                done = frame.get("done", False)
        assert tokens == list(range(10)), tokens
        _wait_for(lambda: router.state.metrics["failovers"] == 1)
    finally:
        router.shutdown()
        router.server_close()
        a.stop()
        b.stop()


def test_client_disconnect_not_charged_to_backend():
    """A client that hangs up mid-stream must not evict the healthy
    backend or trigger sibling replays."""
    a = _StreamBackend(n=200)
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None, {"worker": [a.addr]})
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        port = router.server_address[1]
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        send_msg(s, {"op": "generate", "prompt": [1], "stream": True})
        frame, _, _ = recv_msg(s)
        assert "error" not in frame
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     __import__("struct").pack("ii", 1, 0))
        s.close()                              # RST mid-stream
        _wait_for(lambda: router.state.pool.snapshot()[a.addr]["outstanding"] == 0)
        snap = router.state.pool.snapshot()[a.addr]
        assert snap["fails"] == 0 and snap["down_for_s"] == 0.0
        assert router.state.metrics["retries"] == 0
        assert router.state.pool.evicted() == []
    finally:
        router.shutdown()
        router.server_close()
        a.stop()


def test_pool_prunes_departed_registry_addrs(tmp_path):
    """Addresses that leave the registry are dropped from pool state so a
    long-lived router doesn't accumulate dead pods in its health payload."""
    reg_path = tmp_path / "registry.json"
    reg_path.write_text(json.dumps({
        "pod-a": {"addr": "127.0.0.1:1001", "role": "worker"},
        "pod-b": {"addr": "127.0.0.1:1002", "role": "worker"},
    }))
    st = RouterState(Registry(str(reg_path)), None)
    st.candidates("worker")
    assert set(st.pool.snapshot()) == {"127.0.0.1:1001", "127.0.0.1:1002"}
    time.sleep(0.01)  # distinct mtime
    reg_path.write_text(json.dumps({
        "pod-c": {"addr": "127.0.0.1:1003", "role": "worker"},
    }))
    st.candidates("worker")
    assert set(st.pool.snapshot()) == {"127.0.0.1:1003"}


def test_call_garbage_frame_fails_over():
    """A backend emitting a non-JSON frame is a transport-class failure:
    fail over to the sibling and evict, same as probe() classifies it."""

    class _Garbage(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def __init__(self):
            class H(socketserver.BaseRequestHandler):
                def handle(self):
                    recv_msg(self.request)
                    self.request.sendall(b"not json at all\n")

            super().__init__(("127.0.0.1", 0), H)
            self.addr = f"127.0.0.1:{self.server_address[1]}"
            threading.Thread(target=self.serve_forever, daemon=True).start()

    bad = _Garbage()
    good = _EchoBackend()
    st = RouterState(Registry(None), None,
                     {"worker": [bad.addr, good.addr]})
    try:
        addr, resp, _, _ = st.call("worker", {"op": "generate", "prompt": [1]})
        assert addr == good.addr and resp["tokens"] == [1, 2, 3]
        assert bad.addr in st.pool.evicted()
    finally:
        bad.shutdown()
        bad.server_close()
        good.stop()


def test_blocking_client_disconnect_not_a_router_error():
    """A client that closes before its blocking reply lands is a routine
    disconnect: no error metric, no backend eviction."""
    be = _EchoBackend()
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None, {"worker": [be.addr]})
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        port = router.server_address[1]
        s = socket.create_connection(("127.0.0.1", port), timeout=30)
        send_msg(s, {"op": "generate", "prompt": [1]})
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     __import__("struct").pack("ii", 1, 0))
        s.close()                              # gone before the reply
        _wait_for(lambda: len(be.seen) >= 1)   # backend did serve it
        time.sleep(0.1)                        # let the reply-send fail
        assert router.state.metrics["errors"] == 0
        assert router.state.pool.evicted() == []
    finally:
        router.shutdown()
        router.server_close()
        be.stop()


def test_stream_all_dead_surfaces_error_frame():
    a = _StreamBackend(n=10, die_after=2)
    b = _StreamBackend(n=10, die_after=0)
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None,
                               {"worker": [a.addr, b.addr]})
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        port = router.server_address[1]
        with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
            send_msg(s, {"op": "generate", "prompt": [1], "stream": True})
            frames = []
            while True:
                frame, _, _ = recv_msg(s)
                assert frame is not None
                frames.append(frame)
                if frame.get("done") or "error" in frame:
                    break
        assert "error" in frames[-1]
    finally:
        router.shutdown()
        router.server_close()
        a.stop()
        b.stop()


# ---- e2e: SIGKILL a decode replica mid-stream -----------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_engine_ready(port, timeout=180.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            h, _, _ = request_once(f"127.0.0.1:{port}", {"op": "health"},
                                   timeout=5)
            if h and h.get("ok"):
                return
        except OSError:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"engine on {port} never ready")


@pytest.mark.e2e
@pytest.mark.slow
def test_sigkill_decode_mid_stream_client_completes():
    """The VERDICT-mandated drill: PD group with TWO decode replicas; the
    active one is SIGKILLed mid-stream; the client still receives the
    complete, correct token stream (greedy => bit-identical replay) with
    no error frame."""
    from rbg_tpu.utils import scrubbed_cpu_env

    env = scrubbed_cpu_env()
    pf, d1, d2, rp = (_free_port() for _ in range(4))
    engine_args = ["--model", "tiny", "--page-size", "8",
                   "--num-pages", "128", "--max-seq-len", "512",
                   "--prefill-chunk", "16", "--use-pallas", "never"]
    procs = {}
    try:
        procs["prefill"] = subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.server",
             "--mode", "prefill", "--port", str(pf)] + engine_args, env=env)
        for name, port in (("decode1", d1), ("decode2", d2)):
            procs[name] = subprocess.Popen(
                [sys.executable, "-m", "rbg_tpu.engine.server",
                 "--mode", "decode", "--port", str(port)] + engine_args,
                env=env)
        backends = {"prefill": [f"127.0.0.1:{pf}"],
                    "decode": [f"127.0.0.1:{d1}", f"127.0.0.1:{d2}"]}
        procs["router"] = subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.router",
             "--port", str(rp), "--backends", json.dumps(backends)], env=env)
        for port in (pf, d1, d2):
            _wait_engine_ready(port)
        _wait_engine_ready(rp)

        prompt = [7, 3, 5, 11, 2, 9] * 4
        req = {"op": "generate", "prompt": prompt, "stream": True,
               "max_new_tokens": 160}

        # Reference run (no failure) for the expected stream.
        ref, _, _ = request_once(
            f"127.0.0.1:{rp}", {**req, "stream": False}, timeout=120)
        assert "error" not in ref, ref
        expect = ref["tokens"]
        assert len(expect) == 160  # first (prefill-sampled) token + decode

        with socket.create_connection(("127.0.0.1", rp), timeout=120) as s:
            send_msg(s, req)
            tokens, done, killed = [], False, False
            while not done:
                frame, _, _ = recv_msg(s)
                assert frame is not None, "router closed mid-stream"
                assert "error" not in frame, frame
                tokens.extend(frame.get("tokens") or [])
                done = frame.get("done", False)
                if not killed and len(tokens) >= 8:
                    # Find the decode replica actually serving the stream
                    # (outstanding=1 in the router's pool) and SIGKILL it.
                    h, _, _ = request_once(f"127.0.0.1:{rp}",
                                           {"op": "health"}, timeout=5)
                    busy = [ad for ad, st in h["backends"].items()
                            if ad in backends["decode"][0] + backends["decode"][1]
                            and st["outstanding"] > 0]
                    assert busy, h["backends"]
                    victim = "decode1" if busy[0].endswith(str(d1)) else "decode2"
                    procs[victim].send_signal(signal.SIGKILL)
                    killed = True
        assert killed, "stream finished before the kill could happen"
        assert tokens == expect, (
            f"client stream diverged after failover: got {len(tokens)} "
            f"tokens, expected {len(expect)}")

        def failover_counted():
            h, _, _ = request_once(f"127.0.0.1:{rp}", {"op": "health"},
                                   timeout=5)
            assert h["metrics"]["errors"] == 0
            return h["metrics"]["failovers"] >= 1
        _wait_for(failover_counted)
    finally:
        for p in procs.values():
            p.terminate()
        for p in procs.values():
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


# ---- cache-aware routing (sglang-router property) -------------------------


def test_affinity_routes_same_prefix_to_same_backend():
    """Same-prefix requests stick to one backend (warm radix cache);
    different prefixes spread by least-outstanding."""
    from rbg_tpu.engine.router import RouterState as RS

    a, b = _EchoBackend(), _EchoBackend()
    st = RS(__import__("rbg_tpu.engine.router", fromlist=["Registry"])
            .Registry(None), None, {"worker": [a.addr, b.addr]})
    try:
        p1 = list(range(40))
        p2 = list(range(100, 140))
        first, _, _, _ = st.call("worker", {"op": "generate", "prompt": p1},
                                 prompt=p1)
        for _ in range(4):
            addr, _, _, _ = st.call("worker",
                                    {"op": "generate", "prompt": p1},
                                    prompt=p1)
            assert addr == first                   # sticky
        assert st.metrics["affinity_hits"] >= 4
        # A NEW prefix must land on the colder replica: last_pick is
        # charged to the address actually served (acquire), so the hot
        # affinity replica loses the least-recently-picked tie-break.
        where, _, _, _ = st.call("worker", {"op": "generate", "prompt": p2},
                                 prompt=p2)
        assert where != first
        again, _, _, _ = st.call("worker", {"op": "generate", "prompt": p2},
                                 prompt=p2)
        assert again == where                  # and sticks there
    finally:
        a.stop()
        b.stop()


def test_affinity_yields_to_load_imbalance_and_eviction():
    from rbg_tpu.engine.router import Registry, RouterState

    a, b = _EchoBackend(), _EchoBackend()
    st = RouterState(Registry(None), None, {"worker": [a.addr, b.addr]})
    try:
        p = list(range(40))
        pinned, _, _, _ = st.call("worker", {"op": "generate", "prompt": p},
                                  prompt=p)
        other = b.addr if pinned == a.addr else a.addr
        # Overload the pinned backend past the slack: affinity must yield.
        for _ in range(6):
            st.pool.acquire(pinned)
        cands = st.candidates_for("worker", p)
        assert cands[0] == other
        for _ in range(6):
            st.pool.release(pinned)
        # Evicted affinity target must also yield.
        st.pool.fail(pinned)
        cands = st.candidates_for("worker", p)
        assert cands[0] == other
    finally:
        a.stop()
        b.stop()
