"""Prefill/decode disaggregation: KV handoff preserves exact numerics."""

import jax
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.pd import PDPair, PrefillWorker
from rbg_tpu.models import get_config, init_params


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def ecfg(**kw):
    base = dict(model="tiny", page_size=8, num_pages=64, max_batch=4,
                max_seq_len=128, prefill_chunk=16, use_pallas="never")
    base.update(kw)
    return EngineConfig(**base)


@pytest.mark.slow
def test_pd_matches_unified(tiny_setup):
    """Disaggregated output must be token-identical to a unified engine."""
    cfg, params = tiny_setup
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist() for n in (9, 25, 14)]

    unified = Engine(ecfg(enable_radix_cache=False), params=params)
    expect = unified.generate(prompts, SamplingParams(max_new_tokens=8))

    pair = PDPair(ecfg(), params=params)
    got, ttft = pair.generate(prompts, SamplingParams(max_new_tokens=8),
                              collect_ttft=True)
    assert got == expect
    assert len(ttft) == 3 and all(t > 0 for t in ttft)
    assert pair.prefill.metrics["bundles"] == 3
    assert pair.decode.metrics["bytes_in"] == pair.prefill.metrics["bytes_out"] > 0


def test_pd_single_token_and_stop(tiny_setup):
    cfg, params = tiny_setup
    prompt = [2, 4, 6, 8]
    unified = Engine(ecfg(enable_radix_cache=False), params=params)
    expect = unified.generate([prompt], SamplingParams(max_new_tokens=1))[0]

    pair = PDPair(ecfg(), params=params)
    got = pair.generate([prompt], SamplingParams(max_new_tokens=1))[0]
    assert got == expect
    # pages fully recycled on both sides
    assert pair.decode.engine.allocator.free_pages == 63
    assert pair.prefill.engine.allocator.free_pages == 63


def test_prefill_worker_bundle_shape(tiny_setup):
    cfg, params = tiny_setup
    w = PrefillWorker(ecfg(), params=params)
    bundle = w.prefill(list(range(1, 20)))  # 19 tokens → 3 pages of 8
    assert bundle.k_data.shape == (cfg.num_layers, 3, 8, cfg.num_kv_heads,
                                   cfg.head_dim_)
    assert bundle.nbytes > 0
    assert w.engine.allocator.free_pages == 63  # released after export
