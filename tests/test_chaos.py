"""Chaos: random pod failures under churn must always reconverge.

Reference analog: the e2e stability suites
(``restart_policy_stability`` 666 LoC, ``inactive_pod`` 588 LoC — SURVEY.md
§4) which kill pods repeatedly and assert convergence.
"""

import random
import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RestartPolicyConfig
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import (
    make_group, make_tpu_nodes, simple_role, tpu_leaderworker_role,
)


def test_random_pod_failures_reconverge():
    rng = random.Random(42)
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=4, hosts_per_slice=2)
    with plane:
        for i in range(3):
            role = simple_role("web", replicas=2)
            role.restart_policy = RestartPolicyConfig(base_delay_seconds=0.01,
                                                      max_delay_seconds=0.1)
            tpu_role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
            tpu_role.restart_policy = RestartPolicyConfig(base_delay_seconds=0.01,
                                                          max_delay_seconds=0.1)
            plane.apply(make_group(f"g{i}", role, tpu_role))
        for i in range(3):
            plane.wait_group_ready(f"g{i}", timeout=30)

        # chaos: kill/evict random pods for a while (evictions exercise the
        # inactive-pod handling path, keps/inactive-pod-handling)
        end = time.monotonic() + 3.0
        kills = evictions = 0
        while time.monotonic() < end:
            pods = [p for p in plane.store.list("Pod", namespace="default")
                    if p.active and p.status.phase == "Running"]
            if pods:
                victim = rng.choice(pods)
                if rng.random() < 0.4:
                    plane.kubelet.evict_pod("default", victim.metadata.name)
                    evictions += 1
                else:
                    plane.kubelet.fail_pod("default", victim.metadata.name)
                kills += 1
            time.sleep(0.15)
        assert kills >= 10 and evictions >= 1

        # everything reconverges
        for i in range(3):
            plane.wait_group_ready(f"g{i}", timeout=60)

        # Group-Ready can race the LAST replacement pod's binding (the
        # instance already counts ready while the spare is still
        # Pending): wait until every active pod is actually scheduled, or
        # the slice-invariant check below dereferences node_name == "".
        def all_bound():
            ps = [p for p in plane.store.list("Pod", namespace="default")
                  if p.active]
            return all(p.node_name and p.status.phase == "Running"
                       for p in ps)
        plane.wait_for(all_bound, timeout=60, desc="all active pods bound")

        # invariants after the storm
        nodes = {n.metadata.name: n for n in plane.store.list("Node")}
        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        by_inst = {}
        for p in pods:
            if p.template.scheduler_hints.get("tpu-slice") == "true":
                by_inst.setdefault(p.metadata.labels[C.LABEL_INSTANCE_NAME], []).append(p)
        for inst, ps in by_inst.items():
            slices = {nodes[p.node_name].tpu.slice_id for p in ps}
            assert len(slices) == 1, f"{inst} split across slices after chaos"
            assert len({p.node_name for p in ps}) == len(ps)
        # restart counters recorded
        total_restarts = sum(i.status.restart_count
                             for i in plane.store.list("RoleInstance", namespace="default"))
        assert total_restarts >= 1
        # no inactive (Failed) pod survived the storm un-replaced
        assert not [p for p in plane.store.list("Pod", namespace="default")
                    if p.status.phase == "Failed"
                    and p.metadata.deletion_timestamp is None]
