"""Schema-evolution mechanisms (docs/architecture.md §5; reference analogs:
the v1alpha1 conversion webhook and the tools/crd-upgrade job).

Covers: manifest apiVersion conversion chains at admission, snapshot schema
migrations, and the offline migrate-state CLI.
"""

import json

import pytest

import rbg_tpu.api as api
from rbg_tpu.runtime.store import Store
from rbg_tpu.testutil import make_group, simple_role


def test_current_version_and_absent_version_parse():
    doc = {"kind": "RoleBasedGroup", "metadata": {"name": "g"}}
    assert api.parse_manifest(doc).metadata.name == "g"
    doc["apiVersion"] = api.API_VERSION
    assert api.parse_manifest(doc).metadata.name == "g"


def test_unknown_api_version_rejected():
    with pytest.raises(KeyError, match="unsupported apiVersion"):
        api.parse_manifest({"apiVersion": f"{api.API_GROUP}/v9",
                            "kind": "RoleBasedGroup",
                            "metadata": {"name": "g"}})


def test_conversion_chain_runs_to_current(monkeypatch):
    """A legacy manifest (renamed field, older apiVersion) converts forward
    through the registered chain before strict parsing."""
    v0 = f"{api.API_GROUP}/v0"

    def convert_v0(doc):
        doc = dict(doc)
        spec = dict(doc.get("spec") or {})
        if "groupRoles" in spec:           # v0 spelling of spec.roles
            spec["roles"] = spec.pop("groupRoles")
        doc["spec"] = spec
        doc["apiVersion"] = api.API_VERSION
        return doc

    monkeypatch.setitem(api.MANIFEST_CONVERSIONS, v0, convert_v0)
    obj = api.parse_manifest({
        "apiVersion": v0,
        "kind": "RoleBasedGroup",
        "metadata": {"name": "legacy"},
        "spec": {"groupRoles": [{"name": "srv", "replicas": 2}]},
    })
    assert obj.spec.roles[0].name == "srv"
    assert obj.spec.roles[0].replicas == 2
    # Without the conversion, the old spelling is a strict-parse error —
    # the admission seam stays strict.
    with pytest.raises(Exception):
        api.parse_manifest({
            "kind": "RoleBasedGroup", "metadata": {"name": "x"},
            "spec": {"groupRoles": []},
        })


def test_conversion_cycle_detected(monkeypatch):
    v0 = f"{api.API_GROUP}/v0"
    monkeypatch.setitem(api.MANIFEST_CONVERSIONS, v0, lambda d: dict(d))
    with pytest.raises(KeyError):
        api.parse_manifest({"apiVersion": v0, "kind": "RoleBasedGroup",
                            "metadata": {"name": "g"}})


def test_snapshot_migration_chain(monkeypatch):
    """A schema-0 snapshot migrates forward on load; a newer-schema file is
    an explicit error (never a silent misparse)."""
    src = Store()
    src.create(make_group("mig", simple_role("srv")))
    snap = src.snapshot()

    old = dict(snap, schema=0)

    def migrate_0_to_1(data):
        data = dict(data, schema=1)
        return data

    monkeypatch.setitem(Store._SNAPSHOT_MIGRATIONS, 0, migrate_0_to_1)
    dst = Store()
    assert dst.load_snapshot(old) == 1
    assert dst.get("RoleBasedGroup", "default", "mig") is not None

    with pytest.raises(ValueError, match="newer"):
        Store().load_snapshot(dict(snap, schema=Store.SNAPSHOT_SCHEMA + 1))
    with pytest.raises(ValueError, match="no migration"):
        Store().load_snapshot(dict(snap, schema=-1))


def test_migrate_state_cli(tmp_path, monkeypatch):
    from rbg_tpu.cli.controlplane import cmd_migrate_state

    src = Store()
    src.create(make_group("cli", simple_role("srv", replicas=3)))
    old = dict(src.snapshot(), schema=0)
    monkeypatch.setitem(Store._SNAPSHOT_MIGRATIONS, 0, lambda d: dict(d, schema=1))
    infile = tmp_path / "old.json"
    outfile = tmp_path / "new.json"
    infile.write_text(json.dumps(old))

    class Args:
        pass
    a = Args(); a.infile = str(infile); a.outfile = str(outfile)
    assert cmd_migrate_state(a) == 0

    migrated = json.loads(outfile.read_text())
    assert migrated["schema"] == Store.SNAPSHOT_SCHEMA
    dst = Store()
    assert dst.load_snapshot(migrated) == 1
    g = dst.get("RoleBasedGroup", "default", "cli")
    assert g is not None and g.spec.roles[0].replicas == 3


# ---- the REAL shipped migration: v1alpha1 `stateful` -> v1alpha2 `identity`
# (rbg_tpu/api/conversions.py), proven from committed old-format artifacts.

import os

FIXTURES = os.path.join(os.path.dirname(__file__), "fixtures")


def test_shipped_registries_are_non_empty():
    assert f"{api.API_GROUP}/v1alpha1" in api.MANIFEST_CONVERSIONS
    assert 1 in Store._SNAPSHOT_MIGRATIONS
    assert Store.SNAPSHOT_SCHEMA == 2
    assert api.API_VERSION == f"{api.API_GROUP}/v1alpha2"


def test_v1alpha1_manifest_fixture_converts():
    from rbg_tpu.api.serde import load_yaml_docs
    with open(os.path.join(FIXTURES, "manifest_v1alpha1.yaml")) as f:
        (doc,) = load_yaml_docs(f.read())
    g = api.parse_manifest(doc)
    roles = {r.name: r for r in g.spec.roles}
    assert roles["prefill"].identity == "ordinal" and roles["prefill"].stateful
    assert roles["router"].identity == "random" and not roles["router"].stateful
    assert roles["router"].drain_seconds == 2.0  # untouched fields survive

    # The OLD spelling at the CURRENT version stays a strict-parse error —
    # conversion is per-version, not a lenient alias.
    cur = dict(doc, apiVersion=api.API_VERSION)
    with pytest.raises(Exception):
        api.parse_manifest(cur)


def test_schema1_snapshot_fixture_loads_and_preserves_statelessness():
    """Committed schema-1 snapshot (taken by the previous release's shape):
    the migration must keep the router role STATELESS — a lenient parse
    without migration would silently default it to ordinal."""
    with open(os.path.join(FIXTURES, "state_schema1.json")) as f:
        data = json.load(f)
    assert data["schema"] == 1
    store = Store()
    n = store.load_snapshot(data)
    assert n == len(data["objects"])

    g = store.get("RoleBasedGroup", "default", "legacy")
    roles = {r.name: r for r in g.spec.roles}
    assert roles["router"].identity == "random"
    assert roles["server"].identity == "ordinal"

    ris = store.get("RoleInstanceSet", "default", "legacy-router")
    assert ris.spec.identity == "random" and not ris.spec.stateful

    # ControllerRevision payloads converted too (undo to a pre-upgrade
    # revision must re-apply cleanly).
    revs = store.list("ControllerRevision", namespace="default")
    assert revs
    for rev in revs:
        if "roles" in rev.data:
            for r in rev.data["roles"]:
                assert "stateful" not in r
                assert "identity" in r


def test_migrate_state_cli_on_fixture(tmp_path):
    from rbg_tpu.cli.controlplane import cmd_migrate_state

    outfile = tmp_path / "migrated.json"

    class Args:
        pass
    a = Args()
    a.infile = os.path.join(FIXTURES, "state_schema1.json")
    a.outfile = str(outfile)
    assert cmd_migrate_state(a) == 0

    migrated = json.loads(outfile.read_text())
    assert migrated["schema"] == Store.SNAPSHOT_SCHEMA
    assert "stateful" not in json.dumps(migrated)


def test_plane_resumes_from_schema1_fixture():
    """Full resume: boot a live plane from the old-format state file; the
    stateless role must keep random-id instances (no ordinal rename storm)
    and the group must converge."""
    from rbg_tpu.runtime.plane import ControlPlane

    with open(os.path.join(FIXTURES, "state_schema1.json")) as f:
        data = json.load(f)
    store = Store()
    store.load_snapshot(data)
    p = ControlPlane(store=store, backend="fake")
    with p:
        p.wait_group_ready("legacy", timeout=30)
        instances = store.list("RoleInstance", namespace="default",
                               selector={"rbg.tpu.x-k8s.io/group-name": "legacy"})
        router_inst = [i for i in instances
                       if i.metadata.name.startswith("legacy-router-")]
        assert router_inst
        for inst in router_inst:
            suffix = inst.metadata.name.rsplit("-", 1)[-1]
            assert not suffix.isdigit(), "stateless instance got renamed to ordinal"


def test_invalid_identity_value_rejected_at_admission():
    with pytest.raises(ValueError, match="IdentityMode"):
        api.parse_manifest({
            "kind": "RoleBasedGroup", "metadata": {"name": "g"},
            "spec": {"roles": [{"name": "a", "identity": "Random"}]},
        })
