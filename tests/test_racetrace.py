"""racetrace: the runtime half of the guarded-by discipline — guarded
classes get access probes when armed, violations raise (=1) or record
(warn), __init__ is exempt, reads are sampled, disarm restores the class,
and a full plane lifecycle runs race-free."""

import importlib.util
import os
import sys
import threading

import pytest

TOY_SOURCE = '''\
from rbg_tpu.utils.locktrace import named_lock
from rbg_tpu.utils import racetrace


@racetrace.guard
class Box:
    def __init__(self):
        self._lock = named_lock("toy.box")
        self._items = {}  # guarded_by[toy.box]
        self._count = 0  # guarded_by[toy.box]

    def good_put(self, k, v):
        with self._lock:
            self._items[k] = v
            self._count += 1

    def bad_replace(self):
        self._items = {}

    def bad_read(self):
        return len(self._items)
'''


def _load_toy(tmp_path, name="toybox_mod"):
    p = tmp_path / f"{name}.py"
    p.write_text(TOY_SOURCE)
    spec = importlib.util.spec_from_file_location(name, p)
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture()
def racetrace(monkeypatch):
    monkeypatch.delenv("RBG_LOCKTRACE", raising=False)
    monkeypatch.setenv("RBG_RACETRACE", "1")
    monkeypatch.setenv("RBG_RACETRACE_SAMPLE", "1")  # deterministic reads
    from rbg_tpu.utils import racetrace as rt
    rt.disarm()
    yield rt
    rt.disarm()


def test_write_violation_raises_and_lock_held_passes(racetrace, tmp_path):
    mod = _load_toy(tmp_path, "toy_w")
    racetrace.arm()
    b = mod.Box()
    b.good_put("a", 1)  # under the lock: silent
    assert racetrace.violations() == []
    with pytest.raises(racetrace.RaceError) as ei:
        b.bad_replace()
    assert "guarded_by[toy.box]" in str(ei.value)
    assert racetrace.counters()["rbg_race_violations_total"] >= 1


def test_read_probe_fires_and_is_sampled(racetrace, tmp_path, monkeypatch):
    mod = _load_toy(tmp_path, "toy_r")
    racetrace.arm(strict=False)  # warn mode: record, don't raise
    b = mod.Box()
    for _ in range(6):
        b.bad_read()
    v = racetrace.counters()["rbg_race_violations_total"]
    assert v >= 6  # sample=1: every read checked
    assert any("read" in s for s in racetrace.violations())


def test_init_writes_are_exempt(racetrace, tmp_path):
    mod = _load_toy(tmp_path, "toy_i")
    racetrace.arm()
    mod.Box()  # __init__ writes guarded fields with no lock: fine
    assert racetrace.violations() == []


def test_warn_mode_records_without_raising(racetrace, tmp_path, monkeypatch):
    monkeypatch.setenv("RBG_RACETRACE", "warn")
    mod = _load_toy(tmp_path, "toy_warn")
    racetrace.arm()
    b = mod.Box()
    b.bad_replace()  # no raise
    b.bad_replace()
    assert racetrace.counters()["rbg_race_violations_total"] == 2
    assert len(racetrace.violations()) == 2


def test_disarm_restores_the_class(racetrace, tmp_path):
    mod = _load_toy(tmp_path, "toy_d")
    racetrace.arm()
    b = mod.Box()
    with pytest.raises(racetrace.RaceError):
        b.bad_replace()
    racetrace.disarm()
    mod.Box().bad_replace()  # plain class again
    assert racetrace.violations() == []
    assert racetrace.counters()["rbg_race_guarded_classes"] == 0


def test_disarmed_guard_is_zero_overhead(tmp_path, monkeypatch):
    """Without RBG_RACETRACE the decorator must leave the class alone —
    no wrapper dunders, no per-instance flags."""
    monkeypatch.delenv("RBG_RACETRACE", raising=False)
    mod = _load_toy(tmp_path, "toy_z")
    assert "__setattr__" not in mod.Box.__dict__
    assert "__getattribute__" not in mod.Box.__dict__
    b = mod.Box()
    b.bad_replace()
    assert "_rbg_race_live_" not in b.__dict__


def test_cross_thread_violation_attributes_the_thread(racetrace, tmp_path):
    mod = _load_toy(tmp_path, "toy_t")
    racetrace.arm(strict=False)
    b = mod.Box()
    t = threading.Thread(target=b.bad_replace, name="poker", daemon=True)
    t.start()
    t.join(timeout=10)
    assert any("poker" in s for s in racetrace.violations())


def test_held_other_lock_still_violates(racetrace, tmp_path):
    """Holding SOME lock is not holding THE lock: the owning lock is
    matched by name."""
    from rbg_tpu.utils.locktrace import named_lock
    mod = _load_toy(tmp_path, "toy_o")
    racetrace.arm(strict=False)
    b = mod.Box()
    other = named_lock("toy.other")
    with other:
        b.bad_replace()
    assert any("toy.other" in s for s in racetrace.violations())


@pytest.mark.slow
def test_plane_lifecycle_race_free(racetrace, monkeypatch):
    """The annotated production fleet converges a fake-backend plane with
    the detector armed and records ZERO violations — the same integration
    `rbg-tpu stress --racetrace` asserts via the race_free invariant."""
    monkeypatch.setenv("RBG_RACETRACE", "warn")
    from rbg_tpu.runtime.plane import ControlPlane
    from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role
    racetrace.arm()
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=2)
    with plane:
        plane.apply(make_group("rt", simple_role("worker", replicas=2)))
        plane.wait_group_ready("rt", timeout=30)
    assert racetrace.violations() == []
    assert racetrace.counters()["rbg_race_checked_total"] > 0
