"""Stateless instance engine: random ids, delete preferences,
specified-delete (CloneSet semantics — reference statelessmode)."""

import re

from rbg_tpu.api import constants as C
from rbg_tpu.runtime.controllers.instanceset import ANN_SPECIFIED_DELETE
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


def _plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=2)
    return p


def test_stateless_random_ids_and_scale():
    with _plane() as plane:
        role = simple_role("worker", replicas=3)
        role.stateful = False
        plane.apply(make_group("sl", role))
        plane.wait_group_ready("sl", timeout=20)

        insts = plane.store.list("RoleInstance", namespace="default")
        assert len(insts) == 3
        # CloneSet-style names: {set}-{5-char random id}, not ordinals.
        for i in insts:
            assert re.fullmatch(r"sl-worker-[a-z0-9]{5}", i.metadata.name)
            assert C.LABEL_INSTANCE_INDEX not in i.metadata.labels

        g = plane.store.get("RoleBasedGroup", "default", "sl")
        g.spec.roles[0].replicas = 1
        plane.store.update(g)
        plane.wait_for(
            lambda: len([p for p in plane.store.list("Pod", namespace="default")
                         if p.active]) == 1,
            timeout=20, desc="stateless scale down",
        )


def test_specified_delete_annotation():
    with _plane() as plane:
        role = simple_role("worker", replicas=2)
        role.stateful = False
        plane.apply(make_group("sd", role))
        plane.wait_group_ready("sd", timeout=20)

        victim = plane.store.list("RoleInstance", namespace="default")[0]
        name = victim.metadata.name

        def mark(i):
            i.metadata.annotations[ANN_SPECIFIED_DELETE] = "true"
            return True

        plane.store.mutate("RoleInstance", "default", name, mark)

        def replaced():
            insts = plane.store.list("RoleInstance", namespace="default")
            names = {i.metadata.name for i in insts}
            return len(insts) == 2 and name not in names

        plane.wait_for(replaced, timeout=20,
                       desc="specified-delete replaced the instance")
        plane.wait_group_ready("sd", timeout=20)


def test_stateless_paused_freezes_update():
    """paused stops outdated-instance replacement for stateless sets too
    (scale still applies)."""
    import time as _time
    from rbg_tpu.api.group import RollingUpdate

    with _plane() as plane:
        role = simple_role("worker", replicas=2)
        role.stateful = False
        role.rolling_update = RollingUpdate(paused=True,
                                            in_place_if_possible=False)
        plane.apply(make_group("pz", role))
        plane.wait_group_ready("pz", timeout=20)
        uids0 = {i.metadata.uid for i in
                 plane.store.list("RoleInstance", namespace="default")}

        g = plane.store.get("RoleBasedGroup", "default", "pz")
        g.spec.roles[0].template.containers[0].image = "engine:v2"
        plane.store.update(g)
        _time.sleep(0.8)   # several reconcile cycles
        insts = plane.store.list("RoleInstance", namespace="default")
        assert {i.metadata.uid for i in insts} == uids0, \
            "paused stateless rollout replaced instances"

        # unpause → rollout proceeds
        g = plane.store.get("RoleBasedGroup", "default", "pz")
        g.spec.roles[0].rolling_update.paused = False
        plane.store.update(g)

        def rolled():
            pods = [p for p in plane.store.list("Pod", namespace="default")
                    if p.active]
            return (len(pods) == 2
                    and all(p.template.containers[0].image == "engine:v2"
                            for p in pods))

        plane.wait_for(rolled, timeout=20, desc="unpaused rollout completes")
