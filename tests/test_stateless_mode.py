"""Stateless instance engine: random ids, delete preferences,
specified-delete (CloneSet semantics — reference statelessmode)."""

import re

from rbg_tpu.api import constants as C
from rbg_tpu.runtime.controllers.instanceset import ANN_SPECIFIED_DELETE
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


def _plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=2)
    return p


def test_stateless_random_ids_and_scale():
    with _plane() as plane:
        role = simple_role("worker", replicas=3)
        role.identity = "random"
        plane.apply(make_group("sl", role))
        plane.wait_group_ready("sl", timeout=20)

        insts = plane.store.list("RoleInstance", namespace="default")
        assert len(insts) == 3
        # CloneSet-style names: {set}-{5-char random id}, not ordinals.
        for i in insts:
            assert re.fullmatch(r"sl-worker-[a-z0-9]{5}", i.metadata.name)
            assert C.LABEL_INSTANCE_INDEX not in i.metadata.labels

        g = plane.store.get("RoleBasedGroup", "default", "sl")
        g.spec.roles[0].replicas = 1
        plane.store.update(g)
        plane.wait_for(
            lambda: len([p for p in plane.store.list("Pod", namespace="default")
                         if p.active]) == 1,
            timeout=20, desc="stateless scale down",
        )


def test_specified_delete_annotation():
    with _plane() as plane:
        role = simple_role("worker", replicas=2)
        role.identity = "random"
        plane.apply(make_group("sd", role))
        plane.wait_group_ready("sd", timeout=20)

        victim = plane.store.list("RoleInstance", namespace="default")[0]
        name = victim.metadata.name

        def mark(i):
            i.metadata.annotations[ANN_SPECIFIED_DELETE] = "true"
            return True

        plane.store.mutate("RoleInstance", "default", name, mark)

        def replaced():
            insts = plane.store.list("RoleInstance", namespace="default")
            names = {i.metadata.name for i in insts}
            return len(insts) == 2 and name not in names

        plane.wait_for(replaced, timeout=20,
                       desc="specified-delete replaced the instance")
        plane.wait_group_ready("sd", timeout=20)


def test_stateless_paused_freezes_update():
    """paused stops outdated-instance replacement for stateless sets too
    (scale still applies)."""
    import time as _time
    from rbg_tpu.api.group import RollingUpdate

    with _plane() as plane:
        role = simple_role("worker", replicas=2)
        role.identity = "random"
        role.rolling_update = RollingUpdate(paused=True,
                                            in_place_if_possible=False)
        plane.apply(make_group("pz", role))
        plane.wait_group_ready("pz", timeout=20)
        uids0 = {i.metadata.uid for i in
                 plane.store.list("RoleInstance", namespace="default")}

        g = plane.store.get("RoleBasedGroup", "default", "pz")
        g.spec.roles[0].template.containers[0].image = "engine:v2"
        plane.store.update(g)
        _time.sleep(0.8)   # several reconcile cycles
        insts = plane.store.list("RoleInstance", namespace="default")
        assert {i.metadata.uid for i in insts} == uids0, \
            "paused stateless rollout replaced instances"

        # unpause → rollout proceeds
        g = plane.store.get("RoleBasedGroup", "default", "pz")
        g.spec.roles[0].rolling_update.paused = False
        plane.store.update(g)

        def rolled():
            pods = [p for p in plane.store.list("Pod", namespace="default")
                    if p.active]
            return (len(pods) == 2
                    and all(p.template.containers[0].image == "engine:v2"
                            for p in pods))

        plane.wait_for(rolled, timeout=20, desc="unpaused rollout completes")


# ---- preparingDelete drain lifecycle (reference: statelessmode lifecycle
# states constants.go:75-80; VERDICT r1 item 5) ----


def _drain_role(name="worker", replicas=2, drain=30.0, image="engine:v1"):
    role = simple_role(name, replicas=replicas, image=image)
    role.identity = "random"
    role.drain_seconds = drain
    return role


def _draining(plane):
    return [i for i in plane.store.list("RoleInstance", namespace="default")
            if i.metadata.annotations.get(C.ANN_LIFECYCLE_STATE)
            == C.LIFECYCLE_PREPARING_DELETE]


def test_preparing_delete_drains_then_deletes_on_deadline():
    with _plane() as plane:
        plane.apply(make_group("dr", _drain_role(drain=1.0)))
        plane.wait_group_ready("dr", timeout=20)

        g = plane.store.get("RoleBasedGroup", "default", "dr")
        g.spec.roles[0].replicas = 1
        plane.store.update(g)

        # The condemned instance enters PreparingDelete; its pod keeps
        # RUNNING (in-flight work finishes) and carries the drain signal.
        inst = plane.wait_for(lambda: (_draining(plane) or [None])[0],
                              timeout=10, desc="PreparingDelete")
        assert inst.metadata.annotations.get(C.ANN_DRAIN_DEADLINE)
        pods = [p for p in plane.store.list(
                    "Pod", namespace="default",
                    owner_uid=inst.metadata.uid)]
        assert pods and all(p.status.phase == "Running" for p in pods)
        assert all(p.metadata.annotations.get(C.ANN_LIFECYCLE_STATE)
                   == C.LIFECYCLE_PREPARING_DELETE for p in pods)

        # After the deadline the instance dies for real.
        plane.wait_for(
            lambda: len(plane.store.list("RoleInstance",
                                         namespace="default")) == 1
            and not _draining(plane),
            timeout=10, desc="drain deadline deletion")
        plane.wait_group_ready("dr", timeout=20)


def test_drain_complete_ack_deletes_early():
    with _plane() as plane:
        plane.apply(make_group("ack", _drain_role(drain=300.0)))
        plane.wait_group_ready("ack", timeout=20)
        g = plane.store.get("RoleBasedGroup", "default", "ack")
        g.spec.roles[0].replicas = 1
        plane.store.update(g)
        inst = plane.wait_for(lambda: (_draining(plane) or [None])[0],
                              timeout=10, desc="PreparingDelete")

        def ack(i):
            i.metadata.annotations[C.ANN_DRAIN_COMPLETE] = "true"
            return True

        plane.store.mutate("RoleInstance", "default", inst.metadata.name, ack)
        plane.wait_for(
            lambda: plane.store.get("RoleInstance", "default",
                                    inst.metadata.name) is None,
            timeout=10, desc="deleted on drain ack (not the 300s deadline)")


def test_scale_up_resurrects_draining_instance():
    with _plane() as plane:
        plane.apply(make_group("rez", _drain_role(drain=300.0)))
        plane.wait_group_ready("rez", timeout=20)
        g = plane.store.get("RoleBasedGroup", "default", "rez")
        g.spec.roles[0].replicas = 1
        plane.store.update(g)
        inst = plane.wait_for(lambda: (_draining(plane) or [None])[0],
                              timeout=10, desc="PreparingDelete")
        uid = inst.metadata.uid

        g = plane.store.get("RoleBasedGroup", "default", "rez")
        g.spec.roles[0].replicas = 2
        plane.store.update(g)

        def resurrected():
            insts = plane.store.list("RoleInstance", namespace="default")
            if len(insts) != 2 or _draining(plane):
                return None
            return insts if any(i.metadata.uid == uid for i in insts) else None

        plane.wait_for(resurrected, timeout=10,
                       desc="draining instance reclaimed, no 3rd created")

        # Pods lose the drain signal one reconcile after the instance
        # flips back — wait for the annotation clear instead of racing
        # it (load-sensitive flake otherwise).
        def pods_undrained():
            pods = plane.store.list("Pod", namespace="default",
                                    owner_uid=uid)
            return pods and all(
                C.ANN_LIFECYCLE_STATE not in p.metadata.annotations
                for p in pods)

        plane.wait_for(pods_undrained, timeout=10,
                       desc="pods lost the drain annotation")
        plane.wait_group_ready("rez", timeout=20)


def test_specified_delete_is_never_resurrected():
    with _plane() as plane:
        plane.apply(make_group("nsd", _drain_role(drain=1.0)))
        plane.wait_group_ready("nsd", timeout=20)
        victim = plane.store.list("RoleInstance", namespace="default")[0]
        vuid = victim.metadata.uid

        def mark(i):
            i.metadata.annotations[ANN_SPECIFIED_DELETE] = "true"
            return True

        plane.store.mutate("RoleInstance", "default",
                           victim.metadata.name, mark)

        # Replacement is created while the victim drains; the victim dies at
        # the deadline and never rejoins.
        def replaced():
            insts = plane.store.list("RoleInstance", namespace="default")
            live = [i for i in insts if i.metadata.annotations.get(
                C.ANN_LIFECYCLE_STATE) != C.LIFECYCLE_PREPARING_DELETE]
            return (len(live) == 2
                    and all(i.metadata.uid != vuid for i in live)) or None

        plane.wait_for(replaced, timeout=10, desc="replacement while draining")
        plane.wait_for(
            lambda: plane.store.get("RoleInstance", "default",
                                    victim.metadata.name) is None,
            timeout=10, desc="victim deleted at deadline")
        plane.wait_group_ready("nsd", timeout=20)


def test_paused_rollout_still_fires_drain_deadlines():
    """paused freezes updates, not drain deadlines: a condemned instance
    must die at its deadline even while the rollout is paused (review
    finding: the paused path dropped the drain requeue)."""
    with _plane() as plane:
        role = _drain_role(drain=1.0)
        role.rolling_update.paused = True
        plane.apply(make_group("pd", role))
        plane.wait_group_ready("pd", timeout=20)

        g = plane.store.get("RoleBasedGroup", "default", "pd")
        g.spec.roles[0].replicas = 1
        plane.store.update(g)
        plane.wait_for(lambda: (_draining(plane) or [None])[0],
                       timeout=10, desc="PreparingDelete while paused")
        # Deadline (1s) must delete it well before the 10s resync backstop.
        plane.wait_for(
            lambda: len(plane.store.list("RoleInstance",
                                         namespace="default")) == 1
            and not _draining(plane),
            timeout=5, desc="drain deadline fired under paused rollout")


def test_delete_preference_not_ready_first():
    """Scale-down condemns the not-ready instance, not a serving one."""
    with _plane() as plane:
        role = simple_role("w", replicas=2)
        role.identity = "random"
        plane.apply(make_group("pref", role))
        plane.wait_group_ready("pref", timeout=20)

        # Break one instance's pod: restart-policy None keeps it down? No —
        # default policy recreates; instead hold the recreated pod Pending.
        insts = plane.store.list("RoleInstance", namespace="default")
        victim = insts[0]
        survivor_uid = insts[1].metadata.uid
        plane.kubelet.hold_filter = (
            lambda p, uid=victim.metadata.uid:
            (p.metadata.owner_references or [None])[0] is not None
            and p.metadata.owner_references[0].uid == uid)
        pods = plane.store.list("Pod", namespace="default",
                                owner_uid=victim.metadata.uid)
        plane.kubelet.fail_pod("default", pods[0].metadata.name)

        def victim_not_ready():
            i = plane.store.get("RoleInstance", "default",
                                victim.metadata.name)
            from rbg_tpu.runtime.controllers.instanceset import instance_ready
            return i is not None and not instance_ready(i)

        plane.wait_for(victim_not_ready, timeout=10, desc="victim unready")

        g = plane.store.get("RoleBasedGroup", "default", "pref")
        g.spec.roles[0].replicas = 1
        plane.store.update(g)

        def only_survivor():
            insts = plane.store.list("RoleInstance", namespace="default")
            return (len(insts) == 1
                    and insts[0].metadata.uid == survivor_uid) or None

        plane.wait_for(only_survivor, timeout=10,
                       desc="not-ready instance condemned first")


def test_rolling_replacement_keeps_capacity_with_drain():
    """Recreate-style update with a drain window: the old instance serves
    while its replacement warms — total live instances overshoots replicas
    (capacity-first), then converges to the new image only."""
    with _plane() as plane:
        role = _drain_role("w", replicas=2, drain=1.0)
        role.rolling_update.in_place_if_possible = False
        plane.apply(make_group("cap", role))
        plane.wait_group_ready("cap", timeout=20)

        role2 = _drain_role("w", replicas=2, drain=1.0, image="engine:v2")
        role2.rolling_update.in_place_if_possible = False
        plane.apply(make_group("cap", role2))

        saw_overlap = []

        def converged():
            insts = plane.store.list("RoleInstance", namespace="default")
            if len(insts) > 2:
                saw_overlap.append(len(insts))
            live = [i for i in insts if i.metadata.annotations.get(
                C.ANN_LIFECYCLE_STATE) != C.LIFECYCLE_PREPARING_DELETE]
            from rbg_tpu.runtime.controllers.instanceset import instance_ready
            done = (len(insts) == 2 and len(live) == 2
                    and all(instance_ready(i) for i in live)
                    and all(i.spec.instance.template.containers[0].image
                            == "engine:v2" for i in live))
            return done or None

        plane.wait_for(converged, timeout=25, desc="rollout converged to v2")
        assert saw_overlap, "old instance never overlapped its replacement"
