"""Overload protection + lifecycle robustness through the serving path.

The chaos-style invariants of the serving plane (ISSUE 2):

* under sustained overload the service SHEDS (structured ``overloaded`` +
  retry_after_s; the edge maps it to 429 + Retry-After) instead of
  queueing unboundedly — queue depth stays <= max_queue;
* an expired-deadline request is never dispatched to a backend, and an
  in-flight one is aborted engine-side (slot + KV pages recycle);
* SIGTERM flips a draining state: in-flight streams finish, new ops are
  refused with ``draining``, the router routes around the backend WITHOUT
  evicting it, and the process exits cleanly;
* a vanished client cancels the backend decode leg (pages recycle).
"""

import json
import signal
import socket
import socketserver
import subprocess
import sys
import threading
import time

import pytest

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.protocol import (CODE_DEADLINE, CODE_DRAINING,
                                     CODE_OVERLOADED, recv_msg, request_once,
                                     send_msg)
from rbg_tpu.engine.router import (Handler, Registry, RetryBudget,
                                   RouterServer, RouterState, _Rejected)
from rbg_tpu.engine.service import (DeadlineExceeded, EngineService,
                                    Overloaded)

from test_router_resilience import (_EchoBackend, _StreamBackend, _dead_addr,
                                    _wait_for)


# ---- service-level admission control ---------------------------------------


@pytest.fixture(scope="module")
def svc():
    s = EngineService(
        EngineConfig(model="tiny", page_size=8, num_pages=128, max_batch=2,
                     max_seq_len=256, prefill_chunk=16, use_pallas="never",
                     decode_buckets=(1, 2)),
        max_queue=None)
    # Pay the jit compiles BEFORE any deadline-sensitive test runs.
    s.submit_wait([1, 2, 3], SamplingParams(max_new_tokens=4))
    yield s
    s.stop()


def _drain_service(svc, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with svc._lock:
            empty = not svc._queue
        if empty and not svc.engine.has_work():
            return
        time.sleep(0.02)
    raise TimeoutError("service never drained")


def test_queue_bound_sheds_with_retry_hint(svc):
    svc.max_queue = 2
    shed_before = svc.counters["shed_total"]
    pendings, shed = [], None
    try:
        # Saturate: batch(2) + queue(2) admit; further submits must shed.
        for _ in range(12):
            try:
                pendings.append(svc.submit_async(
                    [5, 6, 7], SamplingParams(max_new_tokens=64)))
            except Overloaded as e:
                shed = e
                break
        assert shed is not None, "queue never shed"
        assert shed.retry_after_s is not None and shed.retry_after_s > 0
        assert shed.to_wire()["code"] == CODE_OVERLOADED
        assert svc.counters["shed_total"] == shed_before + 1
        with svc._lock:
            assert len(svc._queue) <= 2
    finally:
        svc.max_queue = None
        for p in pendings:
            svc.cancel(p)
        _drain_service(svc)


def test_expired_deadline_rejected_synchronously(svc):
    before = dict(svc.engine.metrics)
    with pytest.raises(DeadlineExceeded):
        svc.submit_async([1, 2, 3], SamplingParams(max_new_tokens=4),
                         deadline=time.monotonic() - 0.1)
    # Never reached the engine: no prefill, no steps attributable.
    assert svc.engine.metrics["prefill_tokens"] == before["prefill_tokens"]


def test_queued_expiry_dropped_before_admission(svc):
    """A request whose deadline lapses while QUEUED behind long work is
    dropped by the loop without dispatching — the engine never sees it."""
    drops_before = svc.counters["deadline_queue_drops"]
    blockers = [svc.submit_async([9, 9, 9 + i],
                                 SamplingParams(max_new_tokens=200))
                for i in range(2)]  # occupy both batch slots
    try:
        doomed = svc.submit_async([4, 4, 4], SamplingParams(max_new_tokens=4),
                                  deadline=time.monotonic() + 0.2)
        assert doomed.done.wait(10), "expired entry never resolved"
        assert doomed.code == CODE_DEADLINE
        assert doomed.tokens == []
        assert svc.counters["deadline_queue_drops"] > drops_before
    finally:
        for p in blockers:
            svc.cancel(p)
        _drain_service(svc)


def test_running_abort_recycles_slot_and_pages(svc):
    """An admitted request past deadline is aborted ENGINE-side: batch slot
    and KV pages recycle instead of decoding to max_new_tokens.

    The engine's step is throttled for the test's duration so the request
    CANNOT finish inside the deadline on any machine — without this, a
    fast solo run decodes all 240 tokens before the 1 s budget and the
    abort never needs to fire (observed tier-1 flake)."""
    _drain_service(svc)
    free_before = svc.engine.allocator.free_pages
    aborts_before = svc.counters["deadline_running_aborts"]
    orig_step = svc.engine.step

    def slow_step():
        time.sleep(0.05)        # ≤ ~20 tokens/s: 240 can't finish in 1 s
        return orig_step()

    svc.engine.step = slow_step
    try:
        p = svc.submit_async([11, 12, 13],
                             SamplingParams(max_new_tokens=240),
                             deadline=time.monotonic() + 1.0)
        assert p.done.wait(30), "deadline abort never fired"
        assert p.code == CODE_DEADLINE
        assert svc.counters["deadline_running_aborts"] == aborts_before + 1
        # Partial output was produced (it ran), then the abort cut it short.
        assert len(p.tokens) < 240
    finally:
        svc.engine.step = orig_step
    _wait_for(lambda: svc.engine.allocator.free_pages == free_before,
              timeout=10)
    assert not svc.engine.running and not svc.engine.waiting


def test_estimated_wait_gate_sheds_doomed_request(svc):
    """With a measured completion rate, a deadline the backlog can't meet
    is shed AT ADMISSION (the Orca/SGLang-style overload gate) instead of
    queueing work guaranteed to expire."""
    _drain_service(svc)
    now = time.monotonic()
    # Seed completion history: 1 completion/s (measured, not configured).
    svc._done_times.clear()
    svc._done_times.extend([now - 10 + i for i in range(11)])
    blockers = [svc.submit_async([7, 7, 7 + i],
                                 SamplingParams(max_new_tokens=200))
                for i in range(4)]  # backlog: 2 running + 2 queued
    try:
        est = svc.estimated_wait_s()
        assert est is not None and est > 1.0
        with pytest.raises(Overloaded) as ei:
            svc.submit_async([8, 8, 8], SamplingParams(max_new_tokens=4),
                             deadline=time.monotonic() + 0.5)
        assert ei.value.retry_after_s >= 0.5
    finally:
        svc._done_times.clear()
        for p in blockers:
            svc.cancel(p)
        _drain_service(svc)


def test_overload_scenario_invariants():
    """The stress harness's serving-overload drill: sustained overdemand
    sheds instead of queueing unboundedly, every request is accounted,
    and admitted-request latency stays inside the deadline budget."""
    from rbg_tpu.stress.harness import OverloadConfig, run_serving_overload

    cfg = OverloadConfig(clients=4, requests_per_client=3, max_queue=2,
                         max_batch=2, max_new_tokens=16, timeout_s=60.0)
    report = run_serving_overload(cfg)
    assert report["invariants"]["queue_bounded"]
    assert report["invariants"]["all_accounted"]
    assert report["invariants"]["shed_instead_of_queued"]
    assert report["outcomes"]["error"] == 0
    assert report["max_queue_depth_observed"] <= cfg.max_queue
    # p99 of admitted requests bounded by the deadline budget.
    if report["admitted_latency_ms"]["n"]:
        assert report["admitted_latency_ms"]["p99"] <= cfg.timeout_s * 1000


# ---- router: shed routing, draining, retry budget, deadlines ---------------


class _RejectBackend(socketserver.ThreadingTCPServer):
    """Backend that answers every data op with a structured rejection
    (health stays ok so the pool never evicts it for probing reasons)."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, frame, draining_health=False):
        backend = self
        self.seen = []

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        obj, _, _ = recv_msg(self.request)
                    except (ConnectionError, json.JSONDecodeError):
                        return
                    if obj is None:
                        return
                    backend.seen.append(obj)
                    if obj.get("op") == "health":
                        send_msg(self.request, {
                            "ok": True,
                            "draining": backend.draining_health})
                        continue
                    send_msg(self.request, dict(backend.frame))

        self.frame = frame
        self.draining_health = draining_health
        super().__init__(("127.0.0.1", 0), H)
        self.addr = f"127.0.0.1:{self.server_address[1]}"
        threading.Thread(target=self.serve_forever, daemon=True).start()

    def stop(self):
        self.shutdown()
        self.server_close()


OVERLOADED_FRAME = {"error": "queue full", "code": CODE_OVERLOADED,
                    "retry_after_s": 2.0, "done": True}
DRAINING_FRAME = {"error": "server draining", "code": CODE_DRAINING,
                  "done": True}


def test_router_routes_around_overloaded_backend():
    shed = _RejectBackend(OVERLOADED_FRAME)
    ok = _EchoBackend()
    st = RouterState(Registry(None), None, {"worker": [shed.addr, ok.addr]})
    try:
        addr, resp, _, _ = st.call("worker", {"op": "generate", "prompt": [1]},
                                   deadline=time.monotonic() + 30)
        assert addr == ok.addr and resp["tokens"] == [1, 2, 3]
        assert st.metrics["sheds_routed_around"] == 1
        assert shed.addr not in st.pool.evicted()   # healthy, just busy
    finally:
        shed.stop()
        ok.stop()


def test_router_all_overloaded_returns_structured_shed():
    a = _RejectBackend(dict(OVERLOADED_FRAME, retry_after_s=5.0))
    b = _RejectBackend(dict(OVERLOADED_FRAME, retry_after_s=1.5))
    st = RouterState(Registry(None), None, {"worker": [a.addr, b.addr]})
    try:
        with pytest.raises(_Rejected) as ei:
            st.call("worker", {"op": "generate", "prompt": [1]},
                    deadline=time.monotonic() + 30)
        frame = ei.value.frame
        assert frame["code"] == CODE_OVERLOADED
        assert frame["retry_after_s"] == 1.5    # the SMALLEST hint wins
        assert st.metrics["sheds_returned"] == 1
        assert st.pool.evicted() == []
    finally:
        a.stop()
        b.stop()


def test_router_draining_backend_not_candidate_not_evicted():
    dr = _RejectBackend(DRAINING_FRAME, draining_health=True)
    ok = _EchoBackend()
    st = RouterState(Registry(None), None, {"worker": [dr.addr, ok.addr]})
    try:
        # First call discovers the drain via the structured reply.
        addr, resp, _, _ = st.call("worker", {"op": "generate", "prompt": [1]},
                                   deadline=time.monotonic() + 30)
        assert addr == ok.addr
        assert st.metrics["draining_routed_around"] == 1
        assert dr.addr in st.pool.draining()
        assert dr.addr not in st.pool.evicted()  # routed around, NOT evicted
        assert st.pool.snapshot()[dr.addr]["draining"] is True
        # Subsequent candidate ordering keeps the draining backend last.
        assert st.candidates("worker")[0] == ok.addr
    finally:
        dr.stop()
        ok.stop()


def test_prober_clears_draining_when_backend_undrains():
    be = _EchoBackend()   # healthy: health reply carries no draining flag
    st = RouterState(Registry(None), None, {"worker": [be.addr]})
    try:
        st.pool.set_draining(be.addr, True)
        st.pool.probe(timeout=2.0)
        assert be.addr not in st.pool.draining()
    finally:
        be.stop()


def test_retry_budget_stops_failover_amplification():
    dead = _dead_addr()
    ok = _EchoBackend()
    st = RouterState(Registry(None), None, {"worker": [dead, ok.addr]},
                     retry_budget=RetryBudget(rate=0.0, burst=0.0))
    try:
        # The dead backend is tried first (fresh pool: registry order); the
        # empty budget refuses the sibling retry — failure surfaces NOW.
        with pytest.raises(RuntimeError):
            st.call("worker", {"op": "generate", "prompt": [1]})
        assert st.metrics["retry_budget_exhausted"] == 1
        assert st.metrics["retries"] == 0
        assert len(ok.seen) == 0
    finally:
        ok.stop()


def test_router_refuses_spent_deadline_without_dispatch():
    be = _EchoBackend()
    st = RouterState(Registry(None), None, {"worker": [be.addr]})
    try:
        with pytest.raises(_Rejected) as ei:
            st.call("worker", {"op": "generate", "prompt": [1]},
                    deadline=time.monotonic() - 0.1)
        assert ei.value.frame["code"] == CODE_DEADLINE
        assert st.metrics["deadline_refusals"] == 1
        assert len(be.seen) == 0                 # never dispatched
    finally:
        be.stop()


def test_deadline_budget_not_spent_on_doomed_retry():
    """A backend that eats the whole budget (recv timeout) must not be
    followed by a sibling attempt: the budget is spent, the client gets
    deadline_exceeded, and the sibling never sees the request."""

    class _BlackHole(socketserver.ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def __init__(self):
            class H(socketserver.BaseRequestHandler):
                def handle(self):
                    recv_msg(self.request)
                    time.sleep(5.0)         # way past the request budget

            super().__init__(("127.0.0.1", 0), H)
            self.addr = f"127.0.0.1:{self.server_address[1]}"
            threading.Thread(target=self.serve_forever, daemon=True).start()

    hole = _BlackHole()
    sibling = _EchoBackend()
    st = RouterState(Registry(None), None,
                     {"worker": [hole.addr, sibling.addr]})
    try:
        t0 = time.monotonic()
        with pytest.raises(_Rejected) as ei:
            st.call("worker", {"op": "generate", "prompt": [1]},
                    deadline=time.monotonic() + 0.4)
        assert ei.value.frame["code"] == CODE_DEADLINE
        assert time.monotonic() - t0 < 3.0      # budget, not the 120 s cap
        assert len(sibling.seen) == 0
    finally:
        hole.shutdown()
        hole.server_close()
        sibling.stop()


def test_backend_sees_remaining_budget_not_full_timeout():
    be = _EchoBackend()
    st = RouterState(Registry(None), None, {"worker": [be.addr]})
    try:
        st.call("worker", {"op": "generate", "prompt": [1]},
                deadline=time.monotonic() + 7.0)
        fwd = be.seen[-1]
        assert 0 < fwd["timeout_s"] <= 7.0
    finally:
        be.stop()


# ---- router streaming: shed route-around ------------------------------------


def _stream_via_router(state, req):
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = state
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        port = router.server_address[1]
        with socket.create_connection(("127.0.0.1", port), timeout=30) as s:
            send_msg(s, req)
            frames = []
            while True:
                frame, _, _ = recv_msg(s)
                assert frame is not None, "router closed mid-stream"
                frames.append(frame)
                if frame.get("done") or "error" in frame:
                    return frames
    finally:
        router.shutdown()
        router.server_close()


def test_stream_shed_fails_over_to_sibling():
    shed = _RejectBackend(OVERLOADED_FRAME)
    ok = _StreamBackend(n=5)
    state = RouterState(Registry(None), None,
                        {"worker": [shed.addr, ok.addr]})
    try:
        frames = _stream_via_router(
            state, {"op": "generate", "prompt": [1], "stream": True})
        assert all("error" not in f for f in frames), frames
        tokens = [t for f in frames for t in (f.get("tokens") or [])]
        assert tokens == list(range(5))
        assert state.metrics["sheds_routed_around"] == 1
        assert shed.addr not in state.pool.evicted()
    finally:
        shed.stop()
        ok.stop()


def test_stream_all_shed_surfaces_overloaded_frame():
    a = _RejectBackend(OVERLOADED_FRAME)
    b = _RejectBackend(dict(OVERLOADED_FRAME, retry_after_s=0.7))
    state = RouterState(Registry(None), None,
                        {"worker": [a.addr, b.addr]})
    try:
        frames = _stream_via_router(
            state, {"op": "generate", "prompt": [1], "stream": True})
        last = frames[-1]
        assert last["code"] == CODE_OVERLOADED
        assert last["retry_after_s"] == 0.7
        assert state.metrics["errors"] == 0     # a shed is NOT an error
    finally:
        a.stop()
        b.stop()


def test_router_health_snapshot_carries_new_counters():
    ok = _EchoBackend()
    state = RouterState(Registry(None), None, {"worker": [ok.addr]})
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = state
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        port = router.server_address[1]
        h, _, _ = request_once(f"127.0.0.1:{port}", {"op": "health"},
                               timeout=5)
        for key in ("sheds_routed_around", "sheds_returned",
                    "draining_routed_around", "deadline_refusals",
                    "retry_budget_exhausted"):
            assert key in h["metrics"], key
        assert "retry_budget" in h and "tokens" in h["retry_budget"]
        assert h["draining_backends"] == []
    finally:
        router.shutdown()
        router.server_close()
        ok.stop()


# ---- HTTP edge: status-code mapping -----------------------------------------


@pytest.fixture()
def http_edge():
    """In-process OpenAI front end wired to a scriptable protocol backend."""
    import argparse

    from rbg_tpu.engine import http_frontend

    backend = _RejectBackend(OVERLOADED_FRAME)
    args = argparse.Namespace(port=0, host="127.0.0.1", backend=backend.addr,
                              model="tiny", tokenizer_path="",
                              default_max_tokens=16)
    server = http_frontend.serve(args)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    try:
        yield backend, server.server_address[1]
    finally:
        server.shutdown()
        server.server_close()
        backend.stop()


def _http_post(port, path, body):
    import urllib.error
    import urllib.request
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method="POST",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_http_maps_overloaded_to_429_with_retry_after(http_edge):
    backend, port = http_edge
    status, headers, body = _http_post(port, "/v1/completions",
                                       {"prompt": "hi", "max_tokens": 4})
    assert status == 429
    assert headers.get("Retry-After") == "2"    # ceil(2.0)
    assert body["error"]["type"] == "overloaded"


def test_http_maps_draining_to_503(http_edge):
    backend, port = http_edge
    backend.frame = dict(DRAINING_FRAME)
    status, headers, body = _http_post(port, "/v1/chat/completions",
                                       {"messages": [{"role": "user",
                                                      "content": "hi"}]})
    assert status == 503
    assert body["error"]["type"] == "unavailable"


def test_http_maps_deadline_to_504(http_edge):
    backend, port = http_edge
    backend.frame = {"error": "deadline spent", "code": CODE_DEADLINE,
                     "done": True}
    status, _, body = _http_post(port, "/v1/completions",
                                 {"prompt": "hi", "max_tokens": 4})
    assert status == 504
    assert body["error"]["type"] == "timeout"


def test_http_stream_shed_is_http_status_not_sse(http_edge):
    """An admission shed on a STREAMING request must be a real 429 —
    retry middleware can't see codes buried in a 200 event stream."""
    backend, port = http_edge
    status, headers, body = _http_post(
        port, "/v1/completions",
        {"prompt": "hi", "max_tokens": 4, "stream": True})
    assert status == 429
    assert headers.get("Retry-After") == "2"


def test_http_forwards_timeout_budget(http_edge):
    backend, port = http_edge
    _http_post(port, "/v1/completions",
               {"prompt": "hi", "max_tokens": 4, "timeout_s": 7.5})
    assert backend.seen[-1]["timeout_s"] == 7.5


def test_http_rejects_bad_timeout(http_edge):
    backend, port = http_edge
    status, _, body = _http_post(port, "/v1/completions",
                                 {"prompt": "hi", "timeout_s": -3})
    assert status == 400


# ---- e2e: SIGTERM drain + client-disconnect cancellation --------------------


ENGINE_ARGS = ["--model", "tiny", "--page-size", "8", "--num-pages", "128",
               "--max-seq-len", "512", "--prefill-chunk", "16",
               "--use-pallas", "never"]


@pytest.mark.e2e
@pytest.mark.slow
def test_sigterm_drains_stream_then_exits_cleanly():
    """The rollout drill: SIGTERM lands mid-stream. The in-flight stream
    completes, health reports draining, NEW ops are refused with the
    structured code, and the process exits 0 before the drain deadline."""
    from conftest import SpawnedEngineServer

    srv = SpawnedEngineServer(*ENGINE_ARGS, "--max-queue", "8",
                              "--drain-deadline-s", "60")
    with srv:
        # The first stream pays the jit compiles — a wide window in which
        # the SIGTERM lands while the request is genuinely in flight.
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=120)
        try:
            send_msg(s, {"op": "generate", "prompt": [7, 3, 5, 11],
                         "stream": True, "max_new_tokens": 160})
            first, _, _ = recv_msg(s)
            assert first is not None and "error" not in first, first

            srv.proc.send_signal(signal.SIGTERM)
            _wait_for(lambda: request_once(
                srv.addr, {"op": "health"}, timeout=5)[0].get("draining"),
                timeout=10)
            h, _, _ = request_once(srv.addr, {"op": "health"}, timeout=5)
            assert h["ok"] and h["draining"] and "draining_for_s" in h

            # New work is refused with the structured draining code...
            r, _, _ = request_once(srv.addr, {"op": "generate",
                                              "prompt": [1, 2],
                                              "max_new_tokens": 4},
                                   timeout=10)
            assert r["code"] == CODE_DRAINING, r

            # ...while the in-flight stream runs to completion, no error.
            tokens = list(first.get("tokens") or [])
            while True:
                frame, _, _ = recv_msg(s)
                assert frame is not None, "stream cut during drain"
                assert "error" not in frame, frame
                tokens.extend(frame.get("tokens") or [])
                if frame.get("done"):
                    break
            assert len(tokens) == 160
        finally:
            s.close()
        assert srv.proc.wait(timeout=60) == 0   # clean exit, not a kill
    # metrics/gauges flipped (same-process REGISTRY is per-process; the
    # drain counter lives in the subprocess — rc 0 above is the evidence).


@pytest.mark.e2e
@pytest.mark.slow
def test_client_disconnect_cancels_backend_decode_leg():
    """Satellite: the router's _ClientGone path must CANCEL the backend
    decode leg, not merely stop relaying — verified by the decode
    replica's slot (running==0) and KV pages returning to baseline."""
    from rbg_tpu.utils import scrubbed_cpu_env

    def free_port():
        with socket.socket() as so:
            so.bind(("127.0.0.1", 0))
            return so.getsockname()[1]

    env = scrubbed_cpu_env()
    pf, dc, rp = free_port(), free_port(), free_port()
    procs = []
    try:
        for mode, port in (("prefill", pf), ("decode", dc)):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "rbg_tpu.engine.server",
                 "--mode", mode, "--port", str(port)] + ENGINE_ARGS,
                env=env))
        backends = {"prefill": [f"127.0.0.1:{pf}"],
                    "decode": [f"127.0.0.1:{dc}"]}
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.router",
             "--port", str(rp), "--backends", json.dumps(backends)],
            env=env))

        def ready(port):
            try:
                h, _, _ = request_once(f"127.0.0.1:{port}",
                                       {"op": "health"}, timeout=5)
                return bool(h and h.get("ok"))
            except OSError:
                return False
        for port in (pf, dc, rp):
            _wait_for(lambda p=port: ready(p), timeout=240)

        base, _, _ = request_once(f"127.0.0.1:{dc}", {"op": "metrics"},
                                  timeout=10)
        free_before = base["metrics"]["free_pages"]

        s = socket.create_connection(("127.0.0.1", rp), timeout=120)
        send_msg(s, {"op": "generate", "prompt": [7, 3, 5, 11] * 4,
                     "stream": True, "max_new_tokens": 400})
        got = 0
        while got < 2:   # decode leg is live and relaying
            frame, _, _ = recv_msg(s)
            assert frame is not None and "error" not in frame, frame
            got += len(frame.get("tokens") or [])
        # Vanish abruptly (RST — the SSE-edge crash shape).
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     __import__("struct").pack("ii", 1, 0))
        s.close()

        def recycled():
            m, _, _ = request_once(f"127.0.0.1:{dc}", {"op": "metrics"},
                                   timeout=10)
            return (m["metrics"]["running"] == 0
                    and m["metrics"]["free_pages"] == free_before)
        _wait_for(recycled, timeout=30)

        # The vanished client charged NOTHING to the healthy backend.
        h, _, _ = request_once(f"127.0.0.1:{rp}", {"op": "health"},
                               timeout=5)
        assert h["metrics"]["errors"] == 0
        assert h["backends"][f"127.0.0.1:{dc}"]["fails"] == 0
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=30)
            except subprocess.TimeoutExpired:
                p.kill()
