"""KV cache hierarchy (Mooncake tier): host-DRAM spill tier lifecycle —
spill-on-eviction, promote-on-hit, byte-budget enforcement under churn,
directory tier/hotness updates, cache-aware router scoring, predictive
early rejection — and the bit-identity contract: a host-tier hit decodes
identically to a cold prefill.
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.kvpool import KVPoolStore
from rbg_tpu.engine.kvtier import HostKVTier
from rbg_tpu.kvtransfer.directory import PrefixDirectory

PS = 8
BASE = dict(model="tiny", page_size=PS, max_batch=2, max_seq_len=256,
            prefill_chunk=16, use_pallas="never")


def _prompts(n, length, seed=0, vocab=250):
    rng = np.random.RandomState(seed)
    return [rng.randint(1, vocab, size=length).tolist() for _ in range(n)]


def _pages(tokens, n_pages, seed=1):
    """Fake numpy page payloads [L, n_pages, page, KV, hd]."""
    rng = np.random.RandomState(seed)
    return (rng.rand(2, n_pages, PS, 1, 4).astype(np.float32),
            rng.rand(2, n_pages, PS, 1, 4).astype(np.float32))


# ---- KVPoolStore placeholder / extend / hotness mechanics ------------------


def test_store_placeholders_deep_first_spill_then_fill():
    """Radix eviction is leaf-first: DEEP pages arrive before shallow
    ones. The trie must keep placeholder path nodes so the deep payload
    stays reachable, and fill them when the shallow pages arrive."""
    store = KVPoolStore(PS, max_bytes=1 << 20)
    toks = list(range(1, 4 * PS + 1))          # 4 pages
    k, v = _pages(toks, 2)
    # Pages 2..3 spill first (deep), 0..1 exist only as placeholders.
    stored = store.put(toks, k, v, data_from_page=2)
    assert stored == 2
    assert store.stats()["pages"] == 2
    # match() from the root crosses no payload -> miss; extend() past the
    # placeholder depth finds the run.
    assert store.match(toks)[0] == 0
    extra, ek, ev = store.extend(toks, 2 * PS)
    assert extra == 2 * PS
    assert np.array_equal(ek, k) and np.array_equal(ev, v)
    # Shallow pages arrive later: placeholders fill, full match works.
    k01, v01 = _pages(toks, 2, seed=2)
    assert store.put(toks, k01, v01, data_from_page=0) == 2
    matched, mk, _ = store.match(toks)
    assert matched == 4 * PS
    assert np.array_equal(mk[:, :2], k01)
    assert np.array_equal(mk[:, 2:], k)


def test_store_take_moves_pages_out_and_accounting_follows():
    store = KVPoolStore(PS, max_bytes=1 << 20)
    toks = list(range(1, 3 * PS + 1))
    k, v = _pages(toks, 3)
    store.put(toks, k, v)
    bytes_before = store.stats()["bytes"]
    extra, tk, tv = store.extend(toks, 0, take=True)
    assert extra == 3 * PS
    assert np.array_equal(tk, k) and np.array_equal(tv, v)
    s = store.stats()
    assert s["pages"] == 0 and s["bytes"] == 0 and bytes_before > 0
    # Taken pages are GONE (placeholders remain): no second hit.
    assert store.extend(toks, 0)[0] == 0


def test_store_byte_budget_evicts_coldest_first():
    """LRU-by-hotness: under byte pressure the un-hit prefix dies first
    even when it was touched more recently."""
    one_page_bytes = _pages([0], 1)[0].nbytes * 2
    store = KVPoolStore(PS, max_bytes=3 * one_page_bytes)
    hot = list(range(1, PS + 1))
    cold = list(range(100, 100 + PS))
    k, v = _pages(hot, 1)
    store.put(hot, k, v)
    store.put(cold, *_pages(cold, 1, seed=3))
    for _ in range(3):
        assert store.match(hot)[0] == PS       # heat the hot prefix
    store.put(cold, *_pages(cold, 1, seed=3))  # refresh cold's recency
    # Two more prefixes blow the budget: cold (0 hits) must go first.
    store.put(list(range(200, 200 + PS)), *_pages([0], 1, seed=4))
    store.put(list(range(300, 300 + PS)), *_pages([0], 1, seed=5))
    assert store.stats()["bytes"] <= 3 * one_page_bytes
    assert store.match(hot)[0] == PS
    assert store.match(cold)[0] == 0


# ---- host-tier lifecycle against a real engine -----------------------------


def _expect(prompts, sp, **cfg):
    from rbg_tpu.engine.engine import Engine
    return [Engine(EngineConfig(num_pages=256, enable_radix_cache=False,
                                **BASE)).generate([p], sp)[0]
            for p in prompts]


def test_spill_on_eviction_promote_on_hit_bit_identical():
    """The tentpole lifecycle: an undersized device pool evicts between
    prompts (spill), the second pass promotes from host (hit), and every
    output — cold, spilled, promoted — is bit-identical to a cold
    prefill on a reference engine. Accounting closes throughout."""
    from rbg_tpu.engine.engine import Engine

    prompts = _prompts(5, 40, seed=7)
    sp = SamplingParams(max_new_tokens=6)
    expect = _expect(prompts, sp)
    eng = Engine(EngineConfig(num_pages=24, host_tier_bytes=1 << 26,
                              **BASE))
    pass1 = [eng.generate([p], sp)[0] for p in prompts]
    assert pass1 == expect
    tier = eng.host_tier.stats()
    assert tier["spilled_pages"] > 0, "undersized pool never spilled"
    pass2 = [eng.generate([p], sp)[0] for p in prompts]
    assert pass2 == expect, "host-tier hit diverged from cold prefill"
    tier = eng.host_tier.stats()
    assert tier["promoted_pages"] > 0, "second pass never promoted"
    assert eng.metrics["host_hit_tokens"] > 0
    assert eng.host_tier.accounting_closes(), tier
    # Promotion is a MOVE: no prompt may be payload-resident in both
    # tiers at once (device keeps a prefix of the path, host the rest).
    for p in prompts:
        d = eng.radix.peek(p)
        assert not (d > 0 and eng.host_tier.peek(p, 0) > 0)


def test_host_tier_byte_budget_under_churn():
    from rbg_tpu.engine.engine import Engine

    prompts = _prompts(8, 48, seed=11)
    sp = SamplingParams(max_new_tokens=4)
    # Budget of ~4 pages: churn MUST evict host pages, and the lifetime
    # identity still closes (spilled == promoted + evicted + resident).
    one_page = 2 * 2 * PS * 1 * 8 * 4   # [L=2, page, KV=1, hd=8] f32 x2
    eng = Engine(EngineConfig(num_pages=24,
                              host_tier_bytes=4 * one_page, **BASE))
    for _ in range(2):
        for p in prompts:
            eng.generate([p], sp)
    tier = eng.host_tier.stats()
    assert tier["bytes"] <= 4 * one_page
    assert tier["evicted_pages"] > 0, tier
    assert eng.host_tier.accounting_closes(), tier


def test_host_tier_updates_directory_tier_and_hotness():
    from rbg_tpu.engine.engine import Engine

    directory = PrefixDirectory(page_size=PS)
    # 15 usable pages vs ~6 pages/prompt: every admission evicts.
    eng = Engine(EngineConfig(num_pages=16, host_tier_bytes=1 << 26,
                              **BASE))
    eng.host_tier.wire_directory(directory, "10.0.0.9:9", "slice-z")
    prompts = _prompts(4, 40, seed=13)
    sp = SamplingParams(max_new_tokens=4)
    for p in prompts:
        eng.generate([p], sp)
    assert eng.host_tier.stats()["spilled_pages"] > 0
    # Spills registered the evicted prefixes as host-tier holders.
    matched, detail = directory.lookup_detail(prompts[0])
    assert matched > 0 and detail
    assert all(e["backend"] == "10.0.0.9:9" for e in detail)
    first_hot = detail[0]["hotness"]
    # Hotness climbs per deepest-key lookup.
    _, detail2 = directory.lookup_detail(prompts[0])
    assert detail2[0]["hotness"] == first_hot + 1
    # A promotion re-registers the promoted run as device tier. (The
    # full prompt's DEEPEST key covers the first pass's output page,
    # which legitimately stays host-resident — promotion only takes the
    # page-aligned prompt prefix — so probe at the promoted depth.)
    eng.generate([prompts[0]], sp)
    promoted_depth = (len(prompts[0]) - 1) // PS * PS
    _, detail3 = directory.lookup_detail(prompts[0][:promoted_depth])
    assert any(e["tier"] == "device" for e in detail3), detail3


def test_directory_register_tier_refresh_and_client_invalidate_keys():
    d = PrefixDirectory(page_size=PS)
    toks = list(range(1, 2 * PS + 1))
    d.register(toks, "b1", tier="host")
    _, detail = d.lookup_detail(toks)
    assert detail[0]["tier"] == "host"
    d.register(toks, "b1", tier="device")
    _, detail = d.lookup_detail(toks)
    assert detail[0]["tier"] == "device"
    # invalidate_keys drops exactly those pages.
    from rbg_tpu.kvtransfer.chunks import prefix_keys
    keys = prefix_keys(toks, PS)
    assert d.invalidate_keys(keys[1:]) == 1
    matched, _ = d.lookup_detail(toks)
    assert matched == PS


def test_spill_skips_pages_pinned_by_running_requests():
    """A radix-evicted page a RUNNING request still pins (refcount > 1)
    must NOT spill: it stays device-resident and re-enters the radix at
    that request's finish — spilling a copy would put the same content
    in both tiers."""
    from rbg_tpu.engine.engine import Engine

    eng = Engine(EngineConfig(num_pages=32, host_tier_bytes=1 << 26,
                              **BASE))
    calls = []

    class _FakeTier:
        def spill_from_device(self, toks, ids, cache):
            calls.append(list(ids))
            return len(ids)

    eng.host_tier = _FakeTier()
    pages = eng.allocator.alloc(3)
    eng.allocator.share(pages[:2])       # a running request pins 2 pages
    eng._spill_evicted(list(range(1, 3 * PS + 1)), pages)
    assert calls == [pages[2:]]          # only the unpinned tail spills
    calls.clear()
    eng.allocator.share([pages[2]])      # now everything is pinned
    eng._spill_evicted(list(range(1, 3 * PS + 1)), pages)
    assert calls == []                   # nothing to spill at all


def test_host_hits_not_double_counted_when_admission_blocks():
    """A promotion whose request then fails its remaining alloc counts
    NOTHING — the promoted pages entered the radix, so the retry's
    radix.match re-finds them; charging the promotion too would count
    the same tokens under both tiers (and break the prefixcache drill's
    prefill-accounting equality)."""
    from rbg_tpu.engine.engine import Engine

    prompts = _prompts(6, 40, seed=41)
    sp = SamplingParams(max_new_tokens=6)
    eng = Engine(EngineConfig(num_pages=24, host_tier_bytes=1 << 26,
                              **BASE))
    for _ in range(2):
        for p in prompts:
            eng.generate([p], sp)
    total_prompt = 2 * sum(len(p) for p in prompts)
    hits = (eng.metrics["radix_hit_tokens"]
            + eng.metrics["host_hit_tokens"])
    # Combined hits can never exceed the tokens actually submitted.
    assert hits <= total_prompt
    assert eng.metrics["host_hit_tokens"] > 0


def test_invalidate_keys_scoped_to_backend():
    """Per-replica host-tier eviction drops ONLY that replica's claims:
    prefix keys are content-hashed, so replica A evicting a shared
    system prompt must not wipe replica B's still-valid entry."""
    from rbg_tpu.kvtransfer.chunks import prefix_keys

    d = PrefixDirectory(page_size=PS)
    toks = list(range(1, 2 * PS + 1))
    d.register(toks, "a", tier="host")
    d.register(toks, "b", tier="device")
    keys = prefix_keys(toks, PS)
    assert d.invalidate_keys(keys, backend="a") == 2
    matched, detail = d.lookup_detail(toks)
    assert matched == 2 * PS
    assert [e["backend"] for e in detail] == ["b"]
    # Unscoped keeps the shared-pool semantics: everything goes.
    assert d.invalidate_keys(keys) == 2
    assert d.lookup_detail(toks)[0] == 0


def test_host_tier_requires_radix_cache():
    with pytest.raises(ValueError, match="radix"):
        EngineConfig(host_tier_bytes=1 << 20, enable_radix_cache=False,
                     num_pages=32, **BASE).validate()


# ---- cache-aware router scoring --------------------------------------------


class _StubDirectory:
    def __init__(self, matched_tokens, detail):
        self.matched_tokens = matched_tokens
        self.detail = detail

    def lookup_detail(self, _tokens):
        return self.matched_tokens, [dict(e) for e in self.detail]


def test_router_scores_prefix_depth_by_tier_cost():
    from rbg_tpu.engine.router import Registry, RouterState

    prompt = list(range(1, 65))
    # Equal queues: the device-tier holder wins over host-tier holder
    # and both beat the non-holder.
    st = RouterState(Registry(None), None,
                     {"worker": ["dev:1", "host:2", "none:3"]},
                     directory=_StubDirectory(48, [
                         {"backend": "dev:1", "tier": "device",
                          "hotness": 1},
                         {"backend": "host:2", "tier": "host",
                          "hotness": 1}]))
    st.note_kv_observed(64, 64 * 4096)        # bytes/token estimate
    cands = st.candidates_for("worker", prompt)
    assert cands[0] == "dev:1"
    assert cands[1] == "host:2"
    assert st.metrics["directory_hits"] == 1
    # The balance guard IS the scoring: a swamped deep-hit holder loses
    # to an idle miss.
    for _ in range(4):
        st.pool.acquire("dev:1")
        st.pool.acquire("host:2")
    assert st.candidates_for("worker", prompt)[0] == "none:3"


def test_router_replicates_hot_single_holder_prefix():
    from rbg_tpu.engine.router import (REPLICATE_EVERY, Registry,
                                       RouterState)

    prompt = list(range(1, 65))
    st = RouterState(Registry(None), None,
                     {"worker": ["only:1", "other:2"]},
                     directory=_StubDirectory(64, [
                         {"backend": "only:1", "tier": "device",
                          "hotness": 50}]))
    picks = [st.candidates_for("worker", prompt)[0]
             for _ in range(2 * REPLICATE_EVERY)]
    # Most lookups front the holder; every REPLICATE_EVERY-th scores it
    # as a miss so the (equally loaded) non-holder computes + registers.
    assert "only:1" in picks and "other:2" in picks
    assert st.metrics["dir_replications"] == 2
    # The per-prefix ledger bounds the tax: when the off-holder never
    # registers the copy (this stub directory never gains a second
    # holder), replication stops after REPLICATE_MAX_PER_PREFIX routes
    # instead of deliberately full-prefilling hot traffic forever.
    from rbg_tpu.engine.router import REPLICATE_MAX_PER_PREFIX
    for _ in range(10 * REPLICATE_EVERY):
        st.candidates_for("worker", prompt)
    assert st.metrics["dir_replications"] == REPLICATE_MAX_PER_PREFIX


# ---- predictive early rejection --------------------------------------------


def _mk_service(**over):
    from rbg_tpu.engine.service import EngineService
    cfg = dict(num_pages=64, early_reject="auto", slo_ttft_s=0.5,
               early_reject_factor=1.0, **BASE)
    cfg.update(over)
    return EngineService(EngineConfig(**cfg))


def test_early_reject_sheds_at_ingress_with_retry_hint():
    from rbg_tpu.engine.protocol import Overloaded

    svc = _mk_service()
    try:
        # Force the predictor's inputs: slow measured prefill makes the
        # prediction exceed the gate before ANY engine work happens.
        svc._prefill_rate = 10.0               # tokens/s
        svc._pf_rate_t = time.monotonic()      # fresh, not TTL-expired
        prompt = _prompts(1, 40, seed=17)[0]   # 40 tok / 10 tps = 4 s
        pf_before = svc.engine.metrics["prefill_tokens"]
        with pytest.raises(Overloaded) as ei:
            svc.submit(prompt, SamplingParams(max_new_tokens=4))
        assert ei.value.retry_after_s and ei.value.retry_after_s > 0
        assert svc.counters["early_rejects"] == 1
        # ZERO prefill steps were spent on the rejected request.
        assert svc.engine.metrics["prefill_tokens"] == pf_before
        stats = svc.service_stats()
        assert stats["early_reject_armed"] is True
        assert stats["early_rejects"] == 1
    finally:
        svc.stop()


def test_early_reject_never_sheds_without_rate_history():
    svc = _mk_service()
    try:
        assert svc._prefill_rate is None
        tokens, _ = svc.submit(_prompts(1, 40, seed=19)[0],
                               SamplingParams(max_new_tokens=4))
        assert tokens
        assert svc.counters["early_rejects"] == 0
        # A TTL-expired rate is absence of signal too: a stale-slow EMA
        # (sheds do no prefill, so it could never re-learn) must not
        # lock the service into rejecting everything forever.
        svc._prefill_rate = 1.0
        svc._pf_rate_t = time.monotonic() - 3600.0
        tokens, _ = svc.submit(_prompts(1, 40, seed=20)[0],
                               SamplingParams(max_new_tokens=4))
        assert tokens
        assert svc.counters["early_rejects"] == 0
    finally:
        svc.stop()


def test_predicted_ttft_nets_out_prefix_hit():
    svc = _mk_service()
    try:
        prompt = _prompts(1, 40, seed=23)[0]
        svc.submit(prompt, SamplingParams(max_new_tokens=4))
        svc._prefill_rate = 100.0
        svc._pf_rate_t = time.monotonic()
        cold = svc.predicted_ttft_s(_prompts(1, 40, seed=29)[0], depth=0)
        warm = svc.predicted_ttft_s(prompt, depth=0)
        # The served prompt's radix-cached prefix must shrink its
        # predicted prefill time vs an unseen prompt of equal length.
        assert warm is not None and cold is not None and warm < cold
    finally:
        svc.stop()


def test_early_reject_off_by_default():
    svc = _mk_service(early_reject="off")
    try:
        assert svc._early_reject is False
        svc._prefill_rate = 1.0   # would reject everything if armed
        tokens, _ = svc.submit(_prompts(1, 40, seed=31)[0],
                               SamplingParams(max_new_tokens=4))
        assert tokens
    finally:
        svc.stop()


# ---- operator surface ------------------------------------------------------


def test_slo_response_and_top_render_cache_panel():
    from rbg_tpu.cli.top import _cache_panel
    from rbg_tpu.engine.engine import Engine
    from rbg_tpu.obs.slo import slo_response

    eng = Engine(EngineConfig(num_pages=24, host_tier_bytes=1 << 26,
                              **BASE))
    for p in _prompts(4, 40, seed=37):
        eng.generate([p], SamplingParams(max_new_tokens=4))
    cache = slo_response(60).get("cache")
    assert cache and "host" in cache["tiers"] and "device" in cache["tiers"]
    assert cache["tiers"]["host"]["pages"] is not None
    lines = _cache_panel(cache)
    assert any("kv cache" in ln for ln in lines)
    assert any(ln.strip().startswith("host") for ln in lines)
    assert any(ln.strip().startswith("device") for ln in lines)
