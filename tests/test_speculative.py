"""Speculative decoding (prompt-lookup / n-gram drafting).

The load-bearing property: because sampling randomness is position-keyed
(rbg_tpu/engine/sampler.py), speculative output is BIT-IDENTICAL to
non-speculative output — greedy and temperature sampling alike — so every
test here is an exact-equality check, not a distribution check.

Reference context: the reference's engines (SGLang/vLLM) ship n-gram
speculative decoding as a headline feature; the verify pass here is one
(B, K+1) ``forward_paged`` whose per-query causal masking
(ops/paged_attention.py:58) guarantees junk post-mismatch KV never
pollutes accepted positions."""

import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.spec import NGramIndex


# ---- NGramIndex ----


def test_ngram_draft_basic():
    idx = NGramIndex(2)
    idx.extend([1, 2, 3, 1, 2])
    assert idx.draft(2) == [3, 1]          # continuation of earlier (1,2)
    idx.append(3)                          # tail (2,3) seen earlier at idx 2
    assert idx.draft(3) == [1, 2, 3]


def test_ngram_no_earlier_occurrence():
    idx = NGramIndex(3)
    idx.extend([5, 6, 7])
    assert idx.draft(4) == []              # only occurrence is the tail


def test_ngram_truncated_at_sequence_end():
    idx = NGramIndex(1)
    idx.extend([4, 4, 4])
    assert idx.draft(2) == [4]             # continuation shorter than k


def test_ngram_most_recent_match_wins():
    idx = NGramIndex(2)
    idx.extend([1, 2, 9, 5, 1, 2, 7, 3, 1, 2])
    assert idx.draft(1) == [7]             # the LATER (1,2) continuation


# ---- engine equivalence ----


def _mk(**kw):
    return Engine(EngineConfig(model="tiny", page_size=8, num_pages=128,
                               max_seq_len=256, use_pallas="never",
                               enable_radix_cache=False, **kw))


REP_PROMPT = [1, 2, 3, 4] * 8


def test_spec_greedy_bit_identical():
    plain = _mk().generate([REP_PROMPT], SamplingParams(max_new_tokens=24))[0]
    eng = _mk(speculative="ngram")
    spec = eng.generate([REP_PROMPT], SamplingParams(max_new_tokens=24))[0]
    assert plain == spec
    assert eng.metrics["spec_steps"] > 0
    assert eng.metrics["spec_accepted"] <= eng.metrics["spec_drafted"]


@pytest.mark.slow
def test_spec_sampled_bit_identical():
    sp = SamplingParams(max_new_tokens=24, temperature=1.0, top_p=0.9, seed=3)
    a = _mk().generate([REP_PROMPT], sp)[0]
    b = _mk(speculative="ngram").generate([REP_PROMPT], sp)[0]
    assert a == b


@pytest.mark.slow
def test_spec_batch_bit_identical():
    prompts = [[1, 2, 3] * 6, [9, 8, 7, 6, 5], [4] * 8]
    sp = SamplingParams(max_new_tokens=12)
    assert _mk().generate(prompts, sp) == \
        _mk(speculative="ngram").generate(prompts, sp)


@pytest.mark.slow
def test_spec_stop_token_respected():
    # Find the greedy continuation, then stop on its 3rd token — spec and
    # plain paths must cut at the same place.
    base = _mk().generate([REP_PROMPT], SamplingParams(max_new_tokens=10))[0]
    stop = base[2]
    sp = SamplingParams(max_new_tokens=10, stop_token=stop)
    plain = _mk().generate([REP_PROMPT], sp)[0]
    spec = _mk(speculative="ngram").generate([REP_PROMPT], sp)[0]
    assert plain == spec
    assert plain[-1] == stop or len(plain) == 10


@pytest.mark.slow
def test_spec_penalties_never_draft_but_match_sequential():
    # Penalized rows need sequential count updates, so they never draft —
    # they ride the host-synced step one token at a time with fresh
    # host-built counts, matching the sequential result exactly.
    sp = SamplingParams(max_new_tokens=12, presence_penalty=1e9)
    plain = _mk().generate([REP_PROMPT], sp)[0]
    eng = _mk(speculative="ngram")
    spec = eng.generate([REP_PROMPT], sp)[0]
    assert plain == spec
    assert eng.metrics["spec_drafted"] == 0    # penalties suppress drafting
    assert eng.metrics["spec_steps"] > 0
    assert len(set(spec)) == len(spec)


def test_spec_logprobs_emitted():
    eng = _mk(speculative="ngram")
    rid = eng.add_request(REP_PROMPT,
                          SamplingParams(max_new_tokens=8, logprobs=True))
    lps = []
    while eng.has_work():
        for ev in eng.step():
            if ev.request_id == rid:
                lps.append(ev.logprob)
    assert len(lps) == 8
    assert all(lp is not None and lp <= 0 for lp in lps)


@pytest.mark.slow
def test_spec_preemption_equivalence():
    # Tight page pool forces preemption mid-spec; output must still match
    # the sequential result from an unconstrained engine.
    sp = SamplingParams(max_new_tokens=16, seed=5, temperature=1.0)
    prompts = [[1, 2, 3, 4] * 4, [5, 6, 7, 8] * 4, [2, 4, 6, 8] * 4]
    big = _mk().generate(prompts, sp)
    eng = Engine(EngineConfig(model="tiny", page_size=8, num_pages=10,
                              max_seq_len=256, use_pallas="never",
                              enable_radix_cache=False, speculative="ngram"))
    small = eng.generate(prompts, sp)
    assert eng.metrics["preemptions"] > 0
    assert big == small


def test_spec_config_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        EngineConfig(model="tiny", speculative="ngram",
                     multi_step=4).validate()
    with pytest.raises(ValueError, match="speculative"):
        EngineConfig(model="tiny", speculative="eagle").validate()
