"""Incremental scheduler state (sched/capacity.py) + store label indexes.

The cache must mirror the store exactly under churn — a drifted aggregate
double-books a TPU host or deadlocks a gang (VERDICT r1 item 6).
"""

from rbg_tpu.api import constants as C
from rbg_tpu.api.pod import Pod
from rbg_tpu.runtime.store import Store
from rbg_tpu.sched.capacity import CapacityCache
from rbg_tpu.testutil import make_tpu_nodes


def _pod(name, node="", group="", tpu=False, excl_key=""):
    p = Pod()
    p.metadata.name = name
    p.metadata.namespace = "default"
    p.node_name = node
    if group:
        p.metadata.labels[C.LABEL_GROUP_NAME] = group
    if tpu:
        p.template.scheduler_hints["tpu-slice"] = "true"
    if excl_key:
        p.metadata.annotations[C.ANN_EXCLUSIVE_TOPOLOGY] = excl_key
    return p


def _mirror(store, cap):
    """Assert every cache view equals a from-scratch recompute."""
    fresh = CapacityCache(store)
    fresh.rebuild()
    assert cap.free_view() == fresh.free_view()
    assert cap.tpu_used_view() == fresh.tpu_used_view()
    assert cap.excl_view() == fresh.excl_view()


def test_cache_tracks_bind_fail_delete_churn():
    store = Store()
    make_tpu_nodes(store, slices=2, hosts_per_slice=2)
    cap = CapacityCache(store)
    cap.start()

    p1 = store.create(_pod("a", node="slice-0-host-0", tpu=True))
    p2 = store.create(_pod("b", node="slice-0-host-1"))
    assert cap.tpu_used_view() == {"slice-0-host-0"}
    free = cap.free_view()
    assert free["slice-0-host-0"] == 63 and free["slice-0-host-1"] == 63
    _mirror(store, cap)

    # Failed pod releases its capacity (inactive).
    store.mutate("Pod", "default", "a",
                 lambda p: setattr(p.status, "phase", "Failed") or True,
                 status=True)
    assert cap.tpu_used_view() == set()
    _mirror(store, cap)

    store.delete("Pod", "default", "b")
    assert cap.free_view()["slice-0-host-1"] == 64
    _mirror(store, cap)


def test_exclusive_topology_refcounts():
    store = Store()
    nodes = make_tpu_nodes(store, slices=2, hosts_per_slice=2)
    key = "topology.rbg.tpu/block"
    domain = nodes[0].labels[key]
    cap = CapacityCache(store)
    cap.start()

    store.create(_pod("x1", node="slice-0-host-0", group="g1", excl_key=key))
    store.create(_pod("x2", node="slice-0-host-1", group="g1", excl_key=key))
    assert cap.excl_view() == {(key, domain): "g1"}

    # Ownership survives one pod leaving, releases when the last leaves.
    store.delete("Pod", "default", "x1")
    assert cap.excl_view() == {(key, domain): "g1"}
    store.delete("Pod", "default", "x2")
    assert cap.excl_view() == {}
    _mirror(store, cap)


def test_apply_bind_is_idempotent_with_watch_event():
    store = Store()
    make_tpu_nodes(store, slices=1, hosts_per_slice=2)
    cap = CapacityCache(store)
    cap.start()
    pod = store.create(_pod("p", node=""))
    # Simulate the scheduler's synchronous accounting followed by the
    # watch event for the same bind: UID-keyed replace must not double.
    bound = store.mutate("Pod", "default", "p",
                         lambda p: setattr(p, "node_name", "slice-0-host-0") or True)
    cap.apply_bind(bound)   # explicit (the watch already fired too)
    assert cap.free_view()["slice-0-host-0"] == 63
    _mirror(store, cap)


def test_store_label_index_matches_scan():
    store = Store()
    for i in range(20):
        store.create(_pod(f"p{i}", group=f"g{i % 3}"))
    by_index = store.list("Pod", namespace="default",
                          selector={C.LABEL_GROUP_NAME: "g1"})
    names = {p.metadata.name for p in by_index}
    assert names == {f"p{i}" for i in range(20) if i % 3 == 1}
    # Label change moves the object between buckets.
    store.mutate("Pod", "default", "p1",
                 lambda p: p.metadata.labels.__setitem__(
                     C.LABEL_GROUP_NAME, "g2") or True)
    assert not any(p.metadata.name == "p1" for p in store.list(
        "Pod", selector={C.LABEL_GROUP_NAME: "g1"}))
    assert any(p.metadata.name == "p1" for p in store.list(
        "Pod", selector={C.LABEL_GROUP_NAME: "g2"}))
    # Deletion drops it from the bucket.
    store.delete("Pod", "default", "p1")
    assert not any(p.metadata.name == "p1" for p in store.list(
        "Pod", selector={C.LABEL_GROUP_NAME: "g2"}))


def test_kind_version_bumps_on_writes():
    store = Store()
    v0 = store.kind_version("Node")
    make_tpu_nodes(store, slices=1, hosts_per_slice=1)
    v1 = store.kind_version("Node")
    assert v1 > v0
    assert store.kind_version("Pod") == 0
    store.create(_pod("p"))
    assert store.kind_version("Pod") == 1
    store.delete("Pod", "default", "p")
    assert store.kind_version("Pod") > 1
