"""Deterministic chaos plane + the robustness seams it exercises.

The injectors (``rbg_tpu/chaos``) wrap production boundaries — the
kvtransfer ``Transport``, the ``DirectoryClient`` wire hook, the
injectable clocks — so every test here is the production detection /
degradation path reacting to a scripted fault, never a mock of it.
Engine-free throughout (numpy + sockets): these all run in tier 1.
"""

import threading
import time

import numpy as np
import pytest

from rbg_tpu.chaos import (BROWNOUT, CORRUPT, PARTITION, SKEW, ChaosClock,
                           ChaosTransport, FaultSchedule, FaultWindow,
                           SkewedClock, directory_fault)
from rbg_tpu.kvtransfer import (ChunkAssembler, InProcTransport,
                                KVIntegrityError, StreamError, StreamFin,
                                StreamMeta, bundle_to_frames,
                                payload_checksum)
from rbg_tpu.kvtransfer.chunks import KVChunk
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY


def mk_meta(sid="s1", n_pages=4, layers=3, page=8, kv=2, hd=4):
    prompt = list(range(1, n_pages * page + 1))
    return StreamMeta(stream_id=sid, prompt=prompt, n_pages=n_pages,
                      k_page_shape=(page, kv, hd),
                      v_page_shape=(page, kv, hd),
                      dtype="float32", layers=layers, page_size=page)


def mk_payload(meta, seed=0):
    rng = np.random.RandomState(seed)
    k = rng.randn(*meta.k_shape()).astype(np.float32)
    v = rng.randn(*meta.v_shape()).astype(np.float32)
    return k, v


# ---- schedule & clocks -----------------------------------------------------


def test_fault_windows_open_and_close_on_the_scripted_clock():
    clock = ChaosClock(t0=0.0)
    s = FaultSchedule([FaultWindow(PARTITION, 1.0, 2.0,
                                   params={"dead": ["a->b"]})], clock=clock)
    assert s.active(PARTITION) is None
    clock.set(1.5)
    w = s.active(PARTITION)
    assert w is not None
    assert FaultSchedule.cut(w, "a", "b")
    assert not FaultSchedule.cut(w, "b", "a")      # asymmetry is the point
    clock.set(2.0)                                 # half-open interval
    assert s.active(PARTITION) is None


def test_skewed_clock_offsets_only_named_process_in_window():
    clock = ChaosClock(t0=0.0)
    s = FaultSchedule([FaultWindow(SKEW, 1.0, 2.0,
                                   params={"offsets": {"b": 0.5}})],
                      clock=clock)
    a, b = SkewedClock(clock, s, "a"), SkewedClock(clock, s, "b")
    before = REGISTRY.counter(obs_names.CHAOS_FAULTS_INJECTED_TOTAL,
                              kind=SKEW)
    assert a() == b() == 0.0
    clock.set(1.5)
    assert a() == 1.5 and b() == 2.0
    # Counted once per window entry, not once per read.
    b()
    assert REGISTRY.counter(obs_names.CHAOS_FAULTS_INJECTED_TOTAL,
                            kind=SKEW) == before + 1
    clock.set(2.5)
    assert b() == 2.5


# ---- transport injectors ---------------------------------------------------


def _drain(transport, sid, timeout=2.0):
    out = []
    for f in transport.recv_chunks(sid, timeout=timeout):
        out.append(f)
        if isinstance(f, StreamFin):
            break
    return out


def test_corrupted_chunk_keeps_truthful_checksum_and_fails_commit():
    meta = mk_meta()
    k, v = mk_payload(meta)
    frames = bundle_to_frames(meta, k, v, first_token=7, layer_split=1)
    clock = ChaosClock(t0=0.0)
    sched = FaultSchedule([FaultWindow(CORRUPT, 0.0, 10.0,
                                       params={"max_faults": 1})],
                          clock=clock, seed=5)
    link = ChaosTransport(InProcTransport(), sched)
    before = REGISTRY.counter(obs_names.KVT_INTEGRITY_FAILURES_TOTAL,
                              surface="chunk")
    for f in frames:
        link.send_one("peer", f)
    got = _drain(link, meta.stream_id)
    wounded = [f for f in got if isinstance(f, KVChunk)
               and payload_checksum(f.k_bytes, f.v_bytes) != f.checksum]
    assert len(wounded) == 1, "exactly the budgeted chunk is corrupted"
    a = ChunkAssembler(meta)
    with pytest.raises(KVIntegrityError) as ei:
        for f in got[1:]:
            a.feed(f)
    assert isinstance(ei.value, StreamError)       # rides bundle fallback
    assert ei.value.wire_code == "kv_integrity_failed"
    assert REGISTRY.counter(obs_names.KVT_INTEGRITY_FAILURES_TOTAL,
                            surface="chunk") == before + 1


def test_corruption_budget_and_seed_are_deterministic():
    def run():
        meta = mk_meta()
        k, v = mk_payload(meta)
        frames = bundle_to_frames(meta, k, v, first_token=7, layer_split=1)
        clock = ChaosClock(t0=0.0)
        sched = FaultSchedule([FaultWindow(CORRUPT, 0.0, 10.0,
                                           params={"max_faults": 2})],
                              clock=clock, seed=11)
        link = ChaosTransport(InProcTransport(), sched)
        for f in frames:
            link.send_one("peer", f)
        return [f.k_bytes for f in _drain(link, meta.stream_id)
                if isinstance(f, KVChunk)]

    assert run() == run(), "same schedule + seed must replay byte-exact"


def test_asymmetric_partition_blackholes_one_direction_only():
    meta = mk_meta(sid="p1")
    clock = ChaosClock(t0=5.0)
    sched = FaultSchedule([FaultWindow(PARTITION, 0.0, 10.0,
                                       params={"dead": ["a->b"]})],
                          clock=clock)
    inner = InProcTransport()
    ab = ChaosTransport(inner, sched, src="a", dst="b")
    ba = ChaosTransport(inner, sched, src="b", dst="a")
    ab.send_one("peer", StreamFin(stream_id="p1", n_chunks=0))
    ba.send_one("peer", StreamFin(stream_id="p2", n_chunks=0))
    # a→b vanished: nothing arrives, the receiver's bounded wait fires —
    # exactly how a real blackhole presents (no error, no FIN).
    with pytest.raises(StreamError):
        list(inner.recv_chunks(meta.stream_id, timeout=0.1))
    # … while b→a delivered.
    got = _drain(inner, "p2", timeout=1.0)
    assert len(got) == 1 and isinstance(got[0], StreamFin)


def test_brownout_delays_every_in_window_send():
    clock = ChaosClock(t0=0.0)
    sched = FaultSchedule([FaultWindow(BROWNOUT, 0.0, 10.0,
                                       params={"delay_s": 0.05})],
                          clock=clock)
    link = ChaosTransport(InProcTransport(), sched)
    t0 = time.monotonic()
    link.send_one("peer", StreamFin(stream_id="b1", n_chunks=0))
    assert time.monotonic() - t0 >= 0.05
    clock.set(11.0)                                 # window closed
    t0 = time.monotonic()
    link.send_one("peer", StreamFin(stream_id="b2", n_chunks=0))
    assert time.monotonic() - t0 < 0.04


# ---- duplicate / reordered delivery accounting -----------------------------


def test_assembler_counts_duplicate_and_reordered_chunks():
    meta = mk_meta()
    k, v = mk_payload(meta)
    frames = bundle_to_frames(meta, k, v, first_token=7, layer_split=1)
    data = frames[1:-2]
    dup_before = REGISTRY.counter(obs_names.KVT_CHUNKS_DUPLICATE_TOTAL)
    reo_before = REGISTRY.counter(obs_names.KVT_CHUNKS_REORDERED_TOTAL)
    a = ChunkAssembler(meta)
    # Deliver 0,2,1 then replay 0 twice: one reorder (1 after 2), two dups.
    a.feed(data[0])
    a.feed(data[2])
    a.feed(data[1])
    a.feed(data[0])
    a.feed(data[0])
    for ch in data[3:]:
        a.feed(ch)
    assert a.dup_chunks == 2 and a.reordered_chunks == 1
    assert REGISTRY.counter(
        obs_names.KVT_CHUNKS_DUPLICATE_TOTAL) == dup_before + 2
    assert REGISTRY.counter(
        obs_names.KVT_CHUNKS_REORDERED_TOTAL) == reo_before + 1


def test_duplicate_of_committed_chunk_never_kills_a_healthy_stream():
    """A corrupted RETRANSMIT of an already-committed chunk is dropped by
    the duplicate path before checksum verify — the copy that counted was
    verified; a late wounded twin must not wedge the stream."""
    import dataclasses as _dc

    meta = mk_meta()
    k, v = mk_payload(meta)
    frames = bundle_to_frames(meta, k, v, first_token=7, layer_split=1)
    data = frames[1:-2]
    a = ChunkAssembler(meta)
    for ch in data:
        a.feed(ch)
    bad = _dc.replace(data[0],
                      k_bytes=bytes(len(data[0].k_bytes)))  # zeroed payload
    a.feed(bad)                                             # no raise
    a.feed(frames[-2])
    a.feed(frames[-1])
    assert a.ready()


# ---- pool page integrity ---------------------------------------------------


def _page(shape=(2, 1, 8, 2, 4), seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(*shape).astype(np.float32),
            rng.randn(*shape).astype(np.float32))


def test_pool_detects_bitrot_truncates_hit_and_invalidates_directory():
    from rbg_tpu.engine.kvpool import KVPoolStore
    from rbg_tpu.kvtransfer import PrefixDirectory

    d = PrefixDirectory(page_size=8)
    store = KVPoolStore(8, directory=d)
    store.owner_backend = "10.0.0.9:9000"
    toks = list(range(24))                      # three pages
    k, v = _page(shape=(2, 3, 8, 2, 4))
    assert store.put(toks, k, v) == 3               # pages committed
    d.register(toks, "10.0.0.9:9000")
    before = REGISTRY.counter(obs_names.KVT_INTEGRITY_FAILURES_TOTAL,
                              surface="pool")

    # Bit-rot the middle page in place (host-tier spill, DMA, cosmic ray
    # — the cause doesn't matter; the stored crc no longer matches).
    node = store.root.children[tuple(toks[0:8])]
    mid = node.children[tuple(toks[8:16])]
    mid.k[0].flat[3] += 1.0

    matched, mk, mv = store.match(toks)
    assert matched == 8, "hit truncated to the leading GOOD pages"
    assert mk is not None and mk.shape[1] == 1
    assert REGISTRY.counter(obs_names.KVT_INTEGRITY_FAILURES_TOTAL,
                            surface="pool") == before + 1
    # The rotten page is gone (cannot poison the next hit) and its
    # directory claim is withdrawn.
    assert store.metrics["evicted_pages"] >= 1
    m2, holders = d.lookup(toks)
    assert m2 <= 8
    assert store.match(toks)[0] == 8            # stable on re-match


# ---- directory wire: chaos hook, degrade ladder, single-flight probe -------


def _pool_server():
    from rbg_tpu.engine.kvpool import KVPoolServer, KVPoolStore
    from rbg_tpu.kvtransfer import PrefixDirectory

    d = PrefixDirectory(page_size=8)
    srv = KVPoolServer(("127.0.0.1", 0), KVPoolStore(8, directory=d))
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"127.0.0.1:{srv.server_address[1]}"


def test_directory_partition_degrades_then_recovers_via_breaker():
    from rbg_tpu.kvtransfer.directory import DirectoryClient

    srv, addr = _pool_server()
    try:
        clock = ChaosClock(t0=0.0)
        sched = FaultSchedule(
            [FaultWindow(PARTITION, 1.0, 2.0,
                         params={"dead": ["router->directory"]})],
            clock=clock)
        c = DirectoryClient(addr, timeout=2.0, page_size=8, token="",
                            backoff_s=0.05, backoff_max_s=0.2,
                            chaos=directory_fault(sched))
        toks = list(range(16))
        assert c.register(toks, "b1", slice_id="sl") == 2
        assert c.lookup(toks) == (16, ["b1"])
        clock.set(1.5)                           # partition opens
        assert c.lookup(toks) == (0, [])         # degraded, instantly
        assert REGISTRY.gauge(obs_names.DEGRADED_MODE,
                              ladder="directory") == 1.0
        clock.set(2.5)                           # heal
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline:
            if c.lookup(toks) == (16, ["b1"]):
                break
            time.sleep(0.01)
        assert c.lookup(toks) == (16, ["b1"])
        assert REGISTRY.gauge(obs_names.DEGRADED_MODE,
                              ladder="directory") == 0.0
    finally:
        srv.shutdown()
        srv.server_close()


def test_breaker_half_open_probe_is_single_flight():
    """When the backoff window closes, EXACTLY ONE caller probes; every
    concurrent caller stays on the degraded fast path — recovery must not
    thundering-herd a directory that just came back."""
    from rbg_tpu.kvtransfer.directory import DirectoryClient

    srv, addr = _pool_server()
    try:
        attempts = {"n": 0}
        gate = threading.Event()

        def chaos():
            attempts["n"] += 1
            gate.wait(1.0)     # hold the probe open under the lock-free
                               # window so peers must decide concurrently

        c = DirectoryClient(addr, timeout=2.0, page_size=8, token="",
                            backoff_s=0.05, backoff_max_s=0.2, chaos=chaos)
        # Open the breaker once (real failure), then let the window pass.
        gate.set()
        c._down_until = 0.0
        srv_alive_probe = c.lookup_keys(["k"])   # addr is alive: fine
        assert srv_alive_probe == (0, [])        # no keys registered yet
        with c._lock:
            c._down_until = time.monotonic() + 0.05
        time.sleep(0.08)                         # window now closed
        gate.clear()
        attempts["n"] = 0
        results = []
        start = threading.Barrier(8)

        def caller():
            start.wait(2.0)
            results.append(c.lookup_keys(["k"]))

        threads = [threading.Thread(target=caller) for _ in range(8)]
        for t in threads:
            t.start()
        time.sleep(0.15)       # everyone has hit the breaker by now
        gate.set()             # release the one probe
        for t in threads:
            t.join(timeout=5.0)
        assert attempts["n"] == 1, \
            f"half-open probe not single-flight: {attempts['n']} attempts"
        assert len(results) == 8
    finally:
        srv.shutdown()
        srv.server_close()


# ---- router tier: staleness TTL + ingress counter restart ------------------


def test_stale_peer_spills_to_successors_until_it_speaks():
    from rbg_tpu.engine.routertier import EV_HEALTH, RouterTier

    clock = {"t": 0.0}
    tier = RouterTier(name="st", clock=lambda: clock["t"],
                      peer_stale_after_s=1.0)
    for n in ("ra", "rb", "rc"):
        tier.register(n)
    keys = [f"k{i}" for i in range(48)]
    assert {tier.route(k) for k in keys} == {"ra", "rb", "rc"}
    clock["t"] = 2.0
    for n in ("ra", "rc"):
        tier.publish(n, EV_HEALTH, {"ok": True})
    served = {tier.route(k) for k in keys}
    assert "rb" not in served and served, "silent member must spill"
    assert REGISTRY.gauge(obs_names.DEGRADED_MODE,
                          ladder="peer_feed") == 1.0
    snap = tier.snapshot()
    assert snap["members"]["rb"]["stale"] is True
    tier.publish("rb", EV_HEALTH, {"ok": True})    # proof of life
    assert "rb" in {tier.route(k) for k in keys}
    assert REGISTRY.gauge(obs_names.DEGRADED_MODE,
                          ladder="peer_feed") == 0.0


def test_staleness_off_by_default_keeps_quiet_tiers_routable():
    from rbg_tpu.engine.routertier import RouterTier

    clock = {"t": 0.0}
    tier = RouterTier(name="quiet", clock=lambda: clock["t"])
    tier.register("only")
    clock["t"] = 1e6                               # silent for ages
    assert tier.route("k") == "only"


def test_ingress_publish_counter_restart_folds_not_negative():
    from rbg_tpu.engine.routertier import EV_INGRESS, RouterTier

    clock = {"t": 0.0}
    tier = RouterTier(name="ing", clock=lambda: clock["t"])
    tier.register("r1")
    tier.register("r2")
    tier.publish("r1", EV_INGRESS, {"totals": {"prefill": 100.0,
                                               "decode": 50.0}})
    clock["t"] = 1.0
    tier.publish("r1", EV_INGRESS, {"totals": {"prefill": 160.0,
                                               "decode": 80.0}})
    totals = tier.ingress_totals()
    assert totals["prefill"] == 160.0 and totals["decode"] == 80.0
    # r1 restarts under the same --router-id: cumulative totals reset to
    # a LOWER value — fold the full new value (PR-8 counter-restart
    # convention), never a negative delta.
    clock["t"] = 2.0
    tier.publish("r1", EV_INGRESS, {"totals": {"prefill": 30.0,
                                               "decode": 10.0}})
    totals = tier.ingress_totals()
    assert totals["prefill"] == 190.0 and totals["decode"] == 90.0
    with tier._lock:
        assert all(d > 0 for _, _, _, d in tier._ingress_log)
