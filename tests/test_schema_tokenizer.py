"""Schema generation + tokenizer round-trips + text serving op."""

import json

import pytest

from rbg_tpu.api import KINDS
from rbg_tpu.api.schema import all_schemas, schema_for
from rbg_tpu.engine.tokenizer import ByteTokenizer, load_tokenizer


def test_schema_for_every_kind():
    schemas = all_schemas()
    assert set(schemas) == set(KINDS)
    rbg = schemas["RoleBasedGroup"]
    assert rbg["properties"]["spec"]["$ref"].endswith("RoleBasedGroupSpec")
    role = rbg["definitions"]["RoleSpec"]["properties"]
    assert "sliceTopology" in rbg["definitions"]["TpuSpec"]["properties"]
    assert role["pattern"] == {
        "type": "string",
        "enum": ["standalone", "leaderWorker", "customComponents"],
    }
    # Schemas are valid JSON round-trippable
    json.loads(json.dumps(schemas))


def test_schema_validates_example_manifest():
    """Our generated schema should accept the shipped examples (via
    jsonschema if available, else structural spot-checks)."""
    import yaml
    with open("examples/pd-disagg.yaml") as f:
        doc = yaml.safe_load(f)
    try:
        import jsonschema
    except ImportError:
        pytest.skip("jsonschema not installed")
    jsonschema.validate(doc, schema_for(KINDS["RoleBasedGroup"]))


def test_byte_tokenizer_roundtrip():
    tok = ByteTokenizer()
    text = "Hello, TPU! ünïcôde 🚀"
    ids = tok.encode(text)
    assert ids[0] == tok.bos_id
    assert tok.decode(ids) == text
    assert load_tokenizer(None).vocab_size == 259


@pytest.mark.slow
def test_generate_text_op():
    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once

    with SpawnedEngineServer(
            "--model", "tiny", "--page-size", "8", "--num-pages", "64",
            "--max-seq-len", "128", "--use-pallas", "never") as srv:
        # tiny's vocab (256) is smaller than the byte tokenizer's (259):
        # the server must refuse rather than silently clamp token ids.
        r, _, _ = request_once(srv.addr,
                               {"op": "generate_text", "text": "hi",
                                "max_new_tokens": 8}, timeout=120)
        assert "error" in r and "vocab" in r["error"], r


def test_text_generation_in_process():
    """Positive path: byte tokenizer + a model whose vocab fits it."""
    import jax

    from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
    from rbg_tpu.models import get_config, init_params

    cfg = get_config("tiny", vocab_size=512)
    params = init_params(cfg, jax.random.key(0))
    eng = Engine(EngineConfig(model="tiny", page_size=8, num_pages=64,
                              max_seq_len=128, use_pallas="never"),
                 params=params)
    eng.mcfg = cfg  # widen vocab for this test
    tok = ByteTokenizer()
    ids = eng.generate([tok.encode("hi")],
                       SamplingParams(max_new_tokens=8, stop_token=tok.eos_id))[0]
    assert 0 < len(ids) <= 8
    assert isinstance(tok.decode(ids), str)


def test_timeout_cancellation_recycles_pages():
    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.service import EngineService

    svc = EngineService(EngineConfig(model="tiny", page_size=8, num_pages=64,
                                     max_seq_len=128, prefill_chunk=16,
                                     use_pallas="never"))
    try:
        free0 = svc.engine.allocator.free_pages
        with pytest.raises(TimeoutError):
            svc.submit([1, 2, 3], SamplingParams(max_new_tokens=64),
                       timeout=0.0)
        deadline = __import__("time").monotonic() + 10
        while __import__("time").monotonic() < deadline:
            if (svc.engine.allocator.free_pages == free0
                    and not svc.engine.running and not svc.engine.waiting):
                break
            __import__("time").sleep(0.05)
        assert svc.engine.allocator.free_pages == free0, "cancel leaked pages"
    finally:
        svc.stop()  # a leaked loop thread polls for the rest of the suite
    assert not svc.engine.running and not svc.engine.waiting
    svc.stop()


# ---- real HF tokenizer fixture (VERDICT r3 weak #7) ----

FIXTURE = "tests/fixtures/tiny_hf_tokenizer"


def test_hf_tokenizer_fixture_roundtrip():
    """A committed LOCAL HF tokenizer dir (byte-level BPE, vocab 161 — it
    fits the tiny model's 256 vocab) exercises the transformers path that
    only the byte fallback covered before."""
    tok = load_tokenizer(FIXTURE)
    assert type(tok).__name__ == "HFTokenizer"
    assert tok.vocab_size < 256  # usable as the tiny model's tokenizer
    for text in ("the quick brown fox", "hello world 你好",
                 "prefill decode kv cache"):
        ids = tok.encode(text, add_bos=False)
        assert ids and all(isinstance(i, int) for i in ids)
        assert tok.decode(ids) == text
    # BOS handling.
    with_bos = tok.encode("hello", add_bos=True)
    assert with_bos[0] == tok.bos_id


def test_hf_incremental_detok_bpe_boundaries():
    """Incremental detokenization with REAL BPE: multi-token graphemes and
    byte-level merges must stream without ever emitting partial chars, and
    the commit-window suffix check must hold for BPE too."""
    from rbg_tpu.engine.tokenizer import IncrementalDetokenizer
    tok = load_tokenizer(FIXTURE)
    text = "the quick brown fox jumps over the lazy dog héllo 你好 " * 20
    ids = tok.encode(text, add_bos=False)
    assert len(ids) > 3 * IncrementalDetokenizer.WINDOW
    detok = IncrementalDetokenizer(tok)
    parts = [detok.feed(i) for i in ids]
    joined = "".join(parts) + detok.flush()
    assert joined == tok.decode(ids)
    assert all("�" not in p for p in parts)


@pytest.mark.slow
def test_generate_text_with_hf_tokenizer():
    """decode-to-text quality path: the engine server with a real local
    tokenizer dir returns decoded TEXT (the byte-fallback vocab-guard test
    above shows the refusal; this shows the success path)."""
    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once

    with SpawnedEngineServer(
            "--model", "tiny", "--page-size", "8", "--num-pages", "64",
            "--max-seq-len", "128", "--use-pallas", "never",
            "--tokenizer-path", FIXTURE) as srv:
        r, _, _ = request_once(srv.addr,
                               {"op": "generate_text",
                                "text": "the quick brown",
                                "max_new_tokens": 8}, timeout=120)
        assert "error" not in r, r
        assert isinstance(r["text"], str)
        assert len(r["tokens"]) >= 1
