"""Data-plane wire security (VERDICT r4 #6): shared-token auth on the KV
pool / engine / router sockets, TLS on the pool wire via the admin-wire CA
machinery, and the pool-restart-mid-serving e2e (degrade to cold prefill,
warm refill)."""

import json
import socket
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from rbg_tpu.engine.kvpool import KVPoolClient, KVPoolServer, KVPoolStore
from rbg_tpu.engine.protocol import recv_msg, request_once, send_msg

PS = 8


def _pages(n, L=2, KV=2, hd=32, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(L, n, PS, KV, hd).astype(np.float32),
            rng.randn(L, n, PS, KV, hd).astype(np.float32))


def _serve(store=None, **kw):
    srv = KVPoolServer(("127.0.0.1", 0), store or KVPoolStore(PS), **kw)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, f"127.0.0.1:{srv.server_address[1]}"


# ---- token auth on the pool ----


def test_pool_rejects_unauthenticated_writes_and_reads():
    srv, addr = _serve(auth_token="s3cret")
    try:
        toks = list(range(PS))
        k, v = _pages(1)
        # No token: put and match both refused.
        noauth = KVPoolClient(addr, page_size=PS, token="")
        with pytest.raises(RuntimeError, match="unauthorized"):
            noauth.put(toks, k, v)
        with pytest.raises(RuntimeError, match="unauthorized"):
            noauth.match(toks)
        # Wrong token: refused.
        wrong = KVPoolClient(addr, page_size=PS, token="nope")
        with pytest.raises(RuntimeError, match="unauthorized"):
            wrong.put(toks, k, v)
        # Nothing was stored by the refused writes.
        assert srv.store.stats()["pages"] == 0
        # Right token: full round trip.
        ok = KVPoolClient(addr, page_size=PS, token="s3cret")
        assert ok.put(toks, k, v) == 1
        m, km, _ = ok.match(toks)
        assert m == PS
        np.testing.assert_array_equal(km[:, 0], k[:, 0])
        # Health stays open for probes.
        h, _, _ = request_once(addr, {"op": "health"})
        assert h["ok"]
    finally:
        srv.shutdown()
        srv.server_close()


def test_non_ascii_tokens_compare_without_raising():
    """hmac.compare_digest raises TypeError on non-ASCII str operands —
    the shared gate must compare utf-8 bytes (admin.py documents the same
    pitfall), so a unicode token neither crashes the handler nor leaks a
    TypeError to the peer."""
    from rbg_tpu.engine.protocol import token_ok

    assert token_ok("café", "café")
    assert not token_ok("café", "cafe")
    assert not token_ok(None, "café")
    srv, addr = _serve(auth_token="café")
    try:
        ok = KVPoolClient(addr, page_size=PS, token="café")
        assert ok.put(list(range(PS)), *_pages(1)) == 1
        bad = KVPoolClient(addr, page_size=PS, token="cafeéé")
        with pytest.raises(RuntimeError, match="unauthorized"):
            bad.match(list(range(PS)))
    finally:
        srv.shutdown()
        srv.server_close()


def test_pool_open_wire_without_token_flag():
    srv, addr = _serve()
    try:
        c = KVPoolClient(addr, page_size=PS, token="")
        assert c.put(list(range(PS)), *_pages(1)) == 1
    finally:
        srv.shutdown()
        srv.server_close()


# ---- TLS on the pool wire ----


def test_pool_tls_rejects_plaintext_and_serves_pinned_clients(tmp_path):
    pytest.importorskip("cryptography")   # cert mint needs the optional dep
    from rbg_tpu.runtime.tlsutil import ensure_certs, server_context

    ca, cert, key = ensure_certs(str(tmp_path / "certs"))
    srv, addr = _serve(ssl_context=server_context(cert, key))
    try:
        # Plaintext client: no reply (handshake fails server-side).
        plain = KVPoolClient(addr, page_size=PS, timeout=2, token="")
        with pytest.raises((RuntimeError, OSError)):
            plain.put(list(range(PS)), *_pages(1))
        assert srv.store.stats()["pages"] == 0
        # Pinned-CA TLS client: works.
        tls = KVPoolClient(addr, page_size=PS, token="", ca_path=ca)
        assert tls.put(list(range(PS)), *_pages(1)) == 1
        assert tls.match(list(range(PS)))[0] == PS
        # A client pinning a DIFFERENT CA refuses the server.
        other_ca, _, _ = ensure_certs(str(tmp_path / "other"))
        bad = KVPoolClient(addr, page_size=PS, timeout=2, token="",
                           ca_path=other_ca)
        with pytest.raises((RuntimeError, OSError)):
            bad.match(list(range(PS)))
    finally:
        srv.shutdown()
        srv.server_close()


# ---- router token gate ----


def test_router_requires_token_and_forwards_it():
    from rbg_tpu.engine.router import Handler, Registry, RouterServer, RouterState

    seen = []

    class _Backend(__import__("socketserver").ThreadingTCPServer):
        allow_reuse_address = True
        daemon_threads = True

        def __init__(self):
            import socketserver

            class H(socketserver.BaseRequestHandler):
                def handle(self):
                    while True:
                        try:
                            obj, _, _ = recv_msg(self.request)
                        except (ConnectionError, json.JSONDecodeError):
                            return
                        if obj is None:
                            return
                        seen.append(obj)
                        send_msg(self.request, {"tokens": [1]}
                                 if obj.get("op") != "health"
                                 else {"ok": True})

            super().__init__(("127.0.0.1", 0), H)
            self.addr = f"127.0.0.1:{self.server_address[1]}"
            threading.Thread(target=self.serve_forever, daemon=True).start()

    be = _Backend()
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None, {"worker": [be.addr]},
                               token="rt-token")
    threading.Thread(target=router.serve_forever, daemon=True).start()
    try:
        addr = f"127.0.0.1:{router.server_address[1]}"
        # No token → refused at the router, backend never sees it.
        r, _, _ = request_once(addr, {"op": "generate", "prompt": [1]})
        assert r["error"] == "unauthorized"
        assert not [o for o in seen if o.get("op") == "generate"]
        # With the token → forwarded to the backend verbatim.
        r, _, _ = request_once(addr, {"op": "generate", "prompt": [1],
                                      "token": "rt-token"})
        assert r["tokens"] == [1]
        fwd = [o for o in seen if o.get("op") == "generate"]
        assert fwd and fwd[0]["token"] == "rt-token"
        # Health stays open (the prober depends on it).
        h, _, _ = request_once(addr, {"op": "health"})
        assert h["ok"]
    finally:
        router.shutdown()
        router.server_close()
        be.stop() if hasattr(be, "stop") else (be.shutdown(), be.server_close())


# ---- pool restart mid-serving e2e ----


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_ready(port, timeout=240.0, op="health"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            h, _, _ = request_once(f"127.0.0.1:{port}", {"op": op}, timeout=5)
            if h and h.get("ok"):
                return
        except OSError:
            pass
        time.sleep(0.3)
    raise TimeoutError(f"server on {port} never ready")


@pytest.mark.slow
@pytest.mark.e2e
def test_pool_restart_mid_serving_degrades_then_refills():
    """Kill the KV pool under a live token-gated prefill server: requests
    must degrade to cold prefill (pool_errors counts them, no request
    fails); after the pool restarts on the same address the worker
    re-exports (warm refill) and subsequent identical prompts hit."""
    from rbg_tpu.utils import scrubbed_cpu_env

    token = "e2e-token"
    env = scrubbed_cpu_env(extra={"RBG_SERVE_PORT": None,
                                  "RBG_PORT_SERVE": None})
    pool_port, pf_port = _free_port(), _free_port()
    pool_cmd = [sys.executable, "-m", "rbg_tpu.engine.kvpool",
                "--port", str(pool_port), "--page-size", str(PS),
                "--auth-token", token]

    def metrics():
        m, _, _ = request_once(f"127.0.0.1:{pf_port}",
                               {"op": "metrics"}, timeout=30)
        return m["metrics"]

    def prefill(prompt):
        h, _, _ = request_once(
            f"127.0.0.1:{pf_port}",
            {"op": "prefill", "prompt": prompt, "token": token},
            timeout=300)
        assert "error" not in h, h
        return h

    rng = np.random.RandomState(3)
    prompt = rng.randint(0, 256, size=64).tolist()
    pool = subprocess.Popen(pool_cmd, env=env)
    server = subprocess.Popen(
        [sys.executable, "-m", "rbg_tpu.engine.server",
         "--mode", "prefill", "--port", str(pf_port),
         "--model", "tiny", "--page-size", str(PS),
         "--num-pages", "64", "--max-seq-len", "256",
         "--prefill-chunk", "16", "--use-pallas", "never",
         "--kv-pool", f"127.0.0.1:{pool_port}",
         "--auth-token", token], env=env)
    try:
        _wait_ready(pool_port)
        _wait_ready(pf_port)

        h1 = prefill(prompt)
        m = metrics()
        assert m["pool_exports"] == 1 and m["pool_errors"] == 0

        pool.kill()
        pool.wait(timeout=10)
        h2 = prefill(prompt)            # must succeed, cold
        assert h2["first_token"] == h1["first_token"]
        m = metrics()
        assert m["pool_errors"] >= 1
        assert m["pool_exports"] == 1   # nothing exported while down

        pool = subprocess.Popen(pool_cmd, env=env)
        _wait_ready(pool_port)
        prefill(prompt)                 # warm refill: re-export
        m = metrics()
        assert m["pool_exports"] == 2

        before = metrics()["prefill_tokens"]
        prefill(prompt)                 # now a pool hit: minimal compute
        m = metrics()
        assert m["pool_hits"] >= 1
        assert m["prefill_tokens"] - before <= 16  # last partial page only
    finally:
        for p in (pool, server):
            p.terminate()
        for p in (pool, server):
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
