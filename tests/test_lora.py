"""Multi-LoRA serving: per-request adapters batched inside one compiled
program (punica/S-LoRA-style per-row gather — no recompile per adapter).

Reference context: the reference's engines (SGLang/vLLM) ship multi-LoRA
as a core serving feature; here adapters stack [L, n, d, r] (rank-padded)
and ride the layer scan, with slot 0 reserved for base-model rows."""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.models import get_config, init_params

CFG = get_config("tiny")
PARAMS = init_params(CFG, jax.random.key(0))
BASE_KW = dict(page_size=8, num_pages=96, max_seq_len=128,
               use_pallas="never", enable_radix_cache=False)


def _adapter(seed, targets=("wq", "wo", "w_gate"), r=4, scale=0.05):
    rng = np.random.default_rng(seed)
    out = {}
    for tgt in targets:
        _, d_in, d_out = PARAMS["blocks"][tgt].shape
        out[tgt] = (
            rng.normal(size=(CFG.num_layers, d_in, r)).astype(np.float32)
            * scale,
            rng.normal(size=(CFG.num_layers, r, d_out)).astype(np.float32)
            * scale,
        )
    return out


def _merged(adapter, alpha):
    merged = dict(PARAMS)
    mb = dict(merged["blocks"])
    for tgt, (A, B) in adapter.items():
        r = A.shape[2]
        mb[tgt] = mb[tgt] + (alpha / r) * jnp.einsum(
            "ldr,lro->ldo", jnp.asarray(A), jnp.asarray(B))
    merged = dict(merged)
    merged["blocks"] = mb
    return merged


def _engine(params=PARAMS, **kw):
    return Engine(EngineConfig(model="tiny", **{**BASE_KW, **kw}),
                  params=params)


PROMPT = [1, 2, 3, 4]


@pytest.mark.slow
def test_lora_matches_merged_weights():
    ad = _adapter(0)
    ref = _engine(params=_merged(ad, 8.0)).generate(
        [PROMPT], SamplingParams(max_new_tokens=8))[0]
    eng = _engine()
    eng.load_lora("a", ad, alpha=8.0)
    got = eng.generate([PROMPT], SamplingParams(max_new_tokens=8,
                                                lora="a"))[0]
    assert got == ref


@pytest.mark.slow
def test_base_rows_unaffected_by_loaded_adapters():
    eng = _engine()
    eng.load_lora("a", _adapter(0), alpha=8.0)
    got = eng.generate([PROMPT], SamplingParams(max_new_tokens=8))[0]
    assert got == _engine().generate([PROMPT],
                                     SamplingParams(max_new_tokens=8))[0]


@pytest.mark.slow
def test_mixed_adapters_in_one_batch():
    """Three rows — adapter a, adapter b (different rank), base — decode
    TOGETHER and each matches its solo merged-weights reference."""
    ad_a, ad_b = _adapter(0, r=4), _adapter(1, r=8)
    ref_a = _engine(params=_merged(ad_a, 8.0)).generate(
        [PROMPT], SamplingParams(max_new_tokens=8))[0]
    ref_b = _engine(params=_merged(ad_b, 16.0)).generate(
        [PROMPT], SamplingParams(max_new_tokens=8))[0]
    ref_0 = _engine().generate([PROMPT], SamplingParams(max_new_tokens=8))[0]

    eng = _engine()
    eng.load_lora("a", ad_a, alpha=8.0)
    eng.load_lora("b", ad_b, alpha=16.0)
    rows = {
        eng.add_request(PROMPT, SamplingParams(max_new_tokens=8,
                                               lora="a")): ref_a,
        eng.add_request(PROMPT, SamplingParams(max_new_tokens=8,
                                               lora="b")): ref_b,
        eng.add_request(PROMPT, SamplingParams(max_new_tokens=8)): ref_0,
    }
    outs = {rid: [] for rid in rows}
    while eng.has_work():
        for ev in eng.step():
            outs[ev.request_id].append(ev.token)
    for rid, ref in rows.items():
        assert outs[rid] == ref, rid


@pytest.mark.slow
def test_lora_composes_with_multi_step_and_speculative():
    ad = _adapter(2)
    ref = None
    for kw in ({}, {"multi_step": 4}, {"speculative": "ngram"}):
        eng = _engine(**kw)
        eng.load_lora("a", ad, alpha=8.0)
        got = eng.generate([PROMPT * 4],
                           SamplingParams(max_new_tokens=10, lora="a"))[0]
        if ref is None:
            ref = got
        assert got == ref, kw


def test_unknown_adapter_fails_request_only():
    eng = _engine()
    eng.load_lora("a", _adapter(0))
    with pytest.raises(ValueError, match="unknown LoRA"):
        eng.add_request(PROMPT, SamplingParams(max_new_tokens=4, lora="zz"))
    assert len(eng.generate([PROMPT], SamplingParams(max_new_tokens=4))[0]) \
        == 4


def test_adapter_requests_skip_radix_cache():
    eng = Engine(EngineConfig(model="tiny", page_size=8, num_pages=96,
                              max_seq_len=128, use_pallas="never",
                              enable_radix_cache=True), params=PARAMS)
    eng.load_lora("a", _adapter(0), alpha=8.0)
    sp = SamplingParams(max_new_tokens=6, lora="a")
    eng.generate([PROMPT], sp)
    hits0 = eng.metrics["radix_hit_tokens"]
    # Same prompt again with the adapter: no radix reuse (adapter KV ≠
    # base KV), so hit count must not grow from the adapter request.
    eng.generate([PROMPT], sp)
    assert eng.metrics["radix_hit_tokens"] == hits0


def test_load_lora_validation():
    eng = _engine()
    with pytest.raises(ValueError, match="empty"):
        eng.load_lora("x", {})
    with pytest.raises(ValueError, match="bad shapes"):
        eng.load_lora("x", {"wq": (np.zeros((1, 4, 2), np.float32),
                                   np.zeros((1, 3, 8), np.float32))})
    eng.load_lora("x", _adapter(0))
    with pytest.raises(ValueError, match="already loaded"):
        eng.load_lora("x", _adapter(1))
    mla = Engine(EngineConfig(model="tiny-mla", **BASE_KW))
    with pytest.raises(ValueError, match="unsupported target"):
        mla.load_lora("x", {"wk": (np.zeros((2, 128, 4), np.float32),
                                   np.zeros((2, 4, 64), np.float32))})


@pytest.mark.slow
def test_pd_disagg_carries_adapter():
    from rbg_tpu.engine.pd import PDPair
    ad = _adapter(3)
    ref_eng = _engine()
    ref_eng.load_lora("a", ad, alpha=8.0)
    expect = ref_eng.generate([PROMPT],
                              SamplingParams(max_new_tokens=8, lora="a"))[0]
    pair = PDPair(EngineConfig(model="tiny", **BASE_KW), params=PARAMS)
    pair.prefill.engine.load_lora("a", ad, alpha=8.0)
    pair.decode.engine.load_lora("a", ad, alpha=8.0)
    got = pair.generate([PROMPT], SamplingParams(max_new_tokens=8, lora="a"))
    assert got[0] == expect


@pytest.mark.slow
@pytest.mark.e2e
def test_lora_over_wire_with_npz():
    import tempfile

    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once

    ad = _adapter(4)
    with tempfile.NamedTemporaryFile(suffix=".npz", delete=False) as f:
        np.savez(f, alpha=np.float32(8.0),
                 **{f"{t}.A": A for t, (A, _B) in ad.items()},
                 **{f"{t}.B": B for t, (_A, B) in ad.items()})
        npz_path = f.name
    with SpawnedEngineServer(
            "--model", "tiny", "--page-size", "8", "--num-pages", "96",
            "--max-seq-len", "128", "--use-pallas", "never",
            "--lora", f"style={npz_path}") as srv:
        base, _, _ = request_once(srv.addr,
                                  {"op": "generate", "prompt": PROMPT,
                                   "max_new_tokens": 8}, timeout=180)
        styled, _, _ = request_once(srv.addr,
                                    {"op": "generate", "prompt": PROMPT,
                                     "max_new_tokens": 8, "lora": "style"},
                                    timeout=180)
        assert "error" not in styled, styled
        assert styled["tokens"] != base["tokens"]   # the adapter did bite
        bad, _, _ = request_once(srv.addr,
                                 {"op": "generate", "prompt": PROMPT,
                                  "lora": "nope"}, timeout=30)
        assert "error" in bad and "unknown LoRA" in bad["error"]


@pytest.mark.slow
def test_mixed_rank_targets_scale_per_target():
    """alpha/r must use each TARGET's rank — an adapter mixing r=2 and
    r=8 targets must match the per-target merged reference exactly."""
    rng = np.random.default_rng(7)
    ad = {}
    for tgt, r in (("wq", 2), ("w_down", 8)):
        _, d_in, d_out = PARAMS["blocks"][tgt].shape
        ad[tgt] = (rng.normal(size=(CFG.num_layers, d_in, r))
                   .astype(np.float32) * 0.05,
                   rng.normal(size=(CFG.num_layers, r, d_out))
                   .astype(np.float32) * 0.05)
    ref = _engine(params=_merged(ad, 16.0)).generate(
        [PROMPT], SamplingParams(max_new_tokens=8))[0]
    eng = _engine()
    eng.load_lora("m", ad, alpha=16.0)
    got = eng.generate([PROMPT], SamplingParams(max_new_tokens=8,
                                                lora="m"))[0]
    assert got == ref


def test_load_rejects_unsupported_and_mismatched():
    eng = _engine()
    with pytest.raises(ValueError, match="unsupported target"):
        eng.load_lora("x", {"q_proj": (np.zeros((CFG.num_layers, 128, 4),
                                                np.float32),
                                       np.zeros((CFG.num_layers, 4, 512),
                                                np.float32))})
    with pytest.raises(ValueError, match="wrong base model"):
        eng.load_lora("x", {"wq": (np.zeros((CFG.num_layers, 999, 4),
                                            np.float32),
                                   np.zeros((CFG.num_layers, 4, 128),
                                            np.float32))})
    # MoE models: dense-MLP targets never apply — reject at load.
    moe = Engine(EngineConfig(model="tiny-moe", **BASE_KW))
    with pytest.raises(ValueError, match="unsupported target"):
        moe.load_lora("x", {"w_gate": (np.zeros((2, 128, 4), np.float32),
                                       np.zeros((2, 4, 256), np.float32))})
    # a failed load must leave no half-registered slot behind
    with pytest.raises(ValueError):
        eng.load_lora("ghost", {"wq": (np.zeros((CFG.num_layers, 999, 4),
                                                np.float32),
                                       np.zeros((CFG.num_layers, 4, 128),
                                                np.float32))})
    with pytest.raises(ValueError, match="unknown LoRA"):
        eng.add_request(PROMPT, SamplingParams(max_new_tokens=2,
                                               lora="ghost"))


def test_pool_put_skipped_for_adapter_requests():
    """Prefill with an adapter must neither read from nor publish to the
    shared KV pool (pooled KV is base-model KV)."""
    from rbg_tpu.engine.pd import PrefillWorker

    class SpyPool:
        page_size = None

        def __init__(self):
            self.puts = []
            self.gets = []

        def match(self, tokens):
            self.gets.append(list(tokens))
            return 0, None, None

        def put(self, tokens, k, v):
            self.puts.append(list(tokens))

    pool = SpyPool()
    pw = PrefillWorker(EngineConfig(model="tiny", **BASE_KW),
                       params=PARAMS, pool=pool)
    pw.engine.load_lora("a", _adapter(5), alpha=8.0)
    long_prompt = list(range(1, 20))
    pw.prefill(long_prompt, SamplingParams(max_new_tokens=1, lora="a"))
    assert pool.puts == [] and pool.gets == []
    pw.prefill(long_prompt, SamplingParams(max_new_tokens=1))
    assert pool.gets and pool.puts          # base request uses the pool


@pytest.mark.slow
def test_runtime_load_lora_does_not_drop_inflight_tokens():
    """Loading an adapter mid-serve flushes the fused pipeline instead of
    discarding its pending window — in-flight base requests lose nothing
    and produce the identical greedy continuation."""
    ref = _engine(multi_step=4).generate(
        [PROMPT], SamplingParams(max_new_tokens=16))[0]
    eng = _engine(multi_step=4)
    eng.add_request(PROMPT, SamplingParams(max_new_tokens=16))
    out, steps = [], 0
    while eng.has_work():
        for ev in eng.step():
            out.append(ev.token)
        steps += 1
        if steps == 3:
            eng.load_lora("late", _adapter(9), alpha=8.0)
    assert out == ref



@pytest.mark.slow
def test_mla_lora_matches_merged_weights():
    """MLA adapters (wq / w_dkv / wo) must match the merged-weights
    reference exactly — _post_attention and _mla_qkv both thread LoRA."""
    mla_cfg = get_config("tiny-mla")
    mla_params = init_params(mla_cfg, jax.random.key(2))
    rng = np.random.default_rng(11)
    ad = {}
    for tgt in ("wq", "w_dkv", "wo"):
        _, d_in, d_out = mla_params["blocks"][tgt].shape
        ad[tgt] = (rng.normal(size=(mla_cfg.num_layers, d_in, 4))
                   .astype(np.float32) * 0.05,
                   rng.normal(size=(mla_cfg.num_layers, 4, d_out))
                   .astype(np.float32) * 0.05)
    merged = dict(mla_params)
    mb = dict(merged["blocks"])
    for tgt, (A, B) in ad.items():
        mb[tgt] = mb[tgt] + (8.0 / 4) * jnp.einsum(
            "ldr,lro->ldo", jnp.asarray(A), jnp.asarray(B))
    merged["blocks"] = mb
    ref = Engine(EngineConfig(model="tiny-mla", **BASE_KW),
                 params=merged).generate(
        [PROMPT], SamplingParams(max_new_tokens=8))[0]
    eng = Engine(EngineConfig(model="tiny-mla", **BASE_KW),
                 params=mla_params)
    eng.load_lora("m", ad, alpha=8.0)
    got = eng.generate([PROMPT],
                       SamplingParams(max_new_tokens=8, lora="m"))[0]
    base = eng.generate([PROMPT], SamplingParams(max_new_tokens=8))[0]
    assert got == ref
    assert base == Engine(EngineConfig(model="tiny-mla", **BASE_KW),
                          params=mla_params).generate(
        [PROMPT], SamplingParams(max_new_tokens=8))[0]
