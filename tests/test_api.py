"""API surface: manifest parse/round-trip, validation, naming contracts."""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api import parse_manifest, serde, to_yaml
from rbg_tpu.api.group import PatternType, RoleBasedGroup
from rbg_tpu.api.validation import ValidationError, validate_group
from rbg_tpu.testutil import make_group, simple_role, tpu_leaderworker_role

MANIFEST = """
kind: RoleBasedGroup
metadata:
  name: pd-disagg
  namespace: default
spec:
  roles:
  - name: decode
    replicas: 2
    pattern: leaderWorker
    tpu:
      accelerator: v5e
      sliceTopology: 2x4
    template:
      containers:
      - name: engine
        image: sglang-jax:v1
        args: ["--model", "llama3-8b"]
  - name: router
    replicas: 1
    dependencies: [decode]
    template:
      containers:
      - name: router
        image: router:v1
"""


def test_manifest_parse_and_roundtrip():
    import yaml
    doc = yaml.safe_load(MANIFEST)
    g = parse_manifest(doc)
    assert isinstance(g, RoleBasedGroup)
    assert g.spec.roles[0].pattern == PatternType.LEADER_WORKER
    assert g.spec.roles[0].tpu.slice_topology == "2x4"
    assert g.spec.roles[0].tpu.num_hosts == 2
    assert g.spec.roles[1].dependencies == ["decode"]
    # round-trip
    g2 = parse_manifest(yaml.safe_load(to_yaml(g)))
    assert serde.to_dict(g2) == serde.to_dict(g)


def test_unknown_field_rejected():
    import yaml
    doc = yaml.safe_load(MANIFEST)
    doc["spec"]["roles"][0]["bogusField"] = 1
    with pytest.raises(KeyError, match="bogusField"):
        parse_manifest(doc)


def test_validation_errors():
    g = make_group("ok", simple_role("a"), simple_role("a"))
    with pytest.raises(ValidationError, match="duplicated"):
        validate_group(g)

    g = make_group("bad_name!", simple_role("a"))
    with pytest.raises(ValidationError, match="DNS-1123"):
        validate_group(g)

    g = make_group("ok", simple_role("a", dependencies=["ghost"]))
    with pytest.raises(ValidationError, match="unknown role"):
        validate_group(g)

    role = tpu_leaderworker_role("tp", topology="bogus")
    with pytest.raises(ValidationError, match="sliceTopology"):
        validate_group(make_group("ok", role))


def test_naming_contracts():
    # reference Appendix B: workload {group}-{role}; service s-{group}-{role}
    assert C.workload_name("pd", "decode") == "pd-decode"
    assert C.service_name("pd", "decode") == "s-pd-decode"
    long = "x" * 70
    assert len(C.workload_name(long, "r")) <= 63
    assert not C.workload_name("x" * 62, "r").endswith("-")
