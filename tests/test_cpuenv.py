"""Scrubbed-CPU-env helper (rbg_tpu.utils.cpuenv).

Guards the driver-entry contract: a wedged TPU relay in the parent env must
never leak into CPU-only subprocesses (VERDICT r1 item 1).
"""

from rbg_tpu.utils import scrubbed_cpu_env


def test_scrub_removes_relay_and_forces_cpu():
    base = {"PALLAS_AXON_POOL_IPS": "10.0.0.1", "JAX_PLATFORMS": "axon",
            "PATH": "/usr/bin"}
    env = scrubbed_cpu_env(base)
    assert "PALLAS_AXON_POOL_IPS" not in env
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["PATH"] == "/usr/bin"
    assert base["JAX_PLATFORMS"] == "axon"  # input not mutated


def test_host_devices_replaces_existing_flag():
    base = {"XLA_FLAGS": "--xla_force_host_platform_device_count=2 --foo=1"}
    env = scrubbed_cpu_env(base, host_devices=8)
    assert "--xla_force_host_platform_device_count=8" in env["XLA_FLAGS"]
    assert "device_count=2" not in env["XLA_FLAGS"]
    assert "--foo=1" in env["XLA_FLAGS"]


def test_extra_merges_and_none_deletes():
    base = {"KEEP": "1", "DROP": "1"}
    env = scrubbed_cpu_env(base, extra={"DROP": None, "NEW": "v"})
    assert "DROP" not in env
    assert env["NEW"] == "v"
    assert env["KEEP"] == "1"
