"""Embeddings path (/v1/embeddings analog) + HF chat-template rendering.

Reference engines expose embeddings endpoints alongside generation; ours
mean-pools the final-norm hidden states (models/llama.py encode_hidden)
through a dedicated jitted program on the serving engine."""

import numpy as np
import pytest

from rbg_tpu.engine import EngineConfig
from rbg_tpu.engine.service import EngineService
from rbg_tpu.engine.tokenizer import HFTokenizer


def _svc(**kw):
    return EngineService(EngineConfig(model="tiny", page_size=8,
                                      num_pages=64, max_seq_len=128,
                                      use_pallas="never", **kw))


def test_embed_shape_and_determinism():
    svc = _svc()
    try:
        v1 = svc.embed([1, 2, 3, 4, 5])
        v2 = svc.embed([1, 2, 3, 4, 5])
        assert len(v1) == 128               # tiny hidden_size
        assert v1 == v2
        assert any(abs(x) > 0 for x in v1)
        v3 = svc.embed([9, 8, 7])
        assert v3 != v1
    finally:
        svc.stop()


def test_embed_padding_invariant():
    # The same prompt must pool to the same vector regardless of the
    # chunk bucket it gets padded to (mask-correct pooling).
    a, b = _svc(prefill_chunk=16), _svc(prefill_chunk=64)
    try:
        va = np.asarray(a.embed([1, 2, 3, 4, 5]))
        vb = np.asarray(b.embed([1, 2, 3, 4, 5]))
        assert np.max(np.abs(va - vb)) < 1e-4
    finally:
        a.stop()
        b.stop()


def test_embed_rejects_bad_prompts():
    svc = _svc()
    try:
        with pytest.raises(ValueError, match="vocab"):
            svc.embed([99999])
        with pytest.raises(ValueError, match="empty"):
            svc.embed([])
        with pytest.raises(ValueError, match="max_seq_len"):
            svc.embed(list(range(1, 200)))
    finally:
        svc.stop()


@pytest.mark.slow
def test_hf_chat_template_render_and_fallback():
    tok = HFTokenizer("tests/fixtures/tiny_hf_tokenizer")
    msgs = [{"role": "user", "content": "hi"}]
    assert tok.apply_chat_template(msgs) is None   # fixture has none
    tok._tok.chat_template = (
        "{% for m in messages %}<|{{ m.role }}|>{{ m.content }}"
        "{% endfor %}{% if add_generation_prompt %}<|assistant|>{% endif %}")
    assert tok.apply_chat_template(msgs) == "<|user|>hi<|assistant|>"


@pytest.mark.e2e
@pytest.mark.slow
def test_embeddings_over_http():
    import json
    import socket
    import subprocess
    import sys
    import time
    import urllib.request

    from rbg_tpu.engine.protocol import request_once
    from rbg_tpu.utils import scrubbed_cpu_env

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    env = scrubbed_cpu_env()
    ep, hp = free_port(), free_port()
    env["RBG_SERVE_PORT"] = str(ep)
    procs = [subprocess.Popen(
        [sys.executable, "-m", "rbg_tpu.engine.server", "--model", "tiny",
         "--vocab-size", "512", "--page-size", "8", "--num-pages", "64",
         "--max-seq-len", "128", "--use-pallas", "never"],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)]
    try:
        deadline = time.monotonic() + 240
        while True:
            try:
                h, _, _ = request_once(f"127.0.0.1:{ep}", {"op": "health"},
                                       timeout=2)
                if h and h.get("ok"):
                    break
            except OSError:
                pass
            assert time.monotonic() < deadline, "server never healthy"
            time.sleep(0.3)
        # wire op
        r, _, _ = request_once(f"127.0.0.1:{ep}",
                               {"op": "embed", "prompt": [1, 2, 3]},
                               timeout=180)
        assert r["dim"] == 128 and len(r["embedding"]) == 128
        # HTTP edge
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "rbg_tpu.engine.http_frontend",
             "--port", str(hp), "--host", "127.0.0.1",
             "--backend", f"127.0.0.1:{ep}", "--model", "tiny"],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL))
        deadline = time.monotonic() + 60
        while True:
            try:
                with urllib.request.urlopen(
                        f"http://127.0.0.1:{hp}/healthz", timeout=3) as resp:
                    if json.loads(resp.read()).get("ok"):
                        break
            except Exception:
                pass
            assert time.monotonic() < deadline
            time.sleep(0.3)
        req = urllib.request.Request(
            f"http://127.0.0.1:{hp}/v1/embeddings", method="POST",
            data=json.dumps({"input": ["hello", "world"]}).encode(),
            headers={"Content-Type": "application/json"})
        body = json.loads(urllib.request.urlopen(req, timeout=300).read())
        assert body["object"] == "list" and len(body["data"]) == 2
        assert len(body["data"][0]["embedding"]) == 128
        assert body["data"][0]["embedding"] != body["data"][1]["embedding"]
        assert body["usage"]["prompt_tokens"] == len("hello") + len("world")
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait()


def test_embed_batched_matches_singles_and_chunks():
    from rbg_tpu.engine.service import EMBED_MAX_BATCH, embed_prompts
    svc = _svc()
    try:
        prompts = [[i + 1, i + 2, i + 3] for i in range(EMBED_MAX_BATCH + 3)]
        batch = embed_prompts(svc.engine, prompts)   # chunks internally
        assert len(batch) == len(prompts)
        for i in (0, EMBED_MAX_BATCH - 1, EMBED_MAX_BATCH + 2):
            solo = embed_prompts(svc.engine, [prompts[i]])[0]
            assert np.max(np.abs(np.asarray(solo)
                                 - np.asarray(batch[i]))) < 1e-4
    finally:
        svc.stop()


def test_embed_program_cache_keys_are_bucketed():
    """Compile variety stays logarithmic: every compiled embed program is
    keyed by (_chunk_bucket(B), _chunk_bucket(T, chunk)), so nearby raw
    sizes share one program instead of compiling per exact shape."""
    from rbg_tpu.engine.service import _chunk_bucket, embed_prompts
    svc = _svc()
    try:
        eng = svc.engine
        embed_prompts(eng, [[1, 2, 3]])                       # B=1
        embed_prompts(eng, [[1, 2, 3, 4], [5, 6, 7]])         # B=2
        embed_prompts(eng, [[1, 2], [3, 4], [5, 6]])          # B=3 -> 4
        embed_prompts(eng, [[1], [2], [3], [4]])              # B=4 -> 4
        chunk = eng.cfg.prefill_chunk
        keys = set(eng._embed_cache)
        for (B, T) in keys:
            assert B == _chunk_bucket(B), keys
            assert T == _chunk_bucket(T, chunk), keys
        # The B=3 and B=4 calls share one program (both bucket to 4).
        assert sum(1 for (B, _) in keys if B == 4) == 1
        assert not any(B == 3 for (B, _) in keys)
    finally:
        svc.stop()


def test_chunk_bucket_values():
    from rbg_tpu.engine.service import _chunk_bucket
    assert [_chunk_bucket(n) for n in (1, 2, 3, 4, 5, 9)] == [1, 2, 4, 4, 8, 16]
    assert _chunk_bucket(1, 16) == 16      # one chunk minimum
    assert _chunk_bucket(17, 16) == 32     # chunk x pow2, not chunk multiples
    assert _chunk_bucket(40, 16) == 64
