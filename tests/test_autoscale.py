"""SLO-driven coordinated autoscaler (rbg_tpu/autoscale): signal
reading + staleness, policy hysteresis/cooldown, coordinated-ratio
clamping through coordination/scaling.py, two-writer safety on the
ScalingAdapter, drain-aware victim selection, and the plane-level loop.
"""

from __future__ import annotations

import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RoleBasedGroup, RoleStatus, ScalingAdapterHook
from rbg_tpu.api.policy import (
    CoordinatedScaling, ScalingAdapter, ScalingAdapterSpec,
)
from rbg_tpu.autoscale import (
    AutoscaleConfig, AutoscaleController, CoordinatedRoles, RolePolicy,
    RoleScaler, SignalReader, coordinated_targets,
)
from rbg_tpu.autoscale.signals import RoleSignals
from rbg_tpu.obs import names, slo as slo_mod
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.obs.slo import SLOTargets, SLOTracker
from rbg_tpu.obs.timeseries import TimeSeriesSampler
from rbg_tpu.runtime.store import Store
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


def _sig(role="serve", fresh=True, **kw) -> RoleSignals:
    return RoleSignals(role=role, window_s=60.0, fresh=fresh, **kw)


def _pol(**kw) -> RolePolicy:
    base = dict(role="serve", min_replicas=1, max_replicas=8,
                up_stabilization_s=1.0, down_stabilization_s=5.0,
                cooldown_s=3.0)
    base.update(kw)
    return RolePolicy(**base)


# ---- SignalReader ----------------------------------------------------------


def test_signal_reader_rates_and_staleness():
    s = TimeSeriesSampler(interval_s=1.0, retention_s=300.0)
    role = "sigtest-rates"
    s.sample_now(now=0.0)
    REGISTRY.inc(names.SERVING_REQUESTS_FINISHED_TOTAL, 30.0, role=role)
    REGISTRY.inc(names.SERVING_SHED_TOTAL, 10.0, role=role)
    s.sample_now(now=10.0)
    r = SignalReader(sampler=s, window_s=60.0, stale_after_s=5.0)
    sig = r.read(role, now=10.0)
    assert sig.fresh and sig.sample_age_s == 0.0
    assert sig.requests_rps == pytest.approx(3.0)
    assert sig.shed_rps == pytest.approx(1.0)
    # Newest sample is 20 s old at now=30: stale, never "rate is zero".
    sig = r.read(role, now=30.0)
    assert not sig.fresh and sig.sample_age_s == pytest.approx(20.0)


def test_signal_reader_empty_sampler_is_stale():
    s = TimeSeriesSampler(interval_s=1.0, retention_s=300.0)
    r = SignalReader(sampler=s, window_s=60.0, stale_after_s=5.0)
    assert r.read("whatever", now=0.0).fresh is False


def test_signal_reader_attainment_and_extras():
    slo_mod.reset_trackers()
    tr = SLOTracker(SLOTargets(ttft_s=1.0, tpot_s=0.0), component="sigtest")
    role = "sigtest-att"
    for ttft in (0.2, 0.4, 0.6, 2.5):
        tr.judge(ttft, 0.0, role=role)
    s = TimeSeriesSampler(interval_s=1.0, retention_s=300.0)
    s.sample_now()
    r = SignalReader(sampler=s, window_s=60.0, stale_after_s=60.0,
                     extras_fn=lambda _r: {"queue_depth": 7,
                                           "estimated_wait_s": 0.25})
    sig = r.read(role)
    assert sig.judged == 4
    assert sig.ttft_attainment == pytest.approx(0.75)
    assert sig.goodput_attainment == pytest.approx(0.75)
    assert sig.queue_depth == 7.0
    assert sig.estimated_wait_s == 0.25
    slo_mod.reset_trackers()


def test_signal_reader_measured_ratio():
    s = TimeSeriesSampler(interval_s=1.0, retention_s=300.0)
    s.sample_now(now=0.0)
    REGISTRY.inc(names.SERVING_TOKENS_TOTAL, 200.0, role="sigtest-p")
    REGISTRY.inc(names.SERVING_TOKENS_TOTAL, 100.0, role="sigtest-d")
    s.sample_now(now=10.0)
    r = SignalReader(sampler=s, window_s=60.0)
    assert r.measured_ratio("sigtest-p", "sigtest-d",
                            now=10.0) == pytest.approx(2.0)
    assert r.measured_ratio("sigtest-p", "never-published", now=10.0) is None


def test_signal_reader_measured_ratio_zero_side_is_not_measured():
    """One role of a PD pair with ZERO activity in the window must read
    as not-measured (None) — never ratio 0 or ∞. The topology policy
    consumes this value: a fabricated degenerate ratio would flip a
    fleet off an idle window."""
    s = TimeSeriesSampler(interval_s=1.0, retention_s=300.0)
    s.sample_now(now=0.0)
    # Both roles have published the counter, but only one moved in the
    # window (the "zero judged requests on one side" case).
    REGISTRY.inc(names.SERVING_TOKENS_TOTAL, 0.0, role="sigtest-zp")
    REGISTRY.inc(names.SERVING_TOKENS_TOTAL, 300.0, role="sigtest-zd")
    REGISTRY.inc(names.SLO_JUDGED_TOTAL, 0.0, role="sigtest-zp")
    REGISTRY.inc(names.SLO_JUDGED_TOTAL, 30.0, role="sigtest-zd")
    s.sample_now(now=10.0)
    r = SignalReader(sampler=s, window_s=60.0)
    # Numerator idle -> None (was: 0.0, which a follower target or a
    # topology decision would happily actuate on).
    assert r.measured_ratio("sigtest-zp", "sigtest-zd", now=10.0) is None
    # Denominator idle -> None (was: fell through / inf-shaped).
    assert r.measured_ratio("sigtest-zd", "sigtest-zp", now=10.0) is None
    # Both sides active still measures.
    REGISTRY.inc(names.SERVING_TOKENS_TOTAL, 100.0, role="sigtest-zp")
    REGISTRY.inc(names.SERVING_TOKENS_TOTAL, 100.0, role="sigtest-zd")
    s.sample_now(now=20.0)
    assert r.measured_ratio("sigtest-zp", "sigtest-zd", now=20.0) \
        == pytest.approx(100.0 / 400.0)


# ---- RoleScaler hysteresis -------------------------------------------------


def test_scaler_up_on_low_attainment_after_stabilization():
    sc = RoleScaler(_pol())
    bad = _sig(goodput_attainment=0.5, judged=10)
    d = sc.decide(0.0, bad, 2)
    assert d.direction == "hold" and d.suppressed == "stabilizing"
    d = sc.decide(1.2, bad, 2)
    assert d.direction == "up" and d.target == 3
    assert "attainment" in d.reason


def test_scaler_up_on_estimated_wait():
    sc = RoleScaler(_pol(max_estimated_wait_s=0.5, up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(estimated_wait_s=2.0), 1)
    assert d.direction == "up" and "wait" in d.reason


def test_scaler_load_proportional_jump():
    sc = RoleScaler(_pol(target_rps_per_replica=10.0,
                         up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(requests_rps=38.0, shed_rps=11.0), 2)
    # demand = ceil((38 + 11) / 10) = 5 — sheds count as demand.
    assert d.direction == "up" and d.target == 5


def test_scaler_cooldown_suppresses_and_is_counted_as_suppressed():
    sc = RoleScaler(_pol(up_stabilization_s=0.0))
    bad = _sig(goodput_attainment=0.1, judged=10)
    assert sc.decide(0.0, bad, 1).direction == "up"
    d = sc.decide(1.0, bad, 2)
    assert d.direction == "hold" and d.suppressed == "cooldown"
    assert sc.decide(4.0, bad, 2).direction == "up"


def test_scaler_stale_always_holds():
    sc = RoleScaler(_pol(up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(fresh=False, goodput_attainment=0.0, judged=99),
                  1)
    assert d.direction == "hold" and d.suppressed == "stale"


def test_scaler_min_judged_gate():
    sc = RoleScaler(_pol(up_stabilization_s=0.0, min_judged=5))
    # Two unlucky requests must not scale the fleet.
    d = sc.decide(0.0, _sig(goodput_attainment=0.0, judged=2), 2)
    assert d.direction == "hold"


def test_scaler_down_needs_sustained_headroom_and_respects_window_max():
    sc = RoleScaler(_pol(target_rps_per_replica=10.0,
                         up_stabilization_s=0.0, down_stabilization_s=4.0,
                         cooldown_s=0.0))
    # Demand 4 at t=0 seeds the window; then demand falls to 1.
    assert sc.decide(0.0, _sig(requests_rps=35.0), 5).direction == "hold"
    low = _sig(requests_rps=9.0)
    assert sc.decide(1.0, low, 5).suppressed == "stabilizing"
    assert sc.decide(3.0, low, 5).suppressed == "stabilizing"
    d = sc.decide(5.1, low, 5)
    # Window still contains nothing above demand 1 (the t=0 rec aged
    # out), so the target is the stabilized recommendation.
    assert d.direction == "down" and d.target == 1
    # A recent high recommendation floors the drop.
    sc2 = RoleScaler(_pol(target_rps_per_replica=10.0,
                          up_stabilization_s=0.0,
                          down_stabilization_s=4.0, cooldown_s=0.0))
    sc2.decide(0.0, low, 5)
    sc2.decide(2.0, _sig(requests_rps=35.0), 5)   # demand 4 mid-window
    d = sc2.decide(4.5, low, 5)
    assert d.direction == "down" and d.target == 4


def test_scaler_clamps_to_min_and_max():
    sc = RoleScaler(_pol(max_replicas=3, up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(goodput_attainment=0.0, judged=10), 3)
    assert d.direction == "hold" and "max_replicas" in d.reason
    sc = RoleScaler(_pol(min_replicas=2, target_rps_per_replica=10.0,
                         up_stabilization_s=0.0, down_stabilization_s=0.0,
                         cooldown_s=0.0))
    sc.decide(0.0, _sig(requests_rps=1.0), 3)
    d = sc.decide(0.1, _sig(requests_rps=1.0), 2)
    assert d.direction == "hold" and "min_replicas" in d.reason


def test_scaler_shed_pressure_wins_reason_precedence():
    sc = RoleScaler(_pol(up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(shed_rps=2.0, goodput_attainment=0.1,
                            judged=10), 1)
    assert d.direction == "up" and "shedding" in d.reason


def test_scaler_queue_depth_trigger_and_disable():
    sc = RoleScaler(_pol(max_queue_depth=10.0, up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(queue_depth=25.0), 1)
    assert d.direction == "up" and "queue depth" in d.reason
    off = RoleScaler(_pol(max_queue_depth=0.0, up_stabilization_s=0.0))
    assert off.decide(0.0, _sig(queue_depth=25.0), 1).direction == "hold"


def test_scaler_wait_trigger_disabled_by_zero():
    sc = RoleScaler(_pol(max_estimated_wait_s=0.0, up_stabilization_s=0.0))
    assert sc.decide(0.0, _sig(estimated_wait_s=99.0), 1).direction == "hold"


def test_scaler_no_judgments_is_not_pressure():
    sc = RoleScaler(_pol(up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(goodput_attainment=None, judged=0), 2)
    assert d.direction == "hold"


def test_scaler_stale_resets_stabilization_onset():
    sc = RoleScaler(_pol(up_stabilization_s=1.0))
    bad = _sig(goodput_attainment=0.1, judged=10)
    sc.decide(0.0, bad, 1)                       # onset at t=0
    sc.decide(0.5, _sig(fresh=False), 1)         # stale forgets the onset
    d = sc.decide(1.2, bad, 1)
    assert d.direction == "hold" and d.suppressed == "stabilizing"


def test_scaler_shed_only_demand_counts():
    sc = RoleScaler(_pol(target_rps_per_replica=10.0,
                         up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(requests_rps=None, shed_rps=31.0), 1)
    assert d.direction == "up" and d.target == 4


def test_scaler_cooldown_remaining():
    sc = RoleScaler(_pol(up_stabilization_s=0.0, cooldown_s=3.0))
    assert sc.cooldown_remaining(0.0) == 0.0
    sc.decide(0.0, _sig(goodput_attainment=0.0, judged=10), 1)
    assert sc.cooldown_remaining(1.0) == pytest.approx(2.0)
    assert sc.cooldown_remaining(9.0) == 0.0


def test_scaler_no_signals_holds():
    sc = RoleScaler(_pol(up_stabilization_s=0.0))
    d = sc.decide(0.0, _sig(), 3)
    assert d.direction == "hold" and d.reason == "load matches capacity"


def test_scaler_idle_scale_in_without_load_sizing():
    sc = RoleScaler(_pol(up_stabilization_s=0.0, down_stabilization_s=1.0,
                         cooldown_s=0.0))
    idle = _sig(requests_rps=0.0, queue_depth=0.0)
    assert sc.decide(0.0, idle, 3).suppressed == "stabilizing"
    d = sc.decide(1.5, idle, 3)
    assert d.direction == "down" and d.target == 2


def test_scaler_actuation_resets_onsets():
    sc = RoleScaler(_pol(up_stabilization_s=1.0, cooldown_s=0.0))
    bad = _sig(goodput_attainment=0.1, judged=10)
    sc.decide(0.0, bad, 1)
    assert sc.decide(1.5, bad, 1).direction == "up"
    # The next actuation needs a FRESH stabilization window.
    assert sc.decide(1.6, bad, 2).suppressed == "stabilizing"


def test_scaler_revoke_returns_cooldown_and_stabilization():
    sc = RoleScaler(_pol(up_stabilization_s=1.0, cooldown_s=60.0))
    bad = _sig(goodput_attainment=0.1, judged=10)
    sc.decide(0.0, bad, 5)                       # onset at t=0
    d = sc.decide(1.2, bad, 5)
    assert d.direction == "up"
    # The controller could not land it (skew-gated / write lost):
    sc.revoke(d)
    d2 = sc.decide(1.3, bad, 5)
    # Neither cooldown-suppressed nor re-stabilizing — the unlanded
    # actuation gave both back.
    assert d2.direction == "up" and d2.suppressed is None
    # d2 landed (current became 6). A later HOLD decision is not
    # revocable — the landed actuation's cooldown stands once the fresh
    # stabilization window passes.
    sc.revoke(sc.decide(1.4, bad, 6))            # stabilizing hold
    assert sc.decide(2.5, bad, 6).suppressed == "cooldown"


def test_decision_and_signals_as_dict():
    from rbg_tpu.autoscale.policy import Decision
    d = Decision("serve", 2, 3, "up", "why", clamped=True)
    dd = d.as_dict()
    assert dd["target"] == 3 and dd["clamped"] is True
    sd = _sig(requests_rps=1.0).as_dict()
    assert sd["role"] == "serve" and sd["requests_rps"] == 1.0


def test_signal_reader_extras_override_rates():
    s = TimeSeriesSampler(interval_s=1.0, retention_s=300.0)
    s.sample_now(now=0.0)
    s.sample_now(now=10.0)
    r = SignalReader(sampler=s, window_s=60.0, stale_after_s=60.0,
                     extras_fn=lambda _r: {"requests_rps": 42.0})
    assert r.read("no-such-role", now=10.0).requests_rps == 42.0


def test_signal_reader_read_all_and_broken_extras():
    s = TimeSeriesSampler(interval_s=1.0, retention_s=300.0)
    s.sample_now(now=0.0)

    def boom(_r):
        raise RuntimeError("extras hook broke")

    r = SignalReader(sampler=s, window_s=60.0, extras_fn=boom)
    out = r.read_all(["a", "b"], now=0.0)
    assert set(out) == {"a", "b"}    # a broken hook never kills the loop


def test_sampler_last_sample_age():
    s = TimeSeriesSampler(interval_s=1.0, retention_s=300.0)
    assert s.last_sample_age_s(now=5.0) is None
    s.sample_now(now=5.0)
    assert s.last_sample_age_s(now=5.0) == 0.0
    assert s.last_sample_age_s(now=9.0) == pytest.approx(4.0)


def test_spare_pool_available_peek():
    from rbg_tpu.sched.capacity import SparePool
    pool = SparePool(per_topology=2)
    with pool._lock:
        pool._reserved.update({"s-a": "2x4", "s-b": "2x4", "s-c": "4x4"})
    assert pool.available() == 3
    assert pool.available(topology="2x4") == 2
    assert pool.available(topology="8x8") == 0
    # Peek never consumes.
    assert pool.available() == 3


# ---- coordinated-ratio mode ------------------------------------------------


def _pd_group(prefill=("prefill", 2, 2), decode=("decode", 2, 2)):
    """(name, spec_replicas, ready) per role."""
    g = RoleBasedGroup()
    g.metadata.name = "pd"
    g.spec.roles = [simple_role(prefill[0], replicas=prefill[1]),
                    simple_role(decode[0], replicas=decode[1])]
    g.status.roles = [
        RoleStatus(name=prefill[0], replicas=prefill[2],
                   ready_replicas=prefill[2]),
        RoleStatus(name=decode[0], replicas=decode[2],
                   ready_replicas=decode[2]),
    ]
    return g


def test_coordinated_ratio_derives_follower():
    pair = CoordinatedRoles(driver="decode", follower="prefill",
                            default_ratio=0.5)
    g = _pd_group(prefill=("prefill", 2, 4), decode=("decode", 2, 8))
    targets, clamped = coordinated_targets(
        g, pair, 8, RolePolicy("prefill", min_replicas=1, max_replicas=8))
    assert targets["decode"] == 8 and targets["prefill"] == 4
    assert not clamped
    # Measured ratio wins over the default — and the skew clamp bites:
    # prefill's progress (4) lags the raw 8, so it gets the slowest-role
    # progress+1 step, not the whole jump.
    targets, clamped = coordinated_targets(
        g, pair, 8, RolePolicy("prefill", min_replicas=1, max_replicas=8),
        measured_ratio=1.0)
    assert targets["prefill"] == 5 and clamped


def test_coordinated_growth_keeps_skew_and_converges():
    """Autoscaler-driven growth 2→8 through clamp_targets: every round
    honors the maxSkew bound (non-slowest roles never exceed
    floor(t·(min_ratio+skew)) unless they are the slowest+1), and as
    progress lands the clamp converges to the raw targets."""
    pair = CoordinatedRoles(driver="decode", follower="prefill",
                            max_skew_percent=10)
    ready = {"prefill": 2, "decode": 2}
    seen = []
    for _ in range(12):
        g = _pd_group(prefill=("prefill", 2, ready["prefill"]),
                      decode=("decode", 2, ready["decode"]))
        targets, _ = coordinated_targets(
            g, pair, 8, RolePolicy("prefill", min_replicas=1,
                                   max_replicas=8))
        seen.append(dict(targets))
        min_ratio = min(min(1.0, ready[r] / targets[r]) for r in targets)
        for r, t in targets.items():
            cap = int(t * (min_ratio + 0.10))
            assert t <= max(8, 0) and (
                min(1.0, ready[r] / t) <= min_ratio + 1e-9
                or t <= max(cap, ready[r] + 1)), (r, t, ready, min_ratio)
        # Progression gate: the controllers bring the clamped targets up.
        ready = dict(targets)
        if targets == {"decode": 8, "prefill": 8}:
            break
    assert seen[-1] == {"decode": 8, "prefill": 8}
    # Monotone, stepwise growth — never a jump straight to 8.
    assert seen[0]["decode"] < 8 and seen[0]["prefill"] < 8


def test_coordinated_anti_deadlock_under_oscillating_targets():
    """The slowest role always gets progress+1 even when the skew cap
    rounds to less — oscillating raw targets can never wedge the group."""
    pair = CoordinatedRoles(driver="decode", follower="prefill",
                            max_skew_percent=10)
    ready = {"prefill": 1, "decode": 1}
    for i in range(10):
        raw = 6 if i % 2 == 0 else 4
        g = _pd_group(prefill=("prefill", 1, ready["prefill"]),
                      decode=("decode", 1, ready["decode"]))
        targets, _ = coordinated_targets(
            g, pair, raw, RolePolicy("prefill", min_replicas=1,
                                     max_replicas=8))
        # Anti-deadlock is an UPWARD guarantee: whenever some role is
        # below its raw target, the clamp must leave at least one role
        # room to advance past its progress. (A round where progress
        # covers every target is convergence, not deadlock.)
        if all(ready[r] >= raw for r in ready):
            continue
        assert any(targets[r] > ready[r] for r in targets), (
            "deadlock: no role may advance", targets, ready)
        # Advance ONE role only (worst-case staggered progress).
        lag = min(targets, key=lambda r: ready[r] / max(targets[r], 1))
        ready[lag] = min(targets[lag], ready[lag] + 1)


def test_coordinated_scale_down_during_scale_up_converges():
    pair = CoordinatedRoles(driver="decode", follower="prefill",
                            max_skew_percent=10)
    # Mid-flight: raw 8, progress only 4 — then the autoscaler cuts the
    # raw target to 3. The clamp must follow DOWN at once and stay there.
    g = _pd_group(prefill=("prefill", 2, 4), decode=("decode", 2, 4))
    targets, _ = coordinated_targets(
        g, pair, 3, RolePolicy("prefill", min_replicas=1, max_replicas=8))
    assert targets == {"decode": 3, "prefill": 3}
    g = _pd_group(prefill=("prefill", 2, 3), decode=("decode", 2, 3))
    targets, _ = coordinated_targets(
        g, pair, 3, RolePolicy("prefill", min_replicas=1, max_replicas=8))
    assert targets == {"decode": 3, "prefill": 3}


def test_coordinated_respects_operator_policy():
    pair = CoordinatedRoles(driver="decode", follower="prefill",
                            max_skew_percent=90)
    g = _pd_group(prefill=("prefill", 2, 2), decode=("decode", 2, 2))
    operator = CoordinatedScaling(roles=["prefill", "decode"],
                                  max_skew_percent=0)
    loose, _ = coordinated_targets(
        g, pair, 8, RolePolicy("prefill", min_replicas=1, max_replicas=8))
    tight, _ = coordinated_targets(
        g, pair, 8, RolePolicy("prefill", min_replicas=1, max_replicas=8),
        scaling_policy=operator)
    assert tight["decode"] < loose["decode"]


# ---- controller: store-level actuation -------------------------------------


class _FakeReader:
    def __init__(self):
        self.signals = {}
        self.ratio = None

    def read_all(self, roles, now=None):
        return {r: self.signals[r] for r in roles}

    def measured_ratio(self, num, den, now=None):
        return self.ratio


def _store_env(policy=None, replicas=2):
    store = Store()
    g = make_group("g", simple_role("serve", replicas=replicas))
    store.create(g)
    sa = ScalingAdapter()
    sa.metadata.name = "g-serve-scaling-adapter"
    sa.metadata.namespace = "default"
    sa.spec = ScalingAdapterSpec(group_name="g", role_name="serve",
                                 min_replicas=1, max_replicas=16)
    store.create(sa)
    policy = policy or RolePolicy("serve", min_replicas=1, max_replicas=8,
                                  up_stabilization_s=0.0,
                                  down_stabilization_s=0.0, cooldown_s=0.0)
    ctrl = AutoscaleController(store, AutoscaleConfig(
        roles={"serve": policy}, eval_period_s=60.0))
    ctrl.reader = _FakeReader()
    return store, ctrl


def _adapter(store):
    return store.get("ScalingAdapter", "default", "g-serve-scaling-adapter")


def test_controller_writes_target_through_adapter():
    store, ctrl = _store_env()
    ctrl.reader.signals["serve"] = _sig(goodput_attainment=0.2, judged=10)
    ctrl.reconcile(store, ("default", "g"))
    sa = _adapter(store)
    assert sa.spec.replicas == 3
    assert sa.metadata.annotations[C.ANN_AUTOSCALE_LAST_WRITE] == "3"
    assert REGISTRY.gauge(names.AUTOSCALE_TARGET_REPLICAS,
                          role="serve") == 3.0


def test_controller_two_writer_conflict_backs_off_then_adopts():
    store, ctrl = _store_env()
    ctrl.reader.signals["serve"] = _sig(goodput_attainment=0.2, judged=10)
    ctrl.reconcile(store, ("default", "g"))
    assert _adapter(store).spec.replicas == 3
    before = REGISTRY.counter(names.AUTOSCALE_CONFLICTS_TOTAL, role="serve")

    # An external HPA writes the adapter out from under us.
    def hpa(a):
        a.spec.replicas = 7
        return True
    store.mutate("ScalingAdapter", "default", "g-serve-scaling-adapter", hpa)

    ctrl.reader.signals["serve"] = _sig(goodput_attainment=0.2, judged=10)
    ctrl.reconcile(store, ("default", "g"))
    sa = _adapter(store)
    # Backed off: the foreign value survives, the stamp is dropped, the
    # conflict is counted — never silent last-writer-wins.
    assert sa.spec.replicas == 7
    assert C.ANN_AUTOSCALE_LAST_WRITE not in sa.metadata.annotations
    assert REGISTRY.counter(names.AUTOSCALE_CONFLICTS_TOTAL,
                            role="serve") == before + 1
    # Next cycle resumes control FROM the foreign baseline.
    ctrl.reconcile(store, ("default", "g"))
    sa = _adapter(store)
    assert sa.spec.replicas == 8 \
        and sa.metadata.annotations[C.ANN_AUTOSCALE_LAST_WRITE] == "8"


def test_controller_stale_signals_hold_and_count():
    store, ctrl = _store_env()
    before = REGISTRY.counter(names.AUTOSCALE_STALE_HOLDS_TOTAL,
                              role="serve")
    ctrl.reader.signals["serve"] = _sig(fresh=False, goodput_attainment=0.0,
                                        judged=99)
    ctrl.reconcile(store, ("default", "g"))
    assert _adapter(store).spec.replicas is None
    assert REGISTRY.counter(names.AUTOSCALE_STALE_HOLDS_TOTAL,
                            role="serve") == before + 1


def test_controller_disable_enable_per_role():
    store, ctrl = _store_env()
    assert ctrl.set_enabled("serve", False)
    ctrl.reader.signals["serve"] = _sig(goodput_attainment=0.0, judged=99)
    ctrl.reconcile(store, ("default", "g"))
    assert _adapter(store).spec.replicas is None
    row = ctrl.status()["roles"][0]
    assert row["enabled"] is False
    assert not ctrl.set_enabled("nosuch", True)
    ctrl.set_enabled("serve", True)
    ctrl.reconcile(store, ("default", "g"))
    assert _adapter(store).spec.replicas == 3


def test_controller_stamps_victim_costs_on_scale_down():
    store, ctrl = _store_env(replicas=4)
    ctrl.cfg.inflight_streams_fn = {"p-a": 5.0, "p-b": 0.0}.get
    from rbg_tpu.api.instance import RoleInstance
    from rbg_tpu.api.pod import Pod
    for iname, pname in (("i-a", "p-a"), ("i-b", "p-b")):
        inst = RoleInstance()
        inst.metadata.name = iname
        inst.metadata.namespace = "default"
        inst.metadata.labels = {C.LABEL_GROUP_NAME: "g",
                                C.LABEL_ROLE_NAME: "serve"}
        store.create(inst)
        pod = Pod()
        pod.metadata.name = pname
        pod.metadata.namespace = "default"
        pod.metadata.labels = {C.LABEL_GROUP_NAME: "g",
                               C.LABEL_ROLE_NAME: "serve",
                               C.LABEL_INSTANCE_NAME: iname}
        store.create(pod)
    ctrl.reader.signals["serve"] = _sig(requests_rps=0.0, queue_depth=0.0)
    ctrl.reconcile(store, ("default", "g"))
    assert _adapter(store).spec.replicas == 3
    a = store.get("RoleInstance", "default", "i-a")
    b = store.get("RoleInstance", "default", "i-b")
    assert a.metadata.annotations[C.ANN_SCALE_DOWN_COST] == "5"
    assert b.metadata.annotations[C.ANN_SCALE_DOWN_COST] == "0"


def test_controller_coordinated_pair_follows_driver():
    store = Store()
    g = make_group("g", simple_role("decode", replicas=2),
                   simple_role("prefill", replicas=2))
    g.status.roles = [RoleStatus(name="decode", replicas=6,
                                 ready_replicas=6),
                      RoleStatus(name="prefill", replicas=6,
                                 ready_replicas=6)]
    store.create(g)
    for role in ("decode", "prefill"):
        sa = ScalingAdapter()
        sa.metadata.name = f"g-{role}-scaling-adapter"
        sa.metadata.namespace = "default"
        sa.spec = ScalingAdapterSpec(group_name="g", role_name=role,
                                     min_replicas=1, max_replicas=16)
        store.create(sa)
    pol = dict(min_replicas=1, max_replicas=8, up_stabilization_s=0.0,
               down_stabilization_s=0.0, cooldown_s=0.0)
    ctrl = AutoscaleController(store, AutoscaleConfig(
        roles={"decode": RolePolicy("decode", **pol),
               "prefill": RolePolicy("prefill", **pol)},
        coordinated=[CoordinatedRoles(driver="decode", follower="prefill",
                                      default_ratio=0.5)],
        eval_period_s=60.0))
    ctrl.reader = _FakeReader()
    ctrl.reader.ratio = 1.0     # measured prefill:decode token ratio
    ctrl.reader.signals["decode"] = _sig(role="decode",
                                         goodput_attainment=0.2, judged=10,
                                         requests_rps=50.0)
    ctrl.reader.signals["prefill"] = _sig(role="prefill")
    ctrl.reconcile(store, ("default", "g"))
    dec = store.get("ScalingAdapter", "default", "g-decode-scaling-adapter")
    pre = store.get("ScalingAdapter", "default",
                    "g-prefill-scaling-adapter")
    assert dec.spec.replicas == 3
    # follower = driver × measured ratio 1.0 — progress (6) is ahead of
    # both targets, so no skew clamp bites and the follower is written.
    assert pre.spec.replicas == 3
    row = {r["role"]: r for r in ctrl.status()["roles"]}
    assert "coordinated with decode" in \
        row["prefill"]["last_decision"]["reason"]


def test_gate_growth_only_semantics():
    from rbg_tpu.autoscale.policy import gate_growth_only
    # Rise: the clamp may hold the target anywhere in [current, raw]...
    assert gate_growth_only(raw=6, current=5, clamped=2) == 5
    assert gate_growth_only(6, 5, 5) == 5
    assert gate_growth_only(6, 5, 6) == 6
    assert gate_growth_only(6, 2, 4) == 4
    # ...but a genuine scale-down is never deepened by a lagging partner.
    assert gate_growth_only(raw=4, current=5, clamped=1) == 4
    assert gate_growth_only(4, 5, 4) == 4


def test_controller_skew_clamp_never_sheds_capacity():
    """A transiently lagging follower caps the driver's RISE — it must
    never be persisted as a scale-down of the driver's current
    capacity (the clamp is a progression gate, not a decision)."""
    store = Store()
    g = make_group("g", simple_role("decode", replicas=5),
                   simple_role("prefill", replicas=5))
    # Follower progress badly lags: prefill has 1 ready of 5.
    g.status.roles = [RoleStatus(name="decode", replicas=5,
                                 ready_replicas=5),
                      RoleStatus(name="prefill", replicas=5,
                                 ready_replicas=1)]
    store.create(g)
    for role in ("decode", "prefill"):
        sa = ScalingAdapter()
        sa.metadata.name = f"g-{role}-scaling-adapter"
        sa.metadata.namespace = "default"
        sa.spec = ScalingAdapterSpec(group_name="g", role_name=role,
                                     min_replicas=1, max_replicas=16)
        store.create(sa)
    pol = dict(min_replicas=1, max_replicas=8, up_stabilization_s=0.0,
               down_stabilization_s=0.0, cooldown_s=0.0)
    ctrl = AutoscaleController(store, AutoscaleConfig(
        roles={"decode": RolePolicy("decode", **pol),
               "prefill": RolePolicy("prefill", **pol)},
        coordinated=[CoordinatedRoles(driver="decode", follower="prefill",
                                      default_ratio=1.0)],
        eval_period_s=60.0))
    ctrl.reader = _FakeReader()
    ctrl.reader.signals["decode"] = _sig(role="decode",
                                         goodput_attainment=0.2, judged=10)
    ctrl.reader.signals["prefill"] = _sig(role="prefill")
    ctrl.reconcile(store, ("default", "g"))
    dec = store.get("ScalingAdapter", "default", "g-decode-scaling-adapter")
    # The raw up decision (5→6) was gated by prefill's lag, but decode
    # never dropped below its current 5.
    assert dec.spec.replicas is None or dec.spec.replicas >= 5


def test_controller_tight_adapter_bounds_no_write_loop():
    """Adapter bounds tighter than the policy: no 'Autoscaled N -> N'
    event spam, the clamp is counted, and the gauge shows the bounded
    value that can actually land."""
    store, ctrl = _store_env()

    def tighten(a):
        a.spec.max_replicas = 3    # tighter than the policy's max of 8
        return True
    store.mutate("ScalingAdapter", "default", "g-serve-scaling-adapter",
                 tighten)
    ctrl.reader.signals["serve"] = _sig(goodput_attainment=0.0, judged=10)
    ctrl.reconcile(store, ("default", "g"))
    sa = _adapter(store)
    assert sa.spec.replicas == 3     # wrote up to the adapter bound once
    # Occurrence count, not record count: the recorder count-dedups
    # repeated (type, reason, message), so len() alone would stay flat
    # even under real event spam.
    events1 = sum(e.count for e in store.events_for(sa))
    # Steady pressure at the bound: no further writes, no event spam, no
    # foreign-writer misfire — just the clamp counter moving.
    before_clamp = REGISTRY.counter(names.AUTOSCALE_CLAMPED_TOTAL,
                                    role="serve")
    before_conf = REGISTRY.counter(names.AUTOSCALE_CONFLICTS_TOTAL,
                                   role="serve")
    ctrl.reader.signals["serve"] = _sig(goodput_attainment=0.0, judged=10)
    ctrl.reconcile(store, ("default", "g"))
    sa = _adapter(store)
    assert sa.spec.replicas == 3
    assert sum(e.count for e in store.events_for(sa)) == events1
    assert REGISTRY.counter(names.AUTOSCALE_CONFLICTS_TOTAL,
                            role="serve") == before_conf
    assert REGISTRY.counter(names.AUTOSCALE_CLAMPED_TOTAL,
                            role="serve") > before_clamp
    assert REGISTRY.gauge(names.AUTOSCALE_TARGET_REPLICAS,
                          role="serve") == 3.0


def test_controller_clears_victim_costs_after_down_pressure():
    store, ctrl = _store_env(replicas=4)
    ctrl.cfg.inflight_streams_fn = {"p-a": 5.0}.get
    from rbg_tpu.api.instance import RoleInstance
    from rbg_tpu.api.pod import Pod
    inst = RoleInstance()
    inst.metadata.name = "i-a"
    inst.metadata.namespace = "default"
    inst.metadata.labels = {C.LABEL_GROUP_NAME: "g",
                            C.LABEL_ROLE_NAME: "serve"}
    store.create(inst)
    pod = Pod()
    pod.metadata.name = "p-a"
    pod.metadata.namespace = "default"
    pod.metadata.labels = {C.LABEL_GROUP_NAME: "g",
                           C.LABEL_ROLE_NAME: "serve",
                           C.LABEL_INSTANCE_NAME: "i-a"}
    store.create(pod)
    ctrl.reader.signals["serve"] = _sig(requests_rps=0.0, queue_depth=0.0)
    ctrl.reconcile(store, ("default", "g"))
    got = store.get("RoleInstance", "default", "i-a")
    assert got.metadata.annotations[C.ANN_SCALE_DOWN_COST] == "5"
    # Down pressure gone: the stale stream counts must not survive to
    # order some FUTURE (e.g. operator-driven) scale-down.
    ctrl.reader.signals["serve"] = _sig(requests_rps=30.0)
    ctrl.reconcile(store, ("default", "g"))
    got = store.get("RoleInstance", "default", "i-a")
    assert C.ANN_SCALE_DOWN_COST not in got.metadata.annotations


class _FakeSpares:
    def __init__(self):
        self.taken = []

    def take(self, topology=None):
        self.taken.append(topology)
        return f"spare-{len(self.taken)}"

    def replenish(self, store):
        pass

    def available(self, topology=None):
        return 1


def test_controller_grants_spares_to_pending_tpu_instances():
    from rbg_tpu.api.instance import RoleInstance
    from rbg_tpu.testutil import tpu_leaderworker_role

    store = Store()
    role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
    store.create(make_group("g", role))
    sa = ScalingAdapter()
    sa.metadata.name = "g-serve-scaling-adapter"
    sa.metadata.namespace = "default"
    sa.spec = ScalingAdapterSpec(group_name="g", role_name="serve",
                                 min_replicas=1, max_replicas=8)
    store.create(sa)
    inst = RoleInstance()
    inst.metadata.name = "g-serve-1"
    inst.metadata.namespace = "default"
    inst.metadata.labels = {C.LABEL_GROUP_NAME: "g",
                            C.LABEL_ROLE_NAME: "serve"}
    store.create(inst)
    spares = _FakeSpares()
    ctrl = AutoscaleController(store, AutoscaleConfig(
        roles={"serve": RolePolicy("serve", min_replicas=1, max_replicas=8,
                                   up_stabilization_s=0.0, cooldown_s=0.0)},
        eval_period_s=60.0), spares=spares)
    ctrl.reader = _FakeReader()
    before = REGISTRY.counter(names.AUTOSCALE_SPARE_GRANTS_TOTAL,
                              role="serve")
    ctrl.reader.signals["serve"] = _sig(goodput_attainment=0.0, judged=10)
    ctrl.reconcile(store, ("default", "g"))
    # Scale-up wrote the adapter AND granted the pending instance a warm
    # spare of the role's topology.
    assert _adapter(store).spec.replicas == 2
    assert spares.taken == ["2x4"]
    got = store.get("RoleInstance", "default", "g-serve-1")
    assert got.metadata.annotations[C.ANN_SLICE_BINDING] == "spare-1"
    assert REGISTRY.counter(names.AUTOSCALE_SPARE_GRANTS_TOTAL,
                            role="serve") == before + 1
    assert ctrl.status()["spare_slices_available"] == 1
    # Instances created AFTER the write cycle (the real ordering: group
    # controller → instance set → instances) are granted on a LATER
    # evaluation even though no new write happens.
    late = RoleInstance()
    late.metadata.name = "g-serve-2"
    late.metadata.namespace = "default"
    late.metadata.labels = {C.LABEL_GROUP_NAME: "g",
                            C.LABEL_ROLE_NAME: "serve"}
    store.create(late)
    ctrl.reader.signals["serve"] = _sig()       # no pressure, no write
    ctrl.reconcile(store, ("default", "g"))
    got = store.get("RoleInstance", "default", "g-serve-2")
    assert got.metadata.annotations[C.ANN_SLICE_BINDING] == "spare-2"


# ---- victim selection through the stateless engine -------------------------


def test_stateless_scale_down_retires_lowest_cost_first():
    from rbg_tpu.runtime.plane import ControlPlane
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=2)
    with plane:
        role = simple_role("worker", replicas=3)
        role.identity = "random"
        plane.apply(make_group("vc", role))
        plane.wait_group_ready("vc", timeout=20)
        insts = sorted(plane.store.list("RoleInstance", namespace="default"),
                       key=lambda i: i.metadata.name)
        costs = {insts[0].metadata.name: "5", insts[1].metadata.name: "0",
                 insts[2].metadata.name: "2"}
        for iname, cost in costs.items():
            plane.store.mutate(
                "RoleInstance", "default", iname,
                lambda i, c=cost: (
                    i.metadata.annotations.__setitem__(
                        C.ANN_SCALE_DOWN_COST, c) or True))
        g = plane.store.get("RoleBasedGroup", "default", "vc")
        g.spec.roles[0].replicas = 1
        plane.store.update(g)
        survivor = max(costs, key=lambda k: float(costs[k]))
        plane.wait_for(
            lambda: {i.metadata.name for i in plane.store.list(
                "RoleInstance", namespace="default")} == {survivor},
            timeout=20, desc="lowest-cost victims retired first")


# ---- plane wiring + admin op + top render ----------------------------------


def test_admin_autoscale_op_and_top_render():
    from rbg_tpu.runtime.admin import AdminServer
    from rbg_tpu.runtime.plane import ControlPlane
    from rbg_tpu.engine.protocol import request_once

    cfg = AutoscaleConfig(
        roles={"serve": RolePolicy("serve", min_replicas=1, max_replicas=4,
                                   up_stabilization_s=0.0, cooldown_s=0.0)},
        eval_period_s=0.1, stale_after_s=3600.0)
    plane = ControlPlane(backend="fake", autoscale=cfg)
    make_tpu_nodes(plane.store, slices=1, hosts_per_slice=2)
    from rbg_tpu.obs import timeseries
    timeseries.get_sampler().sample_now()
    with plane:
        role = simple_role("serve", replicas=1)
        role.scaling_adapter = ScalingAdapterHook(enabled=True,
                                                  min_replicas=1,
                                                  max_replicas=4)
        plane.apply(make_group("ad", role))
        plane.wait_group_ready("ad", timeout=20)
        admin = AdminServer(plane, port=0).start()
        try:
            addr = f"127.0.0.1:{admin.port}"
            plane.wait_for(
                lambda: plane.autoscale_controller.status()["roles"],
                timeout=10, desc="autoscaler evaluated once")
            resp, _, _ = request_once(addr, {"op": "autoscale"}, timeout=10)
            rows = resp["autoscale"]["roles"]
            assert rows and rows[0]["role"] == "serve"
            assert "last_decision" in rows[0]
            # Per-role kill switch over the wire.
            resp, _, _ = request_once(addr, {"op": "autoscale",
                                             "disable": "serve"},
                                      timeout=10)
            assert resp["autoscale"]["roles"][0]["enabled"] is False \
                or plane.autoscale_controller.enabled("serve") is False
            resp, _, _ = request_once(addr, {"op": "autoscale",
                                             "enable": "serve"}, timeout=10)
            assert plane.autoscale_controller.enabled("serve") is True
            resp, _, _ = request_once(addr, {"op": "autoscale",
                                             "disable": "nosuch"},
                                      timeout=10)
            assert "error" in resp
            # top renders the posture section from the same payload.
            from rbg_tpu.cli import top as top_mod
            src = {"kind": "admin", "addr": addr, "slo": {},
                   "autoscale": plane.autoscale_controller.status()}
            lines = "\n".join(top_mod._render_admin(src, 60))
            assert "TARGET" in lines and "serve" in lines
            assert "LAST DECISION" in lines
        finally:
            admin.stop()


@pytest.mark.slow
def test_autoscale_loop_e2e_drill():
    """The full capacity-follows-load loop (compact trace): the drill's
    own invariants are the assertions."""
    from rbg_tpu.stress.harness import AutoscaleStressConfig, run_autoscale

    # Default trace length: the post-burst tail must be long enough for
    # the down-stabilization window to fire (a 10 s trace is not).
    rep = run_autoscale(AutoscaleStressConfig())
    assert rep["invariants"]["capacity_follows_load"], rep["burst_react_s"]
    assert rep["invariants"]["zero_dropped_streams"], rep["requests"]
    assert rep["invariants"]["slo_accounted"], rep["requests"]
    assert rep["invariants"]["targets_fell_after_burst"], rep["decisions"]
    assert rep["peak_target"] > 1
