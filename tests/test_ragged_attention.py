"""Ragged paged attention: packed mixed prefill/decode rows vs the split
per-row reference (XLA), and the Pallas token-grid kernel vs the XLA ragged
reference (interpret mode on CPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.ops.paged_attention import paged_attention_xla, quantize_kv
from rbg_tpu.ops.pallas.ragged_attention_kernel import (
    ragged_paged_attention_pallas, ragged_paged_attention_pallas_q)
from rbg_tpu.ops.ragged_paged_attention import (ragged_paged_attention_xla,
                                                write_kv_pages_ragged)


def _pool(rng, NP=32, page=8, KV=2, hd=32):
    k = jnp.asarray(rng.randn(NP, page, KV, hd), jnp.float32)
    v = jnp.asarray(rng.randn(NP, page, KV, hd), jnp.float32)
    return k, v


def _pack(rng, q_specs, H=8, hd=32, P=6, NP=32):
    """q_specs: per row (q_len, kv_len); positions are the causal tail
    (the engine's layout: a chunk's tokens end at kv_len - 1, a decode
    token sits at kv_len - 1)."""
    R = len(q_specs)
    perm = rng.permutation(NP - 1)[: R * P] + 1
    table = jnp.asarray(perm.reshape(R, P), jnp.int32)
    kv_lens = jnp.asarray([kv for _, kv in q_specs], jnp.int32)
    T = sum(ql for ql, _ in q_specs)
    q = jnp.asarray(rng.randn(1, T, H, hd), jnp.float32)
    row_ids, q_pos = [], []
    for r, (ql, kv) in enumerate(q_specs):
        row_ids += [r] * ql
        q_pos += list(range(kv - ql, kv))
    return (q, table, jnp.asarray([q_pos], jnp.int32), kv_lens,
            jnp.asarray(row_ids, jnp.int32))


def _split_reference(q, k, v, table, q_pos, kv_lens, row_ids, q_specs):
    """Per-row paged_attention_xla — the legacy split path's math."""
    outs, off = [], 0
    for r, (ql, _) in enumerate(q_specs):
        outs.append(paged_attention_xla(
            q[:, off:off + ql], k, v, table[r:r + 1],
            q_pos[:, off:off + ql], kv_lens[r:r + 1]))
        off += ql
    return jnp.concatenate(outs, axis=1)


@pytest.mark.parametrize("q_specs", [
    [(1, 9), (1, 21), (1, 33)],             # pure decode
    [(8, 8), (8, 24)],                      # pure prefill chunks
    [(6, 14), (1, 30), (1, 5), (4, 4)],     # mixed
])
def test_ragged_xla_matches_split_reference(q_specs):
    rng = np.random.RandomState(0)
    k, v = _pool(rng)
    q, table, q_pos, kv_lens, row_ids = _pack(rng, q_specs)
    got = ragged_paged_attention_xla(q, k, v, table, q_pos, kv_lens, row_ids)
    ref = _split_reference(q, k, v, table, q_pos, kv_lens, row_ids, q_specs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ragged_causal_mask_from_offsets():
    """A mid-chunk token must ignore KV past its own position even though
    the row's kv_len extends further — poisoning the later slots must not
    change its output."""
    rng = np.random.RandomState(1)
    k, v = _pool(rng, NP=16, page=4)
    q_specs = [(4, 12)]                     # chunk tail: positions 8..11
    q, table, q_pos, kv_lens, row_ids = _pack(rng, q_specs, P=4, NP=16)
    base = ragged_paged_attention_xla(q, k, v, table, q_pos, kv_lens,
                                      row_ids)
    # Poison the physical page holding slots 8..11 of this row EXCEPT the
    # slots each token may see; easiest: recompute with kv beyond each
    # token's position zeroed via a second call where kv_lens is clamped
    # to position+1 — per-token outputs must agree with the full call.
    for t in range(4):
        got_t = ragged_paged_attention_xla(
            q[:, t:t + 1], k, v, table, q_pos[:, t:t + 1],
            jnp.asarray([int(q_pos[0, t]) + 1], jnp.int32),
            jnp.asarray([0], jnp.int32))
        np.testing.assert_allclose(np.asarray(base[:, t:t + 1]),
                                   np.asarray(got_t), rtol=1e-5, atol=1e-5)


def test_ragged_pallas_matches_xla_mixed():
    rng = np.random.RandomState(2)
    k, v = _pool(rng)
    q_specs = [(5, 15), (1, 21), (1, 4), (3, 40)]
    q, table, q_pos, kv_lens, row_ids = _pack(rng, q_specs)
    ref = ragged_paged_attention_xla(q, k, v, table, q_pos, kv_lens, row_ids)
    got = ragged_paged_attention_pallas(q, k, v, table, q_pos, kv_lens,
                                        row_ids, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ragged_pallas_edge_lens():
    # kv exactly on a page boundary, len 1, and a full table
    rng = np.random.RandomState(3)
    k, v = _pool(rng, NP=64, page=4)
    q_specs = [(1, 1), (1, 4), (1, 24), (2, 8)]
    q, table, q_pos, kv_lens, row_ids = _pack(rng, q_specs, P=6, NP=64)
    ref = ragged_paged_attention_xla(q, k, v, table, q_pos, kv_lens, row_ids)
    got = ragged_paged_attention_pallas(q, k, v, table, q_pos, kv_lens,
                                        row_ids, interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_ragged_pallas_quantized_matches_xla():
    rng = np.random.RandomState(4)
    kf, vf = _pool(rng, NP=16, page=4)
    k_q, k_s = quantize_kv(kf)
    v_q, v_s = quantize_kv(vf)
    q_specs = [(4, 8), (1, 13)]
    q, table, q_pos, kv_lens, row_ids = _pack(rng, q_specs, P=4, NP=16)
    ref = ragged_paged_attention_xla(q, k_q, v_q, table, q_pos, kv_lens,
                                     row_ids, k_scales=k_s, v_scales=v_s)
    got = ragged_paged_attention_pallas_q(q, k_q, v_q, table, q_pos,
                                          kv_lens, row_ids, k_s, v_s,
                                          interpret=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_pad_tokens_never_clobber_real_rows():
    """Pack-contract: pad tokens (position -1) may reuse a REAL row id —
    bucket padding does — and must not perturb that row's outputs in
    either implementation."""
    rng = np.random.RandomState(6)
    k, v = _pool(rng, NP=16, page=4)
    q_specs = [(3, 9), (1, 13)]
    q, table, q_pos, kv_lens, row_ids = _pack(rng, q_specs, P=4, NP=16)
    base = ragged_paged_attention_xla(q, k, v, table, q_pos, kv_lens,
                                      row_ids)
    # Append 4 pad tokens tagged row 0 at position -1.
    qp = jnp.concatenate([q, jnp.asarray(rng.randn(1, 4, 8, 32),
                                         jnp.float32)], axis=1)
    rp = jnp.concatenate([row_ids, jnp.zeros(4, jnp.int32)])
    pp = jnp.concatenate([q_pos, jnp.full((1, 4), -1, jnp.int32)], axis=1)
    padded = ragged_paged_attention_xla(qp, k, v, table, pp, kv_lens, rp)
    np.testing.assert_allclose(np.asarray(padded[:, :4]),
                               np.asarray(base), rtol=1e-6, atol=1e-6)
    padded_k = ragged_paged_attention_pallas(qp, k, v, table, pp, kv_lens,
                                             rp, interpret=True)
    np.testing.assert_allclose(np.asarray(padded_k[:, :4]),
                               np.asarray(base), rtol=1e-5, atol=1e-5)


def test_write_kv_pages_ragged_matches_dense_scatter():
    """Packed ragged writes land exactly where the row-major split path
    would put them; pad tokens are dropped."""
    rng = np.random.RandomState(5)
    NP, page, KV, hd = 8, 4, 2, 16
    k_pages = jnp.zeros((NP, page, KV, hd), jnp.float32)
    v_pages = jnp.zeros((NP, page, KV, hd), jnp.float32)
    table = jnp.asarray([[1, 2], [3, 4]], jnp.int32)
    # row 0 writes positions 2..5 (crosses page boundary), row 1 pos 7;
    # one pad token at the end.
    positions = jnp.asarray([[2, 3, 4, 5, 7, 0]], jnp.int32)
    row_ids = jnp.asarray([0, 0, 0, 0, 1, 0], jnp.int32)
    tmask = jnp.asarray([[True] * 5 + [False]])
    k_new = jnp.asarray(rng.randn(1, 6, KV, hd), jnp.float32)
    v_new = jnp.asarray(rng.randn(1, 6, KV, hd), jnp.float32)
    kp, vp, _, _ = write_kv_pages_ragged(k_pages, v_pages, k_new, v_new,
                                         table, row_ids, positions, tmask)
    kp = np.asarray(kp)
    np.testing.assert_allclose(kp[1, 2], np.asarray(k_new[0, 0]))  # pos 2
    np.testing.assert_allclose(kp[1, 3], np.asarray(k_new[0, 1]))  # pos 3
    np.testing.assert_allclose(kp[2, 0], np.asarray(k_new[0, 2]))  # pos 4
    np.testing.assert_allclose(kp[2, 1], np.asarray(k_new[0, 3]))  # pos 5
    np.testing.assert_allclose(kp[4, 3], np.asarray(k_new[0, 4]))  # row 1
    assert np.all(kp[0] == 0) and np.all(kp[5:] == 0)  # pad dropped
