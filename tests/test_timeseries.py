"""Windowed time-series sampler (obs/timeseries.py): window math over an
injected clock, counter-reset handling, retention eviction, catalog
validation, and sampler thread lifecycle."""

import threading
import time

import pytest

from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import Registry
from rbg_tpu.obs.timeseries import TimeSeriesSampler


@pytest.fixture()
def reg():
    return Registry(strict=False)


def mk(reg, interval=1.0, retention=60.0):
    return TimeSeriesSampler(registry=reg, interval_s=interval,
                             retention_s=retention)


def test_rate_delta_over_window(reg):
    s = mk(reg)
    s.sample_now(now=0.0)
    reg.inc(names.SERVING_TOKENS_TOTAL, 10, service="a")
    reg.inc(names.SERVING_TOKENS_TOTAL, 5, service="b")
    s.sample_now(now=5.0)
    reg.inc(names.SERVING_TOKENS_TOTAL, 10, service="a")
    s.sample_now(now=10.0)
    # Subset matching sums across label sets; exact labels narrow it.
    assert s.delta(names.SERVING_TOKENS_TOTAL, 10.0) == 25.0
    assert s.rate(names.SERVING_TOKENS_TOTAL, 10.0) == pytest.approx(2.5)
    assert s.rate(names.SERVING_TOKENS_TOTAL, 10.0,
                  service="a") == pytest.approx(2.0)
    assert s.delta(names.SERVING_TOKENS_TOTAL, 10.0, service="b") == 5.0
    # A narrower window anchored at the newest sample sees only the
    # second increment.
    assert s.delta(names.SERVING_TOKENS_TOTAL, 5.0) == 10.0


def test_empty_window_returns_none(reg):
    s = mk(reg)
    assert s.delta(names.SERVING_TOKENS_TOTAL, 10.0) is None
    assert s.rate(names.SERVING_TOKENS_TOTAL, 10.0) is None
    assert s.mean_gauge(names.SERVING_DRAINING, 10.0) is None
    assert s.mean_observed(names.SERVING_QUEUE_DEPTH, 10.0) is None
    # One sample is not a window either.
    s.sample_now(now=0.0)
    assert s.delta(names.SERVING_TOKENS_TOTAL, 10.0) is None
    # A window anchored far past the newest sample holds at most the
    # baseline sample — still no delta.
    s.sample_now(now=1.0)
    assert s.delta(names.SERVING_TOKENS_TOTAL, 10.0, now=500.0) is None


def test_counter_reset_counts_post_restart_value(reg):
    """A plane restart mid-window (counter decreases) reads as reset-to-
    zero-then-grew — the Prometheus convention — never a negative delta."""
    s = mk(reg)
    reg.inc(names.SERVING_TOKENS_TOTAL, 100)
    s.sample_now(now=0.0)
    reg.reset()   # plane restart
    reg.inc(names.SERVING_TOKENS_TOTAL, 7)
    s.sample_now(now=5.0)
    assert s.delta(names.SERVING_TOKENS_TOTAL, 10.0) == 7.0
    # Explicit decrease (same series, lower value) behaves identically.
    reg2 = Registry(strict=False)
    s2 = mk(reg2)
    reg2.inc(names.SERVING_SHED_TOTAL, 50)
    s2.sample_now(now=0.0)
    reg2._counters.clear()
    reg2.inc(names.SERVING_SHED_TOTAL, 3)
    s2.sample_now(now=2.0)
    reg2.inc(names.SERVING_SHED_TOTAL, 4)
    s2.sample_now(now=4.0)
    assert s2.delta(names.SERVING_SHED_TOTAL, 10.0) == 7.0


def test_series_born_mid_window_counts_from_zero(reg):
    s = mk(reg)
    s.sample_now(now=0.0)
    s.sample_now(now=2.0)
    reg.inc(names.SERVING_SHED_TOTAL, 9, service="new")
    s.sample_now(now=4.0)
    assert s.delta(names.SERVING_SHED_TOTAL, 10.0) == 9.0


def test_retention_evicts_oldest(reg):
    s = mk(reg, interval=1.0, retention=5.0)   # ring of 6 samples
    for t in range(10):
        reg.inc(names.SERVING_TOKENS_TOTAL, 1)
        s.sample_now(now=float(t))
    st = s.stats()
    assert st["samples"] == 6
    # The evicted head is gone: a full-history delta only sees the
    # retained span (5 increments across samples t=4..9).
    assert s.delta(names.SERVING_TOKENS_TOTAL, 100.0) == 5.0
    assert st["span_s"] == pytest.approx(5.0)


def test_mean_gauge_and_mean_observed(reg):
    s = mk(reg)
    reg.set_gauge(names.SERVING_DRAINING, 0.0)
    s.sample_now(now=0.0)
    reg.set_gauge(names.SERVING_DRAINING, 1.0)
    s.sample_now(now=2.0)
    s.sample_now(now=4.0)
    assert s.mean_gauge(names.SERVING_DRAINING, 10.0) == pytest.approx(2 / 3)
    # Histogram windowed mean = Δsum/Δcount, so it reflects only the
    # window's observations — not lifetime history.
    reg.observe(names.SERVING_QUEUE_DEPTH, 100.0)
    s.sample_now(now=6.0)
    reg.observe(names.SERVING_QUEUE_DEPTH, 2.0)
    reg.observe(names.SERVING_QUEUE_DEPTH, 4.0)
    s.sample_now(now=8.0)
    assert s.mean_observed(names.SERVING_QUEUE_DEPTH, 2.0,
                           now=8.0) == pytest.approx(3.0)


def test_uncataloged_rbg_name_rejected(reg):
    s = mk(reg)
    s.sample_now(now=0.0)
    s.sample_now(now=1.0)
    with pytest.raises(ValueError, match="not cataloged"):
        s.rate("rbg_totally_made_up_total", 10.0)
    with pytest.raises(ValueError, match="not cataloged"):
        s.mean_gauge("rbg_totally_made_up", 10.0)


def test_sampler_thread_lifecycle(reg):
    """start() is idempotent, the thread is a daemon (the thread-lifecycle
    lint contract), and stop() provably joins it."""
    s = mk(reg, interval=0.01, retention=1.0)
    before = threading.active_count()
    s.start()
    t = s._thread
    assert t.daemon
    assert s.start() is s and s._thread is t   # idempotent, same thread
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline and s.stats()["samples"] < 3:
        time.sleep(0.01)
    assert s.stats()["samples"] >= 3
    s.stop()
    assert s._thread is None
    assert not t.is_alive()
    assert threading.active_count() <= before
    # stop() twice is a no-op; a fresh start() works after stop.
    s.stop()
    s.start()
    s.stop()


def test_bad_config_rejected(reg):
    with pytest.raises(ValueError):
        TimeSeriesSampler(registry=reg, interval_s=0.0)
    with pytest.raises(ValueError):
        TimeSeriesSampler(registry=reg, interval_s=5.0, retention_s=1.0)
    with pytest.raises(ValueError):
        mk(reg).delta(names.SERVING_TOKENS_TOTAL, 0.0)
