"""End-to-end request tracing (obs/trace.py): span/sink unit contracts,
ambient-context propagation through the service and PD layers, router
retry/shed/deadline trace completeness, and the cross-process PD leg via
the engine-server ``traces`` op."""

import json
import socket
import socketserver
import threading
import time

import pytest

from rbg_tpu.obs import names, trace
from rbg_tpu.obs.metrics import REGISTRY


@pytest.fixture()
def traced():
    """Tracing armed at sample=1.0 with a clean sink; restores the prior
    (off) configuration afterwards so unrelated tests stay zero-overhead."""
    old = (trace._CFG.enabled, trace._CFG.sample, trace._CFG.strict)
    trace.configure(enabled=True, sample=1.0, strict=False)
    trace.SINK.reset()
    yield trace
    trace.configure(enabled=old[0], sample=old[1], strict=old[2])
    trace.SINK.reset()


def _wait_recs(n=1, timeout=10.0, complete=True):
    """The root span ends on the SERVER thread after the response is sent,
    so a client that just got its reply may observe the sink a moment
    before finalization — poll instead of asserting instantly."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        recs = trace.SINK.recent(64)
        if len(recs) >= n and (not complete
                               or all(r["complete"] for r in recs)):
            return recs
        time.sleep(0.01)
    return trace.SINK.recent(64)


# ---- span / sink unit contracts ----


def test_disabled_tracing_returns_null_span():
    trace.configure(enabled=False)
    try:
        sp = trace.start_trace(names.SPAN_STRESS_REQUEST)
        assert not sp
        assert sp.child("anything") is sp
        assert sp.wire() is None
        sp.end()  # no-op, no error
        assert trace.current() is trace.NULL_SPAN
    finally:
        trace.configure(enabled=False)


def test_span_tree_records_complete_trace(traced):
    root = trace.start_trace(names.SPAN_STRESS_REQUEST, client=0)
    assert root and root.sampled
    a = root.child(names.SPAN_SERVICE_QUEUE_WAIT)
    a.end(outcome="admitted")
    b = root.child(names.SPAN_SERVICE_SCAN)
    b.end(outcome="ok", tokens=4)
    root.end(outcome="ok")
    recs = trace.SINK.recent(10)
    assert len(recs) == 1
    rec = recs[0]
    assert rec["complete"] and not rec["leaked"]
    assert rec["root"] == names.SPAN_STRESS_REQUEST
    assert rec["duration_ms"] is not None
    by_name = {s["name"]: s for s in rec["spans"]}
    assert set(by_name) == {names.SPAN_STRESS_REQUEST,
                            names.SPAN_SERVICE_QUEUE_WAIT,
                            names.SPAN_SERVICE_SCAN}
    root_id = by_name[names.SPAN_STRESS_REQUEST]["span_id"]
    assert by_name[names.SPAN_SERVICE_QUEUE_WAIT]["parent_id"] == root_id
    assert by_name[names.SPAN_SERVICE_SCAN]["parent_id"] == root_id
    assert by_name[names.SPAN_SERVICE_SCAN]["attrs"]["tokens"] == 4
    # The same record sits in the slowest buffer (only trace so far).
    assert trace.SINK.slowest(5)[0]["trace_id"] == rec["trace_id"]


def test_unended_child_marks_trace_incomplete(traced):
    root = trace.start_trace(names.SPAN_STRESS_REQUEST)
    root.child(names.SPAN_SERVICE_SCAN)      # never ended
    root.end()
    rec = trace.SINK.recent(1)[0]
    assert not rec["complete"]
    assert "INCOMPLETE" in trace.waterfall(rec)[0]


def test_sampling_rate_zero_suppresses(traced):
    trace.configure(sample=0.0)
    assert not trace.start_trace(names.SPAN_STRESS_REQUEST)
    # Explicit force overrides the rate (the stress drills).
    assert trace.start_trace(names.SPAN_STRESS_REQUEST, sample=True)


def test_strict_mode_rejects_uncataloged_names(traced):
    trace.configure(strict=True)
    with pytest.raises(ValueError, match="not cataloged"):
        trace.start_trace("router.reqest")  # lint: allow[span-name-registry] strict-mode negative test needs an uncataloged literal
    # Cataloged names stay fine.
    sp = trace.start_trace(names.SPAN_ROUTER_REQUEST)
    assert sp
    sp.end()


def test_from_wire_joins_in_process_state(traced):
    root = trace.start_trace(names.SPAN_ROUTER_REQUEST)
    hop = trace.from_wire(root.wire(), names.SPAN_ENGINE_OP, op="generate")
    assert hop.trace_id == root.trace_id
    assert hop.parent_id == root.span_id
    hop.end()
    root.end()
    recs = trace.SINK.recent(10)
    assert len(recs) == 1                    # ONE rooted tree, not two
    assert recs[0]["complete"]
    assert len(recs[0]["spans"]) == 2


def test_from_wire_without_context_is_ingress(traced):
    sp = trace.from_wire(None, names.SPAN_ROUTER_REQUEST)
    assert sp and sp.parent_id is None
    sp.end()
    assert trace.SINK.recent(1)[0]["complete"]


def test_from_wire_foreign_trace_is_local_root(traced):
    """A wire context from ANOTHER process: the local span becomes this
    process's root (parent unresolvable locally) and the record is still
    complete — the cross-process half of trace_complete."""
    ctx = {"trace_id": "a" * 32, "parent_id": "b" * 16, "sampled": True}
    sp = trace.from_wire(ctx, names.SPAN_ENGINE_OP, op="prefill")
    assert sp.trace_id == "a" * 32 and sp.parent_id == "b" * 16
    sp.end()
    rec = trace.SINK.recent(1)[0]
    assert rec["trace_id"] == "a" * 32
    assert rec["complete"]


def test_ingress_span_traceparent():
    trace.configure(enabled=True, sample=0.0)  # local decision would drop
    trace.SINK.reset()
    try:
        tid, parent = "c" * 32, "d" * 16
        sp = trace.ingress_span(names.SPAN_HTTP_REQUEST,
                                f"00-{tid}-{parent}-01")
        assert sp and sp.trace_id == tid and sp.parent_id == parent
        sp.end()
        # Explicitly UNsampled header: the client made the head decision.
        assert not trace.ingress_span(names.SPAN_HTTP_REQUEST,
                                      f"00-{tid}-{parent}-00")
        # Garbage falls back to the local decision (rate 0 ⇒ NULL).
        assert not trace.ingress_span(names.SPAN_HTTP_REQUEST, "zz-bad")
        trace.configure(sample=1.0)
        assert trace.ingress_span(names.SPAN_HTTP_REQUEST, "zz-bad")
    finally:
        trace.configure(enabled=False)
        trace.SINK.reset()


def test_per_trace_span_bound_drops_and_counts(traced):
    before = REGISTRY.counter(names.TRACE_SPANS_DROPPED_TOTAL)
    root = trace.start_trace(names.SPAN_STRESS_REQUEST)
    kept, dropped = 0, 0
    for _ in range(trace.MAX_SPANS_PER_TRACE + 10):
        sp = root.child(names.SPAN_SERVICE_SCAN)
        if sp:
            kept += 1
            sp.end()
        else:
            dropped += 1
    root.end()
    assert kept == trace.MAX_SPANS_PER_TRACE - 1  # root takes one slot
    assert dropped == 11
    rec = trace.SINK.recent(1)[0]
    assert rec["dropped_spans"] == 11
    assert rec["complete"]  # a bounding choice, not an orphan
    assert REGISTRY.counter(names.TRACE_SPANS_DROPPED_TOTAL) - before == 11


def test_active_trace_bound_finalizes_oldest_as_leaked(traced):
    spans = [trace.start_trace(names.SPAN_STRESS_REQUEST, i=i)
             for i in range(trace.MAX_ACTIVE_TRACES + 1)]
    leaked = [r for r in trace.SINK.recent(trace.MAX_ACTIVE_TRACES)
              if r["leaked"]]
    assert len(leaked) == 1
    assert leaked[0]["trace_id"] == spans[0].trace_id
    assert trace.SINK.active_count() == trace.MAX_ACTIVE_TRACES
    for sp in spans[1:]:
        sp.end()


def test_ambient_use_span_and_inject(traced):
    root = trace.start_trace(names.SPAN_ROUTER_REQUEST)
    obj = {}
    with trace.use_span(root):
        assert trace.current() is root
        child = trace.child(names.SPAN_ROUTER_ATTEMPT, attempt=0)
        assert child.parent_id == root.span_id
        trace.inject(obj)
        child.end()
    assert trace.current() is trace.NULL_SPAN
    assert obj["trace"] == {"trace_id": root.trace_id,
                            "parent_id": root.span_id, "sampled": True}
    # Unsampled ambient: inject is a no-op.
    clean = {}
    with trace.use_span(trace.NULL_SPAN):
        trace.inject(clean)
    assert "trace" not in clean
    root.end()


def test_two_local_roots_is_incomplete(traced):
    root = trace.start_trace(names.SPAN_STRESS_REQUEST)
    orphan = trace.Span(names.SPAN_SERVICE_SCAN, root.trace_id,
                        "f" * 16, root._state)  # parent id resolves nowhere
    assert root._state.add(orphan)
    orphan.end()
    root.end()
    assert not trace.SINK.recent(1)[0]["complete"]


def test_slowest_buffer_orders_by_root_duration(traced):
    for ms in (0.0, 0.02, 0.01):
        sp = trace.start_trace(names.SPAN_STRESS_REQUEST, pause=ms)
        time.sleep(ms)
        sp.end()
    slowest = trace.SINK.slowest(2)
    assert len(slowest) == 2
    assert slowest[0]["duration_ms"] >= slowest[1]["duration_ms"]
    assert slowest[0]["spans"][0]["attrs"]["pause"] == 0.02


def test_hop_coverage_union_of_overlapping_children(traced):
    root = trace.start_trace(names.SPAN_STRESS_REQUEST)
    a = root.child(names.SPAN_SERVICE_QUEUE_WAIT)
    b = root.child(names.SPAN_SERVICE_SCAN)
    time.sleep(0.03)
    a.end()
    b.end()
    root.end()
    rec = trace.SINK.recent(1)[0]
    cov = trace.hop_coverage(rec)
    # a and b overlap almost entirely: union ≈ root, never ≈ 2× root.
    assert cov is not None and 0.8 <= cov <= 1.05


def test_waterfall_renders_tree_with_attrs(traced):
    root = trace.start_trace(names.SPAN_ROUTER_REQUEST, op="generate")
    att = root.child(names.SPAN_ROUTER_ATTEMPT, backend="b:1", attempt=0)
    att.end(outcome="ok")
    root.end()
    lines = trace.waterfall(trace.SINK.recent(1)[0])
    assert root.trace_id in lines[0]
    assert any(names.SPAN_ROUTER_ATTEMPT in ln and "backend=b:1" in ln
               for ln in lines)
    # Child is indented deeper than the root span line.
    root_ln = next(ln for ln in lines if names.SPAN_ROUTER_REQUEST in ln)
    att_ln = next(ln for ln in lines if names.SPAN_ROUTER_ATTEMPT in ln)
    indent = lambda s: len(s) - len(s.lstrip())  # noqa: E731
    assert indent(att_ln) > indent(root_ln)


# ---- service-layer propagation (real tiny engine) ----


def test_service_queue_scan_spans_and_rejections_complete(traced):
    """One EngineService: an OK request yields root→queue_wait→scan; a
    queue-full shed and an expired-deadline submit still leave COMPLETE
    traces (the rejection closes its span — no orphans)."""
    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.service import (DeadlineExceeded, EngineService,
                                        Overloaded)

    svc = EngineService(EngineConfig(
        model="tiny", page_size=8, num_pages=64, max_batch=2,
        max_seq_len=128, prefill_chunk=16, use_pallas="never",
        decode_buckets=(2,)), max_queue=4)
    try:
        sp = SamplingParams(max_new_tokens=4)
        ok_root = trace.start_trace(names.SPAN_STRESS_REQUEST)
        svc.submit_wait([1, 2, 3], sp, span=ok_root)
        ok_root.end(outcome="ok")

        dl_root = trace.start_trace(names.SPAN_STRESS_REQUEST)
        with pytest.raises(DeadlineExceeded):
            svc.submit_wait([1, 2, 3], sp, deadline=time.monotonic() - 1.0,
                            span=dl_root)
        dl_root.end(outcome="deadline_exceeded")

        svc.max_queue = 0  # every submission is now over the bound
        shed_root = trace.start_trace(names.SPAN_STRESS_REQUEST)
        with pytest.raises(Overloaded):
            svc.submit_wait([1, 2, 3], sp, span=shed_root)
        shed_root.end(outcome="overloaded")
    finally:
        svc.stop()

    recs = {r["trace_id"]: r for r in trace.SINK.recent(10)}
    assert len(recs) == 3
    assert all(r["complete"] for r in recs.values())
    ok = recs[ok_root.trace_id]
    ok_names = {s["name"] for s in ok["spans"]}
    assert {names.SPAN_SERVICE_QUEUE_WAIT,
            names.SPAN_SERVICE_SCAN} <= ok_names
    qspan = next(s for s in ok["spans"]
                 if s["name"] == names.SPAN_SERVICE_QUEUE_WAIT)
    assert qspan["attrs"]["outcome"] == "admitted"
    scan = next(s for s in ok["spans"]
                if s["name"] == names.SPAN_SERVICE_SCAN)
    assert scan["attrs"]["outcome"] == "ok"
    # Hop durations explain the root (the acceptance-criteria check).
    assert trace.hop_coverage(ok) >= 0.9
    # Rejections: queue_wait span carries the rejection outcome.
    dl = recs[dl_root.trace_id]
    dl_q = next(s for s in dl["spans"]
                if s["name"] == names.SPAN_SERVICE_QUEUE_WAIT)
    assert dl_q["attrs"]["outcome"] == "deadline"
    shed = recs[shed_root.trace_id]
    shed_q = next(s for s in shed["spans"]
                  if s["name"] == names.SPAN_SERVICE_QUEUE_WAIT)
    assert shed_q["attrs"]["outcome"] == "overloaded"
    # The request-duration histogram carries the OK request's exemplar.
    ex = REGISTRY.exemplars(names.SERVING_REQUEST_DURATION_SECONDS,
                            service="engineservice")
    assert any(v["trace_id"] == ok_root.trace_id for v in ex.values())


def test_pd_pair_kv_handoff_span_parents_under_ambient(traced):
    """In-process prefill→decode handoff: DecodeWorker.inject's
    pd.kv_handoff span attaches under the ambient request span."""
    from rbg_tpu.engine.config import EngineConfig, SamplingParams
    from rbg_tpu.engine.pd import PDPair

    pair = PDPair(EngineConfig(
        model="tiny", page_size=8, num_pages=64, max_batch=4,
        max_seq_len=128, prefill_chunk=16, use_pallas="never"))
    root = trace.start_trace(names.SPAN_STRESS_REQUEST)
    with trace.use_span(root):
        out = pair.generate([[1, 2, 3, 4]],
                            SamplingParams(max_new_tokens=4))
    root.end()
    assert len(out[0]) >= 1
    rec = trace.SINK.recent(1)[0]
    assert rec["complete"]
    handoff = [s for s in rec["spans"]
               if s["name"] == names.SPAN_PD_KV_HANDOFF]
    assert len(handoff) == 1
    assert handoff[0]["parent_id"] == rec["spans"][0]["span_id"]
    assert handoff[0]["attrs"]["bytes"] > 0
    assert handoff[0]["attrs"]["pages"] >= 1


# ---- router propagation (scripted backends, no JAX) ----


class _ScriptedBackend(socketserver.ThreadingTCPServer):
    """Protocol-speaking backend: fails the first generate (closes the
    socket) when ``fail_first``, sheds as draining when ``draining``,
    otherwise returns a canned token frame."""

    allow_reuse_address = True
    daemon_threads = True

    def __init__(self, fail_first=False, tokens=(5, 6, 7)):
        from rbg_tpu.engine.protocol import (CODE_DRAINING, recv_msg,
                                             send_msg)
        backend = self
        backend.fail_first = fail_first
        backend.draining = False
        backend.requests = 0

        class H(socketserver.BaseRequestHandler):
            def handle(self):
                while True:
                    try:
                        obj, _, _ = recv_msg(self.request)
                    except (ConnectionError, json.JSONDecodeError):
                        return
                    if obj is None:
                        return
                    if obj.get("op") == "health":
                        send_msg(self.request,
                                 {"ok": True, "draining": backend.draining})
                        continue
                    if backend.draining:
                        send_msg(self.request, {
                            "error": "draining", "code": CODE_DRAINING,
                            "done": True, "retry_after_s": 2.0})
                        continue
                    backend.requests += 1
                    if backend.fail_first:
                        backend.fail_first = False
                        return  # cut the socket: transport error upstream
                    send_msg(self.request, {"tokens": list(tokens)})

        super().__init__(("127.0.0.1", 0), H)
        self.addr = f"127.0.0.1:{self.server_address[1]}"
        threading.Thread(target=self.serve_forever, daemon=True).start()


@pytest.fixture()
def scripted_router():
    from rbg_tpu.engine.router import (Handler, Registry, RouterServer,
                                       RouterState)

    flaky = _ScriptedBackend(fail_first=True)
    steady = _ScriptedBackend()
    router = RouterServer(("127.0.0.1", 0), Handler)
    router.state = RouterState(Registry(None), None,
                               {"worker": [flaky.addr, steady.addr]})
    threading.Thread(target=router.serve_forever, daemon=True).start()
    addr = f"127.0.0.1:{router.server_address[1]}"
    yield addr, router, flaky, steady
    router.shutdown()
    router.server_close()
    flaky.shutdown()
    steady.shutdown()


def test_router_retry_makes_sibling_attempt_spans(traced, scripted_router):
    from rbg_tpu.engine.protocol import request_once

    addr, router, flaky, steady = scripted_router
    # Load the steady backend so the flaky one is picked first, fails at
    # the transport, and the SAME request fails over.
    router.state.pool.acquire(steady.addr)
    try:
        resp, _, _ = request_once(addr, {"op": "generate", "prompt": [1],
                                         "timeout_s": 20}, timeout=30)
    finally:
        router.state.pool.release(steady.addr)
    assert resp == {"tokens": [5, 6, 7]}
    rec = _wait_recs()[0]
    assert rec["complete"], rec
    root = rec["spans"][0]
    assert root["name"] == names.SPAN_ROUTER_REQUEST
    attempts = [s for s in rec["spans"]
                if s["name"] == names.SPAN_ROUTER_ATTEMPT]
    assert len(attempts) == 2
    # SIBLINGS under the one request span, distinguishable by attempt #.
    assert all(a["parent_id"] == root["span_id"] for a in attempts)
    by_attempt = {a["attrs"]["attempt"]: a for a in attempts}
    assert by_attempt[0]["attrs"]["outcome"] == "transport_error"
    assert by_attempt[0]["attrs"]["backend"] == flaky.addr
    assert by_attempt[1]["attrs"]["outcome"] == "ok"
    assert by_attempt[1]["attrs"]["backend"] == steady.addr


def test_router_shed_and_deadline_traces_complete(traced, scripted_router):
    from rbg_tpu.engine.protocol import CODE_DRAINING, request_once

    addr, router, flaky, steady = scripted_router
    flaky.draining = True
    steady.draining = True
    resp, _, _ = request_once(addr, {"op": "generate", "prompt": [1],
                                     "timeout_s": 5}, timeout=30)
    assert resp.get("code") == CODE_DRAINING
    rec = _wait_recs()[0]
    assert rec["complete"], rec           # shed request is NOT an orphan
    assert rec["spans"][0]["attrs"]["outcome"] == CODE_DRAINING
    attempts = [s for s in rec["spans"]
                if s["name"] == names.SPAN_ROUTER_ATTEMPT]
    assert attempts and all(a["attrs"]["outcome"] == CODE_DRAINING
                            for a in attempts)

    # Deadline spent before dispatch: structured reply, complete trace.
    flaky.draining = steady.draining = False
    trace.SINK.reset()
    resp, _, _ = request_once(addr, {"op": "generate", "prompt": [1],
                                     "timeout_s": 0.000001}, timeout=30)
    assert resp.get("code")               # deadline_exceeded frame
    rec = _wait_recs()[0]
    assert rec["complete"], rec


def test_router_wire_context_continues_upstream_trace(traced,
                                                      scripted_router):
    """A client-supplied wire context (the http_frontend leg): the
    router's request span parents under it and joins the SAME trace."""
    from rbg_tpu.engine.protocol import request_once

    addr = scripted_router[0]
    edge = trace.start_trace(names.SPAN_HTTP_REQUEST, path="/v1/completions")
    resp, _, _ = request_once(addr, {"op": "generate", "prompt": [1],
                                     "timeout_s": 20,
                                     "trace": edge.wire()}, timeout=30)
    assert resp == {"tokens": [5, 6, 7]}
    # The router's spans end on ITS thread after the reply: wait for them
    # before finalizing, or the record would snapshot an unfinished hop.
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        with edge._state.lock:
            spans = list(edge._state.spans)
        if len(spans) >= 3 and all(s.duration_s is not None
                                   for s in spans if s is not edge):
            break
        time.sleep(0.01)
    edge.end(status=200)
    rec = trace.SINK.recent(1)[0]
    assert rec["complete"]
    by_name = {s["name"]: s for s in rec["spans"]}
    assert by_name[names.SPAN_ROUTER_REQUEST]["parent_id"] == \
        by_name[names.SPAN_HTTP_REQUEST]["span_id"]


# ---- cross-process PD e2e: spans pulled via the engine `traces` op ----


@pytest.mark.slow
@pytest.mark.e2e
def test_pd_trace_propagation_across_processes(traced):
    """Full PD path over real prefill+decode subprocesses with RBG_TRACE
    armed: the router's per-attempt wire context reaches each server,
    whose engine.op span parents under the attempt that dispatched it —
    queue-wait/prefill spans on the prefill pod, scan/kv-handoff spans on
    the decode pod — all sharing ONE trace id, every local tree complete."""
    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once
    from rbg_tpu.engine.router import (Handler, Registry, RouterServer,
                                       RouterState)

    args = ["--model", "tiny", "--page-size", "8", "--num-pages", "128",
            "--max-seq-len", "256", "--prefill-chunk", "16",
            "--use-pallas", "never"]
    tr_env = {"RBG_TRACE": "1", "RBG_TRACE_SAMPLE": "1"}
    with SpawnedEngineServer("--mode", "prefill", *args,
                             env_extra=tr_env) as pf, \
            SpawnedEngineServer("--mode", "decode", *args,
                                env_extra=tr_env) as dc:
        router = RouterServer(("127.0.0.1", 0), Handler)
        router.state = RouterState(Registry(None), None,
                                   {"prefill": [pf.addr],
                                    "decode": [dc.addr]})
        threading.Thread(target=router.serve_forever, daemon=True).start()
        addr = f"127.0.0.1:{router.server_address[1]}"
        try:
            resp, _, _ = request_once(
                addr, {"op": "generate", "prompt": [1, 2, 3, 4],
                       "max_new_tokens": 6, "timeout_s": 120}, timeout=300)
            assert "error" not in resp, resp
            assert resp["tokens"]

            # Local (router-process) trace: root + one attempt per leg.
            rec = _wait_recs()[0]
            assert rec["complete"], rec
            tid = rec["trace_id"]
            attempts = {s["attrs"]["role"]: s for s in rec["spans"]
                        if s["name"] == names.SPAN_ROUTER_ATTEMPT}
            assert set(attempts) == {"prefill", "decode"}
            assert attempts["decode"]["attrs"]["kv_bytes"] > 0

            def pull(addr):
                deadline = time.monotonic() + 15.0
                while True:
                    t, _, _ = request_once(addr, {"op": "traces"},
                                           timeout=30)
                    recs = [r for r in t["recent"]
                            if r["trace_id"] == tid and r["complete"]]
                    if recs or time.monotonic() > deadline:
                        return t, recs
                    time.sleep(0.05)

            # Prefill pod: engine.op rooted at the prefill ATTEMPT span.
            pt, precs = pull(pf.addr)
            assert len(precs) == 1 and precs[0]["complete"], pt
            pnames = {s["name"] for s in precs[0]["spans"]}
            assert {names.SPAN_ENGINE_OP, names.SPAN_SERVICE_QUEUE_WAIT,
                    names.SPAN_PD_PREFILL} <= pnames
            proot = precs[0]["spans"][0]
            assert proot["name"] == names.SPAN_ENGINE_OP
            assert proot["parent_id"] == \
                attempts["prefill"]["span_id"]

            # Decode pod: engine.op rooted at the decode ATTEMPT span,
            # with the KV-handoff and scan spans under it.
            dt, drecs = pull(dc.addr)
            assert len(drecs) == 1 and drecs[0]["complete"], dt
            dnames = {s["name"] for s in drecs[0]["spans"]}
            assert {names.SPAN_ENGINE_OP, names.SPAN_PD_KV_HANDOFF,
                    names.SPAN_SERVICE_SCAN} <= dnames
            droot = drecs[0]["spans"][0]
            assert droot["parent_id"] == attempts["decode"]["span_id"]
            assert dt["waterfall"], "engine traces op waterfall empty"
        finally:
            router.shutdown()
            router.server_close()
