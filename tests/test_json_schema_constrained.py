"""JSON-Schema-constrained decoding (`json_schema` sampling param —
xgrammar / vLLM guided_json / OpenAI response_format=json_schema analog):
the schema-compiled NFA accepts exactly schema-valid compact JSON, engine
outputs parse AND validate, and the constraint composes with the rest of
the stack."""

import json

import jax
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.grammar import JsonSchemaGrammar
from rbg_tpu.engine.tokenizer import ByteTokenizer
from rbg_tpu.models import get_config, init_params


def _full(g, s: str) -> bool:
    st = g.initial()
    for b in s.encode():
        st = g.advance(st, b)
        if st is None:
            return False
    return g.is_complete(st)


SCHEMA = {"type": "object", "properties": {
    "name": {"type": "string", "minLength": 1},
    "age": {"type": "integer"},
    "tags": {"type": "array", "items": {"enum": ["a", "b"]}, "maxItems": 3},
    "score": {"type": "number"},
    "ok": {"type": "boolean"},
    "ref": {"type": "null"},
    "kind": {"const": "user"},
}}


def test_schema_grammar_accepts_only_valid_documents():
    g = JsonSchemaGrammar(SCHEMA)
    good = ('{"name":"bob","age":42,"tags":["a","b"],"score":-1.5e3,'
            '"ok":true,"ref":null,"kind":"user"}')
    assert _full(g, good)
    json.loads(good)  # and it IS JSON
    for bad in (
        '{"name":"bob"}',                      # missing properties
        good.replace('"user"', '"x"'),         # const violated
        good.replace("42", "4.2"),             # integer violated
        good.replace('"name"', '"nope"', 1),   # wrong key
        good.replace('["a","b"]', '["c"]'),    # enum violated
        good.replace('"bob"', '""'),           # minLength violated
        " " + good,                            # whitespace (compact only)
    ):
        assert not _full(g, bad), bad


def test_schema_grammar_strings_are_utf8_safe():
    g = JsonSchemaGrammar({"type": "string"})
    for s in ('"héllo"', '"a\\nb"', '"\\u00e9"', '"日本"', '"🙂"', '""'):
        assert _full(g, s), s
        json.loads(s)
    # Raw UTF-8 fragment bytes are never legal string content.
    st = g.initial()
    st = g.advance(st, ord('"'))
    assert g.advance(st, 0x80) is None
    # Unpaired surrogate lead byte patterns (0xED 0xA0..) are rejected.
    st2 = g.advance(st, 0xED)
    assert st2 is None or g.advance(st2, 0xA0) is None


def test_schema_grammar_features():
    g = JsonSchemaGrammar({"type": "string", "pattern": r"[A-Z]{2}\d{4}"})
    assert _full(g, '"AB1234"') and not _full(g, '"ab1234"')
    g = JsonSchemaGrammar({"anyOf": [{"type": "integer"}, {"type": "null"}]})
    assert _full(g, "7") and _full(g, "null") and not _full(g, '"7"')
    g = JsonSchemaGrammar({"type": "array", "items": {"type": "integer"},
                           "minItems": 2, "maxItems": 3})
    assert _full(g, "[1,2]") and _full(g, "[1,2,3]")
    assert not _full(g, "[1]") and not _full(g, "[1,2,3,4]")
    g = JsonSchemaGrammar({"type": "array", "items": {"type": "null"}})
    assert _full(g, "[]") and _full(g, "[null,null]")
    g = JsonSchemaGrammar({"type": ["integer", "null"]})
    assert _full(g, "3") and _full(g, "null")
    g = JsonSchemaGrammar({"type": "object", "properties": {}})
    assert _full(g, "{}")


def test_schema_grammar_rejects_unsupported():
    for bad in ({"$ref": "#/x"}, {"allOf": []}, {"type": "frob"},
                {"enum": []}, {"enum": [{"x": 1}]},
                {"type": "array", "minItems": 3, "maxItems": 1},
                # Array without "items" means any-value members — silently
                # emitting array-of-strings would diverge from the
                # client's schema; must raise at admission.
                {"type": "array"},
                {"type": "array", "minItems": 1},
                "not a dict"):
        with pytest.raises(ValueError):
            JsonSchemaGrammar(bad)


# ---- engine integration ----


@pytest.fixture(scope="module")
def eng():
    cfg = get_config("tiny", vocab_size=512)
    params = init_params(cfg, jax.random.key(0))
    e = Engine(EngineConfig(model="tiny", vocab_size=512, page_size=8,
                            num_pages=128, max_seq_len=256,
                            use_pallas="never"), params=params)
    e.mcfg = cfg
    e.enable_json_grammar(ByteTokenizer())
    return e


def test_schema_outputs_validate(eng):
    tok = ByteTokenizer()
    schema = {"type": "object", "properties": {
        "id": {"type": "integer"},
        "state": {"enum": ["on", "off"]},
    }}
    completed = 0
    for seed in range(3):
        rid = eng.add_request(
            tok.encode("emit:"),
            SamplingParams(max_new_tokens=48, temperature=0.9, seed=seed,
                           json_schema=schema, stop_token=tok.eos_id))
        out = []
        while eng.has_work():
            for ev in eng.step():
                if ev.request_id == rid:
                    out.append(ev.token)
        assert out                              # something was produced
        done = out[-1] == tok.eos_id
        text = tok.decode([t for t in out if t != tok.eos_id])
        if done:
            completed += 1
            doc = json.loads(text)              # parses...
            assert set(doc) == {"id", "state"}  # ...and validates
            assert isinstance(doc["id"], int)
            assert doc["state"] in ("on", "off")
        else:
            # Budget-truncated (the schema admits unbounded integer
            # digits): the emitted prefix must still be schema-legal,
            # and the truncation must be the BUDGET's doing.
            assert len(out) == 48, text
            g = JsonSchemaGrammar(schema)
            s = g.initial()
            for b in text.encode():
                s = g.advance(s, b)
                assert s is not None, text
    # EOS must actually be reachable: with these fixed seeds the engine
    # is deterministic and most runs complete — zero completions would
    # mean EOS never became legal (e.g. a broken is_complete/table row).
    assert completed >= 1


def test_schema_admission_and_cache(eng):
    with pytest.raises(ValueError, match="unsupported keyword"):
        eng.add_request([1, 2], SamplingParams(max_new_tokens=4,
                                               json_schema={"$ref": "#/x"}))
    with pytest.raises(ValueError, match="mutually exclusive"):
        SamplingParams(json_mode=True, json_schema={"type": "null"}).validate()
    s = {"type": "object", "properties": {"a": {"type": "null"}}}
    g1 = eng._grammar_for(SamplingParams(json_schema=s))
    g2 = eng._grammar_for(SamplingParams(json_schema=dict(s)))
    assert g1 is g2                         # keyed by canonical dump
    assert g1.trie is eng.grammar.trie      # shared tokenizer trie


def test_schema_malformed_shapes_are_value_errors():
    """TypeError must never escape compilation — the server maps only
    ValueError to a clean 'bad sampling params' reply."""
    for bad in ({"anyOf": []}, {"oneOf": "x"},
                {"type": "object", "properties": {"a": True}},
                {"type": "array", "items": None}):
        with pytest.raises(ValueError):
            JsonSchemaGrammar(bad)


def test_empty_schema_means_any_json(eng):
    g = eng._grammar_for(SamplingParams(json_schema={}))
    assert g is eng.grammar          # the generic JSON grammar
    # And from_wire must not drop it.
    sp = SamplingParams.from_wire({"json_schema": {}})
    assert sp.json_schema == {}


def test_empty_regex_means_empty_output_only():
    from rbg_tpu.engine.grammar import RegexGrammar
    g = RegexGrammar("")
    assert g.is_complete(g.initial())
    assert g.advance(g.initial(), ord("a")) is None
    sp = SamplingParams.from_wire({"regex": ""})
    assert sp.regex == ""


def test_semantic_regex_escapes_raise():
    from rbg_tpu.engine.grammar import RegexGrammar
    for pat in (r"\bfoo\b", r"\Astart", r"end\Z", r"\Bx"):
        with pytest.raises(ValueError, match="escape"):
            RegexGrammar(pat)
    # Escaped punctuation stays literal.
    g = RegexGrammar(r"\.\+")
    st = g.initial()
    for b in b".+":
        st = g.advance(st, b)
    assert g.is_complete(st)


def test_schema_cache_respects_property_order(eng):
    a_first = {"type": "object", "properties": {"a": {"type": "null"},
                                                "b": {"type": "null"}}}
    b_first = {"type": "object", "properties": {"b": {"type": "null"},
                                                "a": {"type": "null"}}}
    ga = eng._grammar_for(SamplingParams(json_schema=a_first))
    gb = eng._grammar_for(SamplingParams(json_schema=b_first))
    assert ga is not gb              # order-sensitive emission
    assert _full(ga.grammar, '{"a":null,"b":null}')
    assert _full(gb.grammar, '{"b":null,"a":null}')
    assert not _full(ga.grammar, '{"b":null,"a":null}')


def test_http_edge_maps_schema_fields():
    from rbg_tpu.engine.http_frontend import Handler

    f = Handler._sampling_fields
    s = {"type": "object", "properties": {"a": {"type": "null"}}}
    assert f({"guided_json": s})["json_schema"] == s
    assert f({"response_format": {"type": "json_schema",
                                  "json_schema": {"schema": s}}}
             )["json_schema"] == s
    assert f({"guided_regex": r"\d+"})["regex"] == r"\d+"
    with pytest.raises(ValueError):
        f({"response_format": {"type": "json_schema"}})
    with pytest.raises(ValueError):
        f({"guided_json": "not a schema"})


@pytest.mark.e2e
@pytest.mark.slow
def test_json_schema_over_wire():
    """guided_json through a real server subprocess: generate_text with a
    json_schema constraint returns text that parses AND validates."""
    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once

    with SpawnedEngineServer(
            "--model", "tiny", "--vocab-size", "512", "--page-size", "8",
            "--num-pages", "128", "--max-seq-len", "256",
            "--use-pallas", "never") as srv:
        schema = {"type": "object", "properties": {
            "n": {"type": "integer"},
            "tag": {"enum": ["x", "y"]}}}
        r, _, _ = request_once(
            srv.addr,
            {"op": "generate_text", "text": "emit:", "max_new_tokens": 40,
             "temperature": 0.8, "seed": 2, "json_schema": schema},
            timeout=180)
        assert "error" not in r, r
        assert r["text"]                       # something was produced
        g = JsonSchemaGrammar(schema)
        s = g.initial()
        for b in r["text"].encode():
            s = g.advance(s, b)
            assert s is not None, r["text"]     # schema-legal prefix
        if g.is_complete(s):
            doc = json.loads(r["text"])
            assert set(doc) == {"n", "tag"} and doc["tag"] in ("x", "y")
            assert isinstance(doc["n"], int)
        else:
            # Incomplete is acceptable ONLY as budget truncation (byte
            # tokenizer: one token per byte, EOS filtered server-side) —
            # an engine that stalls or never legalizes EOS fails here.
            assert len(r["text"].encode()) == 40, r["text"]
        # A malformed schema is a clean per-request error, not a dead wire.
        r2, _, _ = request_once(
            srv.addr,
            {"op": "generate_text", "text": "emit:", "max_new_tokens": 8,
             "json_schema": {"$ref": "#/x"}}, timeout=60)
        assert "error" in r2 and "unsupported keyword" in r2["error"]
