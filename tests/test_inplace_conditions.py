"""In-place update condition machinery (VERDICT r1 item 4).

Reference analog: ``pkg/inplace/pod/inplaceupdate/inplace_update.go:223-316``
(InPlaceUpdateReady readiness gate + grace period) and
``pkg/reconciler/roleinstance/sync/instance_scale.go:542-607`` (container
restart baselines — an expected post-update restart must not trip the
restart policy). On TPU the stakes are a full-slice gang recreate.
"""

import json
import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.meta import get_condition
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import (
    make_group, make_tpu_nodes, simple_role, tpu_leaderworker_role,
)


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        yield p


def _pods(plane, role):
    return sorted(
        (p for p in plane.store.list("Pod", namespace="default")
         if p.metadata.labels.get(C.LABEL_ROLE_NAME) == role),
        key=lambda p: p.metadata.name)


def _wait_all_images(plane, role, image, count):
    def check():
        pods = _pods(plane, role)
        if len(pods) != count:
            return None
        for p in pods:
            if any(c.image != image for c in p.template.containers):
                return None
            if not p.running_ready:
                return None
        return pods

    return plane.wait_for(check, timeout=15,
                          desc=f"{role} pods on {image} and ready")


def test_leaderworker_inplace_update_keeps_gang(plane):
    """Image-only rollout on a leaderWorker (slice) instance: processes
    restart, pod identity survives, no gang recreate, restart policy calm."""
    plane.apply(make_group("tp", tpu_leaderworker_role("serve", replicas=1,
                                                       topology="2x4")))
    plane.wait_group_ready("tp")
    before = _pods(plane, "serve")
    assert len(before) == 2
    uids = {p.metadata.name: p.metadata.uid for p in before}

    g2 = make_group("tp", tpu_leaderworker_role("serve", replicas=1,
                                                topology="2x4",
                                                image="engine:v2"))
    plane.apply(g2)
    after = _wait_all_images(plane, "serve", "engine:v2", 2)

    # Same pods (no recreate): uid-stable across the whole gang.
    assert {p.metadata.name: p.metadata.uid for p in after} == uids
    for p in after:
        # exactly the one expected restart per swapped container
        assert p.status.container_restarts.get("engine") == 1
        cond = get_condition(p.status.conditions, C.COND_INPLACE_UPDATE_READY)
        assert cond is not None and cond.status == "True"
        assert p.status.observed_revision == p.metadata.labels[C.LABEL_REVISION_NAME]
    # Restart policy never fired: no instance-level restart accounting.
    insts = plane.store.list("RoleInstance", namespace="default")
    assert all(i.status.restart_count == 0 for i in insts)
    plane.wait_group_ready("tp")


def test_inplace_state_records_baselines(plane):
    plane.apply(make_group("bl", simple_role("srv", replicas=1)))
    plane.wait_group_ready("bl")
    plane.apply(make_group("bl", simple_role("srv", replicas=1,
                                             image="engine:v2")))
    (pod,) = _wait_all_images(plane, "srv", "engine:v2", 1)
    state = json.loads(pod.metadata.annotations[C.ANN_INPLACE_UPDATE_STATE])
    assert state["images"] == {"engine": "engine:v2"}
    assert state["restarted"] == ["engine"]
    assert state["baselines"] == {"engine": 0}


def test_grace_period_drains_before_patch(plane):
    """With graceSeconds, the pod turns not-ready while STILL on the old
    image (drain window), and only then gets patched."""
    role = simple_role("api", replicas=1)
    role.rolling_update.grace_seconds = 0.6
    plane.apply(make_group("gr", role))
    plane.wait_group_ready("gr")

    role2 = simple_role("api", replicas=1, image="engine:v2")
    role2.rolling_update.grace_seconds = 0.6
    plane.apply(make_group("gr", role2))

    def draining():
        (p,) = _pods(plane, "api") or [None]
        if p is None:
            return None
        cond = get_condition(p.status.conditions, C.COND_INPLACE_UPDATE_READY)
        if cond is None or cond.status != "False":
            return None
        # gate held AND image not yet swapped = drain window
        return p if p.template.containers[0].image == "engine:v1" else None

    drained = plane.wait_for(draining, timeout=5, desc="drain window")
    assert not drained.running_ready  # readiness gate held
    _wait_all_images(plane, "api", "engine:v2", 1)
    plane.wait_group_ready("gr")


def test_second_update_mid_grace_converges_to_newest(plane):
    """A newer revision landing while a pod drains restages it: the pod
    must end on the NEWEST image with truthful restart accounting — no
    wedge, no recreate (review finding r2: staging must be level-triggered)."""
    role = simple_role("api", replicas=1)
    role.rolling_update.grace_seconds = 0.8
    plane.apply(make_group("g2", role))
    plane.wait_group_ready("g2")
    (pod0,) = _pods(plane, "api")
    uid = pod0.metadata.uid

    for img in ("engine:v2", "engine:v3"):
        r = simple_role("api", replicas=1, image=img)
        r.rolling_update.grace_seconds = 0.8
        plane.apply(make_group("g2", r))
        if img == "engine:v2":
            # wait until the drain gate is held, then land v3 mid-grace
            def draining():
                (p,) = _pods(plane, "api") or [None]
                if p is None:
                    return None
                cond = get_condition(p.status.conditions,
                                     C.COND_INPLACE_UPDATE_READY)
                return p if (cond and cond.status == "False") else None
            plane.wait_for(draining, timeout=5, desc="drain gate")

    (pod,) = _wait_all_images(plane, "api", "engine:v3", 1)
    assert pod.metadata.uid == uid  # still the same pod
    # The availability budget may serialize v2 before v3 (two restarts) or
    # restage directly to v3 (one); either way every restart was expected —
    # the restart policy must never have fired.
    assert pod.status.container_restarts.get("engine") in (1, 2)
    insts = plane.store.list("RoleInstance", namespace="default")
    assert all(i.status.restart_count == 0 for i in insts)
    plane.wait_group_ready("g2")


def test_rollback_mid_grace_converges_in_place(plane):
    """Rolling back to the original spec while the pod drains must converge
    in place (same pod, final image = original) without a gang recreate or a
    restart-policy trip — whether the gate releases patch-free or the budget
    serializes v2 first."""
    role = simple_role("rb", replicas=1)
    role.rolling_update.grace_seconds = 1.0
    plane.apply(make_group("g3", role))
    plane.wait_group_ready("g3")
    (pod0,) = _pods(plane, "rb")
    uid = pod0.metadata.uid

    r2 = simple_role("rb", replicas=1, image="engine:v2")
    r2.rolling_update.grace_seconds = 1.0
    plane.apply(make_group("g3", r2))

    def draining():
        (p,) = _pods(plane, "rb") or [None]
        if p is None:
            return None
        cond = get_condition(p.status.conditions, C.COND_INPLACE_UPDATE_READY)
        return p if (cond and cond.status == "False"
                     and p.template.containers[0].image == "engine:v1") else None

    plane.wait_for(draining, timeout=5, desc="drain gate on old image")

    r1 = simple_role("rb", replicas=1)
    r1.rolling_update.grace_seconds = 1.0
    plane.apply(make_group("g3", r1))

    (pod,) = _wait_all_images(plane, "rb", "engine:v1", 1)
    assert pod.metadata.uid == uid
    # Possibly v2 was applied first (budget serialization) and then rolled
    # back — but never a recreate, and never a restart-policy trip.
    insts = plane.store.list("RoleInstance", namespace="default")
    assert all(i.status.restart_count == 0 for i in insts)
    plane.wait_group_ready("g3")


def test_unexpected_restart_still_trips_policy(plane):
    """Baselines only excuse the expected restart: a crash AFTER the
    in-place update completes triggers the normal gang recreate."""
    plane.apply(make_group("rp", simple_role("w", replicas=1)))
    plane.wait_group_ready("rp")
    plane.apply(make_group("rp", simple_role("w", replicas=1,
                                             image="engine:v2")))
    (pod,) = _wait_all_images(plane, "w", "engine:v2", 1)
    assert pod.status.container_restarts.get("engine") == 1
    old_uid = pod.metadata.uid

    # Crash beyond the baseline allowance.
    plane.kubelet.restart_container("default", pod.metadata.name, "engine")

    def recreated():
        pods = _pods(plane, "w")
        if len(pods) != 1 or pods[0].metadata.uid == old_uid:
            return None
        return pods[0] if pods[0].running_ready else None

    plane.wait_for(recreated, timeout=15, desc="gang recreate after crash")
    insts = plane.store.list("RoleInstance", namespace="default")
    assert all(i.status.restart_count == 1 for i in insts)


def test_restart_policy_only_change_applies_in_place(plane):
    """A restart-policy-only change is template-identical (image diff {}),
    so it rides the in-place path — and must actually LAND on the instance
    (review finding: the label flipped while the policy was dropped)."""
    plane.apply(make_group("rpo", simple_role("w", replicas=1)))
    plane.wait_group_ready("rpo")
    (pod0,) = _pods(plane, "w")

    role = simple_role("w", replicas=1)
    role.restart_policy.base_delay_seconds = 7.5
    plane.apply(make_group("rpo", role))

    def policy_applied():
        insts = plane.store.list("RoleInstance", namespace="default")
        if len(insts) != 1:
            return None
        i = insts[0]
        return i if i.spec.restart_policy.base_delay_seconds == 7.5 else None

    inst = plane.wait_for(policy_applied, timeout=10,
                          desc="restart policy landed on instance")
    # No recreate, no container restart (nothing image-shaped changed).
    (pod,) = _pods(plane, "w")
    assert pod.metadata.uid == pod0.metadata.uid
    assert not pod.status.container_restarts
    plane.wait_group_ready("rpo")


def test_structural_change_recreates(plane):
    """A non-image change (env var) must take the recreate path."""
    plane.apply(make_group("st", simple_role("w", replicas=1)))
    plane.wait_group_ready("st")
    before = _pods(plane, "w")
    role = simple_role("w", replicas=1, image="engine:v2")
    from rbg_tpu.api.pod import EnvVar
    role.template.containers[0].env.append(EnvVar(name="X", value="1"))
    plane.apply(make_group("st", role))

    def recreated():
        pods = _pods(plane, "w")
        if len(pods) != 1:
            return None
        p = pods[0]
        if p.metadata.uid == before[0].metadata.uid:
            return None
        return p if (p.running_ready
                     and p.template.containers[0].image == "engine:v2") else None

    plane.wait_for(recreated, timeout=15, desc="recreate on structural change")
