"""Control-plane observability: workqueue telemetry, the structured
event recorder, reconcile tracing, the admin operator surface, and the
fleet drill (PR: control-plane observability)."""

import threading
import time

import pytest

from rbg_tpu.obs import names, trace
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.controller import (Controller, InstrumentedWorkQueue,
                                        Result, Watch, own_keys)
from rbg_tpu.runtime.queue import WorkQueue
from rbg_tpu.runtime.store import EVENT_WARNING, EventRecord, Store


def _pod(name, ns="default"):
    from rbg_tpu.api.pod import Pod
    p = Pod()
    p.metadata.name = name
    p.metadata.namespace = ns
    return p


# ---- workqueue telemetry ----------------------------------------------------


def test_workqueue_depth_and_age_metrics():
    q = InstrumentedWorkQueue(WorkQueue(), controller="tq1")
    adds0 = REGISTRY.counter(names.WORKQUEUE_ADDS_TOTAL, controller="tq1")
    age0 = (REGISTRY.hist_stats(names.WORKQUEUE_QUEUE_AGE_SECONDS,
                                controller="tq1") or {}).get("count", 0)
    for i in range(5):
        q.add(("ns", f"k{i}"))
    assert REGISTRY.gauge(names.WORKQUEUE_DEPTH, controller="tq1") == 5.0
    assert REGISTRY.counter(names.WORKQUEUE_ADDS_TOTAL,
                            controller="tq1") - adds0 == 5.0
    got = []
    while True:
        item = q.get(timeout=0.1)
        if item is None:
            break
        got.append(item)
        q.done(item)
    assert len(got) == 5
    assert REGISTRY.gauge(names.WORKQUEUE_DEPTH, controller="tq1") == 0.0
    st = REGISTRY.hist_stats(names.WORKQUEUE_QUEUE_AGE_SECONDS,
                             controller="tq1")
    assert st["count"] - age0 == 5


def test_workqueue_age_excludes_intentional_delay():
    q = InstrumentedWorkQueue(WorkQueue(), controller="tq2")
    q.add_after(("ns", "delayed"), 0.15)
    item = q.get(timeout=2.0)
    assert item == ("ns", "delayed")
    st = REGISTRY.hist_stats(names.WORKQUEUE_QUEUE_AGE_SECONDS,
                             controller="tq2")
    # Age measures waiting BEYOND the intentional add_after delay — a
    # backoff requeue must not read as queue backlog.
    assert st["max"] < 0.1
    q.done(item)


def test_workqueue_immediate_add_overrides_future_stamp():
    q = InstrumentedWorkQueue(WorkQueue(), controller="tq4")
    q.add_after(("ns", "k"), 5.0)    # parked in backoff: future stamp
    q.add(("ns", "k"))               # watch event: ready NOW
    time.sleep(0.1)
    item = q.get(timeout=1.0)
    assert item == ("ns", "k")
    st = REGISTRY.hist_stats(names.WORKQUEUE_QUEUE_AGE_SECONDS,
                             controller="tq4")
    # Age is measured from the immediate add — the lingering future
    # backoff stamp must not clamp a real backlog wait to 0.
    assert st["max"] >= 0.05
    q.done(item)


def test_workqueue_concurrent_add_get_all_accounted():
    q = InstrumentedWorkQueue(WorkQueue(), controller="tq3")
    n_producers, per = 4, 50
    seen = set()
    seen_lock = threading.Lock()
    stop = threading.Event()

    def produce(pid):
        for i in range(per):
            q.add((pid, i))

    def consume():
        while not stop.is_set():
            item = q.get(timeout=0.05)
            if item is None:
                continue
            with seen_lock:
                seen.add(item)
            q.done(item)

    consumers = [threading.Thread(target=consume, daemon=True)
                 for _ in range(3)]
    for t in consumers:
        t.start()
    producers = [threading.Thread(target=produce, args=(p,), daemon=True)
                 for p in range(n_producers)]
    for t in producers:
        t.start()
    for t in producers:
        t.join(timeout=10.0)
    deadline = time.monotonic() + 10.0
    while time.monotonic() < deadline:
        with seen_lock:
            if len(seen) == n_producers * per:
                break
        time.sleep(0.01)
    stop.set()
    q.shutdown()
    for t in consumers:
        t.join(timeout=2.0)
    assert len(seen) == n_producers * per
    assert REGISTRY.gauge(names.WORKQUEUE_DEPTH, controller="tq3") == 0.0


# ---- structured event recorder ----------------------------------------------


def test_event_dedup_and_tuple_compat():
    s = Store()
    p = _pod("a")
    for _ in range(4):
        s.record_event(p, "FailedScheduling", "no feasible node",
                       type_=EVENT_WARNING)
    evs = s.events_for(p)
    assert len(evs) == 1
    rec = evs[0]
    assert isinstance(rec, EventRecord)
    assert rec.count == 4 and rec.type == "Warning"
    assert rec.first_time <= rec.time
    # Legacy flat-log compatibility: 4-tuple unpack + positional index.
    t, ref, reason, msg = rec
    assert ref == "Pod/default/a" and reason == "FailedScheduling"
    assert rec[3] == "no feasible node"
    # A different message is a new record, not a dedup bump.
    s.record_event(p, "FailedScheduling", "still no feasible node",
                   type_=EVENT_WARNING)
    assert len(s.events_for(p)) == 2


def test_event_per_object_bound_protects_other_objects():
    s = Store()
    chatty, quiet = _pod("chatty"), _pod("quiet")
    s.record_event(quiet, "Scheduled", "bound to node-1")
    for i in range(Store.MAX_EVENTS_PER_OBJECT * 3):
        s.record_event(chatty, f"Reason{i}", "spam", type_=EVENT_WARNING)
    assert len(s.events_for(chatty)) <= Store.MAX_EVENTS_PER_OBJECT
    # The old flat log trimmed globally — a chatty controller evicted
    # every other object's history. The per-ref index must not.
    assert len(s.events_for(quiet)) == 1


def test_event_filters_and_accounting():
    s = Store()
    rec0 = {t: REGISTRY.counter(names.EVENTS_RECORDED_TOTAL, type=t)
            for t in ("Normal", "Warning")}
    evict0 = REGISTRY.counter(names.EVENTS_EVICTED_TOTAL)
    a, b = _pod("a"), _pod("b")
    s.record_event(a, "Scheduled", "bound")
    s.record_event(a, "Restarting", "gang restart", type_=EVENT_WARNING)
    time.sleep(0.02)
    cut = time.time()
    s.record_event(b, "Scheduled", "bound")
    assert [e.reason for e in s.events_for(reason="Restarting")] == [
        "Restarting"]
    assert len(s.events_for(event_type="Warning")) == 1
    assert [e[1] for e in s.events_for(since=cut)] == ["Pod/default/b"]
    assert len(s.events_for(limit=2)) == 2
    # Accounting: recorded == live counts + evicted (the fleet drill's
    # events_accounted invariant).
    recorded = sum(
        REGISTRY.counter(names.EVENTS_RECORDED_TOTAL, type=t) - rec0[t]
        for t in ("Normal", "Warning"))
    evicted = REGISTRY.counter(names.EVENTS_EVICTED_TOTAL) - evict0
    assert recorded == s.event_stats()["total_count"] + evicted == 3


# ---- reconcile tracing ------------------------------------------------------


class _NodeEcho(Controller):
    """Minimal controller: reconciles Node objects, counts passes."""

    name = "nodeecho"
    workers = 1
    resync_period = 0  # no resync loop — the watch is the only trigger

    def __init__(self, store):
        super().__init__(store)
        self.seen = []

    def watches(self):
        return [Watch("Node", own_keys)]

    def reconcile(self, store, key):
        self.seen.append(key)
        return None


@pytest.fixture()
def traced():
    was, sample = trace.enabled(), trace._CFG.sample
    trace.configure(enabled=True, sample=1.0)
    trace.SINK.reset()
    yield
    trace.configure(enabled=was, sample=sample)
    trace.SINK.reset()


def test_reconcile_span_parents_off_watch_event(traced):
    from rbg_tpu.api.pod import Node
    store = Store()
    ctrl = _NodeEcho(store)
    ctrl.start()
    try:
        n = Node()
        n.metadata.name = "n1"
        n.metadata.namespace = "default"
        store.create(n)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and not ctrl.seen:
            time.sleep(0.01)
        assert ctrl.seen
        time.sleep(0.1)
    finally:
        ctrl.stop()
    pairs = [r for r in trace.SINK.recent(64)
             if r["root"] == names.SPAN_CTRL_EVENT
             and any(s["name"] == names.SPAN_CTRL_RECONCILE
                     for s in r["spans"])]
    assert pairs, "no event->reconcile trace finalized"
    rec = pairs[0]
    assert rec["complete"]
    ev = rec["spans"][0]
    rc = next(s for s in rec["spans"]
              if s["name"] == names.SPAN_CTRL_RECONCILE)
    assert rc["parent_id"] == ev["span_id"]
    assert ev["attrs"]["controller"] == "nodeecho"
    assert ev["attrs"]["kind"] == "Node"
    assert rc["attrs"]["outcome"] == "success"
    # Exemplar satellite: the duration histogram links to the trace.
    ex = REGISTRY.exemplars(names.RECONCILE_DURATION_SECONDS,
                            controller="nodeecho")
    assert any(e["trace_id"] == rec["trace_id"] for e in ex.values())


def test_unsampled_event_stamps_null_decision(traced):
    """An event that LOSES the head-sampling roll still records its
    decision: the worker must find the (falsy) sentinel and neither
    re-roll sampling nor mislabel the reconcile as resync-origin."""
    from rbg_tpu.api.pod import Node
    from rbg_tpu.runtime.store import Event
    trace.configure(sample=0.0)
    ctrl = _NodeEcho(Store())   # not started — no workers to race
    n = Node()
    n.metadata.name = "n1"
    n.metadata.namespace = "default"
    ctrl._stamp_event_span(Event(Event.ADDED, n), ("default", "n1"))
    sp = ctrl._take_event_span(("default", "n1"))
    assert sp is not None and not sp
    assert ctrl._take_event_span(("default", "n1")) is None


def test_reconcile_error_requeue_accounting():
    store = Store()

    class Flaky(_NodeEcho):
        name = "flakyecho"
        fails = 2

        def reconcile(self, store, key):
            self.seen.append(key)
            if len(self.seen) <= self.fails:
                raise RuntimeError("transient")
            return Result(requeue_after=30.0)

    err0 = REGISTRY.counter(names.RECONCILE_REQUEUES_TOTAL,
                            controller="flakyecho", reason="error")
    ctrl = Flaky(store)
    ctrl.start()
    try:
        from rbg_tpu.api.pod import Node
        n = Node()
        n.metadata.name = "n1"
        n.metadata.namespace = "default"
        store.create(n)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and len(ctrl.seen) < 3:
            time.sleep(0.01)
    finally:
        ctrl.stop()
    assert len(ctrl.seen) >= 3
    assert REGISTRY.counter(names.RECONCILE_REQUEUES_TOTAL,
                            controller="flakyecho",
                            reason="error") - err0 == 2.0
    assert REGISTRY.counter(names.RECONCILE_REQUEUES_TOTAL,
                            controller="flakyecho",
                            reason="requeue_after") >= 1.0
    # Success forgot the backoff: nothing pending, gauge settled at 0.
    assert ctrl.backoff.pending_count() == 0
    st = ctrl.stats()
    assert st["queue_depth"] == 0 and st["retries_pending"] == 0


# ---- admin operator surface -------------------------------------------------


@pytest.fixture()
def served_plane():
    from rbg_tpu.runtime.admin import AdminServer
    from rbg_tpu.runtime.plane import ControlPlane
    from rbg_tpu.testutil import make_tpu_nodes
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=2)
    p.start()
    admin = AdminServer(p, port=0).start()
    yield p, f"127.0.0.1:{admin.port}"
    admin.stop()
    p.stop()


def _call(addr, obj):
    from rbg_tpu.engine.protocol import request_once
    resp, _, _ = request_once(addr, obj)
    assert resp is not None
    return resp


def test_admin_events_op_filters_and_clamping(served_plane):
    plane, addr = served_plane
    p = _pod("evpod")
    plane.store.create(p)
    for i in range(8):
        plane.store.record_event(p, "FailedScheduling", f"attempt {i}",
                                 type_=EVENT_WARNING)
    plane.store.record_event(p, "Scheduled", "bound")
    resp = _call(addr, {"op": "events"})
    assert resp["stats"]["objects"] >= 1
    assert any(e["reason"] == "Scheduled" for e in resp["events"])
    # Filters: object ref, reason, type.
    resp = _call(addr, {"op": "events", "kind": "Pod", "name": "evpod",
                        "type": "Warning"})
    assert all(e["type"] == "Warning" for e in resp["events"])
    assert len(resp["events"]) == 8
    # Clamping: absurd/malformed limits degrade, never kill the handler.
    resp = _call(addr, {"op": "events", "limit": 10 ** 9})
    assert "events" in resp
    resp = _call(addr, {"op": "events", "limit": "garbage",
                        "since": "alsogarbage"})
    assert "events" in resp
    resp = _call(addr, {"op": "events", "limit": 1})
    assert len(resp["events"]) == 1
    # Events OUTLIVE their object: the post-mortem of a deleted pod must
    # still be readable (lookup is by ref, not by live object).
    plane.store.delete("Pod", "default", "evpod")
    resp = _call(addr, {"op": "events", "kind": "Pod", "name": "evpod"})
    assert len(resp["events"]) == 9  # 8 distinct warnings + Scheduled
    # An unknown ref is just an empty timeline, not an error.
    resp = _call(addr, {"op": "events", "kind": "Pod", "name": "nope"})
    assert resp["events"] == []


def test_admin_controlplane_op(served_plane):
    plane, addr = served_plane
    from rbg_tpu.testutil import make_group, simple_role
    plane.apply(make_group("cp", simple_role("s", replicas=1)))
    plane.wait_group_ready("cp")
    resp = _call(addr, {"op": "controlplane"})
    cp = resp["controlplane"]
    by_name = {c["name"]: c for c in cp["controllers"]}
    assert "scheduler" in by_name and "rolebasedgroup" in by_name
    sched = by_name["scheduler"]
    assert sched["reconciles"]["success"] >= 1
    assert sched["reconcile_p99_s"] is not None
    assert sched["queue_depth"] == 0
    assert "events" in cp and "watch" in cp
    assert cp["watch"]["dispatch_p99_s"].get("Pod") is not None


# ---- fleet drill smoke ------------------------------------------------------


def _run_fleet_small(**kw):
    from rbg_tpu.stress.harness import FleetConfig, run_fleet
    cfg = FleetConfig(nodes=40, hosts_per_slice=4, groups=4,
                      roles_per_group=2, replicas=1, create_qps=200.0,
                      timeout_s=60.0, drain_timeout_s=30.0,
                      sample_interval_s=0.1, **kw)
    return run_fleet(cfg)


def test_fleet_scenario_smoke():
    report = _run_fleet_small()
    assert all(report["invariants"].values()), report["invariants"]
    assert report["reconcile_latency"], "latency curves empty"
    assert report["fleet"]["pods_peak"] == 8
    assert report["scheduler"]["binds_total"] >= 8
    assert any(c["binds_per_s"] > 0 for c in report["throughput_curve"])
    assert report["events"]["recorded_total"] == (
        report["events"]["total_count"] + report["events"]["evicted_total"])
    # HTML render of the curves must not throw and must carry both SVGs.
    from rbg_tpu.stress.harness import _fleet_sections
    html = _fleet_sections(report)
    assert html.count("<svg") == 2


@pytest.mark.slow
def test_fleet_scenario_at_scale():
    from rbg_tpu.stress.harness import FleetConfig, run_fleet
    report = run_fleet(FleetConfig(nodes=2000, groups=60, replicas=2,
                                   timeout_s=300.0))
    assert all(report["invariants"].values()), report["invariants"]
    assert report["fleet"]["nodes"] >= 2000
    assert report["slowest_reconcile_by_controller"]
