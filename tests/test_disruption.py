"""Slice disruption lifecycle: gang semantics under no-notice preemption,
advance-notice migration before the deadline, warm-spare reservation.

The failure unit on GKE TPU is the SLICE (one ICI domain): spot preemption
takes every host together with no notice; maintenance events give a
deadline. The disruption controller must (a) never leave partial-slice
survivors wedged in collective ops, (b) migrate make-ready-then-drain
inside the notice window, (c) recover bind-time onto warm spares.
"""

import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RestartPolicyConfig
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.controllers.disruption import (
    notify_maintenance, preempt_slice, restore_slice,
)
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.sched.capacity import SparePool
from rbg_tpu.testutil import make_group, make_tpu_nodes, tpu_leaderworker_role


def _fast_tpu_role(name="serve", replicas=1):
    role = tpu_leaderworker_role(name, replicas=replicas, topology="2x4")
    role.restart_policy = RestartPolicyConfig(base_delay_seconds=0.01,
                                              max_delay_seconds=0.1)
    return role


def _gang_pods(store, role="serve"):
    return [p for p in store.list("Pod", namespace="default")
            if p.metadata.labels.get(C.LABEL_ROLE_NAME) == role and p.active]


def _gang_slice(store, role="serve"):
    nodes = {n.metadata.name: n for n in store.list("Node")}
    slices = {nodes[p.node_name].tpu.slice_id
              for p in _gang_pods(store, role) if p.node_name}
    assert len(slices) == 1, f"gang spans slices: {slices}"
    return slices.pop()


def test_preemption_gang_semantics_partial_loss():
    """Losing ONE host of a slice fails the whole replica: survivors are
    killed (GangPreempted) and the gang recovers WHOLE on a healthy
    slice — zero partial-slice survivors."""
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=4, hosts_per_slice=2)
    kills_before = REGISTRY.counter("rbg_disruption_gang_kills_total")
    preempt_before = REGISTRY.counter("rbg_disruption_preemptions_total")
    with plane:
        plane.apply(make_group("g", _fast_tpu_role()))
        plane.wait_group_ready("g", timeout=30)
        old_slice = _gang_slice(plane.store)
        old_uids = {p.metadata.uid for p in _gang_pods(plane.store)}
        victim = sorted(p.node_name for p in _gang_pods(plane.store))[0]

        # Partial loss: only ONE host vanishes — the window gang
        # semantics must close.
        assert preempt_slice(plane.store, old_slice, hosts=[victim]) == 1

        def recovered():
            ps = _gang_pods(plane.store)
            return (len(ps) == 2
                    and old_uids.isdisjoint({p.metadata.uid for p in ps})
                    and all(p.running_ready and p.node_name for p in ps))

        plane.wait_for(recovered, timeout=30, desc="gang recovered whole")
        new_slice = _gang_slice(plane.store)
        assert new_slice != old_slice
        # No survivor pod remained bound to the preempted domain.
        nodes = {n.metadata.name: n for n in plane.store.list("Node")}
        on_old = [p for p in plane.store.list("Pod", namespace="default")
                  if p.node_name and nodes[p.node_name].tpu.slice_id == old_slice
                  and p.active]
        assert not on_old, "partial-slice survivors left on preempted slice"
        inst = plane.store.list("RoleInstance", namespace="default")[0]
        assert inst.status.restart_count >= 1
        # Fresh coordinator epoch injected into the replacement gang.
        pod = _gang_pods(plane.store)[0]
        epochs = {e.value for c in pod.template.containers for e in c.env
                  if e.name == C.ENV_JAX_RESTART_EPOCH}
        assert epochs and epochs != {"0"}
    assert REGISTRY.counter("rbg_disruption_gang_kills_total") > kills_before
    assert REGISTRY.counter("rbg_disruption_preemptions_total") > preempt_before


def test_maintenance_migration_beats_deadline():
    """Advance notice: cordon → warm the replacement → drain → released,
    all before the deadline; the group reconverges on the target slice."""
    plane = ControlPlane(backend="fake", warm_spares=1)
    make_tpu_nodes(plane.store, slices=4, hosts_per_slice=2)
    done_before = REGISTRY.counter("rbg_disruption_migrations_completed_total")
    missed_before = REGISTRY.counter(
        "rbg_disruption_migrations_missed_deadline_total")
    notices_before = REGISTRY.counter("rbg_disruption_notices_total")
    consumed_before = REGISTRY.counter("rbg_disruption_spares_consumed_total")
    with plane:
        plane.apply(make_group("g", _fast_tpu_role()))
        plane.wait_group_ready("g", timeout=30)
        old_slice = _gang_slice(plane.store)
        # Wide notice window: the drill asserts release-before-deadline,
        # and a loaded CI host must not turn scheduling jitter into a
        # missed-deadline flake.
        deadline_s = 45.0
        t0 = time.time()
        assert notify_maintenance(plane.store, old_slice, deadline_s) == 2

        def released():
            nodes = [n for n in plane.store.list("Node")
                     if n.tpu.slice_id == old_slice]
            return all(n.metadata.annotations.get(C.ANN_MAINT_RELEASED)
                       for n in nodes)

        plane.wait_for(released, timeout=deadline_s, desc="slice released")
        released_at = time.time()
        assert released_at - t0 < deadline_s, "release missed the deadline"

        # Old hosts are cordoned; the gang serves from the new slice.
        for n in plane.store.list("Node"):
            if n.tpu.slice_id == old_slice:
                assert n.unschedulable

        def serving_again():
            ps = _gang_pods(plane.store)
            return (len(ps) == 2
                    and all(p.running_ready and p.node_name for p in ps))

        plane.wait_for(serving_again, timeout=30, desc="gang serving again")
        plane.wait_group_ready("g", timeout=30)
        new_slice = _gang_slice(plane.store)
        assert new_slice != old_slice

        # Migration bookkeeping unwinds (the controller's next pass after
        # the gang turns ready clears the annotations — poll, don't race).
        def unwound():
            inst = plane.store.list("RoleInstance", namespace="default")[0]
            return C.ANN_MIGRATION_STATE not in inst.metadata.annotations

        plane.wait_for(unwound, timeout=15, desc="migration state cleared")
    assert REGISTRY.counter(
        "rbg_disruption_migrations_completed_total") > done_before
    assert REGISTRY.counter(
        "rbg_disruption_migrations_missed_deadline_total") == missed_before
    assert REGISTRY.counter("rbg_disruption_notices_total") > notices_before
    # Exactly ONE spare consumed: a grant must not be revoked by
    # replenish and then double-charged by a scheduler raid.
    assert REGISTRY.counter(
        "rbg_disruption_spares_consumed_total") - consumed_before == 1


def test_spare_pool_reserve_take_replenish():
    """SparePool holds N idle slices per topology; take() consumes,
    replenish() refills from remaining idle capacity."""
    from rbg_tpu.runtime.store import Store
    store = Store()
    make_tpu_nodes(store, slices=3, hosts_per_slice=2)
    pool = SparePool(per_topology=2)
    pool.replenish(store)
    assert len(pool.reserved_slices()) == 2
    topo = next(iter(pool.depth()))
    taken = pool.take(topology=topo)
    assert taken is not None and not pool.is_reserved(taken)
    pool.replenish(store)
    # The third idle slice backfills the pool.
    assert len(pool.reserved_slices()) == 2
    assert taken not in pool.reserved_slices() or True


def test_scheduler_avoids_spares_but_raids_when_starved():
    """Ordinary gangs steer around reserved slices; when ONLY a spare
    fits, the scheduler takes it from the pool instead of wedging."""
    plane = ControlPlane(backend="fake", warm_spares=1)
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=2)
    with plane:
        plane.wait_for(lambda: len(plane.spares.reserved_slices()) == 1,
                       timeout=10, desc="spare reserved")
        reserved = next(iter(plane.spares.reserved_slices()))
        plane.apply(make_group("g1", _fast_tpu_role()))
        plane.wait_group_ready("g1", timeout=30)
        assert _gang_slice(plane.store) != reserved
        # Starvation: the only remaining capacity IS the spare — raid it.
        plane.apply(make_group("g2", _fast_tpu_role()))
        plane.wait_group_ready("g2", timeout=30)
        nodes = {n.metadata.name: n for n in plane.store.list("Node")}
        g2_slices = {nodes[p.node_name].tpu.slice_id
                     for p in plane.store.list("Pod", namespace="default")
                     if p.active and p.node_name
                     and p.metadata.labels.get(C.LABEL_GROUP_NAME) == "g2"}
        assert g2_slices == {reserved}
        assert not plane.spares.is_reserved(reserved)


@pytest.mark.slow
def test_k8s_backend_preemption_recovers_gang():
    """Full wire path: the fake GKE apiserver preempts a node pool (one
    ICI domain) → the backend's node resync + pod reflector surface it →
    the disruption controller recovers the gang whole on another pool."""
    from rbg_tpu.k8s import translate as T
    from rbg_tpu.k8s.client import KubeClient
    from rbg_tpu.k8s.fake_apiserver import FakeK8sApiServer

    srv = FakeK8sApiServer()
    for s in range(2):
        for h in range(2):
            srv.add_node(
                f"slice-{s}-host-{h}",
                labels={
                    T.LABEL_GKE_TPU_ACCEL: "tpu-v5-lite-podslice",
                    T.LABEL_GKE_TPU_TOPOLOGY: "2x4",
                    T.LABEL_GKE_NODEPOOL: f"pool-{s}",
                    T.LABEL_WORKER_INDEX: str(h),
                    T.LABEL_HOSTNAME: f"slice-{s}-host-{h}",
                },
                address=f"10.0.{s}.{h + 10}", tpu=4)
    with srv:
        plane = ControlPlane(backend="k8s", k8s_client=KubeClient(srv.url))
        with plane:
            plane.apply(make_group("g", _fast_tpu_role()))
            plane.wait_group_ready("g", timeout=60)
            old_slice = _gang_slice(plane.store)
            old_uids = {p.metadata.uid for p in _gang_pods(plane.store)}

            srv.preempt_slice(old_slice)

            def recovered():
                ps = _gang_pods(plane.store)
                return (len(ps) == 2
                        and old_uids.isdisjoint({p.metadata.uid for p in ps})
                        and all(p.running_ready and p.node_name for p in ps))

            plane.wait_for(recovered, timeout=60,
                           desc="gang recovered via k8s wire")
            assert _gang_slice(plane.store) != old_slice
            # Preempted pool is off-limits until restored.
            for n in plane.store.list("Node"):
                if n.tpu.slice_id == old_slice:
                    assert not n.schedulable


@pytest.mark.slow
def test_preemption_stress_scenario_invariants():
    """The acceptance drill: ``rbg-tpu stress --scenario preemption``
    passes every invariant (gang semantics, deadline migration, router
    replay, rolling drain, counters)."""
    from rbg_tpu.stress.harness import PreemptionConfig, run_preemption
    report = run_preemption(PreemptionConfig(
        slices=6, hosts_per_slice=2, notice_deadline_s=45.0))
    assert report["invariants"] == {
        k: True for k in report["invariants"]}, (
        report["invariants"], report["disruption_counters"])
    assert report["disruption_counters"][
        "rbg_disruption_migrations_missed_deadline_total"] == 0


def test_cancelled_maintenance_unwinds_migration():
    """Maintenance cancelled mid-migration: the state machine unwinds
    (no wedged annotations), the nodes uncordon, and the granted spare
    returns to the pool instead of leaking in probation."""
    plane = ControlPlane(backend="fake", warm_spares=1)
    make_tpu_nodes(plane.store, slices=4, hosts_per_slice=2)
    with plane:
        plane.apply(make_group("g", _fast_tpu_role()))
        plane.wait_group_ready("g", timeout=30)
        old_slice = _gang_slice(plane.store)
        notify_maintenance(plane.store, old_slice, 120.0)

        def migrating():
            insts = plane.store.list("RoleInstance", namespace="default")
            return any(C.ANN_MIGRATION_STATE in i.metadata.annotations
                       for i in insts)

        try:
            plane.wait_for(migrating, timeout=10, desc="migration started")
        except TimeoutError:
            pass  # migration already completed — cancellation is a no-op
        restore_slice(plane.store, old_slice)

        def unwound():
            insts = plane.store.list("RoleInstance", namespace="default")
            nodes = [n for n in plane.store.list("Node")
                     if n.tpu.slice_id == old_slice]
            return (all(C.ANN_MIGRATION_STATE not in i.metadata.annotations
                        for i in insts)
                    and all(not n.unschedulable for n in nodes))

        plane.wait_for(unwound, timeout=30, desc="migration unwound")
        plane.wait_group_ready("g", timeout=30)
        # The pool recovers its full depth (granted-but-unused spares do
        # not leak in probation; replenish can use the idle fleet).
        plane.spares.replenish(plane.store)
        assert sum(plane.spares.depth().values()) == 1


def test_restore_slice_uncordons():
    """Cleared disruption (capacity re-provisioned) lifts the
    controller's own cordon so the slice returns to the pool — for BOTH
    the maintenance path and the preemption path (whose injector cordons
    the nodes itself)."""
    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=2)
    with plane:
        notify_maintenance(plane.store, "slice-0", 30.0)
        preempt_slice(plane.store, "slice-1")

        def cordoned():
            ns = plane.store.list("Node")
            return all(n.unschedulable for n in ns)

        plane.wait_for(cordoned, timeout=10, desc="slices cordoned")
        restore_slice(plane.store, "slice-0")
        restore_slice(plane.store, "slice-1")

        def uncordoned():
            ns = plane.store.list("Node")
            return all(not n.unschedulable and n.schedulable for n in ns)

        plane.wait_for(uncordoned, timeout=10, desc="slices uncordoned")
