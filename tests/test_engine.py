"""Serving engine: paged attention numerics, continuous batching, radix
cache, preemption."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.models import KVCache, forward, get_config, init_params
from rbg_tpu.models.llama import prefill_and_decode_greedy


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def make_engine(params, radix=True, num_pages=64, **kw):
    ecfg = EngineConfig(model="tiny", page_size=8, num_pages=num_pages,
                        max_batch=4, max_seq_len=128, prefill_chunk=16,
                        enable_radix_cache=radix, use_pallas="never", **kw)
    return Engine(ecfg, params=params)


def ref_greedy(params, cfg, prompt, steps):
    out = prefill_and_decode_greedy(
        params, cfg, jnp.asarray([prompt], jnp.int32), steps)
    return [int(t) for t in np.asarray(out)[0]]


@pytest.mark.slow
def test_paged_attention_matches_dense(tiny_setup):
    """Paged forward == contiguous forward for a single sequence."""
    cfg, params = tiny_setup
    prompt = [5, 9, 13, 2, 7, 11, 3, 1, 8, 4]
    expect = ref_greedy(params, cfg, prompt, steps=8)
    eng = make_engine(params, radix=False)
    got = eng.generate([prompt], SamplingParams(max_new_tokens=8))[0]
    assert got == expect


@pytest.mark.slow
def test_chunked_prefill_long_prompt(tiny_setup):
    cfg, params = tiny_setup
    rng = np.random.RandomState(0)
    prompt = rng.randint(0, cfg.vocab_size, size=50).tolist()  # > prefill_chunk
    expect = ref_greedy(params, cfg, prompt, steps=5)
    eng = make_engine(params, radix=False)
    got = eng.generate([prompt], SamplingParams(max_new_tokens=5))[0]
    assert got == expect


@pytest.mark.slow
def test_continuous_batching_mixed_lengths(tiny_setup):
    cfg, params = tiny_setup
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in (4, 23, 9, 17)]
    expect = [ref_greedy(params, cfg, p, steps=6) for p in prompts]
    eng = make_engine(params, radix=True)
    got = eng.generate(prompts, SamplingParams(max_new_tokens=6))
    assert got == expect


def test_radix_cache_hit_same_output(tiny_setup):
    cfg, params = tiny_setup
    prompt = list(range(1, 41))  # 40 tokens = 5 full pages
    eng = make_engine(params, radix=True)
    first = eng.generate([prompt], SamplingParams(max_new_tokens=6))[0]
    assert eng.metrics["radix_hit_tokens"] == 0
    second = eng.generate([prompt], SamplingParams(max_new_tokens=6))[0]
    assert second == first
    assert eng.metrics["radix_hit_tokens"] >= 32  # ≥4 pages reused
    # prefill work for the second pass shrinks accordingly
    assert eng.metrics["prefill_tokens"] < 2 * len(prompt)


@pytest.mark.slow
def test_preemption_under_page_pressure(tiny_setup):
    """Pool sized so concurrent decodes exhaust pages mid-flight (admission
    reserves prompt-only pages; decode growth oversubscribes): the engine
    must preempt and still produce exactly the sequential-reference
    outputs."""
    cfg, params = tiny_setup
    rng = np.random.RandomState(2)
    prompts = [rng.randint(0, cfg.vocab_size, size=20).tolist() for _ in range(3)]
    steps = 30
    expect = [ref_greedy(params, cfg, p, steps=steps) for p in prompts]
    # 3 prompts × 3 pages at admission = 9 pages < 10; decode growth to
    # ~7 pages each forces preemption.
    eng = make_engine(params, radix=False, num_pages=11)
    got = eng.generate(prompts, SamplingParams(max_new_tokens=steps))
    assert got == expect
    assert eng.metrics["preemptions"] >= 1, "page pressure must trigger preemption"


@pytest.mark.slow
def test_sampling_modes(tiny_setup):
    cfg, params = tiny_setup
    prompt = [3, 1, 4, 1, 5]
    eng = make_engine(params, radix=False)
    greedy = eng.generate([prompt], SamplingParams(max_new_tokens=5, temperature=0.0))[0]
    eng2 = make_engine(params, radix=False)
    topk1 = eng2.generate([prompt], SamplingParams(max_new_tokens=5,
                                                   temperature=1.0, top_k=1))[0]
    assert topk1 == greedy  # top_k=1 == argmax regardless of temperature

    eng3 = make_engine(params, radix=False)
    hot = eng3.generate([prompt] * 2, SamplingParams(max_new_tokens=8, temperature=5.0))
    assert hot[0] != hot[1]  # two hot samples almost surely diverge


@pytest.mark.slow
def test_stop_token(tiny_setup):
    cfg, params = tiny_setup
    prompt = [2, 4, 6]
    eng = make_engine(params, radix=False)
    expect = ref_greedy(params, cfg, prompt, steps=10)
    stop = expect[2]
    got = eng.generate([prompt], SamplingParams(max_new_tokens=10, stop_token=stop))[0]
    assert got == expect[:3]


@pytest.mark.slow
def test_page_accounting_balances(tiny_setup):
    cfg, params = tiny_setup
    eng = make_engine(params, radix=False, num_pages=32)
    free0 = eng.allocator.free_pages
    eng.generate([[1, 2, 3, 4]] * 3, SamplingParams(max_new_tokens=4))
    assert eng.allocator.free_pages == free0  # all pages returned
    eng_r = make_engine(params, radix=True, num_pages=32)
    free0 = eng_r.allocator.free_pages
    eng_r.generate([[1, 2, 3, 4, 5, 6, 7, 8, 9]] * 2, SamplingParams(max_new_tokens=4))
    held = free0 - eng_r.allocator.free_pages
    assert held >= 0  # radix retains frozen prefix pages (refcounted), never leaks
    eng_r.radix.evict(10**9)
    assert eng_r.allocator.free_pages == free0  # full eviction returns the rest


@pytest.mark.slow
def test_engine_on_mesh_matches_single_device(tiny_setup):
    """The sharded serving path (Engine(mesh=...)): tp/dp-sharded params and
    KV pages produce identical tokens."""
    from rbg_tpu.parallel import make_mesh

    cfg, params = tiny_setup
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist() for n in (6, 19)]

    single = make_engine(params, radix=False)
    expect = single.generate(prompts, SamplingParams(max_new_tokens=6))

    mesh = make_mesh(dp=1, sp=1, ep=1, tp=2)
    sharded = Engine(
        EngineConfig(model="tiny", page_size=8, num_pages=64, max_batch=4,
                     max_seq_len=128, prefill_chunk=16,
                     enable_radix_cache=False, use_pallas="never"),
        params=params, mesh=mesh)
    got = sharded.generate(prompts, SamplingParams(max_new_tokens=6))
    assert got == expect


@pytest.mark.slow
def test_int8_kv_cache(tiny_setup):
    """int8-quantized KV pool: half the KV memory, bounded logit deviation,
    page accounting still balanced."""
    cfg, params = tiny_setup
    prompt = list(range(1, 25))

    ref = make_engine(params, radix=False)
    ref_logits = np.asarray(ref._run(
        tokens=[prompt], positions=[list(range(len(prompt)))],
        lens=[len(prompt)], pages=[ref.allocator.alloc(4)], T_bucket=32,
    ))[0, len(prompt) - 1]

    q = Engine(EngineConfig(model="tiny", page_size=8, num_pages=64,
                            max_batch=4, max_seq_len=128, prefill_chunk=16,
                            enable_radix_cache=False, use_pallas="never",
                            kv_dtype="int8"), params=params)
    assert q.cache.quantized and q.cache.k_pages.dtype == jnp.int8
    q_logits = np.asarray(q._run(
        tokens=[prompt], positions=[list(range(len(prompt)))],
        lens=[len(prompt)], pages=[q.allocator.alloc(4)], T_bucket=32,
    ))[0, len(prompt) - 1]

    # Deterministic bounded deviation from per-vector absmax int8.
    denom = np.maximum(np.abs(ref_logits), 1.0)
    assert np.max(np.abs(ref_logits - q_logits) / denom) < 0.05
    # Cosine similarity of the full logit rows stays high.
    cos = np.dot(ref_logits, q_logits) / (
        np.linalg.norm(ref_logits) * np.linalg.norm(q_logits))
    assert cos > 0.999

    # End-to-end generation runs, pages balance, greedy tokens mostly agree.
    q2 = Engine(EngineConfig(model="tiny", page_size=8, num_pages=64,
                             max_batch=4, max_seq_len=128, prefill_chunk=16,
                             enable_radix_cache=False, use_pallas="never",
                             kv_dtype="int8"), params=params)
    out = q2.generate([prompt], SamplingParams(max_new_tokens=8))[0]
    assert len(out) == 8
    assert q2.allocator.free_pages == 63

    ref_out = ref_greedy(params, cfg, prompt, steps=8)
    agree = sum(a == b for a, b in zip(out, ref_out))
    assert agree >= 5, f"int8 KV diverged too far: {out} vs {ref_out}"


def test_int8_kv_rejected_for_pd_modes():
    with pytest.raises(ValueError, match="unified"):
        EngineConfig(model="tiny", kv_dtype="int8", mode="prefill").validate()


def test_int8_kv_accepts_pallas_always():
    # Round 5: the decode kernel grew a dequantizing int8 variant, so the
    # incompatibility guard is gone.
    EngineConfig(model="tiny", kv_dtype="int8",
                 use_pallas="always").validate()


# ---- multi-step (device-side decode window, EngineConfig.multi_step) ----


@pytest.mark.slow
def test_multistep_matches_single_step_greedy(tiny_setup):
    """A K-step scan window must produce the exact single-step token stream
    (same forward, same greedy argmax — only dispatch granularity differs)."""
    cfg, params = tiny_setup
    rng = np.random.RandomState(7)
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in (4, 19, 11)]
    expect = [ref_greedy(params, cfg, p, steps=12) for p in prompts]
    for k in (2, 4, 5):
        eng = make_engine(params, radix=False, multi_step=k)
        got = eng.generate(prompts, SamplingParams(max_new_tokens=12))
        assert got == expect, f"multi_step={k}"


@pytest.mark.slow
def test_multistep_stop_token_mid_window(tiny_setup):
    """A stop token landing mid-window cuts emission at the stop; the
    window's speculative tail is discarded and pages are reclaimed."""
    cfg, params = tiny_setup
    prompt = [2, 4, 6]
    expect = ref_greedy(params, cfg, prompt, steps=10)
    stop = expect[2]
    eng = make_engine(params, radix=False, multi_step=4)
    free0 = eng.allocator.free_pages
    got = eng.generate([prompt], SamplingParams(max_new_tokens=10,
                                                stop_token=stop))[0]
    assert got == expect[:3]
    assert eng.allocator.free_pages == free0


@pytest.mark.slow
def test_multistep_uneven_lengths_finish_correctly(tiny_setup):
    """Rows whose max_new_tokens is not a multiple of the window, or less
    than one window, emit exactly their budget."""
    cfg, params = tiny_setup
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, cfg.vocab_size, size=6).tolist()
               for _ in range(3)]
    eng = make_engine(params, radix=False, multi_step=4)
    ids = [eng.add_request(p, SamplingParams(max_new_tokens=m))
           for p, m in zip(prompts, (2, 7, 9))]
    outputs = {i: [] for i in ids}
    while eng.has_work():
        for ev in eng.step():
            outputs[ev.request_id].append(ev.token)
    assert [len(outputs[i]) for i in ids] == [2, 7, 9]
    expect = [ref_greedy(params, cfg, p, steps=m)
              for p, m in zip(prompts, (2, 7, 9))]
    assert [outputs[i] for i in ids] == expect


@pytest.mark.slow
def test_multistep_preemption_under_pressure(tiny_setup):
    """Page exhaustion with a multi-step window still preempts + resumes
    without corrupting any stream."""
    cfg, params = tiny_setup
    rng = np.random.RandomState(5)
    prompts = [rng.randint(0, cfg.vocab_size, size=24).tolist()
               for _ in range(4)]
    expect = [ref_greedy(params, cfg, p, steps=16) for p in prompts]
    eng = make_engine(params, radix=False, num_pages=18, multi_step=3)
    got = eng.generate(prompts, SamplingParams(max_new_tokens=16))
    assert got == expect
    assert eng.metrics["preemptions"] > 0


@pytest.mark.slow
def test_multistep_stop_plus_page_pressure_no_leak(tiny_setup):
    """A pending stop token emitted by the alloc-retry drain finishes the
    very request being grown — its freshly allocated pages must return to
    the allocator, and the finished stream must not be resurrected."""
    cfg, params = tiny_setup
    rng = np.random.RandomState(11)
    stopper = [2, 4, 6]
    expect = ref_greedy(params, cfg, stopper, steps=12)
    stop = expect[2]  # lands mid-window
    growers = [rng.randint(0, cfg.vocab_size, size=20).tolist()
               for _ in range(3)]
    eng = make_engine(params, radix=False, num_pages=15, multi_step=4)
    free0 = eng.allocator.free_pages
    ids = [eng.add_request(stopper, SamplingParams(max_new_tokens=12,
                                                   stop_token=stop))]
    ids += [eng.add_request(p, SamplingParams(max_new_tokens=12))
            for p in growers]
    outputs = {i: [] for i in ids}
    finished = set()
    while eng.has_work():
        for ev in eng.step():
            outputs[ev.request_id].append(ev.token)
            if ev.finished:
                assert ev.request_id not in finished, "stream resurrected"
                finished.add(ev.request_id)
    assert outputs[ids[0]] == expect[:3]
    assert eng.allocator.free_pages == free0, "page leak"


def test_unified_emission_is_one_batched_fetch_per_step(tiny_setup,
                                                        monkeypatch):
    """The unified step emits via ONE jax.device_get over the (toks, lps)
    pytree — not two sequential per-array syncs (the jit-hygiene fix).
    Every device fetch during a generate must be the batched pair form."""
    cfg, params = tiny_setup
    eng = make_engine(params)
    calls = []
    real = jax.device_get

    def counting(x):
        calls.append(x)
        return real(x)

    monkeypatch.setattr(jax, "device_get", counting)
    out = eng.generate([[5, 9, 13, 2]], SamplingParams(max_new_tokens=4))[0]
    assert len(out) == 4
    assert eng.metrics["unified_steps"] > 0
    assert calls, "emission must flow through the batched jax.device_get"
    assert all(isinstance(c, tuple) and len(c) == 2 for c in calls), (
        [type(c) for c in calls])
