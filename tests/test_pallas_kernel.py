"""Pallas paged-attention kernel vs XLA reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.ops.paged_attention import paged_attention_xla
from rbg_tpu.ops.pallas.paged_attention_kernel import paged_attention_pallas


def _setup(B=3, H=8, KV=2, hd=32, page=8, NP=32, P=6, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, 1, H, hd), jnp.float32)
    k_pages = jnp.asarray(rng.randn(NP, page, KV, hd), jnp.float32)
    v_pages = jnp.asarray(rng.randn(NP, page, KV, hd), jnp.float32)
    # Distinct physical pages per sequence (as the allocator guarantees).
    perm = rng.permutation(NP - 1)[: B * P] + 1
    table = jnp.asarray(perm.reshape(B, P), jnp.int32)
    kv_lens = jnp.asarray(rng.randint(1, P * page, size=B), jnp.int32)
    q_pos = (kv_lens - 1)[:, None]
    return q, k_pages, v_pages, table, q_pos, kv_lens


def test_decode_kernel_matches_xla():
    q, k, v, table, q_pos, lens = _setup()
    ref = paged_attention_xla(q, k, v, table, q_pos, lens)
    got = paged_attention_pallas(q, k, v, table, q_pos, lens, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_decode_kernel_gqa_and_edge_lens():
    # G=1 (MHA-like) and kv_len exactly on a page boundary + len 1
    q, k, v, table, _, _ = _setup(B=4, H=4, KV=4, hd=16, page=4, NP=64, P=8,
                                  seed=1)
    lens = jnp.asarray([1, 4, 32, 17], jnp.int32)  # 1, boundary, full, mid
    q_pos = (lens - 1)[:, None]
    ref = paged_attention_xla(q, k, v, table, q_pos, lens)
    got = paged_attention_pallas(q, k, v, table, q_pos, lens, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_prefill_falls_back_to_xla():
    """T > 1 routes to the XLA path (same function, so trivially equal)."""
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 4, 8, 32), jnp.float32)
    k = jnp.asarray(rng.randn(16, 8, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(16, 8, 2, 32), jnp.float32)
    table = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    lens = jnp.asarray([10, 20], jnp.int32)
    q_pos = jnp.asarray([[6, 7, 8, 9], [16, 17, 18, 19]], jnp.int32)
    ref = paged_attention_xla(q, k, v, table, q_pos, lens)
    got = paged_attention_pallas(q, k, v, table, q_pos, lens, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got))


# ---- MLA (latent) decode kernel (VERDICT r4 #8) ----


from rbg_tpu.ops.mla_attention import (paged_mla_attention,
                                       paged_mla_attention_xla)
from rbg_tpu.ops.pallas.paged_attention_kernel import paged_mla_attention_pallas


def _mla_setup(B=3, H=16, dc=512, dr=64, page=8, NP=32, P=6, seed=3):
    """DeepSeek-V2-Lite latent dims by default: kv_lora_rank 512,
    qk_rope_head_dim 64, 16 heads."""
    rng = np.random.RandomState(seed)
    q_lat = jnp.asarray(rng.randn(B, 1, H, dc) * 0.1, jnp.float32)
    q_pe = jnp.asarray(rng.randn(B, 1, H, dr) * 0.1, jnp.float32)
    c_pages = jnp.asarray(rng.randn(NP, page, 1, dc) * 0.1, jnp.float32)
    pe_pages = jnp.asarray(rng.randn(NP, page, 1, dr) * 0.1, jnp.float32)
    perm = rng.permutation(NP - 1)[: B * P] + 1
    table = jnp.asarray(perm.reshape(B, P), jnp.int32)
    kv_lens = jnp.asarray(rng.randint(1, P * page, size=B), jnp.int32)
    q_pos = (kv_lens - 1)[:, None]
    scale = 1.0 / np.sqrt(128 + dr)  # qk_nope_head_dim + qk_rope_head_dim
    return q_lat, q_pe, c_pages, pe_pages, table, q_pos, kv_lens, scale


def test_mla_decode_kernel_matches_xla_v2lite_dims():
    ql, qp, c, pe, table, q_pos, lens, scale = _mla_setup()
    ref = paged_mla_attention_xla(ql, qp, c, pe, table, q_pos, lens, scale)
    got = paged_mla_attention_pallas(ql, qp, c, pe, table, q_pos, lens,
                                     scale, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_mla_decode_kernel_edge_lens():
    ql, qp, c, pe, table, _, _, scale = _mla_setup(B=4, page=4, NP=64, P=8,
                                                   dc=128, dr=32, H=4, seed=4)
    lens = jnp.asarray([1, 4, 32, 17], jnp.int32)  # 1, boundary, full, mid
    q_pos = (lens - 1)[:, None]
    ref = paged_mla_attention_xla(ql, qp, c, pe, table, q_pos, lens, scale)
    got = paged_mla_attention_pallas(ql, qp, c, pe, table, q_pos, lens,
                                     scale, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_mla_prefill_falls_back_to_xla():
    ql, qp, c, pe, table, _, lens, scale = _mla_setup(dc=64, dr=16, H=4)
    T = 3
    rng = np.random.RandomState(5)
    ql = jnp.asarray(rng.randn(3, T, 4, 64) * 0.1, jnp.float32)
    qp = jnp.asarray(rng.randn(3, T, 4, 16) * 0.1, jnp.float32)
    q_pos = jnp.stack([lens - 3, lens - 2, lens - 1], axis=1)
    ref = paged_mla_attention_xla(ql, qp, c, pe, table, q_pos, lens, scale)
    got = paged_mla_attention_pallas(ql, qp, c, pe, table, q_pos, lens,
                                     scale, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got))


def test_mla_dispatcher_routes_and_preserves_args(monkeypatch):
    """The dispatcher must route 'never' to the XLA path and 'always' to
    the kernel WITH the arguments in the right order — a swapped
    c_pages/pe_pages would only surface in TPU serving otherwise."""
    from rbg_tpu.ops.pallas import paged_attention_kernel as K

    ql, qp, c, pe, table, q_pos, lens, scale = _mla_setup(dc=64, dr=16, H=4)
    ref = paged_mla_attention_xla(ql, qp, c, pe, table, q_pos, lens, scale)
    never = paged_mla_attention(ql, qp, c, pe, table, q_pos, lens, scale,
                                use_pallas="never")
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(never))

    calls = []

    def spy(*args, **kw):
        calls.append(args)
        return paged_mla_attention_pallas(*args, interpret=True, **kw)

    monkeypatch.setattr(K, "paged_mla_attention_pallas", spy)
    always = paged_mla_attention(ql, qp, c, pe, table, q_pos, lens, scale,
                                 use_pallas="always")
    assert len(calls) == 1
    np.testing.assert_allclose(np.asarray(ref), np.asarray(always),
                               rtol=1e-5, atol=1e-5)

    # The config guard is gone: 'always' is legal for MLA models now.
    from rbg_tpu.engine.config import EngineConfig
    EngineConfig(model="deepseek-v2-lite", use_pallas="always").validate()


# ---- int8 (quantized pool) decode kernel ----


from rbg_tpu.ops.paged_attention import quantize_kv
from rbg_tpu.ops.pallas.paged_attention_kernel import paged_attention_pallas_q


def _quantize_pages(k, v):
    kq, ks = quantize_kv(np.asarray(k))
    vq, vs = quantize_kv(np.asarray(v))
    return (jnp.asarray(kq), jnp.asarray(vq),
            jnp.asarray(ks), jnp.asarray(vs))


def test_int8_decode_kernel_matches_xla_dequant():
    q, k, v, table, q_pos, lens = _setup(seed=7)
    kq, vq, ks, vs = _quantize_pages(k, v)
    ref = paged_attention_xla(q, kq, vq, table, q_pos, lens, ks, vs)
    got = paged_attention_pallas_q(q, kq, vq, table, q_pos, lens, ks, vs,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_int8_decode_kernel_edge_lens():
    q, k, v, table, _, _ = _setup(B=4, H=4, KV=4, hd=16, page=4, NP=64, P=8,
                                  seed=8)
    lens = jnp.asarray([1, 4, 32, 17], jnp.int32)
    q_pos = (lens - 1)[:, None]
    kq, vq, ks, vs = _quantize_pages(k, v)
    ref = paged_attention_xla(q, kq, vq, table, q_pos, lens, ks, vs)
    got = paged_attention_pallas_q(q, kq, vq, table, q_pos, lens, ks, vs,
                                   interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_mla_int8_decode_kernel_matches_xla_dequant():
    """The dequantizing MLA decode kernel (round 16): int8 latent pools
    + per-slot scales vs the XLA dequant gather."""
    from rbg_tpu.ops.pallas.paged_attention_kernel import \
        paged_mla_attention_pallas_q

    ql, qp, c, pe, table, q_pos, lens, scale = _mla_setup(seed=11)
    cq, cs = quantize_kv(c)
    peq, pes = quantize_kv(pe)
    ref = paged_mla_attention_xla(ql, qp, cq, peq, table, q_pos, lens,
                                  scale, c_scales=cs, pe_scales=pes)
    got = paged_mla_attention_pallas_q(ql, qp, cq, peq, table, q_pos,
                                       lens, scale, cs, pes,
                                       interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-4, atol=1e-4)


def test_mla_int8_dispatch_routes_to_quantized_kernel(monkeypatch):
    """use_pallas='always' + int8 MLA now routes to the dequantizing
    kernel instead of raising (the last 'dequantize first' guard fell in
    round 16)."""
    from rbg_tpu.ops.pallas import paged_attention_kernel as K
    from rbg_tpu.ops.pallas.paged_attention_kernel import \
        paged_mla_attention_pallas_q

    ql, qp, c, pe, table, q_pos, lens, scale = _mla_setup(dc=64, dr=16,
                                                          H=4, seed=12)
    cq, cs = quantize_kv(c)
    peq, pes = quantize_kv(pe)
    calls = []

    def spy(*args, **kw):
        calls.append(args)
        return paged_mla_attention_pallas_q(*args, interpret=True, **kw)

    monkeypatch.setattr(K, "paged_mla_attention_pallas_q", spy)
    got = paged_mla_attention(ql, qp, cq, peq, table, q_pos, lens, scale,
                              use_pallas="always", c_scales=cs,
                              pe_scales=pes)
    assert len(calls) == 1
    ref = paged_mla_attention_xla(ql, qp, cq, peq, table, q_pos, lens,
                                  scale, c_scales=cs, pe_scales=pes)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-4, atol=1e-4)


def test_int8_dispatch_routes_to_quantized_kernel(monkeypatch):
    from rbg_tpu.ops import paged_attention as PA
    from rbg_tpu.ops.pallas import paged_attention_kernel as K

    q, k, v, table, q_pos, lens = _setup(seed=9)
    kq, vq, ks, vs = _quantize_pages(k, v)
    calls = []

    def spy(*args, **kw):
        calls.append(args)
        return paged_attention_pallas_q(*args, interpret=True, **kw)

    monkeypatch.setattr(K, "paged_attention_pallas_q", spy)
    got = PA.paged_attention(q, kq, vq, table, q_pos, lens,
                             use_pallas="always", k_scales=ks, v_scales=vs)
    assert len(calls) == 1
    ref = paged_attention_xla(q, kq, vq, table, q_pos, lens, ks, vs)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)
