"""Pallas paged-attention kernel vs XLA reference (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.ops.paged_attention import paged_attention_xla
from rbg_tpu.ops.pallas.paged_attention_kernel import paged_attention_pallas


def _setup(B=3, H=8, KV=2, hd=32, page=8, NP=32, P=6, seed=0):
    rng = np.random.RandomState(seed)
    q = jnp.asarray(rng.randn(B, 1, H, hd), jnp.float32)
    k_pages = jnp.asarray(rng.randn(NP, page, KV, hd), jnp.float32)
    v_pages = jnp.asarray(rng.randn(NP, page, KV, hd), jnp.float32)
    # Distinct physical pages per sequence (as the allocator guarantees).
    perm = rng.permutation(NP - 1)[: B * P] + 1
    table = jnp.asarray(perm.reshape(B, P), jnp.int32)
    kv_lens = jnp.asarray(rng.randint(1, P * page, size=B), jnp.int32)
    q_pos = (kv_lens - 1)[:, None]
    return q, k_pages, v_pages, table, q_pos, kv_lens


def test_decode_kernel_matches_xla():
    q, k, v, table, q_pos, lens = _setup()
    ref = paged_attention_xla(q, k, v, table, q_pos, lens)
    got = paged_attention_pallas(q, k, v, table, q_pos, lens, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_decode_kernel_gqa_and_edge_lens():
    # G=1 (MHA-like) and kv_len exactly on a page boundary + len 1
    q, k, v, table, _, _ = _setup(B=4, H=4, KV=4, hd=16, page=4, NP=64, P=8,
                                  seed=1)
    lens = jnp.asarray([1, 4, 32, 17], jnp.int32)  # 1, boundary, full, mid
    q_pos = (lens - 1)[:, None]
    ref = paged_attention_xla(q, k, v, table, q_pos, lens)
    got = paged_attention_pallas(q, k, v, table, q_pos, lens, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                               rtol=1e-5, atol=1e-5)


def test_prefill_falls_back_to_xla():
    """T > 1 routes to the XLA path (same function, so trivially equal)."""
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(2, 4, 8, 32), jnp.float32)
    k = jnp.asarray(rng.randn(16, 8, 2, 32), jnp.float32)
    v = jnp.asarray(rng.randn(16, 8, 2, 32), jnp.float32)
    table = jnp.asarray(rng.randint(1, 16, size=(2, 4)), jnp.int32)
    lens = jnp.asarray([10, 20], jnp.int32)
    q_pos = jnp.asarray([[6, 7, 8, 9], [16, 17, 18, 19]], jnp.int32)
    ref = paged_attention_xla(q, k, v, table, q_pos, lens)
    got = paged_attention_pallas(q, k, v, table, q_pos, lens, interpret=True)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got))
