"""Discovery plane: topology ConfigMap, port allocator, component ordering,
sidecar injection, native bindings."""

import json

import pytest
import yaml

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import ComponentSpec, EngineRuntimeRef, PatternType, RoleSpec
from rbg_tpu.api.pod import Container, PodTemplate
from rbg_tpu.api.policy import EngineRuntimeProfile
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import (
    make_group, make_tpu_nodes, simple_container, simple_role,
    tpu_leaderworker_role,
)


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        yield p


def test_topology_configmap(plane):
    plane.apply(make_group("t", tpu_leaderworker_role("serve", replicas=1, topology="2x4")))
    plane.wait_group_ready("t")

    def cm_has_hosts():
        cm = plane.store.get("ConfigMap", "default", "t-topology")
        if cm is None:
            return None
        cfg = yaml.safe_load(cm.data[C.DISCOVERY_CONFIG_FILE])
        insts = cfg["roles"][0]["instances"]
        if insts and len(insts[0]["hosts"]) == 2 and insts[0]["hosts"][0]["ip"]:
            return cfg
        return None

    cfg = plane.wait_for(cm_has_hosts, desc="topology configmap populated")
    role = cfg["roles"][0]
    assert role["service"] == "s-t-serve"
    inst = role["instances"][0]
    assert inst["sliceTopology"] == "2x4"
    assert inst["coordinator"].endswith(":8476")
    assert inst["sliceId"].startswith("slice-")
    hosts = inst["hosts"]
    assert [h["processId"] for h in hosts] == [0, 1]
    assert all(h["meshCoords"] for h in hosts)


def test_port_allocation_role_scoped(plane):
    role = simple_role("server", replicas=2)
    role.template.annotations[C.ANN_PORT_ALLOCATOR] = json.dumps(
        [{"name": "dist", "scope": "role"}])
    plane.apply(make_group("p", role))
    plane.wait_group_ready("p")

    ris = plane.store.get("RoleInstanceSet", "default", "p-server")
    alloc = json.loads(ris.metadata.annotations[C.ANN_ALLOCATED_PORTS])
    assert 30000 <= alloc["dist"] < 35000
    for pod in plane.store.list("Pod", namespace="default"):
        envs = {e.name: e.value for e in pod.template.containers[0].env}
        assert envs["RBG_PORT_DIST"] == str(alloc["dist"])


def test_port_unique_across_groups_and_released(plane):
    for g in ("g1", "g2"):
        role = simple_role("s")
        role.template.annotations[C.ANN_PORT_ALLOCATOR] = json.dumps(
            [{"name": "http", "scope": "role"}])
        plane.apply(make_group(g, role))
        plane.wait_group_ready(g)
    p1 = json.loads(plane.store.get("RoleInstanceSet", "default", "g1-s")
                    .metadata.annotations[C.ANN_ALLOCATED_PORTS])["http"]
    p2 = json.loads(plane.store.get("RoleInstanceSet", "default", "g2-s")
                    .metadata.annotations[C.ANN_ALLOCATED_PORTS])["http"]
    assert p1 != p2
    used_before = plane.ports.allocator.in_use()
    plane.store.delete("RoleBasedGroup", "default", "g1")
    plane.wait_for(lambda: plane.ports.allocator.in_use() == used_before - 1,
                   desc="port released on delete")


def test_component_startup_ordering(plane):
    role = RoleSpec(
        name="ep", replicas=1, pattern=PatternType.CUSTOM_COMPONENTS,
        components=[
            ComponentSpec(name="server", size=1, template=PodTemplate(
                containers=[simple_container("server")],
                annotations={C.ANN_COMPONENT_DEPENDS_ON: '{"startAfter": ["cache"]}'},
            )),
            ComponentSpec(name="cache", size=1, template=PodTemplate(
                containers=[simple_container("cache")])),
        ],
    )
    plane.apply(make_group("ord", role))
    plane.wait_group_ready("ord", timeout=15)
    pods = plane.store.list("Pod", namespace="default")
    by_comp = {p.metadata.labels[C.LABEL_COMPONENT_NAME]: p for p in pods}
    assert set(by_comp) == {"server", "cache"}
    assert (by_comp["cache"].metadata.creation_timestamp
            < by_comp["server"].metadata.creation_timestamp)
    # intra-role discovery env present
    envs = {e.name: e.value for e in by_comp["server"].template.containers[0].env}
    assert envs["RBG_COMPONENT_CACHE_ADDRESSES"] == "ord-ep-xxxxx-cache-0.s-ord-ep".replace(
        "xxxxx", by_comp["cache"].metadata.labels[C.LABEL_INSTANCE_NAME].rsplit("-", 1)[-1]
    ) or "cache-0" in envs["RBG_COMPONENT_CACHE_ADDRESSES"]


def test_engine_runtime_sidecar_injection(plane):
    prof = EngineRuntimeProfile()
    prof.metadata.name = "sglang-runtime"
    prof.containers = [simple_container("metrics", image="metrics:v1")]
    prof.init_containers = [simple_container("warmup", image="warmup:v1")]
    prof.volumes = ["cache-vol"]
    plane.store.create(prof)

    role = simple_role("server")
    role.engine_runtime = EngineRuntimeRef(
        profile_name="sglang-runtime",
        container_args={"engine": ["--extra-flag"]},
        container_env={"metrics": {"SCRAPE_PORT": "9100"}},
    )
    plane.apply(make_group("er", role))
    plane.wait_group_ready("er")
    pod = plane.store.list("Pod", namespace="default")[0]
    names = [c.name for c in pod.template.containers]
    assert names == ["engine", "metrics"]
    assert [c.name for c in pod.template.init_containers] == ["warmup"]
    assert "cache-vol" in pod.template.volumes
    assert "--extra-flag" in pod.template.containers[0].args
    envs = {e.name: e.value for e in pod.template.containers[1].env}
    assert envs["SCRAPE_PORT"] == "9100"


def test_native_bindings_loaded():
    from rbg_tpu.native import load_native
    from rbg_tpu.portalloc import PortAllocator
    lib = load_native()
    assert lib is not None, "native library should be built (make -C native)"
    pa = PortAllocator(40000, 16)
    assert pa.native
    ports = {pa.allocate() for _ in range(16)}
    assert len(ports) == 16 and all(40000 <= p < 40016 for p in ports)
    assert pa.allocate() is None  # exhausted
    pa.release(40003)
    assert pa.allocate() == 40003
    assert not pa.reserve(40003)


def test_native_workqueue_semantics():
    import time
    from rbg_tpu.native import NativeWorkQueue
    q = NativeWorkQueue()
    q.add(("ns", "a"))
    q.add(("ns", "a"))  # dedup
    q.add(("ns", "b"))
    assert q.get(0.1) == ("ns", "a")
    # re-add while processing → must be re-delivered after done()
    q.add(("ns", "a"))
    assert q.get(0.1) == ("ns", "b")
    q.done(("ns", "b"))
    assert q.get(0.05) is None  # 'a' still processing, not re-delivered yet
    q.done(("ns", "a"))
    assert q.get(0.1) == ("ns", "a")
    q.done(("ns", "a"))
    # delayed add
    t0 = time.monotonic()
    q.add_after(("ns", "c"), 0.15)
    assert q.get(1.0) == ("ns", "c")
    assert time.monotonic() - t0 >= 0.14
    q.shutdown()
    assert q.get(0.05) is None


def test_unique_per_replica_services_kep275():
    """KEP-275 UniquePerReplica: one headless service per RoleInstance
    (named after it, selecting only its pods); the shared role service is
    removed in steady state; discovery addresses use the per-instance
    subdomain. Admission rejects non-leaderWorker roles."""
    import yaml

    from rbg_tpu.api import constants as C
    from rbg_tpu.api.group import NetworkConfig
    from rbg_tpu.api.validation import ValidationError, validate_group
    from rbg_tpu.runtime.plane import ControlPlane
    from rbg_tpu.testutil import (make_group, make_tpu_nodes, simple_role,
                                  tpu_leaderworker_role)

    # Admission: standalone + UniquePerReplica rejected, never downgraded.
    bad = make_group("bad", simple_role("srv"))
    bad.spec.roles[0].network = NetworkConfig(
        subdomain_policy="UniquePerReplica")
    try:
        validate_group(bad)
        assert False, "expected rejection"
    except ValidationError:
        pass

    plane = ControlPlane(backend="fake")
    make_tpu_nodes(plane.store, slices=2, hosts_per_slice=2)
    with plane:
        role = tpu_leaderworker_role("serve", replicas=2, topology="2x4")
        role.network = NetworkConfig(subdomain_policy="UniquePerReplica")
        plane.apply(make_group("net", role))
        plane.wait_group_ready("net", timeout=15)

        def services_converged():
            svcs = {s.metadata.name: s
                    for s in plane.store.list("Service", namespace="default")}
            return (len(svcs) == 2
                    and C.service_name("net", "serve") not in svcs
                    and svcs) or None
        svcs = plane.wait_for(services_converged, timeout=10,
                              desc="per-replica services, shared gone")
        insts = plane.store.list("RoleInstance", namespace="default")
        assert sorted(svcs) == sorted(i.metadata.name for i in insts)
        for name, svc in svcs.items():
            assert svc.selector == {C.LABEL_INSTANCE_NAME: name}

        # Discovery addresses ride the per-instance subdomain.
        from rbg_tpu.discovery.config_builder import build_cluster_config
        cfg = build_cluster_config(
            plane.store, plane.store.get("RoleBasedGroup", "default", "net"))
        (role_out,) = cfg["roles"]
        for entry in role_out["instances"]:
            assert entry["subdomain"] == entry["name"]
            assert entry["coordinator"].startswith(
                f"{entry['name']}-0.{entry['name']}:")
            for h in entry["hosts"]:
                assert h["address"].endswith("." + entry["name"])

        # Scale down: the removed instance's service is GC'd.
        g = plane.store.get("RoleBasedGroup", "default", "net")
        g.spec.roles[0].replicas = 1
        plane.apply(g)
        plane.wait_for(
            lambda: len(plane.store.list("Service", namespace="default")) == 1,
            timeout=15, desc="scale-down removes per-replica service")
