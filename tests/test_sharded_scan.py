"""Topology-sharded feasibility scan (sched/scheduler.py + the
CapacityCache shard index): the sharded path must produce BIT-IDENTICAL
placements to the reference full scan on any fleet — shard pruning and
the free-bucket argmax are pure accelerations, never semantic changes.

The equivalence drills run seeded randomized fleets mixing plain singles,
constrained singles (selector/affinity), multi-host TPU gangs, pre-bound
pods, cordoned slices, and spare-pool-held slices, and compare the two
paths' plans after every churn step. A from-scratch index rebuild is
asserted equal to the incrementally maintained one at each step.
"""

import random

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.pod import NodeAffinityTerm, Pod
from rbg_tpu.runtime.store import Store
from rbg_tpu.sched.capacity import CapacityCache, SparePool
from rbg_tpu.sched.scheduler import SchedulerController
from rbg_tpu.testutil import make_tpu_nodes


def _single(name, selector=None, affinity=None, excl=None, group=""):
    p = Pod()
    p.metadata.name = name
    p.metadata.namespace = "default"
    if selector:
        p.template.node_selector.update(selector)
    if affinity:
        p.affinity.extend(affinity)
    if excl:
        p.metadata.annotations[C.ANN_EXCLUSIVE_TOPOLOGY] = excl
    if group:
        p.metadata.labels[C.LABEL_GROUP_NAME] = group
    return p


def _gang(inst, size, ordinal="0"):
    pods = []
    for i in range(size):
        p = Pod()
        p.metadata.name = f"{inst}-{ordinal}-{i}"
        p.metadata.namespace = "default"
        p.metadata.labels[C.LABEL_INSTANCE_NAME] = inst
        p.metadata.labels[C.LABEL_SLICE_ORDINAL] = ordinal
        p.metadata.labels[C.LABEL_COMPONENT_INDEX] = str(i)
        p.template.scheduler_hints["tpu-slice"] = "true"
        pods.append(p)
    return pods


def _mk_sched(store, spares=None):
    s = SchedulerController(store, spares=spares)
    s.cap.start()
    return s


def _both_plans(sched, store, pods):
    sharded = sched._place_inner(store, pods, sharded=True)
    full = sched._place_inner(store, pods, sharded=False)
    return sharded, full


def _assert_index_consistent(cap, store):
    fresh = CapacityCache(store)
    fresh.rebuild()
    with cap._lock, fresh._lock:
        assert cap._slices == fresh._slices
        assert cap._slice_placeable == fresh._slice_placeable
        assert cap._free_buckets == fresh._free_buckets


def test_plain_singles_equivalent():
    store = Store()
    make_tpu_nodes(store, slices=6, hosts_per_slice=3)
    sched = _mk_sched(store)
    pods = [store.create(_single(f"s{i}")) for i in range(5)]
    sharded, full = _both_plans(sched, store, pods)
    assert sharded == full and sharded is not None


def test_gang_prunes_shards_but_matches():
    store = Store()
    make_tpu_nodes(store, slices=8, hosts_per_slice=4)
    sched = _mk_sched(store)
    # Occupy two slices partially so their placeable bound drops below 4.
    for i, node in enumerate(["slice-0-host-0", "slice-1-host-1"]):
        p = _single(f"occ{i}")
        p.template.scheduler_hints["tpu-slice"] = "true"
        p.node_name = node
        store.create(p)
    gang = [store.create(p) for p in _gang("inst-a", 4)]
    sharded, full = _both_plans(sched, store, gang)
    assert sharded == full and sharded is not None
    # All four land on ONE slice, none of the partially occupied ones.
    sids = {store.get("Node", "default", n, copy_=False).tpu.slice_id
            for n in sharded.values()}
    assert len(sids) == 1
    assert sids & {"slice-0", "slice-1"} == set()


def test_cordoned_and_spare_held_slices_equivalent():
    store = Store()
    make_tpu_nodes(store, slices=5, hosts_per_slice=2)
    # Cordon one whole slice.
    for h in range(2):
        store.mutate("Node", "default", f"slice-2-host-{h}",
                     lambda n: setattr(n, "unschedulable", True) or True)
    spares = SparePool(1)
    sched = _mk_sched(store, spares=spares)
    spares.replenish(store)
    assert spares.held_slices()  # the pool actually reserved something
    pods = ([store.create(_single(f"s{i}")) for i in range(3)]
            + [store.create(p) for p in _gang("g1", 2)])
    sharded, full = _both_plans(sched, store, pods)
    assert sharded == full and sharded is not None
    for node in sharded.values():
        n = store.get("Node", "default", node, copy_=False)
        assert n.schedulable


def test_constrained_singles_equivalent():
    store = Store()
    make_tpu_nodes(store, slices=4, hosts_per_slice=3)
    sched = _mk_sched(store)
    pods = [
        store.create(_single("sel", selector={"tpu-slice": "slice-1"})),
        store.create(_single("aff", affinity=[NodeAffinityTerm(
            key="tpu-slice", operator="In", values=["slice-3"],
            required=False, weight=5)])),
        store.create(_single("req", affinity=[NodeAffinityTerm(
            key="tpu-slice", operator="NotIn", values=["slice-0"],
            required=True)])),
    ]
    sharded, full = _both_plans(sched, store, pods)
    assert sharded == full and sharded is not None
    assert full[("default", "sel")].startswith("slice-1-")
    assert full[("default", "aff")].startswith("slice-3-")
    assert not full[("default", "req")].startswith("slice-0-")


@pytest.mark.parametrize("seed", range(8))
def test_randomized_fleet_equivalence(seed):
    """Seeded random fleets + churn: plans identical at every step, and
    the incremental shard index never drifts from a fresh rebuild."""
    rng = random.Random(seed)
    store = Store()
    make_tpu_nodes(store, slices=rng.randint(4, 10),
                   hosts_per_slice=rng.randint(2, 4))
    # Random cordons.
    for n in store.list("Node", copy_=False):
        if rng.random() < 0.15:
            store.mutate("Node", "default", n.metadata.name,
                         lambda o: setattr(o, "unschedulable", True) or True)
    spares = SparePool(rng.choice([0, 1]))
    sched = _mk_sched(store, spares=spares)
    spares.replenish(store)

    created = []
    for step in range(4):
        batch = []
        for i in range(rng.randint(1, 3)):
            kind = rng.random()
            name = f"p{seed}-{step}-{i}"
            if kind < 0.5:
                batch.append(store.create(_single(name)))
            elif kind < 0.75:
                batch.append(store.create(_single(
                    name, affinity=[NodeAffinityTerm(
                        key="tpu-slice", operator="In",
                        values=[f"slice-{rng.randint(0, 3)}"],
                        required=False, weight=rng.randint(1, 3))])))
            else:
                batch.extend(store.create(p) for p in _gang(
                    name, rng.randint(2, 3)))
        sharded, full = _both_plans(sched, store, batch)
        assert sharded == full, f"seed={seed} step={step}"
        # Commit the plan (as _bind would) so later steps see real churn.
        if full:
            for (ns, pname), node in full.items():
                obj = store.mutate(
                    "Pod", ns, pname,
                    lambda p, node=node: (setattr(p, "node_name", node)
                                          or True))
                sched.cap.apply_bind(obj)
                created.append((ns, pname))
        # Random deletes release capacity.
        if created and rng.random() < 0.5:
            ns, pname = created.pop(rng.randrange(len(created)))
            store.delete("Pod", ns, pname)
        _assert_index_consistent(sched.cap, store)


def test_stale_node_event_never_overwrites_newer_state():
    """_on_node enforces the same rv ordering _apply gives pods: the
    watch-resume replay path deliberately redelivers, and a stale
    'uncordoned' snapshot landing after the cordon must not hand the
    sharded scan a node the store says is unschedulable."""
    from rbg_tpu.runtime.store import Event
    store = Store()
    make_tpu_nodes(store, slices=1, hosts_per_slice=2)
    cap = CapacityCache(store)
    cap.start()
    stale = store.get("Node", "default", "slice-0-host-0")  # pre-cordon
    store.mutate("Node", "default", "slice-0-host-0",
                 lambda n: setattr(n, "unschedulable", True) or True)
    assert all(n.metadata.name != "slice-0-host-0"
               for n in cap.placeable_nodes())
    # Redeliver the stale pre-cordon snapshot (replay / late dispatch).
    cap._on_node(Event(Event.MODIFIED, stale))
    assert all(n.metadata.name != "slice-0-host-0"
               for n in cap.placeable_nodes())
    with cap._lock:
        assert cap._slice_placeable.get("slice-0") == 1
    # A DELETED tombstone blocks pre-delete stragglers too.
    pre_delete = store.get("Node", "default", "slice-0-host-1")
    store.delete("Node", "default", "slice-0-host-1")
    cap._on_node(Event(Event.MODIFIED, pre_delete))
    with cap._lock:
        assert "slice-0-host-1" not in cap._nodes


def test_shard_index_tracks_cordon_and_capacity_churn():
    store = Store()
    make_tpu_nodes(store, slices=3, hosts_per_slice=2)
    cap = CapacityCache(store)
    cap.start()
    with cap._lock:
        assert cap._slice_placeable == {"slice-0": 2, "slice-1": 2,
                                        "slice-2": 2}
    store.mutate("Node", "default", "slice-1-host-0",
                 lambda n: setattr(n, "unschedulable", True) or True)
    with cap._lock:
        assert cap._slice_placeable["slice-1"] == 1
    # A slice pod consumes the host's placeable-ness entirely.
    p = Pod()
    p.metadata.name = "g"
    p.metadata.namespace = "default"
    p.template.scheduler_hints["tpu-slice"] = "true"
    p.node_name = "slice-0-host-1"
    store.create(p)
    with cap._lock:
        assert cap._slice_placeable["slice-0"] == 1
    store.delete("Pod", "default", "g")
    with cap._lock:
        assert cap._slice_placeable["slice-0"] == 2
    _assert_index_consistent(cap, store)
