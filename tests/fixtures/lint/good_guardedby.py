"""Known-good corpus for the guarded-by rule: lexical `with`, helpers
proven lock-held through the call-graph fixpoint (any depth), helpers that
acquire the lock themselves, __init__ writes, and guarded module globals."""

from rbg_tpu.utils.locktrace import named_lock, named_rlock

_glock = named_lock("fixture.good_module")
_singleton = None  # guarded_by[fixture.good_module]


def set_singleton(v):
    global _singleton
    with _glock:
        _singleton = v


def get_singleton():
    with _glock:
        return _singleton


class Cache:
    def __init__(self):
        self._lock = named_rlock("fixture.good_cache")
        self._items = {}  # guarded_by[fixture.good_cache]
        # guarded_by[fixture.good_cache]
        self._count = 0

    def put(self, k, v):
        with self._lock:
            self._insert(k, v)

    def _insert(self, k, v):
        # Lock-held helper: every call site holds the lock.
        self._items[k] = v
        self._bump()

    def _bump(self):
        # Two levels deep: caller (_insert) is itself lock-held.
        self._count += 1

    def snapshot(self):
        with self._lock:
            return dict(self._items), self._count

    def size(self):
        with self._lock:
            return len(self._items)
