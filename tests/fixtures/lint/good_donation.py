"""Known-good fixture for donation-safety: donated references rebound
before reuse (name rebind and the prefix-kill cache rebind), the warm
loops' multi-line call-then-rebind idiom, and non-donated programs."""

import jax

_PROGRAMS = {}


def _step(x, pages):
    return x + pages, pages


def _get_step(n):
    fn = _PROGRAMS.get(n)
    if fn is None:
        fn = _PROGRAMS[n] = jax.jit(_step, donate_argnums=(1,))
    return fn


def _get_plain(n):
    fn = _PROGRAMS.get(("plain", n))
    if fn is None:
        fn = _PROGRAMS[("plain", n)] = jax.jit(_step)
    return fn


def rebind_then_reuse(x, pages):
    fn = _get_step(4)
    out, fresh = fn(x, pages)
    pages = fresh          # rebind: the name points at live data again
    return out, pages.sum()


def multiline_call_then_rebind(x, pages):
    fn = _get_step(8)
    out, fresh = fn(
        x,
        pages,
    )
    pages = fresh
    return out, pages


def plain_program(x, pages):
    fn = _get_plain(4)
    out = fn(x, pages)
    return out, pages.sum()   # nothing donated: reuse is fine


class Cache:
    def __init__(self, pages):
        self.pages = pages


class Pool:
    def __init__(self, cache):
        self.cache = cache
        self._fns = {}

    def _get_promote(self, n):
        fn = self._fns.get(n)
        if fn is None:
            fn = self._fns[n] = jax.jit(_step, donate_argnums=(1,))
        return fn

    def promote(self, x):
        fn = self._get_promote(2)
        out, new_pages = fn(x, self.cache.pages)
        self.cache = Cache(new_pages)
        return self.cache.pages.sum()   # the prefix rebind revived the chain
