"""Known-bad fixture: span-name violations at tracer call sites."""
from rbg_tpu.obs import names, trace
from rbg_tpu.obs.trace import start_trace


def handle(parent):
    root = trace.start_trace("router.reqest")          # BAD: typo/unregistered
    sp = trace.child("service.queue_waits")            # BAD: unregistered
    trace.from_wire({}, "engine.opp")                  # BAD: name is arg 2
    trace.ingress_span("HTTP.Request")                 # BAD: naming contract
    other = start_trace("pd.prefil")                   # BAD: from-import form
    parent.child("router.atempt")                      # BAD: method call site
    root.end()
    sp.end()
    return other
