"""Known-bad corpus for stale-allow: suppressions whose rule no longer
fires on the covered line must themselves be findings."""


def fixed_long_ago():
    x = 1  # lint: allow[deadline-hygiene] the mint this excused was removed  # BAD
    return x


def fixed_too():
    # lint: allow[blocking-in-critical-section] sleep was moved out  # BAD
    y = 2
    return y
