"""Known-bad fixture: metric-name violations at REGISTRY call sites."""
from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import REGISTRY


def record():
    REGISTRY.inc("rbg_serving_sheds_total")          # BAD: typo/unregistered
    REGISTRY.inc("rbg_serving_queue_depth")          # BAD: histogram via inc
    REGISTRY.set_gauge("rbg_reconcile_total", 1.0)   # BAD: counter as gauge
    REGISTRY.observe("rbg_serving_draining", 1.0)    # BAD: gauge observed
    REGISTRY.observe(names.SERVING_SHED_TOTAL, 1.0)  # BAD: constant, wrong kind
