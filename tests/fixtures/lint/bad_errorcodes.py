"""Known-bad fixture: error-code literals missing from api/errors.py."""


class MistypedReject(RuntimeError):
    code = "overladed"  # BAD: typo, not in catalog


def to_wire(msg):
    return {"error": msg, "code": "drainning"}  # BAD: typo dict value


def mark(frame):
    frame["code"] = "deadline_exceded"  # BAD: typo assignment
    return frame


def route(resp):
    if resp.get("code") == "over_loaded":  # BAD: typo comparison
        return "retry"
    return "fail"


def build(make_error):
    return make_error("boom", code="not_in_catalog")  # BAD: unknown code
