"""Known-bad fixture for the op-registry rule: dispatch arms and client
frame constructions naming ops that ``rbg_tpu/api/ops.py`` does not
catalog. Every BAD-marked line must be flagged."""


def handle(sock, send_msg, obj):
    op = obj.get("op")
    if op == "frobnicate":  # BAD: dispatch arm for an uncataloged op
        send_msg(sock, {"ok": True})
        return
    if op == "generate":    # cataloged — clean
        send_msg(sock, {"tokens": []})
        return
    send_msg(sock, {"error": f"unsupported op {op!r}"})


def client(send_msg, sock):
    send_msg(sock, {"op": "mystery_op"})  # BAD: constructs an uncataloged op
    send_msg(sock, {"op": "health"})      # cataloged — clean
