"""Known-good fixture: cataloged span names, constants and literals."""
from rbg_tpu.obs import names, trace


def handle(parent, tree):
    root = trace.start_trace(names.SPAN_ROUTER_REQUEST)     # constant: ok
    sp = trace.child("service.queue_wait")                  # cataloged literal
    trace.from_wire({}, names.SPAN_ENGINE_OP, op="generate")
    trace.ingress_span("http.request", traceparent=None)
    attempt = parent.child(names.SPAN_ROUTER_ATTEMPT)       # method call site
    tree.child("section")          # non-span .child(): not a dotted name, ok
    attempt.end()
    sp.end()
    return root
