"""Known-bad fixture: fresh deadline minting (the PR-2 invariant break)."""
import time as _time

RETRY_BUDGET_S = 30.0


def handler(request):
    deadline = _time.monotonic() + 30.0  # BAD: fresh literal deadline
    return run(request, deadline)


def retry(request):
    deadline = _time.time() + RETRY_BUDGET_S  # BAD: fresh constant deadline
    return run(request, deadline)


def submit(service, prompt):
    return service.submit(prompt, deadline=_time.monotonic() + 5.0)  # BAD


def annotated(request):
    deadline: float = _time.monotonic() + 10.0  # BAD: AnnAssign mint
    return run(request, deadline)


def tupled(request):
    req, deadline = request, _time.monotonic() + 2.5  # BAD: tuple-target mint
    return run(req, deadline)


def run(request, deadline):
    return (request, deadline)
