"""Known-good fixture: cataloged names used under their own kind."""
from rbg_tpu.obs import names
from rbg_tpu.obs.metrics import REGISTRY


def record(duration):
    REGISTRY.inc(names.SERVING_SHED_TOTAL, reason="queue_full")
    REGISTRY.inc("rbg_serving_shed_total")           # cataloged literal: ok
    REGISTRY.set_gauge(names.SERVING_DRAINING, 1.0)
    REGISTRY.observe(names.RECONCILE_DURATION_SECONDS, duration)
    REGISTRY.inc("other_system_total")               # non-rbg_ namespace: ok
