"""Known-good corpus for stale-allow: a justified allow that still
suppresses a live finding is NOT stale."""

import time as _t


def ingress():
    deadline = _t.monotonic() + 3.0  # lint: allow[deadline-hygiene] ingress stamp example (fixture)
    return deadline
