"""Known-bad fixture: blocking calls inside critical sections + unbounded
joins. Every marked line MUST be flagged by blocking-in-critical-section."""
import socket
import subprocess
import threading
import time

_lock = threading.Lock()


def sleeps_under_lock():
    with _lock:
        time.sleep(0.5)  # BAD: sleep in critical section


def subprocess_under_lock(self):
    with self._lock:
        subprocess.run(["true"])  # BAD: subprocess in critical section


def io_under_lock(self, addr):
    with self.state.lock:
        socket.create_connection(addr, timeout=1)  # BAD: connect under lock


def join_under_lock(t):
    with _lock:
        t.join()  # BAD: thread join in critical section (and unbounded)


def unbounded_join(t):
    t.join()  # BAD: no timeout


def connect_no_timeout(addr):
    return socket.create_connection(addr)  # BAD: no timeout
