"""Known-good fixture for jit-hygiene: metadata reads, cache-seam program
fetches, host math on host values, and forcers confined to functions no
hot-path root ever reaches."""

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("fixture")

_PROGRAMS = {}


def _kernel(x):
    return x * 2


def _get_step(b):
    # The cache seam: construction here is legal even though the hot
    # root reaches this function — programs are fetched, not rebuilt.
    fn = _PROGRAMS.get(b)
    if fn is None:
        fn = _PROGRAMS[b] = jax.jit(_kernel)
    return fn


# hot_path
def serve_step(batch, state):
    fn = _get_step(len(batch))
    y = fn(state)
    rows = y.shape[0]            # metadata read, not a sync
    width = float(rows)          # host int -> float: no device value involved
    log.info("dispatched %d rows", rows)
    emitted = jnp.where(y > 0, y, 0)
    # lint: allow[jit-hygiene] the step's one intrinsic emission fetch for the fixture
    return np.asarray(emitted), width


def drain_and_report(y):
    # Not reachable from any hot root: forcers are fine here.
    time.sleep(0.001)
    host = np.asarray(y)
    return float(host[0])
