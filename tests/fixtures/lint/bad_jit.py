"""Known-bad corpus for jit-hygiene: every marked line must be flagged —
forcers on device values, per-request program construction, sleeps and
device-value logging, both in the hot root itself and in helpers only
reachable through the call chain."""

import logging
import time

import jax
import jax.numpy as jnp
import numpy as np

log = logging.getLogger("fixture")


def _kernel(x):
    return x * 2


# hot_path
def serve_step(batch):
    x = jnp.zeros((4,))
    first = float(x[0])  # BAD float() on a device value forces a host sync
    arr = np.asarray(x)  # BAD np.asarray on a device value forces a host sync
    fn = jax.jit(_kernel)  # BAD program built per request, not via a seam
    y = fn(x)
    y.block_until_ready()  # BAD explicit device sync on the hot path
    host = jax.device_get(y)  # BAD device_get on the hot path
    log.info("step result %s", y)  # BAD logging interpolates a device value
    _stage_one(y)
    return first, arr, host


def _stage_one(y):
    _stage_two(y)


def _stage_two(y):
    time.sleep(0.001)  # BAD sleep, serve_step -> _stage_one -> _stage_two
    z = jnp.ones(2)
    return z.item()  # BAD .item() in a transitively-hot helper


class Worker:
    def __init__(self):
        self.cache = None
        self.fn = jax.jit(_kernel)  # fine: init-time construction, not hot

    # hot_path
    def inject(self, tokens):
        pages = np.asarray(self.cache.k_pages)  # BAD KV slab fetch is a sync
        self._refresh()
        return pages

    def _refresh(self):
        self.fn = jax.jit(_kernel)  # BAD rebuilt via inject -> _refresh
