"""Known-good fixture: codes imported from the canonical catalog."""
from rbg_tpu.api.errors import CODE_DRAINING, CODE_OVERLOADED


class Shed(RuntimeError):
    code = CODE_OVERLOADED                       # constant, not literal


def to_wire(msg):
    return {"error": msg, "code": CODE_DRAINING}


def route(resp):
    if resp.get("code") == CODE_OVERLOADED:
        return "retry"
    # Comparing against a cataloged literal is legal too (the registry
    # exists to catch drift, not to ban the strings).
    if resp.get("code") == "draining":
        return "sibling"
    return "fail"


def http_status(code):
    return {"status": 429} if code == 429 else {}   # ints are not codes
