"""Known-good fixture: the legal shapes the blocking rule must NOT flag."""
import socket
import threading
import time

_lock = threading.Lock()


def io_outside_lock(addr):
    with _lock:
        snapshot = list(range(3))
    time.sleep(0.01)                                  # outside the lock
    return socket.create_connection(addr, timeout=5), snapshot


def bounded_join(t):
    t.join(timeout=2.0)                               # bounded


def str_join(parts):
    return ", ".join(parts)                           # str.join has an arg


def deferred_work_under_lock():
    with _lock:
        def later():
            time.sleep(1.0)                           # runs OUTSIDE the lock
        return later


def justified(t):
    with _lock:
        t.join()  # lint: allow[blocking-in-critical-section] example justified suppression for the allowlist test
