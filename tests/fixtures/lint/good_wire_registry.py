"""Known-good twin of bad_wire_registry.py: every dispatch arm and every
client frame construction names a cataloged op — including through the
``api/ops.py`` constants, which the rule resolves like literals."""

from rbg_tpu.api.ops import OP_HEALTH, OP_METRICS


def handle(sock, send_msg, obj):
    op = obj.get("op")
    if op == OP_HEALTH:         # constant from the catalog — clean
        send_msg(sock, {"ok": True})
        return
    if op == "generate":        # literal, cataloged — clean
        send_msg(sock, {"tokens": []})
        return
    send_msg(sock, {"error": f"unsupported op {op!r}"})


def client(send_msg, sock):
    send_msg(sock, {"op": OP_METRICS})
    send_msg(sock, {"op": "slo"})
