"""Known-good fixture: legal deadline derivation (the PR-2 pattern)."""
import time


def derive(request, deadline):
    remaining = deadline - time.monotonic()          # derive, don't mint
    return min(remaining, 5.0)


def from_wire(obj):
    t = obj.get("timeout_s")
    return None if t is None else time.monotonic() + float(t)


def hop(service, prompt, deadline):
    return service.submit(prompt, deadline=deadline)  # propagate verbatim


def ingress(request):
    # lint: allow[deadline-hygiene] example ingress stamp for the allowlist test
    deadline = time.monotonic() + 30.0
    return (request, deadline)
