"""Known-good twin of bad_wire_codes.py: every error code a handler
replies with is declared for its op in ``api/ops.py`` — resolved both
from literals and from the ``api/errors.py`` constants."""

from rbg_tpu.api.errors import CODE_DEADLINE, CODE_OVERLOADED


def handle(sock, send_msg, obj):
    op = obj.get("op")
    if op == "generate":
        send_msg(sock, {"error": "shed", "code": CODE_OVERLOADED,
                        "retry_after_s": 0.5})
        send_msg(sock, {"error": "too slow", "code": CODE_DEADLINE,
                        "done": True})
        send_msg(sock, {"error": "kv pull failed",
                        "code": "kv_stream_failed", "done": True})
        return
    send_msg(sock, {"error": f"unsupported op {op!r}"})
