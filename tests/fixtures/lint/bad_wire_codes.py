"""Known-bad fixture for the error-code-flow rule: handlers replying
with error codes their op does not declare in ``api/ops.py`` (or that no
op declares at all). Every BAD-marked line must be flagged."""


def handle(sock, send_msg, obj):
    op = obj.get("op")
    if not op:
        send_msg(sock, {"error": "x", "code": "not_a_code"})  # BAD: no op declares this
        return
    if op == "generate":
        send_msg(sock, {"error": "y", "code": "quantum_flux_inverted"})  # BAD: not in catalog
        send_msg(sock, {"error": "kv pull failed",
                        "code": "kv_stream_failed"})  # declared for generate — clean
        return
    if op == "health":
        send_msg(sock, {"error": "busy", "code": "overloaded"})  # BAD: health declares none
        return
