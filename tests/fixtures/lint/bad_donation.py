"""Known-bad corpus for donation-safety: references passed in donated
positions reused after the call without a rebind — via a donated getter,
a direct jax.jit site, and the conditional-donation idiom."""

import jax

_PROGRAMS = {}


def _step(x, pages):
    return x + pages, pages


def _step4(x, a, b, c):
    return x, a, b, c


def _get_step(n):
    fn = _PROGRAMS.get(n)
    if fn is None:
        fn = _PROGRAMS[n] = jax.jit(_step, donate_argnums=(1,))
    return fn


def _get_cond(n, quantized):
    fn = _PROGRAMS.get((n, quantized))
    if fn is None:
        donate = (2,) if quantized else (2, 3)
        fn = _PROGRAMS[(n, quantized)] = jax.jit(
            _step4, donate_argnums=donate)
    return fn


def reuse_via_getter(x, pages):
    fn = _get_step(4)
    out, new_pages = fn(x, pages)
    total = pages.sum()  # BAD pages was donated at the call above
    return out, total


def reuse_direct(x, pages):
    fn = jax.jit(_step, donate_argnums=(1,))
    out = fn(x, pages)
    return out, pages + 1  # BAD donated buffer read again


def reuse_conditional(x, a, b, c):
    fn = _get_cond(2, True)
    out = fn(x, a, b, c)
    return out, b * 2, c * 2  # BAD both conditionally-donated buffers dead
