"""Known-bad fixture: threads neither daemonized nor provably joined."""
import threading


def fire_and_forget(fn):
    threading.Thread(target=fn).start()  # BAD: unbound, non-daemon


def leaked_local(fn):
    t = threading.Thread(target=fn)  # BAD: started, never joined
    t.start()
    return True


class Service:
    def start(self, fn):
        self._worker = threading.Thread(target=fn)  # BAD: no stop() joins it
        self._worker.start()

    def poke(self):
        return self._worker.is_alive()
