"""Known-good twin of bad_wire_fields.py: every handler read, reply key,
client construction and client reply read stays inside the fields the
``api/ops.py`` catalog declares (universal request fields and the error
reply envelope included)."""


def handle(sock, send_msg, obj):
    op = obj.get("op")
    if op == "generate":
        prompt = obj.get("prompt")
        deadline = obj.get("timeout_s")     # universal request field
        send_msg(sock, {"tokens": [1], "ttft_s": 0.5})
        return prompt, deadline
    if op == "prefill":
        send_msg(sock, {"prompt": [], "first_token": 0,
                        "shape": [1, 4], "dtype": "float32"})
        return
    send_msg(sock, {"error": f"unsupported op {op!r}"})


def client(send_msg, request_once, sock):
    send_msg(sock, {"op": "generate", "prompt": [1], "timeout_s": 5})
    resp, _, _ = request_once("10.0.0.1:1", {"op": "generate", "prompt": [1]})
    if resp.get("error"):                   # error envelope — declared
        return None, resp.get("retry_after_s")
    return resp.get("tokens"), resp.get("ttft_s")
