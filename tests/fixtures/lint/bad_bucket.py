"""Known-bad corpus for bucket-discipline: raw shape values reaching a
jitted program's identity — getter arguments on the hot path and cache
keys inside the seam itself."""

import jax

_PROGRAMS = {}


def _kernel(x):
    return x


def _get_fn(n):
    fn = _PROGRAMS.get(n)
    if fn is None:
        fn = _PROGRAMS[n] = jax.jit(_kernel)
    return fn


def _get_raw_keyed(batch):
    key = len(batch)
    fn = _PROGRAMS.get(key)  # BAD raw cache key selects the program
    if fn is None:
        fn = _PROGRAMS[key] = jax.jit(_kernel)
    return fn


# hot_path
def serve(prompts, state):
    b = len(prompts)
    fn = _get_fn(b)  # BAD raw batch size into the getter
    t = max(len(p) for p in prompts)
    fn2 = _get_fn(t + 1)  # BAD raw token-count arithmetic into the getter
    rows = state.shape[0]
    fn3 = _get_fn(rows)  # BAD .shape flows into the program identity
    return fn(state), fn2(state), fn3(state)
