"""Known-good fixture: daemonized, joined-in-function, and joined-by-stop
thread lifecycles."""
import threading


def daemonized(fn):
    threading.Thread(target=fn, daemon=True).start()


def daemon_via_attr(fn):
    t = threading.Thread(target=fn)
    t.daemon = True
    t.start()


def scoped(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=5.0)


def fanout(fn, n):
    threads = [threading.Thread(target=fn) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=5.0)


class Service:
    def __init__(self):
        self._threads = []

    def start(self, fn):
        self._worker = threading.Thread(target=fn)
        self._worker.start()
        t = threading.Thread(target=fn)
        t.start()
        self._threads.append(t)

    def stop(self):
        self._worker.join(timeout=2.0)
        for t in self._threads:
            t.join(timeout=2.0)
