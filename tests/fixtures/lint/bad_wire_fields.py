"""Known-bad fixture for the field-discipline rule: handler reads of
undeclared request fields, replies carrying undeclared keys, client
constructions sending undeclared or omitting required fields, and client
reads of undeclared reply keys. Every BAD-marked line must be flagged.

The ``n_pages`` prefill arm is the real drift class this rule exists
for: the bundle header replaced ``n_pages`` with ``shape``/``dtype``
(``protocol.bundle_to_wire``), and a stub still speaking the old shape
rode the wire silently until the catalog pinned the contract."""


def handle(sock, send_msg, obj):
    op = obj.get("op")
    if op == "generate":
        prompt = obj.get("prompt")          # declared — clean
        speed = obj.get("warp_factor")      # BAD: undeclared request field
        send_msg(sock, {"tokens": [1], "addr": "10.0.0.1:1"})  # BAD: undeclared reply key
        return prompt, speed
    if op == "prefill":
        n = obj.get("n_pages")              # BAD: stale pre-shape/dtype bundle field
        send_msg(sock, {"prompt": [], "first_token": 0,
                        "shape": [1, 4], "dtype": "float32"})
        return n


def client(send_msg, request_once, sock):
    send_msg(sock, {"op": "generate", "prompt": [1], "volume": 11})  # BAD: undeclared request field
    send_msg(sock, {"op": "generate"})  # BAD: omits required field 'prompt'
    resp, _, _ = request_once("10.0.0.1:1", {"op": "generate", "prompt": [1]})
    tokens = resp.get("tokens")             # declared — clean
    where = resp.get("addr")                # BAD: reads undeclared reply key
    return tokens, where
