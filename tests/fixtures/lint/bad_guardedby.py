"""Known-bad corpus for the guarded-by rule: every marked line must be
flagged (direct unlocked access, helper reached without the lock, module
global outside the module lock, unverifiable annotation)."""

from rbg_tpu.utils.locktrace import named_lock

_glock = named_lock("fixture.module")
_registry = {}  # guarded_by[fixture.module]


def module_reader():
    return len(_registry)  # BAD module global read without fixture.module


class Cache:
    def __init__(self):
        self._lock = named_lock("fixture.cache")
        self._items = {}  # guarded_by[fixture.cache]
        self._count = 0  # guarded_by[fixture.cache]

    def get(self, k):
        return self._items.get(k)  # BAD direct access outside the lock

    def put(self, k, v):
        with self._lock:
            self._items[k] = v
        self._count += 1  # BAD write after the with block closed

    def _bump(self):
        self._count += 1  # BAD helper reached from an unlocked caller

    def public_bump(self):
        self._bump()


class Orphan:
    def __init__(self):
        self._weird = {}  # guarded_by[missing.lock] # BAD lock never built here
