"""Known-good fixture for bucket-discipline: every shape value is
laundered through a registered # bucket_fn helper before it touches
program identity; cold paths may size things freely."""

import jax

_PROGRAMS = {}


def _kernel(x):
    return x


# bucket_fn
def _fixture_bucket(n):
    m = 1
    while m < n:
        m *= 2
    return m


def _get_fn(n):
    fn = _PROGRAMS.get(n)
    if fn is None:
        fn = _PROGRAMS[n] = jax.jit(_kernel)
    return fn


# hot_path
def serve(prompts, state):
    b = _fixture_bucket(len(prompts))
    fn = _get_fn(b)
    t = _fixture_bucket(max(len(p) for p in prompts))
    return fn(state), _get_fn(t)(state)


def admin_resize(pool, n):
    # Cold path: no hot_path root reaches this, raw sizes are fine.
    return pool.resize(len(pool.items) + n)
