"""Subprocess e2e scenario matrix (reference: the kind-cluster ginkgo suite,
``test/e2e/e2e_test.go:30-96`` — update_strategy, convergence,
shared_service_selection, port_allocator, warmup, coordinated_policy,
webhook_validation, inplace, restart stability, roletemplate...).

Every scenario here drives the SHIPPED binary path: a ``rbg-tpu serve``
subprocess (plane + scheduler + fake kubelet + admin API) spoken to over the
admin wire protocol — nothing reaches into plane internals. The plane-kill
convergence scenario additionally exercises the state-file resume path.
"""

import json
import os
import signal
import socket
import subprocess
import sys
import time

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api import serde
from rbg_tpu.api.group import RoleBasedGroupSet
from rbg_tpu.engine.protocol import request_once
from rbg_tpu.testutil import make_group, simple_role, tpu_leaderworker_role

pytestmark = pytest.mark.e2e


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


class ServedPlane:
    """A real ``rbg-tpu serve`` subprocess + admin-wire client."""

    def __init__(self, state_file=None, token="e2e-token", slices=4, hosts=2):
        self.port = _free_port()
        self.token = token
        self.state_file = state_file
        self.slices, self.hosts = slices, hosts
        self.proc = None

    def start(self, timeout=90):
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env.pop("RBG_ADMIN_TOKEN", None)
        cmd = [sys.executable, "-m", "rbg_tpu.cli.main", "serve",
               "--backend", "fake", "--admin-port", str(self.port),
               "--slices", str(self.slices), "--hosts", str(self.hosts),
               "--admin-token", self.token]
        if self.state_file:
            cmd += ["--state-file", self.state_file]
        self.proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                                     stderr=subprocess.STDOUT, text=True)
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                resp, _, _ = request_once(f"127.0.0.1:{self.port}",
                                          {"op": "health"}, timeout=2.0)
                if resp and resp.get("ok"):
                    return self
            except OSError:
                pass
            if self.proc.poll() is not None:
                out = self.proc.stdout.read()
                raise RuntimeError(f"serve died rc={self.proc.returncode}:\n{out}")
            time.sleep(0.2)
        raise TimeoutError("serve did not come up")

    def stop(self):
        if self.proc and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGINT)
            try:
                self.proc.wait(15)
            except subprocess.TimeoutExpired:
                self.proc.kill()

    def kill9(self):
        self.proc.kill()
        self.proc.wait(10)

    # ---- wire client ----

    def call(self, **obj):
        obj.setdefault("token", self.token)
        resp, _, _ = request_once(f"127.0.0.1:{self.port}", obj, timeout=30.0)
        assert resp is not None, "admin closed connection"
        return resp

    def ok(self, **obj):
        resp = self.call(**obj)
        assert "error" not in resp, resp
        return resp

    def apply(self, manifest):
        if not isinstance(manifest, dict):
            manifest = dict(serde.to_dict(manifest), kind=manifest.kind)
        return self.ok(op="apply", manifest=manifest)

    def wait(self, fn, timeout=60, desc="condition"):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            try:
                v = fn()
            except AssertionError:
                v = None
            if v:
                return v
            time.sleep(0.2)
        raise TimeoutError(f"e2e timed out waiting for {desc}")

    def wait_ready(self, name, timeout=90):
        return self.wait(
            lambda: (lambda st: st if st.get("ready") else None)(
                self.call(op="status", name=name)),
            timeout=timeout, desc=f"group {name} ready")

    def pods(self, group):
        return self.call(op="status", name=group).get("pods", [])

    def get(self, kind, name):
        r = self.call(op="get", kind=kind, name=name)
        return r.get("object")


@pytest.fixture(scope="module")
def plane():
    p = ServedPlane().start()
    yield p
    p.stop()


# ---- scenario 1: update_strategy (surge x partition over the wire) ----

def test_update_strategy_partition_and_surge(plane):
    g = make_group("us", simple_role("srv", replicas=3, image="engine:v1"))
    g.spec.roles[0].rolling_update.max_surge = 1
    g.spec.roles[0].rolling_update.partition = 2
    g.spec.roles[0].rolling_update.in_place_if_possible = False
    plane.apply(g)
    plane.wait_ready("us")

    g = serde.from_dict(type(g), plane.get("RoleBasedGroup", "us"))
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.apply(g)

    def partitioned():
        ris = plane.get("RoleInstanceSet", "us-srv")
        st = ris.get("status", {})
        return (st.get("updatedReadyReplicas") == 1
                and st.get("readyReplicas", 0) >= 3)
    plane.wait(partitioned, desc="only ordinal >= partition updated")
    # The partition must HOLD: rather than a fixed sleep (flaky on slow
    # CI), require N consecutive observations at exactly 1 updated-ready.
    # More than 1 means the partition broke — fail immediately; fewer
    # (a readiness flap) resets the stability counter.
    stable = 0
    deadline = time.monotonic() + 10.0
    while stable < 5:
        assert time.monotonic() < deadline, "partition stability poll timeout"
        ris = plane.get("RoleInstanceSet", "us-srv")
        # serde drops default-valued fields: absent == 0 (a flap).
        updated = ris["status"].get("updatedReadyReplicas", 0)
        assert updated <= 1, "partition must hold the rollout"
        stable = stable + 1 if updated == 1 else 0
        time.sleep(0.1)

    g = serde.from_dict(type(g), plane.get("RoleBasedGroup", "us"))
    g.spec.roles[0].rolling_update.partition = 0
    plane.apply(g)
    plane.wait(
        lambda: plane.get("RoleInstanceSet", "us-srv")["status"]
        .get("updatedReadyReplicas") == 3,
        desc="open partition rolls everyone")
    plane.wait_ready("us")


# ---- scenario 2: admission rejects (webhook_validation analog) ----

def test_admission_rejects_bad_manifests(plane):
    dup = serde.to_dict(make_group("bad", simple_role("a"), simple_role("a")))
    r = plane.call(op="apply", manifest=dict(dup, kind="RoleBasedGroup"))
    assert "error" in r and "duplicated" in r["error"]

    bad_id = serde.to_dict(make_group("bad2", simple_role("a")))
    bad_id["spec"]["roles"][0]["identity"] = "Random"  # misspelled
    r = plane.call(op="apply", manifest=dict(bad_id, kind="RoleBasedGroup"))
    assert "error" in r and "IdentityMode" in r["error"]

    typo = serde.to_dict(make_group("bad3", simple_role("a")))
    typo["spec"]["rolez"] = []  # unknown key = strict-parse error
    r = plane.call(op="apply", manifest=dict(typo, kind="RoleBasedGroup"))
    assert "error" in r

    assert plane.get("RoleBasedGroup", "bad") is None


# ---- scenario 3: v1alpha1 manifest converts live ----

def test_v1alpha1_manifest_served(plane):
    doc = serde.to_dict(make_group("legacy", simple_role("srv", replicas=2)))
    doc = dict(doc, kind="RoleBasedGroup",
               apiVersion="rbg.tpu.x-k8s.io/v1alpha1")
    doc["spec"]["roles"][0].pop("identity", None)
    doc["spec"]["roles"][0]["stateful"] = False
    plane.apply(doc)
    plane.wait_ready("legacy")
    g = plane.get("RoleBasedGroup", "legacy")
    assert g["spec"]["roles"][0]["identity"] == "random"
    # stateless instances got random ids, not ordinals
    names = [p["name"] for p in plane.pods("legacy")]
    assert names and all(not n.rsplit("-", 1)[-1].isdigit() for n in names)


# ---- scenario 4: shared_service_selection LeaderOnly (KEP-260) ----

def test_shared_service_selection_leader_only(plane):
    role = tpu_leaderworker_role("tp", replicas=1, topology="2x4")
    role.service_selection = "LeaderOnly"
    plane.apply(make_group("svc-sel", role))
    plane.wait_ready("svc-sel")
    svc = plane.get("Service", "s-svc-sel-tp")
    assert svc is not None and svc.get("leaderOnly") is True
    pods = plane.pods("svc-sel")
    assert len(pods) == 2  # leader + worker on a 2-host slice


# ---- scenario 5: port allocator (KEP-171) ----

def test_port_allocator_roundtrip(plane):
    g = make_group("ports", simple_role("srv", replicas=1))
    g.spec.roles[0].template.annotations = {
        C.ANN_PORT_ALLOCATOR: json.dumps([{"name": "dist", "scope": "role"}]),
    }
    plane.apply(g)
    plane.wait_ready("ports")
    ris = plane.get("RoleInstanceSet", "ports-srv")
    alloc = ris["metadata"].get("annotations", {}).get(C.ANN_ALLOCATED_PORTS)
    assert alloc, "role-scoped port not persisted on the RIS"
    assert json.loads(alloc)


# ---- scenario 6: warmup jobs (KEP-129) ----

def test_warmup_completes_on_group_nodes(plane):
    plane.apply(make_group("wsvc", simple_role("srv", replicas=2)))
    plane.wait_ready("wsvc")
    from rbg_tpu.api.policy import Warmup
    w = Warmup()
    w.metadata.name = "prime"
    w.spec.target.group_name = "wsvc"
    plane.apply(dict(serde.to_dict(w), kind="Warmup"))
    plane.wait(
        lambda: (plane.get("Warmup", "prime").get("status", {})
                 .get("succeededNodes", 0)) >= 1,
        desc="warmup succeeded on the group's nodes")


# ---- scenario 7: coordinated_policy maxSkew scaling ----

def test_coordinated_policy_staged_scaling(plane):
    from rbg_tpu.api.policy import (
        CoordinatedPolicy, CoordinatedPolicySpec, CoordinatedScaling,
    )
    plane.apply(make_group("cp", simple_role("prefill", replicas=4),
                           simple_role("decode", replicas=4)))
    pol = CoordinatedPolicy()
    pol.metadata.name = "cp-pol"
    pol.spec = CoordinatedPolicySpec(
        group_name="cp",
        scaling=CoordinatedScaling(roles=["prefill", "decode"],
                                   max_skew_percent=25))
    plane.apply(dict(serde.to_dict(pol), kind="CoordinatedPolicy"))
    plane.wait_ready("cp", timeout=120)
    assert len(plane.pods("cp")) == 8


# ---- scenario 8: self-healing after pod delete (restart stability) ----

def test_pod_delete_self_heals(plane):
    plane.apply(make_group("heal", simple_role("srv", replicas=2)))
    plane.wait_ready("heal")
    victim = plane.pods("heal")[0]["name"]
    plane.ok(op="delete", kind="Pod", name=victim)
    plane.wait(
        lambda: (lambda ps: len(ps) == 2 and all(p["ready"] for p in ps))(
            plane.pods("heal")),
        desc="deleted pod recreated and ready")
    plane.wait_ready("heal")


# ---- scenario 9: rollout history + undo over the wire ----

def test_rollout_undo_restores_image(plane):
    g = make_group("undo", simple_role("srv", replicas=1, image="engine:v1"))
    plane.apply(g)
    plane.wait_ready("undo")
    g = serde.from_dict(type(g), plane.get("RoleBasedGroup", "undo"))
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.apply(g)
    plane.wait(
        lambda: len(plane.ok(op="history", name="undo")["revisions"]) == 2,
        desc="two revisions")
    plane.wait_ready("undo")
    plane.ok(op="undo", name="undo")
    plane.wait(
        lambda: plane.get("RoleBasedGroup", "undo")["spec"]["roles"][0]
        ["template"]["containers"][0]["image"] == "engine:v1",
        desc="undo restored v1")
    plane.wait_ready("undo")


# ---- scenario 10: in-place update keeps the pod ----

def test_inplace_update_preserves_pod(plane):
    g = make_group("inp", simple_role("srv", replicas=1, image="engine:v1"))
    g.spec.roles[0].rolling_update.in_place_if_possible = True
    plane.apply(g)
    plane.wait_ready("inp")
    uid0 = {p["name"] for p in plane.pods("inp")}

    g = serde.from_dict(type(g), plane.get("RoleBasedGroup", "inp"))
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.apply(g)
    plane.wait(
        lambda: plane.get("RoleInstanceSet", "inp-srv")["status"]
        .get("updatedReadyReplicas") == 1,
        desc="in-place update done")
    assert {p["name"] for p in plane.pods("inp")} == uid0, \
        "image-only change must not recreate the pod"


# ---- scenario 11: groupset fleet over the wire ----

def test_groupset_fleet_rollout(plane):
    gs = RoleBasedGroupSet()
    gs.metadata.name = "fleet"
    gs.spec.replicas = 2
    gs.spec.max_unavailable = 0  # both cells at once: keep e2e fast
    gs.spec.template.spec.roles = [simple_role("srv", replicas=1,
                                               image="engine:v1")]
    plane.apply(dict(serde.to_dict(gs), kind="RoleBasedGroupSet"))
    plane.wait(
        lambda: (plane.get("RoleBasedGroupSet", "fleet") or {}).get(
            "status", {}).get("readyReplicas") == 2,
        desc="fleet of 2 ready")

    gs2 = plane.get("RoleBasedGroupSet", "fleet")
    gs2["spec"]["template"]["spec"]["roles"][0]["template"]["containers"][0][
        "image"] = "engine:v2"
    plane.apply(dict(gs2, kind="RoleBasedGroupSet"))
    plane.wait(
        lambda: all(
            (plane.get("RoleBasedGroup", f"fleet-{i}") or {})["spec"]["roles"]
            [0]["template"]["containers"][0]["image"] == "engine:v2"
            for i in (0, 1)),
        desc="template bump reaches every cell")
    plane.wait(
        lambda: (plane.get("RoleBasedGroupSet", "fleet") or {}).get(
            "status", {}).get("updatedReplicas") == 2,
        desc="fleet updated counter")


# ---- scenario 12: convergence after plane SIGKILL mid-rollout ----

@pytest.mark.slow
def test_convergence_after_plane_kill(tmp_path):
    state = str(tmp_path / "state.json")
    p = ServedPlane(state_file=state, slices=2, hosts=2)
    p.start()
    try:
        g = make_group("conv", simple_role("srv", replicas=3,
                                           image="engine:v1"))
        g.spec.roles[0].rolling_update.in_place_if_possible = False
        p.apply(g)
        p.wait_ready("conv")
        # Ensure the pre-rollout state hit disk (5s autosave cadence).
        p.wait(lambda: os.path.exists(state), desc="state file exists")
        time.sleep(6.0)

        g = serde.from_dict(type(g), p.get("RoleBasedGroup", "conv"))
        g.spec.roles[0].template.containers[0].image = "engine:v2"
        p.apply(g)
        time.sleep(6.0)  # let the rollout start + autosave mid-flight
        p.kill9()
    finally:
        if p.proc.poll() is None:
            p.stop()

    # Restart from the state file: the rollout must finish, not restart.
    p2 = ServedPlane(state_file=state, slices=2, hosts=2)
    p2.port = _free_port()
    p2.start()
    try:
        p2.wait_ready("conv", timeout=120)
        ris = p2.get("RoleInstanceSet", "conv-srv")
        assert ris["status"].get("updatedReadyReplicas") == 3
        pods = p2.pods("conv")
        assert len(pods) == 3 and all(pp["ready"] for pp in pods)
    finally:
        p2.stop()
