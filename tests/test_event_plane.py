"""Event-carried control plane: watch-resume watermarks on the store,
generation dedup in the workqueue, drift-backstop skip accounting, and
the watch-driven k8s node sync.

The dedup-safety property drilled here is the one the refactor must
never break: the NEWEST generation of an object is never skipped — a
dequeued key is a no-op only when a COMPLETED reconcile already observed
store state at least as new as every pending trigger.
"""

import threading
import time

import pytest

from rbg_tpu.api.pod import Pod
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.controller import Controller, Result, Watch, own_keys
from rbg_tpu.runtime.store import Store, WatchExpired
from rbg_tpu.testutil import make_tpu_nodes


def _pod(name, ns="default"):
    p = Pod()
    p.metadata.name = name
    p.metadata.namespace = ns
    return p


# ---- store watch resume ----------------------------------------------------


def test_watch_resume_covers_list_to_watch_gap():
    """The reflector pattern: snapshot rv, list, and only THEN subscribe
    — a write landing in the gap must be replayed, not dropped."""
    store = Store()
    store.create(_pod("a"))
    rv0 = store.current_rv()
    listed = [p.metadata.name for p in store.list("Pod")]
    assert listed == ["a"]
    # The gap write: lands after the list snapshot, before the watch.
    store.create(_pod("gap"))
    seen = []
    store.watch("Pod", lambda ev: seen.append(
        (ev.type, ev.object.metadata.name)), since_rv=rv0)
    assert ("ADDED", "gap") in seen
    # Live events flow after the replay drained.
    store.create(_pod("live"))
    assert ("ADDED", "live") in seen


def test_watch_resume_replays_in_order_and_counts():
    store = Store()
    base = REGISTRY.counter(obs_names.WATCH_REPLAYS_TOTAL, kind="Pod")
    rv0 = store.current_rv()
    store.create(_pod("x"))
    store.mutate("Pod", "default", "x",
                 lambda p: setattr(p.status, "phase", "Running") or True,
                 status=True)
    store.delete("Pod", "default", "x")
    seen = []
    store.watch("Pod", lambda ev: seen.append(ev.type), since_rv=rv0)
    assert seen == ["ADDED", "MODIFIED", "DELETED"]
    assert REGISTRY.counter(obs_names.WATCH_REPLAYS_TOTAL,
                            kind="Pod") - base == 3


def test_watch_resume_expired_after_log_trim():
    small = Store()
    small_log_max = 16
    small.WATCH_LOG_MAX = small_log_max  # shrink per-instance
    rv0 = small.current_rv()
    for i in range(small_log_max * 3):
        small.create(_pod(f"p{i}"))
    with pytest.raises(WatchExpired):
        small.watch("Pod", lambda ev: None, since_rv=rv0)
    # A fresh watermark (post-trim) still resumes fine.
    rv1 = small.current_rv()
    small.create(_pod("tail"))
    seen = []
    small.watch("Pod", lambda ev: seen.append(ev.object.metadata.name),
                since_rv=rv1)
    assert seen == ["tail"]


def test_hard_delete_mints_fresh_rv():
    """DELETED events order after every prior write: rv-watermark
    consumers (workqueue dedup, replay) must never see a tombstone as
    already-covered stale state."""
    store = Store()
    obj = store.create(_pod("d"))
    rv_create = obj.metadata.resource_version
    events = []
    store.watch("Pod", lambda ev: events.append(ev))
    store.delete("Pod", "default", "d")
    deleted = [ev for ev in events if ev.type == "DELETED"]
    assert deleted and (deleted[0].object.metadata.resource_version
                        > rv_create)


def test_capacity_cache_start_survives_injected_gap_write(monkeypatch):
    """A bind injected between the cache's rebuild list and its watch
    registration is replayed by the resume watermark — the cache
    converges without any further event."""
    from rbg_tpu.sched.capacity import CapacityCache
    store = Store()
    make_tpu_nodes(store, slices=1, hosts_per_slice=2)
    cap = CapacityCache(store)
    orig_rebuild = CapacityCache.rebuild

    def rebuild_then_write(self):
        orig_rebuild(self)
        # The gap: a pod binds after the list snapshot was consumed.
        p = _pod("gapper")
        p.node_name = "slice-0-host-0"
        store.create(p)
        monkeypatch.setattr(CapacityCache, "rebuild", orig_rebuild)

    monkeypatch.setattr(CapacityCache, "rebuild", rebuild_then_write)
    cap.start()
    assert cap.free_view()["slice-0-host-0"] == 63


# ---- workqueue dedup -------------------------------------------------------


class _Recorder(Controller):
    """Reconciles Pods, recording the store rv observed per reconcile."""

    name = "recorder"
    workers = 2
    resync_period = 0  # event-driven only unless a test says otherwise

    def __init__(self, store, write_status=False, requeue=None):
        super().__init__(store)
        self.write_status = write_status
        self.requeue = requeue
        self.observed = []  # (key, rv at read time)
        self._obs_lock = threading.Lock()

    def watches(self):
        return [Watch("Pod", own_keys)]

    def reconcile(self, store, key):
        rv = store.current_rv()
        with self._obs_lock:
            self.observed.append((key, rv))
        if self.write_status:
            obj = store.get("Pod", *key, copy_=False)
            if obj is not None:
                # Idempotent status write (level-triggered discipline):
                # second pass is a no-op → no event → convergence.
                def fn(p):
                    if p.status.reason == "seen":
                        return False
                    p.status.reason = "seen"
                    return True
                store.mutate("Pod", *key, fn, status=True)
        if self.requeue is not None:
            return Result(requeue_after=self.requeue)
        return None


def _deduped(name):
    return REGISTRY.counter(obs_names.RECONCILE_DEDUPED_TOTAL,
                            controller=name)


def _wait(fn, timeout=5.0, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if fn():
            return
        time.sleep(0.01)
    raise AssertionError(f"timed out: {desc}")


def test_newest_generation_never_skipped_under_coalescing_storm():
    """Property: after an update storm plus requeue-after churn settles,
    the LAST completed reconcile observed store state at least as new as
    the final write — dedup may collapse the middle, never the end."""
    store = Store()
    ctrl = _Recorder(store, requeue=0.005)
    store.create(_pod("storm"))
    ctrl.start()
    try:
        _wait(lambda: ctrl.observed, desc="first reconcile")
        for i in range(60):
            store.mutate("Pod", "default", "storm",
                         lambda p, i=i: setattr(
                             p.status, "reason", f"r{i}") or True,
                         status=True)
            if i % 7 == 0:
                time.sleep(0.002)
        final_rv = store.current_rv()
        _wait(lambda: ctrl.observed and ctrl.observed[-1][1] >= final_rv,
              desc="final write observed")
        with ctrl._obs_lock:
            last = ctrl.observed[-1]
        assert last[1] >= final_rv
    finally:
        ctrl.stop()


def test_stale_coalesced_events_dedup_as_counted_noops():
    store = Store()
    ctrl = _Recorder(store)
    ctrl.start()
    base_ded = _deduped(ctrl.name)
    try:
        store.create(_pod("c"))
        _wait(lambda: len(ctrl.observed) >= 1, desc="create reconciled")
        # Quiesce, then deliver a STALE trigger: enqueue with an old rv.
        time.sleep(0.05)
        n_before = len(ctrl.observed)
        stale_rv = store.current_rv() - 1
        ctrl.queue.add(("default", "c"), version=max(0, stale_rv))
        _wait(lambda: _deduped(ctrl.name) > base_ded,
              desc="stale trigger counted as dedup")
        time.sleep(0.05)
        assert len(ctrl.observed) == n_before  # reconcile did NOT run
    finally:
        ctrl.stop()


def test_self_write_retriggers_once_then_duplicates_dedup():
    """A controller's own write re-triggers EXACTLY ONE (no-op)
    reconcile — never zero: a reconcile may rely on re-observing its own
    state transition, and a foreign write interleaved with the self-write
    must never be masked (the two unsound failure modes of watermark
    self-folding). The no-op pass then advances the watermark, so
    DUPLICATE stale triggers for the covered state dedup."""
    store = Store()
    ctrl = _Recorder(store, write_status=True)
    base_ded = _deduped(ctrl.name)
    ctrl.start()
    try:
        store.create(_pod("sw"))
        # create-pass writes status → retrigger → idempotent no-op pass.
        _wait(lambda: len(ctrl.observed) >= 2, desc="self-write retrigger")
        time.sleep(0.1)
        with ctrl._obs_lock:
            runs = len(ctrl.observed)
        assert runs == 2  # converged: no self-sustaining write loop
        # A stale duplicate of the covered state dedups, not reconciles.
        ctrl.queue.add(("default", "sw"), version=store.current_rv())
        _wait(lambda: _deduped(ctrl.name) > base_ded,
              desc="stale duplicate deduped")
        time.sleep(0.05)
        with ctrl._obs_lock:
            assert len(ctrl.observed) == runs
    finally:
        ctrl.stop()


def test_forced_requeue_never_deduped():
    store = Store()
    ctrl = _Recorder(store, requeue=0.01)
    store.create(_pod("f"))
    ctrl.start()
    try:
        _wait(lambda: len(ctrl.observed) >= 4,
              desc="requeue_after keeps firing despite unchanged rv")
    finally:
        ctrl.stop()


def test_backstop_skips_recently_reconciled_keys():
    store = Store()
    ctrl = _Recorder(store)
    ctrl.resync_period = 0.2
    ctrl.backstop_period = 0.2
    store.create(_pod("warm"))
    store.create(_pod("cold"))
    base_enq = REGISTRY.counter(obs_names.RESYNC_BACKSTOP_ENQUEUED_TOTAL,
                                controller=ctrl.name)
    base_skip = REGISTRY.counter(obs_names.RESYNC_BACKSTOP_SKIPPED_TOTAL,
                                 controller=ctrl.name)
    ctrl.start()
    try:
        _wait(lambda: len(ctrl.observed) >= 2, desc="initial sync")
        # Both keys were just reconciled → the first backstop tick skips
        # them entirely.
        _wait(lambda: REGISTRY.counter(
            obs_names.RESYNC_BACKSTOP_SKIPPED_TOTAL,
            controller=ctrl.name) - base_skip >= 2,
            desc="backstop skipped recent keys")
        # After a quiet period (no reconciles), the next tick enqueues
        # them — and the versioned add dedups at dequeue (drift sweep of
        # unchanged objects costs zero reconcile work).
        n = len(ctrl.observed)
        _wait(lambda: REGISTRY.counter(
            obs_names.RESYNC_BACKSTOP_ENQUEUED_TOTAL,
            controller=ctrl.name) - base_enq >= 2,
            desc="backstop enqueued after quiet period")
        time.sleep(0.1)
        assert len(ctrl.observed) == n  # deduped, not reconciled
    finally:
        ctrl.stop()


# ---- plane toggle + k8s node watch ----------------------------------------


def test_plane_is_event_carried_by_default():
    """The legacy_resync A/B toggle is deleted: every plane is event-
    carried — sharded feasibility scan on, long backstop periods, dedup
    active (the _Recorder dedup tests above prove the behavior)."""
    from rbg_tpu.runtime.plane import ControlPlane
    plane = ControlPlane(backend="none")
    assert plane.scheduler.use_sharded is True
    assert all((c.backstop_period or c.resync_period) >= 30.0
               for c in plane.manager.controllers)


def test_k8s_node_watch_carries_disruption_without_polling():
    """Node disruption state must reach the plane through the node WATCH
    stream (the 2 s poll is demoted to a 60 s backstop — polling cadence
    can no longer be what carries the signal)."""
    from rbg_tpu.k8s import translate as T
    from rbg_tpu.k8s.backend import K8sPodBackend
    from rbg_tpu.k8s.client import KubeClient
    from rbg_tpu.k8s.fake_apiserver import FakeK8sApiServer

    api = FakeK8sApiServer(agent=False).start()
    try:
        for h in range(2):
            api.add_node(f"w-{h}", labels={
                T.LABEL_GKE_NODEPOOL: "pool-a",
                T.LABEL_GKE_TPU_TOPOLOGY: "2x2",
                T.LABEL_GKE_TPU_ACCEL: "tpu-v5-lite-podslice",
            }, tpu=4)
        store = Store()
        backend = K8sPodBackend(store, KubeClient(api.url))
        assert backend.NODE_BACKSTOP_S >= 60.0
        backend.start()
        try:
            _wait(lambda: len(store.list("Node")) == 2,
                  desc="nodes imported")
            api.set_maintenance("pool-a", deadline_s=300.0)
            # Well inside the 60 s backstop — only the watch can carry it.
            _wait(lambda: all(
                n.disruption == "maintenance"
                for n in store.list("Node", copy_=False)),
                timeout=10.0, desc="maintenance reached the plane via watch")
        finally:
            backend.stop()
    finally:
        api.stop()


# ---- fleet drill (throughput reps + 10k slow) ------------------------------


def test_fleet_rep_section_small():
    """Two throughput reps at toy scale: each completes with identical
    bind counts (the churn wave is deterministic per rep). (Dedup VOLUME
    is asserted at real churn scale — the tier1 fleet smoke — because a
    16-pod rep can legitimately coalesce nothing.)"""
    from rbg_tpu.stress.harness import FleetConfig, _run_fleet_rep
    cfg = FleetConfig(nodes=24, hosts_per_slice=4, groups=4, ab_groups=4,
                      replicas=1, roles_per_group=1, timeout_s=60.0)
    a = _run_fleet_rep(cfg)
    b = _run_fleet_rep(cfg)
    assert a["ok"] and b["ok"]
    assert a["binds_total"] == b["binds_total"] > 0


@pytest.mark.slow
def test_fleet_drill_10k_nodes():
    """The acceptance-scale slow drill: 10k nodes, invariants green."""
    from rbg_tpu.stress.harness import FleetConfig, run_fleet
    report = run_fleet(FleetConfig(
        nodes=10000, hosts_per_slice=4, groups=60, roles_per_group=2,
        replicas=2, create_qps=200.0, timeout_s=240.0,
        drain_timeout_s=120.0, ab_reps=0))
    inv = report["invariants"]
    assert inv["workqueue_drained"], report["workqueues"]
    assert inv["no_stuck_keys"], report["stuck_keys"]
    assert inv["reconcile_p99_bound"], report["reconcile_latency"]
    assert inv["events_accounted"], report["events"]
    assert report["fleet"]["nodes"] == 10000
