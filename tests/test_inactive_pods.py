"""Inactive-pod handling (reference: keps/inactive-pod-handling; VERDICT r1
item 8): Failed/Evicted pods must be deleted so their fixed-name replacement
can be created — under every restart policy — and the reason must surface
as an event."""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RestartPolicy
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=1, hosts_per_slice=2)
    with p:
        yield p


def _events(plane, reason):
    # store events are (ts, ref, reason, message) tuples
    return [e for e in plane.store.events_for() if e[2] == reason]


def test_evicted_pod_replaced_policy_none(plane):
    """Story 1/3: eviction under policy None → pod-level replacement with
    the same fixed name, active replica count restored."""
    role = simple_role("srv", replicas=2)
    role.restart_policy.policy = RestartPolicy.NONE
    plane.apply(make_group("ev", role))
    plane.wait_group_ready("ev")
    pods = plane.store.list("Pod", namespace="default")
    victim = pods[0]

    plane.kubelet.evict_pod("default", victim.metadata.name)

    def replaced():
        p = plane.store.get("Pod", "default", victim.metadata.name)
        return (p is not None and p.metadata.uid != victim.metadata.uid
                and p.running_ready) or None

    plane.wait_for(replaced, timeout=15, desc="same-name replacement")
    plane.wait_group_ready("ev")
    evs = _events(plane, "ReplacingFailedPod")
    assert evs and "Evicted" in evs[0][3]


def test_failed_ignored_component_replaced_pod_level(plane):
    """A component excluded from the gang-restart trigger (Ignore) still
    gets pod-level replacement when it fails — previously it squatted its
    name forever (KEP root cause)."""
    from rbg_tpu.api.group import ComponentSpec, PatternType
    from rbg_tpu.api.pod import PodTemplate
    from rbg_tpu.testutil import simple_container

    role = simple_role("mix", replicas=1)
    role.pattern = PatternType.CUSTOM_COMPONENTS
    role.components = [
        ComponentSpec(name="engine", size=1,
                      template=PodTemplate(containers=[simple_container()])),
        ComponentSpec(name="cache", size=1,
                      template=PodTemplate(
                          containers=[simple_container(name="cache")],
                          annotations={C.ANN_RESTART_TRIGGER_POLICY: "Ignore"})),
    ]
    role.template = PodTemplate(containers=[simple_container()])
    plane.apply(make_group("ig", role))
    plane.wait_group_ready("ig")

    pods = plane.store.list("Pod", namespace="default")
    cache_pod = next(p for p in pods
                     if p.metadata.labels.get(C.LABEL_COMPONENT_NAME) == "cache")
    engine_pod = next(p for p in pods
                      if p.metadata.labels.get(C.LABEL_COMPONENT_NAME) == "engine")

    plane.kubelet.fail_pod("default", cache_pod.metadata.name,
                           reason="UnexpectedAdmissionError")

    def replaced():
        p = plane.store.get("Pod", "default", cache_pod.metadata.name)
        return (p is not None and p.metadata.uid != cache_pod.metadata.uid
                and p.running_ready) or None

    plane.wait_for(replaced, timeout=15, desc="ignored component replaced")
    # The engine pod was NOT gang-restarted (Ignore confined the blast).
    e = plane.store.get("Pod", "default", engine_pod.metadata.name)
    assert e is not None and e.metadata.uid == engine_pod.metadata.uid
    insts = plane.store.list("RoleInstance", namespace="default")
    assert all(i.status.restart_count == 0 for i in insts)
    plane.wait_group_ready("ig")


def test_evicted_pod_instance_recreate_policy(plane):
    """Story 2: under RecreateInstance policy an eviction recreates the
    whole gang (level 2), exactly once."""
    role = simple_role("srv", replicas=1)
    plane.apply(make_group("l2", role))
    plane.wait_group_ready("l2")
    (pod,) = plane.store.list("Pod", namespace="default")

    plane.kubelet.evict_pod("default", pod.metadata.name)

    def recreated():
        pods = plane.store.list("Pod", namespace="default")
        if len(pods) != 1 or pods[0].metadata.uid == pod.metadata.uid:
            return None
        return pods[0] if pods[0].running_ready else None

    plane.wait_for(recreated, timeout=15, desc="gang recreate")
    insts = plane.store.list("RoleInstance", namespace="default")
    assert [i.status.restart_count for i in insts] == [1]
    plane.wait_group_ready("l2")
