"""Multi-host rendezvous e2e: the plane's injected JAX contract forms a REAL
multi-process JAX job (Gloo collectives across two local processes)."""

import json
import os

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import LeaderWorkerSpec, PatternType, RoleSpec
from rbg_tpu.api.pod import Container, Node, PodTemplate
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group

# Forms a REAL two-process jax.distributed job (~2 min when it works, a
# 120 s wait_for when the Gloo rendezvous wedges, as it does on this
# image) — tier-2 material; the tier-1 budget (870 s) can't afford it.
pytestmark = pytest.mark.slow


@pytest.mark.e2e
def test_injected_contract_forms_real_jax_job(tmp_path):
    out = str(tmp_path / "rdv")
    role = RoleSpec(
        name="trainer", replicas=1,
        pattern=PatternType.LEADER_WORKER,
        leader_worker=LeaderWorkerSpec(size=2),
        template=PodTemplate(containers=[Container(
            name="worker",
            command=["python", "-m", "rbg_tpu.engine.rendezvous_check"],
        )]),
    )

    plane = ControlPlane(
        backend="local",
        executor_env={
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": None,   # keep the TPU-relay hook out
            "RBG_RENDEZVOUS_OUT": out,
        },
    )
    node = Node()
    node.metadata.name = "localhost"
    plane.store.create(node)

    with plane:
        plane.apply(make_group("dist", role))
        plane.wait_group_ready("dist", timeout=180)

        def both_reported():
            return (os.path.exists(f"{out}.0") and os.path.exists(f"{out}.1"))

        plane.wait_for(both_reported, timeout=120, desc="both ranks rendezvoused")

    r0 = json.load(open(f"{out}.0"))
    r1 = json.load(open(f"{out}.1"))
    assert r0["num_processes"] == r1["num_processes"] == 2
    assert {r0["process_id"], r1["process_id"]} == {0, 1}
    # One consistent global device view across BOTH processes (= the
    # distributed service connected them); local device count varies with
    # inherited XLA flags, so only agreement and divisibility are asserted.
    assert r0["global_devices"] == r1["global_devices"]
    assert r0["global_devices"] % 2 == 0 and r0["global_devices"] >= 2
    # Worker received the leader's broadcast (group name length, leader pid 0).
    assert r1["leader_pid"] == 0
    assert r1["leader_group_len"] == len("dist")
