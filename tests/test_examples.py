"""Shipped example manifests: BASELINE.md's five benchmark configs map
1:1 onto committed examples (VERDICT r4 #9), and every example parses,
passes admission, and validates against the generated JSON Schema."""

import glob

import pytest
import yaml

from rbg_tpu.api import KINDS, parse_manifest
from rbg_tpu.api.schema import schema_for
from rbg_tpu.api.validation import validate_group

# BASELINE.md "Benchmark configs to reproduce" -> examples/ file.
BASELINE_CONFIG_MAP = {
    1: "examples/single-role.yaml",       # single-role CPU serve
    2: "examples/agg-standalone.yaml",    # router+worker, one TPU host
    3: "examples/pd-disagg.yaml",         # prefill/decode disaggregated
    4: "examples/kv-pool-components.yaml",  # Mooncake-style KV pool
    5: "examples/agg-multihost.yaml",     # multi-host LWS role, TP slice
}


def _docs(path):
    with open(path) as f:
        return [d for d in yaml.safe_load_all(f) if d]


@pytest.mark.parametrize("cfg,path", sorted(BASELINE_CONFIG_MAP.items()))
def test_baseline_config_example_exists_and_parses(cfg, path):
    docs = _docs(path)
    assert docs, f"config {cfg}: {path} is empty"
    for doc in docs:
        obj = parse_manifest(doc)
        if doc.get("kind") == "RoleBasedGroup":
            validate_group(obj)  # admission must accept what we ship


@pytest.mark.parametrize("path", sorted(glob.glob("examples/*.yaml")))
def test_every_example_schema_validates(path):
    jsonschema = pytest.importorskip("jsonschema")
    for doc in _docs(path):
        kind = doc.get("kind")
        assert kind in KINDS, f"{path}: unknown kind {kind}"
        jsonschema.validate(doc, schema_for(KINDS[kind]))
        obj = parse_manifest(doc)
        if kind == "RoleBasedGroup":
            validate_group(obj)
