"""Pipeline parallelism: pipelined block stack == dense forward, and it
differentiates (training path)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.models import get_config, init_params
from rbg_tpu.models.llama import forward_train
from rbg_tpu.parallel.mesh import AXES
from rbg_tpu.parallel.pipeline import pipeline_forward_train

from jax.sharding import Mesh


def pp_mesh(pp: int) -> Mesh:
    import numpy as _np
    devices = jax.devices()[: pp]
    return Mesh(_np.asarray(devices).reshape(pp), ("pp",))


@pytest.mark.parametrize("pp,micro", [(2, 4), (2, 2)])
def test_pipeline_matches_dense(pp, micro):
    cfg = get_config("tiny")  # 2 layers → 1 per stage at pp=2
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (4, 8), 0, cfg.vocab_size)
    dense = forward_train(params, cfg, tokens)
    piped = pipeline_forward_train(params, cfg, tokens, mesh=pp_mesh(pp),
                                   num_microbatches=micro)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(piped),
                               rtol=1e-4, atol=1e-4)


def test_pipeline_with_padding_mask():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    B, T = 4, 8
    tokens = jax.random.randint(jax.random.key(2), (B, T), 0, cfg.vocab_size)
    mask = jnp.asarray(np.random.RandomState(0).rand(B, T) > 0.3)
    mask = mask.at[:, 0].set(True)
    dense = forward_train(params, cfg, tokens, mask)
    piped = pipeline_forward_train(params, cfg, tokens, mask, mesh=pp_mesh(2),
                                   num_microbatches=4)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(piped),
                               rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_pipeline_differentiates():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(3), (4, 8), 0, cfg.vocab_size)
    mesh = pp_mesh(2)

    def loss_pp(p):
        lg = pipeline_forward_train(p, cfg, tokens, mesh=mesh, num_microbatches=2)
        return jnp.mean(lg.astype(jnp.float32) ** 2)

    def loss_dense(p):
        lg = forward_train(p, cfg, tokens)
        return jnp.mean(lg.astype(jnp.float32) ** 2)

    g_pp = jax.grad(loss_pp)(params)
    g_dense = jax.grad(loss_dense)(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-3, atol=1e-5),
        g_pp, g_dense,
    )
