"""Shared KV pool (Mooncake-store analog, keps/74): store semantics,
prefill integration, and the cross-process reuse e2e."""

import socket
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.kvpool import KVPoolClient, KVPoolServer, KVPoolStore
from rbg_tpu.engine.pd import PrefillWorker
from rbg_tpu.models import get_config, init_params

PS = 8  # page size everywhere here


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = get_config("tiny")
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def ecfg(**kw):
    base = dict(model="tiny", page_size=PS, num_pages=64, max_batch=4,
                max_seq_len=256, prefill_chunk=16, use_pallas="never")
    base.update(kw)
    return EngineConfig(**base)


def fake_pages(n, L=2, KV=2, hd=32, seed=0):
    rng = np.random.RandomState(seed)
    return (rng.randn(L, n, PS, KV, hd).astype(np.float32),
            rng.randn(L, n, PS, KV, hd).astype(np.float32))


# ---- store semantics ----


def test_store_match_put_page_aligned():
    st = KVPoolStore(PS)
    toks = list(range(30))           # 3 full pages + 6 tokens
    k, v = fake_pages(3)
    assert st.put(toks, k, v) == 3
    # Identical prefix: full page-aligned match.
    m, km, vm = st.match(toks)
    assert m == 24 and km.shape[1] == 3
    np.testing.assert_array_equal(km[:, 0], k[:, 0])
    # Shorter query matches fewer pages.
    m2, km2, _ = st.match(toks[:17])
    assert m2 == 16 and km2.shape[1] == 2
    # Diverging second page stops after page 1.
    div = toks[:PS] + [99] * PS
    m3, km3, _ = st.match(div)
    assert m3 == PS and km3.shape[1] == 1
    # Complete miss.
    m4, km4, _ = st.match([99] * 16)
    assert m4 == 0 and km4 is None
    # Re-put refreshes, no duplicates.
    assert st.put(toks, k, v) == 0
    s = st.stats()
    assert s["pages"] == 3 and s["hits"] == 3 and s["misses"] == 1


def test_store_lru_eviction_by_bytes():
    k, v = fake_pages(1)
    page_bytes = k.nbytes + v.nbytes
    st = KVPoolStore(PS, max_bytes=page_bytes * 2)
    a, b, c = [list(range(i * 100, i * 100 + PS)) for i in range(3)]
    st.put(a, *fake_pages(1, seed=1))
    time.sleep(0.01)
    st.put(b, *fake_pages(1, seed=2))
    st.match(a)                      # refresh a → b becomes LRU
    time.sleep(0.01)
    st.put(c, *fake_pages(1, seed=3))   # evicts b
    assert st.match(a)[0] == PS
    assert st.match(b)[0] == 0
    assert st.match(c)[0] == PS
    s = st.stats()
    assert s["evicted_pages"] == 1 and s["pages"] == 2
    assert s["bytes"] <= s["max_bytes"]


# ---- prefill integration (in-process, two workers sharing one pool) ----


@pytest.mark.slow
def test_second_replica_skips_prefill_through_pool(tiny_setup):
    cfg, params = tiny_setup
    srv = KVPoolServer(("127.0.0.1", 0), KVPoolStore(PS))
    import threading
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        rng = np.random.RandomState(7)
        prompt = rng.randint(0, cfg.vocab_size, size=128).tolist()

        w1 = PrefillWorker(ecfg(), params=params, pool=KVPoolClient(addr))
        b1 = w1.prefill(prompt)
        assert w1.engine.metrics["prefill_tokens"] == 128
        assert w1.metrics["pool_exports"] == 1

        # A DIFFERENT worker (fresh engine, empty radix) reuses the pool:
        # only the last partial page computes -> >=90% of prefill skipped.
        w2 = PrefillWorker(ecfg(), params=params, pool=KVPoolClient(addr))
        b2 = w2.prefill(prompt)
        computed = w2.engine.metrics["prefill_tokens"]
        assert computed <= 128 * 0.10, f"computed {computed} of 128"
        assert w2.metrics["pool_hits"] == 1
        assert w2.metrics["pool_hit_tokens"] == 120

        # Numerics: the reused path produces the SAME first token and the
        # same exported KV as the cold path.
        assert b2.first_token == b1.first_token
        np.testing.assert_allclose(b2.k_data, b1.k_data, rtol=2e-4, atol=2e-4)

        # Prefix (not just identical-prompt) reuse.
        longer = prompt + rng.randint(0, cfg.vocab_size, size=40).tolist()
        w3 = PrefillWorker(ecfg(), params=params, pool=KVPoolClient(addr))
        w3.prefill(longer)
        assert w3.metrics["pool_hit_tokens"] == 128  # 16 full pages
        assert w3.engine.metrics["prefill_tokens"] == 40
    finally:
        srv.shutdown()
        srv.server_close()


def test_pool_failure_degrades_to_cold_prefill(tiny_setup):
    cfg, params = tiny_setup
    # Nothing listens on this port.
    dead = KVPoolClient("127.0.0.1:1", timeout=0.2)
    w = PrefillWorker(ecfg(), params=params, pool=dead)
    rng = np.random.RandomState(5)
    prompt = rng.randint(0, cfg.vocab_size, size=64).tolist()
    b = w.prefill(prompt)
    assert b.first_token is not None
    assert w.engine.metrics["prefill_tokens"] == 64
    assert w.metrics["pool_errors"] >= 1


# ---- cross-process e2e: two prefill server replicas + pool + decode ----


def _wait_port(port, timeout=120.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            with socket.create_connection(("127.0.0.1", port), timeout=1):
                return
        except OSError:
            time.sleep(0.2)
    raise TimeoutError(f"port {port} never opened")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait_engine_ready(port, timeout=180.0):
    from rbg_tpu.engine.protocol import request_once
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            h, _, _ = request_once(f"127.0.0.1:{port}", {"op": "health"},
                                   timeout=5)
            if h.get("ok"):
                return
        except OSError:
            pass
        time.sleep(0.5)
    raise TimeoutError(f"engine on {port} never ready")


@pytest.mark.slow
@pytest.mark.e2e
def test_kvpool_reuse_across_real_processes():
    """BASELINE config 4 shape: the second identical prompt, served by a
    DIFFERENT prefill replica process, skips >=90% of prefill compute via
    the shared pool; the exported bundle decodes identically."""
    from rbg_tpu.engine.protocol import bundle_from_wire, request_once
    from rbg_tpu.utils import scrubbed_cpu_env

    env = scrubbed_cpu_env()
    pool_port, p1, p2 = _free_port(), _free_port(), _free_port()
    engine_args = ["--model", "tiny", "--page-size", str(PS),
                   "--num-pages", "64", "--max-seq-len", "256",
                   "--prefill-chunk", "16", "--use-pallas", "never",
                   "--kv-pool", f"127.0.0.1:{pool_port}"]
    procs = [subprocess.Popen(
        [sys.executable, "-m", "rbg_tpu.engine.kvpool",
         "--port", str(pool_port), "--page-size", str(PS)], env=env)]
    try:
        _wait_port(pool_port)
        for port in (p1, p2):
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "rbg_tpu.engine.server",
                 "--mode", "prefill", "--port", str(port)] + engine_args,
                env=env))
        _wait_engine_ready(p1)
        _wait_engine_ready(p2)

        rng = np.random.RandomState(11)
        prompt = rng.randint(0, 256, size=128).tolist()

        h1, k1, v1 = request_once(f"127.0.0.1:{p1}",
                                  {"op": "prefill", "prompt": prompt})
        assert "error" not in h1
        m1, _, _ = request_once(f"127.0.0.1:{p1}", {"op": "metrics"})
        assert m1["metrics"]["prefill_tokens"] == 128
        assert m1["metrics"]["pool_exports"] == 1

        h2, k2, v2 = request_once(f"127.0.0.1:{p2}",
                                  {"op": "prefill", "prompt": prompt})
        assert "error" not in h2
        m2, _, _ = request_once(f"127.0.0.1:{p2}", {"op": "metrics"})
        computed = m2["metrics"]["prefill_tokens"]
        assert computed <= 128 * 0.10, \
            f"replica 2 computed {computed}/128 prefill tokens"
        assert m2["metrics"]["pool_hits"] == 1

        # Same numerics across replicas (same seed -> same params).
        assert h2["first_token"] == h1["first_token"]
        b1 = bundle_from_wire(h1, k1, v1)
        b2 = bundle_from_wire(h2, k2, v2)
        np.testing.assert_allclose(b2.k_data, b1.k_data, rtol=2e-4, atol=2e-4)

        # Pool-side metrics: one export, one hit.
        stats = KVPoolClient(f"127.0.0.1:{pool_port}").stats()
        assert stats["hits"] == 1 and stats["hit_tokens"] == 120
        assert stats["put_pages"] == 16

        # The reused bundle decodes: feed it to a decode worker in-process
        # and check the continuation matches the cold bundle's.
        cfg = get_config("tiny")
        params = init_params(cfg, jax.random.key(0))
        from rbg_tpu.engine.pd import DecodeWorker
        outs = []
        for b in (b1, b2):
            dw = DecodeWorker(ecfg(), params=params)
            rid = dw.inject(b, SamplingParams(max_new_tokens=6))
            toks = [b.first_token]
            while dw.engine.has_work():
                for ev in dw.engine.step():
                    if ev.request_id == rid:
                        toks.append(ev.token)
            outs.append(toks)
        assert len(outs[0]) == 6 and outs[0] == outs[1]
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()


def test_store_sibling_pages_with_shared_first_token_coexist():
    """Pages sharing a first token but diverging inside the page must
    coexist (children keyed by full page content) with exact byte
    accounting."""
    st = KVPoolStore(PS)
    a = [7, 1, 2, 3, 4, 5, 6, 7]
    b = [7, 9, 9, 9, 9, 9, 9, 9]
    ka, va = fake_pages(1, seed=1)
    kb, vb = fake_pages(1, seed=2)
    assert st.put(a, ka, va) == 1
    assert st.put(b, kb, vb) == 1
    ma, kma, _ = st.match(a)
    mb, kmb, _ = st.match(b)
    assert ma == PS and mb == PS
    np.testing.assert_array_equal(kma[:, 0], ka[:, 0])
    np.testing.assert_array_equal(kmb[:, 0], kb[:, 0])
    s = st.stats()
    assert s["pages"] == 2
    assert s["bytes"] == ka.nbytes + va.nbytes + kb.nbytes + vb.nbytes


def test_pool_page_size_handshake_rejected(tiny_setup):
    """A client whose engine page size differs from the pool's is refused
    (silent reinterpretation would corrupt KV) — and the prefill worker
    degrades to cold prefill."""
    cfg, params = tiny_setup
    import threading
    srv = KVPoolServer(("127.0.0.1", 0), KVPoolStore(page_size=16))  # != PS
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    try:
        addr = f"127.0.0.1:{srv.server_address[1]}"
        w = PrefillWorker(ecfg(), params=params, pool=KVPoolClient(addr))
        rng = np.random.RandomState(3)
        prompt = rng.randint(0, cfg.vocab_size, size=64).tolist()
        b = w.prefill(prompt)  # must not raise
        assert b.first_token is not None
        assert w.engine.metrics["prefill_tokens"] == 64  # cold
        assert w.metrics["pool_errors"] >= 1
    finally:
        srv.shutdown()
        srv.server_close()
