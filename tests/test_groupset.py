"""RoleBasedGroupSet depth tests: scale, template propagation, staged fleet
rollout (reference: ``rolebasedgroupset_controller.go`` needsUpdate /
updateExistingRBGs :158-191, :374-430)."""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RoleBasedGroupSet
from rbg_tpu.api.meta import get_condition
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_tpu_nodes, simple_role


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        yield p


def make_set(name="cells", replicas=2, image="engine:v1", max_unavailable=1):
    gs = RoleBasedGroupSet()
    gs.metadata.name = name
    gs.spec.replicas = replicas
    gs.spec.max_unavailable = max_unavailable
    gs.spec.template.metadata.labels = {"tier": "serving"}
    gs.spec.template.metadata.annotations = {"team": "ml"}
    role = simple_role("server", replicas=1, image=image)
    # Recreate (not in-place) so held kubelets make a mid-update cell
    # observably not-Ready in the staged-rollout tests.
    role.rolling_update.in_place_if_possible = False
    gs.spec.template.spec.roles = [role]
    return gs


def groups(plane, ns="default"):
    return sorted(plane.store.list("RoleBasedGroup", namespace=ns),
                  key=lambda g: g.metadata.name)


def wait_all_ready(plane, name, n):
    def ok():
        s = plane.store.get("RoleBasedGroupSet", "default", name)
        return s is not None and s.status.ready_replicas == n
    plane.wait_for(ok, timeout=30, desc=f"groupset {name}: {n} groups ready")


def test_create_scale_up_down(plane):
    plane.apply(make_set(replicas=2))
    wait_all_ready(plane, "cells", 2)
    assert [g.metadata.name for g in groups(plane)] == ["cells-0", "cells-1"]

    gs = plane.store.get("RoleBasedGroupSet", "default", "cells")
    gs.spec.replicas = 3
    plane.store.update(gs)
    wait_all_ready(plane, "cells", 3)

    gs = plane.store.get("RoleBasedGroupSet", "default", "cells")
    gs.spec.replicas = 1
    plane.store.update(gs)
    plane.wait_for(lambda: len(groups(plane)) == 1, desc="scale down to 1")
    assert groups(plane)[0].metadata.name == "cells-0"


def test_template_spec_propagates_to_live_groups(plane):
    plane.apply(make_set(replicas=2, image="engine:v1"))
    wait_all_ready(plane, "cells", 2)

    gs = plane.store.get("RoleBasedGroupSet", "default", "cells")
    gs.spec.template.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(gs)

    def converged():
        gl = groups(plane)
        return len(gl) == 2 and all(
            g.spec.roles[0].template.containers[0].image == "engine:v2"
            for g in gl)
    plane.wait_for(converged, timeout=30, desc="image bump reaches every group")

    # ... and all the way down to running pods of every cell.
    def pods_updated():
        pods = [p for p in plane.store.list("Pod", namespace="default")
                if p.active]
        return len(pods) == 2 and all(
            p.template.containers[0].image == "engine:v2" for p in pods)
    plane.wait_for(pods_updated, timeout=30, desc="fleet pods on v2")


def test_template_labels_annotations_propagate_and_index_survives(plane):
    plane.apply(make_set(replicas=2))
    wait_all_ready(plane, "cells", 2)

    gs = plane.store.get("RoleBasedGroupSet", "default", "cells")
    gs.spec.template.metadata.labels = {"tier": "canary", "zone": "a"}
    gs.spec.template.metadata.annotations = {}  # removal propagates too
    plane.store.update(gs)

    def converged():
        gl = groups(plane)
        if len(gl) != 2:
            return False
        for i, g in enumerate(gl):
            if g.metadata.labels.get("tier") != "canary":
                return False
            if g.metadata.labels.get("zone") != "a":
                return False
            if "team" in g.metadata.annotations:
                return False
            # set-managed identity labels must survive the propagation
            if g.metadata.labels.get(C.LABEL_GROUP_SET_NAME) != "cells":
                return False
            if g.metadata.labels.get(C.LABEL_GROUP_SET_INDEX) != str(i):
                return False
        return True
    plane.wait_for(converged, timeout=30, desc="labels/annotations converge")

    # Old template label gone (reference needsTemplateLabelUpdate removal leg)
    gs = plane.store.get("RoleBasedGroupSet", "default", "cells")
    gs.spec.template.metadata.labels = {"zone": "a"}
    plane.store.update(gs)
    plane.wait_for(
        lambda: all("tier" not in g.metadata.labels for g in groups(plane)),
        timeout=30, desc="removed template label leaves groups")


def test_fleet_rollout_is_staged_by_max_unavailable():
    """With max_unavailable=1 and readiness frozen, only ONE cell may be
    disrupted: the second drifted group must wait until the first is Ready
    again at the new template."""
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        p.apply(make_set(replicas=2, image="engine:v1", max_unavailable=1))
        wait_all_ready(p, "cells", 2)

        # Hold the fake kubelet so no new pod ever turns Ready: an updated
        # cell stays not-Ready, holding the budget.
        p.kubelet.hold_filter = lambda pod: True
        gs = p.store.get("RoleBasedGroupSet", "default", "cells")
        gs.spec.template.spec.roles[0].template.containers[0].image = "engine:v2"
        p.store.update(gs)

        def one_updated():
            imgs = [g.spec.roles[0].template.containers[0].image
                    for g in groups(p)]
            return sorted(imgs) == ["engine:v1", "engine:v2"]
        p.wait_for(one_updated, timeout=30, desc="exactly one cell updated")

        # Budget exhausted: the laggard must NOT be updated while the first
        # cell is unready. Hold and re-check.
        import time
        time.sleep(1.0)
        assert one_updated(), "second cell updated while budget exhausted"

        s = p.store.get("RoleBasedGroupSet", "default", "cells")
        # spec-level progress counter: the pushed cell counts, the laggard not
        assert s.status.updated_replicas == 1

        # Release → first cell converges → budget frees → second follows.
        p.kubelet.release_holds()

        def all_updated():
            gl = groups(p)
            return len(gl) == 2 and all(
                g.spec.roles[0].template.containers[0].image == "engine:v2"
                for g in gl)
        p.wait_for(all_updated, timeout=30, desc="second cell follows")
        wait_all_ready(p, "cells", 2)
        p.wait_for(
            lambda: p.store.get("RoleBasedGroupSet", "default", "cells")
            .status.updated_replicas == 2,
            timeout=30, desc="updated_replicas reaches 2")


def test_unbounded_rollout_updates_all_at_once(plane):
    """max_unavailable<=0 reproduces the reference's simultaneous update."""
    plane.apply(make_set(replicas=3, image="engine:v1", max_unavailable=0))
    wait_all_ready(plane, "cells", 3)
    plane.kubelet.hold_filter = lambda pod: True

    gs = plane.store.get("RoleBasedGroupSet", "default", "cells")
    gs.spec.template.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(gs)
    plane.wait_for(
        lambda: all(g.spec.roles[0].template.containers[0].image == "engine:v2"
                    for g in groups(plane)),
        timeout=30, desc="all cells updated simultaneously")
    plane.kubelet.release_holds()


def test_adapter_override_is_not_template_drift(plane):
    """A Bound ScalingAdapter owns a role's replicas in a child group; the
    set controller must not stomp that back to the template value (the
    group and set controllers would fight forever)."""
    from rbg_tpu.api.group import ScalingAdapterHook
    gs = make_set(replicas=1)
    gs.spec.template.spec.roles[0].scaling_adapter = ScalingAdapterHook(
        enabled=True, min_replicas=1, max_replicas=5)
    plane.apply(gs)
    wait_all_ready(plane, "cells", 1)

    def adapter_bound():
        a = plane.store.get("ScalingAdapter", "default",
                            "cells-0-server-scaling-adapter")
        return a if (a is not None and a.status.phase == "Bound") else None
    adapter = plane.wait_for(adapter_bound, desc="auto adapter bound")

    adapter = plane.store.get("ScalingAdapter", "default", adapter.metadata.name)
    adapter.spec.replicas = 3
    plane.store.update(adapter)
    plane.wait_for(
        lambda: plane.store.get("RoleBasedGroup", "default", "cells-0")
        .spec.roles[0].replicas == 3,
        timeout=20, desc="adapter override lands in child spec")

    # Hold: the override must stick (no revert to the template's 1), and
    # the child must count as template-matching.
    import time
    rv_samples = []
    for _ in range(8):
        time.sleep(0.25)
        g = plane.store.get("RoleBasedGroup", "default", "cells-0")
        assert g.spec.roles[0].replicas == 3, "set controller stomped adapter"
        rv_samples.append(g.metadata.generation)
    # No write storm: generation settles (one bump for the override itself).
    assert rv_samples[-1] == rv_samples[2]
    plane.wait_for(
        lambda: plane.store.get("RoleBasedGroupSet", "default", "cells")
        .status.updated_replicas == 1,
        timeout=10, desc="adapter-scaled child still counts as updated")


def test_budget_counts_cells_created_same_pass():
    """Scale-up + template change in ONE edit: freshly created (unready)
    cells consume the max_unavailable budget, so no stable old cell is torn
    down until the new ones come up."""
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        p.apply(make_set(replicas=2, image="engine:v1", max_unavailable=1))
        wait_all_ready(p, "cells", 2)

        p.kubelet.hold_filter = lambda pod: True  # new pods never turn Ready
        gs = p.store.get("RoleBasedGroupSet", "default", "cells")
        gs.spec.replicas = 3
        gs.spec.template.spec.roles[0].template.containers[0].image = "engine:v2"
        p.store.update(gs)

        p.wait_for(lambda: len(groups(p)) == 3, desc="cell 2 created")
        import time
        time.sleep(1.0)
        # Old cells 0/1 must still be on v1 AND serving: the new cell's
        # unreadiness exhausted the budget.
        old = [g for g in groups(p)
               if g.metadata.labels[C.LABEL_GROUP_SET_INDEX] in ("0", "1")]
        assert all(g.spec.roles[0].template.containers[0].image == "engine:v1"
                   for g in old), "stable cell torn down while scale-up pending"

        p.kubelet.release_holds()
        p.wait_for(
            lambda: all(g.spec.roles[0].template.containers[0].image
                        == "engine:v2" for g in groups(p)),
            timeout=40, desc="fleet converges to v2 once cells come up")
        wait_all_ready(p, "cells", 3)


def test_out_of_range_group_deleted_even_if_drifted(plane):
    plane.apply(make_set(replicas=2))
    wait_all_ready(plane, "cells", 2)
    gs = plane.store.get("RoleBasedGroupSet", "default", "cells")
    gs.spec.replicas = 1
    gs.spec.template.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(gs)
    plane.wait_for(lambda: len(groups(plane)) == 1, desc="scale down wins")
    plane.wait_for(
        lambda: groups(plane)[0].spec.roles[0].template.containers[0].image
        == "engine:v2",
        timeout=30, desc="survivor still gets the template")
