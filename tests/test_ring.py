"""Ring attention == dense causal attention, on the 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.ops.attention import gqa_attention
from rbg_tpu.parallel import make_mesh
from rbg_tpu.parallel.ring import ring_attention


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense(sp):
    mesh = make_mesh(dp=1, sp=sp, tp=1)
    B, S, H, KV, hd = 2, 32, 4, 2, 16
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, S, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, KV, hd), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, KV, hd), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))

    dense = gqa_attention(q, k, v, pos, jnp.ones((B, S), bool))
    ring = ring_attention(q, k, v, pos, pos, mesh, axis="sp")
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               rtol=1e-5, atol=1e-5)


def test_ring_under_jit_with_sharded_inputs():
    mesh = make_mesh(dp=2, sp=4, tp=1)
    from jax.sharding import NamedSharding, PartitionSpec as P
    B, S, H, KV, hd = 4, 64, 8, 4, 32
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(B, S, H, hd), jnp.float32)
    k = jnp.asarray(rng.randn(B, S, KV, hd), jnp.float32)
    v = jnp.asarray(rng.randn(B, S, KV, hd), jnp.float32)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32)[None], (B, S))

    sh = NamedSharding(mesh, P("dp", "sp", None, None))
    q_s = jax.device_put(q, sh)
    k_s = jax.device_put(k, sh)
    v_s = jax.device_put(v, sh)

    fn = jax.jit(lambda q, k, v, p: ring_attention(q, k, v, p, p, mesh))
    ring = fn(q_s, k_s, v_s, pos)
    dense = gqa_attention(q, k, v, pos, jnp.ones((B, S), bool))
    np.testing.assert_allclose(np.asarray(dense), np.asarray(ring),
                               rtol=1e-5, atol=1e-5)
