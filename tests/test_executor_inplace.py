"""LocalExecutor in-place update: an image-only change restarts the real
process while the pod object (uid, name, registry identity) survives."""

import os

import pytest

from rbg_tpu.api.pod import Container, Node
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, simple_role

WORKER = (
    "import os,time,socketserver,threading\n"
    "from rbg_tpu.engine.protocol import recv_msg, send_msg\n"
    "open(os.environ['MARKER'] + '.' + os.environ['RBG_CONTAINER_IMAGE'], 'a').write('x')\n"
    "class H(socketserver.BaseRequestHandler):\n"
    "    def handle(self):\n"
    "        while True:\n"
    "            o, _, _ = recv_msg(self.request)\n"
    "            if o is None: return\n"
    "            send_msg(self.request, {'ok': True})\n"
    "s = socketserver.ThreadingTCPServer(('127.0.0.1', int(os.environ['RBG_SERVE_PORT'])), H)\n"
    "s.daemon_threads = True\n"
    "threading.Thread(target=s.serve_forever, daemon=True).start()\n"
    "time.sleep(3600)\n"
)


@pytest.mark.e2e
def test_inplace_image_update_restarts_process(tmp_path):
    marker = str(tmp_path / "marker")
    role = simple_role("svc", replicas=1)
    role.template.containers = [Container(
        name="svc", image="v1", command=["python", "-c", WORKER],
    )]

    plane = ControlPlane(
        backend="local",
        executor_env={"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": None,
                      "MARKER": marker},
    )
    node = Node()
    node.metadata.name = "localhost"
    plane.store.create(node)

    with plane:
        plane.apply(make_group("ip", role))
        plane.wait_group_ready("ip", timeout=120)
        pod0 = plane.store.list("Pod", namespace="default")[0]
        uid0 = pod0.metadata.uid
        assert os.path.exists(marker + ".v1")

        cur = plane.store.get("RoleBasedGroup", "default", "ip")
        cur.spec.roles[0].template.containers[0].image = "v2"  # image-ONLY
        plane.store.update(cur)

        def restarted_in_place():
            pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
            return (pods and os.path.exists(marker + ".v2") and pods[0].running_ready)

        plane.wait_for(restarted_in_place, timeout=120,
                       desc="process restarted with new image")
        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        assert len(pods) == 1
        # In-place: same pod object — the slice/identity survived the rollout.
        assert pods[0].metadata.uid == uid0
        assert pods[0].template.containers[0].image == "v2"

        # Restart-policy-ONLY change: no container differs, so there is
        # nothing to drain and no backend ack to wait for — the group must
        # return to Ready without a process restart (review finding: the
        # gate used to wait forever for an observed_revision the executor
        # never reports on label-only patches).
        cur = plane.store.get("RoleBasedGroup", "default", "ip")
        cur.spec.roles[0].restart_policy.base_delay_seconds = 9.0
        plane.store.update(cur)

        def policy_landed():
            insts = plane.store.list("RoleInstance", namespace="default")
            return (insts
                    and insts[0].spec.restart_policy.base_delay_seconds == 9.0
                    or None)

        plane.wait_for(policy_landed, timeout=60, desc="policy landed")
        plane.wait_group_ready("ip", timeout=60)
        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        assert pods[0].metadata.uid == uid0 and pods[0].running_ready
