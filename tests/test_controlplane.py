"""Control-plane e2e (envtest-style): real controllers + fake kubelet.

Mirrors the reference's envtest tier (SURVEY.md §4 tier 2): all controllers
run against the in-process store; the FakeKubelet plays kubelet.
"""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.meta import get_condition
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import (
    make_group, make_tpu_nodes, simple_role, tpu_leaderworker_role,
)


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        yield p


def test_single_role_group_becomes_ready(plane):
    plane.apply(make_group("demo", simple_role("server", replicas=2)))
    g = plane.wait_group_ready("demo")
    st = g.status.role("server")
    assert st.replicas == 2 and st.ready_replicas == 2
    # child objects exist with the naming contract
    assert plane.store.get("RoleInstanceSet", "default", "demo-server") is not None
    assert plane.store.get("Service", "default", "s-demo-server") is not None
    pods = plane.store.list("Pod", namespace="default")
    assert len(pods) == 2
    assert {p.metadata.labels[C.LABEL_ROLE_NAME] for p in pods} == {"server"}


def test_dependency_ordering_router_waits_for_worker(plane):
    plane.apply(make_group(
        "pd",
        simple_role("worker", replicas=1),
        simple_role("router", replicas=1, dependencies=["worker"]),
    ))
    # Router pods must not exist before worker is ready; by the time the group
    # is Ready, both exist. Verify creation ordering via creation timestamps.
    plane.wait_group_ready("pd")
    pods = plane.store.list("Pod", namespace="default")
    by_role = {p.metadata.labels[C.LABEL_ROLE_NAME]: p for p in pods}
    assert set(by_role) == {"worker", "router"}
    assert (by_role["worker"].metadata.creation_timestamp
            <= by_role["router"].metadata.creation_timestamp)


def test_dependency_cycle_rejected(plane):
    g = make_group(
        "cyc",
        simple_role("a", dependencies=["b"]),
        simple_role("b", dependencies=["a"]),
    )
    plane.apply(g)

    def check():
        cur = plane.store.get("RoleBasedGroup", "default", "cyc")
        c = get_condition(cur.status.conditions, C.COND_READY)
        return c if (c and c.status == "False" and c.reason == "ValidationFailed") else None

    plane.wait_for(check, desc="validation failure condition")
    assert plane.store.list("RoleInstanceSet", namespace="default", owner_uid=None) == []


def test_leaderworker_slice_atomic_placement(plane):
    # 2x4 topology / 4 chips per host = 2 hosts per instance; 2 replicas fill
    # both fake slices. Pods of one instance must share a slice, one per host.
    plane.apply(make_group("tp", tpu_leaderworker_role("serve", replicas=2, topology="2x4")))
    g = plane.wait_group_ready("tp")
    assert g.status.role("serve").ready_replicas == 2
    pods = plane.store.list("Pod", namespace="default")
    assert len(pods) == 4
    by_inst = {}
    for p in pods:
        by_inst.setdefault(p.metadata.labels[C.LABEL_INSTANCE_NAME], []).append(p)
    assert len(by_inst) == 2
    nodes = {n.metadata.name: n for n in plane.store.list("Node")}
    for inst, ps in by_inst.items():
        slice_ids = {nodes[p.node_name].tpu.slice_id for p in ps}
        hosts = {p.node_name for p in ps}
        assert len(slice_ids) == 1, f"instance {inst} spans slices {slice_ids}"
        assert len(hosts) == len(ps), "two gang pods on one host"
        # JAX process id == slice worker index (ring-order alignment)
        for p in ps:
            envs = {e.name: e.value for e in p.template.containers[0].env}
            assert envs[C.ENV_JAX_NUM_PROCESSES] == "2"
            assert envs[C.ENV_JAX_PROCESS_ID] == p.metadata.labels[C.LABEL_COMPONENT_INDEX]
            assert C.ENV_JAX_COORDINATOR in envs


def test_gang_all_or_nothing_until_capacity(plane):
    # Needs 2 hosts in ONE slice; make a group that needs 3 hosts per instance
    # → cannot fit any 2-host slice → nothing binds.
    plane.apply(make_group("big", tpu_leaderworker_role("serve", replicas=1, topology="3x4")))

    import time
    time.sleep(0.5)
    pods = plane.store.list("Pod", namespace="default")
    assert len(pods) == 3
    assert all(not p.node_name for p in pods), "partial gang placement happened"

    # Add a 3-host slice → gang binds.
    from rbg_tpu.api.pod import Node, TpuNodeInfo
    for h in range(3):
        n = Node()
        n.metadata.name = f"bigslice-host-{h}"
        n.tpu = TpuNodeInfo(accelerator="v5e", slice_id="bigslice", worker_index=h, chips=4)
        plane.store.create(n)
    plane.wait_group_ready("big")
    pods = plane.store.list("Pod", namespace="default")
    assert all(p.node_name.startswith("bigslice") for p in pods)


def test_scale_up_and_down(plane):
    plane.apply(make_group("s", simple_role("server", replicas=1)))
    plane.wait_group_ready("s")

    g = plane.store.get("RoleBasedGroup", "default", "s")
    g.spec.roles[0].replicas = 3
    plane.store.update(g)
    plane.wait_for(
        lambda: len([p for p in plane.store.list("Pod", namespace="default") if p.active]) == 3,
        timeout=30, desc="scale up to 3",
    )
    g = plane.store.get("RoleBasedGroup", "default", "s")
    g.spec.roles[0].replicas = 1
    plane.store.update(g)
    plane.wait_for(
        lambda: len([p for p in plane.store.list("Pod", namespace="default") if p.active]) == 1,
        timeout=30, desc="scale down to 1",
    )
    # stateful: highest ordinals removed first — survivor is ordinal 0
    pod = [p for p in plane.store.list("Pod", namespace="default") if p.active][0]
    assert pod.metadata.labels[C.LABEL_INSTANCE_INDEX] == "0"


def test_orphan_role_cleanup(plane):
    plane.apply(make_group("o", simple_role("a"), simple_role("b")))
    plane.wait_group_ready("o")
    g = plane.store.get("RoleBasedGroup", "default", "o")
    g.spec.roles = [r for r in g.spec.roles if r.name == "a"]
    plane.store.update(g)
    plane.wait_for(
        lambda: plane.store.get("RoleInstanceSet", "default", "o-b") is None,
        desc="orphan RIS deleted",
    )
    plane.wait_for(
        lambda: plane.store.get("Service", "default", "s-o-b") is None,
        desc="orphan service deleted",
    )


def test_group_delete_cascades(plane):
    plane.apply(make_group("d", simple_role("server", replicas=2)))
    plane.wait_group_ready("d")
    plane.store.delete("RoleBasedGroup", "default", "d")
    plane.wait_for(
        lambda: not plane.store.list("Pod", namespace="default"),
        desc="cascade delete pods",
    )
    assert plane.store.list("RoleInstanceSet", namespace="default") == []


def test_restart_policy_recreates_gang_with_backoff(plane):
    from rbg_tpu.api.group import RestartPolicyConfig
    role = simple_role("server", replicas=1)
    role.restart_policy = RestartPolicyConfig(base_delay_seconds=0.05, max_delay_seconds=1.0)
    plane.apply(make_group("r", role))
    plane.wait_group_ready("r")
    pod0 = plane.store.list("Pod", namespace="default")[0]
    uid0 = pod0.metadata.uid

    plane.kubelet.fail_pod("default", pod0.metadata.name)

    def recreated():
        ps = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return ps and all(p.metadata.uid != uid0 for p in ps) and ps[0].running_ready

    plane.wait_for(recreated, desc="pod gang recreated")
    inst = plane.store.list("RoleInstance", namespace="default")[0]
    assert inst.status.restart_count == 1
    assert inst.status.last_restart_time > 0
    plane.wait_group_ready("r")


def test_rolling_update_recreates_descending(plane):
    from rbg_tpu.api.group import RollingUpdate
    role = simple_role("server", replicas=3)
    # Force the recreate path (the in-place engine would otherwise absorb an
    # image-only change without recreation — covered in test_coordination).
    role.rolling_update = RollingUpdate(max_unavailable=1, in_place_if_possible=False)
    plane.apply(make_group("u", role))
    plane.wait_group_ready("u")
    old_uids = {p.metadata.labels[C.LABEL_INSTANCE_NAME]: p.metadata.uid
                for p in plane.store.list("Pod", namespace="default")}

    g = plane.store.get("RoleBasedGroup", "default", "u")
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(g)

    def all_updated():
        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return (len(pods) == 3
                and all(p.template.containers[0].image == "engine:v2" for p in pods)
                and all(p.running_ready for p in pods))

    plane.wait_for(all_updated, timeout=15, desc="rolling update complete")
    new_uids = {p.metadata.labels[C.LABEL_INSTANCE_NAME]: p.metadata.uid
                for p in plane.store.list("Pod", namespace="default")}
    assert set(new_uids) == set(old_uids)
    assert all(new_uids[k] != old_uids[k] for k in old_uids)

    def status_converged():
        ris = plane.store.get("RoleInstanceSet", "default", "u-server")
        return (ris.status.updated_replicas == 3
                and ris.status.updated_ready_replicas == 3)

    plane.wait_for(status_converged, desc="RIS status rollup")


def test_warm_slice_rebinding_after_restart(plane):
    """Atomic slice recovery: a restarted multi-host instance returns to the
    SAME slice (warm HBM/compile caches) — SURVEY.md §7 hard parts."""
    from rbg_tpu.api.group import RestartPolicyConfig
    role = tpu_leaderworker_role("serve", replicas=1, topology="2x4")
    role.restart_policy = RestartPolicyConfig(base_delay_seconds=0.01)
    plane.apply(make_group("warm", role))
    plane.wait_group_ready("warm")
    nodes = {n.metadata.name: n for n in plane.store.list("Node")}
    pods0 = [p for p in plane.store.list("Pod", namespace="default")]
    slice0 = {nodes[p.node_name].tpu.slice_id for p in pods0}.pop()
    uids0 = {p.metadata.uid for p in pods0}

    plane.kubelet.fail_pod("default", pods0[0].metadata.name)

    def recreated_ready():
        ps = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return (len(ps) == 2 and uids0.isdisjoint({p.metadata.uid for p in ps})
                and all(p.running_ready for p in ps))

    # 30 s: recreate goes through restart backoff + scheduler + kubelet
    # ready — comfortable solo, but the full tier-1 run's ambient load has
    # pushed it past a 15 s budget (order-dependent flake otherwise).
    plane.wait_for(recreated_ready, timeout=30, desc="gang recreated")
    pods1 = [p for p in plane.store.list("Pod", namespace="default") if p.active]
    slice1 = {nodes[p.node_name].tpu.slice_id for p in pods1}.pop()
    assert slice1 == slice0, f"instance moved {slice0} -> {slice1} (cold slice)"
