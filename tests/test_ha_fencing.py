"""Control-plane HA: lease lifecycle, fencing-token refusal, and the
FENCING dimension of every annotation-carried state machine.

The protocol under test: the store's lease object mints a monotonically
increasing EPOCH per leadership term; every write a leader issues carries
its (lease, epoch) fence; a deposed leader's in-flight writes — replayed
after a takeover minted a newer epoch — are refused with ``LeaseFenced``
and must leave state untouched. The three resumable state machines
(PR-3 migrations, PR-13 topology flips, PR-9 autoscale stamps) all write
annotations through this fence, so one stale-epoch test per path pins
the no-double-actuation guarantee.
"""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.ha import DEFAULT_LEASE, FencedStore, LeaderElector
from rbg_tpu.runtime.store import LeaseFenced, Store
from rbg_tpu.testutil import make_group, simple_role


# ---- lease object ----------------------------------------------------------


def test_lease_acquire_renew_keeps_epoch():
    st = Store()
    e1 = st.acquire_lease("L", "a", ttl_s=10.0, now=0.0)
    assert e1 == 1
    # Re-acquisition by the SAME holder is a renewal, not a term change.
    assert st.acquire_lease("L", "a", ttl_s=10.0, now=5.0) == e1
    assert st.renew_lease("L", "a", e1, ttl_s=10.0, now=9.0)
    info = st.lease_info("L", now=9.0)
    assert info["holder"] == "a" and info["epoch"] == e1
    assert info["expires_in_s"] == pytest.approx(10.0)


def test_lease_contended_then_expired_mints_fresh_epoch():
    st = Store()
    e1 = st.acquire_lease("L", "a", ttl_s=10.0, now=0.0)
    # Live lease: the standby's campaign loses.
    assert st.acquire_lease("L", "b", ttl_s=10.0, now=5.0) is None
    # TTL elapsed: takeover mints epoch+1; the old term's renewals are
    # refused from that instant (deposed — stop acting as leader).
    e2 = st.acquire_lease("L", "b", ttl_s=10.0, now=10.1)
    assert e2 == e1 + 1
    assert not st.renew_lease("L", "a", e1, ttl_s=10.0, now=10.2)


def test_lease_graceful_release_skips_ttl_wait():
    st = Store()
    e1 = st.acquire_lease("L", "a", ttl_s=60.0, now=0.0)
    # Only the current (holder, epoch) may release.
    assert not st.release_lease("L", "b", e1, now=1.0)
    assert not st.release_lease("L", "a", e1 + 1, now=1.0)
    assert st.release_lease("L", "a", e1, now=1.0)
    # Standby acquires immediately — no TTL wait — with a FRESH epoch.
    assert st.acquire_lease("L", "b", ttl_s=60.0, now=1.1) == e1 + 1


# ---- fenced writes ---------------------------------------------------------


def _group_store(name="g"):
    st = Store()
    st.create(make_group(name, simple_role("serve", replicas=1)))
    return st


def test_stale_epoch_write_refused_and_counted():
    st = _group_store()
    e_old = st.acquire_lease("L", "a", ttl_s=10.0, now=0.0)
    st.acquire_lease("L", "b", ttl_s=10.0, now=10.1)  # depose a

    before = REGISTRY.counter(obs_names.PLANE_FENCED_WRITES_TOTAL,
                              lease="L")

    def poison(g):
        g.metadata.annotations["x"] = "1"
        return True

    with pytest.raises(LeaseFenced) as ei:
        st.mutate("RoleBasedGroup", "default", "g", poison,
                  fence=("L", e_old))
    assert ei.value.stale_epoch == e_old
    assert ei.value.current_epoch == e_old + 1
    assert ei.value.holder == "b"
    g = st.get("RoleBasedGroup", "default", "g")
    assert "x" not in g.metadata.annotations, "fenced write landed"
    assert REGISTRY.counter(obs_names.PLANE_FENCED_WRITES_TOTAL,
                            lease="L") == before + 1


def test_mutate_noop_path_still_fence_checked():
    """A deposed leader's read-modify-write that HAPPENS to be a no-op
    must still be refused: the caller's next write won't be a no-op, and
    'sometimes fenced' is not a protocol."""
    st = _group_store()
    e_old = st.acquire_lease("L", "a", ttl_s=10.0, now=0.0)
    st.acquire_lease("L", "b", ttl_s=10.0, now=10.1)
    with pytest.raises(LeaseFenced):
        st.mutate("RoleBasedGroup", "default", "g", lambda g: False,
                  fence=("L", e_old))


def test_current_epoch_write_succeeds():
    st = _group_store()
    st.acquire_lease("L", "a", ttl_s=10.0, now=0.0)
    e_new = st.acquire_lease("L", "b", ttl_s=10.0, now=10.1)

    def mark(g):
        g.metadata.annotations["owner"] = "b"
        return True

    st.mutate("RoleBasedGroup", "default", "g", mark, fence=("L", e_new))
    assert st.get("RoleBasedGroup", "default",
                  "g").metadata.annotations["owner"] == "b"


def test_fenced_store_proxy_stamps_every_write():
    st = _group_store()
    e_old = st.acquire_lease(DEFAULT_LEASE, "a", ttl_s=10.0, now=0.0)
    deposed = FencedStore(st, DEFAULT_LEASE, e_old)
    st.acquire_lease(DEFAULT_LEASE, "b", ttl_s=10.0, now=10.1)

    with pytest.raises(LeaseFenced):
        deposed.create(make_group("g2", simple_role("serve")))
    g = st.get("RoleBasedGroup", "default", "g")
    with pytest.raises(LeaseFenced):
        deposed.update(g)
    with pytest.raises(LeaseFenced):
        deposed.update_status(g)
    with pytest.raises(LeaseFenced):
        deposed.mutate("RoleBasedGroup", "default", "g",
                       lambda o: True)
    with pytest.raises(LeaseFenced):
        deposed.delete("RoleBasedGroup", "default", "g")
    # Reads pass through unfenced — a deposed process may still observe.
    assert deposed.get("RoleBasedGroup", "default", "g") is not None
    assert st.get("RoleBasedGroup", "default", "g2") is None


# ---- FENCING dimension: the three resumable state machines -----------------
#
# Each path writes its durable state through an annotation; the test
# replays the exact write a deposed leader would issue and asserts (a)
# LeaseFenced, (b) state byte-identical, (c) the successor's same write
# with the current epoch lands.


def _deposed_pair(st, lease="L"):
    e_old = st.acquire_lease(lease, "a", ttl_s=10.0, now=0.0)
    e_new = st.acquire_lease(lease, "b", ttl_s=10.0, now=10.1)
    return e_old, e_new


@pytest.mark.parametrize("ann,value", [
    (C.ANN_MIGRATION_STATE, C.MIGRATION_WARMING),      # PR-3 migrations
    (C.ANN_TOPOLOGY_STATE, "Warming"),                 # PR-13 flips
    (C.ANN_AUTOSCALE_LAST_WRITE, "3"),                 # PR-9 stamps
])
def test_state_machine_write_fenced_then_resumed(ann, value):
    st = _group_store()
    e_old, e_new = _deposed_pair(st)

    def advance(g):
        g.metadata.annotations[ann] = value
        return True

    with pytest.raises(LeaseFenced):
        st.mutate("RoleBasedGroup", "default", "g", advance,
                  fence=("L", e_old))
    g = st.get("RoleBasedGroup", "default", "g")
    assert ann not in g.metadata.annotations

    # The standby resumes the machine with ITS epoch: same write, lands.
    st.mutate("RoleBasedGroup", "default", "g", advance,
              fence=("L", e_new))
    g = st.get("RoleBasedGroup", "default", "g")
    assert g.metadata.annotations[ann] == value


# ---- elector on scripted clocks -------------------------------------------


class _DummyPlane:
    def __init__(self):
        self.started = self.stopped = 0

    def start(self):
        self.started += 1

    def stop(self):
        self.stopped += 1


def _elector(name, st, clock_slot):
    return LeaderElector(name, st, lambda fenced: _DummyPlane(),
                         ttl_s=1.0, clock=lambda: clock_slot["t"])


def test_elector_scripted_takeover_and_fenced_replay():
    st = Store()
    t = {"t": 0.0}
    a, b = _elector("a", st, t), _elector("b", st, t)
    a._subscribe_tail()
    b._subscribe_tail()

    a.tick(now=0.0)
    b.tick(now=0.1)
    assert a.is_leader and not b.is_leader
    assert a.plane.started == 1
    assert a.transitions == 1 and b.transitions == 0

    # Renewals hold the lease while the clock advances inside the TTL.
    a.tick(now=0.9)
    b.tick(now=0.95)
    assert a.is_leader and not b.is_leader

    # Crash: A stops renewing; B campaigns past the TTL and takes over.
    deposed = a.fenced_store
    b.tick(now=2.0)
    assert b.is_leader and b.transitions == 1
    assert b.epoch == a.epoch + 1

    # A's replayed in-flight write is refused; its next tick deposes it.
    with pytest.raises(LeaseFenced):
        deposed.create(make_group("late", simple_role("serve")))
    plane_a = a.plane
    a.tick(now=2.1)
    assert not a.is_leader
    assert plane_a.stopped == 1

    # The standby tailed every write of A's term (warm resume point).
    assert b.tailed_events >= 0
    snap = b.snapshot()
    assert snap["leader"] and snap["lease_holder"] == "b"


def test_elector_graceful_stop_hands_over_without_ttl_wait():
    st = Store()
    t = {"t": 0.0}
    a, b = _elector("a", st, t), _elector("b", st, t)
    a.tick(now=0.0)
    assert a.is_leader
    t["t"] = 0.5
    a.stop()          # releases the lease at t=0.5, well inside the TTL
    b.tick(now=0.6)   # immediate takeover — no TTL wait
    assert b.is_leader and b.epoch == 2


def test_standby_tails_store_writes():
    st = Store()
    t = {"t": 0.0}
    b = _elector("b", st, t)
    b._subscribe_tail()
    before = b.tailed_events
    st.create(make_group("g", simple_role("serve")))
    st.mutate("RoleBasedGroup", "default", "g",
              lambda g: g.metadata.annotations.update(x="1") or True)
    assert b.tailed_events >= before + 2
    assert b.tail_rv > 0


def test_takeover_finishes_tail_catchup_before_actuating():
    """A standby behind on its watch tail must catch up to the store's
    watermark BEFORE its plane starts. In-process watch delivery is
    synchronous, so the gate is satisfiable immediately — the assertion
    is that it ran and measured zero lag, not that it spun."""
    st = Store()
    st.create(make_group("g", simple_role("serve")))
    t = {"t": 0.0}
    b = _elector("b", st, t)
    b._subscribe_tail()
    b.tick(now=0.0)
    assert b.is_leader and b.plane.started == 1
    assert b.catchup_lag_rv == 0
    assert b.tail_rv >= st.current_rv() or b.tailed_events == 0


# ---- fencing under clock skew (chaos SKEW schedule) ------------------------


def _skewed_pair(st, offsets, window=(0.0, 100.0)):
    from rbg_tpu.chaos import (SKEW, ChaosClock, FaultSchedule,
                               FaultWindow, SkewedClock)

    base = ChaosClock(t0=0.0)
    sched = FaultSchedule(
        [FaultWindow(SKEW, window[0], window[1],
                     params={"offsets": offsets})], clock=base)
    clocks = {w: SkewedClock(base, sched, w) for w in ("a", "b")}

    def mk(n):
        return LeaderElector(n, st, lambda fenced: _DummyPlane(),
                             ttl_s=1.0, clock=clocks[n], tail=False)

    return base, clocks, mk("a"), mk("b")


def test_skewed_standby_takeover_fences_deposed_writer_mid_replay():
    """B's clock runs 0.4 s FAST (chaos SKEW window): it sees A's lease
    expire early and takes over while A — on true time — still believes
    it leads. The epoch fence, not the clocks, is what keeps A's
    mid-takeover replay out: the no-op mutate path first ('sometimes
    fenced' is not a protocol), then the real write; both refused, state
    untouched, and the successor's same write lands."""
    st = Store()
    st.create(make_group("g", simple_role("serve")))
    base, clocks, a, b = _skewed_pair(st, {"b": 0.4})
    a.tick(now=clocks["a"]())
    assert a.is_leader
    deposed = a.fenced_store

    base.set(0.7)                     # true 0.7 → B reads 1.1 > TTL
    b.tick(now=clocks["b"]())
    assert b.is_leader and b.epoch == a.epoch + 1

    with pytest.raises(LeaseFenced):  # no-op path, still fence-checked
        deposed.mutate("RoleBasedGroup", "default", "g", lambda g: False)

    def poison(g):
        g.metadata.annotations["skew-poison"] = "1"
        return True

    with pytest.raises(LeaseFenced):  # the real in-flight write
        deposed.mutate("RoleBasedGroup", "default", "g", poison)
    g = st.get("RoleBasedGroup", "default", "g")
    assert "skew-poison" not in g.metadata.annotations

    # The successor resumes the same machine with ITS epoch: lands.
    b.fenced_store.mutate("RoleBasedGroup", "default", "g",
                          lambda o: o.metadata.annotations.update(
                              owner="b") or True)
    assert st.get("RoleBasedGroup", "default",
                  "g").metadata.annotations["owner"] == "b"

    # A's own next renewal — still on its slow clock — deposes it.
    a.tick(now=clocks["a"]())
    assert not a.is_leader


def test_skew_fault_is_counted_once_per_window_entry():
    st = Store()
    base, clocks, a, b = _skewed_pair(st, {"b": 0.4}, window=(0.5, 2.0))
    before = REGISTRY.counter(obs_names.CHAOS_FAULTS_INJECTED_TOTAL,
                              kind="skew")
    assert clocks["b"]() == 0.0       # window closed: no offset, no count
    base.set(1.0)
    assert clocks["b"]() == 1.4
    clocks["b"]()
    assert REGISTRY.counter(obs_names.CHAOS_FAULTS_INJECTED_TOTAL,
                            kind="skew") == before + 1
    assert a.name == "a" and b.name == "b"


# ---- self-demotion: renewals RAISE (coordinator partition) -----------------


class _FlakyLeaseStore:
    """Coordinator-partition sim: renew_lease RAISES while every other
    store surface (including fenced data writes) still works."""

    def __init__(self, inner):
        self._inner = inner
        self.fail = False

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def renew_lease(self, *a, **kw):
        if self.fail:
            raise OSError("lease store unreachable")
        return self._inner.renew_lease(*a, **kw)


def test_renewal_raise_self_demotes_before_ttl_expiry():
    st = Store()
    fl = _FlakyLeaseStore(st)
    el = LeaderElector("a", fl, lambda fenced: _DummyPlane(), ttl_s=1.0,
                       clock=lambda: 0.0, tail=False)
    before = REGISTRY.counter(obs_names.PLANE_SELF_DEMOTIONS_TOTAL,
                              plane="a")
    el.tick(now=0.0)
    assert el.is_leader
    el.tick(now=0.2)                  # last confirmed renewal at 0.2
    fl.fail = True
    el.tick(now=0.4)                  # 0.2 s since last OK: holds on
    assert el.is_leader and el.self_demotions == 0
    plane = el.plane
    el.tick(now=0.75)                 # 0.55 s >= ttl/2: demote NOW —
    assert not el.is_leader           # lease would expire at 1.2
    assert el.self_demotions == 1 and plane.stopped == 1
    assert REGISTRY.counter(obs_names.PLANE_SELF_DEMOTIONS_TOTAL,
                            plane="a") == before + 1
    assert REGISTRY.gauge(obs_names.DEGRADED_MODE, ladder="lease") == 1.0

    # A healthy standby still waits out the TTL — the demotion at 0.75
    # strictly precedes its earliest takeover: the terms never overlap.
    b = _elector("b", st, {"t": 0.0})
    b.tick(now=1.0)
    assert not b.is_leader
    b.tick(now=1.3)
    assert b.is_leader

    # The healed ex-leader re-campaigns as a standby (clean ladder exit).
    fl.fail = False
    el.tick(now=1.4)
    assert not el.is_leader           # b holds a live lease now
