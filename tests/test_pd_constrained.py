"""PD disaggregation × constrained decoding.

Regression suite for the DecodeWorker.inject grammar handoff: a json_mode
bundle used to crash the decode batch (req.grammar stayed None while
req.gstate was set), and regex/json_schema bundles silently decoded
UNCONSTRAINED. Now all three constraint kinds resolve the grammar at
inject, fold the prefill-side first token into the state, and decode
bit-identically to a unified engine — including through a real router
over real prefill/decode server subprocesses."""

import json
import re
import threading

import jax
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.pd import PDPair
from rbg_tpu.engine.tokenizer import ByteTokenizer

_TOK = ByteTokenizer()

SCHEMA = {"type": "object", "properties": {
    "id": {"type": "integer"},
    "state": {"enum": ["on", "off"]},
}}


def ecfg(**kw):
    base = dict(model="tiny", vocab_size=512, page_size=8, num_pages=128,
                max_batch=4, max_seq_len=256, prefill_chunk=16,
                use_pallas="never")
    base.update(kw)
    return EngineConfig(**base)


def _wired_pair(**kw):
    pair = PDPair(ecfg(**kw))
    pair.prefill.engine.enable_json_grammar(_TOK)
    pair.decode.engine.enable_json_grammar(_TOK)
    return pair


CONSTRAINTS = [
    ("json_mode", dict(json_mode=True)),
    ("regex", dict(regex=r"\d{3}-\d{4}")),
    ("json_schema", dict(json_schema=SCHEMA)),
]


@pytest.mark.parametrize("kind,fields", CONSTRAINTS)
def test_pd_constrained_matches_unified(kind, fields):
    """Each constraint kind round-trips PD token-identically to a unified
    engine — the inject fix folds the first token into the grammar state
    for ALL kinds, not just json_mode."""
    sp = SamplingParams(max_new_tokens=40, temperature=0.8, seed=5,
                        stop_token=_TOK.eos_id, **fields)
    prompt = _TOK.encode(kind + ":", add_bos=False)
    pair = _wired_pair()
    uni = Engine(ecfg(enable_radix_cache=False),
                 params=pair.prefill.engine.params)
    uni.enable_json_grammar(_TOK)
    expect = uni.generate([prompt], sp)[0]
    got = pair.generate([prompt], sp)[0]
    assert got == expect
    # The decode side really carries the grammar (constraint enforced,
    # not vacuously equal).
    text = _TOK.decode([t for t in got if t != _TOK.eos_id])
    if kind == "regex":
        assert re.fullmatch(r"\d{3}-\d{4}", text), text
    elif kind == "json_schema":
        doc = json.loads(text)
        assert set(doc) == {"id", "state"}


def test_pd_inject_sets_grammar_state():
    pair = _wired_pair()
    sp = SamplingParams(max_new_tokens=20, temperature=0.7, seed=2,
                        regex=r"[ab]{2,20}c", stop_token=_TOK.eos_id)
    bundle = pair.prefill.prefill(_TOK.encode("x:", add_bos=False), sp)
    rid = pair.decode.inject(bundle, sp)
    req = pair.decode.engine.requests[rid]
    assert req.grammar is not None and req.gstate is not None
    # gstate already reflects the prefill-side first token.
    g = req.grammar
    assert req.gstate == g.advance_token(g.initial(), bundle.first_token)


def test_pd_inject_rejects_constraint_violating_first_token():
    """A first token the grammar forbids means the prefill peer ignored
    the constraint (mixed-version deploy): reject the bundle, leak no
    pages."""
    pair = _wired_pair()
    sp = SamplingParams(max_new_tokens=8, regex=r"\d+",
                        stop_token=_TOK.eos_id)
    bundle = pair.prefill.prefill(_TOK.encode("n:", add_bos=False), sp)
    bundle.first_token = ord("x")          # not a digit
    free_before = pair.decode.engine.allocator.free_pages
    with pytest.raises(ValueError, match="violates"):
        pair.decode.inject(bundle, sp)
    assert pair.decode.engine.allocator.free_pages == free_before


def test_pd_constrained_decode_uses_fused_tables():
    """On the decode side, a tabled grammar bundle decodes through the
    fused window (no host-synced steps) — the PD handoff composes with
    device-resident grammar decode."""
    pair = _wired_pair(multi_step=4)
    sp = SamplingParams(max_new_tokens=30, temperature=0.8, seed=9,
                        regex=r"[mn]{4,24}o", stop_token=_TOK.eos_id)
    out = pair.generate([_TOK.encode("t:", add_bos=False)], sp)[0]
    assert re.fullmatch(r"[mn]{4,24}o?",
                        _TOK.decode([t for t in out if t != _TOK.eos_id]))
    assert pair.decode.engine.metrics["spec_steps"] == 0


@pytest.mark.slow
@pytest.mark.e2e
def test_pd_constrained_through_router():
    """guided json_mode / regex / json_schema through a REAL router over
    real prefill+decode server subprocesses: the router forwards the
    constraint on both legs, the decode replica enforces it, and the
    response satisfies it."""
    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once
    from rbg_tpu.engine.router import (Handler, Registry, RouterServer,
                                       RouterState)

    args = ["--model", "tiny", "--vocab-size", "512", "--page-size", "8",
            "--num-pages", "128", "--max-seq-len", "256",
            "--prefill-chunk", "16", "--use-pallas", "never"]
    with SpawnedEngineServer("--mode", "prefill", *args) as pf, \
            SpawnedEngineServer("--mode", "decode", *args) as dc:
        router = RouterServer(("127.0.0.1", 0), Handler)
        router.state = RouterState(Registry(None), None,
                                   {"prefill": [pf.addr],
                                    "decode": [dc.addr]})
        threading.Thread(target=router.serve_forever, daemon=True).start()
        addr = f"127.0.0.1:{router.server_address[1]}"
        try:
            prompt = _TOK.encode("emit:", add_bos=False)
            base = {"op": "generate", "prompt": prompt,
                    "max_new_tokens": 40, "temperature": 0.8,
                    "stop_token": _TOK.eos_id}

            r, _, _ = request_once(addr, {**base, "seed": 3,
                                          "json_mode": True}, timeout=300)
            assert "error" not in r, r
            text = _TOK.decode(r["tokens"])
            st = JsonPrefixOK(text)
            assert st, text

            r, _, _ = request_once(addr, {**base, "seed": 4,
                                          "regex": r"\d{3}-\d{4}"},
                                   timeout=300)
            assert "error" not in r, r
            text = _TOK.decode([t for t in r["tokens"]
                                if t != _TOK.eos_id])
            assert re.fullmatch(r"\d{3}-\d{4}", text), text

            r, _, _ = request_once(addr, {**base, "seed": 5,
                                          "json_schema": SCHEMA},
                                   timeout=300)
            assert "error" not in r, r
            doc = json.loads(_TOK.decode([t for t in r["tokens"]
                                          if t != _TOK.eos_id]))
            assert set(doc) == {"id", "state"}
            assert router.state.metrics["pd_requests"] == 3
        finally:
            router.shutdown()
            router.server_close()


def JsonPrefixOK(text: str) -> bool:
    """Valid JSON, or a legal truncated prefix of one (budget cut)."""
    from rbg_tpu.engine.grammar import JsonGrammar
    g = JsonGrammar()
    s = g.initial()
    for b in text.encode():
        s = g.advance(s, b)
        if s is None:
            return False
    return True
