"""Full-stack e2e: the control plane orchestrates REAL engine processes.

The closest analog to the reference's kind-cluster e2e tier (SURVEY.md §4
tier 3): apply a PD-disagg RoleBasedGroup → the scheduler places pods → the
LocalExecutor spawns actual engine/router subprocesses with the injected
env → dependency ordering gates the router until prefill+decode serve →
a generate request flows router → prefill (KV bundle over TCP) → decode.
"""

import numpy as np
import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import RoleSpec
from rbg_tpu.api.pod import Container, Node, PodTemplate
from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.protocol import request_once
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group

ENGINE_ARGS = ["--model", "tiny", "--page-size", "8", "--num-pages", "128",
               "--max-seq-len", "128", "--prefill-chunk", "16",
               "--use-pallas", "never"]


def engine_role(name: str, mode: str) -> RoleSpec:
    return RoleSpec(
        name=name, replicas=1,
        template=PodTemplate(containers=[Container(
            name="engine",
            command=["python", "-m", "rbg_tpu.engine.server"],
            args=["--mode", mode] + ENGINE_ARGS,
        )]),
    )


def router_role() -> RoleSpec:
    return RoleSpec(
        name="router", replicas=1, dependencies=["prefill", "decode"],
        template=PodTemplate(containers=[Container(
            name="router",
            command=["python", "-m", "rbg_tpu.engine.router"],
        )]),
    )


@pytest.mark.e2e
@pytest.mark.slow
def test_pd_disagg_serves_through_real_processes(tmp_path):
    plane = ControlPlane(
        backend="local",
        executor_env={
            "JAX_PLATFORMS": "cpu", "RBG_TPU_NATIVE": "1",
            # Engines here are CPU-only: drop the image's TPU-relay hook
            # trigger so sitecustomize can't stall interpreter start when the
            # relay is busy (see .claude/skills/verify/SKILL.md).
            "PALLAS_AXON_POOL_IPS": None,
        },
    )
    node = Node()
    node.metadata.name = "localhost"
    plane.store.create(node)

    with plane:
        plane.apply(make_group(
            "pd", engine_role("prefill", "prefill"),
            engine_role("decode", "decode"), router_role(),
        ))
        plane.wait_group_ready("pd", timeout=180)

        # Dependency contract: router started only after prefill+decode ready.
        pods = plane.store.list("Pod", namespace="default")
        by_role = {p.metadata.labels[C.LABEL_ROLE_NAME]: p for p in pods}
        assert set(by_role) == {"prefill", "decode", "router"}

        router_port = plane.kubelet.port_of("default", by_role["router"].metadata.name)
        assert router_port is not None

        # Health: router must report PD mode (both roles discovered).
        health, _, _ = request_once(f"127.0.0.1:{router_port}", {"op": "health"})
        assert health["ok"] and health["pd"] is True

        prompt = list(range(1, 13))
        resp, _, _ = request_once(
            f"127.0.0.1:{router_port}",
            {"op": "generate", "prompt": prompt, "max_new_tokens": 6},
            timeout=300.0,
        )
        assert "error" not in resp, resp
        tokens = resp["tokens"]
        assert len(tokens) == 6

        # Numerics: identical to an in-process engine with the same seed.
        ref = Engine(EngineConfig(model="tiny", page_size=8, num_pages=128,
                                  max_seq_len=128, prefill_chunk=16,
                                  use_pallas="never"))
        expect = ref.generate([prompt], SamplingParams(max_new_tokens=6))[0]
        assert tokens == expect

        # KV actually crossed the wire.
        health, _, _ = request_once(f"127.0.0.1:{router_port}", {"op": "health"})
        assert health["metrics"]["kv_bytes_routed"] > 0
        assert health["metrics"]["pd_requests"] == 1
