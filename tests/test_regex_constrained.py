"""Regex-constrained decoding (`regex` sampling param): the byte-level
NFA grammar, trie-mask exactness, engine integration (every finished
output matches the anchored pattern), composition with speculative
decoding, and admission errors for bad patterns."""

import re

import jax
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.grammar import (RegexGrammar, TokenGrammar,
                                    token_bytes_for)
from rbg_tpu.engine.tokenizer import ByteTokenizer
from rbg_tpu.models import get_config, init_params


# ---- automaton semantics vs Python re (anchored) ----


@pytest.mark.parametrize("pattern,accept,reject", [
    (r"\d{3}-\d{4}", ["555-1234"], ["555-123", "5551-234", "x55-1234"]),
    (r"(yes|no)", ["yes", "no"], ["", "y", "yesno"]),
    (r"[A-Fa-f0-9]{2,8}", ["deadBEEF", "00"], ["0", "deadbeef0x"]),
    (r"-?\d+(\.\d+)?", ["-3.14", "42"], ["3.", ".5", "-"]),
    (r"[^ ]+@[^ ]+", ["a@b"], ["a@", " a@b"]),
    (r"a+b?", ["a", "aab"], ["b", "abb"]),
])
def test_regex_grammar_matches_re_semantics(pattern, accept, reject):
    g = RegexGrammar(pattern)

    def full(s):
        st = g.initial()
        for b in s.encode():
            st = g.advance(st, b)
            if st is None:
                return False
        return g.is_complete(st)

    for s in accept:
        assert re.fullmatch(pattern, s), f"test vector wrong: {s}"
        assert full(s), f"{pattern} should accept {s}"
    for s in reject:
        assert not re.fullmatch(pattern, s), f"test vector wrong: {s}"
        assert not full(s), f"{pattern} should reject {s}"


def test_regex_negated_escapes_and_utf8_safety():
    """\\D / \\W / \\S are real negated classes (not literal letters), and
    '.', negated classes, and negated escapes stay within ASCII so the
    mask can never force-sample a lone UTF-8 fragment byte."""
    g = RegexGrammar(r"\D")
    assert g.advance(g.initial(), ord("x")) is not None
    assert g.advance(g.initial(), ord("5")) is None
    gw = RegexGrammar(r"\W")
    assert gw.advance(gw.initial(), ord("!")) is not None
    assert gw.advance(gw.initial(), ord("a")) is None
    for pat in (r".", r"[^0-9]", r"\S"):
        gp = RegexGrammar(pat)
        assert gp.advance(gp.initial(), 0x80) is None, pat  # UTF-8 fragment
    # Non-ASCII literals still match their full byte sequence.
    gl = RegexGrammar("é+")
    st = gl.initial()
    for b in "éé".encode():
        st = gl.advance(st, b)
        assert st is not None
    assert gl.is_complete(st)


def test_regex_grammars_share_one_trie(eng_factory):
    eng = eng_factory()
    g1 = eng._regex_grammar(r"\d+")
    g2 = eng._regex_grammar(r"[a-z]+")
    assert g1.trie is g2.trie is eng.grammar.trie


def test_regex_cache_is_lru_not_fifo(eng_factory):
    eng = eng_factory()
    eng._REGEX_GRAMMAR_CACHE = 2
    hot = eng._regex_grammar(r"\d+")
    eng._regex_grammar(r"[a-z]+")
    eng._regex_grammar(r"\d+")        # refresh the hot pattern
    eng._regex_grammar(r"[A-Z]+")     # evicts [a-z]+, not the hot one
    assert eng._regex_grammar(r"\d+") is hot


def test_regex_bad_patterns_raise():
    for bad in ["(open", "a{3,1}", "[z-a]", "*lead", "x{bad}", "[unterm",
                "trail\\"]:
        with pytest.raises(ValueError):
            RegexGrammar(bad)


def test_regex_trie_mask_equals_probe():
    tok = ByteTokenizer()
    tg = TokenGrammar(RegexGrammar(r"(GET|POST) /[a-z/]* HTTP"),
                      token_bytes_for(tok), tok.eos_id)
    s = tg.initial()
    for b in b"GET /api/":
        np.testing.assert_array_equal(tg.mask(s), tg._mask_probe(s))
        s = tg.grammar.advance(s, b)
        assert s is not None
    np.testing.assert_array_equal(tg.mask(s), tg._mask_probe(s))


# ---- engine integration ----


@pytest.fixture(scope="module")
def eng_factory():
    cfg = get_config("tiny", vocab_size=512)
    params = init_params(cfg, jax.random.key(0))

    def make(**kw):
        e = Engine(EngineConfig(model="tiny", vocab_size=512, page_size=8,
                                num_pages=128, max_seq_len=256,
                                use_pallas="never", **kw), params=params)
        e.mcfg = cfg
        e.enable_json_grammar(ByteTokenizer())
        return e

    return make


PATTERNS = [r"\d{3}-\d{4}", r"(alpha|beta|gamma)", r"[a-f]{4,12}"]


def test_regex_outputs_match_pattern(eng_factory):
    eng = eng_factory()
    tok = ByteTokenizer()
    for seed, pattern in enumerate(PATTERNS):
        rid = eng.add_request(
            tok.encode("value:"),
            SamplingParams(max_new_tokens=24, temperature=0.9, seed=seed,
                           regex=pattern, stop_token=tok.eos_id))
        out = []
        while eng.has_work():
            for ev in eng.step():
                if ev.request_id == rid:
                    out.append(ev.token)
        text = tok.decode(out)
        assert re.fullmatch(pattern, text), (pattern, text)


def test_regex_composes_with_speculative(eng_factory):
    eng = eng_factory(speculative="ngram", spec_k=4, spec_ngram=3)
    tok = ByteTokenizer()
    pattern = r"(ab)+c"
    rid = eng.add_request(
        tok.encode("repeat: ababab"),
        SamplingParams(max_new_tokens=20, temperature=0.8, seed=3,
                       regex=pattern, stop_token=tok.eos_id))
    out = []
    while eng.has_work():
        for ev in eng.step():
            if ev.request_id == rid:
                out.append(ev.token)
    assert re.fullmatch(pattern, tok.decode(out))


@pytest.mark.slow
def test_regex_mixed_batch_leaves_unconstrained_rows_alone(eng_factory):
    """A regex row and a plain greedy row decode together; the greedy
    row's output is identical to a solo run (constrained rows must not
    perturb the fused path)."""
    eng = eng_factory()
    tok = ByteTokenizer()
    solo = eng_factory()
    prompt = tok.encode("hello world")
    ref = solo.generate([prompt], SamplingParams(max_new_tokens=12))[0]

    rid_free = eng.add_request(prompt, SamplingParams(max_new_tokens=12))
    rid_re = eng.add_request(
        tok.encode("id:"),
        SamplingParams(max_new_tokens=16, temperature=0.7, seed=1,
                       regex=r"\d+", stop_token=tok.eos_id))
    outs = {rid_free: [], rid_re: []}
    while eng.has_work():
        for ev in eng.step():
            outs[ev.request_id].append(ev.token)
    assert outs[rid_free] == ref
    assert re.fullmatch(r"\d+", tok.decode(outs[rid_re]))


def test_regex_admission_errors(eng_factory):
    eng = eng_factory()
    with pytest.raises(ValueError, match="regex"):
        eng.add_request([1, 2], SamplingParams(max_new_tokens=4,
                                               regex="(bad"))
    with pytest.raises(ValueError, match="mutually exclusive"):
        SamplingParams(max_new_tokens=4, json_mode=True,
                       regex=r"\d+").validate()
    bare = Engine(EngineConfig(model="tiny", vocab_size=512, page_size=8,
                               num_pages=64, max_seq_len=128,
                               use_pallas="never"))
    with pytest.raises(ValueError, match="grammar table"):
        bare.add_request([1, 2], SamplingParams(max_new_tokens=4,
                                                regex=r"\d+"))


def test_regex_pattern_cache_reused(eng_factory):
    eng = eng_factory()
    g1 = eng._regex_grammar(r"\d+")
    g2 = eng._regex_grammar(r"\d+")
    assert g1 is g2
    assert len(eng._regex_grammars) == 1
