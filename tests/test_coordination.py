"""Coordination + rollout machinery: maxSkew scaling, in-place update,
scaling adapter, groupset, warmup."""

import pytest

from rbg_tpu.api import constants as C
from rbg_tpu.api.group import (
    RoleBasedGroupSet, RoleStatus, ScalingAdapterHook,
)
from rbg_tpu.api.meta import get_condition
from rbg_tpu.api.policy import (
    CoordinatedPolicy, CoordinatedPolicySpec, CoordinatedScaling,
    ScalingAdapter, ScalingAdapterSpec, Warmup,
)
from rbg_tpu.coordination.scaling import clamp_targets
from rbg_tpu.runtime.plane import ControlPlane
from rbg_tpu.testutil import make_group, make_tpu_nodes, simple_role


@pytest.fixture()
def plane():
    p = ControlPlane(backend="fake")
    make_tpu_nodes(p.store, slices=2, hosts_per_slice=2)
    with p:
        yield p


# ---- pure math ----

def test_clamp_targets_bounds_skew():
    g = make_group("x", simple_role("prefill", replicas=10),
                   simple_role("decode", replicas=10))
    g.status.roles = [RoleStatus(name="prefill", replicas=0, ready_replicas=0),
                      RoleStatus(name="decode", replicas=0, ready_replicas=0)]
    pol = CoordinatedScaling(roles=["prefill", "decode"], max_skew_percent=20)
    out = clamp_targets(g, pol, {"prefill": 10, "decode": 10})
    # nothing ready yet: each role may only open 20% + the slowest +1 rule
    assert out["prefill"] <= 2 or out["prefill"] == 1
    assert out == {"prefill": max(out["prefill"], 1), "decode": max(out["decode"], 1)}

    # prefill half-ready, decode nothing → decode is slowest, prefill capped
    g.status.roles[0].ready_replicas = 5
    out = clamp_targets(g, pol, {"prefill": 10, "decode": 10})
    assert out["decode"] >= 1           # slowest gets its +1
    assert out["prefill"] <= 5 + 2      # can't run ahead more than skew+ready

    # both fully ready → full targets
    g.status.roles[0].ready_replicas = 10
    g.status.roles[1].ready_replicas = 10
    out = clamp_targets(g, pol, {"prefill": 10, "decode": 10})
    assert out == {"prefill": 10, "decode": 10}


def test_coordinated_scaling_end_to_end(plane):
    plane.apply(make_group(
        "pd", simple_role("prefill", replicas=4), simple_role("decode", replicas=4)))
    pol = CoordinatedPolicy()
    pol.metadata.name = "pd-policy"
    pol.spec = CoordinatedPolicySpec(
        group_name="pd",
        scaling=CoordinatedScaling(roles=["prefill", "decode"], max_skew_percent=25),
    )
    plane.apply(pol)
    g = plane.wait_group_ready("pd", timeout=60)
    assert g.status.role("prefill").ready_replicas == 4
    assert g.status.role("decode").ready_replicas == 4


# ---- in-place update ----

def test_inplace_update_preserves_pods(plane):
    role = simple_role("server", replicas=2)
    plane.apply(make_group("ip", role))
    plane.wait_group_ready("ip")
    uids0 = {p.metadata.name: p.metadata.uid
             for p in plane.store.list("Pod", namespace="default")}

    g = plane.store.get("RoleBasedGroup", "default", "ip")
    g.spec.roles[0].template.containers[0].image = "engine:v2"
    plane.store.update(g)

    def updated_in_place():
        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return (len(pods) == 2
                and all(p.template.containers[0].image == "engine:v2" for p in pods)
                and {p.metadata.name: p.metadata.uid for p in pods} == uids0)

    plane.wait_for(updated_in_place, timeout=15,
                   desc="image-only rollout patched pods in place")


def test_structural_change_recreates(plane):
    role = simple_role("server", replicas=1)
    plane.apply(make_group("rc", role))
    plane.wait_group_ready("rc")
    uid0 = plane.store.list("Pod", namespace="default")[0].metadata.uid

    g = plane.store.get("RoleBasedGroup", "default", "rc")
    g.spec.roles[0].template.containers[0].args = ["--new-flag"]  # not image-only
    plane.store.update(g)

    def recreated():
        pods = [p for p in plane.store.list("Pod", namespace="default") if p.active]
        return (pods and pods[0].metadata.uid != uid0
                and pods[0].template.containers[0].args == ["--new-flag"]
                and pods[0].running_ready)

    plane.wait_for(recreated, timeout=15, desc="structural change recreated pod")


# ---- scaling adapter ----

def test_scaling_adapter_drives_replicas(plane):
    role = simple_role("server", replicas=1)
    role.scaling_adapter = ScalingAdapterHook(enabled=True, min_replicas=1,
                                              max_replicas=3)
    plane.apply(make_group("sa", role))
    plane.wait_group_ready("sa")

    def adapter_bound():
        a = plane.store.get("ScalingAdapter", "default", "sa-server-scaling-adapter")
        return a if (a is not None and a.status.phase == "Bound") else None

    adapter = plane.wait_for(adapter_bound, desc="auto-created adapter bound")

    # External autoscaler writes replicas (the scale subresource analog).
    adapter = plane.store.get("ScalingAdapter", "default", adapter.metadata.name)
    adapter.spec.replicas = 5  # above max → clamped to 3
    plane.store.update(adapter)
    plane.wait_for(
        lambda: len([p for p in plane.store.list("Pod", namespace="default")
                     if p.active]) == 3,
        timeout=15, desc="adapter-driven scale to clamped max",
    )


# ---- groupset ----

def test_groupset_scales_groups(plane):
    gs = RoleBasedGroupSet()
    gs.metadata.name = "cells"
    gs.spec.replicas = 2
    gs.spec.template.spec.roles = [simple_role("server", replicas=1)]
    plane.apply(gs)

    def both_ready():
        s = plane.store.get("RoleBasedGroupSet", "default", "cells")
        return s.status.ready_replicas == 2

    plane.wait_for(both_ready, timeout=20, desc="groupset 2 groups ready")
    names = {g.metadata.name for g in plane.store.list("RoleBasedGroup", namespace="default")}
    assert names == {"cells-0", "cells-1"}

    gs = plane.store.get("RoleBasedGroupSet", "default", "cells")
    gs.spec.replicas = 1
    plane.store.update(gs)
    plane.wait_for(
        lambda: len(plane.store.list("RoleBasedGroup", namespace="default")) == 1,
        desc="groupset scale down",
    )


# ---- warmup ----

def test_warmup_runs_on_group_nodes(plane):
    plane.apply(make_group("svc", simple_role("server", replicas=2)))
    plane.wait_group_ready("svc")

    w = Warmup()
    w.metadata.name = "prime"
    w.spec.target.group_name = "svc"
    w.spec.template.containers = []
    plane.apply(w)

    def done():
        cur = plane.store.get("Warmup", "default", "prime")
        return cur if cur and cur.status.phase == "Succeeded" else None

    done_w = plane.wait_for(done, timeout=15, desc="warmup succeeded")
    assert done_w.status.succeeded_nodes == done_w.status.desired_nodes > 0


def test_dependencies_ready_uses_rolled_up_flag():
    """dependencies_ready consumes RoleStatus.ready (capacity-aware during
    surge rollouts), not raw counter equality — a surge rollout's transient
    base-counter dip must not flap dependents."""
    from rbg_tpu.coordination.dependency import dependencies_ready

    g = make_group("dep", simple_role("a", replicas=2),
                   simple_role("b", replicas=1))
    g.spec.roles[1].dependencies = ["a"]
    role_b = g.spec.roles[1]

    # Mid-surge-rollout: base counter dipped to 1 but the rolled-up flag
    # (from the RIS Ready condition) says capacity is held.
    g.status.roles = [RoleStatus(name="a", replicas=1, ready_replicas=1,
                                 ready=True)]
    assert dependencies_ready(g, role_b)

    g.status.roles[0].ready = False
    assert not dependencies_ready(g, role_b)
