"""MoE model family: routing exactness, serving-path integration, expert
parallelism over the ep mesh axis."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.models import KVCache, forward, get_config, init_params
from rbg_tpu.models.llama import forward_train
from rbg_tpu.models.training import train_n_steps
from rbg_tpu.parallel import make_mesh, param_specs, shard_pytree


@pytest.fixture(scope="module")
def moe_setup():
    cfg = get_config("tiny-moe")
    params = init_params(cfg, jax.random.key(0))
    return cfg, params


def test_moe_forward_shapes_and_cache_path(moe_setup):
    cfg, params = moe_setup
    tokens = jax.random.randint(jax.random.key(1), (2, 8), 0, cfg.vocab_size)
    logits, cache = forward(params, cfg, tokens, KVCache.create(cfg, 2, 16))
    assert logits.shape == (2, 8, cfg.vocab_size)
    assert forward_train(params, cfg, tokens).shape == (2, 8, cfg.vocab_size)


def test_moe_routing_matches_manual_reference(moe_setup):
    """The einsum dense-dispatch must equal a per-token python loop over the
    selected experts."""
    cfg, params = moe_setup
    from rbg_tpu.models.llama import _moe_mlp

    blk0 = jax.tree_util.tree_map(lambda x: x[0], params["blocks"])
    x = jax.random.normal(jax.random.key(2), (1, 5, cfg.hidden_size), jnp.float32)
    got = np.asarray(_moe_mlp(cfg, blk0, x))

    xn = np.asarray(x)
    router = np.asarray(blk0["router"], np.float64)
    want = np.zeros_like(got, dtype=np.float64)
    for t in range(5):
        xv = xn[0, t]
        logits = xv @ router
        probs = np.exp(logits - logits.max())
        probs /= probs.sum()
        top = np.argsort(probs)[::-1][: cfg.experts_per_token]
        w = probs[top] / probs[top].sum()
        for wi, e in zip(w, top):
            g = xv @ np.asarray(blk0["moe_gate"])[e]
            u = xv @ np.asarray(blk0["moe_up"])[e]
            silu = g / (1 + np.exp(-g)) * u
            want[0, t] += wi * (silu @ np.asarray(blk0["moe_down"])[e])
        # shared expert
        g = xv @ np.asarray(blk0["w_gate"])
        u = xv @ np.asarray(blk0["w_up"])
        want[0, t] += (g / (1 + np.exp(-g)) * u) @ np.asarray(blk0["w_down"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_moe_expert_parallel_matches_single_device(moe_setup):
    cfg, params = moe_setup
    mesh = make_mesh(dp=1, sp=1, ep=4, tp=2)
    tokens = jax.random.randint(jax.random.key(3), (2, 8), 0, cfg.vocab_size)
    ref = forward_train(params, cfg, tokens)
    p_sh = shard_pytree(params, param_specs(cfg), mesh)
    got = jax.jit(lambda p, t: forward_train(p, cfg, t))(p_sh, tokens)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(got), rtol=1e-4, atol=1e-4)


@pytest.mark.slow
def test_moe_train_step_reduces_loss(moe_setup):
    cfg, params = moe_setup
    mesh = make_mesh(dp=1, sp=2, ep=2, tp=2)
    tokens = jax.random.randint(jax.random.key(4), (2, 16), 0, cfg.vocab_size)
    from rbg_tpu.models.training import next_token_loss
    loss0 = float(next_token_loss(params, cfg, tokens))
    _, loss = train_n_steps(cfg, mesh, params, tokens, n=4)
    assert float(loss) < loss0


@pytest.mark.slow
def test_moe_serving_engine(moe_setup):
    """The engine serves MoE models unchanged (paged path uses the same
    block math)."""
    cfg, params = moe_setup
    from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
    from rbg_tpu.models.llama import prefill_and_decode_greedy

    prompt = [3, 1, 4, 1, 5, 9, 2, 6]
    expect = [int(t) for t in np.asarray(prefill_and_decode_greedy(
        params, cfg, jnp.asarray([prompt], jnp.int32), 6))[0]]
    eng = Engine(EngineConfig(model="tiny-moe", page_size=8, num_pages=64,
                              max_seq_len=128, prefill_chunk=16,
                              use_pallas="never"), params=params)
    got = eng.generate([prompt], SamplingParams(max_new_tokens=6))[0]
    assert got == expect
