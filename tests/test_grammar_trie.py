"""Compiled token-trie grammar masks (VERDICT r4 #5): exactness vs the
probe reference, the per-step cost bound at a >=32k vocab, state-mask
memoization, and json_mode over the wire with the committed HF tokenizer
fixture."""

import json
import random
import string

import numpy as np
import pytest

from rbg_tpu.engine.grammar import (JsonGrammar, TokenGrammar, TokenTrie,
                                    token_bytes_for)
from rbg_tpu.engine.tokenizer import ByteTokenizer, load_tokenizer

FIXTURE = "tests/fixtures/tiny_hf_tokenizer"


def _states_along(tg: TokenGrammar, text: str):
    """Every automaton state visited while consuming ``text`` bytewise."""
    g = tg.grammar
    s = g.initial()
    states = [s]
    for b in text.encode():
        s = g.advance(s, b)
        assert s is not None, text
        states.append(s)
    return states


SAMPLE = ('{"name": "trie \\u00e9", "nums": [-1.5e3, 0, 42], '
          '"ok": true, "null": null, "nested": {"a": []}}')


def test_trie_mask_equals_probe_byte_tokenizer():
    tok = ByteTokenizer()
    tg = TokenGrammar(JsonGrammar(), token_bytes_for(tok), tok.eos_id)
    for s in _states_along(tg, SAMPLE):
        np.testing.assert_array_equal(tg.mask(s), tg._mask_probe(s))


def test_trie_mask_equals_probe_hf_fixture():
    tok = load_tokenizer(FIXTURE)
    tg = TokenGrammar(JsonGrammar(), token_bytes_for(tok), tok.eos_id)
    for s in _states_along(tg, SAMPLE):
        np.testing.assert_array_equal(tg.mask(s), tg._mask_probe(s))


def _synthetic_vocab(v: int):
    """A >=32k-token table shaped like a real BPE vocab: 256 byte tokens,
    then word/number/punctuation fragments."""
    rng = random.Random(7)
    table = [bytes([i]) for i in range(256)]
    frags = set()
    while len(table) + len(frags) < v:
        kind = rng.random()
        if kind < 0.7:
            w = "".join(rng.choices(string.ascii_lowercase,
                                    k=rng.randint(2, 10)))
            if rng.random() < 0.5:
                w = " " + w
        elif kind < 0.85:
            w = "".join(rng.choices(string.digits, k=rng.randint(1, 6)))
        else:
            w = "".join(rng.choices('{}[]",: .eE+-', k=rng.randint(1, 3)))
        frags.add(w.encode())
    table.extend(sorted(frags))
    return table


def test_trie_mask_cost_bound_32k_vocab():
    """The point of the trie: per-step mask cost is bounded by the LEGAL
    byte paths, not the vocabulary size. At a 32k vocab the probe loop
    costs total_bytes (~190k) automaton advances per step; the trie must
    (a) stay exact, (b) cost <5% of that in restrictive states, (c) beat
    the probe even in the most permissive state (string interior), and
    (d) cost zero advances on a state-cache hit."""
    table = _synthetic_vocab(32_768)
    tg = TokenGrammar(JsonGrammar(), table, eos_id=None)
    total = tg.trie.total_bytes
    assert total > 100_000

    # (a) exact vs probe on three representative states.
    g = tg.grammar
    s_value = g.initial()
    s_string = s_value
    for b in b'{"k": "in':
        s_string = g.advance(s_string, b)
    s_number = s_value
    for b in b"[1":
        s_number = g.advance(s_number, b)
    for s in (s_value, s_string, s_number):
        np.testing.assert_array_equal(tg.mask(s), tg._mask_probe(s))

    # (b) restrictive state: only JSON value-openers are legal first
    # bytes — the trie prunes almost the whole vocab at depth 1.
    tg2 = TokenGrammar(JsonGrammar(), table, eos_id=None)
    tg2.mask(s_value)
    assert tg2.stats["advance_calls"] < 0.05 * total, (
        f"{tg2.stats['advance_calls']} advances vs {total} total bytes")

    # (c) permissive state (string interior): nearly every ascii token is
    # legal, but shared prefixes still make the trie cheaper than probing.
    tg3 = TokenGrammar(JsonGrammar(), table, eos_id=None)
    tg3.mask(s_string)
    assert tg3.stats["advance_calls"] < 0.8 * total

    # (d) memoization: the same state again is a pure cache hit.
    before = dict(tg3.stats)
    m = tg3.mask(s_string)
    assert tg3.stats["advance_calls"] == before["advance_calls"]
    assert tg3.stats["mask_cache_hits"] == before["mask_cache_hits"] + 1
    # Cached masks are copies — caller mutation must not poison the cache.
    m[:] = False
    assert tg3.mask(s_string).any()


def test_trie_structure_shares_prefixes():
    trie = TokenTrie([b"abc", b"abd", b"a", None, b""])
    # root -> a -> b -> {c, d}: 4 nodes beyond root, not 7.
    assert len(trie.children) == 5
    assert trie.tokens[1] == [2]          # "a" ends at depth-1 node
    assert trie.total_bytes == 7


@pytest.mark.slow
@pytest.mark.e2e
def test_json_mode_hf_tokenizer_over_wire():
    """VERDICT done-condition: json_mode works with --tokenizer-path.
    The committed HF fixture (vocab 161) serves grammar-constrained text
    through a real server subprocess."""
    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once

    with SpawnedEngineServer(
            "--model", "tiny", "--page-size", "8", "--num-pages", "128",
            "--max-seq-len", "256", "--use-pallas", "never",
            "--tokenizer-path", FIXTURE) as srv:
        r, _, _ = request_once(
            srv.addr,
            {"op": "generate_text", "text": "emit json:",
             "max_new_tokens": 48, "temperature": 0.8, "seed": 11,
             "json_mode": True}, timeout=180)
        assert "error" not in r, r
        # The decoded text must be valid JSON or a legal prefix of one.
        g = JsonGrammar()
        s = g.initial()
        for b in r["text"].encode():
            s = g.advance(s, b)
            assert s is not None, r["text"]
        try:
            json.loads(r["text"])
        except json.JSONDecodeError:
            pass  # legal truncated prefix (hit max_new_tokens)
