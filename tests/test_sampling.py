"""Sampling surface: top-p/min-p masking, penalties, per-request seeds,
logprobs — unit math on the sampler plus engine-level behavior.

Reference context: the reference orchestrates engines (SGLang/vLLM) whose
request API carries these fields; the TPU engine implements them natively
(rbg_tpu/engine/sampler.py) with per-row PRNG streams and optional
penalty state threaded through the fused decode scan."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rbg_tpu.engine import Engine, EngineConfig, SamplingParams
from rbg_tpu.engine.sampler import apply_penalties, row_keys, sample, step_keys


def _keys(n, seed=0):
    return row_keys([None] * n, jax.random.key(seed), list(range(n)))


def _arr(x, dt=jnp.float32):
    return jnp.asarray(x, dt)


# ---- sampler unit math ----


def test_top_p_masks_tail():
    # Row distribution: probs ~ [0.6, 0.3, 0.05, 0.05]; top_p=0.8 keeps
    # {0, 1} only (exclusive cumulative 0.0, 0.6 < 0.8; 0.9 for idx 2).
    logits = jnp.log(jnp.asarray([[0.6, 0.3, 0.05, 0.05]]))
    logits = jnp.tile(logits, (64, 1))
    toks, _ = sample(logits, _keys(64), _arr([1.0] * 64),
                     jnp.zeros(64, jnp.int32), _arr([0.8] * 64),
                     _arr([0.0] * 64))
    assert set(np.asarray(toks).tolist()) <= {0, 1}


def test_top_p_one_is_disabled():
    logits = jnp.tile(jnp.log(jnp.asarray([[0.25, 0.25, 0.25, 0.25]])),
                      (256, 1))
    toks, _ = sample(logits, _keys(256), _arr([1.0] * 256),
                     jnp.zeros(256, jnp.int32), _arr([1.0] * 256),
                     _arr([0.0] * 256))
    assert set(np.asarray(toks).tolist()) == {0, 1, 2, 3}


def test_min_p_masks_below_ratio():
    # max prob 0.5; min_p=0.3 keeps probs >= 0.15 → {0 (0.5), 1 (0.3)}.
    logits = jnp.tile(jnp.log(jnp.asarray([[0.5, 0.3, 0.12, 0.08]])),
                      (64, 1))
    toks, _ = sample(logits, _keys(64), _arr([1.0] * 64),
                     jnp.zeros(64, jnp.int32), _arr([1.0] * 64),
                     _arr([0.3] * 64))
    assert set(np.asarray(toks).tolist()) <= {0, 1}


def test_per_row_params_mix():
    # Row 0 greedy, row 1 top-k=1 (== greedy), row 2 top-p over a peaked
    # distribution — each row honors ITS params inside one batch.
    logits = jnp.asarray([[5.0, 0.0, 0.0], [0.0, 5.0, 0.0],
                          [0.0, 0.0, 5.0]])
    toks, _ = sample(logits, _keys(3), _arr([0.0, 1.0, 1.0]),
                     jnp.asarray([0, 1, 0], jnp.int32),
                     _arr([1.0, 1.0, 0.5]), _arr([0.0] * 3))
    got = np.asarray(toks).tolist()
    assert got[0] == 0 and got[1] == 1 and got[2] == 2


def test_seeded_rows_reproduce():
    logits = jnp.tile(jnp.asarray([[1.0, 1.1, 0.9, 1.05]]), (4, 1))
    keys = row_keys([7, 7, None, None], jax.random.key(3), [0, 1, 2, 3])
    keys = step_keys(keys, jnp.asarray([5, 5, 5, 5], jnp.int32))
    toks, _ = sample(logits, keys, _arr([1.0] * 4),
                     jnp.zeros(4, jnp.int32), _arr([1.0] * 4),
                     _arr([0.0] * 4))
    got = np.asarray(toks)
    assert got[0] == got[1]  # same seed, same position → same sample


def test_apply_penalties_math():
    logits = jnp.asarray([[2.0, -2.0, 1.0, 0.5]])
    pmask = jnp.asarray([[True, True, False, False]])
    counts = jnp.asarray([[0, 0, 3, 0]], jnp.int32)
    out = apply_penalties(logits, pmask, counts,
                          rep=_arr([2.0]), pres=_arr([0.5]),
                          freq=_arr([0.1]))
    out = np.asarray(out)[0]
    # token 0: prompt-seen, positive → 2.0/2 = 1.0
    assert out[0] == pytest.approx(1.0)
    # token 1: prompt-seen, negative → -2.0*2 = -4.0
    assert out[1] == pytest.approx(-4.0)
    # token 2: output-seen ×3 → 1.0/2 (rep) - 0.5 (pres) - 0.3 (freq)
    assert out[2] == pytest.approx(1.0 / 2 - 0.5 - 0.3)
    # token 3: unseen → untouched
    assert out[3] == pytest.approx(0.5)


def test_logprobs_returned_and_normalized():
    logits = jnp.asarray([[0.0, jnp.log(3.0)]])  # probs = [0.25, 0.75]
    toks, lps = sample(logits, _keys(1), _arr([0.0]),
                       jnp.zeros(1, jnp.int32), _arr([1.0]), _arr([0.0]),
                       want_logprobs=True)
    assert int(toks[0]) == 1
    assert float(lps[0]) == pytest.approx(np.log(0.75), abs=1e-5)


# ---- engine behavior ----


def _engine(**kw):
    cfg = EngineConfig(model="tiny", page_size=8, num_pages=96,
                       max_seq_len=128, use_pallas="never", **kw)
    return Engine(cfg)


@pytest.mark.slow
def test_engine_seed_reproducible_across_instances():
    sp = SamplingParams(max_new_tokens=8, temperature=1.0, top_p=0.9, seed=42)
    a = _engine().generate([[1, 2, 3, 4]], sp)[0]
    b = _engine().generate([[1, 2, 3, 4]], sp)[0]
    assert a == b


@pytest.mark.slow
def test_engine_presence_penalty_forces_distinct_tokens():
    # Greedy + overwhelming presence penalty → no output token repeats
    # (the in-scan count update must apply within a multi-step window too).
    sp = SamplingParams(max_new_tokens=12, temperature=0.0,
                        presence_penalty=1e9)
    for ms in (1, 4):
        out = _engine(multi_step=ms).generate([[1, 2, 3, 4]], sp)[0]
        assert len(out) == len(set(out)), (ms, out)


def test_engine_repetition_penalty_blocks_prompt_echo():
    # Repetition penalty so extreme every prompt token is suppressed —
    # output must avoid the prompt tokens entirely (logits stay positive
    # pre-division for the argmax winner on random init, so a huge divisor
    # pushes prompt tokens below every unseen token).
    prompt = [9, 9, 9, 9, 9, 9]
    sp = SamplingParams(max_new_tokens=8, temperature=0.0,
                        repetition_penalty=1e6, presence_penalty=1e9)
    out = _engine().generate([prompt], sp)[0]
    assert 9 not in out


def test_engine_logprobs_events_all_steps():
    eng = _engine(multi_step=2)
    rid = eng.add_request([1, 2, 3, 4],
                          SamplingParams(max_new_tokens=6, logprobs=True))
    lps = []
    while eng.has_work():
        for ev in eng.step():
            if ev.request_id == rid:
                lps.append(ev.logprob)
    assert len(lps) == 6
    assert all(lp is not None and lp <= 0.0 for lp in lps)


def test_engine_mixed_batch_logprobs_only_where_requested():
    eng = _engine()
    r1 = eng.add_request([1, 2, 3], SamplingParams(max_new_tokens=4,
                                                   logprobs=True))
    r2 = eng.add_request([4, 5, 6], SamplingParams(max_new_tokens=4))
    got = {r1: [], r2: []}
    while eng.has_work():
        for ev in eng.step():
            got[ev.request_id].append(ev.logprob)
    assert all(lp is not None for lp in got[r1])
    assert all(lp is None for lp in got[r2])


def test_sampling_params_validation():
    with pytest.raises(ValueError):
        SamplingParams(top_p=0.0).validate()
    with pytest.raises(ValueError):
        SamplingParams(min_p=1.0).validate()
    with pytest.raises(ValueError):
        SamplingParams(temperature=-0.1).validate()
    with pytest.raises(ValueError):
        SamplingParams(repetition_penalty=0.0).validate()
    with pytest.raises(ValueError):
        SamplingParams.from_wire({"top_p": 2.0})


def test_from_wire_roundtrip_defaults():
    sp = SamplingParams.from_wire({}, default_max_tokens=9, stop_token=3)
    assert sp.max_new_tokens == 9 and sp.stop_token == 3
    assert not sp.needs_penalties() and not sp.logprobs
    sp2 = SamplingParams.from_wire(
        {"temperature": 0.7, "top_p": 0.9, "seed": 5, "logprobs": True,
         "presence_penalty": 0.2, "stop_token": 11}, stop_token=3)
    assert sp2.stop_token == 11 and sp2.seed == 5
    assert sp2.needs_penalties() and sp2.logprobs


def test_greedy_unchanged_by_sampling_machinery():
    # The default path (no penalties, no logprobs) must produce the same
    # greedy continuation as before the sampling surface grew.
    out1 = _engine().generate([[1, 2, 3, 4]],
                              SamplingParams(max_new_tokens=8))[0]
    out2 = _engine(multi_step=4).generate([[1, 2, 3, 4]],
                                          SamplingParams(max_new_tokens=8))[0]
    assert out1 == out2


# ---- over the wire (unified engine server subprocess) ----


@pytest.mark.slow
@pytest.mark.e2e
def test_server_seed_and_logprobs_over_wire():
    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import request_once

    with SpawnedEngineServer(
            "--model", "tiny", "--page-size", "8", "--num-pages", "64",
            "--max-seq-len", "128", "--use-pallas", "never") as srv:
        req = {"op": "generate", "prompt": [1, 2, 3, 4],
               "max_new_tokens": 8, "temperature": 0.9, "top_p": 0.9,
               "seed": 77, "logprobs": True}
        r1, _, _ = request_once(srv.addr, req, timeout=180)
        r2, _, _ = request_once(srv.addr, req, timeout=180)
        assert "error" not in r1, r1
        assert r1["tokens"] == r2["tokens"]          # seeded → reproducible
        assert len(r1["logprobs"]) == len(r1["tokens"])
        assert all(lp <= 0 for lp in r1["logprobs"])
        # invalid params fail the request, not the server
        bad, _, _ = request_once(srv.addr,
                                 {"op": "generate", "prompt": [1],
                                  "top_p": 5.0}, timeout=30)
        assert "error" in bad and "top_p" in bad["error"]
        h, _, _ = request_once(srv.addr, {"op": "health"}, timeout=5)
        assert h["ok"]


@pytest.mark.slow
@pytest.mark.e2e
def test_server_cancels_generation_on_client_disconnect():
    """A streaming client that goes away mid-generation must not leave the
    request occupying a batch slot for its whole max_new_tokens budget
    (the HTTP edge cuts streams at stop strings this way)."""
    import socket
    import time

    from conftest import SpawnedEngineServer
    from rbg_tpu.engine.protocol import recv_msg, request_once, send_msg

    with SpawnedEngineServer(
            "--model", "tiny", "--page-size", "8", "--num-pages", "2048",
            "--max-seq-len", "8192", "--use-pallas", "never") as srv:
        # Start a long streaming generation, read one frame, vanish.
        conn = socket.create_connection(("127.0.0.1", srv.port), timeout=60)
        send_msg(conn, {"op": "generate", "prompt": [1, 2, 3],
                        "max_new_tokens": 8000, "stream": True})
        frame, _, _ = recv_msg(conn)
        assert frame and "tokens" in frame
        conn.close()
        # The engine must abort the request well before 8000 tokens.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            m, _, _ = request_once(srv.addr, {"op": "metrics"}, timeout=10)
            st = m["metrics"]
            if st["running"] == 0 and st["waiting"] == 0:
                break
            time.sleep(0.2)
        assert st["running"] == 0 and st["waiting"] == 0, st
        assert st["decode_tokens"] < 8000, st


@pytest.mark.slow
def test_extreme_seed_values_do_not_crash():
    # Wire seeds are arbitrary ints; uint32 masking must keep the engine
    # loop alive (NumPy 2.x raises OverflowError on bad conversions).
    for seed in (2**40, -1, 2**63 - 1):
        sp = SamplingParams(max_new_tokens=3, temperature=1.0, seed=seed)
        out = _engine().generate([[1, 2, 3]], sp)[0]
        assert len(out) == 3


def test_out_of_vocab_prompt_rejected_at_admission():
    eng = _engine()
    V = eng.mcfg.vocab_size
    with pytest.raises(ValueError, match="vocab"):
        eng.add_request([1, V], SamplingParams(max_new_tokens=2))
    with pytest.raises(ValueError, match="vocab"):
        eng.add_request([-1], SamplingParams(max_new_tokens=2))
    with pytest.raises(ValueError, match="empty"):
        eng.add_request([], SamplingParams(max_new_tokens=2))
    # the engine still works after rejections
    assert len(eng.generate([[1, 2]], SamplingParams(max_new_tokens=2))[0]) == 2


@pytest.mark.slow
def test_seeded_output_invariant_under_preemption():
    """Preemption folds output into prompt for re-prefill; penalty counts
    and position-keyed sampling must survive so a seeded request yields
    the SAME tokens whether or not it was preempted."""
    sp = SamplingParams(max_new_tokens=24, temperature=1.0,
                        presence_penalty=0.6, repetition_penalty=1.2,
                        seed=11)
    prompts = [[1, 2, 3, 4], [5, 6, 7, 8], [2, 4, 6, 8]]

    def run(num_pages):
        eng = Engine(EngineConfig(model="tiny", page_size=8,
                                  num_pages=num_pages, max_seq_len=128,
                                  use_pallas="never",
                                  enable_radix_cache=False))
        out = eng.generate(prompts, sp)
        return out, eng.metrics["preemptions"]

    big, pre_big = run(64)
    small, pre_small = run(9)
    assert pre_big == 0
    assert pre_small > 0, "small pool must actually preempt"
    assert big == small
